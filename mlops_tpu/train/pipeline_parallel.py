"""Pipeline-parallel training: GPipe over transformer trunks as a REAL config.

SURVEY §2.7's pipeline-parallel obligation, made load-bearing the same way
`train/long_context.py` did for sequence parallelism: a training
configuration (``model.pipeline_stages=S`` on a TransformerBlock-trunk
family — bert or ft_transformer) splits the encoder's ``depth`` blocks
into S GPipe stages over the mesh's 'stage' axis and streams
``train.pipeline_microbatches`` microbatches through the
ppermute ring (`parallel/pipeline.py`). Composes with data parallelism:
on a ``('data','stage')`` mesh the microbatch batch dim shards over
'data' while activations hand off stage-to-stage over 'stage'.

The stage-stacked parameters are exactly the dense model's ``block_i``
subtrees stacked on a leading ``[S, L, ...]`` axis (L = depth // S
layers per stage), so a PP-trained model converts losslessly back to
the dense param tree (``merge_trunk_params``) and packages/serves like
any other bundle of its family — pipeline parallelism is a
training-time layout, not a different model. Equivalence with the dense
forward pass and trainability are pinned by
``tests/test_pipeline_parallel.py``; the multi-device step runs in
``__graft_entry__.dryrun_multichip``.

The reference has no model parallelism of any kind (its training is
sklearn in-process — SURVEY §2.7 cites `01-train-model.ipynb:227`), so
there is no reference analogue: this is TPU-native capability the
rebuild adds.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax
from flax import linen as nn
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from mlops_tpu.config import ModelConfig, TrainConfig
from mlops_tpu.models.bert import (
    TokenLayout,
    apply_cls_head,
    apply_embed_front,
    tokenize,
)
from mlops_tpu.models.ft_transformer import (
    FeatureTokenizer,
    TransformerBlock,
    apply_ft_head,
)
from mlops_tpu.parallel.pipeline import make_pipeline
from mlops_tpu.schema.features import SCHEMA
from mlops_tpu.train.loop import make_optimizer, sigmoid_bce, update_ema


class BertPPEmbed(nn.Module):
    """The dense ``BertEncoder``'s embedding front as its own module —
    the SAME ``apply_embed_front`` helper (`models/bert.py`), so its param
    tree is a verbatim slice of the dense tree (``split_bert_params``)."""

    cards: tuple[int, ...]
    num_numeric: int
    hidden: int
    num_bins: int = 32
    dtype: jnp.dtype = jnp.bfloat16

    @property
    def layout(self) -> TokenLayout:
        return TokenLayout(tuple(self.cards), self.num_numeric, self.num_bins)

    @nn.compact
    def __call__(self, cat_ids: jnp.ndarray, numeric: jnp.ndarray) -> jnp.ndarray:
        layout = self.layout
        tokens = tokenize(cat_ids, numeric, layout)
        return apply_embed_front(
            self, tokens, layout.vocab_size, layout.seq_len, self.hidden, self.dtype
        )


class BertPPHead(nn.Module):
    """The dense ``BertEncoder``'s read-out, via the shared
    ``apply_cls_head`` helper (`models/bert.py`)."""

    hidden: int
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        return apply_cls_head(self, x, self.hidden, self.dtype)


class FTPPEmbed(nn.Module):
    """The dense ``FTTransformer``'s feature tokenizer as the PP front —
    the SAME ``FeatureTokenizer`` module under its auto-assigned dense
    name, so the param tree is a verbatim slice of the dense tree."""

    cards: tuple[int, ...]
    num_numeric: int
    token_dim: int
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, cat_ids: jnp.ndarray, numeric: jnp.ndarray) -> jnp.ndarray:
        return FeatureTokenizer(
            tuple(self.cards),
            self.num_numeric,
            self.token_dim,
            dtype=self.dtype,
            name="FeatureTokenizer_0",
        )(cat_ids, numeric)


class FTPPHead(nn.Module):
    """The dense ``FTTransformer``'s read-out, via the shared
    ``apply_ft_head`` helper (`models/ft_transformer.py`)."""

    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        return apply_ft_head(self, x, self.dtype)


# Per-family trunk split: which top-level keys of the dense param tree
# belong to the (replicated) embedding front and read-out; everything
# block_i in between stage-stacks. PP supports exactly the families whose
# depth is a run of identical TransformerBlocks.
_FAMILY_SPLITS = {
    "bert": (
        ("tok_embed", "pos_embed", "ln_embed"),
        ("ln_final", "pooler", "head"),
    ),
    "ft_transformer": (
        ("FeatureTokenizer_0",),
        ("ln_final", "head"),
    ),
}


def split_trunk_params(dense: dict, stages: int, family: str = "bert") -> dict:
    """Dense param tree → the PP layout:
    ``{"embed": ..., "stages": [S, L, ...]-stacked blocks, "head": ...}``.
    """
    embed_keys, head_keys = _FAMILY_SPLITS[family]
    depth = sum(1 for k in dense if k.startswith("block_"))
    if depth == 0 or depth % stages:
        raise ValueError(f"depth {depth} not divisible into {stages} stages")
    layers = depth // stages
    blocks = [dense[f"block_{i}"] for i in range(depth)]
    per_stage = [
        jax.tree.map(lambda *xs: jnp.stack(xs), *blocks[s * layers : (s + 1) * layers])
        for s in range(stages)
    ]
    return {
        "embed": {k: dense[k] for k in embed_keys},
        "stages": jax.tree.map(lambda *xs: jnp.stack(xs), *per_stage),
        "head": {k: dense[k] for k in head_keys},
    }


def split_bert_params(dense: dict, stages: int) -> dict:
    return split_trunk_params(dense, stages, "bert")


def merge_trunk_params(pp: dict) -> dict:
    """Inverse of ``split_trunk_params`` (family-agnostic: the embed/head
    subtrees carry their own keys): reassemble the dense tree so a
    PP-trained model packages/serves as a normal bundle."""
    leaves = jax.tree.leaves(pp["stages"])
    stages, layers = leaves[0].shape[0], leaves[0].shape[1]
    dense = {**pp["embed"], **pp["head"]}
    for i in range(stages * layers):
        dense[f"block_{i}"] = jax.tree.map(
            lambda a, i=i: a[i // layers, i % layers], pp["stages"]
        )
    return dense


merge_bert_params = merge_trunk_params  # bert-era name, same function


@dataclasses.dataclass
class PPTrainStep:
    forward_fn: Callable  # (pp_params, cat, num) -> logits[N]
    step_fn: Callable  # (pp_params, opt_state, ema, cat, num, lab) ->
    # (pp_params, opt_state, ema, loss); ema is None (empty pytree) when
    # train.ema_decay == 0 and threads through untouched
    params: Any  # PP layout, stage leaves sharded over 'stage'
    opt_state: Any
    stages: int
    microbatches: int
    ema: Any = None  # zero-init Polyak accumulator in the PP layout
    # (inherits each param's sharding) when ema_decay > 0


def make_pp_train_step(
    model_config: ModelConfig,
    train_config: TrainConfig,
    mesh: Mesh,
    seed: int = 0,
    init_variables: Any | None = None,
) -> PPTrainStep:
    """One jitted (DP×)PP train step over a TransformerBlock-trunk family
    (bert or ft_transformer — `_FAMILY_SPLITS`).

    The 'stage' mesh axis carries the encoder blocks (each device holds
    depth/S of them); 'data', when present, shards the microbatch batch
    dim. Params start from the SAME init as the dense model (split via
    ``split_trunk_params``) and train under the SAME optimizer
    (``loop.make_optimizer``: global-norm clip + warmup-cosine); the
    forward pass equals the dense model's exactly (pinned per family by
    ``test_pp_forward_matches_dense``).
    """
    if model_config.family not in _FAMILY_SPLITS:
        raise ValueError(
            "pipeline_stages applies to the TransformerBlock-trunk "
            f"families {tuple(_FAMILY_SPLITS)}, not {model_config.family!r}"
        )
    if model_config.ensemble_size > 1:
        # A DeepEnsemble's param tree has no top-level block_* keys, so
        # split_trunk_params would die with a cryptic stage-divisibility
        # error; name the unsupported combination instead.
        raise ValueError(
            "pipeline_stages does not compose with ensemble_size>1: the "
            "pipeline splits a single trunk's blocks across stages; train "
            "the ensemble dense or set ensemble_size=1"
        )
    if "stage" not in mesh.axis_names:
        raise ValueError(
            "model.pipeline_stages needs a mesh with a 'stage' axis "
            "(parallel.make_nd_mesh({'data': d, 'stage': s}))"
        )
    stages = mesh.shape["stage"]
    if model_config.pipeline_stages and model_config.pipeline_stages != stages:
        raise ValueError(
            f"config pipeline_stages={model_config.pipeline_stages} != "
            f"mesh 'stage' axis {stages}"
        )
    if model_config.depth % stages:
        raise ValueError(
            f"model.depth={model_config.depth} must divide into {stages} stages"
        )
    if model_config.dropout:
        raise ValueError(
            "the pipeline path needs model.dropout=0 (stage_fn runs inside "
            "shard_map without an rng stream; long_context.py makes the "
            "same trade for the ring)"
        )
    micro = train_config.pipeline_microbatches
    dp = mesh.shape.get("data", 1)
    if train_config.batch_size % micro or (train_config.batch_size // micro) % dp:
        raise ValueError(
            f"batch_size={train_config.batch_size} must split into "
            f"{micro} microbatches x 'data' axis {dp}"
        )
    layers = model_config.depth // stages
    dtype = {"bf16": jnp.bfloat16, "f32": jnp.float32}[model_config.precision]

    from mlops_tpu.models import build_model, init_params

    # init_variables: a DENSE variables tree (e.g. a pretrained trunk
    # grafted by `load_pretrained_variables`) — the PP layout is derived
    # from it exactly as from a fresh init.
    dense_variables = init_variables or init_params(
        build_model(model_config), jax.random.PRNGKey(seed)
    )
    pp_params = split_trunk_params(
        dense_variables["params"], stages, model_config.family
    )

    if model_config.family == "bert":
        embed_mod = BertPPEmbed(
            cards=tuple(SCHEMA.cards),
            num_numeric=SCHEMA.num_numeric,
            hidden=model_config.token_dim,
            dtype=dtype,
        )
        head_mod = BertPPHead(hidden=model_config.token_dim, dtype=dtype)
    else:  # ft_transformer
        embed_mod = FTPPEmbed(
            cards=tuple(SCHEMA.cards),
            num_numeric=SCHEMA.num_numeric,
            token_dim=model_config.token_dim,
            dtype=dtype,
        )
        head_mod = FTPPHead(dtype=dtype)
    block = TransformerBlock(
        heads=model_config.heads,
        token_dim=model_config.token_dim,
        dropout=0.0,
        dtype=dtype,
    )

    def stage_fn(w, h):
        # w leaves are [L, ...] — this device's layers, applied in order.
        for j in range(layers):
            h = block.apply(
                {"params": jax.tree.map(lambda a, j=j: a[j], w)}, h, train=False
            )
        return h

    if train_config.pipeline_remat:
        # Drop the INTERNAL activations of each stage's L blocks
        # (attention scores, MLP intermediates — the L x internals term
        # that dominates at depth) and recompute them on backward from the
        # stage-boundary input, which the scan must keep either way.
        # prevent_cse=False: the barrier CSE protection is unnecessary —
        # and fusion-hostile — when the checkpointed fn runs under scan.
        stage_fn = jax.checkpoint(stage_fn, prevent_cse=False)

    batch_axis = "data" if "data" in mesh.axis_names else None
    pipeline = make_pipeline(mesh, stage_fn, batch_axis=batch_axis)

    def forward(pp, cat, num):
        x = embed_mod.apply({"params": pp["embed"]}, cat, num)  # [N, S, H]
        n = x.shape[0]
        xm = x.reshape(micro, n // micro, *x.shape[1:])
        y = pipeline(pp["stages"], xm).reshape(n, *x.shape[1:])
        return head_mod.apply({"params": pp["head"]}, y)

    optimizer = make_optimizer(train_config)

    decay = train_config.ema_decay

    def step(pp, opt_state, ema, cat, num, lab):
        def loss_of(p):
            return sigmoid_bce(forward(p, cat, num), lab, train_config.pos_weight)

        loss, grads = jax.value_and_grad(loss_of)(pp)
        updates, opt_state = optimizer.update(grads, opt_state, pp)
        pp = optax.apply_updates(pp, updates)
        if decay:  # static at trace time; ema=None threads through otherwise
            ema = update_ema(ema, pp, decay)
        return pp, opt_state, ema, loss

    # Placement: stage-stacked leaves shard their leading axis over
    # 'stage'; embed/head replicate. The optimizer state inherits the
    # layout through optax's zeros_like init; jit then propagates the
    # committed shardings instead of needing explicit in_shardings over
    # the whole adamw state tree.
    rep = NamedSharding(mesh, P())
    stage_sh = NamedSharding(mesh, P("stage"))
    pp_params = {
        "embed": jax.device_put(pp_params["embed"], rep),
        "stages": jax.device_put(pp_params["stages"], stage_sh),
        "head": jax.device_put(pp_params["head"], rep),
    }
    opt_state = optimizer.init(pp_params)
    # No donation: the dataclass exposes the initial params/opt_state, and
    # a donated first step would delete those buffers on TPU (the fit()
    # donation bug class) — for this trainer activations dominate memory,
    # so donation buys ~nothing.
    # zeros_like inherits each leaf's committed sharding, so the EMA
    # shadow lives stage-sharded next to its param with no collectives.
    ema0 = jax.tree_util.tree_map(jnp.zeros_like, pp_params) if decay else None
    return PPTrainStep(
        forward_fn=jax.jit(forward),
        step_fn=jax.jit(step),  # tpulint: disable=TPU105
        params=pp_params,
        opt_state=opt_state,
        stages=stages,
        microbatches=micro,
        ema=ema0,
    )
