"""Temperature scaling — calibrated probabilities for the served model.

The reference serves raw ``predict_proba`` scores with no calibration
step anywhere (`02-register-model.ipynb:330-353`); tree-ensemble and
neural-net scores are both routinely over/under-confident. Here every
bundle carries a temperature fitted on the held-out validation split:
serving divides the model's logit by it before the sigmoid, which
leaves rankings (AUC) and any threshold decision unchanged while making
the probabilities honest (minimum validation NLL).

One parameter, one convex objective: with ``s = 1/T`` the NLL
``mean(softplus(s·z) - y·s·z)`` is convex in ``s`` (softplus is convex,
the rest is linear), so a golden-section search on ``log s`` finds the
global optimum without gradients or scipy.
"""

from __future__ import annotations

import math

import numpy as np

_PHI = (math.sqrt(5.0) - 1.0) / 2.0  # golden ratio step

# The one clip epsilon shared by calibration fitting and every serving
# path that rebuilds logits from probabilities (sklearn flavor); keeps
# fit-time and serve-time transforms exactly inverse of each other.
PROB_EPS = 1e-7


def probs_to_logits(probs: np.ndarray) -> np.ndarray:
    """Inverse sigmoid with the shared clip (tree ensembles emit exact 0/1)."""
    p = np.clip(np.asarray(probs, np.float64), PROB_EPS, 1.0 - PROB_EPS)
    return np.log(p) - np.log1p(-p)


def apply_temperature(probs: np.ndarray, temperature: float) -> np.ndarray:
    """Re-scale probabilities through logit space: sigmoid(logit(p) / T)."""
    if temperature == 1.0:
        return np.asarray(probs)
    return 1.0 / (1.0 + np.exp(-probs_to_logits(probs) / temperature))


def binary_nll(logits: np.ndarray, labels: np.ndarray) -> float:
    """Mean negative log-likelihood of sigmoid(logits) vs 0/1 labels."""
    z = np.asarray(logits, np.float64)
    y = np.asarray(labels, np.float64)
    # softplus(z) - y*z, with the stable softplus identity for large |z|
    softplus = np.logaddexp(0.0, z)
    return float(np.mean(softplus - y * z))


def fit_temperature(
    logits: np.ndarray,
    labels: np.ndarray,
    log_s_range: tuple[float, float] = (-4.0, 4.0),
    iters: int = 80,
) -> float:
    """Fit T minimizing validation NLL of ``sigmoid(logits / T)``.

    Golden-section over ``log s`` (``s = 1/T``) on a convex objective;
    80 iterations brackets the optimum to ~1e-16 of the range width.
    """
    z = np.asarray(logits, np.float64)
    y = np.asarray(labels, np.float64)
    if z.size == 0 or np.unique(y).size < 2:
        return 1.0  # degenerate split: calibration undefined, identity T

    def nll_of(log_s: float) -> float:
        return binary_nll(z * math.exp(log_s), y)

    lo, hi = log_s_range
    a, b = lo, hi
    c = b - _PHI * (b - a)
    d = a + _PHI * (b - a)
    fc, fd = nll_of(c), nll_of(d)
    for _ in range(iters):
        if fc < fd:
            b, d, fd = d, c, fc
            c = b - _PHI * (b - a)
            fc = nll_of(c)
        else:
            a, c, fc = c, d, fd
            d = a + _PHI * (b - a)
            fd = nll_of(d)
    log_s = (a + b) / 2.0
    return float(math.exp(-log_s))  # T = 1/s


def calibration_record(
    logits: np.ndarray, labels: np.ndarray
) -> dict[str, float]:
    """Fit T and report before/after validation NLL for the manifest."""
    temperature = fit_temperature(logits, labels)
    z = np.asarray(logits, np.float64)
    return {
        "temperature": round(temperature, 6),
        "val_nll_uncalibrated": round(binary_nll(z, labels), 6),
        "val_nll_calibrated": round(binary_nll(z / temperature, labels), 6),
    }
