"""Tensor-parallel training as a product configuration.

``model.tensor_parallel = K`` promotes the DP×TP library step
(`parallel/steps.py make_sharded_train_step` — Megatron column/row/head
PARAM_RULES over a ('data','model') mesh) to a first-class training
config, the way ``pipeline_stages`` promotes GPipe: the CLI `train`
dispatches here, checkpoints resume onto the mesh layout, and the result
packages into a normal servable bundle.

The reference's analogue is single-process sklearn — no distributed
training exists there (SURVEY.md §2.7 notes the gap); this is the
TPU-native capability the survey's §2.7 TP row obligates: "pjit +
NamedSharding over a ('data','model') mesh for the FT-Transformer/BERT
configs" (SURVEY.md:190).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

import jax
import jax.numpy as jnp

from mlops_tpu.config import Config
from mlops_tpu.parallel.mesh import make_mesh
from mlops_tpu.parallel.steps import make_sharded_train_step
from mlops_tpu.train.loop import TrainState, make_optimizer

# Families with Flax param trees the PARAM_RULES know how to lay out.
# gbm/rf are CPU tree baselines with no param tree to shard.
TP_FAMILIES = ("mlp", "linear", "ft_transformer", "bert", "moe")


@dataclasses.dataclass
class TPTrainer:
    """Everything the TP training loop + dryrun need from one builder, so
    the product path and the driver's multichip dryrun compile the SAME
    config-derived program."""

    model: Any
    step_fn: Callable  # (TrainState, cat, num, lab, dropout_rng) -> (state, loss)
    state: TrainState  # initial (or graft-initialized) state
    shardings: TrainState  # NamedSharding tree matching ``state``
    mesh: Any

    # _layout_run_setup compatibility: the shared resume helper restores
    # {params, opt_state[, ema]} via these attributes.
    @property
    def params(self):
        return self.state.params

    @property
    def opt_state(self):
        return self.state.opt_state

    @property
    def ema(self):
        return self.state.ema


def make_tp_trainer(
    config: Config,
    mesh=None,
    init_variables: Any | None = None,
    compile_cache: Any | None = None,
) -> TPTrainer:
    """Build the DP×TP trainer for a ``model.tensor_parallel=K`` config.

    The mesh defaults to ('data', 'model') over ALL visible devices with
    the 'model' axis sized K — on a v5e slice the TP collectives
    (column/row all-gathers and reduce-scatters) ride ICI between
    adjacent chips. ``init_variables`` grafts a pretrained dense tree
    (same mechanism as the dense/PP fine-tune paths).
    """
    from mlops_tpu.models import build_model, init_params

    mcfg = config.model
    k = mcfg.tensor_parallel
    if k < 2:
        raise ValueError(
            f"make_tp_trainer needs model.tensor_parallel >= 2, got {k}"
        )
    if mcfg.family not in TP_FAMILIES:
        raise ValueError(
            f"tensor_parallel applies to the Flax families {TP_FAMILIES}, "
            f"not {mcfg.family!r}"
        )
    if mesh is None:
        n_dev = len(jax.devices())
        if n_dev % k:
            raise ValueError(
                f"model.tensor_parallel={k} needs the device count to be a "
                f"multiple of it; have {n_dev} (run on a v5e slice or the "
                f"fake {k}-device env)"
            )
        mesh = make_mesh(n_dev, model_parallel=k)
    elif mesh.shape.get("model", 1) != k:
        raise ValueError(
            f"config tensor_parallel={k} != mesh 'model' axis "
            f"{mesh.shape.get('model', 1)}"
        )
    dp = mesh.shape.get("data", 1)
    if config.train.batch_size % dp:
        # Fail with a named error before any training state exists — the
        # sharded step would otherwise die mid-run with an opaque XLA
        # "dimension not divisible" error (the PP trainer's guard class).
        raise ValueError(
            f"train.batch_size={config.train.batch_size} must divide by "
            f"the mesh 'data' axis {dp} (devices / tensor_parallel)"
        )

    # The MODEL is the plain dense family — TP is a layout, not a
    # different network (the same invariant the PP path pins with
    # forward-equality tests). Build it WITHOUT the layout knob so the
    # packaged bundle serves through the standard dense engine.
    model = build_model(dataclasses.replace(mcfg, tensor_parallel=0))
    variables = init_variables or init_params(
        model, jax.random.PRNGKey(config.train.seed)
    )
    params = variables["params"]
    optimizer = make_optimizer(config.train)
    step_fn, shardings = make_sharded_train_step(
        model, optimizer, config.train, mesh, params
    )
    state = TrainState(
        params=params,
        opt_state=optimizer.init(params),
        step=jnp.asarray(0, jnp.int32),
        rng=jax.random.PRNGKey(config.train.seed),
        ema=(
            jax.tree_util.tree_map(jnp.zeros_like, params)
            if config.train.ema_decay
            else None
        ),
    )
    if compile_cache is None:
        from mlops_tpu.compilecache.cache import from_config

        compile_cache = from_config(config)
    if compile_cache is not None:
        # AOT-load the pjit step through the persistent executable cache
        # (entry ``train-step-tp``), keyed by mesh shape + state/batch
        # signature; any OTHER batch shape falls back to the jitted step
        # so the cached executable is never fed a novel signature. On
        # backends where the donated state makes a deserialized executable
        # unsafe, the cache layer bypass-compiles (compilecache/cache.py).
        from mlops_tpu.compilecache.warmup import tp_step_job

        batch = config.train.batch_size
        aot_step = compile_cache.load_or_compile(
            tp_step_job(
                model, optimizer, config.train, mesh, state, batch, step_fn
            )
        )
        jit_step = step_fn

        def step_fn(state, cat, num, lab, rng):  # noqa: F811 - guarded swap
            run = aot_step if cat.shape[0] == batch else jit_step
            return run(state, cat, num, lab, rng)

    return TPTrainer(
        model=model, step_fn=step_fn, state=state, shardings=shardings,
        mesh=mesh,
    )
