"""Hyperparameter search: vmapped trials, sharded across the mesh.

The reference runs 10 sequential hyperopt-TPE trials, each re-reading the
dataset from Spark and re-fitting sklearn pipelines
(`01-train-model.ipynb:252-360`), then selects the best child run by
``validation_roc_auc_score DESC`` via ``mlflow.search_runs`` (cell 10).

TPU-native restatement: trials with a shared architecture differ only in
*continuous* hyperparameters (learning rate, weight decay, positive-class
weight), so the ENTIRE per-trial training loop is ``vmap``-ed over a stacked
trial axis and the trial axis is sharded over the mesh's 'data' axis — T
trials train simultaneously, one compiled program, zero Python in the loop.
Selection uses the same objective ordering as the reference. Architecture
sweeps (different shapes) run as an outer Python loop over vmapped groups:
``run_architecture_hpo`` parses ``hpo.architectures`` specs into per-group
``ModelConfig``s, runs one vmapped sweep per group, and selects across ALL
trials of ALL groups by the same metric ordering — the structural analogue
of the reference's ``n_estimators``/``max_depth``/``criterion`` space
(`01-train-model.ipynb:342-353`).
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from mlops_tpu.config import HPOConfig, ModelConfig, TrainConfig
from mlops_tpu.data.encode import EncodedDataset
from mlops_tpu.models import build_model
from mlops_tpu.schema.features import SCHEMA
from mlops_tpu.train.loop import training_loss, update_ema
from mlops_tpu.train.metrics import binary_metrics


@dataclasses.dataclass
class HPOResult:
    best_index: int
    best_hyperparams: dict[str, Any]  # floats, plus structural fields
    # (family/hidden_dims/...) when an architecture sweep selected them
    best_params: Any  # param pytree of the winning trial
    best_metrics: dict[str, float]
    trials: list[dict[str, Any]]  # per-trial {hyperparams, metrics}


def parse_architecture_spec(spec: str, base: ModelConfig) -> ModelConfig:
    """One ``hpo.architectures`` entry -> a ModelConfig.

    Spec grammar: comma-separated ``field=value`` overrides of any
    ModelConfig field; tuple fields use ``x`` as the element separator
    (``hidden_dims=64x64``) because ``,`` is the pair separator. Values
    coerce by the field's current type, same rules as the config loader.
    """
    overrides: dict[str, Any] = {}
    for pair in spec.split(","):
        pair = pair.strip()
        if not pair:
            continue
        field, sep, raw = pair.partition("=")
        field = field.strip()
        if not sep or not hasattr(base, field):
            raise ValueError(
                f"bad architecture spec {spec!r}: "
                f"{pair!r} is not a ModelConfig field=value override"
            )
        current = getattr(base, field)
        if isinstance(current, tuple):
            inner = type(current[0]) if current else int
            overrides[field] = tuple(
                inner(x) for x in raw.strip().split("x") if x.strip()
            )
        elif isinstance(current, bool):
            overrides[field] = raw.strip().lower() in ("1", "true", "yes", "on")
        elif isinstance(current, int):
            overrides[field] = int(raw)
        elif isinstance(current, float):
            overrides[field] = float(raw)
        else:
            overrides[field] = raw.strip()
    result = dataclasses.replace(base, **overrides)
    from mlops_tpu.models import FAMILIES

    if result.family not in FAMILIES:
        # Fail at parse time, not after earlier groups already trained:
        # the vmapped sweep trains Flax families only (sklearn gbm/rf go
        # through `train`, same guard as run_tuning).
        raise ValueError(
            f"bad architecture spec {spec!r}: family {result.family!r} is "
            f"not vmappable (Flax families: {FAMILIES})"
        )
    return result


def sample_hyperparams(config: HPOConfig) -> dict[str, np.ndarray]:
    """Log-uniform lr/weight-decay, uniform pos_weight — stacked [T] arrays.

    (The reference's space is RandomForest-shaped — trees/depth/criterion,
    `01-train-model.ipynb:342-353`; the neural equivalent knobs are the
    optimizer's.) Bounds come from the config (``lr_log10`` etc.), not
    hardcoded ranges.
    """
    rng = np.random.default_rng(config.seed)
    t = config.trials
    return {
        "learning_rate": 10 ** rng.uniform(*config.lr_log10, t),
        "weight_decay": 10 ** rng.uniform(*config.wd_log10, t),
        "pos_weight": rng.uniform(*config.pos_weight_range, t),
    }


def run_hpo(
    model_config: ModelConfig,
    train_config: TrainConfig,
    hpo_config: HPOConfig,
    train_ds: EncodedDataset,
    valid_ds: EncodedDataset,
    mesh=None,
) -> HPOResult:
    """Train all trials simultaneously and pick the objective winner.
    ``hpo.strategy="sha"`` routes to successive halving (`run_sha`)."""
    if hpo_config.strategy == "sha":
        return run_sha(
            model_config, train_config, hpo_config, train_ds, valid_ds,
            mesh=mesh,
        )
    if hpo_config.strategy != "random":
        raise ValueError(
            f"hpo.strategy must be 'random' or 'sha', not "
            f"{hpo_config.strategy!r}"
        )
    model = build_model(model_config)
    t = hpo_config.trials
    steps = hpo_config.steps
    batch = train_config.batch_size

    hp = sample_hyperparams(hpo_config)
    # Pad the trial axis up to a multiple of the mesh's data axis so trial
    # sharding always engages (a 10-trial default on an 8-chip mesh would
    # otherwise silently fall back to one device). Padded trials re-run the
    # leading hyperparams and are dropped before selection.
    axis = mesh.devices.shape[0] if mesh is not None else 1
    t_run = ((t + axis - 1) // axis) * axis
    if t_run != t:
        # np.resize cycles the leading trials, so this is correct even when
        # the pad amount exceeds the trial count (e.g. 3 trials on 8 chips).
        hp_run = {k: np.resize(v, t_run) for k, v in hp.items()}
    else:
        hp_run = hp
    lrs = jnp.asarray(hp_run["learning_rate"], jnp.float32)
    wds = jnp.asarray(hp_run["weight_decay"], jnp.float32)
    pws = jnp.asarray(hp_run["pos_weight"], jnp.float32)
    rngs = jax.random.split(jax.random.PRNGKey(hpo_config.seed), t_run)

    cat = jnp.asarray(train_ds.cat_ids)
    num = jnp.asarray(train_ds.numeric)
    lab = jnp.asarray(train_ds.labels, dtype=jnp.float32)
    vcat = jnp.asarray(valid_ds.cat_ids)
    vnum = jnp.asarray(valid_ds.numeric)
    vlab = jnp.asarray(valid_ds.labels, dtype=jnp.float32)
    n = cat.shape[0]

    def train_one(lr, wd, pw, rng):
        init_rng, loop_rng = jax.random.split(rng)
        dummy_cat = jnp.zeros((2, SCHEMA.num_categorical), jnp.int32)
        dummy_num = jnp.zeros((2, SCHEMA.num_numeric), jnp.float32)
        params = model.init({"params": init_rng}, dummy_cat, dummy_num,
                            train=False)["params"]

        # Warmup-cosine schedule written out by hand: optax's constructor
        # bool-checks peak_value, which fails when lr is a vmapped tracer.
        warmup = max(1, steps // 20)

        def schedule(step):
            step = step.astype(jnp.float32)
            warm = lr * step / warmup
            progress = jnp.clip((step - warmup) / max(steps - warmup, 1), 0.0, 1.0)
            cosine = lr * (0.05 + 0.95 * 0.5 * (1.0 + jnp.cos(jnp.pi * progress)))
            return jnp.where(step < warmup, warm, cosine)

        optimizer = optax.chain(
            optax.clip_by_global_norm(1.0),
            optax.adamw(schedule, weight_decay=wd),
        )
        opt_state = optimizer.init(params)
        # Per-trial Polyak EMA rides the scan carry (one shadow tree per
        # trial under vmap); the trial's RETURNED params are the debiased
        # average, so selection grades exactly what would be packaged —
        # the same invariant loop.fit keeps.
        decay = train_config.ema_decay
        ema = (
            jax.tree_util.tree_map(jnp.zeros_like, params) if decay else None
        )

        def one_step(carry, i):
            params, opt_state, ema = carry
            step_rng = jax.random.fold_in(loop_rng, i)
            idx_rng, dropout_rng = jax.random.split(step_rng)
            idx = jax.random.randint(idx_rng, (batch,), 0, n)

            def loss_of(p):
                return training_loss(
                    model, p, cat[idx], num[idx], lab[idx], dropout_rng, pw
                )

            loss, grads = jax.value_and_grad(loss_of)(params)
            updates, opt_state = optimizer.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            if decay:  # static at trace time
                ema = update_ema(ema, params, decay)
            return (params, opt_state, ema), loss

        (params, _, ema), _ = jax.lax.scan(
            one_step, (params, opt_state, ema), jnp.arange(steps)
        )
        if decay:
            # steps is static, so the bias correction is a plain float.
            params = jax.tree_util.tree_map(
                lambda e: e / (1.0 - decay**steps), ema
            )
        logits = model.apply({"params": params}, vcat, vnum, train=False)
        metrics = binary_metrics(logits, vlab)
        return params, metrics

    vmapped = jax.vmap(train_one)
    if mesh is not None:
        trial_shard = NamedSharding(mesh, P("data"))
        key_shard = NamedSharding(mesh, P("data", None))
        run = jax.jit(
            vmapped,
            in_shardings=(trial_shard, trial_shard, trial_shard, key_shard),
        )
    else:
        run = jax.jit(vmapped)
    stacked_params, stacked_metrics = run(lrs, wds, pws, rngs)
    stacked_metrics = {k: np.asarray(v)[:t] for k, v in stacked_metrics.items()}

    # Parity: order_by objective DESC — but a diverged trial's NaN metric
    # must never win (np.argmax would return it).
    objective = stacked_metrics[hpo_config.objective]
    finite = np.isfinite(objective)
    if not finite.any():
        raise RuntimeError(
            f"all {t} trials produced non-finite "
            f"{hpo_config.objective}: {objective.tolist()}"
        )
    best = int(np.argmax(np.where(finite, objective, -np.inf)))
    best_params = jax.tree_util.tree_map(
        lambda leaf: np.asarray(leaf[best]), stacked_params
    )
    trials = [
        {
            "hyperparams": {k: float(v[i]) for k, v in hp.items()},
            "metrics": {
                f"validation_{k}_score": float(v[i])
                for k, v in stacked_metrics.items()
            },
        }
        for i in range(t)
    ]
    return HPOResult(
        best_index=best,
        best_hyperparams=trials[best]["hyperparams"],
        best_params=best_params,
        best_metrics=trials[best]["metrics"],
        trials=trials,
    )


def run_sha(
    model_config: ModelConfig,
    train_config: TrainConfig,
    hpo_config: HPOConfig,
    train_ds: EncodedDataset,
    valid_ds: EncodedDataset,
    mesh=None,
) -> HPOResult:
    """Successive halving: the ADAPTIVE sweep (VERDICT r4 #6).

    The reference ran adaptive TPE (`01-train-model.ipynb:349`); random
    search spends most of a 32-trial budget on obvious losers. SHA fixes
    that at EQUAL step budget: train all N candidates one rung in one
    vmapped compiled program, keep the top 1/eta by the objective,
    continue ONLY the survivors (optimizer state and all) for the next
    rung. Rung length is ``trials*steps / sum(survivor counts)``, so the
    total step budget never exceeds random search's — it just
    concentrates on candidates that earn it. Trials eliminated at rung r
    are recorded with the metrics they died with.

    Mesh-path budget caveat (ADVICE r5): on a multi-device mesh each
    rung's survivor set is padded up to a multiple of the 'data' axis
    with CYCLED DUPLICATE trials (``pad_to_axis``) so the vmapped rung
    shards evenly — the duplicates train full rungs but are dropped at
    selection. The advertised "total step budget <= trials*steps" (and
    `scripts/sha_vs_random.py`'s sum over trials) counts LOGICAL trials
    only, so real device-step spend on a mesh exceeds the reported
    budget by up to ``(axis - 1) / axis`` per rung of the padded slots —
    e.g. 2 survivors padded to an 8-way data axis run 4x the logical
    steps that rung. Single-device runs (axis=1) pad nothing and report
    exactly.
    """
    model = build_model(model_config)
    n0 = hpo_config.trials
    eta = max(2, hpo_config.eta)
    rungs = max(1, hpo_config.sha_rungs)
    counts = [max(1, n0 // eta**r) for r in range(rungs)]
    rung_steps = max(1, (n0 * hpo_config.steps) // sum(counts))
    horizon = rung_steps * rungs  # a finalist's total steps (schedule span)
    batch = train_config.batch_size
    decay = train_config.ema_decay
    axis = mesh.devices.shape[0] if mesh is not None else 1

    hp = sample_hyperparams(hpo_config)
    cat = jnp.asarray(train_ds.cat_ids)
    num = jnp.asarray(train_ds.numeric)
    lab = jnp.asarray(train_ds.labels, dtype=jnp.float32)
    vcat = jnp.asarray(valid_ds.cat_ids)
    vnum = jnp.asarray(valid_ds.numeric)
    vlab = jnp.asarray(valid_ds.labels, dtype=jnp.float32)
    n = cat.shape[0]
    warmup = max(1, horizon // 20)

    def make_optimizer(lr, wd):
        # Same handwritten warmup-cosine as run_hpo (optax's constructor
        # bool-checks peak_value, which fails on vmapped tracers), spanned
        # over the FULL horizon — an early-eliminated trial simply never
        # reaches the schedule tail.
        def schedule(step):
            step = step.astype(jnp.float32)
            warm = lr * step / warmup
            progress = jnp.clip(
                (step - warmup) / max(horizon - warmup, 1), 0.0, 1.0
            )
            cosine = lr * (0.05 + 0.95 * 0.5 * (1.0 + jnp.cos(jnp.pi * progress)))
            return jnp.where(step < warmup, warm, cosine)

        return optax.chain(
            optax.clip_by_global_norm(1.0),
            optax.adamw(schedule, weight_decay=wd),
        )

    def init_one(lr, wd, rng):
        dummy_cat = jnp.zeros((2, SCHEMA.num_categorical), jnp.int32)
        dummy_num = jnp.zeros((2, SCHEMA.num_numeric), jnp.float32)
        params = model.init(
            {"params": rng}, dummy_cat, dummy_num, train=False
        )["params"]
        opt_state = make_optimizer(lr, wd).init(params)
        ema = jax.tree_util.tree_map(jnp.zeros_like, params) if decay else None
        return params, opt_state, ema

    def segment(lr, wd, pw, rng, params, opt_state, ema, start_step):
        """One rung: ``rung_steps`` more steps continuing from the carry.
        Batch rng folds in the GLOBAL step so a continued trial never
        replays its previous rung's batches."""
        optimizer = make_optimizer(lr, wd)

        def one_step(carry, i):
            params, opt_state, ema = carry
            step_rng = jax.random.fold_in(rng, start_step + i)
            idx_rng, dropout_rng = jax.random.split(step_rng)
            idx = jax.random.randint(idx_rng, (batch,), 0, n)

            def loss_of(p):
                return training_loss(
                    model, p, cat[idx], num[idx], lab[idx], dropout_rng, pw
                )

            loss, grads = jax.value_and_grad(loss_of)(params)
            updates, opt_state = optimizer.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            if decay:
                ema = update_ema(ema, params, decay)
            return (params, opt_state, ema), loss

        (params, opt_state, ema), _ = jax.lax.scan(
            one_step, (params, opt_state, ema), jnp.arange(rung_steps)
        )
        return params, opt_state, ema

    def pad_to_axis(arr_idx: np.ndarray) -> np.ndarray:
        k = arr_idx.shape[0]
        k_pad = ((k + axis - 1) // axis) * axis
        return np.resize(arr_idx, k_pad)

    # Stacked state for the CURRENT survivor set; [k_pad, ...] leaves.
    lrs = jnp.asarray(hp["learning_rate"], jnp.float32)
    wds = jnp.asarray(hp["weight_decay"], jnp.float32)
    pws = jnp.asarray(hp["pos_weight"], jnp.float32)
    all_rngs = jax.random.split(jax.random.PRNGKey(hpo_config.seed), n0)

    live = pad_to_axis(np.arange(n0))  # indices into the ORIGINAL trials
    valid_k = n0

    def take_hp(idx):
        sel = jnp.asarray(idx)
        return lrs[sel], wds[sel], pws[sel], all_rngs[sel]

    s_lr, s_wd, s_pw, s_rng = take_hp(live)
    params, opt_state, ema = jax.vmap(init_one)(s_lr, s_wd, s_rng)

    trials: list[dict[str, Any]] = [None] * n0  # filled at elimination
    vseg = jax.jit(jax.vmap(segment, in_axes=(0, 0, 0, 0, 0, 0, 0, None)))
    if mesh is not None:
        # Trial axis shards over 'data' exactly as run_hpo's sweep; the
        # per-rung shapes differ, so each rung is its own compile (the
        # architecture-group precedent: shapes differ -> separate
        # compiles). The survivor gather leaves state replicated, so each
        # rung re-places it onto the trial sharding instead of pinning
        # in_shardings (which would reject the gathered layout).
        tsh = NamedSharding(mesh, P("data"))
        ksh = NamedSharding(mesh, P("data", None))

        def place(hp_state, key_arr, state):
            return (
                jax.device_put(hp_state, tsh),
                jax.device_put(key_arr, ksh),
                jax.device_put(state, tsh),
            )
    else:
        place = None
    veval = jax.vmap(
        lambda p: binary_metrics(
            model.apply({"params": p}, vcat, vnum, train=False), vlab
        )
    )

    steps_done = 0
    for r in range(rungs):
        if place is not None:
            (s_lr, s_wd, s_pw), s_rng, (params, opt_state, ema) = place(
                (s_lr, s_wd, s_pw), s_rng, (params, opt_state, ema)
            )
        params, opt_state, ema = vseg(
            s_lr, s_wd, s_pw, s_rng, params, opt_state, ema, steps_done
        )
        steps_done += rung_steps
        eval_tree = params
        if decay:
            eval_tree = jax.tree_util.tree_map(
                lambda e: e / (1.0 - decay**steps_done), ema
            )
        metrics = {k: np.asarray(v) for k, v in veval(eval_tree).items()}
        objective = metrics[hpo_config.objective][: valid_k]
        finite = np.isfinite(objective)
        if not finite.any():
            raise RuntimeError(
                f"sha rung {r}: all {valid_k} trials produced non-finite "
                f"{hpo_config.objective}: {objective.tolist()}"
            )
        ranked = np.argsort(np.where(finite, objective, -np.inf))[::-1]
        keep = (
            max(1, valid_k // eta) if r < rungs - 1 else valid_k
        )
        # Record every trial's metrics as of THIS rung (survivors get
        # overwritten at later rungs with fresher numbers).
        for local_i in range(valid_k):
            gi = int(live[local_i])
            trials[gi] = {
                "hyperparams": {k: float(v[gi]) for k, v in hp.items()},
                "metrics": {
                    f"validation_{k}_score": float(v[local_i])
                    for k, v in metrics.items()
                },
                "rung": r,
                "steps": steps_done,
            }
        if r == rungs - 1:
            best_local = int(ranked[0])
            break
        survivors = ranked[:keep]
        live = pad_to_axis(live[survivors])
        valid_k = keep
        # np.resize cycles indices exactly the way pad_to_axis cycled
        # `live`, so the gathered state stays aligned with take_hp(live).
        sel = jnp.asarray(np.resize(survivors, len(live)))
        params, opt_state, ema = jax.tree_util.tree_map(
            lambda a: a[sel], (params, opt_state, ema)
        )
        s_lr, s_wd, s_pw, s_rng = take_hp(live)

    best = int(live[best_local])
    best_tree = eval_tree
    best_params = jax.tree_util.tree_map(
        lambda leaf: np.asarray(leaf[best_local]), best_tree
    )
    return HPOResult(
        best_index=best,
        best_hyperparams=trials[best]["hyperparams"],
        best_params=best_params,
        best_metrics=trials[best]["metrics"],
        trials=trials,
    )


def _dataset_digest(ds) -> str:
    """Content digest of an encoded dataset. Row count alone is not an
    identity: a retried sweep reusing the same run_name with different
    data of the SAME size (new data.seed, updated train_path file) must
    not restore stale cached group results. Full arrays, not a strided
    sample — this tabular dataset is a few MB and blake2b hashes that in
    milliseconds, while a sample would miss small in-place edits."""
    import hashlib

    h = hashlib.blake2b(digest_size=16)
    h.update(np.ascontiguousarray(ds.cat_ids).tobytes())
    h.update(np.ascontiguousarray(ds.numeric).tobytes())
    if ds.labels is not None:
        h.update(np.ascontiguousarray(ds.labels).tobytes())
    return h.hexdigest()


def _group_fingerprint(
    cfg: ModelConfig, group_hpo: HPOConfig, train_config: TrainConfig, train_ds
) -> str:
    """Everything a completed group's cached result is valid for: the FULL
    group ModelConfig (not just the spec-overridden fields — an edit to a
    base field like precision or dropout must invalidate too), the sweep
    shape/seed/objective, the training recipe, and the dataset identity
    (row count + content digest)."""
    import json

    return json.dumps(
        {
            "model": dataclasses.asdict(cfg),
            # The FULL sweep config: strategy/eta/rungs and the search
            # ranges are selection-relevant, not just trials/steps/seed.
            "hpo": dataclasses.asdict(group_hpo),
            "train": dataclasses.asdict(train_config),
            "rows": train_ds.n,
            "data_digest": _dataset_digest(train_ds),
        },
        sort_keys=True,
        default=str,
    )


def _save_group_result(resume_dir, g: int, fingerprint: str, res: HPOResult):
    """Persist a finished group so a retried sweep skips its recompute:
    JSON record + the winning params as msgpack (restored against a
    fresh init of the group's architecture)."""
    import json

    from mlops_tpu.train.checkpoint import tree_bytes
    from mlops_tpu.utils.io import atomic_write

    directory = resume_dir / "hpo_groups"
    directory.mkdir(parents=True, exist_ok=True)
    atomic_write(directory / f"group_{g}.msgpack", tree_bytes(res.best_params))
    atomic_write(
        directory / f"group_{g}.json",
        json.dumps(
            {
                "fingerprint": fingerprint,
                "best_index": res.best_index,
                "best_hyperparams": res.best_hyperparams,
                "best_metrics": res.best_metrics,
                "trials": res.trials,
            },
            default=float,
        ).encode(),
    )


def _load_group_result(resume_dir, g: int, fingerprint: str, cfg: ModelConfig):
    """Restore a finished group when its fingerprint still matches; None
    on any mismatch or unreadable/absent file (recompute)."""
    import json

    from mlops_tpu.models import init_params
    from mlops_tpu.train.checkpoint import restore_tree

    directory = resume_dir / "hpo_groups"
    try:
        meta = json.loads((directory / f"group_{g}.json").read_text())
        if meta["fingerprint"] != fingerprint:
            return None
        template = init_params(build_model(cfg), jax.random.PRNGKey(0))["params"]
        params = restore_tree(
            template, (directory / f"group_{g}.msgpack").read_bytes()
        )
        return HPOResult(
            best_index=int(meta["best_index"]),
            best_hyperparams=meta["best_hyperparams"],
            best_params=params,
            best_metrics=meta["best_metrics"],
            trials=meta["trials"],
        )
    except (OSError, ValueError, KeyError, TypeError, AttributeError):
        return None


def run_architecture_hpo(
    model_config: ModelConfig,
    train_config: TrainConfig,
    hpo_config: HPOConfig,
    train_ds: EncodedDataset,
    valid_ds: EncodedDataset,
    mesh=None,
    resume_dir=None,
) -> tuple[ModelConfig, HPOResult]:
    """Structural axis: loop architecture groups, vmap trials within each.

    Each ``hpo.architectures`` spec defines one group (a distinct set of
    shapes -> its own compile); within a group the continuous space is the
    usual vmapped sweep, seeded per-group so groups explore different
    lr/wd/pos_weight draws. The winner is the single best trial across
    every group, ordered by the SAME objective as the inner sweep (parity:
    ``mlflow.search_runs(order_by=[objective DESC])`` ranks all child runs
    of the joint TPE space together, `01-train-model.ipynb` cell 10).

    Returns ``(winning ModelConfig, merged HPOResult)``; the result's
    ``best_hyperparams`` carries the structural choices (family plus every
    overridden field) alongside the continuous ones, and each trial record
    gains ``group``/``architecture`` keys.
    """
    if not hpo_config.architectures:
        return model_config, run_hpo(
            model_config, train_config, hpo_config, train_ds, valid_ds, mesh=mesh
        )

    groups: list[tuple[ModelConfig, dict[str, Any]]] = []
    for spec in hpo_config.architectures:
        cfg = parse_architecture_spec(spec, model_config)
        overridden = {
            f.name: getattr(cfg, f.name)
            for f in dataclasses.fields(ModelConfig)
            if getattr(cfg, f.name) != getattr(model_config, f.name)
        }
        structural = {"family": cfg.family, **overridden}
        groups.append((cfg, structural))

    results: list[HPOResult] = []
    merged_trials: list[dict[str, Any]] = []
    for g, (cfg, structural) in enumerate(groups):
        group_hpo = dataclasses.replace(hpo_config, seed=hpo_config.seed + g)
        fingerprint = _group_fingerprint(cfg, group_hpo, train_config, train_ds)
        res = (
            _load_group_result(resume_dir, g, fingerprint, cfg)
            if resume_dir is not None
            else None
        )
        if res is None:
            res = run_hpo(
                cfg, train_config, group_hpo, train_ds, valid_ds, mesh=mesh
            )
            if resume_dir is not None:
                # Group-granular resume: a retried/preempted sweep (K8s
                # backoffLimit on the tune Job) recomputes only the
                # groups that had not finished.
                _save_group_result(resume_dir, g, fingerprint, res)
        results.append(res)
        for trial in res.trials:
            merged_trials.append(
                {"group": g, "architecture": structural, **trial}
            )

    def objective_of(res: HPOResult) -> float:
        v = res.best_metrics[f"validation_{hpo_config.objective}_score"]
        return v if np.isfinite(v) else -np.inf

    best_group = int(np.argmax([objective_of(r) for r in results]))
    winner = results[best_group]
    win_cfg, win_structural = groups[best_group]
    offset = sum(len(r.trials) for r in results[:best_group])
    # Tuples stringify for the report the same way the spec wrote them.
    surfaced = {
        k: ("x".join(map(str, v)) if isinstance(v, tuple) else v)
        for k, v in win_structural.items()
    }
    merged = HPOResult(
        best_index=offset + winner.best_index,
        best_hyperparams={**surfaced, **winner.best_hyperparams},
        best_params=winner.best_params,
        best_metrics=winner.best_metrics,
        trials=merged_trials,
    )
    return win_cfg, merged
