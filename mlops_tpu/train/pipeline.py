"""End-to-end training pipeline: data -> train -> monitor -> bundle -> registry.

This is the TPU-native restatement of the reference's two-notebook job
(`train_register_model_job`: notebook 01 trains + selects, notebook 02 fits
detectors + packages + registers — SURVEY.md SS3.2). One process, one data
read, typed artifacts instead of ``dbutils.jobs.taskValues`` handoffs.
"""

from __future__ import annotations

import dataclasses
import logging
import time
from pathlib import Path
from typing import Any

import jax
import numpy as np

from mlops_tpu.bundle import ModelRegistry, save_bundle
from mlops_tpu.config import Config
from mlops_tpu.data import (
    EncodedDataset,
    Preprocessor,
    generate_synthetic,
    load_table_columns,
)
from mlops_tpu.models import build_model
from mlops_tpu.models.gbm import SKLEARN_FAMILIES, SklearnBaseline
from mlops_tpu.monitor import fit_monitor
from mlops_tpu.train.loop import TrainResult, fit

logger = logging.getLogger("mlops_tpu.train")


@dataclasses.dataclass
class PipelineResult:
    bundle_dir: Path | None  # None only when this process is not the
    # multi-host coordinator (every trained model otherwise packages —
    # doc models as the 'doc' bundle flavor)
    model_uri: str | None
    train_result: TrainResult
    run_dir: Path


def new_run_dir(config: Config, run_name: str | None = None) -> Path:
    """The one place the run-directory convention lives:
    ``<registry.run_root>/<timestamp-or-name>/`` (used by train, tune and
    pretrain alike)."""
    run_dir = Path(config.registry.run_root) / (
        run_name or time.strftime("%Y%m%d-%H%M%S")
    )
    run_dir.mkdir(parents=True, exist_ok=True)
    return run_dir


def load_training_data(config: Config) -> tuple[dict[str, list], np.ndarray]:
    """CSV/Parquet if configured, else the synthetic generator (data layer
    contract; format dispatch on extension)."""
    if config.data.train_path:
        columns, labels = load_table_columns(
            config.data.train_path, require_target=True
        )
        return columns, labels
    return generate_synthetic(config.data.rows, seed=config.data.seed)


def split_dataset(
    ds: EncodedDataset, valid_fraction: float, seed: int = 2024
) -> tuple[EncodedDataset, EncodedDataset]:
    """Shuffled split (parity: ``train_test_split(random_state=2024)``,
    `01-train-model.ipynb` cell 7)."""
    rng = np.random.default_rng(seed)
    perm = rng.permutation(ds.n)
    n_valid = int(ds.n * valid_fraction)
    return ds.slice(perm[n_valid:]), ds.slice(perm[:n_valid])


def _fit_calibration(
    valid_ds: EncodedDataset, params: Any, model=None
) -> dict[str, float]:
    """Temperature-scale on the held-out split (train/calibrate.py): the
    bundle serves ``sigmoid(logit / T)`` instead of the reference's raw
    ``predict_proba`` (`02-register-model.ipynb:330-353` has no
    calibration step). ``model=None`` means the sklearn flavor, where
    ``params`` is the estimator and logits come from its probabilities."""
    import jax.numpy as jnp

    from mlops_tpu.train.calibrate import calibration_record, probs_to_logits

    if model is None:
        logits = probs_to_logits(
            params.predict_proba(valid_ds.cat_ids, valid_ds.numeric)
        )
    else:
        logits = np.asarray(
            model.apply(
                {"params": params},
                jnp.asarray(valid_ds.cat_ids),
                jnp.asarray(valid_ds.numeric),
                train=False,
            )
        )
    return calibration_record(logits, valid_ds.labels)


def _package_and_register(
    config: Config,
    run_dir: Path,
    params: Any,
    preprocessor: Preprocessor,
    train_ds: EncodedDataset,
    metrics: dict[str, float],
    bundle_tags: dict[str, str],
    registry_tags: dict[str, str],
    register: bool,
    calibration: dict[str, float] | None = None,
    model_config=None,
    bulk=None,
    quant=None,
) -> tuple[Path, str | None]:
    """Shared packaging tail: fit monitors, write the bundle, register it
    (notebook 02's role — `02-register-model.ipynb` cells 6-15).

    Multi-host cohorts (JobSet over DCN): every process computes
    identically, but only the coordinator writes the bundle and registry
    entry — N hosts registering N duplicate versions (and racing the
    index write) is the multi-host failure mode this guards.
    """
    from mlops_tpu.parallel.distributed import is_coordinator

    bundle_dir = run_dir / "bundle"
    if not is_coordinator():
        return bundle_dir, None
    monitor = fit_monitor(train_ds, config.monitor, seed=config.data.seed)
    save_bundle(
        bundle_dir,
        model_config if model_config is not None else config.model,
        params,
        preprocessor,
        monitor,
        metrics=metrics,
        tags=bundle_tags,
        calibration=calibration,
        bulk=bulk,
        quant=quant,
    )
    model_uri = None
    if register:
        registry = ModelRegistry(config.registry.root)
        model_uri = registry.register(
            config.registry.model_name, bundle_dir, tags=registry_tags
        )
    return bundle_dir, model_uri


_DISTILL_FAMILIES = ("ft_transformer", "moe", "bert")


def _maybe_distill(config, model_config, model, params, train_ds, valid_ds):
    """Package-time distillation gate: models whose per-row FLOPs lose CPU
    bulk scoring to the sklearn floor — ensembles (K× a small MLP) and
    the transformer families — get a bulk student (train/distill.py)
    unless train.distill_bulk turned it off. ``model`` is None on the
    sklearn path, which never distills (it IS the floor)."""
    expensive = (
        model_config.ensemble_size > 1
        or model_config.family in _DISTILL_FAMILIES
    )
    if model is None or not expensive or not config.train.distill_bulk:
        return None
    from mlops_tpu.train.distill import distill_for_bulk

    return distill_for_bulk(
        model,
        {"params": params},
        model_config,
        train_ds,
        valid_ds,
        seed=config.train.seed,
    )


def _maybe_distill_quant(config, model, params, train_ds, valid_ds):
    """Package-time quant-tier gate: opt-in (``train.distill_quant``),
    flax teachers only. The quantized student ships with its own fidelity
    record, refit temperature, and a STAMPED promotion decision
    (`lifecycle/promote.py quant_tier_gates`) — the engine admits the
    tier from the stamp alone."""
    if model is None or not config.train.distill_quant:
        return None
    from mlops_tpu.train.distill import distill_quant_student

    return distill_quant_student(
        model,
        {"params": params},
        train_ds,
        valid_ds,
        seed=config.train.seed,
        lifecycle=config.lifecycle,
    )


def run_training(
    config: Config,
    register: bool = True,
    run_name: str | None = None,
) -> PipelineResult:
    """Train one model per config and package it as a bundle.

    Steps (each replacing a reference stage):
      1. read + encode data once (vs per-trial Spark re-reads)
      2. ``fit`` the model (notebook 01's role)
      3. fit drift + outlier monitors on the training split (notebook 02
         cell 6)
      4. write the bundle (notebook 02's pyfunc ``log_model``)
      5. register it (notebook 02's ``register_model``), returning a
         ``models:/<name>/<version>`` URI
    """
    if config.model.uses_layout_trainer:
        # Loud, not silent: this entrypoint trains the single-record dense
        # model; a multi-device layout knob left set would otherwise train
        # a plain model without the requested parallelism and no warning.
        raise ValueError(
            "run_training trains the single-record dense model; "
            "multi-device training layouts have dedicated trainers "
            "(model.doc_records/seq_parallel -> train/long_context.py, "
            "model.pipeline_stages -> train/pipeline_parallel.py) — call "
            "run_layout_training, which the `train` CLI dispatches to "
            "automatically"
        )
    run_name = run_name or time.strftime("%Y%m%d-%H%M%S")
    run_dir = new_run_dir(config, run_name)

    columns, labels = load_training_data(config)
    preprocessor = Preprocessor.fit(columns)
    ds = preprocessor.encode(columns, labels)
    train_ds, valid_ds = split_dataset(ds, config.data.valid_fraction)

    calibration_model = None
    if config.model.family in SKLEARN_FAMILIES:
        # BASELINE config 1: the CPU tree-ensemble comparison floor, trained
        # and packaged through the exact same pipeline tail as the TPU models.
        baseline = SklearnBaseline.train(config.model, config.train, train_ds)
        result = TrainResult(
            params=baseline,
            metrics=baseline.evaluate(valid_ds),
            history=[],
            steps=config.model.n_estimators,
        )
    else:
        model = build_model(config.model)
        init_variables = None
        if config.train.init_params and config.model.ensemble_size > 1:
            raise ValueError(
                "train.init_params grafts a pretrained trunk by parameter "
                "name, which cannot target the vmapped member axis of an "
                "ensemble — use ensemble_size=1 for fine-tuning runs"
            )
        # Fine-tune from masked-feature pretraining (`pretrain` CLI):
        # trunk comes from the MLM run, heads stay freshly initialized.
        init_variables = _load_init_variables(config, model) or init_variables
        from mlops_tpu.compilecache.cache import from_config

        result = fit(
            model,
            train_ds,
            valid_ds,
            config.train,
            init_variables=init_variables,
            metrics_path=run_dir / "metrics.jsonl",
            checkpoint_dir=run_dir / "checkpoints",
            # cache.dir set -> the window scan deserializes from the
            # persistent executable cache instead of recompiling per run.
            compile_cache=from_config(config),
        )
        calibration_model = model

    calibration = _fit_calibration(valid_ds, result.params, calibration_model)
    bulk = _maybe_distill(
        config, config.model, calibration_model, result.params, train_ds, valid_ds
    )
    quant = _maybe_distill_quant(
        config, calibration_model, result.params, train_ds, valid_ds
    )
    bundle_dir, model_uri = _package_and_register(
        config,
        run_dir,
        result.params,
        preprocessor,
        train_ds,
        metrics=result.metrics,
        bundle_tags={
            "run_name": run_name,
            "experiment": config.registry.experiment_name,
        },
        registry_tags={
            "run_name": run_name,
            **{k: f"{v:.6f}" for k, v in result.metrics.items()},
        },
        register=register,
        calibration=calibration,
        bulk=bulk,
        quant=quant,
    )
    return PipelineResult(
        bundle_dir=bundle_dir,
        model_uri=model_uri,
        train_result=result,
        run_dir=run_dir,
    )


def run_layout_training(
    config: Config,
    register: bool = True,
    run_name: str | None = None,
) -> PipelineResult:
    """Real training runs for the multi-device layout configs the dense
    entrypoint rejects (the `train` CLI dispatches here automatically):

    - ``model.pipeline_stages=S``: GPipe trainer on a ``('data','stage')``
      mesh (`train/pipeline_parallel.py`). After training, the
      stage-stacked params MERGE back into the dense bert tree and flow
      through the normal calibrate → distill → package → register tail —
      a PP-trained model serves like any other bert bundle.
    - ``model.doc_records>1``: document-BERT trainer
      (`train/long_context.py`), on a ``('data','seq')`` ring mesh when
      ``seq_parallel`` is set. Document models read record HISTORIES, not
      the single-record serving contract, so the run saves params
      (msgpack) + metrics.jsonl instead of a serving bundle.

    Needs enough devices to host the mesh (a v5e-8 / JobSet in
    production, the fake 8-device CPU env in tests/CI); raises with the
    required count otherwise.
    """
    if not config.model.uses_layout_trainer:
        # The mirror of run_training's guard: a dense config routed here
        # would silently train a 1-record "document" model.
        raise ValueError(
            "run_layout_training needs a layout knob set "
            "(model.pipeline_stages / seq_parallel / doc_records>1); "
            "dense configs train via run_training"
        )
    _check_layout_knobs(config)
    if config.train.init_params:
        # Fail BEFORE the run dir and data load: an incompatible graft
        # must not leave an orphan run directory or pay the encode.
        if not (config.model.pipeline_stages or config.model.tensor_parallel):
            raise ValueError(
                "train.init_params is not supported for document training: "
                "the pretrained pos_embed covers one 48-token record, not "
                "a 2+46R document sequence"
            )
        if config.model.family != "bert":
            raise ValueError(
                "train.init_params grafts a bert masked-LM trunk; "
                f"family {config.model.family!r} shares no trunk with it"
            )
    run_name = run_name or time.strftime("%Y%m%d-%H%M%S")
    run_dir = new_run_dir(config, run_name)
    columns, labels = load_training_data(config)
    preprocessor = Preprocessor.fit(columns)
    ds = preprocessor.encode(columns, labels)
    train_ds, valid_ds = split_dataset(ds, config.data.valid_fraction)
    if config.model.pipeline_stages:
        return _run_pp_training(
            config, run_dir, run_name, preprocessor, train_ds, valid_ds, register
        )
    if config.model.tensor_parallel:
        return _run_tp_training(
            config, run_dir, run_name, preprocessor, train_ds, valid_ds, register
        )
    return _run_doc_training(
        config, run_dir, run_name, preprocessor, train_ds, valid_ds, register
    )


def _check_layout_knobs(config: Config) -> None:
    """Reject layout-knob combinations that have no trainer. Without this,
    the dispatch order would win silently and a config asking for two
    layouts would train only one — the silent-route class every other
    entry point (run_training / run_tuning / pretrain) guards loudly
    against."""
    knobs = {
        "pipeline_stages": bool(config.model.pipeline_stages),
        "tensor_parallel": bool(config.model.tensor_parallel),
        "doc_records>1/seq_parallel": (
            config.model.doc_records > 1 or config.model.seq_parallel
        ),
    }
    active = [name for name, on in knobs.items() if on]
    if len(active) > 1:
        raise ValueError(
            f"layout knobs {active} cannot combine: each selects its own "
            "trainer (PP / DP×TP / DP×SP documents); set exactly one"
        )


def _journal_max_step(path: Path) -> int:
    """Highest step already recorded in a metrics.jsonl (0 when absent):
    a resumed run must not append duplicate rows for eval steps that were
    journaled after the checkpoint it restored from. Bad lines are
    skipped per-line — a write truncated by the preemption itself must
    not blind the scan to the intact records before it."""
    import json

    best = 0
    try:
        lines = path.read_text().splitlines()
    except OSError:
        return 0
    for line in lines:
        try:
            best = max(best, int(json.loads(line)["step"]))
        except (ValueError, KeyError, TypeError):
            continue
    return best


def _batch_indices(n_rows: int, batch: int, seed: int, step: int) -> np.ndarray:
    """Minibatch indices for ONE step, seeded by (seed, step): the data
    order is a pure function of the step counter, so a checkpoint-resumed
    run sees exactly the batches the preempted run would have."""
    return np.random.default_rng((seed, step)).integers(0, n_rows, batch)


def _load_init_variables(config: Config, model) -> Any | None:
    """Graft the pretrained masked-LM trunk (``train.init_params``) into a
    fresh init of ``model``; None when unset. One helper for the dense
    and pipeline-parallel fine-tune paths."""
    if not config.train.init_params:
        return None
    from mlops_tpu.models import init_params as fresh_init
    from mlops_tpu.train.pretrain import load_pretrained_variables

    return load_pretrained_variables(
        config.train.init_params,
        config.model,
        fresh_init(model, jax.random.PRNGKey(config.train.seed)),
    )


def _layout_run_setup(tcfg, run_dir: Path, trainer):
    """The shared resume preamble for both layout loops: eval/checkpoint
    cadences (checkpoint_every=0 falls back to the eval window, as in
    ``fit``), state restore from the newest checkpoint, and the journal
    floor that suppresses duplicate metric rows on resume."""
    eval_every = max(1, min(tcfg.eval_every, tcfg.steps))
    ckpt_every = max(1, tcfg.checkpoint_every or eval_every)
    ckpt_dir = run_dir / "checkpoints"
    params, opt_state, ema, start_step = _restore_layout_state(
        ckpt_dir, trainer.params, trainer.opt_state, trainer.ema
    )
    journal_floor = _journal_max_step(run_dir / "metrics.jsonl")
    return (
        eval_every,
        ckpt_every,
        ckpt_dir,
        params,
        opt_state,
        ema,
        start_step,
        journal_floor,
    )


def _metric_writers(run_dir: Path, tcfg):
    """The layout loops' metric sinks — the ONE shared contract
    (`train/loop.py metric_writers`, also used by ``fit``): metrics.jsonl
    always, TensorBoard when ``train.tensorboard_dir`` is set."""
    from mlops_tpu.train.loop import metric_writers

    return metric_writers(run_dir / "metrics.jsonl", tcfg)


def _maybe_checkpoint(ckpt_dir, params, opt_state, ema, step, ckpt_every, steps):
    from mlops_tpu.train.checkpoint import save_checkpoint

    if step % ckpt_every == 0 or step == steps:
        state = {"params": params, "opt_state": opt_state}
        if ema is not None:
            # Only when enabled: the key's presence must match the resume
            # template, which is derived from the same config toggle.
            state["ema"] = ema
        save_checkpoint(ckpt_dir, jax.device_get(state), step)


def _final_validation_metrics(history, steps, fallback):
    """The loop's last eval IS the final metric set on any run that
    reached the step budget; ``fallback`` covers the zero-iteration
    resume (checkpoint already at/past the budget)."""
    if history and history[-1]["step"] == steps:
        return {
            k: v for k, v in history[-1].items() if k.startswith("validation_")
        }
    return fallback()


def _restore_layout_state(ckpt_dir, params, opt_state, ema=None):
    """Resume {params, opt_state[, ema]} from the newest checkpoint,
    re-placing host arrays onto each template leaf's sharding
    (stage-sharded PP leaves included). ``ema`` joins the template only
    when the trainer carries one (train.ema_decay > 0). Returns
    (params, opt_state, ema, start_step)."""
    import jax.numpy as jnp
    from jax.sharding import NamedSharding

    from mlops_tpu.train.checkpoint import CKPT_GLOB, load_checkpoint

    ckpt_dir = Path(ckpt_dir)
    if not (ckpt_dir / "latest.json").exists() and not any(
        ckpt_dir.glob(CKPT_GLOB)
    ):
        # Fresh start (the common case): skip building the host template —
        # it would device_get params + the 2x-sized adam state for nothing.
        return params, opt_state, ema, 0
    template = {"params": params, "opt_state": opt_state}
    if ema is not None:
        template["ema"] = ema
    loaded = load_checkpoint(ckpt_dir, jax.device_get(template))
    if loaded is None:
        # Checkpoints EXIST (the early return above covers the fresh-start
        # case) but none matched the current template — load_checkpoint
        # warned loudly with the per-file errors and the likely cause
        # (toggling train.ema_decay changes the pytree structure, ADVICE
        # r5) instead of silently discarding the run's progress.
        return params, opt_state, ema, 0
    host_state, step = loaded

    def put(t, h):
        # Mesh-laid-out leaves (params, adam mu/nu) go back to their
        # NamedSharding; scalar counters etc. stay UNCOMMITTED like
        # optax's own init leaves them — committing those to one device
        # would conflict with the mesh-committed arguments under jit.
        if isinstance(t.sharding, NamedSharding):
            return jax.device_put(h, t.sharding)
        return jnp.asarray(h)

    return (
        jax.tree.map(put, params, host_state["params"]),
        jax.tree.map(put, opt_state, host_state["opt_state"]),
        (
            jax.tree.map(put, ema, host_state["ema"])
            if ema is not None
            else None
        ),
        step,
    )


def _run_pp_training(
    config, run_dir, run_name, preprocessor, train_ds, valid_ds, register
) -> PipelineResult:
    import jax.numpy as jnp

    from mlops_tpu.parallel import make_nd_mesh
    from mlops_tpu.train.loop import evaluate
    from mlops_tpu.train.pipeline_parallel import (
        make_pp_train_step,
        merge_bert_params,
    )

    stages = config.model.pipeline_stages
    n_dev = len(jax.devices())
    if n_dev % stages:
        raise ValueError(
            f"model.pipeline_stages={stages} needs the device count to be a "
            f"multiple of it; have {n_dev} (run on a v5e pod slice or the "
            f"fake {stages}-device env)"
        )
    mesh = make_nd_mesh({"data": n_dev // stages, "stage": stages})
    dense_model = build_model(
        dataclasses.replace(config.model, pipeline_stages=0)
    )
    # Pretrain -> PP fine-tune: graft the masked-LM trunk into a fresh
    # dense tree (the shared helper; run_layout_training fail-fasts the
    # incompatible cases), then split into the stage layout.
    trainer = make_pp_train_step(
        config.model,
        config.train,
        mesh,
        seed=config.train.seed,
        init_variables=_load_init_variables(config, dense_model),
    )
    tcfg = config.train
    (
        eval_every,
        ckpt_every,
        ckpt_dir,
        params,
        opt_state,
        ema,
        start_step,
        journal_floor,
    ) = _layout_run_setup(tcfg, run_dir, trainer)

    def packaged_params(step):
        # Metrics must describe the params that will be PACKAGED — the
        # debiased EMA when enabled (fit keeps the same invariant).
        from mlops_tpu.train.loop import packaged_or_raw

        pp = packaged_or_raw(ema, params, tcfg.ema_decay, step)
        return merge_bert_params(jax.device_get(pp))

    history: list[dict] = []
    merged = None
    with _metric_writers(run_dir, tcfg) as emit:
        for step in range(start_step + 1, tcfg.steps + 1):
            idx = _batch_indices(train_ds.n, tcfg.batch_size, tcfg.seed, step)
            params, opt_state, ema, loss = trainer.step_fn(
                params,
                opt_state,
                ema,
                jnp.asarray(train_ds.cat_ids[idx]),
                jnp.asarray(train_ds.numeric[idx]),
                jnp.asarray(train_ds.labels[idx]),
            )
            if step % eval_every == 0 or step == tcfg.steps:
                merged = packaged_params(step)
                metrics = evaluate(dense_model, merged, valid_ds)
                record = {"step": step, "loss": round(float(loss), 6), **metrics}
                if step > journal_floor:  # no duplicate rows on resume
                    emit(record)
                history.append(record)
            _maybe_checkpoint(
                ckpt_dir, params, opt_state, ema, step, ckpt_every, tcfg.steps
            )

    def fresh_eval():
        nonlocal merged
        merged = packaged_params(start_step)
        return evaluate(dense_model, merged, valid_ds)

    final = _final_validation_metrics(history, tcfg.steps, fresh_eval)
    result = TrainResult(
        params=merged,
        metrics=final,
        history=history,
        steps=tcfg.steps,
        packaged_step=tcfg.steps,
    )
    calibration = _fit_calibration(valid_ds, merged, dense_model)
    bulk = _maybe_distill(
        config, config.model, dense_model, merged, train_ds, valid_ds
    )
    bundle_dir, model_uri = _package_and_register(
        config,
        run_dir,
        merged,
        preprocessor,
        train_ds,
        metrics=final,
        bundle_tags={
            "run_name": run_name,
            "experiment": config.registry.experiment_name,
            "trained_with": f"pipeline_parallel dp{mesh.shape['data']}xpp{stages}",
        },
        registry_tags={
            "run_name": run_name,
            **{k: f"{v:.6f}" for k, v in final.items()},
        },
        register=register,
        calibration=calibration,
        bulk=bulk,
    )
    return PipelineResult(
        bundle_dir=bundle_dir,
        model_uri=model_uri,
        train_result=result,
        run_dir=run_dir,
    )


def _run_tp_training(
    config, run_dir, run_name, preprocessor, train_ds, valid_ds, register
) -> PipelineResult:
    """DP×TP product training (`model.tensor_parallel=K`): the Megatron-
    laid-out sharded step over a ('data','model') mesh, with the same
    checkpoint/resume, EMA, and packaging tail as the PP path. The params
    are the DENSE family tree (TP is a layout), so the packaged bundle
    serves through the standard engine unchanged."""
    import jax.numpy as jnp

    from mlops_tpu.train.loop import evaluate, packaged_or_raw
    from mlops_tpu.train.tensor_parallel import make_tp_trainer

    dense_model_cfg = dataclasses.replace(config.model, tensor_parallel=0)
    trainer = make_tp_trainer(
        config,
        init_variables=_load_init_variables(
            config, build_model(dense_model_cfg)
        ),
    )
    tcfg = config.train
    (
        eval_every,
        ckpt_every,
        ckpt_dir,
        params,
        opt_state,
        ema,
        start_step,
        journal_floor,
    ) = _layout_run_setup(tcfg, run_dir, trainer)
    state = trainer.state.replace(
        params=params,
        opt_state=opt_state,
        ema=ema,
        step=jnp.asarray(start_step, jnp.int32),
    )
    # Deterministic dropout stream, pure in the step counter — a resumed
    # run sees exactly the per-step rngs the preempted run would have.
    drop_key = jax.random.fold_in(
        jax.random.PRNGKey(tcfg.seed), 0x7EA50000
    )

    def packaged_params(step_count):
        return jax.device_get(
            packaged_or_raw(state.ema, state.params, tcfg.ema_decay, step_count)
        )

    history: list[dict] = []
    packaged = None
    with _metric_writers(run_dir, tcfg) as emit:
        for step in range(start_step + 1, tcfg.steps + 1):
            idx = _batch_indices(train_ds.n, tcfg.batch_size, tcfg.seed, step)
            state, loss = trainer.step_fn(
                state,
                jnp.asarray(train_ds.cat_ids[idx]),
                jnp.asarray(train_ds.numeric[idx]),
                jnp.asarray(train_ds.labels[idx]),
                jax.random.fold_in(drop_key, step),
            )
            if step % eval_every == 0 or step == tcfg.steps:
                packaged = packaged_params(step)
                metrics = evaluate(trainer.model, packaged, valid_ds)
                record = {"step": step, "loss": round(float(loss), 6), **metrics}
                if step > journal_floor:  # no duplicate rows on resume
                    emit(record)
                history.append(record)
            _maybe_checkpoint(
                ckpt_dir, state.params, state.opt_state, state.ema,
                step, ckpt_every, tcfg.steps,
            )

    def fresh_eval():
        nonlocal packaged
        packaged = packaged_params(start_step)
        return evaluate(trainer.model, packaged, valid_ds)

    final = _final_validation_metrics(history, tcfg.steps, fresh_eval)
    result = TrainResult(
        params=packaged,
        metrics=final,
        history=history,
        steps=tcfg.steps,
        packaged_step=tcfg.steps,
    )
    calibration = _fit_calibration(valid_ds, packaged, trainer.model)
    bulk = _maybe_distill(
        config, dense_model_cfg, trainer.model, packaged, train_ds, valid_ds
    )
    mesh_shape = dict(
        zip(trainer.mesh.axis_names, trainer.mesh.devices.shape)
    )
    bundle_dir, model_uri = _package_and_register(
        config,
        run_dir,
        packaged,
        preprocessor,
        train_ds,
        metrics=final,
        bundle_tags={
            "run_name": run_name,
            "experiment": config.registry.experiment_name,
            "trained_with": (
                f"tensor_parallel dp{mesh_shape.get('data', 1)}x"
                f"tp{mesh_shape.get('model', 1)}"
            ),
        },
        registry_tags={
            "run_name": run_name,
            **{k: f"{v:.6f}" for k, v in final.items()},
        },
        register=register,
        calibration=calibration,
        bulk=bulk,
    )
    return PipelineResult(
        bundle_dir=bundle_dir,
        model_uri=model_uri,
        train_result=result,
        run_dir=run_dir,
    )


def _run_doc_training(
    config, run_dir, run_name, preprocessor, train_ds, valid_ds, register
) -> PipelineResult:
    import jax.numpy as jnp

    from mlops_tpu.parallel import make_nd_mesh
    from mlops_tpu.train.checkpoint import tree_bytes
    from mlops_tpu.train.long_context import make_doc_train_step, make_documents
    from mlops_tpu.train.metrics import binary_metrics
    from mlops_tpu.utils.io import atomic_write

    n_dev = len(jax.devices())
    mesh = None
    dp = 1
    if config.model.seq_parallel:
        from mlops_tpu.train.long_context import build_doc_model

        # The authoritative length (BertDocEncoder.doc_seq_len), not a
        # copy of its formula.
        seq = build_doc_model(
            dataclasses.replace(config.model, seq_parallel=False)
        ).doc_seq_len
        sp = max(
            (d for d in range(1, n_dev + 1) if n_dev % d == 0 and seq % d == 0),
            default=1,
        )
        if sp == 1:
            raise ValueError(
                f"seq_parallel needs the document length (2 + 46*doc_records "
                f"= {seq}) to share a factor with the device count {n_dev}; "
                f"pick doc_records accordingly (11 -> 508 works on 2/4-way)"
            )
        mesh = make_nd_mesh({"data": n_dev // sp, "seq": sp})
        dp = n_dev // sp
    trainer = make_doc_train_step(
        config.model, config.train, mesh=mesh, seed=config.train.seed
    )
    dcat, dnum, dlab = make_documents(train_ds, config.model.doc_records)
    vcat, vnum, vlab = make_documents(valid_ds, config.model.doc_records)
    tcfg = config.train
    batch = max(dp, tcfg.batch_size - tcfg.batch_size % dp)

    def valid_doc_logits(params) -> jnp.ndarray:
        # Pad the valid docs to a multiple of the 'data' axis (the ring's
        # shard_map requires an even batch split), then slice back.
        n = vcat.shape[0]
        pad = (-n) % dp
        return trainer.model.apply(
            {"params": params},
            jnp.asarray(np.pad(vcat, ((0, pad), (0, 0), (0, 0)))),
            jnp.asarray(np.pad(vnum, ((0, pad), (0, 0), (0, 0)))),
            train=False,
        )[:n]

    def doc_eval(params) -> dict[str, float]:
        metrics = binary_metrics(valid_doc_logits(params), jnp.asarray(vlab))
        return {f"validation_{k}_score": round(float(v), 6) for k, v in metrics.items()}

    (
        eval_every,
        ckpt_every,
        ckpt_dir,
        params,
        opt_state,
        ema,
        start_step,
        journal_floor,
    ) = _layout_run_setup(tcfg, run_dir, trainer)

    def packaged_doc_params(step):
        # Same invariant as fit/PP: evals and the shipped artifact use the
        # debiased EMA when enabled.
        from mlops_tpu.train.loop import packaged_or_raw

        return packaged_or_raw(ema, params, tcfg.ema_decay, step)

    history: list[dict] = []
    with _metric_writers(run_dir, tcfg) as emit:
        for step in range(start_step + 1, tcfg.steps + 1):
            idx = _batch_indices(dcat.shape[0], batch, tcfg.seed, step)
            params, opt_state, ema, loss = trainer.step_fn(
                params,
                opt_state,
                ema,
                jnp.asarray(dcat[idx]),
                jnp.asarray(dnum[idx]),
                jnp.asarray(dlab[idx]),
            )
            if step % eval_every == 0 or step == tcfg.steps:
                record = {
                    "step": step,
                    "loss": round(float(loss), 6),
                    **doc_eval(packaged_doc_params(step)),
                }
                if step > journal_floor:  # no duplicate rows on resume
                    emit(record)
                history.append(record)
            _maybe_checkpoint(
                ckpt_dir, params, opt_state, ema, step, ckpt_every, tcfg.steps
            )

    final_params = packaged_doc_params(max(start_step, tcfg.steps))
    params_host = jax.device_get(final_params)
    # Kept alongside the bundle for backward compatibility with round-4
    # tooling that read the raw tree.
    atomic_write(run_dir / "doc_params.msgpack", tree_bytes(params_host))
    final = _final_validation_metrics(
        history, tcfg.steps, lambda: doc_eval(final_params)
    )
    result = TrainResult(
        params=params_host,
        metrics=final,
        history=history,
        steps=tcfg.steps,
        packaged_step=tcfg.steps,
    )
    # Deployment path (VERDICT r4 #4): every trained model becomes a
    # servable, versioned artifact — doc models package as the 'doc'
    # bundle flavor (params + preprocessor + doc layout in the manifest)
    # and register a models:/ URI; scoring runs offline via
    # `predict-file` over record-history CSVs
    # (ref: `02-register-model.ipynb:431-440` invariant).
    from mlops_tpu.train.calibrate import calibration_record

    calibration = calibration_record(
        np.asarray(valid_doc_logits(final_params)), np.asarray(vlab)
    )
    mesh_desc = (
        f"long_context dp{dp}xsp{mesh.shape['seq']}" if mesh is not None
        else "long_context dense"
    )
    bundle_dir, model_uri = _package_and_register(
        config,
        run_dir,
        params_host,
        preprocessor,
        train_ds,
        metrics=final,
        bundle_tags={
            "run_name": run_name or run_dir.name,
            "experiment": config.registry.experiment_name,
            "trained_with": mesh_desc,
        },
        registry_tags={
            "run_name": run_name or run_dir.name,
            **{k: f"{v:.6f}" for k, v in final.items()},
        },
        register=register,
        calibration=calibration,
    )
    return PipelineResult(
        bundle_dir=bundle_dir,
        model_uri=model_uri,
        train_result=result,
        run_dir=run_dir,
    )


def run_tuning(
    config: Config,
    register: bool = True,
    run_name: str | None = None,
    mesh=None,
) -> tuple[PipelineResult, "Any"]:
    """HPO sweep -> package the winning trial (the reference's notebook-01
    select-best-child-run flow, `01-train-model.ipynb` cells 8-10 +
    notebook-02 packaging, in one process).
    """
    import json

    from mlops_tpu.train.hpo import run_architecture_hpo
    from mlops_tpu.utils.io import atomic_write

    if config.model.family in SKLEARN_FAMILIES:
        raise ValueError(
            "sklearn baseline families (gbm/rf) train via `train`; the "
            "vmapped/sharded `tune` sweep applies to the Flax families only"
        )
    if config.model.uses_layout_trainer:
        # Same loud guard as run_training: the sweep trains dense models,
        # so a layout knob left set would silently drop the requested
        # parallelism from every trial.
        raise ValueError(
            "`tune` sweeps dense single-record models; layout knobs "
            "(model.pipeline_stages / seq_parallel / doc_records>1) train "
            "via `train` -> run_layout_training"
        )

    run_name = run_name or time.strftime("%Y%m%d-%H%M%S") + "-tune"
    run_dir = Path(config.registry.run_root) / run_name
    run_dir.mkdir(parents=True, exist_ok=True)

    columns, labels = load_training_data(config)
    preprocessor = Preprocessor.fit(columns)
    ds = preprocessor.encode(columns, labels)
    train_ds, valid_ds = split_dataset(ds, config.data.valid_fraction)

    # Architecture groups (hpo.architectures) loop outside; the continuous
    # space vmaps inside each group. win_model is the structural winner's
    # ModelConfig — calibration and the packaged bundle must describe THAT
    # architecture, not the base config's.
    win_model, hpo_result = run_architecture_hpo(
        config.model,
        config.train,
        config.hpo,
        train_ds,
        valid_ds,
        mesh=mesh,
        # Architecture groups persist as they finish; a retried job with a
        # stable registry.run_name recomputes only unfinished groups.
        resume_dir=run_dir,
    )
    # Full atomic rewrite, NOT append: the record set always covers every
    # trial (restored groups included), so appending on a retried run
    # would duplicate all rows.
    atomic_write(
        run_dir / "trials.jsonl",
        "".join(
            json.dumps({"trial": i, **trial}, default=float) + "\n"
            for i, trial in enumerate(hpo_result.trials)
        ).encode(),
    )
    (run_dir / "best.json").write_text(
        json.dumps(
            {
                "best_index": hpo_result.best_index,
                "hyperparams": hpo_result.best_hyperparams,
                "metrics": hpo_result.best_metrics,
            },
            indent=2,
        )
    )

    win_module = build_model(win_model)
    calibration = _fit_calibration(valid_ds, hpo_result.best_params, win_module)
    bulk = _maybe_distill(
        config, win_model, win_module, hpo_result.best_params, train_ds, valid_ds
    )
    bundle_dir, model_uri = _package_and_register(
        config,
        run_dir,
        hpo_result.best_params,
        preprocessor,
        train_ds,
        metrics=hpo_result.best_metrics,
        bundle_tags={
            "run_name": run_name,
            "best_trial": str(hpo_result.best_index),
            # Structural winners (family/hidden_dims/...) surface as strings.
            **{
                k: (f"{v:.6g}" if isinstance(v, float) else str(v))
                for k, v in hpo_result.best_hyperparams.items()
            },
        },
        registry_tags={
            "run_name": run_name,
            "best_trial": str(hpo_result.best_index),
        },
        register=register,
        calibration=calibration,
        model_config=win_model,
        bulk=bulk,
    )
    result = PipelineResult(
        bundle_dir=bundle_dir,
        model_uri=model_uri,
        train_result=TrainResult(
            params=hpo_result.best_params,
            metrics=hpo_result.best_metrics,
            history=[],
            steps=config.hpo.steps,
        ),
        run_dir=run_dir,
    )
    return result, hpo_result
