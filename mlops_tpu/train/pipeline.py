"""End-to-end training pipeline: data -> train -> monitor -> bundle -> registry.

This is the TPU-native restatement of the reference's two-notebook job
(`train_register_model_job`: notebook 01 trains + selects, notebook 02 fits
detectors + packages + registers — SURVEY.md SS3.2). One process, one data
read, typed artifacts instead of ``dbutils.jobs.taskValues`` handoffs.
"""

from __future__ import annotations

import dataclasses
import time
from pathlib import Path
from typing import Any

import jax
import numpy as np

from mlops_tpu.bundle import ModelRegistry, save_bundle
from mlops_tpu.config import Config
from mlops_tpu.data import (
    EncodedDataset,
    Preprocessor,
    generate_synthetic,
    load_table_columns,
)
from mlops_tpu.models import build_model
from mlops_tpu.models.gbm import SKLEARN_FAMILIES, SklearnBaseline
from mlops_tpu.monitor import fit_monitor
from mlops_tpu.train.loop import TrainResult, fit


@dataclasses.dataclass
class PipelineResult:
    bundle_dir: Path
    model_uri: str | None
    train_result: TrainResult
    run_dir: Path


def new_run_dir(config: Config, run_name: str | None = None) -> Path:
    """The one place the run-directory convention lives:
    ``<registry.run_root>/<timestamp-or-name>/`` (used by train, tune and
    pretrain alike)."""
    run_dir = Path(config.registry.run_root) / (
        run_name or time.strftime("%Y%m%d-%H%M%S")
    )
    run_dir.mkdir(parents=True, exist_ok=True)
    return run_dir


def load_training_data(config: Config) -> tuple[dict[str, list], np.ndarray]:
    """CSV/Parquet if configured, else the synthetic generator (data layer
    contract; format dispatch on extension)."""
    if config.data.train_path:
        columns, labels = load_table_columns(
            config.data.train_path, require_target=True
        )
        return columns, labels
    return generate_synthetic(config.data.rows, seed=config.data.seed)


def split_dataset(
    ds: EncodedDataset, valid_fraction: float, seed: int = 2024
) -> tuple[EncodedDataset, EncodedDataset]:
    """Shuffled split (parity: ``train_test_split(random_state=2024)``,
    `01-train-model.ipynb` cell 7)."""
    rng = np.random.default_rng(seed)
    perm = rng.permutation(ds.n)
    n_valid = int(ds.n * valid_fraction)
    return ds.slice(perm[n_valid:]), ds.slice(perm[:n_valid])


def _fit_calibration(
    valid_ds: EncodedDataset, params: Any, model=None
) -> dict[str, float]:
    """Temperature-scale on the held-out split (train/calibrate.py): the
    bundle serves ``sigmoid(logit / T)`` instead of the reference's raw
    ``predict_proba`` (`02-register-model.ipynb:330-353` has no
    calibration step). ``model=None`` means the sklearn flavor, where
    ``params`` is the estimator and logits come from its probabilities."""
    import jax.numpy as jnp

    from mlops_tpu.train.calibrate import calibration_record, probs_to_logits

    if model is None:
        logits = probs_to_logits(
            params.predict_proba(valid_ds.cat_ids, valid_ds.numeric)
        )
    else:
        logits = np.asarray(
            model.apply(
                {"params": params},
                jnp.asarray(valid_ds.cat_ids),
                jnp.asarray(valid_ds.numeric),
                train=False,
            )
        )
    return calibration_record(logits, valid_ds.labels)


def _package_and_register(
    config: Config,
    run_dir: Path,
    params: Any,
    preprocessor: Preprocessor,
    train_ds: EncodedDataset,
    metrics: dict[str, float],
    bundle_tags: dict[str, str],
    registry_tags: dict[str, str],
    register: bool,
    calibration: dict[str, float] | None = None,
    model_config=None,
    bulk=None,
) -> tuple[Path, str | None]:
    """Shared packaging tail: fit monitors, write the bundle, register it
    (notebook 02's role — `02-register-model.ipynb` cells 6-15).

    Multi-host cohorts (JobSet over DCN): every process computes
    identically, but only the coordinator writes the bundle and registry
    entry — N hosts registering N duplicate versions (and racing the
    index write) is the multi-host failure mode this guards.
    """
    from mlops_tpu.parallel.distributed import is_coordinator

    bundle_dir = run_dir / "bundle"
    if not is_coordinator():
        return bundle_dir, None
    monitor = fit_monitor(train_ds, config.monitor, seed=config.data.seed)
    save_bundle(
        bundle_dir,
        model_config if model_config is not None else config.model,
        params,
        preprocessor,
        monitor,
        metrics=metrics,
        tags=bundle_tags,
        calibration=calibration,
        bulk=bulk,
    )
    model_uri = None
    if register:
        registry = ModelRegistry(config.registry.root)
        model_uri = registry.register(
            config.registry.model_name, bundle_dir, tags=registry_tags
        )
    return bundle_dir, model_uri


_DISTILL_FAMILIES = ("ft_transformer", "moe", "bert")


def _maybe_distill(config, model_config, model, params, train_ds, valid_ds):
    """Package-time distillation gate: models whose per-row FLOPs lose CPU
    bulk scoring to the sklearn floor — ensembles (K× a small MLP) and
    the transformer families — get a bulk student (train/distill.py)
    unless train.distill_bulk turned it off. ``model`` is None on the
    sklearn path, which never distills (it IS the floor)."""
    expensive = (
        model_config.ensemble_size > 1
        or model_config.family in _DISTILL_FAMILIES
    )
    if model is None or not expensive or not config.train.distill_bulk:
        return None
    from mlops_tpu.train.distill import distill_for_bulk

    return distill_for_bulk(
        model,
        {"params": params},
        model_config,
        train_ds,
        valid_ds,
        seed=config.train.seed,
    )


def run_training(
    config: Config,
    register: bool = True,
    run_name: str | None = None,
) -> PipelineResult:
    """Train one model per config and package it as a bundle.

    Steps (each replacing a reference stage):
      1. read + encode data once (vs per-trial Spark re-reads)
      2. ``fit`` the model (notebook 01's role)
      3. fit drift + outlier monitors on the training split (notebook 02
         cell 6)
      4. write the bundle (notebook 02's pyfunc ``log_model``)
      5. register it (notebook 02's ``register_model``), returning a
         ``models:/<name>/<version>`` URI
    """
    run_name = run_name or time.strftime("%Y%m%d-%H%M%S")
    run_dir = new_run_dir(config, run_name)

    columns, labels = load_training_data(config)
    preprocessor = Preprocessor.fit(columns)
    ds = preprocessor.encode(columns, labels)
    train_ds, valid_ds = split_dataset(ds, config.data.valid_fraction)

    calibration_model = None
    if config.model.family in SKLEARN_FAMILIES:
        # BASELINE config 1: the CPU tree-ensemble comparison floor, trained
        # and packaged through the exact same pipeline tail as the TPU models.
        baseline = SklearnBaseline.train(config.model, config.train, train_ds)
        result = TrainResult(
            params=baseline,
            metrics=baseline.evaluate(valid_ds),
            history=[],
            steps=config.model.n_estimators,
        )
    else:
        model = build_model(config.model)
        init_variables = None
        if config.train.init_params and config.model.ensemble_size > 1:
            raise ValueError(
                "train.init_params grafts a pretrained trunk by parameter "
                "name, which cannot target the vmapped member axis of an "
                "ensemble — use ensemble_size=1 for fine-tuning runs"
            )
        if config.train.init_params:
            # Fine-tune from masked-feature pretraining (`pretrain` CLI):
            # trunk comes from the MLM run, heads stay freshly initialized.
            from mlops_tpu.models import init_params as fresh_init
            from mlops_tpu.train.pretrain import load_pretrained_variables

            init_variables = load_pretrained_variables(
                config.train.init_params,
                config.model,
                fresh_init(model, jax.random.PRNGKey(config.train.seed)),
            )
        result = fit(
            model,
            train_ds,
            valid_ds,
            config.train,
            init_variables=init_variables,
            metrics_path=run_dir / "metrics.jsonl",
            checkpoint_dir=run_dir / "checkpoints",
        )
        calibration_model = model

    calibration = _fit_calibration(valid_ds, result.params, calibration_model)
    bulk = _maybe_distill(
        config, config.model, calibration_model, result.params, train_ds, valid_ds
    )
    bundle_dir, model_uri = _package_and_register(
        config,
        run_dir,
        result.params,
        preprocessor,
        train_ds,
        metrics=result.metrics,
        bundle_tags={
            "run_name": run_name,
            "experiment": config.registry.experiment_name,
        },
        registry_tags={
            "run_name": run_name,
            **{k: f"{v:.6f}" for k, v in result.metrics.items()},
        },
        register=register,
        calibration=calibration,
        bulk=bulk,
    )
    return PipelineResult(
        bundle_dir=bundle_dir,
        model_uri=model_uri,
        train_result=result,
        run_dir=run_dir,
    )


def run_tuning(
    config: Config,
    register: bool = True,
    run_name: str | None = None,
    mesh=None,
) -> tuple[PipelineResult, "Any"]:
    """HPO sweep -> package the winning trial (the reference's notebook-01
    select-best-child-run flow, `01-train-model.ipynb` cells 8-10 +
    notebook-02 packaging, in one process).
    """
    import json

    from mlops_tpu.train.hpo import run_architecture_hpo
    from mlops_tpu.utils.jsonl import JsonlWriter

    if config.model.family in SKLEARN_FAMILIES:
        raise ValueError(
            "sklearn baseline families (gbm/rf) train via `train`; the "
            "vmapped/sharded `tune` sweep applies to the Flax families only"
        )

    run_name = run_name or time.strftime("%Y%m%d-%H%M%S") + "-tune"
    run_dir = Path(config.registry.run_root) / run_name
    run_dir.mkdir(parents=True, exist_ok=True)

    columns, labels = load_training_data(config)
    preprocessor = Preprocessor.fit(columns)
    ds = preprocessor.encode(columns, labels)
    train_ds, valid_ds = split_dataset(ds, config.data.valid_fraction)

    # Architecture groups (hpo.architectures) loop outside; the continuous
    # space vmaps inside each group. win_model is the structural winner's
    # ModelConfig — calibration and the packaged bundle must describe THAT
    # architecture, not the base config's.
    win_model, hpo_result = run_architecture_hpo(
        config.model, config.train, config.hpo, train_ds, valid_ds, mesh=mesh
    )
    with JsonlWriter(run_dir / "trials.jsonl") as writer:
        for i, trial in enumerate(hpo_result.trials):
            writer.write({"trial": i, **trial})
    (run_dir / "best.json").write_text(
        json.dumps(
            {
                "best_index": hpo_result.best_index,
                "hyperparams": hpo_result.best_hyperparams,
                "metrics": hpo_result.best_metrics,
            },
            indent=2,
        )
    )

    win_module = build_model(win_model)
    calibration = _fit_calibration(valid_ds, hpo_result.best_params, win_module)
    bulk = _maybe_distill(
        config, win_model, win_module, hpo_result.best_params, train_ds, valid_ds
    )
    bundle_dir, model_uri = _package_and_register(
        config,
        run_dir,
        hpo_result.best_params,
        preprocessor,
        train_ds,
        metrics=hpo_result.best_metrics,
        bundle_tags={
            "run_name": run_name,
            "best_trial": str(hpo_result.best_index),
            # Structural winners (family/hidden_dims/...) surface as strings.
            **{
                k: (f"{v:.6g}" if isinstance(v, float) else str(v))
                for k, v in hpo_result.best_hyperparams.items()
            },
        },
        registry_tags={
            "run_name": run_name,
            "best_trial": str(hpo_result.best_index),
        },
        register=register,
        calibration=calibration,
        model_config=win_model,
        bulk=bulk,
    )
    result = PipelineResult(
        bundle_dir=bundle_dir,
        model_uri=model_uri,
        train_result=TrainResult(
            params=hpo_result.best_params,
            metrics=hpo_result.best_metrics,
            history=[],
            steps=config.hpo.steps,
        ),
        run_dir=run_dir,
    )
    return result, hpo_result
