"""Long-context training: document BERT over a ('data','seq') mesh.

SURVEY §5.7's long-context obligation, made load-bearing: the ring-attention
library (`parallel/ring_attention.py`) stops being demo-grade here — a real
training configuration (``model.family=bert model.doc_records=R
model.seq_parallel=true``) reads R consecutive records as one ~500-token
document and trains `models.bert.BertDocEncoder` with its attention running
as the ppermute ring over the mesh's 'seq' axis while the batch shards over
'data' (combined DP × SP). The same builder with ``seq_parallel=false``
produces the dense single-chip model, which is also the tests' equivalence
reference: ring and dense training steps must match to numerical tolerance.

The reference has no sequence workloads (23 fixed tabular features), so
there is no reference analogue to cite — this is a capability the TPU
rebuild adds (BASELINE config 5's stretch direction).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from mlops_tpu.config import ModelConfig, TrainConfig
from mlops_tpu.data.encode import EncodedDataset
from mlops_tpu.models.bert import BertDocEncoder
from mlops_tpu.parallel.ring_attention import make_ring_attention
from mlops_tpu.schema.features import SCHEMA
from mlops_tpu.train.loop import sigmoid_bce, update_ema


def group_documents(
    cat_ids: np.ndarray, numeric: np.ndarray, doc_records: int
) -> tuple[np.ndarray, np.ndarray]:
    """Group consecutive encoded rows into record histories:
    ``[N,C]`` -> ``[D,R,C]``. Rows past the last full document drop.
    The label-free half of ``make_documents`` — the inference path
    (``predict-file`` on a doc bundle) scores unlabeled histories."""
    docs = cat_ids.shape[0] // doc_records
    take = docs * doc_records
    cat = cat_ids[:take].reshape(docs, doc_records, -1)
    num = numeric[:take].reshape(docs, doc_records, -1)
    return cat, num


def make_documents(
    ds: EncodedDataset, doc_records: int
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Group consecutive rows into histories: ``[N,C]`` -> ``[D,R,C]``.

    The label of a document is its LAST record's label (predict the next
    default from the history). Rows past the last full document drop.
    """
    if ds.labels is None:
        raise ValueError("document training needs labels")
    cat, num = group_documents(ds.cat_ids, ds.numeric, doc_records)
    docs = cat.shape[0]
    labels = ds.labels[: docs * doc_records].reshape(docs, doc_records)[:, -1]
    return cat, num, labels.astype(np.float32)


def build_doc_model(
    config: ModelConfig, mesh: Mesh | None = None
) -> BertDocEncoder:
    """BertDocEncoder per config; ``seq_parallel=true`` + a mesh with a
    'seq' axis injects the ring; otherwise attention is the dense kernel
    dispatcher (the single-chip / equivalence-reference path)."""
    attend_fn: Callable | None = None
    if config.seq_parallel:
        if mesh is None or "seq" not in mesh.axis_names:
            raise ValueError(
                "model.seq_parallel=true needs a mesh with a 'seq' axis "
                "(parallel.make_nd_mesh({'data': d, 'seq': s}))"
            )
        batch_axis = "data" if "data" in mesh.axis_names else None
        attend_fn = make_ring_attention(mesh, "seq", batch_axis=batch_axis)
    dtype = {"bf16": jnp.bfloat16, "f32": jnp.float32}[config.precision]
    return BertDocEncoder(
        cards=SCHEMA.cards,
        num_numeric=SCHEMA.num_numeric,
        doc_records=config.doc_records,
        hidden=config.token_dim,
        depth=config.depth,
        heads=config.heads,
        dropout=0.0,  # ring attention never materializes scores (see
        # models/layers.py); embedding/FFN dropout would be fine but is
        # kept off so dense and ring paths stay bit-comparable
        dtype=dtype,
        attend_fn=attend_fn,
    )


@dataclasses.dataclass
class DocTrainStep:
    model: BertDocEncoder
    step_fn: Callable  # (params, opt_state, ema, cat, num, lab) ->
    # (params, opt_state, ema, loss); ema is None (empty pytree) when
    # train.ema_decay == 0 and threads through untouched
    params: Any
    opt_state: Any
    ema: Any = None  # zero-init Polyak accumulator when ema_decay > 0


def make_doc_train_step(
    model_config: ModelConfig,
    train_config: TrainConfig,
    mesh: Mesh | None = None,
    seed: int = 0,
) -> DocTrainStep:
    """One jitted DP×SP train step over documents.

    With a mesh: batch shards over 'data', the R record axis (= sequence)
    over 'seq'; params replicate; XLA psums gradients over both axes while
    the attention inner loop rides the explicit ppermute ring. Without a
    mesh: the same step, dense, single device.
    """
    model = build_doc_model(model_config, mesh)
    r = model_config.doc_records
    dummy_cat = jnp.zeros((2, r, SCHEMA.num_categorical), jnp.int32)
    dummy_num = jnp.zeros((2, r, SCHEMA.num_numeric), jnp.float32)
    params = model.init({"params": jax.random.PRNGKey(seed)},
                        dummy_cat, dummy_num, train=False)["params"]
    optimizer = optax.adamw(
        train_config.learning_rate, weight_decay=train_config.weight_decay
    )
    opt_state = optimizer.init(params)
    decay = train_config.ema_decay
    ema0 = jax.tree_util.tree_map(jnp.zeros_like, params) if decay else None

    def step(params, opt_state, ema, cat, num, lab):
        def loss_of(p):
            logits = model.apply({"params": p}, cat, num, train=True)
            return sigmoid_bce(logits, lab, train_config.pos_weight)

        loss, grads = jax.value_and_grad(loss_of)(params)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        if decay:  # static at trace time; ema=None threads through otherwise
            ema = update_ema(ema, params, decay)
        return params, opt_state, ema, loss

    # No donation on either path: DocTrainStep exposes the initial
    # params/opt_state, and a donated first step would delete those
    # buffers on TPU (the fit() donation bug class); activations dominate
    # this trainer's memory anyway.
    if mesh is None:
        step_fn = jax.jit(step)  # tpulint: disable=TPU105
    else:
        batch = "data" if "data" in mesh.axis_names else None
        # Inputs shard over 'data' only: the R record axis (11 for a
        # 508-token doc) rarely divides the seq axis — XLA reshards the
        # token activations onto the ring's ('seq'-sharded) layout at the
        # shard_map boundary, after tokenize+embed.
        doc_in = NamedSharding(mesh, P(batch, None, None))
        lab_in = NamedSharding(mesh, P(batch))
        rep = NamedSharding(mesh, P())
        step_fn = jax.jit(  # tpulint: disable=TPU105
            step,
            in_shardings=(rep, rep, rep, doc_in, doc_in, lab_in),
            out_shardings=(rep, rep, rep, rep),
        )
    return DocTrainStep(
        model=model, step_fn=step_fn, params=params, opt_state=opt_state,
        ema=ema0,
    )
