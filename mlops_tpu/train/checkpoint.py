"""Checkpoint / resume for training state and param pytrees.

The reference has no in-run checkpointing (SURVEY.md SS5.4) — runs restart
from zero. Here: periodic serialization of ``{params, opt_state, step}`` so
training resumes after preemption (first-class on preemptible TPU pools),
plus the pytree (de)serialization primitive the bundle format reuses.

Format: flax msgpack bytes (``flax.serialization.to_bytes``) + a tiny JSON
sidecar with the step counter — restore requires a structurally matching
target pytree, which the trainer reconstructs from config.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Any

from flax import serialization

from mlops_tpu.utils.io import atomic_write

# The checkpoint filename pattern is owned HERE (save_checkpoint writes
# it, load_checkpoint and the existence probes glob it) — callers import
# this instead of re-spelling the literal.
CKPT_GLOB = "ckpt_*.msgpack"


def tree_bytes(tree: Any) -> bytes:
    return serialization.to_bytes(tree)


def restore_tree(target: Any, data: bytes) -> Any:
    """Restore msgpack bytes into the structure of ``target``."""
    return serialization.from_bytes(target, data)


def save_checkpoint(directory: str | Path, state: Any, step: int) -> Path:
    directory = Path(directory)
    directory.mkdir(parents=True, exist_ok=True)
    path = directory / f"ckpt_{step:08d}.msgpack"
    atomic_write(path, tree_bytes(state))
    atomic_write(
        directory / "latest.json",
        json.dumps({"step": step, "file": path.name}).encode(),
    )
    return path


BEST_PARAMS_NAME = "best_params.msgpack"
BEST_RECORD_NAME = "best_record.json"


def save_best(directory: Path, params, record: dict) -> None:
    """Persist the best-eval-window snapshot (train.keep_best) so a
    crash-resume continues the best-so-far comparison."""
    directory.mkdir(parents=True, exist_ok=True)
    atomic_write(directory / BEST_PARAMS_NAME, tree_bytes(params))
    atomic_write(
        directory / BEST_RECORD_NAME, json.dumps(record).encode()
    )


def load_best(directory: Path, template):
    """Restore the persisted best snapshot; None when absent or unreadable
    (e.g. the params pytree shape changed between runs)."""
    try:
        params = restore_tree(
            template, (directory / BEST_PARAMS_NAME).read_bytes()
        )
        record = json.loads((directory / BEST_RECORD_NAME).read_text())
        float(record["validation_roc_auc_score"])  # shape sanity
        return params, record
    except (
        OSError,
        ValueError,
        KeyError,
        TypeError,
        AttributeError,
        json.JSONDecodeError,
    ):
        return None


def load_checkpoint(directory: str | Path, target: Any) -> tuple[Any, int] | None:
    """Load the newest readable checkpoint into ``target``'s structure.

    Prefers the ``latest.json`` pointer; falls back to the newest
    ``ckpt_*.msgpack`` on disk if the pointer or its target is corrupt, and
    returns None (fresh start) when nothing is recoverable.
    """
    directory = Path(directory)
    candidates: list[tuple[Path, int | None]] = []
    latest = directory / "latest.json"
    if latest.exists():
        try:
            meta = json.loads(latest.read_text())
            candidates.append((directory / meta["file"], int(meta["step"])))
        except (json.JSONDecodeError, KeyError, TypeError, ValueError, OSError):
            pass
    pointed = {path for path, _ in candidates}
    candidates.extend(
        (p, None)
        for p in sorted(directory.glob(CKPT_GLOB), reverse=True)
        if p not in pointed  # don't retry (and double-count) the pointer's file
    )
    failures = []
    for path, known_step in candidates:
        try:
            restored = restore_tree(target, path.read_bytes())
            step = (
                known_step
                if known_step is not None
                else int(path.stem.split("_")[1])
            )
        except (
            OSError,
            ValueError,
            KeyError,
            IndexError,
            AttributeError,  # flax pytree-structure mismatch (e.g. the
            TypeError,  # TrainState gained/lost the ema field)
        ) as err:
            failures.append((path, err))
            continue
        return restored, step
    if failures:
        # Checkpoints exist but NONE restored — most likely a state-shape
        # mismatch (e.g. toggling train.ema_decay changes the TrainState
        # pytree). Restarting silently from step 0 would throw away the
        # run's progress without a trace, so say it loudly.
        import warnings

        path, err = failures[0]
        warnings.warn(
            f"{len(failures)} checkpoint(s) in {directory} failed to "
            f"restore (first: {path.name}: {err}); training restarts from "
            "step 0 — if the TrainState shape changed (most commonly "
            "train.ema_decay toggled between runs, which adds/removes the "
            "ema field), resume with the original settings or clear the "
            "checkpoint dir",
            stacklevel=2,
        )
    return None
