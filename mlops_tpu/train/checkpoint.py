"""Checkpoint / resume for training state and param pytrees.

The reference has no in-run checkpointing (SURVEY.md SS5.4) — runs restart
from zero. Here: periodic serialization of ``{params, opt_state, step}`` so
training resumes after preemption (first-class on preemptible TPU pools),
plus the pytree (de)serialization primitive the bundle format reuses.

Format: flax msgpack bytes (``flax.serialization.to_bytes``) + a tiny JSON
sidecar with the step counter — restore requires a structurally matching
target pytree, which the trainer reconstructs from config.
"""

from __future__ import annotations

import json
import os
import tempfile
from pathlib import Path
from typing import Any

from flax import serialization


def tree_bytes(tree: Any) -> bytes:
    return serialization.to_bytes(tree)


def restore_tree(target: Any, data: bytes) -> Any:
    """Restore msgpack bytes into the structure of ``target``."""
    return serialization.from_bytes(target, data)


def _atomic_write(path: Path, data: bytes) -> None:
    """Write via temp file + rename so a preemption never leaves a torn file."""
    fd, tmp = tempfile.mkstemp(dir=path.parent, prefix=f".{path.name}.")
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(data)
        os.replace(tmp, path)
    except BaseException:
        os.unlink(tmp)
        raise


def save_checkpoint(directory: str | Path, state: Any, step: int) -> Path:
    directory = Path(directory)
    directory.mkdir(parents=True, exist_ok=True)
    path = directory / f"ckpt_{step:08d}.msgpack"
    _atomic_write(path, tree_bytes(state))
    _atomic_write(
        directory / "latest.json",
        json.dumps({"step": step, "file": path.name}).encode(),
    )
    return path


def load_checkpoint(directory: str | Path, target: Any) -> tuple[Any, int] | None:
    """Load the newest readable checkpoint into ``target``'s structure.

    Prefers the ``latest.json`` pointer; falls back to the newest
    ``ckpt_*.msgpack`` on disk if the pointer or its target is corrupt, and
    returns None (fresh start) when nothing is recoverable.
    """
    directory = Path(directory)
    candidates: list[Path] = []
    latest = directory / "latest.json"
    if latest.exists():
        try:
            meta = json.loads(latest.read_text())
            candidates.append(directory / meta["file"])
        except (json.JSONDecodeError, KeyError, OSError):
            pass
    candidates.extend(sorted(directory.glob("ckpt_*.msgpack"), reverse=True))
    for path in candidates:
        try:
            restored = restore_tree(target, path.read_bytes())
        except (OSError, ValueError, KeyError):
            continue
        step = int(path.stem.split("_")[1])
        return restored, step
    return None
