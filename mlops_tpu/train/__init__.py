"""Training: optax loop under jit, eval metrics, checkpoint/resume, HPO.

Replaces the reference's Databricks job (`train_register_model.yml:11-39`)
running hyperopt over sklearn fits (`01-train-model.ipynb:252-360`). The
reference re-reads the dataset from Spark and re-fits the pipeline three
times per trial (SURVEY.md SS7 bugs); here data is encoded once, lives on
device, and the step loop is a single compiled ``lax.scan``.
"""

from mlops_tpu.train.loop import TrainResult, evaluate, fit
from mlops_tpu.train.metrics import binary_metrics, roc_auc
from mlops_tpu.train.checkpoint import (
    load_checkpoint,
    restore_tree,
    save_checkpoint,
    tree_bytes,
)

__all__ = [
    "TrainResult",
    "binary_metrics",
    "evaluate",
    "fit",
    "load_checkpoint",
    "restore_tree",
    "roc_auc",
    "save_checkpoint",
    "tree_bytes",
]
