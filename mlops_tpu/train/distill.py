"""Ensemble -> single-student distillation for the bulk scoring path.

The flagship serving model is a K-member vmapped deep ensemble
(`models/ensemble.py`) — the MXU answer to the reference's RandomForest
(`01-train-model.ipynb:195-227`). On the TPU that costs nearly nothing; on a
CPU backend the K× FLOPs make BULK scoring lose to the reference's sklearn
GBM floor (BASELINE.md config 1: ~99k rows/s). Rather than silently serving
one member (whose predictions differ from the ensemble's), the packaging
step distills the ensemble's LOGITS into one small MLP and records the
fidelity it achieved; `parallel/bulk.py` routes bulk sweeps through the
student on CPU backends (serving always uses the exact ensemble).

Distillation here is plain logit matching (Hinton et al.'s soft-target
recipe degenerates to this for binary outputs served as probabilities): the
student minimizes MSE against teacher logits, so the fitted calibration
temperature (manifest ``calibration``) applies to student outputs unchanged.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
import optax

from mlops_tpu.config import LifecycleConfig, ModelConfig
from mlops_tpu.data.encode import EncodedDataset
from mlops_tpu.models import build_model, init_params
from mlops_tpu.ops.quant import (
    QUANT_EMBED_DIM,
    QUANT_HIDDEN,
    init_quant_master,
    master_student_logits,
    quant_student_logits,
    quantize_student,
)
from mlops_tpu.train.calibrate import fit_temperature
from mlops_tpu.train.metrics import binary_metrics


@dataclasses.dataclass
class DistillResult:
    student_config: ModelConfig
    student_params: Any
    fidelity: dict[str, float]  # prob-space agreement + AUC delta on valid


@dataclasses.dataclass
class QuantDistillResult:
    """The quantized serving tier, fully graded at packaging time.

    - ``qparams``: the int8/bf16 tree `ops/quant.py` serves from.
    - ``fidelity``: POST-quantization numbers on the held-out split
      (prob deltas vs teacher, AUC delta, calibrated ECE) — measured on
      the exact tree that will serve, not the f32 master.
    - ``temperature``: post-hoc refit (`train/calibrate.py`) on the QUANT
      logits; quantization shifts the logit scale, so the teacher's
      temperature does not transfer.
    - ``gates``: the stamped promotion decision
      (`lifecycle/promote.py quant_tier_gates`) plus the thresholds it
      was graded against — the record `serve/engine.py` trusts.
    """

    qparams: Any
    fidelity: dict[str, float]
    temperature: float
    gates: dict[str, Any]


def teacher_logits(model, variables, ds: EncodedDataset, chunk: int = 16_384):
    """Teacher forward over the whole dataset, chunked at a fixed shape so
    one executable serves every chunk (tail pads)."""

    @jax.jit
    def fwd(cat, num):
        return model.apply(variables, cat, num, train=False)

    out = np.empty(ds.n, np.float32)
    for start in range(0, ds.n, chunk):
        stop = min(start + chunk, ds.n)
        cat, num = ds.cat_ids[start:stop], ds.numeric[start:stop]
        pad = chunk - (stop - start)
        if pad:
            cat = np.pad(cat, ((0, pad), (0, 0)))
            num = np.pad(num, ((0, pad), (0, 0)))
        out[start:stop] = np.asarray(fwd(cat, num))[: stop - start]
    return out


def distill_for_bulk(
    teacher_model,
    teacher_variables,
    model_config: ModelConfig,
    train_ds: EncodedDataset,
    valid_ds: EncodedDataset,
    hidden_dims: tuple[int, ...] = (64, 64),
    steps: int = 800,
    batch_size: int = 2048,
    learning_rate: float = 3e-3,
    seed: int = 0,
) -> DistillResult:
    """Fit a small-MLP student to the teacher's logits.

    The student keeps the teacher's embed_dim (categorical structure) but
    shrinks the trunk to ``hidden_dims`` — at the credit-default widths
    that is ~80× fewer FLOPs/row than the 8-member flagship, which is what
    buys back the CPU bulk throughput. Returns params + a fidelity record
    (mean/max |Δprob| vs teacher and AUC delta on the validation split)
    that the bundle manifest carries so the routing decision is auditable.
    """
    student_config = dataclasses.replace(
        model_config,
        family="mlp",
        ensemble_size=1,
        hidden_dims=tuple(hidden_dims),
        dropout=0.0,
    )
    student = build_model(student_config)
    t_train = teacher_logits(teacher_model, teacher_variables, train_ds)

    params = init_params(student, jax.random.PRNGKey(seed))["params"]
    optimizer = optax.adam(learning_rate)
    opt_state = optimizer.init(params)

    cat = jnp.asarray(train_ds.cat_ids)
    num = jnp.asarray(train_ds.numeric)
    target = jnp.asarray(t_train)
    n = train_ds.n

    # lax.scan keeps the whole fit one compiled program (zero Python in the
    # loop — the same shape as the HPO inner loop, `train/hpo.py`).
    def scan_step(carry, i):
        params, opt_state = carry
        idx = jax.random.randint(
            jax.random.fold_in(jax.random.PRNGKey(seed + 1), i),
            (batch_size,),
            0,
            n,
        )

        def loss_of(p):
            pred = student.apply({"params": p}, cat[idx], num[idx], train=False)
            return jnp.mean(jnp.square(pred - target[idx]))

        loss, grads = jax.value_and_grad(loss_of)(params)
        updates, opt_state = optimizer.update(grads, opt_state)
        return (optax.apply_updates(params, updates), opt_state), loss

    @jax.jit
    def fit(params, opt_state):
        return jax.lax.scan(scan_step, (params, opt_state), jnp.arange(steps))

    (params, _), _ = fit(params, opt_state)

    # Fidelity on the held-out split: the number that says whether routing
    # bulk sweeps through the student is safe.
    t_valid = teacher_logits(teacher_model, teacher_variables, valid_ds)
    s_valid = teacher_logits(student, {"params": params}, valid_ds)
    p_t = 1.0 / (1.0 + np.exp(-t_valid))
    p_s = 1.0 / (1.0 + np.exp(-s_valid))
    fidelity = {
        "mean_abs_prob_delta": float(np.mean(np.abs(p_t - p_s))),
        "max_abs_prob_delta": float(np.max(np.abs(p_t - p_s))),
    }
    if valid_ds.labels is not None:
        lab = jnp.asarray(valid_ds.labels, jnp.float32)
        auc_t = float(binary_metrics(jnp.asarray(t_valid), lab)["roc_auc"])
        auc_s = float(binary_metrics(jnp.asarray(s_valid), lab)["roc_auc"])
        fidelity["teacher_roc_auc"] = auc_t
        fidelity["student_roc_auc"] = auc_s
        fidelity["roc_auc_delta"] = auc_s - auc_t
    return DistillResult(
        student_config=student_config,
        student_params=jax.device_get(params),
        fidelity=fidelity,
    )


def _quant_logits_chunked(
    qparams: Any, ds: EncodedDataset, chunk: int = 16_384
) -> np.ndarray:
    """Quant-student forward over a dataset at one fixed chunk shape
    (same padding discipline as `teacher_logits`)."""

    @jax.jit
    def fwd(cat, num):
        return quant_student_logits(qparams, cat, num)

    out = np.empty(ds.n, np.float32)
    for start in range(0, ds.n, chunk):
        stop = min(start + chunk, ds.n)
        cat, num = ds.cat_ids[start:stop], ds.numeric[start:stop]
        pad = chunk - (stop - start)
        if pad:
            cat = np.pad(cat, ((0, pad), (0, 0)))
            num = np.pad(num, ((0, pad), (0, 0)))
        out[start:stop] = np.asarray(
            fwd(jnp.asarray(cat, jnp.int32), jnp.asarray(num))
        )[: stop - start]
    return out


def distill_quant_student(
    teacher_model,
    teacher_variables,
    train_ds: EncodedDataset,
    valid_ds: EncodedDataset,
    embed_dim: int = QUANT_EMBED_DIM,
    hidden: int = QUANT_HIDDEN,
    steps: int = 800,
    batch_size: int = 2048,
    learning_rate: float = 3e-3,
    seed: int = 0,
    lifecycle: LifecycleConfig | None = None,
) -> QuantDistillResult:
    """Distill the teacher into the QUANTIZED serving tier and grade it.

    Same logit-MSE scan fit as `distill_for_bulk`, but against the
    hand-written `ops/quant.py` student (one-hot embeds + a single
    relu trunk — the architecture the Pallas fused kernel serves), then:

    1. quantize the fitted f32 master (int8 dense / bf16 embeds),
    2. refit the calibration temperature on the QUANT logits
       (`train/calibrate.py fit_temperature` — quantization shifts the
       logit scale, so the teacher's T does not transfer),
    3. measure fidelity POST-quantization on the held-out split, and
    4. stamp the promotion decision (`quant_tier_gates` — the same
       ``max_auc_drop`` / ``max_ece`` knobs the shadow gates use).

    The result is self-contained evidence: the bundle carries it, the
    engine trusts it, the fidelity-pin test re-derives it.
    """
    from mlops_tpu.lifecycle.promote import (
        expected_calibration_error,
        quant_tier_gates,
    )

    lifecycle = lifecycle or LifecycleConfig()
    t_train = teacher_logits(teacher_model, teacher_variables, train_ds)

    master = init_quant_master(seed, embed_dim, hidden)
    optimizer = optax.adam(learning_rate)
    opt_state = optimizer.init(master)

    cat = jnp.asarray(train_ds.cat_ids, jnp.int32)
    num = jnp.asarray(train_ds.numeric)
    target = jnp.asarray(t_train)
    n = train_ds.n

    def scan_step(carry, i):
        master, opt_state = carry
        idx = jax.random.randint(
            jax.random.fold_in(jax.random.PRNGKey(seed + 1), i),
            (batch_size,),
            0,
            n,
        )

        def loss_of(p):
            pred = master_student_logits(p, cat[idx], num[idx])
            return jnp.mean(jnp.square(pred - target[idx]))

        loss, grads = jax.value_and_grad(loss_of)(master)
        updates, opt_state = optimizer.update(grads, opt_state)
        return (optax.apply_updates(master, updates), opt_state), loss

    @jax.jit
    def fit(master, opt_state):
        return jax.lax.scan(scan_step, (master, opt_state), jnp.arange(steps))

    (master, _), _ = fit(master, opt_state)
    qparams = quantize_student(jax.device_get(master))

    # Everything below grades the QUANTIZED tree — the exact tensor bits
    # that will serve — never the f32 master.
    t_valid = teacher_logits(teacher_model, teacher_variables, valid_ds)
    s_valid = _quant_logits_chunked(qparams, valid_ds)
    p_t = 1.0 / (1.0 + np.exp(-t_valid))
    p_s = 1.0 / (1.0 + np.exp(-s_valid))
    fidelity = {
        "mean_abs_prob_delta": float(np.mean(np.abs(p_t - p_s))),
        "max_abs_prob_delta": float(np.max(np.abs(p_t - p_s))),
    }
    temperature = 1.0
    if valid_ds.labels is not None:
        lab = np.asarray(valid_ds.labels, np.float32)
        temperature = fit_temperature(s_valid, lab)
        auc_t = float(
            binary_metrics(jnp.asarray(t_valid), jnp.asarray(lab))["roc_auc"]
        )
        auc_s = float(
            binary_metrics(jnp.asarray(s_valid), jnp.asarray(lab))["roc_auc"]
        )
        fidelity["teacher_roc_auc"] = auc_t
        fidelity["student_roc_auc"] = auc_s
        fidelity["roc_auc_delta"] = auc_s - auc_t
        fidelity["ece"] = expected_calibration_error(
            1.0 / (1.0 + np.exp(-s_valid / temperature)), lab
        )
    decision = quant_tier_gates(fidelity, lifecycle)
    gates = decision.as_dict() | {
        "max_auc_drop": lifecycle.max_auc_drop,
        "max_ece": lifecycle.max_ece,
    }
    return QuantDistillResult(
        qparams=qparams,
        fidelity=fidelity,
        temperature=float(temperature),
        gates=gates,
    )
