"""Masked-feature pretraining for the BERT family (BASELINE config 5).

The reference trains supervised-only (sklearn on labeled rows). The BERT
stretch config says "fine-tune", which implies something to fine-tune FROM:
this loop pretrains the encoder trunk on unlabeled rows with the
masked-feature objective (``models.bert.BertMaskedLM``) — 15% of value
tokens masked per row, cross-entropy on the masked positions only — then
``fine_tune_params`` grafts the trunk into the classifier for the standard
supervised trainer. Jitted scan over steps, data-parallel-ready (the step
is pure; shard the batch axis like any other step).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
import optax

from mlops_tpu.config import ModelConfig, TrainConfig
from mlops_tpu.data.encode import EncodedDataset
from mlops_tpu.models.bert import BertMaskedLM, transfer_encoder_params
from mlops_tpu.schema.features import SCHEMA

MASK_FRACTION = 0.15


@dataclasses.dataclass
class PretrainResult:
    params: Any  # trunk + mlm head
    losses: list[float]  # per-eval-interval mean masked-token loss


def build_mlm(config: ModelConfig) -> BertMaskedLM:
    dtype = {"bf16": jnp.bfloat16, "f32": jnp.float32}[config.precision]
    return BertMaskedLM(
        cards=SCHEMA.cards,
        num_numeric=SCHEMA.num_numeric,
        hidden=config.token_dim,
        depth=config.depth,
        heads=config.heads,
        dropout=config.dropout,
        dtype=dtype,
    )


def masked_loss(logits, targets, mask):
    """Mean cross-entropy over masked positions only."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    denom = jnp.maximum(mask.sum(), 1)
    return jnp.where(mask, nll, 0.0).sum() / denom


def pretrain_bert(
    model_config: ModelConfig,
    ds: EncodedDataset,
    steps: int = 1000,
    batch_size: int = 256,
    learning_rate: float = 3e-4,
    seed: int = 0,
) -> PretrainResult:
    """Pretrain on an encoded (unlabeled) dataset; returns MLM params."""
    model = build_mlm(model_config)
    value_pos = jnp.asarray(model.value_positions())
    seq_len = model.layout.seq_len

    rng = jax.random.PRNGKey(seed)
    rng, init_rng = jax.random.split(rng)
    n = ds.n
    batch_size = min(batch_size, n)

    cat = jnp.asarray(ds.cat_ids)
    num = jnp.asarray(ds.numeric)

    init_mask = jnp.zeros((2, seq_len), bool)
    variables = model.init(
        {"params": init_rng}, cat[:2], num[:2], init_mask, train=False
    )
    params = variables["params"]
    tx = optax.adamw(learning_rate)
    opt_state = tx.init(params)

    def sample_mask(rng, batch):
        """Bernoulli(0.15) over value positions; guarantee >=1 mask/row by
        forcing one uniformly-chosen value position when none drew."""
        r1, r2 = jax.random.split(rng)
        draw = (
            jax.random.uniform(r1, (batch, value_pos.shape[0]))
            < MASK_FRACTION
        )
        forced = jax.nn.one_hot(
            jax.random.randint(r2, (batch,), 0, value_pos.shape[0]),
            value_pos.shape[0],
            dtype=bool,
        )
        draw = jnp.where(draw.any(axis=1, keepdims=True), draw, forced)
        mask = jnp.zeros((batch, seq_len), bool)
        return mask.at[:, value_pos].set(draw)

    # Scan body — run() below owns (and donates) the carry buffers; a
    # second donation here would double-free them.
    @jax.jit
    def step(carry, _):  # tpulint: disable=TPU105
        params, opt_state, rng = carry
        rng, bkey, mkey, dkey = jax.random.split(rng, 4)
        idx = jax.random.randint(bkey, (batch_size,), 0, n)
        mask = sample_mask(mkey, batch_size)

        def loss_fn(p):
            logits, targets = model.apply(
                {"params": p}, cat[idx], num[idx], mask,
                train=True, rngs={"dropout": dkey},
            )
            return masked_loss(logits, targets, mask)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return (params, opt_state, rng), loss

    # The initial carry is never reused after the call: donate it so the
    # params + adam moments update in place in HBM instead of
    # double-buffering (tpulint TPU105). Gated off on the 0.4.x CPU
    # backend (cached donated executables misbehave — parallel/compat.py).
    from mlops_tpu.parallel.compat import donation_argnums

    @partial(
        jax.jit, static_argnums=1, donate_argnums=donation_argnums(0)
    )
    def run(carry, n_steps):
        return jax.lax.scan(step, carry, None, length=n_steps)

    (params, opt_state, rng), losses = run((params, opt_state, rng), steps)
    losses = np.asarray(jax.device_get(losses))
    # Coarse loss curve (10 buckets) for logging/tests.
    chunks = np.array_split(losses, min(10, len(losses)))
    return PretrainResult(
        params=params, losses=[float(c.mean()) for c in chunks]
    )


def fine_tune_params(pretrain: PretrainResult, classifier_variables) -> Any:
    """Graft the pretrained trunk into freshly-initialized classifier
    variables (heads keep their init); feed to the standard trainer."""
    params = dict(classifier_variables["params"])
    merged = transfer_encoder_params(dict(pretrain.params), params)
    return {**classifier_variables, "params": merged}


def save_pretrained(result: PretrainResult, path) -> None:
    from pathlib import Path

    from mlops_tpu.train.checkpoint import tree_bytes
    from mlops_tpu.utils.io import atomic_write

    atomic_write(Path(path), tree_bytes(result.params))


def load_pretrained_variables(
    path, model_config: ModelConfig, classifier_variables
) -> Any:
    """Load saved MLM params and graft them into classifier variables."""
    from pathlib import Path

    from mlops_tpu.train.checkpoint import restore_tree

    if model_config.family != "bert":
        # The graft matches subtrees by NAME. mlp/linear share nothing
        # (the graft would be a silent no-op and "fine-tuning" would
        # start from a fresh model); ft_transformer shares the block_i
        # names and would silently absorb bert-pretrained blocks. Every
        # caller must hit this, so the check lives here, not per site.
        raise ValueError(
            "train.init_params grafts a bert masked-LM trunk by name; "
            f"family {model_config.family!r} shares no trunk with it"
        )

    mlm = build_mlm(model_config)
    seq_len = mlm.layout.seq_len
    template = mlm.init(
        {"params": jax.random.PRNGKey(0)},
        jnp.zeros((2, SCHEMA.num_categorical), jnp.int32),
        jnp.zeros((2, SCHEMA.num_numeric), jnp.float32),
        jnp.zeros((2, seq_len), bool),
        train=False,
    )["params"]
    params = restore_tree(template, Path(path).read_bytes())
    return fine_tune_params(
        PretrainResult(params=params, losses=[]), classifier_variables
    )
