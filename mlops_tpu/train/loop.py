"""The training loop: one compiled ``lax.scan`` per eval window.

Replaces the reference's per-trial sklearn ``pipeline.fit`` driven from
Python (`01-train-model.ipynb:252-330`). TPU-first structure:

- the encoded dataset is placed on device **once** (the reference re-reads
  Spark every trial);
- minibatches are gathered on device from uniform random indices inside the
  scan body — no host->device transfer in the hot loop;
- ``eval_every`` steps run as a single ``lax.scan`` under ``jit`` with the
  train state donated, so Python dispatch cost is paid once per window, not
  per step;
- metrics parity: each eval computes the reference's five validation metrics
  (`01-train-model.ipynb:296-304`) on the held-out split.
"""

from __future__ import annotations

import contextlib
import dataclasses
from pathlib import Path
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import struct

from mlops_tpu.config import TrainConfig
from mlops_tpu.data.encode import EncodedDataset
from mlops_tpu.train import checkpoint as ckpt
from mlops_tpu.train.metrics import binary_metrics


class TrainState(struct.PyTreeNode):
    params: Any
    opt_state: Any
    step: jnp.ndarray
    rng: jnp.ndarray
    # Raw (biased) EMA accumulator when train.ema_decay > 0, else None.
    # Zero-initialized; consumers debias via ``ema_debiased``.
    ema: Any = None


def update_ema(ema: Any, params: Any, decay: float) -> Any:
    """One Polyak step of the raw (biased) accumulator — THE recurrence
    every trainer shares (dense scan, sharded step, vmapped HPO,
    long-context, pipeline parallel); a fix here fixes all of them."""
    return jax.tree_util.tree_map(
        lambda e, q: decay * e + (1.0 - decay) * q, ema, params
    )


def packaged_or_raw(ema: Any, params: Any, decay: float, step) -> Any:
    """What ships/evals: the debiased EMA when enabled and at least one
    step has run (a zero-step run's all-zeros accumulator would debias to
    0/0), else the raw params. Shared by the layout-loop packaging
    closures."""
    return debias_ema(ema, decay, step) if decay and step > 0 else params


def debias_ema(ema: Any, decay: float, step) -> Any:
    """Bias-corrected Polyak average: ``ema / (1 - decay^step)`` — exact
    from step 1, so short runs (bench trains 600 steps) are not dragged
    toward the zero init the raw accumulator starts from. ``step`` may be
    a traced array or a plain int (the layout loops' Python counter)."""
    correction = 1.0 - decay ** jnp.asarray(step, jnp.float32)
    return jax.tree_util.tree_map(lambda e: e / correction, ema)


def ema_debiased(state: TrainState, decay: float):
    return debias_ema(state.ema, decay, state.step)


@dataclasses.dataclass
class TrainResult:
    params: Any
    metrics: dict[str, float]  # metrics of the PACKAGED params (with
    # keep_best that is the best eval window, not necessarily the final)
    history: list[dict[str, float]]
    steps: int  # total steps trained
    packaged_step: int = 0  # the eval step the packaged params came from


def sigmoid_bce(
    logits: jnp.ndarray, labels: jnp.ndarray, pos_weight: float = 1.0
) -> jnp.ndarray:
    """Weighted sigmoid binary cross-entropy (mean).

    ``pos_weight`` scales the positive-class term for class imbalance — the
    reference leaves imbalance unhandled (SURVEY.md SS7 hard parts).
    """
    labels = labels.astype(jnp.float32)
    softplus = jax.nn.softplus
    per_example = pos_weight * labels * softplus(-logits) + (1.0 - labels) * softplus(
        logits
    )
    return per_example.mean()


def training_loss(
    model,
    params: Any,
    cat: jnp.ndarray,
    num: jnp.ndarray,
    lab: jnp.ndarray,
    dropout_rng: jnp.ndarray,
    pos_weight: float = 1.0,
) -> jnp.ndarray:
    """BCE plus every auxiliary the model sows into ``aux_losses`` (e.g.
    the MoE load-balance term, `models/moe.py`) — the one loss definition
    shared by the local scan trainer, the sharded pjit step and the
    vmapped HPO trials, so trainers never need to know which families
    carry auxiliaries (they sow pre-scaled values; non-MoE families sow
    nothing and pay nothing)."""
    logits, aux_state = model.apply(
        {"params": params},
        cat,
        num,
        train=True,
        rngs={"dropout": dropout_rng},
        mutable=["aux_losses"],
    )
    loss = sigmoid_bce(logits, lab, pos_weight)
    for leaf in jax.tree_util.tree_leaves(aux_state):
        loss = loss + jnp.mean(leaf)
    return loss


@contextlib.contextmanager
def metric_writers(metrics_path, config: TrainConfig):
    """THE metric-sink contract, shared by ``fit`` and every layout loop
    (train/pipeline.py): jsonl when a path is given, TensorBoard when
    ``train.tensorboard_dir`` is set — no trainer may silently ignore
    either knob. Yields ``emit(record)``; both sinks close on every exit
    (the TB writer buffers events, and a mid-run crash must not lose
    exactly the records a debugging session needs)."""
    from mlops_tpu.utils.jsonl import JsonlWriter

    writer = JsonlWriter(metrics_path) if metrics_path else None
    tb = None
    if config.tensorboard_dir:
        from mlops_tpu.utils.tboard import TensorBoardWriter

        tb = TensorBoardWriter(config.tensorboard_dir)

    def emit(record: dict) -> None:
        if writer is not None:
            writer.write(record)
        if tb is not None:
            tb.write(record)

    try:
        yield emit
    finally:
        if writer is not None:
            writer.close()
        if tb is not None:
            tb.close()


def make_optimizer(config: TrainConfig) -> optax.GradientTransformation:
    schedule = optax.warmup_cosine_decay_schedule(
        init_value=0.0,
        peak_value=config.learning_rate,
        warmup_steps=config.warmup_steps,
        decay_steps=max(config.steps, config.warmup_steps + 1),
        end_value=config.learning_rate * 0.05,
    )
    return optax.chain(
        optax.clip_by_global_norm(1.0),
        optax.adamw(schedule, weight_decay=config.weight_decay),
    )


def _device_put_dataset(ds: EncodedDataset, sharding=None):
    put = (lambda x: jax.device_put(x, sharding)) if sharding else jax.device_put
    return (
        put(jnp.asarray(ds.cat_ids)),
        put(jnp.asarray(ds.numeric)),
        put(jnp.asarray(ds.labels, dtype=jnp.float32)),
    )


def make_train_window(
    model,
    optimizer: optax.GradientTransformation,
    config: TrainConfig,
    window: int,
) -> Callable:
    """Build the jitted scan running ``window`` steps on device.

    The train state is donated: parameter/optimizer buffers are updated in
    place in HBM rather than reallocated each window.
    """

    def run_window(state: TrainState, cat, num, lab):
        n = cat.shape[0]

        def one_step(state: TrainState, _):
            step_rng = jax.random.fold_in(state.rng, state.step)
            idx_rng, dropout_rng = jax.random.split(step_rng)
            idx = jax.random.randint(idx_rng, (config.batch_size,), 0, n)

            def loss_of(params):
                return training_loss(
                    model,
                    params,
                    cat[idx],
                    num[idx],
                    lab[idx],
                    dropout_rng,
                    config.pos_weight,
                )

            loss, grads = jax.value_and_grad(loss_of)(state.params)
            updates, opt_state = optimizer.update(
                grads, state.opt_state, state.params
            )
            params = optax.apply_updates(state.params, updates)
            ema = state.ema
            if config.ema_decay:  # static at trace time
                ema = update_ema(ema, params, config.ema_decay)
            new_state = state.replace(
                params=params, opt_state=opt_state, step=state.step + 1, ema=ema
            )
            return new_state, loss

        state, losses = jax.lax.scan(one_step, state, xs=None, length=window)
        return state, losses.mean()

    from mlops_tpu.parallel.compat import donation_argnums

    # Donation gated off only on the 0.4.x CPU backend, where a cached
    # donated executable silently corrupts its results after
    # deserialization (parallel/compat.py); everywhere else the train
    # state updates in place in HBM.
    return jax.jit(run_window, donate_argnums=donation_argnums(0))


def make_eval_fn(model) -> Callable:
    """Jitted full-split eval; build once per model and reuse across calls."""

    @jax.jit
    def _eval(params, cat, num, lab):
        logits = model.apply({"params": params}, cat, num, train=False)
        return binary_metrics(logits, lab)

    return _eval


def evaluate(model, params, ds: EncodedDataset) -> dict[str, float]:
    """One-shot eval with the reference's metric names (standalone use;
    inside ``fit`` the jitted eval fn and device data are cached instead)."""
    cat, num, lab = _device_put_dataset(ds)
    metrics = make_eval_fn(model)(params, cat, num, lab)
    return {f"validation_{k}_score": float(v) for k, v in metrics.items()}


def fit(
    model,
    train_ds: EncodedDataset,
    valid_ds: EncodedDataset,
    config: TrainConfig,
    init_variables: Any | None = None,
    metrics_path: str | Path | None = None,
    checkpoint_dir: str | Path | None = None,
    compile_cache=None,
) -> TrainResult:
    """Train ``model`` on an encoded dataset; resume from checkpoints if any."""
    from mlops_tpu.models import init_params

    rng = jax.random.PRNGKey(config.seed)
    init_rng, loop_rng = jax.random.split(rng)
    variables = init_variables or init_params(model, init_rng)
    params = variables["params"]
    if init_variables is not None:
        # Donation safety: run_window donates the TrainState, deleting its
        # input buffers in place. Caller-provided init arrays (a pretrained
        # trunk fine-tuned several times, ablation loops) must not be
        # consumed — copy them into fresh buffers the donation may eat.
        params = jax.tree_util.tree_map(jnp.array, params)
    optimizer = make_optimizer(config)
    state = TrainState(
        params=params,
        opt_state=optimizer.init(params),
        step=jnp.asarray(0, jnp.int32),
        rng=loop_rng,
        ema=(
            jax.tree_util.tree_map(jnp.zeros_like, params)
            if config.ema_decay
            else None
        ),
    )

    start_step = 0
    if checkpoint_dir is not None:
        restored = ckpt.load_checkpoint(checkpoint_dir, state)
        if restored is not None:
            state, start_step = restored

    base_window = max(1, min(config.eval_every, config.steps))
    window_fns: dict[int, Callable] = {}
    cat, num, lab = _device_put_dataset(train_ds)
    eval_fn = make_eval_fn(model)
    vcat, vnum, vlab = _device_put_dataset(valid_ds)

    # Best-eval tracking (train.keep_best): snapshot the params of the
    # highest-AUC eval window so long runs cannot ship an overfit tail.
    # The snapshot persists NEXT TO the checkpoints so a crash-resume
    # continues the comparison instead of restarting it at -inf (which
    # would re-ship the overfit tail the feature exists to prevent).
    best_auc = float("-inf")
    best_params = None
    best_record: dict | None = None
    if config.keep_best and checkpoint_dir is not None:
        restored_best = ckpt.load_best(Path(checkpoint_dir), params)
        if restored_best is not None:
            best_params, best_record = restored_best
            best_auc = best_record["validation_roc_auc_score"]

    history: list[dict[str, float]] = []
    step = start_step
    last_ckpt = start_step
    with metric_writers(metrics_path, config) as emit:
        while step < config.steps:
            # Final window shrinks so the step budget is honored exactly even
            # when steps % eval_every != 0 or when resuming mid-window.
            window = min(base_window, config.steps - step)
            run_window = window_fns.get(window)
            if run_window is None:
                run_window = make_train_window(model, optimizer, config, window)
                if compile_cache is not None:
                    # AOT-load the window scan through the persistent
                    # executable cache (entry ``train-step-dense``): repeat
                    # runs of a config deserialize instead of re-tracing +
                    # re-XLA-compiling per process. On backends where the
                    # state is donated and a cached donated executable
                    # misbehaves, the cache layer's capability gate
                    # bypass-compiles (compilecache/cache.py).
                    from mlops_tpu.compilecache.warmup import train_window_job

                    run_window = compile_cache.load_or_compile(
                        train_window_job(
                            model, optimizer, config, window,
                            state, cat, num, lab, jitted=run_window,
                        )
                    )
                window_fns[window] = run_window
            state, mean_loss = run_window(state, cat, num, lab)
            step = int(state.step)
            # Metrics must describe the params that will be PACKAGED —
            # the debiased EMA when enabled (a promotion decision made on
            # raw-param metrics would grade a model that never ships).
            eval_params = (
                ema_debiased(state, config.ema_decay)
                if config.ema_decay
                else state.params
            )
            record = {"step": step, "train_loss": float(mean_loss)}
            record.update(
                {
                    f"validation_{k}_score": float(v)
                    for k, v in eval_fn(eval_params, vcat, vnum, vlab).items()
                }
            )
            if (
                config.keep_best
                and record["validation_roc_auc_score"] > best_auc
            ):
                # strict >: a plateaued run must not re-pay the full
                # device->host params copy every tying window
                best_auc = record["validation_roc_auc_score"]
                best_params = jax.device_get(eval_params)
                best_record = record
                if checkpoint_dir is not None:
                    ckpt.save_best(Path(checkpoint_dir), best_params, best_record)
            history.append(record)
            emit(record)
            if (
                checkpoint_dir is not None
                and step - last_ckpt >= config.checkpoint_every
            ):
                ckpt.save_checkpoint(checkpoint_dir, state, step)
                last_ckpt = step
        if checkpoint_dir is not None and step > last_ckpt:
            ckpt.save_checkpoint(checkpoint_dir, state, step)

    # step == 0 (eval-only / fully-resumed-with-no-new-steps runs that never
    # entered the loop THIS process but restored step>0 are fine; a literal
    # zero-step run has an all-zeros accumulator and a 1-d^0 = 0 correction)
    # falls back to the raw params instead of packaging 0/0 = NaN.
    serving_params = (
        ema_debiased(state, config.ema_decay)
        if config.ema_decay and int(state.step) > 0
        else state.params
    )
    if best_params is not None:
        # Metrics and params come from the SAME (best) eval window — the
        # bundle always grades exactly what it serves.
        final, packaged = best_record, best_params
    else:
        final = (
            history[-1]
            if history
            else {
                f"validation_{k}_score": float(v)
                for k, v in eval_fn(serving_params, vcat, vnum, vlab).items()
            }
        )
        packaged = jax.device_get(serving_params)
    return TrainResult(
        params=packaged,
        metrics={k: v for k, v in final.items() if k.startswith("validation_")},
        history=history,
        steps=step,
        packaged_step=int(final.get("step", step)),
    )
