"""Jittable binary-classification metrics.

Parity with the reference's per-trial validation metrics
(`01-train-model.ipynb:296-304`): ``validation_{accuracy, roc_auc, f1,
precision, recall}_score`` — computed here as pure JAX so they run on device
inside the compiled eval step (no sklearn, no host round-trip).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def roc_auc(scores: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    """ROC-AUC via the Mann-Whitney U statistic with average ranks for ties.

    Equivalent to ``sklearn.metrics.roc_auc_score`` (which the reference gets
    through ``mlflow.sklearn.autolog``) up to floating point.
    """
    scores = scores.astype(jnp.float32)
    labels = labels.astype(jnp.float32)
    n = scores.shape[0]
    order = jnp.argsort(scores)
    sorted_scores = scores[order]
    # Average ranks with tie handling: rank = mean of ordinal ranks within a
    # tied group. Compute via searchsorted on the sorted array.
    first = jnp.searchsorted(sorted_scores, scores, side="left")
    last = jnp.searchsorted(sorted_scores, scores, side="right")
    ranks = (first + last + 1.0) / 2.0  # 1-indexed average ranks
    n_pos = labels.sum()
    n_neg = n - n_pos
    rank_sum = jnp.sum(ranks * labels)
    u = rank_sum - n_pos * (n_pos + 1.0) / 2.0
    denom = jnp.maximum(n_pos * n_neg, 1.0)
    return jnp.where((n_pos == 0) | (n_neg == 0), 0.5, u / denom)


def binary_metrics(
    logits: jnp.ndarray, labels: jnp.ndarray, threshold: float = 0.5
) -> dict[str, jnp.ndarray]:
    """accuracy / roc_auc / f1 / precision / recall at a probability threshold.

    ``logits`` are raw (pre-sigmoid) model outputs.
    """
    labels = labels.astype(jnp.float32)
    probs = jax.nn.sigmoid(jnp.asarray(logits, jnp.float32))
    preds = (probs >= threshold).astype(jnp.float32)
    tp = jnp.sum(preds * labels)
    fp = jnp.sum(preds * (1.0 - labels))
    fn = jnp.sum((1.0 - preds) * labels)
    precision = tp / jnp.maximum(tp + fp, 1.0)
    recall = tp / jnp.maximum(tp + fn, 1.0)
    f1 = 2.0 * precision * recall / jnp.maximum(precision + recall, 1e-12)
    return {
        "accuracy": jnp.mean(preds == labels),
        "roc_auc": roc_auc(probs, labels),
        "f1": f1,
        "precision": precision,
        "recall": recall,
    }
