"""Fitted monitor state (a pytree) + the jittable scoring functions."""

from __future__ import annotations

from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from mlops_tpu.config import MonitorConfig
from mlops_tpu.data.encode import EncodedDataset
from mlops_tpu.ops.drift import (
    chi2_two_sample,
    ks_two_sample,
    ks_two_sample_masked,
    ks_two_sample_small_masked,
)
from mlops_tpu.ops.outlier import fit_mahalanobis, mahalanobis_sq
from mlops_tpu.schema.features import SCHEMA


class MonitorState(struct.PyTreeNode):
    """Everything the fused predict needs, as fixed-shape device arrays.

    - ``cat_ref_counts``  f32 [C, max_card]: training category counts per
      categorical feature, zero-padded to the max cardinality.
    - ``num_ref_sorted``  f32 [M, R]: sorted training reference sample per
      numeric feature (subsampled to ``drift_ref_size``).
    - ``num_ref_cdf``     f32 [M, R]: each reference's own right-continuous
      ECDF values (tie-aware) — a fit-time constant that lets the grouped
      serving path run K-S without per-slot sorts (`ops/drift.py`).
    - ``out_mean/out_precision/out_threshold``: Mahalanobis detector.
    """

    cat_ref_counts: jnp.ndarray
    num_ref_sorted: jnp.ndarray
    num_ref_cdf: jnp.ndarray
    out_mean: jnp.ndarray
    out_precision: jnp.ndarray
    out_threshold: jnp.ndarray

    # ------------------------------------------------------------ serialize
    def to_arrays(self) -> dict[str, np.ndarray]:
        return {
            "cat_ref_counts": np.asarray(self.cat_ref_counts),
            "num_ref_sorted": np.asarray(self.num_ref_sorted),
            "num_ref_cdf": np.asarray(self.num_ref_cdf),
            "out_mean": np.asarray(self.out_mean),
            "out_precision": np.asarray(self.out_precision),
            "out_threshold": np.asarray(self.out_threshold),
        }

    @classmethod
    def from_arrays(cls, arrays: dict[str, np.ndarray]) -> "MonitorState":
        arrays = dict(arrays)
        if "num_ref_cdf" not in arrays:  # bundles saved before the field
            arrays["num_ref_cdf"] = _ref_cdf(
                np.asarray(arrays["num_ref_sorted"])
            )
        return cls(
            **{k: jnp.asarray(arrays[k]) for k in (
                "cat_ref_counts",
                "num_ref_sorted",
                "num_ref_cdf",
                "out_mean",
                "out_precision",
                "out_threshold",
            )}
        )

    def save(self, path: str | Path) -> None:
        np.savez(Path(path).with_suffix(".npz"), **self.to_arrays())

    @classmethod
    def load(cls, path: str | Path) -> "MonitorState":
        with np.load(Path(path).with_suffix(".npz")) as data:
            return cls.from_arrays({k: data[k] for k in data.files})


def _ref_cdf(ref_sorted: np.ndarray) -> np.ndarray:
    """Right-continuous ECDF of each sorted reference row at its own
    points (ties collapse to the last occurrence, matching
    ``searchsorted(..., side="right")``)."""
    m, r = ref_sorted.shape
    out = np.empty((m, r), dtype=np.float32)
    for j in range(m):
        out[j] = np.searchsorted(
            ref_sorted[j], ref_sorted[j], side="right"
        ) / float(r)
    return out


def abstract_monitor_state(config: MonitorConfig | None = None) -> MonitorState:
    """Shape-only MonitorState (ShapeDtypeStruct leaves) for abstract
    tracing and AOT cache keys: the monitor's array shapes are fully
    determined by the schema and ``drift_ref_size``, so the tpulint
    Layer-2 registry (`analysis/entrypoints.py`) and the compile-cache
    warmup CLI (`compilecache/warmup.py`) can lower the serving programs
    without a fitted monitor — and produce the exact keys a fitted one
    would."""
    config = config or MonitorConfig()
    S = jax.ShapeDtypeStruct
    ref = config.drift_ref_size
    return MonitorState(
        cat_ref_counts=S((SCHEMA.num_categorical, max(SCHEMA.cards)), jnp.float32),
        num_ref_sorted=S((SCHEMA.num_numeric, ref), jnp.float32),
        num_ref_cdf=S((SCHEMA.num_numeric, ref), jnp.float32),
        out_mean=S((SCHEMA.num_numeric,), jnp.float32),
        out_precision=S((SCHEMA.num_numeric, SCHEMA.num_numeric), jnp.float32),
        out_threshold=S((), jnp.float32),
    )


def fit_monitor(
    ds: EncodedDataset, config: MonitorConfig | None = None, seed: int = 0
) -> MonitorState:
    """Host-side fit on the encoded TRAINING split.

    Mirrors the reference's fit inputs: drift reference = full feature
    matrix, outlier detector = numeric features only
    (`02-register-model.ipynb:225-233`).
    """
    config = config or MonitorConfig()
    max_card = max(SCHEMA.cards)
    counts = np.zeros((SCHEMA.num_categorical, max_card), dtype=np.float32)
    for j, feat in enumerate(SCHEMA.categorical):
        binc = np.bincount(ds.cat_ids[:, j], minlength=feat.card)
        counts[j, : feat.card] = binc

    rng = np.random.default_rng(seed)
    n = ds.numeric.shape[0]
    size = min(config.drift_ref_size, n)
    idx = rng.choice(n, size=size, replace=False)
    ref = np.sort(ds.numeric[idx].astype(np.float32), axis=0).T  # [M, R]

    mean, precision, threshold = fit_mahalanobis(
        ds.numeric, quantile=config.outlier_quantile
    )
    return MonitorState(
        cat_ref_counts=jnp.asarray(counts),
        num_ref_sorted=jnp.asarray(ref),
        num_ref_cdf=jnp.asarray(_ref_cdf(ref)),
        out_mean=jnp.asarray(mean),
        out_precision=jnp.asarray(precision),
        out_threshold=jnp.asarray(threshold, dtype=jnp.float32),
    )


def drift_scores(
    state: MonitorState,
    cat_ids: jnp.ndarray,
    numeric: jnp.ndarray,
    mask: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """Per-feature drift scores ``1 - p_val`` in schema order ([C+M]).

    Categorical: chi-squared contingency vs training counts. Numeric:
    two-sample K-S vs the stored reference sample. Both vmapped across
    features — the entire drift pass is one fused computation. ``mask``
    (bool [N]) excludes padded rows when serving pads to bucket sizes.
    """
    max_card = state.cat_ref_counts.shape[1]
    one_hot = jax.nn.one_hot(cat_ids, max_card, dtype=jnp.float32)  # [N, C, K]
    if mask is not None:
        one_hot = one_hot * mask.astype(jnp.float32)[:, None, None]
    batch_counts = one_hot.sum(axis=0)  # [C, K]
    _, cat_p = jax.vmap(chi2_two_sample)(state.cat_ref_counts, batch_counts)

    if mask is None:
        _, num_p = jax.vmap(ks_two_sample)(state.num_ref_sorted, numeric.T)
    elif numeric.shape[0] <= 64:
        # Small (serving / grouped) batches: dense-comparison K-S — no
        # per-call sorts or gathers, which dominate vmapped-per-request
        # dispatches on TPU (see ops/drift.py).
        _, num_p = jax.vmap(
            ks_two_sample_small_masked, in_axes=(0, 0, 0, None)
        )(state.num_ref_sorted, state.num_ref_cdf, numeric.T, mask)
    else:
        _, num_p = jax.vmap(ks_two_sample_masked, in_axes=(0, 0, None))(
            state.num_ref_sorted, numeric.T, mask
        )
    return 1.0 - jnp.concatenate([cat_p, num_p])


def outlier_flags(
    state: MonitorState, numeric: jnp.ndarray, mask: jnp.ndarray | None = None
) -> jnp.ndarray:
    """Per-row 0/1 outlier flags (reference contract: `app/model.py:69`)."""
    distances = mahalanobis_sq(numeric, state.out_mean, state.out_precision)
    flags = (distances > state.out_threshold).astype(jnp.float32)
    if mask is not None:
        flags = flags * mask.astype(jnp.float32)
    return flags
