"""Fitted monitor state (a pytree) + the jittable scoring functions."""

from __future__ import annotations

from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from mlops_tpu.config import MonitorConfig
from mlops_tpu.data.encode import EncodedDataset
from mlops_tpu.ops.drift import (
    chi2_two_sample,
    ks_two_sample,
    ks_two_sample_masked,
    ks_two_sample_small_masked,
)
from mlops_tpu.ops.outlier import fit_mahalanobis, mahalanobis_sq
from mlops_tpu.schema.features import SCHEMA


class MonitorState(struct.PyTreeNode):
    """Everything the fused predict needs, as fixed-shape device arrays.

    - ``cat_ref_counts``  f32 [C, max_card]: training category counts per
      categorical feature, zero-padded to the max cardinality.
    - ``num_ref_sorted``  f32 [M, R]: sorted training reference sample per
      numeric feature (subsampled to ``drift_ref_size``).
    - ``num_ref_cdf``     f32 [M, R]: each reference's own right-continuous
      ECDF values (tie-aware) — a fit-time constant that lets the grouped
      serving path run K-S without per-slot sorts (`ops/drift.py`).
    - ``out_mean/out_precision/out_threshold``: Mahalanobis detector.
    """

    cat_ref_counts: jnp.ndarray
    num_ref_sorted: jnp.ndarray
    num_ref_cdf: jnp.ndarray
    out_mean: jnp.ndarray
    out_precision: jnp.ndarray
    out_threshold: jnp.ndarray

    # ------------------------------------------------------------ serialize
    def to_arrays(self) -> dict[str, np.ndarray]:
        return {
            "cat_ref_counts": np.asarray(self.cat_ref_counts),
            "num_ref_sorted": np.asarray(self.num_ref_sorted),
            "num_ref_cdf": np.asarray(self.num_ref_cdf),
            "out_mean": np.asarray(self.out_mean),
            "out_precision": np.asarray(self.out_precision),
            "out_threshold": np.asarray(self.out_threshold),
        }

    @classmethod
    def from_arrays(cls, arrays: dict[str, np.ndarray]) -> "MonitorState":
        arrays = dict(arrays)
        if "num_ref_cdf" not in arrays:  # bundles saved before the field
            arrays["num_ref_cdf"] = _ref_cdf(
                np.asarray(arrays["num_ref_sorted"])
            )
        return cls(
            **{k: jnp.asarray(arrays[k]) for k in (
                "cat_ref_counts",
                "num_ref_sorted",
                "num_ref_cdf",
                "out_mean",
                "out_precision",
                "out_threshold",
            )}
        )

    def save(self, path: str | Path) -> None:
        np.savez(Path(path).with_suffix(".npz"), **self.to_arrays())

    @classmethod
    def load(cls, path: str | Path) -> "MonitorState":
        with np.load(Path(path).with_suffix(".npz")) as data:
            return cls.from_arrays({k: data[k] for k in data.files})


def _ref_cdf(ref_sorted: np.ndarray) -> np.ndarray:
    """Right-continuous ECDF of each sorted reference row at its own
    points (ties collapse to the last occurrence, matching
    ``searchsorted(..., side="right")``)."""
    m, r = ref_sorted.shape
    out = np.empty((m, r), dtype=np.float32)
    for j in range(m):
        out[j] = np.searchsorted(
            ref_sorted[j], ref_sorted[j], side="right"
        ) / float(r)
    return out


def abstract_monitor_state(config: MonitorConfig | None = None) -> MonitorState:
    """Shape-only MonitorState (ShapeDtypeStruct leaves) for abstract
    tracing and AOT cache keys: the monitor's array shapes are fully
    determined by the schema and ``drift_ref_size``, so the tpulint
    Layer-2 registry (`analysis/entrypoints.py`) and the compile-cache
    warmup CLI (`compilecache/warmup.py`) can lower the serving programs
    without a fitted monitor — and produce the exact keys a fitted one
    would."""
    config = config or MonitorConfig()
    S = jax.ShapeDtypeStruct
    ref = config.drift_ref_size
    return MonitorState(
        cat_ref_counts=S((SCHEMA.num_categorical, max(SCHEMA.cards)), jnp.float32),
        num_ref_sorted=S((SCHEMA.num_numeric, ref), jnp.float32),
        num_ref_cdf=S((SCHEMA.num_numeric, ref), jnp.float32),
        out_mean=S((SCHEMA.num_numeric,), jnp.float32),
        out_precision=S((SCHEMA.num_numeric, SCHEMA.num_numeric), jnp.float32),
        out_threshold=S((), jnp.float32),
    )


def fit_monitor(
    ds: EncodedDataset, config: MonitorConfig | None = None, seed: int = 0
) -> MonitorState:
    """Host-side fit on the encoded TRAINING split.

    Mirrors the reference's fit inputs: drift reference = full feature
    matrix, outlier detector = numeric features only
    (`02-register-model.ipynb:225-233`).
    """
    config = config or MonitorConfig()
    max_card = max(SCHEMA.cards)
    counts = np.zeros((SCHEMA.num_categorical, max_card), dtype=np.float32)
    for j, feat in enumerate(SCHEMA.categorical):
        binc = np.bincount(ds.cat_ids[:, j], minlength=feat.card)
        counts[j, : feat.card] = binc

    rng = np.random.default_rng(seed)
    n = ds.numeric.shape[0]
    size = min(config.drift_ref_size, n)
    idx = rng.choice(n, size=size, replace=False)
    ref = np.sort(ds.numeric[idx].astype(np.float32), axis=0).T  # [M, R]

    mean, precision, threshold = fit_mahalanobis(
        ds.numeric, quantile=config.outlier_quantile
    )
    return MonitorState(
        cat_ref_counts=jnp.asarray(counts),
        num_ref_sorted=jnp.asarray(ref),
        num_ref_cdf=jnp.asarray(_ref_cdf(ref)),
        out_mean=jnp.asarray(mean),
        out_precision=jnp.asarray(precision),
        out_threshold=jnp.asarray(threshold, dtype=jnp.float32),
    )


class MonitorAccumulator(struct.PyTreeNode):
    """Device-resident running aggregate of the serving monitors.

    The seed path derived /metrics totals on the HOST from every response
    (sum the outlier flags, copy the drift dict — per request, on the hot
    path). Here the aggregate lives on the device and is folded INSIDE the
    fused predict program (`ops/predict.py make_packed_*`): the request
    path never fetches it, a telemetry task reads it every K requests /
    T seconds (`serve/server.py`). All leaves are f32 so the whole state
    rides one tiny D2H transfer — and each read RESETS the device window
    (`serve/engine.py monitor_snapshot` folds it into exact host-side f64
    totals), so the f32 counters never approach 2^24, where integer
    increments would silently stop.

    - ``rows``      f32 []:  valid (non-padding) rows scored
    - ``outliers``  f32 []:  outlier flags raised
    - ``batches``   f32 []:  dispatches folded (grouped slots count one
      per non-empty request slot)
    - ``drift_sum`` f32 [D]: per-feature sum of batch drift scores (mean
      drift = drift_sum / batches)
    - ``drift_last``f32 [D]: drift of the most recently folded dispatch
      (grouped dispatches fold the mean over their non-empty slots)
    """

    rows: jnp.ndarray
    outliers: jnp.ndarray
    batches: jnp.ndarray
    drift_sum: jnp.ndarray
    drift_last: jnp.ndarray


def init_accumulator() -> MonitorAccumulator:
    # DISTINCT arrays per leaf (never alias one zeros scalar): the engine
    # threads the accumulator as a donated argument where the backend
    # allows, and donating one buffer under two leaves is an XLA error
    # ("attempt to donate the same buffer twice").
    d = SCHEMA.num_categorical + SCHEMA.num_numeric
    return MonitorAccumulator(
        rows=jnp.zeros((), jnp.float32),
        outliers=jnp.zeros((), jnp.float32),
        batches=jnp.zeros((), jnp.float32),
        drift_sum=jnp.zeros((d,), jnp.float32),
        drift_last=jnp.zeros((d,), jnp.float32),
    )


def abstract_accumulator() -> MonitorAccumulator:
    """Shape-only accumulator (ShapeDtypeStruct leaves) — the tracing /
    AOT-cache-key twin of ``init_accumulator`` (same role as
    ``abstract_monitor_state``): shapes depend only on the schema."""
    d = SCHEMA.num_categorical + SCHEMA.num_numeric
    S = jax.ShapeDtypeStruct
    return MonitorAccumulator(
        rows=S((), jnp.float32),
        outliers=S((), jnp.float32),
        batches=S((), jnp.float32),
        drift_sum=S((d,), jnp.float32),
        drift_last=S((d,), jnp.float32),
    )


def fold_accumulator(
    acc: MonitorAccumulator,
    flags: jnp.ndarray,
    drift: jnp.ndarray,
    mask: jnp.ndarray,
) -> MonitorAccumulator:
    """Fold one padded batch into the running aggregate (jittable; called
    inside the fused predict). ``flags`` are already mask-zeroed
    (`outlier_flags`); an all-padding batch contributes nothing — not even
    to ``drift_last`` (an empty batch has no drift signal, the same
    invariant the engine's empty-request path keeps)."""
    n_valid = mask.astype(jnp.float32).sum()
    nonempty = (n_valid > 0).astype(jnp.float32)
    # Select, don't multiply: drift over ZERO valid rows can be NaN (the
    # chi-squared path divides by the row count) and NaN * 0 is still
    # NaN — a multiplicative mask would poison the running sum forever.
    safe_drift = jnp.where(nonempty > 0, drift, jnp.zeros_like(drift))
    return MonitorAccumulator(
        rows=acc.rows + n_valid,
        outliers=acc.outliers + flags.sum(),
        batches=acc.batches + nonempty,
        drift_sum=acc.drift_sum + safe_drift,
        drift_last=jnp.where(nonempty > 0, drift, acc.drift_last),
    )


def fold_accumulator_grouped(
    acc: MonitorAccumulator,
    flags: jnp.ndarray,
    drift: jnp.ndarray,
    mask: jnp.ndarray,
) -> MonitorAccumulator:
    """Grouped-dispatch fold: ``flags``/``mask`` are [S, R], ``drift`` is
    [S, D]. Padding SLOTS (mask all-false) are excluded everywhere; each
    non-empty slot counts as one batch and ``drift_last`` takes the mean
    drift over this dispatch's non-empty slots."""
    slot_rows = mask.astype(jnp.float32).sum(axis=1)  # [S]
    slot_valid = (slot_rows > 0).astype(jnp.float32)
    n_slots = slot_valid.sum()
    # Select, don't multiply: PADDING slots compute drift over zero rows,
    # where the chi-squared path divides by zero and yields NaN — and
    # NaN * 0 is still NaN, so a multiplicative mask would poison
    # drift_sum (and mean_drift) forever.
    safe_drift = jnp.where(slot_valid[:, None] > 0, drift, 0.0)
    drift_total = safe_drift.sum(axis=0)
    mean_drift = drift_total / jnp.maximum(n_slots, 1.0)
    return MonitorAccumulator(
        rows=acc.rows + slot_rows.sum(),
        outliers=acc.outliers + flags.sum(),
        batches=acc.batches + n_slots,
        drift_sum=acc.drift_sum + drift_total,
        drift_last=jnp.where(n_slots > 0, mean_drift, acc.drift_last),
    )


def merge_accumulators(
    older: MonitorAccumulator, newer: MonitorAccumulator
) -> MonitorAccumulator:
    """Combine two accumulator windows: counters and sums add;
    ``drift_last`` takes the newer window's unless it folded no batches.
    Used by `serve/engine.py monitor_snapshot` to fold an un-fetched
    window back into the live accumulator when a telemetry fetch fails —
    a transient device error must DELAY the counts, not drop them.

    Lock discipline: callers invoke this UNDER the engine's ``_acc_lock``
    (see TPULINT_LOCK_ORDER in serve/engine.py) so no dispatch can donate
    either operand mid-merge — which is safe under tpulint TPU403 because
    the merge is an eager device ENQUEUE, never a host-blocking fetch."""
    return MonitorAccumulator(
        rows=older.rows + newer.rows,
        outliers=older.outliers + newer.outliers,
        batches=older.batches + newer.batches,
        drift_sum=older.drift_sum + newer.drift_sum,
        drift_last=jnp.where(
            newer.batches > 0, newer.drift_last, older.drift_last
        ),
    )


def drift_scores(
    state: MonitorState,
    cat_ids: jnp.ndarray,
    numeric: jnp.ndarray,
    mask: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """Per-feature drift scores ``1 - p_val`` in schema order ([C+M]).

    Categorical: chi-squared contingency vs training counts. Numeric:
    two-sample K-S vs the stored reference sample. Both vmapped across
    features — the entire drift pass is one fused computation. ``mask``
    (bool [N]) excludes padded rows when serving pads to bucket sizes.
    """
    max_card = state.cat_ref_counts.shape[1]
    one_hot = jax.nn.one_hot(cat_ids, max_card, dtype=jnp.float32)  # [N, C, K]
    if mask is not None:
        one_hot = one_hot * mask.astype(jnp.float32)[:, None, None]
    batch_counts = one_hot.sum(axis=0)  # [C, K]
    _, cat_p = jax.vmap(chi2_two_sample)(state.cat_ref_counts, batch_counts)

    if mask is None:
        _, num_p = jax.vmap(ks_two_sample)(state.num_ref_sorted, numeric.T)
    elif numeric.shape[0] <= 64:
        # Small (serving / grouped) batches: dense-comparison K-S — no
        # per-call sorts or gathers, which dominate vmapped-per-request
        # dispatches on TPU (see ops/drift.py).
        _, num_p = jax.vmap(
            ks_two_sample_small_masked, in_axes=(0, 0, 0, None)
        )(state.num_ref_sorted, state.num_ref_cdf, numeric.T, mask)
    else:
        _, num_p = jax.vmap(ks_two_sample_masked, in_axes=(0, 0, None))(
            state.num_ref_sorted, numeric.T, mask
        )
    return 1.0 - jnp.concatenate([cat_p, num_p])


def outlier_flags(
    state: MonitorState, numeric: jnp.ndarray, mask: jnp.ndarray | None = None
) -> jnp.ndarray:
    """Per-row 0/1 outlier flags (reference contract: `app/model.py:69`)."""
    distances = mahalanobis_sq(numeric, state.out_mean, state.out_precision)
    flags = (distances > state.out_threshold).astype(jnp.float32)
    if mask is not None:
        flags = flags * mask.astype(jnp.float32)
    return flags
