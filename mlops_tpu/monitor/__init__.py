"""Monitoring: drift + outlier detector state, fit, and jit scoring.

Replaces the reference's alibi-detect pair bundled into its pyfunc artifact
(`02-register-model.ipynb:225-233`: ``TabularDrift(p_val=.05)`` on all
features + ``IForest(threshold=0.95)`` on numeric features; scored serially
on CPU inside ``CustomModel.predict``, `:330-353`). Here the fitted state is
a pytree of arrays that rides into the SAME compiled predict function as the
classifier, and the response contract is identical: per-feature drift scores
``1 - p_val`` and per-row 0/1 outlier flags.
"""

from mlops_tpu.monitor.state import (
    MonitorAccumulator,
    MonitorState,
    drift_scores,
    fit_monitor,
    fold_accumulator,
    fold_accumulator_grouped,
    init_accumulator,
    merge_accumulators,
    outlier_flags,
)

__all__ = [
    "MonitorAccumulator",
    "MonitorState",
    "drift_scores",
    "fit_monitor",
    "fold_accumulator",
    "fold_accumulator_grouped",
    "init_accumulator",
    "merge_accumulators",
    "outlier_flags",
]
