"""The compile cache's JAX-free registry surface.

This module is imported by tpulint's Layer-1 AST rules (`analysis/astrules.py`
TPU203), which must run on machines without an accelerator stack — so it can
never import JAX (or anything that transitively does).

Two invariants live here:

- ``CACHE_ENTRY_IDS`` — the entry points the cache knows how to warm. A test
  (tests/test_compilecache.py) pins this set equal to the tpulint Layer-2
  entry-point registry (`analysis/entrypoints.py registered_entry_points`),
  and ``warmup.warm_entry_points`` raises on any registered entry point
  without a warmer — the analyzer and the cache can never disagree about
  what the hot programs are.
- ``CACHED_JIT_BUILDERS`` — the builder functions under ``serve/`` and
  ``parallel/`` whose ``jax.jit`` call sites ARE routed through the cache.
  TPU203 flags any other jit site in those trees: a hot-path program that
  the cache cannot warm recompiles on every process start.
"""

from __future__ import annotations

CACHE_ENTRY_IDS: tuple[str, ...] = (
    "train-step-dense",
    "train-step-tp",
    # PR 4 replaced the dict-output serve programs ("serve-predict" /
    # "serve-predict-group") with the packed single-buffer forms
    # (`ops/predict.py make_packed_predict_base` / `make_packed_grouped_base`):
    # one contiguous f32 D2H buffer per request plus the device-resident
    # monitor accumulator. New entry ids, so stale dict-form artifacts can
    # never be probed, and the warmers/registry/tpulint lockstep moves as
    # one.
    "serve-predict-packed",
    "serve-predict-group-packed",
    # Quantized student tier (ops/quant_kernel.py): the int8/bf16 packed
    # programs — same 7-arg cacheable signature and packed layout as the
    # exact tier, different program family (Pallas-fused on TPU, jnp
    # composite elsewhere). Separate ids: a quant executable served where
    # the exact tier was asked for (or vice versa) must be a cache MISS,
    # never a silent hit.
    "serve-predict-quant-packed",
    "serve-predict-quant-group-packed",
    # GBM-tensor tier (ops/gbm_tensor.py, ISSUE 19): the Hummingbird-style
    # tensorization of the HistGBM baseline in the same packed 7-arg form —
    # f64 tree compares lowered inside the x64 context (the jobs carry
    # warmup._X64Jitted), keyed apart by the ensemble's static geometry
    # plus an explicit x64 marker in the config hash.
    "serve-predict-gbm-packed",
    "serve-predict-gbm-group-packed",
    "bulk-score-chunk",
)

# Function names (under serve/ and parallel/) whose jit sites are wired to
# cache.load_or_compile — the TPU203 whitelist. Keep in sync with the job
# builders in `compilecache/warmup.py`.
CACHED_JIT_BUILDERS: frozenset[str] = frozenset(
    {
        "make_chunk_scorer",  # parallel/bulk.py  -> bulk-score-chunk
        "make_bulk_jit",  # parallel/bulk.py      -> bulk-score-chunk
        "make_bulk_quant_jit",  # parallel/bulk.py -> bulk-score-chunk (quant)
        "make_sharded_train_step",  # parallel/steps.py -> train-step-tp
    }
)
