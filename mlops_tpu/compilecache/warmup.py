"""AOT warmup: job builders per registered entry point + the parallel runner.

Every production call site that compiles a hot program (the serving
engine's bucketed/grouped warmup, the bulk chunk scorer, the dense train
window, the TP pjit step) builds its `CacheJob` HERE, and the warmup CLI
(`mlops-tpu warmup`) enumerates the tpulint Layer-2 entry-point registry
(`analysis/entrypoints.py registered_entry_points`) through the same
builders — one definition per entry point, so a cache pre-populated at
container build time produces byte-for-byte the keys the serving process
probes. ``warm_entry_points`` raises on a registered entry point without a
warmer: the analyzer and the cache can never disagree about what the hot
programs are.

Misses compile IN PARALLEL: XLA compilation releases the GIL, so a small
thread pool over buckets turns the serial ~54 s cold warmup into
max-of-compiles instead of sum-of-compiles even with an empty cache.
"""

from __future__ import annotations

import concurrent.futures
import os
import time
from typing import Any, Callable

from mlops_tpu.compilecache.cache import CacheJob, CompileCache
from mlops_tpu.compilecache.keys import (
    model_fingerprint,
    train_fingerprint,
    tree_avals,
)
from mlops_tpu.compilecache.registry import CACHE_ENTRY_IDS


def _is_concrete(tree: Any) -> bool:
    import jax

    leaves = jax.tree_util.tree_leaves(tree)
    return bool(leaves) and not isinstance(leaves[0], jax.ShapeDtypeStruct)


def _schema_avals(batch_shape: tuple[int, ...], cat_dtype=None):
    import jax
    import jax.numpy as jnp

    from mlops_tpu.schema import SCHEMA

    S = jax.ShapeDtypeStruct
    return (
        S((*batch_shape, SCHEMA.num_categorical), cat_dtype or jnp.int32),
        S((*batch_shape, SCHEMA.num_numeric), jnp.float32),
        S(batch_shape, jnp.bool_),
    )


def _schema_zeros(batch_shape: tuple[int, ...], cat_dtype=None):
    import numpy as np

    from mlops_tpu.schema import SCHEMA

    return (
        np.zeros((*batch_shape, SCHEMA.num_categorical), cat_dtype or np.int32),
        np.zeros((*batch_shape, SCHEMA.num_numeric), np.float32),
        np.ones(batch_shape, bool),
    )


def _temp_aval():
    import jax
    import jax.numpy as jnp

    return jax.ShapeDtypeStruct((), jnp.float32)


# ----------------------------------------------------------- serve entries
def _acc_aval():
    from mlops_tpu.monitor.state import abstract_accumulator

    return tree_avals(abstract_accumulator())


def _acc_zeros():
    import jax

    from mlops_tpu.monitor.state import init_accumulator

    return jax.device_get(init_accumulator())


def _serve_avals(variables, monitor, batch_shape, mesh, placement=None):
    """The 7-arg serving signature's avals, optionally PLACEMENT-PINNED
    (ISSUE 13): with a ('model',) mesh (``serve.model_shards``) the
    param/monitor avals carry the engine's live committed shardings and
    the accumulator/temperature/batch avals pin to full replication;
    with a single-device ``placement`` (a replica's own device) every
    aval pins there. AOT lowering then bakes the layout into the
    artifact, and the cache key's mesh_shape/device_tag axes keep
    differently-placed binaries apart."""
    import jax

    var_avals, mon_avals = tree_avals(variables), tree_avals(monitor)
    acc_aval, temp_aval = _acc_aval(), _temp_aval()
    batch_avals = _schema_avals(batch_shape)
    if mesh is None and placement is None:
        return (var_avals, mon_avals, acc_aval, temp_aval, *batch_avals)
    from mlops_tpu.parallel.sharding import replicated_avals, sharded_avals

    if mesh is not None:
        return (
            sharded_avals(variables),
            sharded_avals(monitor),
            replicated_avals(acc_aval, mesh),
            replicated_avals(temp_aval, mesh),
            *replicated_avals(batch_avals, mesh),
        )

    def pin(tree):
        return jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(
                a.shape, a.dtype, sharding=placement
            ),
            tree,
        )

    return (
        sharded_avals(variables),  # committed leaves carry the placement
        sharded_avals(monitor),
        pin(acc_aval),
        pin(temp_aval),
        *pin(batch_avals),
    )


def serve_predict_jobs(
    model,
    model_config,
    variables,
    monitor,
    buckets: tuple[int, ...],
    temperature: float = 1.0,
    mesh=None,
    placement=None,
    device_tag: str = "",
) -> list[CacheJob]:
    """One job per warmup bucket of the PACKED serving predict (entry
    ``serve-predict-packed``: one flat f32 output buffer + the device
    monitor accumulator threaded as the gated-donation argument —
    `ops/predict.py make_packed_predict_base`). ``variables``/``monitor``
    may be concrete (the engine: jobs also execute once to pay
    first-dispatch allocation) or ShapeDtypeStruct trees (the warmup CLI:
    compile+persist only). ``mesh`` (a ('model',) serve mesh) requires
    CONCRETE committed trees — their live shardings become the lowered
    layout and the cache key grows the mesh shape. ``placement``/
    ``device_tag`` pin an engine replica's own device into the lowering
    and the key (serve.engine_replicas on a shared-visibility host)."""
    import jax
    import numpy as np

    from mlops_tpu.ops.predict import _acc_donation, make_packed_predict_base

    concrete = _is_concrete(variables)
    if (mesh is not None or placement is not None) and not concrete:
        raise ValueError(
            "placed serve warmup needs committed device trees (their "
            "shardings are the lowered layout)"
        )
    config_hash = model_fingerprint(model_config) + device_tag
    donate = _acc_donation()
    mesh_shape = tuple(mesh.devices.shape) if mesh is not None else None
    jobs = []
    for bucket in buckets:
        jobs.append(
            CacheJob(
                entry_id="serve-predict-packed",
                # A fresh jit per job: AOT lowering never reuses the jit
                # dispatch cache, and per-job objects keep the thread pool
                # free of shared mutable state.
                jitted=jax.jit(
                    make_packed_predict_base(model), donate_argnums=donate
                ),
                abstract_args=_serve_avals(
                    variables, monitor, (bucket,), mesh, placement
                ),
                config_hash=config_hash,
                mesh_shape=mesh_shape,
                donated=bool(donate),
                label=f"serve-predict-packed/b{bucket}",
                meta={"bucket": bucket},
                execute_args=(
                    (variables, monitor, _acc_zeros(),
                     np.float32(temperature), *_schema_zeros((bucket,)))
                    if concrete
                    else None
                ),
            )
        )
    return jobs


def serve_group_jobs(
    model,
    model_config,
    variables,
    monitor,
    grid: list[tuple[int, int]],
    temperature: float = 1.0,
    mesh=None,
    placement=None,
    device_tag: str = "",
) -> list[CacheJob]:
    """One job per (slots, rows) shape of the micro-batcher's PACKED
    vmapped dispatch (entry ``serve-predict-group-packed``).
    ``mesh``/``placement``/``device_tag``: see `serve_predict_jobs`."""
    import jax
    import numpy as np

    from mlops_tpu.ops.predict import _acc_donation, make_packed_grouped_base

    concrete = _is_concrete(variables)
    if (mesh is not None or placement is not None) and not concrete:
        raise ValueError(
            "placed serve warmup needs committed device trees (their "
            "shardings are the lowered layout)"
        )
    config_hash = model_fingerprint(model_config) + device_tag
    donate = _acc_donation()
    mesh_shape = tuple(mesh.devices.shape) if mesh is not None else None
    jobs = []
    for slots, rows in grid:
        jobs.append(
            CacheJob(
                entry_id="serve-predict-group-packed",
                jitted=jax.jit(
                    make_packed_grouped_base(model), donate_argnums=donate
                ),
                abstract_args=_serve_avals(
                    variables, monitor, (slots, rows), mesh, placement
                ),
                config_hash=config_hash,
                mesh_shape=mesh_shape,
                donated=bool(donate),
                label=f"serve-predict-group-packed/g{slots}x{rows}",
                meta={"slots": slots, "rows": rows},
                execute_args=(
                    (variables, monitor, _acc_zeros(),
                     np.float32(temperature), *_schema_zeros((slots, rows)))
                    if concrete
                    else None
                ),
            )
        )
    return jobs


def serve_quant_jobs(
    qparams,
    monitor,
    buckets: tuple[int, ...],
    temperature: float = 1.0,
    placement=None,
    device_tag: str = "",
) -> list[CacheJob]:
    """One job per warmup bucket of the QUANTIZED packed predict (entry
    ``serve-predict-quant-packed`` — `ops/quant_kernel.py
    make_quant_packed_base`). Same 7-arg signature and packed layout as
    the exact tier; ``qparams`` may be the bundle's concrete int8/bf16
    tree or the `ops/quant.py abstract_quant_params` twin. The quant tier
    is single-device by contract (the engine refuses quant + model
    shards), so there is no ``mesh`` axis — only the replica
    ``placement``/``device_tag`` pin."""
    import jax
    import numpy as np

    from mlops_tpu.ops.predict import _acc_donation
    from mlops_tpu.ops.quant import QUANT_FORMAT, quant_params_geometry
    from mlops_tpu.ops.quant_kernel import make_quant_packed_base

    concrete = _is_concrete(qparams)
    if placement is not None and not concrete:
        raise ValueError(
            "placed quant warmup needs committed device trees (their "
            "shardings are the lowered layout)"
        )
    embed_dim, hidden = quant_params_geometry(qparams)
    config_hash = (
        model_fingerprint((QUANT_FORMAT, embed_dim, hidden)) + device_tag
    )
    donate = _acc_donation()
    jobs = []
    for bucket in buckets:
        jobs.append(
            CacheJob(
                entry_id="serve-predict-quant-packed",
                jitted=jax.jit(
                    make_quant_packed_base(), donate_argnums=donate
                ),
                abstract_args=_serve_avals(
                    qparams, monitor, (bucket,), None, placement
                ),
                config_hash=config_hash,
                donated=bool(donate),
                label=f"serve-predict-quant-packed/b{bucket}",
                meta={"bucket": bucket},
                execute_args=(
                    (qparams, monitor, _acc_zeros(),
                     np.float32(temperature), *_schema_zeros((bucket,)))
                    if concrete
                    else None
                ),
            )
        )
    return jobs


def serve_quant_group_jobs(
    qparams,
    monitor,
    grid: list[tuple[int, int]],
    temperature: float = 1.0,
    placement=None,
    device_tag: str = "",
) -> list[CacheJob]:
    """One job per (slots, rows) shape of the quant tier's vmapped
    grouped dispatch (entry ``serve-predict-quant-group-packed``)."""
    import jax
    import numpy as np

    from mlops_tpu.ops.predict import _acc_donation
    from mlops_tpu.ops.quant import QUANT_FORMAT, quant_params_geometry
    from mlops_tpu.ops.quant_kernel import make_quant_grouped_base

    concrete = _is_concrete(qparams)
    if placement is not None and not concrete:
        raise ValueError(
            "placed quant warmup needs committed device trees (their "
            "shardings are the lowered layout)"
        )
    embed_dim, hidden = quant_params_geometry(qparams)
    config_hash = (
        model_fingerprint((QUANT_FORMAT, embed_dim, hidden)) + device_tag
    )
    donate = _acc_donation()
    jobs = []
    for slots, rows in grid:
        jobs.append(
            CacheJob(
                entry_id="serve-predict-quant-group-packed",
                jitted=jax.jit(
                    make_quant_grouped_base(), donate_argnums=donate
                ),
                abstract_args=_serve_avals(
                    qparams, monitor, (slots, rows), None, placement
                ),
                config_hash=config_hash,
                donated=bool(donate),
                label=f"serve-predict-quant-group-packed/g{slots}x{rows}",
                meta={"slots": slots, "rows": rows},
                execute_args=(
                    (qparams, monitor, _acc_zeros(),
                     np.float32(temperature), *_schema_zeros((slots, rows)))
                    if concrete
                    else None
                ),
            )
        )
    return jobs


def _gbm_serve_avals(variables, monitor, batch_shape, placement):
    """`_serve_avals` with the gbm tier's ONE dtype deviation: a f64
    temperature argument. The host hybrid this tier must match bit-for-bit
    divides logits by the FULL python float (`train/calibrate.py
    apply_temperature`); an f32 rounding of T shifts tempered
    probabilities by one ulp."""
    import jax
    import numpy as np

    avals = list(_serve_avals(variables, monitor, batch_shape, None, placement))
    avals[3] = (
        jax.ShapeDtypeStruct((), np.float64)
        if placement is None
        else jax.ShapeDtypeStruct((), np.float64, sharding=placement)
    )
    return tuple(avals)


class _X64Lowered:
    """See `_X64Jitted` — the lowering-side half of the wrapper."""

    def __init__(self, lowered):
        self._lowered = lowered

    def compile(self):
        from mlops_tpu.ops.gbm_tensor import x64_context

        with x64_context():
            return self._lowered.compile()


class _X64Jitted:
    """A jitted program whose AOT ``lower``/``compile`` must run inside
    the thread-local x64 context (the gbm-tensor tier: f64 tree compares
    — `ops/gbm_tensor.py`). Both cache consumers only ever call
    ``job.jitted.lower(*avals).compile()`` (`cache.py
    CompileCache._compile` and `run_jobs`'s cacheless path), and
    ``compile()`` returns the REAL compiled executable — persistence
    (executable serialize) and execution see a plain jax object, never
    this wrapper."""

    def __init__(self, jitted):
        self._jitted = jitted

    def lower(self, *args):
        from mlops_tpu.ops.gbm_tensor import x64_context

        with x64_context():
            return _X64Lowered(self._jitted.lower(*args))


def serve_gbm_jobs(
    variables,
    monitor,
    buckets: tuple[int, ...],
    geometry=None,
    temperature: float = 1.0,
    placement=None,
    device_tag: str = "",
) -> list[CacheJob]:
    """One job per warmup bucket of the GBM-TENSOR packed predict (entry
    ``serve-predict-gbm-packed`` — `ops/gbm_tensor.py
    make_gbm_packed_base`): the tensorized HistGBM ensemble in the same
    packed 7-arg form. The tree tensors are f64 and the program lowers
    inside the x64 context, so the jobs carry the `_X64Jitted` wrapper;
    the ensemble's static ``geometry`` rides the config hash
    (`gbm_fingerprint` — with an explicit x64 marker). Single-device by
    contract like the quant tier: only the replica ``placement``/
    ``device_tag`` pin, no mesh axis. ``variables`` must be COMMITTED
    under the x64 context (or host f64 numpy) so the avals stay f64."""
    import jax
    import numpy as np

    from mlops_tpu.ops.gbm_tensor import (
        device_put_x64,
        gbm_fingerprint,
        make_gbm_packed_base,
    )
    from mlops_tpu.ops.predict import _acc_donation

    concrete = _is_concrete(variables)
    if placement is not None and not concrete:
        raise ValueError(
            "placed gbm warmup needs committed device trees (their "
            "shardings are the lowered layout)"
        )
    config_hash = gbm_fingerprint(geometry) + device_tag
    donate = _acc_donation()
    # Committed f64 scalar: a host np.float64 fed to the compiled
    # executable outside the x64 context would canonicalize to f32 and
    # miss the f64 temperature signature.
    temp = device_put_x64(np.float64(temperature)) if concrete else None
    jobs = []
    for bucket in buckets:
        jobs.append(
            CacheJob(
                entry_id="serve-predict-gbm-packed",
                jitted=_X64Jitted(
                    jax.jit(
                        make_gbm_packed_base(geometry.depth),
                        donate_argnums=donate,
                    )
                ),
                abstract_args=_gbm_serve_avals(
                    variables, monitor, (bucket,), placement
                ),
                config_hash=config_hash,
                donated=bool(donate),
                label=f"serve-predict-gbm-packed/b{bucket}",
                meta={"bucket": bucket},
                execute_args=(
                    (variables, monitor, _acc_zeros(),
                     temp, *_schema_zeros((bucket,)))
                    if concrete
                    else None
                ),
            )
        )
    return jobs


def serve_gbm_group_jobs(
    variables,
    monitor,
    grid: list[tuple[int, int]],
    geometry=None,
    temperature: float = 1.0,
    placement=None,
    device_tag: str = "",
) -> list[CacheJob]:
    """One job per (slots, rows) shape of the gbm-tensor tier's vmapped
    grouped dispatch (entry ``serve-predict-gbm-group-packed``)."""
    import jax
    import numpy as np

    from mlops_tpu.ops.gbm_tensor import (
        device_put_x64,
        gbm_fingerprint,
        make_gbm_grouped_base,
    )
    from mlops_tpu.ops.predict import _acc_donation

    concrete = _is_concrete(variables)
    if placement is not None and not concrete:
        raise ValueError(
            "placed gbm warmup needs committed device trees (their "
            "shardings are the lowered layout)"
        )
    config_hash = gbm_fingerprint(geometry) + device_tag
    donate = _acc_donation()
    temp = device_put_x64(np.float64(temperature)) if concrete else None
    jobs = []
    for slots, rows in grid:
        jobs.append(
            CacheJob(
                entry_id="serve-predict-gbm-group-packed",
                jitted=_X64Jitted(
                    jax.jit(
                        make_gbm_grouped_base(geometry.depth),
                        donate_argnums=donate,
                    )
                ),
                abstract_args=_gbm_serve_avals(
                    variables, monitor, (slots, rows), placement
                ),
                config_hash=config_hash,
                donated=bool(donate),
                label=f"serve-predict-gbm-group-packed/g{slots}x{rows}",
                meta={"slots": slots, "rows": rows},
                execute_args=(
                    (variables, monitor, _acc_zeros(),
                     temp, *_schema_zeros((slots, rows)))
                    if concrete
                    else None
                ),
            )
        )
    return jobs


# ------------------------------------------------------------- bulk entry
def bulk_chunk_job(
    model,
    model_config,
    variables,
    monitor,
    chunk_rows: int,
    mesh=None,
    path_label: str = "exact",
    jitted: Callable | None = None,
) -> CacheJob:
    """The fused bulk chunk program (entry ``bulk-score-chunk``) at one
    chunk shape, with the production int8 categorical ids. ``path_label``
    keys the exact-ensemble and distilled-student programs apart (their
    architectures differ even when their signatures happen to match)."""
    import jax.numpy as jnp

    from mlops_tpu.parallel.bulk import make_bulk_jit

    return CacheJob(
        entry_id="bulk-score-chunk",
        jitted=jitted if jitted is not None else make_bulk_jit(model, mesh),
        abstract_args=(
            tree_avals(variables),
            tree_avals(monitor),
            _temp_aval(),
            *_schema_avals((chunk_rows,), cat_dtype=jnp.int8),
        ),
        config_hash=model_fingerprint((path_label, model_config)),
        mesh_shape=tuple(mesh.devices.shape) if mesh is not None else None,
        label=f"bulk-score-chunk/{path_label}-c{chunk_rows}",
        meta={"chunk_rows": chunk_rows, "path": path_label},
    )


def bulk_quant_chunk_job(
    qparams,
    monitor,
    chunk_rows: int,
    mesh=None,
    jitted: Callable | None = None,
) -> CacheJob:
    """The quant-tier bulk chunk program — same ``bulk-score-chunk``
    entry, keyed apart by ``path_label="quant"`` plus the quant FORMAT and
    geometry (the serve quant jobs' fingerprint discipline: the flax model
    config says nothing about this program — the int8/bf16 packing scheme
    and the (embed_dim, hidden) widths do)."""
    import jax.numpy as jnp

    from mlops_tpu.ops.quant import QUANT_FORMAT, quant_params_geometry
    from mlops_tpu.parallel.bulk import make_bulk_quant_jit

    embed_dim, hidden = quant_params_geometry(qparams)
    return CacheJob(
        entry_id="bulk-score-chunk",
        jitted=jitted if jitted is not None else make_bulk_quant_jit(mesh),
        abstract_args=(
            tree_avals(qparams),
            tree_avals(monitor),
            _temp_aval(),
            *_schema_avals((chunk_rows,), cat_dtype=jnp.int8),
        ),
        config_hash=model_fingerprint(
            ("quant", QUANT_FORMAT, embed_dim, hidden)
        ),
        mesh_shape=tuple(mesh.devices.shape) if mesh is not None else None,
        label=f"bulk-score-chunk/quant-c{chunk_rows}",
        meta={"chunk_rows": chunk_rows, "path": "quant"},
    )


# ------------------------------------------------------------ train entries
def train_window_job(
    model,
    optimizer,
    train_config,
    window: int,
    state,
    cat,
    num,
    lab,
    jitted: Callable | None = None,
) -> CacheJob:
    """The dense scan window (entry ``train-step-dense``) at one (window,
    dataset-shape) signature. Donation follows `parallel/compat.py
    donation_argnums`: when the backend donates the train state, the cache
    layer's capability gate bypasses deserialization on backends where a
    cached donated executable misbehaves."""
    import jax

    from mlops_tpu.parallel.compat import donation_argnums
    from mlops_tpu.train.loop import make_train_window

    if jitted is None:
        jitted = make_train_window(model, optimizer, train_config, window)
    args = tuple(tree_avals(a) for a in (state, cat, num, lab))
    rows = jax.tree_util.tree_leaves(args[1])[0].shape[0]
    return CacheJob(
        entry_id="train-step-dense",
        jitted=jitted,
        abstract_args=args,
        config_hash=train_fingerprint(model, train_config, f"window={window}"),
        donated=bool(donation_argnums(0)),
        label=f"train-step-dense/w{window}xn{rows}",
        meta={"window": window, "rows": rows},
    )


def tp_step_job(
    model,
    optimizer,
    train_config,
    mesh,
    state,
    batch_size: int,
    jitted: Callable,
) -> CacheJob:
    """The DP×TP pjit step (entry ``train-step-tp``) at the configured
    per-step batch. ``jitted`` is the REAL step from
    `parallel/steps.py make_sharded_train_step` — the cache wraps
    production programs, never re-implementations."""
    import jax
    import jax.numpy as jnp

    from mlops_tpu.parallel.compat import donation_argnums

    S = jax.ShapeDtypeStruct
    cat_a, num_a, _ = _schema_avals((batch_size,))
    return CacheJob(
        entry_id="train-step-tp",
        jitted=jitted,
        abstract_args=(
            tree_avals(state),
            cat_a,
            num_a,
            S((batch_size,), jnp.float32),
            S((2,), jnp.uint32),
        ),
        config_hash=train_fingerprint(model, train_config, "tp"),
        mesh_shape=tuple(mesh.devices.shape),
        donated=bool(donation_argnums(0)),
        label=f"train-step-tp/b{batch_size}",
        meta={"batch_size": batch_size},
    )


# --------------------------------------------------------------- execution
def default_workers(n_jobs: int, configured: int = 0) -> int:
    if configured > 0:
        return min(configured, n_jobs)
    return max(1, min(8, os.cpu_count() or 1, n_jobs))


def run_jobs(
    jobs: list[CacheJob],
    cache: CompileCache | None = None,
    workers: int = 0,
) -> list[tuple[CacheJob, Callable]]:
    """Load/compile every job on a small thread pool (misses overlap; hits
    deserialize in milliseconds each). Without a cache the jobs still AOT
    compile in parallel — the cacheless cold start gets max-of-compiles
    too, it just cannot persist."""

    def one(job: CacheJob) -> Callable:
        if cache is not None:
            return cache.load_or_compile(job)
        fn = job.jitted.lower(*job.abstract_args).compile()
        if job.execute_args is not None:
            import jax

            jax.block_until_ready(fn(*job.execute_args))
        return fn

    if not jobs:
        return []
    n = default_workers(len(jobs), workers)
    if n == 1:
        return [(job, one(job)) for job in jobs]
    with concurrent.futures.ThreadPoolExecutor(
        max_workers=n, thread_name_prefix="aot-warmup"
    ) as pool:
        compiled = list(pool.map(one, jobs))
    return list(zip(jobs, compiled))


# ------------------------------------------------------------- CLI warmers
def _serve_model_state(config, bundle):
    """(model, model_config, variables, monitor, temperature) for the serve
    entries — the bundle's real state when given (exact keys for that
    deployment), else abstract state derived purely from the config (what a
    container build can warm before any training ran)."""
    from mlops_tpu.models import build_model

    if bundle is not None:
        return (
            bundle.model,
            bundle.model_config,
            bundle.variables,
            bundle.monitor,
            bundle.temperature,
        )
    from mlops_tpu.models import abstract_variables
    from mlops_tpu.monitor.state import abstract_monitor_state

    model = build_model(config.model)
    return (
        model,
        config.model,
        abstract_variables(model),
        abstract_monitor_state(config.monitor),
        1.0,
    )


def _warm_serve_predict(config, bundle) -> list[CacheJob]:
    model, mcfg, variables, monitor, temp = _serve_model_state(config, bundle)
    return serve_predict_jobs(
        model, mcfg, variables, monitor,
        tuple(config.serve.warmup_batch_sizes), temperature=temp,
    )


def _warm_serve_group(config, bundle) -> list[CacheJob]:
    if config.serve.batch_window_ms <= 0:
        return []  # grouping disabled: the engine never builds these shapes
    from mlops_tpu.serve.engine import GROUP_ROW_BUCKETS, GROUP_SLOT_BUCKETS

    model, mcfg, variables, monitor, temp = _serve_model_state(config, bundle)
    grid = [(s, r) for r in GROUP_ROW_BUCKETS for s in GROUP_SLOT_BUCKETS]
    return serve_group_jobs(
        model, mcfg, variables, monitor, grid, temperature=temp
    )


def _quant_serve_state(config, bundle):
    """(qparams, monitor, temperature) for the quant serve entries, or
    None when this deployment will never dispatch them: ``serve_tier``
    "exact" (the knob that routes tiers — `serve/engine.py`), or a bundle
    whose quant tier is absent/ungated (`bundle.quant_gates_passed`)."""
    if config.serve.serve_tier == "exact":
        return None
    if bundle is not None:
        if not (bundle.has_quant and bundle.quant_gates_passed):
            return None
        return bundle.quant_params, bundle.monitor, bundle.quant_temperature
    from mlops_tpu.monitor.state import abstract_monitor_state
    from mlops_tpu.ops.quant import abstract_quant_params

    return (
        abstract_quant_params(),
        abstract_monitor_state(config.monitor),
        1.0,
    )


def _warm_serve_quant(config, bundle) -> list[CacheJob]:
    state = _quant_serve_state(config, bundle)
    if state is None:
        return []
    qparams, monitor, temp = state
    return serve_quant_jobs(
        qparams, monitor,
        tuple(config.serve.warmup_batch_sizes), temperature=temp,
    )


def _warm_serve_quant_group(config, bundle) -> list[CacheJob]:
    state = _quant_serve_state(config, bundle)
    if state is None or config.serve.batch_window_ms <= 0:
        return []
    from mlops_tpu.serve.engine import GROUP_ROW_BUCKETS, GROUP_SLOT_BUCKETS

    qparams, monitor, temp = state
    grid = [(s, r) for r in GROUP_ROW_BUCKETS for s in GROUP_SLOT_BUCKETS]
    return serve_quant_group_jobs(
        qparams, monitor, grid, temperature=temp
    )


def _gbm_serve_state(config, bundle):
    """(tree variables, monitor, geometry, temperature) for the gbm-tensor
    serve entries, or None when this deployment never dispatches them.
    Unlike the flax/quant entries there is NO config-only abstract mode:
    the traced program's structure (GbmGeometry) is a fact of the FITTED
    ensemble, so a container build warms these from a bundle or not at
    all — `warm_entry_points` reports the entry as skipped."""
    if bundle is None or bundle.flavor != "sklearn":
        return None
    from mlops_tpu.ops.gbm_tensor import (
        device_put_x64,
        extract_gbm,
        supports_gbm_tensorization,
    )

    if not supports_gbm_tensorization(bundle.estimator):
        return None  # the rf family keeps the host hybrid path
    variables, geometry = extract_gbm(bundle.estimator)
    # Committed under the x64 context so the f64 leaves survive both the
    # aval derivation and the execute-once pass.
    return (
        device_put_x64(variables),
        bundle.monitor,
        geometry,
        bundle.temperature,
    )


def _warm_serve_gbm(config, bundle) -> list[CacheJob]:
    state = _gbm_serve_state(config, bundle)
    if state is None:
        return []
    variables, monitor, geometry, temp = state
    return serve_gbm_jobs(
        variables, monitor,
        tuple(config.serve.warmup_batch_sizes),
        geometry=geometry, temperature=temp,
    )


def _warm_serve_gbm_group(config, bundle) -> list[CacheJob]:
    state = _gbm_serve_state(config, bundle)
    if state is None or config.serve.batch_window_ms <= 0:
        return []
    from mlops_tpu.serve.engine import GROUP_ROW_BUCKETS, GROUP_SLOT_BUCKETS

    variables, monitor, geometry, temp = state
    grid = [(s, r) for r in GROUP_ROW_BUCKETS for s in GROUP_SLOT_BUCKETS]
    return serve_gbm_group_jobs(
        variables, monitor, grid, geometry=geometry, temperature=temp
    )


def _warm_bulk(config, bundle) -> list[CacheJob]:
    import jax

    from mlops_tpu.monitor.state import abstract_monitor_state
    from mlops_tpu.parallel import make_mesh
    from mlops_tpu.parallel.bulk import mesh_chunk_rows, use_distilled_bulk

    mesh = make_mesh(jax.device_count()) if jax.device_count() > 1 else None
    # The SAME rounding rule the scoring paths apply — a divergence here
    # is a guaranteed cache-key miss at run time.
    chunk = mesh_chunk_rows(config.score.chunk_rows, mesh)
    jobs = []
    if bundle is not None:
        monitor = bundle.monitor
        variants = [("exact", bundle.model, bundle.model_config, bundle.variables)]
        if use_distilled_bulk(bundle):
            variants.append(
                ("distilled", bundle.bulk_model,
                 bundle.model_config, bundle.bulk_variables)
            )
    else:
        from mlops_tpu.models import abstract_variables, build_model

        model = build_model(config.model)
        monitor = abstract_monitor_state(config.monitor)
        variants = [("exact", model, config.model, abstract_variables(model))]
    for path_label, model, mcfg, variables in variants:
        jobs.append(
            bulk_chunk_job(
                model, mcfg, variables, monitor, chunk, mesh,
                path_label=path_label,
            )
        )
    if (
        bundle is not None
        and bundle.flavor != "sklearn"
        and bundle.has_quant
        and bundle.quant_gates_passed
    ):
        # Gate-passed quant tree present: warm its chunk program too, so a
        # `score --tier quant` sweep deserializes instead of compiling.
        jobs.append(
            bulk_quant_chunk_job(bundle.quant_params, monitor, chunk, mesh)
        )
    return jobs


def _abstract_train_state(config, model, optimizer):
    """Abstract TrainState matching what ``fit`` will build — including the
    EMA accumulator when ``train.ema_decay`` is on (its presence changes
    the pytree structure and therefore the key)."""
    import jax
    import jax.numpy as jnp

    from mlops_tpu.models import abstract_variables
    from mlops_tpu.train.loop import TrainState

    variables = abstract_variables(model)
    params = variables["params"]
    S = jax.ShapeDtypeStruct
    return TrainState(
        params=params,
        opt_state=jax.eval_shape(optimizer.init, params),
        step=S((), jnp.int32),
        rng=S((2,), jnp.uint32),
        ema=params if config.train.ema_decay else None,
    )


def _warm_train_dense(config, bundle) -> list[CacheJob]:
    import jax
    import jax.numpy as jnp

    from mlops_tpu.models import build_model
    from mlops_tpu.train.loop import make_optimizer

    if config.model.family in ("gbm", "rf"):
        return []  # sklearn families have no jitted train step
    model = build_model(config.model)
    optimizer = make_optimizer(config.train)
    state = _abstract_train_state(config, model, optimizer)
    # The scan consumes the TRAIN SPLIT arrays — mirror split_dataset's
    # arithmetic so a later `train` run with this config is an exact hit.
    n = config.data.rows
    n_train = n - int(n * config.data.valid_fraction)
    cat, num, _ = _schema_avals((n_train,))
    lab = jax.ShapeDtypeStruct((n_train,), jnp.float32)
    base = max(1, min(config.train.eval_every, config.train.steps))
    windows = {base}
    if config.train.steps % base:
        windows.add(config.train.steps % base)  # the shrunk final window
    return [
        train_window_job(model, optimizer, config.train, w, state, cat, num, lab)
        for w in sorted(windows)
    ]


def _warm_train_tp(config, bundle) -> list[CacheJob]:
    import dataclasses

    import jax

    if jax.device_count() < 2:
        return []  # reported as skipped by warm_entry_points
    if config.model.family in ("gbm", "rf"):
        return []
    from mlops_tpu.models import build_model
    from mlops_tpu.parallel import make_mesh
    from mlops_tpu.parallel.steps import make_sharded_train_step
    from mlops_tpu.train.loop import make_optimizer

    k = config.model.tensor_parallel
    mesh = make_mesh(jax.device_count(), model_parallel=k) if k >= 2 else (
        make_mesh(jax.device_count())
    )
    # TP is a layout, not a different network (train/tensor_parallel.py):
    # the step compiles against the PLAIN dense family.
    model = build_model(dataclasses.replace(config.model, tensor_parallel=0))
    optimizer = make_optimizer(config.train)
    state = _abstract_train_state(config, model, optimizer)
    step_fn, _ = make_sharded_train_step(
        model, optimizer, config.train, mesh, state.params
    )
    return [
        tp_step_job(
            model, optimizer, config.train, mesh, state,
            config.train.batch_size, step_fn,
        )
    ]


_WARMERS: dict[str, Callable] = {
    "serve-predict-packed": _warm_serve_predict,
    "serve-predict-group-packed": _warm_serve_group,
    "serve-predict-quant-packed": _warm_serve_quant,
    "serve-predict-quant-group-packed": _warm_serve_quant_group,
    "serve-predict-gbm-packed": _warm_serve_gbm,
    "serve-predict-gbm-group-packed": _warm_serve_gbm_group,
    "bulk-score-chunk": _warm_bulk,
    "train-step-dense": _warm_train_dense,
    "train-step-tp": _warm_train_tp,
}


def warm_entry_points(config, cache: CompileCache, bundle=None) -> dict:
    """Pre-populate ``cache`` with every registered entry point's hot
    programs (the `mlops-tpu warmup` CLI body). The enumeration IS the
    tpulint Layer-2 registry; an entry point registered there without a
    warmer here is a hard error, not a silent gap."""
    from mlops_tpu.analysis.entrypoints import registered_entry_points

    if set(_WARMERS) != set(CACHE_ENTRY_IDS):  # survives python -O
        raise RuntimeError(
            "compilecache warmers out of sync with registry.CACHE_ENTRY_IDS: "
            f"{sorted(set(_WARMERS) ^ set(CACHE_ENTRY_IDS))}"
        )
    t0 = time.perf_counter()
    jobs: list[CacheJob] = []
    entries: dict[str, dict] = {}
    for entry in registered_entry_points():
        warmer = _WARMERS.get(entry.name)
        if warmer is None:
            raise RuntimeError(
                f"entry point {entry.name!r} has no compile-cache warmer — "
                "register one in mlops_tpu/compilecache/warmup.py and add it "
                "to registry.CACHE_ENTRY_IDS"
            )
        entry_jobs = warmer(config, bundle)
        entries[entry.name] = {"programs": len(entry_jobs)}
        if not entry_jobs:
            entries[entry.name]["skipped"] = True
        jobs.extend(entry_jobs)
    run_jobs(jobs, cache=cache, workers=config.cache.warmup_workers)
    return {
        "cache_dir": str(cache.directory),
        "mode": "bundle" if bundle is not None else "config",
        "entries": entries,
        "programs": len(jobs),
        "warmup_s": round(time.perf_counter() - t0, 3),
        "cache": cache.stats(),
    }
