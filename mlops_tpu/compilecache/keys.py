"""Versioned cache keys for AOT-compiled executables.

A serialized executable is only reusable in a process whose compiler stack,
backend, and program are EXACTLY the ones that produced it. The key bakes in
every axis that can change the binary:

- format version (this module's serialization layout),
- jax + jaxlib versions (XLA codegen changes between releases),
- backend platform, device kind, device count, and the x64 flag,
- mesh shape (sharded programs embed a device assignment),
- donation flags (donated and undonated lowerings differ),
- the entry-point id and the full abstract call signature
  (pytree structure + per-leaf shape/dtype),
- a config hash covering everything the program closes over that the
  signature cannot see (model family/architecture knobs, optimizer
  schedule constants, ...).

Any mismatch is a MISS, never a wrong artifact — stale executables cannot
be served because a changed component changes the key.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
from typing import Any

CACHE_FORMAT_VERSION = 1


def environment_fingerprint() -> dict[str, Any]:
    """The compiler-stack/backend components of every cache key, read at
    call time (tests monkeypatch this module attribute to simulate version
    bumps)."""
    import jax
    import jaxlib

    device = jax.devices()[0]
    return {
        "format": CACHE_FORMAT_VERSION,
        "jax": jax.__version__,
        "jaxlib": jaxlib.__version__,
        "backend": jax.default_backend(),
        "device_kind": getattr(device, "device_kind", "unknown"),
        "device_count": jax.device_count(),
        "x64": bool(jax.config.jax_enable_x64),
    }


def tree_avals(tree: Any) -> Any:
    """Concrete pytree -> matching ShapeDtypeStruct pytree (identity for
    leaves that already are abstract)."""
    import jax

    def aval(leaf):
        if isinstance(leaf, jax.ShapeDtypeStruct):
            return leaf
        return jax.ShapeDtypeStruct(leaf.shape, leaf.dtype)

    return jax.tree_util.tree_map(aval, tree)


def abstract_signature(args: Any) -> str:
    """Canonical string for a call signature: the flattened pytree
    structure plus every leaf's dtype and shape."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(tree_avals(args))
    shapes = ",".join(f"{leaf.dtype}{list(leaf.shape)}" for leaf in leaves)
    return f"{treedef}|{shapes}"


def fingerprint(*parts: Any) -> str:
    """Short stable hash of arbitrary JSON-serializable parts (dataclasses
    are converted; everything else falls back to ``str``)."""

    def norm(part: Any) -> Any:
        if dataclasses.is_dataclass(part) and not isinstance(part, type):
            return dataclasses.asdict(part)
        if isinstance(part, (dict, list, tuple, str, int, float, bool)) or part is None:
            return part
        return str(part)

    blob = json.dumps([norm(p) for p in parts], sort_keys=True, default=str)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def model_fingerprint(model_config: Any) -> str:
    """Hash of everything a predict program closes over that its abstract
    signature cannot see: the model architecture. Params, monitor state,
    and the calibration temperature are ARGUMENTS of the cached programs,
    so their shapes live in the signature and their values never touch the
    executable."""
    return fingerprint("model", model_config)


def train_fingerprint(model: Any, train_config: Any, tag: Any) -> str:
    """Hash for train-step programs: the built model's structure (its flax
    repr names every submodule and hyperparameter), the TrainConfig (the
    optimizer schedule constants are baked into the step), and a tag
    distinguishing program variants (window length, 'tp', ...)."""
    return fingerprint("train", str(model), train_config, tag)


def cache_key(
    entry_id: str,
    abstract_args: Any,
    config_hash: str = "",
    mesh_shape: tuple[int, ...] | None = None,
    donated: bool = False,
    env: dict[str, Any] | None = None,
) -> tuple[dict[str, Any], str]:
    """Assemble the key components and their sha256 digest (the cache file
    name). ``env`` overrides the live environment fingerprint (tests)."""
    signature = abstract_signature(abstract_args)
    components = {
        **(environment_fingerprint() if env is None else env),
        "entry": entry_id,
        "mesh": list(mesh_shape) if mesh_shape is not None else None,
        "donated": bool(donated),
        "config": config_hash,
        "signature_sha": hashlib.sha256(signature.encode()).hexdigest(),
    }
    digest = hashlib.sha256(
        json.dumps(components, sort_keys=True).encode()
    ).hexdigest()
    # The full signature is kept alongside (truncated) for debuggability,
    # but hashed above so arbitrarily large param trees stay keyable.
    components["signature"] = signature[:2000]
    return components, digest
