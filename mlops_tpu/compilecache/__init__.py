"""Persistent AOT compile cache: kill cold-start by making compiled
executables first-class artifacts (probe -> deserialize hits, compile
misses in parallel -> persist). See `cache.py` for mechanics, `warmup.py`
for the per-entry-point job builders, `keys.py` for the versioned key, and
`registry.py` for the JAX-free surface tpulint's TPU203 rule reads."""

from mlops_tpu.compilecache.cache import (
    CacheJob,
    CompileCache,
    donation_deserialize_safe,
    from_config,
    serialization_available,
)

__all__ = [
    "CacheJob",
    "CompileCache",
    "donation_deserialize_safe",
    "from_config",
    "serialization_available",
]
