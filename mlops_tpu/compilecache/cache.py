"""Persistent AOT executable cache.

The readiness gate of every process — serve warmup, train windows, bulk
sweeps — pays XLA compilation from scratch today (~54 s measured for the
serving engine's bucket/group grid on the bench box). Compile time is pure
goodput loss (*ML Productivity Goodput*, arxiv 2502.06982), and prediction
serving is exactly the workload where ahead-of-time compiled artifacts pay
off (*A Tensor Compiler for Unified ML Prediction Serving*, arxiv
2010.04804). This module makes the compiled program a first-class,
persistent, integrity-checked artifact:

    lowered  = jitted.lower(*abstract_args)      # trace, no devices touched
    compiled = lowered.compile()                 # XLA compile (releases GIL)
    payload  = serialize_executable.serialize(compiled)   # bytes on disk

keyed by `keys.cache_key` (jax/jaxlib versions, backend + device kind, mesh
shape, donation flags, entry id, abstract signature, config hash). Reads
verify a sha256 checksum and discard-and-recompile on ANY failure; writes
are atomic tmp+rename (the same discipline as `data/stream.py` outputs), so
a crashed process can never leave a half-written artifact that a later one
trusts.

Capability gates, formalized here instead of scattered at call sites:

- ``serialization_available()`` — jaxlibs without
  ``jax.experimental.serialize_executable`` fall back to configuring JAX's
  own persistent compilation cache dir under ``<dir>/xla`` (slower than
  executable deserialization, still skips XLA re-optimization).
- ``donation_deserialize_safe()`` — on the jaxlib 0.4.x CPU backend a
  DONATED executable deserialized from cache segfaults (TP pjit step) or
  silently corrupts results (dense scan window) — reproduced fresh-vs-warm
  both ways (see parallel/compat.py donation_argnums, PR 1). Donated
  programs on that backend bypass the cache entirely (no read, no write)
  and the bypass is counted with its reason in ``stats()``.

Artifacts are trusted local state (same trust level as JAX's own persistent
compilation cache): the checksum guards corruption and truncation, not
adversarial payloads — do not point ``cache.dir`` at an untrusted store.
"""

from __future__ import annotations

import dataclasses
import os
import pickle
import threading
import time
from hashlib import sha256
from pathlib import Path
from typing import Any, Callable

from mlops_tpu import faults
from mlops_tpu.compilecache import keys
from mlops_tpu.utils.timing import StageClock

_HEADER_MAGIC = "mlops-tpu-exe"

# tpulint Layer-3 manifest: one stats mutex, declared so the analyzer (and
# the runtime sanitizer) flag any future nesting under it. Compiles,
# deserializes, and disk I/O all happen OUTSIDE `_lock` by design — it
# guards only the counters/program-stats dicts (see _record/stats).
TPULINT_LOCK_ORDER = {"CompileCache": ("_lock",)}


def _serialize_module():
    try:
        from jax.experimental import serialize_executable

        return serialize_executable
    # Capability probe, not error handling: any import failure (renamed
    # module on a future jax, missing pjrt support) means "use the
    # jax-persistent-cache fallback". pragma: depends on installed jaxlib.
    except Exception:  # tpulint: disable=TPU201
        return None


def serialization_available() -> bool:
    """True when this jaxlib can serialize/deserialize compiled
    executables (`jax.experimental.serialize_executable`)."""
    return _serialize_module() is not None


def donation_deserialize_safe() -> bool:
    """False on the jaxlib 0.4.x CPU backend, where executing a donated
    executable deserialized from cache segfaults or silently corrupts
    results (the PR 1 reproduction this gate formalizes)."""
    import jax
    import jaxlib

    legacy = jaxlib.__version__.startswith("0.4.")
    return not (legacy and jax.default_backend() == "cpu")


@dataclasses.dataclass
class CacheJob:
    """One program to warm: a jitted callable plus the abstract call
    signature to lower it at, and the key components the signature cannot
    express. ``execute_args`` (concrete) optionally runs the program once
    after load — the engine uses it to pay first-dispatch allocation at
    warmup and to fail loudly on an executable that loads but cannot run."""

    entry_id: str
    jitted: Callable
    abstract_args: tuple
    config_hash: str = ""
    mesh_shape: tuple[int, ...] | None = None
    donated: bool = False
    label: str = ""
    meta: dict = dataclasses.field(default_factory=dict)
    execute_args: tuple | None = None


class CompileCache:
    """Directory-backed executable cache; thread-safe (warmup pools call
    ``load_or_compile`` concurrently — XLA compilation releases the GIL, so
    misses genuinely overlap)."""

    def __init__(self, directory: str | Path):
        self.directory = Path(directory)
        self.directory.mkdir(parents=True, exist_ok=True)
        self._se = _serialize_module()
        self.mode = "serialize" if self._se is not None else "jax-persistent-cache"
        if self._se is None:  # pragma: no cover - depends on installed jaxlib
            self._enable_xla_fallback()
        self._lock = threading.Lock()
        self._clock = StageClock()
        self._counts = {
            "hits": 0,
            "misses": 0,
            "bypasses": 0,
            "discards": 0,
            "unserializable": 0,
        }
        self._bypass_reasons: dict[str, int] = {}
        self._programs: dict[str, dict[str, Any]] = {}

    # ----------------------------------------------------------- fallback
    def _enable_xla_fallback(self) -> None:
        """No executable serialization on this jaxlib: route XLA's own
        persistent compilation cache at ``<dir>/xla`` so recompiles still
        skip optimization. Never clobbers a cache dir the process already
        configured (tests/CI point JAX at their own)."""
        import jax

        if getattr(jax.config, "jax_compilation_cache_dir", None):
            return
        xla_dir = self.directory / "xla"
        xla_dir.mkdir(parents=True, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", str(xla_dir))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)

    # -------------------------------------------------------------- paths
    def _artifact_path(self, entry_id: str, digest: str) -> Path:
        return self.directory / entry_id / f"{digest}.jaxexe"

    # ------------------------------------------------------------ loading
    def load_or_compile(self, job: CacheJob) -> Callable:
        """Probe -> deserialize hit / compile miss (persisting the result).

        Returns a callable executable for EXACTLY ``job.abstract_args``'s
        shapes/dtypes; it raises on mismatched inputs rather than
        recompiling (callers keep their jitted fallback for novel shapes).
        """
        label = job.label or job.entry_id
        components, digest = keys.cache_key(
            job.entry_id,
            job.abstract_args,
            config_hash=job.config_hash,
            mesh_shape=job.mesh_shape,
            donated=job.donated,
        )
        if job.donated and not donation_deserialize_safe():
            # The formalized jaxlib-0.4.x-CPU hazard: never deserialize —
            # and never persist, so no artifact exists for this backend to
            # read back by accident.
            fn, seconds = self._compile(job)
            self._record(
                label, digest, "bypass-compiled", seconds,
                bypass_reason="donated-deserialize-unsafe",
            )
            return self._maybe_execute(job, fn)

        path = self._artifact_path(job.entry_id, digest)
        if self._se is not None and path.is_file():
            fn, seconds = self._try_deserialize(path)
            if fn is not None:
                self._record(label, digest, "deserialized", seconds)
                return self._maybe_execute(job, fn)
            # Corrupt/truncated/incompatible artifact: already unlinked by
            # _try_deserialize; fall through to a fresh compile.

        fn, seconds = self._compile(job)
        if self._se is not None:
            self._persist(path, components, fn)
        self._record(label, digest, "compiled", seconds)
        return self._maybe_execute(job, fn)

    def _maybe_execute(self, job: CacheJob, fn: Callable) -> Callable:
        if job.execute_args is not None:
            import jax

            jax.block_until_ready(fn(*job.execute_args))
        return fn

    def _compile(self, job: CacheJob) -> tuple[Callable, float]:
        start = time.perf_counter()
        with self._clock.stage("compile"):
            compiled = job.jitted.lower(*job.abstract_args).compile()
        return compiled, time.perf_counter() - start

    def _try_deserialize(self, path: Path) -> tuple[Callable | None, float]:
        """Checksum-verified read; ANY failure discards the artifact and
        reports None (the caller recompiles) — corruption can cost a
        compile, never a crash and never a stale/garbled program."""
        start = time.perf_counter()
        try:
            with self._clock.stage("deserialize"):
                raw = path.read_bytes()
                # Injection point (mlops_tpu/faults): corrupt-on-read —
                # seeded bit flips here must land in the discard+recompile
                # path below, never in a served program.
                raw = faults.corrupt("compilecache.read", raw)
                header_line, _, blob = raw.partition(b"\n")
                import json

                header = json.loads(header_line)
                if header.get("magic") != _HEADER_MAGIC:
                    raise ValueError("bad artifact magic")
                if header.get("format") != keys.CACHE_FORMAT_VERSION:
                    raise ValueError("artifact format version mismatch")
                if len(blob) != header.get("payload_bytes"):
                    raise ValueError("artifact truncated")
                if sha256(blob).hexdigest() != header.get("sha256"):
                    raise ValueError("artifact checksum mismatch")
                payload, in_tree, out_tree = pickle.loads(blob)
                fn = self._se.deserialize_and_load(payload, in_tree, out_tree)
            return fn, time.perf_counter() - start
        # The breadth is the contract: unreadable pickle, jaxlib refusing
        # the executable, header rot — all become a counted discard plus a
        # recompile, never an exception on the warmup path.
        except Exception:  # tpulint: disable=TPU201
            path.unlink(missing_ok=True)
            with self._lock:
                self._counts["discards"] += 1
            return None, time.perf_counter() - start

    def _persist(self, path: Path, components: dict, compiled: Any) -> None:
        """Atomic tmp+rename write (stream.py discipline): concurrent
        writers race benignly (same key -> same bytes; os.replace is
        atomic), and a crash never leaves a partial artifact in place.

        The payload is VALIDATED (one in-process deserialize) before it
        touches disk: on jaxlib 0.4.x CPU, an executable whose compile was
        served from JAX's persistent compilation cache on disk serializes
        into an artifact that fails at load with "Symbols not found"
        (reproduced cross-process) — such programs are counted
        ``unserializable`` and never persisted, so the artifact store only
        ever holds executables proven to round-trip."""
        try:
            serialized = self._se.serialize(compiled)
            self._se.deserialize_and_load(*serialized)
        # Some backends compile programs their PjRt runtime cannot
        # serialize or round-trip (the jaxlib 0.4.x case above; exotic
        # plugin backends); serving must not die for a cache write.
        except Exception:  # tpulint: disable=TPU201
            with self._lock:
                self._counts["unserializable"] += 1
            return
        import json

        blob = pickle.dumps(serialized)
        header = {
            "magic": _HEADER_MAGIC,
            "format": keys.CACHE_FORMAT_VERSION,
            "sha256": sha256(blob).hexdigest(),
            "payload_bytes": len(blob),
            "key": components,
        }
        path.parent.mkdir(parents=True, exist_ok=True)
        tmp = path.with_name(
            f".{path.name}.tmp-{os.getpid()}-{threading.get_ident()}"
        )
        try:
            tmp.write_bytes(json.dumps(header).encode() + b"\n" + blob)
            # Injection point (mlops_tpu/faults): a kill here — after the
            # tmp write, before the atomic rename — is the torn-persist
            # proof: the artifact path must either not exist or hold a
            # fully verified prior artifact (chaos smoke asserts it).
            faults.fire("compilecache.persist.midwrite")
            os.replace(tmp, path)
        except OSError:
            tmp.unlink(missing_ok=True)

    # -------------------------------------------------------------- stats
    def _record(
        self,
        label: str,
        digest: str,
        source: str,
        seconds: float,
        bypass_reason: str | None = None,
    ) -> None:
        with self._lock:
            if source == "deserialized":
                self._counts["hits"] += 1
            elif source == "compiled":
                self._counts["misses"] += 1
            else:
                self._counts["bypasses"] += 1
                self._bypass_reasons[bypass_reason] = (
                    self._bypass_reasons.get(bypass_reason, 0) + 1
                )
            self._programs[label] = {
                "source": source,
                "seconds": round(seconds, 4),
                "key": digest[:12],
            }

    def stats(self) -> dict[str, Any]:
        """Hit/miss/bypass counts plus per-program compile vs deserialize
        wall time (`utils/timing.py StageClock` accumulates the busy
        seconds per stage)."""
        with self._lock:
            clock = {
                name: timing["busy_s"]
                for name, timing in self._clock.report(1.0).items()
            }
            return {
                "mode": self.mode,
                "dir": str(self.directory),
                **dict(self._counts),
                "bypass_reasons": dict(self._bypass_reasons),
                "compile_s": round(clock.get("compile", 0.0), 4),
                "deserialize_s": round(clock.get("deserialize", 0.0), 4),
                "programs": {k: dict(v) for k, v in self._programs.items()},
            }


def from_config(config: Any) -> CompileCache | None:
    """The one construction rule every subsystem shares: ``cache.dir``
    set -> a CompileCache there; empty (the default) -> caching off."""
    directory = getattr(getattr(config, "cache", None), "dir", "")
    return CompileCache(directory) if directory else None
