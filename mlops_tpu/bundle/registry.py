"""Filesystem model registry with staged promotion.

Replaces the MLflow model registry (`02-register-model.ipynb:461-470`
``mlflow.register_model`` with tags; addressed as
``models:/<name>/<version>``, `:503-504`) and the reference's
dev -> staging -> production environment model
(`.github/docs/getting-started.md:57-69`). Works on a local directory (or a
mounted GCS bucket) — no tracking server.

Layout:

    <root>/<name>/versions/<v>/   the bundle directory
    <root>/<name>/index.json      versions, stages, tags (atomic rewrite)
"""

from __future__ import annotations

import datetime
import json
import shutil
import uuid
from pathlib import Path
from typing import Any

from mlops_tpu.utils.io import atomic_write

STAGES = ("none", "staging", "production")


def parse_model_uri(uri: str) -> tuple[str, str]:
    """Parse ``models:/<name>/<version-or-stage>`` (reference URI contract)."""
    if not uri.startswith("models:/"):
        raise ValueError(f"not a model uri: {uri!r}")
    name, _, version = uri[len("models:/") :].partition("/")
    if not name or not version:
        raise ValueError(f"malformed model uri: {uri!r}")
    return name, version


class ModelRegistry:
    def __init__(self, root: str | Path):
        self.root = Path(root)

    # ---------------------------------------------------------------- index
    def _index_path(self, name: str) -> Path:
        return self.root / name / "index.json"

    def _read_index(self, name: str) -> dict[str, Any]:
        path = self._index_path(name)
        if not path.exists():
            return {"name": name, "versions": []}
        return json.loads(path.read_text())

    def _write_index(self, name: str, index: dict[str, Any]) -> None:
        path = self._index_path(name)
        path.parent.mkdir(parents=True, exist_ok=True)
        atomic_write(path, json.dumps(index, indent=2).encode())

    # ------------------------------------------------------------------ api
    def register(
        self,
        name: str,
        bundle_dir: str | Path,
        tags: dict[str, str] | None = None,
    ) -> str:
        """Copy a bundle into the registry as the next version.

        Returns a ``models:/<name>/<version>`` URI — the same contract the
        reference's registration notebook exits with
        (`02-register-model.ipynb:504`).
        """
        index = self._read_index(name)
        versions_dir = self.root / name / "versions"
        # Next version = 1 + max over index AND on-disk dirs, so an orphan
        # directory from a crash between copy and index write can never
        # collide with a later registration.
        on_disk = (
            int(p.name)
            for p in versions_dir.glob("[0-9]*")
            if p.is_dir() and p.name.isdigit()
        )
        version = 1 + max(
            [0, *(v["version"] for v in index["versions"]), *on_disk]
        )
        dest = versions_dir / str(version)
        # Copy to a temp sibling then rename: a partial copy is never visible
        # under a version number. Single-writer assumption: concurrent
        # registers of the same name are not coordinated (CI serializes the
        # release pipeline, as the reference's workflow jobs do via `needs:`).
        staging = versions_dir / f".incoming-{uuid.uuid4().hex}"
        try:
            shutil.copytree(bundle_dir, staging)
            staging.replace(dest)
        except BaseException:
            shutil.rmtree(staging, ignore_errors=True)
            raise
        index["versions"].append(
            {
                "version": version,
                "created_at": datetime.datetime.now(
                    datetime.timezone.utc
                ).isoformat(),
                "stage": "none",
                "tags": tags or {},
            }
        )
        self._write_index(name, index)
        return f"models:/{name}/{version}"

    def resolve(self, name: str, version_or_stage: str) -> Path:
        """Resolve a version number, stage name, or 'latest' to a bundle dir."""
        index = self._read_index(name)
        if not index["versions"]:
            raise KeyError(f"no versions registered for model {name!r}")
        if version_or_stage == "latest":
            version = max(v["version"] for v in index["versions"])
        elif version_or_stage.isdigit():
            version = int(version_or_stage)
            if not any(v["version"] == version for v in index["versions"]):
                raise KeyError(f"model {name!r} has no version {version}")
        elif version_or_stage in STAGES:
            staged = [
                v for v in index["versions"] if v["stage"] == version_or_stage
            ]
            if not staged:
                raise KeyError(
                    f"model {name!r} has no version in stage {version_or_stage!r}"
                )
            version = max(v["version"] for v in staged)
        else:
            raise KeyError(f"unknown version or stage {version_or_stage!r}")
        return self.root / name / "versions" / str(version)

    def resolve_uri(self, uri: str) -> Path:
        return self.resolve(*parse_model_uri(uri))

    def set_stage(self, name: str, version: int, stage: str) -> None:
        """Promote/demote a version (staging -> production gate, SURVEY.md
        SS3.4). Single-holder semantics: promoting a version to a stage
        archives (stage='none') whichever version held it before.
        """
        if stage not in STAGES:
            raise ValueError(f"stage must be one of {STAGES}")
        index = self._read_index(name)
        target = next(
            (e for e in index["versions"] if e["version"] == version), None
        )
        if target is None:
            raise KeyError(f"model {name!r} has no version {version}")
        if stage != "none":
            for entry in index["versions"]:
                if entry is not target and entry["stage"] == stage:
                    entry["stage"] = "none"
        target["stage"] = stage
        target[f"{stage}_since"] = datetime.datetime.now(
            datetime.timezone.utc
        ).isoformat()
        self._write_index(name, index)

    def list_versions(self, name: str) -> list[dict[str, Any]]:
        return self._read_index(name)["versions"]
