"""Filesystem model registry with staged promotion.

Replaces the MLflow model registry (`02-register-model.ipynb:461-470`
``mlflow.register_model`` with tags; addressed as
``models:/<name>/<version>``, `:503-504`) and the reference's
dev -> staging -> production environment model
(`.github/docs/getting-started.md:57-69`). Works on a local directory (or a
mounted GCS bucket) — no tracking server.

Layout:

    <root>/<name>/versions/<v>/   the bundle directory
    <root>/<name>/index.json      versions, stages, tags (atomic rewrite)
"""

from __future__ import annotations

import contextlib
import datetime
import fcntl
import hashlib
import json
import shutil
import threading
import uuid
from pathlib import Path
from typing import Any

from mlops_tpu.utils import storage
from mlops_tpu.utils.io import atomic_write

STAGES = ("none", "staging", "production")

# Intra-process serialization of index mutations, keyed by resolved
# (root, name); the cross-process half is an flock alongside the index.
_LOCKS_GUARD = threading.Lock()
_LOCKS: dict[str, threading.Lock] = {}


def parse_model_uri(uri: str) -> tuple[str, str]:
    """Parse ``models:/<name>/<version-or-stage>`` (reference URI contract)."""
    if not uri.startswith("models:/"):
        raise ValueError(f"not a model uri: {uri!r}")
    name, _, version = uri[len("models:/") :].partition("/")
    if not name or not version:
        raise ValueError(f"malformed model uri: {uri!r}")
    return name, version


class ModelRegistry:
    """Registry over a local directory OR a ``gs://bucket/prefix`` root.

    The GCS flavor is the analogue of the reference registering models in
    a workspace-scoped MLflow registry reachable from every estate
    component (`02-register-model.ipynb:461-470`): CI trains on one
    machine, the serving image build and the GKE training Job resolve the
    same ``models:/`` URI from the bucket. Bundle versions are immutable,
    so GCS resolves download into a content-stable local cache.
    """

    def __init__(
        self,
        root: str | Path,
        client: "storage.GCSClient | None" = None,
        cache_dir: str | Path | None = None,
    ):
        self._gcs = storage.is_gcs(root)
        self.root = str(root).rstrip("/") if self._gcs else Path(root)
        self._client = client
        # Per-user cache, created 0700 in resolve(): a world-writable
        # shared temp dir would let another local user pre-plant a
        # "cached" bundle that resolve() trusts as immutable. Namespaced
        # by a hash of the registry root so two registries (staging vs
        # production buckets) can never serve each other's versions.
        root_tag = hashlib.sha256(str(self.root).encode()).hexdigest()[:16]
        self._cache_dir = (
            Path(
                cache_dir
                or Path.home() / ".cache" / "mlops_tpu" / "registry"
            )
            / root_tag
        )

    # -------------------------------------------------------------- locking
    @contextlib.contextmanager
    def _locked(self, name: str):
        """Serialize index mutations per model: a process-local lock for
        threads plus an ``flock`` for concurrent processes (flock alone
        cannot arbitrate threads sharing one process). Lifts the local
        backend past the reference's implicit CI-serializes-releases
        assumption; the GCS flavor keeps the documented single-writer
        contract (object stores have no flock — CI's ``needs:`` chain is
        the serializer there, as in the reference's workflows)."""
        if self._gcs:
            yield
            return
        key = str(Path(self.root).resolve() / name)
        with _LOCKS_GUARD:
            thread_lock = _LOCKS.setdefault(key, threading.Lock())
        with thread_lock:
            # Locks live under <root>/.locks, NOT <root>/<name>/ — taking
            # the lock for a typo'd name must not create a phantom model
            # directory a registry listing would then surface.
            lock_dir = self.root / ".locks"
            lock_dir.mkdir(parents=True, exist_ok=True)
            with open(lock_dir / f"{name}.lock", "w") as lock_file:
                fcntl.flock(lock_file, fcntl.LOCK_EX)
                try:
                    yield
                finally:
                    fcntl.flock(lock_file, fcntl.LOCK_UN)

    # ---------------------------------------------------------------- index
    def _index_path(self, name: str) -> str | Path:
        return storage.join(self.root, name, "index.json")

    def _read_index(self, name: str) -> dict[str, Any]:
        try:
            return json.loads(
                storage.read_bytes(self._index_path(name), self._client)
            )
        except FileNotFoundError:
            return {"name": name, "versions": []}

    def _write_index(self, name: str, index: dict[str, Any]) -> None:
        storage.write_bytes(
            self._index_path(name),
            json.dumps(index, indent=2).encode(),
            self._client,
        )

    def _stored_versions(self, name: str) -> list[int]:
        """Version numbers physically present under versions/ (orphan scan)."""
        if self._gcs:
            prefix = f"{self.root}/{name}/versions/"
            found = set()
            # A listing failure must FAIL the register: numbering from the
            # index alone could collide with a crashed upload's orphan and
            # merge two bundles under one version (the orphan scan is the
            # collision protection). delimiter listing returns one child
            # prefix per version instead of every bundle file's key.
            client = self._client or storage.gcs_client()
            for child in client.list_prefixes(prefix):
                head = child.rstrip("/").rsplit("/", 1)[-1]
                if head.isdigit():
                    found.add(int(head))
            return sorted(found)
        versions_dir = self.root / name / "versions"
        return sorted(
            int(p.name)
            for p in versions_dir.glob("[0-9]*")
            if p.is_dir() and p.name.isdigit()
        )

    # ------------------------------------------------------------------ api
    def register(
        self,
        name: str,
        bundle_dir: str | Path,
        tags: dict[str, str] | None = None,
    ) -> str:
        """Copy a bundle into the registry as the next version.

        Returns a ``models:/<name>/<version>`` URI — the same contract the
        reference's registration notebook exits with
        (`02-register-model.ipynb:504`).
        """
        with self._locked(name):
            return self._register_locked(name, bundle_dir, tags)

    def _register_locked(
        self,
        name: str,
        bundle_dir: str | Path,
        tags: dict[str, str] | None,
    ) -> str:
        index = self._read_index(name)
        # Next version = 1 + max over index AND already-stored dirs, so an
        # orphan from a crash between copy and index write can never
        # collide with a later registration.
        version = 1 + max(
            [
                0,
                *(v["version"] for v in index["versions"]),
                *self._stored_versions(name),
            ]
        )
        if self._gcs:
            # Objects upload under the final prefix directly: GCS has no
            # rename, but the version only becomes resolvable once the
            # index write lands (single-writer assumption below), and a
            # crashed partial upload is shadowed by the orphan scan above.
            storage.upload_dir(
                bundle_dir,
                f"{self.root}/{name}/versions/{version}",
                self._client,
            )
        else:
            versions_dir = self.root / name / "versions"
            dest = versions_dir / str(version)
            # Copy to a temp sibling then rename: a partial copy is never
            # visible under a version number. Concurrent LOCAL registers
            # are serialized by _locked (thread lock + flock); only the
            # GCS flavor still assumes CI serializes the release pipeline.
            versions_dir.mkdir(parents=True, exist_ok=True)
            staging = versions_dir / f".incoming-{uuid.uuid4().hex}"
            try:
                shutil.copytree(bundle_dir, staging)
                staging.replace(dest)
            except BaseException:
                shutil.rmtree(staging, ignore_errors=True)
                raise
        index["versions"].append(
            {
                "version": version,
                "created_at": datetime.datetime.now(
                    datetime.timezone.utc
                ).isoformat(),
                "stage": "none",
                "tags": tags or {},
            }
        )
        self._write_index(name, index)
        return f"models:/{name}/{version}"

    def resolve(self, name: str, version_or_stage: str) -> Path:
        """Resolve a version number, stage name, or 'latest' to a bundle dir."""
        index = self._read_index(name)
        if not index["versions"]:
            raise KeyError(f"no versions registered for model {name!r}")
        if version_or_stage == "latest":
            version = max(v["version"] for v in index["versions"])
        elif version_or_stage.isdigit():
            version = int(version_or_stage)
            if not any(v["version"] == version for v in index["versions"]):
                raise KeyError(f"model {name!r} has no version {version}")
        elif version_or_stage in STAGES:
            staged = [
                v for v in index["versions"] if v["stage"] == version_or_stage
            ]
            if not staged:
                raise KeyError(
                    f"model {name!r} has no version in stage {version_or_stage!r}"
                )
            version = max(v["version"] for v in staged)
        else:
            raise KeyError(f"unknown version or stage {version_or_stage!r}")
        if not self._gcs:
            return self.root / name / "versions" / str(version)
        # GCS: download into the local cache (bundle versions are
        # immutable, so a populated cache dir is authoritative). Download
        # into a temp sibling and rename so an interrupted download can
        # never masquerade as a complete cached bundle.
        local = self._cache_dir / name / str(version)
        if not local.exists():
            self._cache_dir.mkdir(parents=True, exist_ok=True, mode=0o700)
            local.parent.mkdir(parents=True, exist_ok=True)
            incoming = local.parent / f".incoming-{uuid.uuid4().hex}"
            try:
                storage.download_dir(
                    f"{self.root}/{name}/versions/{version}",
                    incoming,
                    self._client,
                )
                try:
                    incoming.replace(local)
                except OSError:
                    # Concurrent resolver won the rename; its copy of the
                    # immutable bundle is as good as ours.
                    if not (local / "manifest.json").exists():
                        raise
                    shutil.rmtree(incoming, ignore_errors=True)
            except BaseException:
                shutil.rmtree(incoming, ignore_errors=True)
                raise
        return local

    def resolve_uri(self, uri: str) -> Path:
        return self.resolve(*parse_model_uri(uri))

    def set_stage(self, name: str, version: int, stage: str) -> None:
        """Promote/demote a version (staging -> production gate, SURVEY.md
        SS3.4). Single-holder semantics: promoting a version to a stage
        archives (stage='none') whichever version held it before.
        """
        if stage not in STAGES:
            raise ValueError(f"stage must be one of {STAGES}")
        with self._locked(name):
            index = self._read_index(name)
            target = next(
                (e for e in index["versions"] if e["version"] == version), None
            )
            if target is None:
                raise KeyError(f"model {name!r} has no version {version}")
            if stage != "none":
                for entry in index["versions"]:
                    if entry is not target and entry["stage"] == stage:
                        entry["stage"] = "none"
            target["stage"] = stage
            target[f"{stage}_since"] = datetime.datetime.now(
                datetime.timezone.utc
            ).isoformat()
            self._write_index(name, index)

    def list_versions(self, name: str) -> list[dict[str, Any]]:
        return self._read_index(name)["versions"]

    def gc(self, name: str, keep_unstaged: int = 0) -> dict[str, list[int]]:
        """Prune registry garbage for one model (local backend).

        Removes ORPHAN version dirs (present on disk, absent from the
        index) and abandoned ``.incoming-*`` staging dirs — both are
        crash-mid-register leftovers the runbook otherwise asks operators
        to delete by hand — and with ``keep_unstaged > 0`` also the oldest
        stage-'none' versions beyond the newest N. Staged versions are
        never touched. Returns what was removed.
        """
        if self._gcs:
            raise ValueError(
                "gc supports the local registry backend; for gs:// roots "
                "use bucket lifecycle rules (versions are immutable "
                "prefixes)"
            )
        with self._locked(name):
            index = self._read_index(name)
            known = {v["version"] for v in index["versions"]}
            versions_dir = self.root / name / "versions"
            orphans_removed = []
            for v in self._stored_versions(name):
                if v not in known:
                    shutil.rmtree(versions_dir / str(v), ignore_errors=True)
                    orphans_removed.append(v)
            # Hard-killed register()s (SIGKILL skips the cleanup handler)
            # leave full-bundle-sized staging dirs; no register can be in
            # flight while gc holds the lock, so they are safe to drop.
            if versions_dir.is_dir():
                for staging in versions_dir.glob(".incoming-*"):
                    shutil.rmtree(staging, ignore_errors=True)
            versions_removed = []
            if keep_unstaged > 0:
                unstaged = sorted(
                    (e for e in index["versions"] if e["stage"] == "none"),
                    key=lambda e: e["version"],
                )
                doomed = unstaged[:-keep_unstaged]
                if doomed:
                    # Index first, dirs after — the inverse order would
                    # leave dangling index entries on a crash mid-loop,
                    # while this order leaves only orphan dirs, which the
                    # scan above self-heals on the next gc.
                    for entry in doomed:
                        index["versions"].remove(entry)
                        versions_removed.append(entry["version"])
                    self._write_index(name, index)
                    for v in versions_removed:
                        shutil.rmtree(versions_dir / str(v), ignore_errors=True)
            return {
                "orphans_removed": orphans_removed,
                "versions_removed": versions_removed,
            }
