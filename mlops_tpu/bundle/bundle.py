"""Bundle save/load: one directory carrying everything serving needs."""

from __future__ import annotations

import dataclasses
import datetime
import json
from pathlib import Path
from typing import Any

import jax

from mlops_tpu.config import ModelConfig
from mlops_tpu.data.encode import Preprocessor
from mlops_tpu.monitor.state import MonitorState
from mlops_tpu.schema.features import SCHEMA
from mlops_tpu.train.checkpoint import restore_tree, tree_bytes
from mlops_tpu.version import __version__

MANIFEST_NAME = "manifest.json"
PARAMS_NAME = "params.msgpack"
BULK_PARAMS_NAME = "bulk_params.msgpack"
QUANT_PARAMS_NAME = "quant_params.npz"
ESTIMATOR_NAME = "estimator.joblib"
PREPROCESS_NAME = "preprocess.npz"
MONITOR_NAME = "monitor.npz"


@dataclasses.dataclass
class Bundle:
    """A loaded bundle: rebuilt model + fitted state, ready to serve.

    Three flavors behind one interface (manifest ``flavor``):
    ``flax`` carries a params pytree for a zoo module; ``sklearn`` carries
    the CPU tree-ensemble floor (BASELINE config 1) — the reference ships
    only the sklearn kind (`02-register-model.ipynb:305-353`); ``doc``
    carries a long-context document model (``doc_records > 1``,
    `train/long_context.py`) whose inputs are record HISTORIES
    ``[D, R, C]`` — it scores offline via ``predict-file``/bulk paths,
    not the single-record HTTP endpoint.
    """

    manifest: dict[str, Any]
    model: Any  # nn.Module (flax flavor) | None
    variables: dict[str, Any]
    preprocessor: Preprocessor
    monitor: MonitorState
    estimator: Any = None  # SklearnBaseline (sklearn flavor) | None
    bulk_model: Any = None  # distilled student (train/distill.py) | None
    bulk_variables: dict[str, Any] | None = None
    quant_params: dict[str, Any] | None = None  # int8/bf16 tier (ops/quant.py)

    @property
    def flavor(self) -> str:
        return self.manifest.get("flavor", "flax")

    @property
    def has_bulk(self) -> bool:
        """True when the bundle carries a distilled bulk student — the
        CPU-backend bulk scorer routes through it (`parallel/bulk.py`);
        serving always uses the exact model."""
        return self.bulk_model is not None

    @property
    def bulk_fidelity(self) -> dict[str, float]:
        return dict(self.manifest.get("bulk", {}).get("fidelity", {}))

    @property
    def has_quant(self) -> bool:
        """True when the bundle carries the int8/bf16 quantized student
        tier (`ops/quant.py`, fitted by `train/distill.py
        distill_quant_student`). Presence alone does NOT make it
        servable — `quant_gates_passed` is the engine's admission check."""
        return self.quant_params is not None

    @property
    def quant_fidelity(self) -> dict[str, float]:
        return dict(self.manifest.get("quant", {}).get("fidelity", {}))

    @property
    def quant_temperature(self) -> float:
        """Post-hoc refit temperature for the quant tier's logits; falls
        back to the exact tier's temperature for old manifests."""
        quant = self.manifest.get("quant", {})
        return float(quant.get("temperature", self.temperature))

    @property
    def quant_gates_passed(self) -> bool:
        """The stamped packaging-time promotion decision
        (`lifecycle/promote.py quant_tier_gates`). Absent block or absent
        decision grades as FAILED — an ungraded tier must not serve."""
        return bool(
            self.manifest.get("quant", {}).get("gates", {}).get("passed", False)
        )

    @property
    def model_config(self) -> ModelConfig:
        return _model_config_from_manifest(self.manifest)

    @property
    def temperature(self) -> float:
        """Fitted calibration temperature (train/calibrate.py); 1.0 when
        the bundle predates calibration or the fit was degenerate."""
        return float(self.manifest.get("calibration", {}).get("temperature", 1.0))


def _model_config_from_manifest(manifest: dict[str, Any]) -> ModelConfig:
    """JSON lists -> tuples so manifests round-trip to equal ModelConfigs."""
    return ModelConfig(**{
        k: tuple(v) if isinstance(v, list) else v
        for k, v in manifest["model_config"].items()
    })


def _environment_pins(flavor: str) -> dict[str, str]:
    """Every runtime package whose version shapes the bundle's behavior —
    the analogue of the reference's conda-env synthesis, which reads
    installed versions via ``importlib.metadata`` and pins them into the
    artifact (`02-register-model.ipynb` cell 11, ~:400-425). A serving
    environment can be reconstructed (or a skew detected) from the
    manifest alone.
    """
    import importlib.metadata
    import platform

    packages = ["jax", "jaxlib", "flax", "optax", "numpy", "pydantic"]
    if flavor == "sklearn":
        packages += ["scikit-learn", "joblib"]
    pins = {"python": platform.python_version()}
    for package in packages:
        try:
            pins[package] = importlib.metadata.version(package)
        except importlib.metadata.PackageNotFoundError:
            pass  # optional dep absent in this env: nothing to pin
    return pins


def save_bundle(
    directory: str | Path,
    model_config: ModelConfig,
    params: Any,
    preprocessor: Preprocessor,
    monitor: MonitorState,
    metrics: dict[str, float] | None = None,
    tags: dict[str, str] | None = None,
    calibration: dict[str, float] | None = None,
    bulk: Any = None,  # DistillResult (train/distill.py) | None
    quant: Any = None,  # QuantDistillResult (train/distill.py) | None
) -> Path:
    """Write a self-contained bundle directory.

    The manifest is the typed replacement for the reference's implicit
    notebook->notebook ``taskValues`` handoff + conda-env synthesis
    (`02-register-model.ipynb` cells 7, 11; SURVEY.md SS3.2).
    """
    from mlops_tpu.models.gbm import SKLEARN_FAMILIES

    directory = Path(directory)
    directory.mkdir(parents=True, exist_ok=True)
    if model_config.family in SKLEARN_FAMILIES:
        flavor = "sklearn"
    elif model_config.doc_records > 1:
        flavor = "doc"
    else:
        flavor = "flax"
    manifest = {
        "format_version": 1,
        "flavor": flavor,
        "framework": {"mlops_tpu": __version__, **_environment_pins(flavor)},
        "created_at": datetime.datetime.now(datetime.timezone.utc).isoformat(),
        "schema_fingerprint": SCHEMA.fingerprint(),
        "model_config": dataclasses.asdict(model_config),
        "metrics": metrics or {},
        "tags": tags or {},
        "calibration": calibration or {},
    }
    if flavor == "sklearn":
        params.save(directory / ESTIMATOR_NAME)  # a SklearnBaseline
    else:
        (directory / PARAMS_NAME).write_bytes(tree_bytes(params))
    if bulk is not None:
        # Distilled bulk student (train/distill.py): a second, smaller
        # param tree + its fidelity record, so bulk routing is auditable.
        manifest["bulk"] = {
            "model_config": dataclasses.asdict(bulk.student_config),
            "fidelity": bulk.fidelity,
        }
        (directory / BULK_PARAMS_NAME).write_bytes(
            tree_bytes(bulk.student_params)
        )
    if quant is not None:
        # Quantized student tier (train/distill.py distill_quant_student):
        # flat npz (numpy has no bf16 — ops/quant.py ships the embed as
        # its exact f32 image), with fidelity, refit temperature, AND the
        # stamped gate decision so serving admission needs no labels.
        import numpy as np

        from mlops_tpu.ops.quant import QUANT_FORMAT, quant_params_to_arrays

        manifest["quant"] = {
            "format": QUANT_FORMAT,
            "fidelity": quant.fidelity,
            "temperature": quant.temperature,
            "gates": quant.gates,
        }
        np.savez(
            directory / QUANT_PARAMS_NAME,
            **quant_params_to_arrays(quant.qparams),
        )
    preprocessor.save(directory / PREPROCESS_NAME)
    monitor.save(directory / MONITOR_NAME)
    (directory / MANIFEST_NAME).write_text(json.dumps(manifest, indent=2))
    return directory


def load_bundle(directory: str | Path) -> Bundle:
    """Load + validate a bundle; rebuilds the model from its manifest.

    Schema-fingerprint mismatch is a hard error: serving a bundle trained
    against a different feature contract is the train/serve skew the
    reference is exposed to via its triple-duplicated feature lists
    (SURVEY.md SS2.2 "Feature schema constants").
    """
    from mlops_tpu.models import build_model, init_params

    directory = Path(directory)
    manifest = json.loads((directory / MANIFEST_NAME).read_text())
    if manifest["schema_fingerprint"] != SCHEMA.fingerprint():
        raise ValueError(
            f"bundle {directory} was built for schema "
            f"{manifest['schema_fingerprint']}, runtime schema is "
            f"{SCHEMA.fingerprint()}"
        )
    model_config = _model_config_from_manifest(manifest)
    preprocessor = Preprocessor.load(directory / PREPROCESS_NAME)
    monitor = MonitorState.load(directory / MONITOR_NAME)
    if manifest.get("flavor", "flax") == "sklearn":
        from mlops_tpu.models.gbm import SklearnBaseline

        return Bundle(
            manifest=manifest,
            model=None,
            variables={},
            preprocessor=preprocessor,
            monitor=monitor,
            estimator=SklearnBaseline.load(directory / ESTIMATOR_NAME),
        )
    if manifest.get("flavor") == "doc":
        # Long-context document model: the DENSE BertDocEncoder (the
        # ring is a training-time layout) with a doc-shaped init template.
        import jax.numpy as jnp

        from mlops_tpu.train.long_context import build_doc_model

        model = build_doc_model(
            dataclasses.replace(model_config, seq_parallel=False)
        )
        template = model.init(
            {"params": jax.random.PRNGKey(0)},
            jnp.zeros((2, model_config.doc_records, SCHEMA.num_categorical), jnp.int32),
            jnp.zeros((2, model_config.doc_records, SCHEMA.num_numeric), jnp.float32),
            train=False,
        )
    else:
        model = build_model(model_config)
        template = init_params(model, jax.random.PRNGKey(0))
    try:
        params = restore_tree(
            template["params"], (directory / PARAMS_NAME).read_bytes()
        )
    except ValueError as err:
        raise ValueError(
            f"bundle {directory} holds a param tree that no longer matches "
            f"the {model_config.family!r} module this framework version "
            "builds — re-train/re-register the model with the current "
            "framework"
        ) from err
    quant_params = None
    if "quant" in manifest and (directory / QUANT_PARAMS_NAME).exists():
        import numpy as np

        from mlops_tpu.ops.quant import QUANT_FORMAT, quant_params_from_arrays

        stored = manifest["quant"].get("format")
        if stored != QUANT_FORMAT:
            raise ValueError(
                f"bundle {directory} carries quant params in format "
                f"{stored!r}; this framework serves {QUANT_FORMAT!r} — "
                "re-run packaging to regenerate the quant tier"
            )
        with np.load(directory / QUANT_PARAMS_NAME) as data:
            quant_params = quant_params_from_arrays(
                {k: data[k] for k in data.files}
            )
    bulk_model = None
    bulk_variables = None
    if "bulk" in manifest and (directory / BULK_PARAMS_NAME).exists():
        bulk_config = _model_config_from_manifest(manifest["bulk"])
        bulk_model = build_model(bulk_config)
        bulk_template = init_params(bulk_model, jax.random.PRNGKey(0))
        bulk_variables = {
            "params": restore_tree(
                bulk_template["params"],
                (directory / BULK_PARAMS_NAME).read_bytes(),
            )
        }
    return Bundle(
        manifest=manifest,
        model=model,
        variables={"params": params},
        preprocessor=preprocessor,
        monitor=monitor,
        bulk_model=bulk_model,
        bulk_variables=bulk_variables,
        quant_params=quant_params,
    )
