"""Model bundle + registry — the packaging layer.

Replaces the reference's MLflow pyfunc ``CustomModel`` artifact (one artifact
= classifier + drift detector + outlier detector + pinned env,
`02-register-model.ipynb:305-353,431-440`) and the MLflow model registry
(`:461-470`, addressed as ``models:/<name>/<version>``, `:503-504`).

A bundle is a directory:

    manifest.json     version, schema fingerprint, model config, metrics,
                      framework versions, tags
    params.msgpack    flax param pytree
    preprocess.npz    fitted Preprocessor state
    monitor.npz       fitted MonitorState (drift refs + outlier detector)

The deploy invariant preserved from the reference: the serving image bakes
the bundle in; rollback = previous image tag (SURVEY.md SS3.4).
"""

from mlops_tpu.bundle.bundle import Bundle, load_bundle, save_bundle
from mlops_tpu.bundle.registry import ModelRegistry, parse_model_uri

__all__ = [
    "Bundle",
    "ModelRegistry",
    "load_bundle",
    "parse_model_uri",
    "save_bundle",
]
