"""Data layer: ingest, synthesis, stats fit, fixed-shape device encoding.

Replaces the reference's Spark external table + pandas path
(`databricks/src/00-create-external-table.ipynb:92-95`,
`01-train-model.ipynb` cell 7's per-trial ``spark.read.table(...).toPandas()``)
with a local/GCS CSV pipeline that reads **once** and encodes to fixed-shape
arrays ready for the TPU: ``int32[N, 9]`` categorical ids + ``float32[N, 14]``
standardized numerics.
"""

from mlops_tpu.data.encode import EncodedDataset, Preprocessor
from mlops_tpu.data.ingest import (
    load_csv_columns,
    load_table_columns,
    write_csv_columns,
)
from mlops_tpu.data.stream import (
    fit_streaming,
    iter_csv_chunks,
    iter_raw_csv_chunks,
    iter_table_chunks,
    score_csv_stream,
)
from mlops_tpu.data.synth import generate_synthetic

__all__ = [
    "EncodedDataset",
    "Preprocessor",
    "fit_streaming",
    "generate_synthetic",
    "iter_csv_chunks",
    "iter_raw_csv_chunks",
    "iter_table_chunks",
    "load_csv_columns",
    "load_table_columns",
    "score_csv_stream",
    "write_csv_columns",
]
