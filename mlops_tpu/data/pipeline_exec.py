"""Pipelined streaming executor: overlap host I/O, encode, device compute,
and output across chunks.

The bulk paths (`data/stream.py score_csv_stream` / `fit_streaming`,
`parallel/bulk.py score_dataset`) are chunked loops whose per-chunk work
decomposes into independent stages — read+parse, vectorized encode,
host->device transfer, device compute, result fetch, output write. Run
serially, the chip idles during host work and the host idles during
compute; "ML Productivity Goodput" (arXiv 2502.06982) identifies exactly
this input-pipeline stall as the dominant accelerator fleet-efficiency
loss. This module is the shared fix: a bounded-queue software pipeline
that keeps every stage busy on a different chunk at once.

Execution model
---------------
``run_pipeline(source, stages, sink, depth)`` wires

    source ──q──> stage 1 ──q──> ... ──q──> stage S ──q──> sink

with one thread per producer stage (the source iterator pumps on its own
thread; each ``Stage.fn`` runs on its own thread; the ``sink`` runs on
the CALLER's thread). Every link is a ``queue.Queue(maxsize=depth)``:

- **Backpressure / memory model**: a stage that races ahead blocks on its
  full output queue, so peak in-flight work is bounded at
  ``(S + 1) * depth`` queued items (per-stage ``queue_depth`` overrides
  included) plus one in-hand item per stage — a fixed small number of
  chunks regardless of dataset size.
- **Ordering**: single-threaded stages + FIFO queues preserve chunk
  order end to end, so a deterministic stage graph produces BIT-IDENTICAL
  output at any depth. ``depth <= 1`` short-circuits to a plain serial
  loop on the caller thread — exactly the pre-pipeline behavior.
- **Double buffering** falls out of the structure: with a transfer stage
  ahead of the compute stage, chunk N+1's ``jax.device_put`` runs while
  chunk N computes, and a fetch stage behind it pulls chunk N-1's results
  during chunk N's dispatch.
- **Batch stages** (``Stage(batch_max=k)``): the worker gathers whatever
  is immediately available (1..k items) and passes the LIST to ``fn``,
  which must return one output per input. Grouping varies with timing, so
  ``fn`` must be grouping-invariant (e.g. a batched ``jax.device_get``
  that amortizes transport round trips without changing per-item values).
- **Failure semantics**: an exception in ANY stage (or the source, or the
  sink) stops the pipeline promptly and cleanly — the failing worker
  forwards a failure marker downstream and keeps draining its input so no
  producer is ever left blocked on a full queue; upstream workers see the
  stop flag and discard. The caller joins every thread, then re-raises
  the ORIGINAL exception. No hung threads, no half-consumed queues.

Per-stage wall/occupancy timing (`utils/timing.py StageClock`) comes back
in the returned ``PipelineStats`` so overlap wins are measured, not
asserted: occupancies sum to ~1.0 when serial and exceed it when
overlapped, and the largest occupancy names the bottleneck stage.

Concurrency discipline (tpulint Layer 3): this executor deliberately owns
NO explicit locks — all cross-thread state rides the bounded
``queue.Queue`` links (internally locked) plus one ``threading.Event``
stop flag, so there is no order to violate and nothing for
blocking-under-lock to flag. The schedule-dependent invariants (FIFO
bit-identical outputs, clean failure drain) are exercised under seeded
schedule perturbation instead (`analysis/lockcheck.py SchedulePerturber`,
tests/test_pipeline_exec.py) — keep new shared state on the queues, not
on ad-hoc locks.
"""

from __future__ import annotations

import dataclasses
import queue
import threading
import time
from typing import Any, Callable, Iterable

from mlops_tpu.utils.timing import StageClock

# How long the caller waits for workers to drain after the last sentinel
# before declaring the executor wedged. Generous: drain is bounded by the
# in-flight item count, not the dataset.
_JOIN_TIMEOUT_S = 60.0


@dataclasses.dataclass(frozen=True)
class Stage:
    """One pipeline stage: ``fn(item) -> item`` on its own worker thread.

    ``batch_max > 1`` switches ``fn`` to list-in/list-out over whatever
    items are immediately available (at most ``batch_max``); results must
    not depend on the grouping (see module docstring).

    ``queue_depth`` overrides the bound of this stage's INPUT queue
    (default: the pipeline's ``depth``). A batched fetch stage uses it to
    keep a deep async-dispatch window — its producer can run that many
    chunks ahead — without deepening every other queue in the pipeline.
    """

    name: str
    fn: Callable[[Any], Any]
    batch_max: int = 1
    queue_depth: int | None = None


@dataclasses.dataclass
class PipelineStats:
    """Timing evidence for one pipeline run."""

    depth: int
    wall_s: float
    items: int  # items the sink consumed
    stages: dict[str, dict[str, float]]  # name -> busy_s / items / occupancy

    def as_dict(self) -> dict[str, Any]:
        return {
            "depth": self.depth,
            "wall_s": round(self.wall_s, 4),
            "items": self.items,
            "stages": self.stages,
        }


class _Failure:
    """A stage's exception, traveling the queues in place of an item."""

    __slots__ = ("stage", "exc")

    def __init__(self, stage: str, exc: BaseException):
        self.stage = stage
        self.exc = exc


_DONE = object()  # end-of-stream sentinel; exactly one per producer


def run_pipeline(
    source: Iterable[Any],
    stages: list[Stage],
    sink: Callable[[Any], None],
    depth: int = 4,
    source_name: str = "read",
    sink_name: str = "write",
    stage_sink: Callable | None = None,
) -> PipelineStats:
    """Stream ``source`` through ``stages`` into ``sink`` (see module
    docstring for the execution model). Returns per-stage timing stats;
    re-raises the original exception if any stage fails. ``stage_sink``
    (tracewire — `trace/recorder.TraceRecorder.stage_sink`) additionally
    streams every completed stage execution into the span JSONL."""
    depth = max(1, int(depth))
    clock = StageClock(sink=stage_sink)
    start = time.perf_counter()
    if depth <= 1:
        items = _run_serial(source, stages, sink, clock, source_name, sink_name)
    else:
        items = _run_threaded(
            source, stages, sink, depth, clock, source_name, sink_name
        )
    wall = time.perf_counter() - start
    return PipelineStats(
        depth=depth, wall_s=wall, items=items, stages=clock.report(wall)
    )


def _run_serial(source, stages, sink, clock, source_name, sink_name) -> int:
    """depth<=1: the exact pre-pipeline serial loop, instrumented."""
    iterator = iter(source)
    count = 0
    while True:
        with clock.stage(source_name):
            item = next(iterator, _DONE)
        if item is _DONE:
            break
        for stage in stages:
            with clock.stage(stage.name):
                if stage.batch_max > 1:
                    item = stage.fn([item])[0]
                else:
                    item = stage.fn(item)
        with clock.stage(sink_name):
            sink(item)
        count += 1
    return count


def _run_threaded(
    source, stages, sink, depth, clock, source_name, sink_name
) -> int:
    stop = threading.Event()
    links = [
        queue.Queue(maxsize=stage.queue_depth or depth) for stage in stages
    ] + [queue.Queue(maxsize=depth)]

    threads = [
        threading.Thread(
            target=_pump_source,
            args=(source, links[0], stop, clock, source_name),
            name=f"pipeline-{source_name}",
            daemon=True,
        )
    ]
    for i, stage in enumerate(stages):
        threads.append(
            threading.Thread(
                target=_run_stage,
                args=(stage, links[i], links[i + 1], stop, clock),
                name=f"pipeline-{stage.name}",
                daemon=True,
            )
        )
    for t in threads:
        t.start()

    failures: list[_Failure] = []
    count = 0
    final = links[-1]
    try:
        # The sink loop consumes to _DONE UNCONDITIONALLY — even after a
        # failure — so upstream workers can always finish their drain.
        while True:
            item = final.get()
            if item is _DONE:
                break
            if isinstance(item, _Failure):
                stop.set()
                failures.append(item)
                continue
            if failures or stop.is_set():
                continue  # draining after a sink-side failure
            try:
                with clock.stage(sink_name):
                    sink(item)
                count += 1
            # Captured, forwarded, and re-raised after the drain —
            # nothing is swallowed.  # tpulint: disable=TPU201
            except BaseException as exc:
                stop.set()
                failures.append(_Failure(sink_name, exc))
    finally:
        for t in threads:
            t.join(timeout=_JOIN_TIMEOUT_S)
        wedged = [t.name for t in threads if t.is_alive()]
        if wedged:
            # Executor invariant broken (a worker failed to drain). Never
            # silently returns with live threads.
            raise RuntimeError(
                f"pipeline workers failed to drain: {wedged}"
            ) from (failures[0].exc if failures else None)
    if failures:
        raise failures[0].exc
    return count


def _pump_source(source, out, stop, clock, name) -> None:
    try:
        iterator = iter(source)
        while not stop.is_set():
            with clock.stage(name):
                item = next(iterator, _DONE)
            if item is _DONE:
                break
            out.put(item)
    # Captured as a _Failure and re-raised by the caller.  # tpulint: disable=TPU201
    except BaseException as exc:
        stop.set()
        out.put(_Failure(name, exc))
    finally:
        out.put(_DONE)


def _run_stage(stage: Stage, inq, outq, stop, clock) -> None:
    draining = False
    try:
        while True:
            item = inq.get()
            if item is _DONE:
                break
            if isinstance(item, _Failure):
                stop.set()
                outq.put(item)
                draining = True
                continue
            if draining or stop.is_set():
                continue
            try:
                if stage.batch_max > 1:
                    if _run_batch(stage, item, inq, outq, stop, clock):
                        break
                else:
                    with clock.stage(stage.name):
                        out = stage.fn(item)
                    outq.put(out)
            # Captured as a _Failure and re-raised by the caller.  # tpulint: disable=TPU201
            except BaseException as exc:
                stop.set()
                outq.put(_Failure(stage.name, exc))
                draining = True
    finally:
        outq.put(_DONE)


def _run_batch(stage: Stage, first, inq, outq, stop, clock) -> bool:
    """Gather up to ``batch_max`` immediately-available items, run ``fn``
    over the list, forward each result. Handles its OWN fn failure — the
    gather may have swallowed the _DONE sentinel, and an exception escaping
    past that fact would leave the worker blocked on an empty queue.
    Returns True when _DONE was swallowed (the stage must exit)."""
    batch = [first]
    saw_done = False
    pending: _Failure | None = None
    while len(batch) < stage.batch_max:
        try:
            extra = inq.get_nowait()
        except queue.Empty:
            break
        if extra is _DONE:
            saw_done = True
            break
        if isinstance(extra, _Failure):
            pending = extra
            break
        batch.append(extra)
    try:
        with clock.stage(stage.name, items=len(batch)):
            outs = stage.fn(batch)
    # Captured as a _Failure and re-raised by the caller.  # tpulint: disable=TPU201
    except BaseException as exc:
        stop.set()
        outq.put(_Failure(stage.name, exc))
        outs = []
    for out in outs:
        outq.put(out)
    if pending is not None:
        stop.set()
        outq.put(pending)
        # Keep draining on the normal loop; the failure is already forwarded.
    return saw_done
