"""Synthetic credit-default data generator.

The reference trains on an adapted UCI Credit Card Default CSV
(`databricks/data/curated.csv`, referenced at
`.github/workflows/deploy-infrastructure.yml:195-198` but stripped from the
mount) and ships an 80-row `databricks/data/inference.csv` sample. This module
generates schema-conforming data with a known generative process so training,
HPO, drift, and benchmarks are reproducible without the original dataset.

The generative process encodes real credit-risk structure so learned models
have signal to find: a latent delinquency trait drives repayment-status
categories, payment-to-bill ratios, and the default probability; utilization
(bill/credit-limit) and demographics modulate it.
"""

from __future__ import annotations

import numpy as np

from mlops_tpu.schema.features import SCHEMA, _REPAYMENT_VOCAB


def _sigmoid(x: np.ndarray) -> np.ndarray:
    return 1.0 / (1.0 + np.exp(-x))


def generate_synthetic(
    n: int,
    seed: int = 0,
    drift: float = 0.0,
) -> tuple[dict[str, list], np.ndarray]:
    """Generate ``n`` rows of schema-conforming data.

    Args:
      n: number of rows.
      seed: RNG seed.
      drift: 0.0 for in-distribution data; larger values shift the
        distributions (used to test drift detection, parity with
        alibi-detect's semantics in `02-register-model.ipynb:225-230`).

    Returns:
      (columns, labels) where ``columns`` maps feature name -> list of python
      values (str for categorical, float for numeric) and ``labels`` is an
      int8 array of default indicators.
    """
    rng = np.random.default_rng(seed)

    # Latent delinquency trait in [0, 1]: most customers low, a tail high.
    delinquency = rng.beta(1.2 + drift * 2.0, 4.0, size=n)

    age = np.clip(rng.normal(37.0 + 8.0 * drift, 9.5, size=n), 21.0, 79.0)

    education_p = np.array([0.38, 0.42, 0.17, 0.03])
    education = rng.choice(len(education_p), size=n, p=education_p)
    sex = rng.choice(2, size=n, p=[0.45, 0.55])
    marriage_p = np.array([0.45, 0.52, 0.03])
    marriage = rng.choice(len(marriage_p), size=n, p=marriage_p)

    # Credit limit: lognormal, higher for more educated / older, in dollars.
    limit_mu = 9.4 + 0.25 * (education == 0) - 0.15 * (education == 2) + 0.004 * age
    credit_limit = np.exp(rng.normal(limit_mu, 0.55 + 0.2 * drift))
    credit_limit = np.round(np.clip(credit_limit, 1000.0, 300000.0), -2)

    # Repayment statuses: delinquency trait -> delay months (0..9 mapped onto
    # vocab: duly_paid, no_delay, delay_1..9). AR(1)-ish persistence month to
    # month.
    n_levels = len(_REPAYMENT_VOCAB)
    base_level = np.clip(
        rng.poisson(delinquency * 3.2) + (delinquency > 0.6).astype(int),
        0,
        n_levels - 1,
    )
    repayment = np.zeros((6, n), dtype=np.int64)
    level = base_level
    for month in range(6):
        step = rng.integers(-1, 2, size=n)
        level = np.clip(level + step * (rng.random(n) < 0.35), 0, n_levels - 1)
        repayment[month] = level

    # Utilization and bills: delinquent customers carry higher balances.
    utilization = np.clip(
        rng.beta(2.0, 5.0, size=n) + 0.5 * delinquency + 0.2 * drift, 0.0, 1.5
    )
    bills = np.empty((6, n))
    bill = utilization * credit_limit * rng.uniform(0.7, 1.1, size=n)
    for month in range(6):
        bill = np.clip(
            bill * rng.uniform(0.85, 1.15, size=n)
            + rng.normal(0, 0.02, size=n) * credit_limit,
            0.0,
            None,
        )
        bills[month] = np.round(bill, 2)

    # Payments: fraction of the bill, lower for delinquent customers.
    pay_frac = np.clip(
        rng.beta(3.0, 2.0, size=n) * (1.0 - 0.8 * delinquency), 0.0, 1.0
    )
    payments = np.round(
        bills * pay_frac * rng.uniform(0.6, 1.0, size=(6, n)), 2
    )

    # Default probability: driven by delinquency, utilization, payment ratio.
    payment_ratio = payments.sum(0) / np.maximum(bills.sum(0), 1.0)
    logit = (
        -3.6
        + 7.0 * delinquency
        + 2.0 * np.clip(utilization, 0, 1.2)
        - 2.6 * payment_ratio
        + 0.5 * (repayment[0] >= 3)
        - 0.02 * (age - 37.0)
    )
    labels = (rng.random(n) < _sigmoid(logit)).astype(np.int8)

    edu_vocab = SCHEMA.categorical[1].vocab
    mar_vocab = SCHEMA.categorical[2].vocab
    sex_vocab = SCHEMA.categorical[0].vocab

    columns: dict[str, list] = {
        "sex": [sex_vocab[i] for i in sex],
        "education": [edu_vocab[i] for i in education],
        "marriage": [mar_vocab[i] for i in marriage],
    }
    for month in range(6):
        columns[f"repayment_status_{month + 1}"] = [
            _REPAYMENT_VOCAB[i] for i in repayment[month]
        ]
    columns["credit_limit"] = credit_limit.tolist()
    columns["age"] = np.round(age, 1).tolist()
    for month in range(6):
        columns[f"bill_amount_{month + 1}"] = bills[month].tolist()
    for month in range(6):
        columns[f"payment_amount_{month + 1}"] = payments[month].tolist()

    return columns, labels
