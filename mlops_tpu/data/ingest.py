"""CSV ingest — the external-table analogue.

The reference mounts a CSV as a Spark external table
(`00-create-external-table.ipynb:92-95`, ``USING csv OPTIONS (header "true",
inferSchema "true")``) and re-reads it into pandas every HPO trial
(`01-train-model.ipynb` cell 7). Here: read once into columnar python lists
keyed by the canonical schema, with header validation. A native C++ fast path
(``mlops_tpu.native``) accelerates bulk parsing when built.
"""

from __future__ import annotations

import csv
from pathlib import Path

import numpy as np

from mlops_tpu.schema.features import SCHEMA, FeatureSchema
from mlops_tpu.utils import storage


def fetch_local(path: str | Path, workdir: str | Path | None = None) -> Path:
    """Materialize ``path`` as a local file. Local paths pass through;
    ``gs://`` objects download into ``workdir`` (default: a per-user
    cache under ``~/.cache/mlops_tpu``) so byte-oriented consumers — the
    native C++ CSV kernel above all — can run on remote datasets too. The
    analogue of the reference's DBFS staging
    (`deploy-infrastructure.yml:195-198`).

    The cache key includes the object's generation (or md5/size when the
    server omits it), so a re-staged dataset at the same URI is re-fetched
    instead of silently served stale.
    """
    if not storage.is_gcs(path):
        return Path(path)
    import hashlib

    workdir = Path(workdir or Path.home() / ".cache" / "mlops_tpu" / "data")
    workdir.mkdir(parents=True, exist_ok=True)
    client = storage.gcs_client()
    meta = client.stat(str(path))
    stamp = str(
        meta.get("generation") or meta.get("md5Hash") or meta.get("size", "")
    )
    tag = hashlib.sha256(f"{path}\x00{stamp}".encode()).hexdigest()[:16]
    local = workdir / f"{tag}-{str(path).rsplit('/', 1)[-1]}"
    if not local.exists():
        client.read_to_file(str(path), local)
    return local


def _cell(row: list, i: int) -> str:
    return row[i] if i < len(row) else ""


def _to_float(raw: str) -> float:
    try:
        return float(raw)
    except ValueError:
        return float("nan")


def rows_to_columns(
    rows: list, col_index: dict[str, int], schema: FeatureSchema = SCHEMA
) -> dict[str, list]:
    """Parsed CSV rows -> columnar lists, one contract for the batch reader
    and the streaming reader (`data/stream.py`): categorical cells pass
    through as strings (missing -> "" -> OOV), numerics parse leniently
    (unparseable -> NaN -> median imputation)."""
    columns: dict[str, list] = {}
    for feat in schema.categorical:
        i = col_index[feat.name]
        columns[feat.name] = [_cell(row, i) for row in rows]
    for feat in schema.numeric:
        i = col_index[feat.name]
        columns[feat.name] = [_to_float(_cell(row, i)) for row in rows]
    return columns


def parse_labels(
    rows: list,
    col_index: dict[str, int],
    schema: FeatureSchema,
    path,
    base_row: int,
) -> np.ndarray:
    """Strict TRAINING-label parse: any unparseable value fails fast
    (silently training on garbage would surface only as mysteriously bad
    AUC; the native kernel mirrors this — MLOPS_ERR_BAD_LABEL)."""
    i = col_index[schema.target]
    raw = np.asarray([_to_float(_cell(row, i)) for row in rows])
    bad = ~np.isfinite(raw)
    if bad.any():
        raise ValueError(
            f"{path}: {int(bad.sum())} unparseable value(s) in target "
            f"column {schema.target!r} (first at data row "
            f"{base_row + int(np.argmax(bad))})"
        )
    return raw.astype(np.int8)


def load_csv_columns(
    path: str | Path,
    schema: FeatureSchema = SCHEMA,
    require_target: bool = False,
) -> tuple[dict[str, list], np.ndarray | None]:
    """Read a schema-conforming CSV into columnar lists (+labels if present).

    Accepts local paths and ``gs://`` URIs (the uploaded-dataset contract:
    `deploy-infrastructure.yml` stages curated.csv into the estate bucket);
    remote objects stream to the local cache first rather than being
    buffered (and decoded) whole in memory.
    """
    with fetch_local(path).open(newline="") as f:
        reader = csv.reader(f)
        header = next(reader)
        # Malformed-row semantics are pinned to the native kernel's
        # (`native/encoder.cpp`, parity-tested): blank lines are skipped,
        # short rows read missing cells as empty (-> OOV / median).
        rows = [row for row in reader if row and row != [""]]

    col_index = {name: i for i, name in enumerate(header)}
    missing = [n for n in schema.feature_names if n not in col_index]
    if missing:
        raise ValueError(f"{path}: missing required columns {missing}")
    if require_target and schema.target not in col_index:
        raise ValueError(f"{path}: missing target column {schema.target!r}")

    columns = rows_to_columns(rows, col_index, schema)

    labels = None
    if schema.target in col_index:
        if require_target:
            labels = parse_labels(rows, col_index, schema, path, 0)
        else:
            # Scoring/pretrain paths: parse permissively; any unparseable
            # value means the file is unlabeled as a whole.
            i = col_index[schema.target]
            raw = np.asarray([_to_float(_cell(row, i)) for row in rows])
            labels = None if (~np.isfinite(raw)).any() else raw.astype(np.int8)
    return columns, labels


def load_table_columns(
    path: str | Path,
    schema: FeatureSchema = SCHEMA,
    require_target: bool = False,
) -> tuple[dict[str, list], np.ndarray | None]:
    """Format-dispatching reader: ``.parquet``/``.pq`` routes to the
    columnar path (`data/parquet.py`), everything else to CSV. One contract
    either way — this is the entry point pipelines should use (the
    reference gets the same property from Spark's format-agnostic
    ``read.table``)."""
    from mlops_tpu.data import parquet

    if parquet.is_parquet(path):
        return parquet.load_parquet_columns(path, schema, require_target)
    return load_csv_columns(path, schema, require_target)


def write_csv_columns(
    path: str | Path,
    columns: dict[str, list],
    labels: np.ndarray | None = None,
    schema: FeatureSchema = SCHEMA,
) -> None:
    """Write columnar data to CSV in canonical schema order."""
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    names = list(schema.feature_names)
    if labels is not None:
        names_out = names + [schema.target]
    else:
        names_out = names
    n = len(columns[names[0]])
    with path.open("w", newline="") as f:
        writer = csv.writer(f)
        writer.writerow(names_out)
        for i in range(n):
            row = [columns[name][i] for name in names]
            if labels is not None:
                row.append(int(labels[i]))
            writer.writerow(row)
