"""CSV ingest — the external-table analogue.

The reference mounts a CSV as a Spark external table
(`00-create-external-table.ipynb:92-95`, ``USING csv OPTIONS (header "true",
inferSchema "true")``) and re-reads it into pandas every HPO trial
(`01-train-model.ipynb` cell 7). Here: read once into columnar python lists
keyed by the canonical schema, with header validation. A native C++ fast path
(``mlops_tpu.native``) accelerates bulk parsing when built.
"""

from __future__ import annotations

import csv
from pathlib import Path

import numpy as np

from mlops_tpu.schema.features import SCHEMA, FeatureSchema
from mlops_tpu.utils import storage


def fetch_local(path: str | Path, workdir: str | Path | None = None) -> Path:
    """Materialize ``path`` as a local file. Local paths pass through;
    ``gs://`` objects download into ``workdir`` (default: a per-user
    cache under ``~/.cache/mlops_tpu``) so byte-oriented consumers — the
    native C++ CSV kernel above all — can run on remote datasets too. The
    analogue of the reference's DBFS staging
    (`deploy-infrastructure.yml:195-198`).

    The cache key includes the object's generation (or md5/size when the
    server omits it), so a re-staged dataset at the same URI is re-fetched
    instead of silently served stale.
    """
    if not storage.is_gcs(path):
        return Path(path)
    import hashlib

    workdir = Path(workdir or Path.home() / ".cache" / "mlops_tpu" / "data")
    workdir.mkdir(parents=True, exist_ok=True)
    client = storage.gcs_client()
    meta = client.stat(str(path))
    stamp = str(
        meta.get("generation") or meta.get("md5Hash") or meta.get("size", "")
    )
    tag = hashlib.sha256(f"{path}\x00{stamp}".encode()).hexdigest()[:16]
    local = workdir / f"{tag}-{str(path).rsplit('/', 1)[-1]}"
    if not local.exists():
        client.read_to_file(str(path), local)
    return local


def load_csv_columns(
    path: str | Path,
    schema: FeatureSchema = SCHEMA,
    require_target: bool = False,
) -> tuple[dict[str, list], np.ndarray | None]:
    """Read a schema-conforming CSV into columnar lists (+labels if present).

    Accepts local paths and ``gs://`` URIs (the uploaded-dataset contract:
    `deploy-infrastructure.yml` stages curated.csv into the estate bucket);
    remote objects stream to the local cache first rather than being
    buffered (and decoded) whole in memory.
    """
    with fetch_local(path).open(newline="") as f:
        reader = csv.reader(f)
        header = next(reader)
        # Malformed-row semantics are pinned to the native kernel's
        # (`native/encoder.cpp`, parity-tested): blank lines are skipped,
        # short rows read missing cells as empty (-> OOV / median).
        rows = [row for row in reader if row and row != [""]]

    col_index = {name: i for i, name in enumerate(header)}
    missing = [n for n in schema.feature_names if n not in col_index]
    if missing:
        raise ValueError(f"{path}: missing required columns {missing}")
    if require_target and schema.target not in col_index:
        raise ValueError(f"{path}: missing target column {schema.target!r}")

    def cell(row: list, i: int) -> str:
        return row[i] if i < len(row) else ""

    def to_float(raw: str) -> float:
        try:
            return float(raw)
        except ValueError:
            return float("nan")

    columns: dict[str, list] = {}
    for feat in schema.categorical:
        i = col_index[feat.name]
        columns[feat.name] = [cell(row, i) for row in rows]
    for feat in schema.numeric:
        i = col_index[feat.name]
        columns[feat.name] = [to_float(cell(row, i)) for row in rows]

    labels = None
    if schema.target in col_index:
        i = col_index[schema.target]
        raw = np.asarray([to_float(cell(row, i)) for row in rows])
        bad = ~np.isfinite(raw)
        if bad.any():
            if require_target:
                # Features degrade gracefully (OOV/median) but corrupt
                # TRAINING labels fail fast — silently training on garbage
                # would surface only as mysteriously bad AUC. Native
                # kernel mirrors this (MLOPS_ERR_BAD_LABEL).
                raise ValueError(
                    f"{path}: {int(bad.sum())} unparseable value(s) in "
                    f"target column {schema.target!r} (first at data row "
                    f"{int(np.argmax(bad))})"
                )
            # Scoring/pretrain paths: a partially-blank target column just
            # means the file is unlabeled — labels are never read there.
            labels = None
        else:
            labels = raw.astype(np.int8)
    return columns, labels


def write_csv_columns(
    path: str | Path,
    columns: dict[str, list],
    labels: np.ndarray | None = None,
    schema: FeatureSchema = SCHEMA,
) -> None:
    """Write columnar data to CSV in canonical schema order."""
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    names = list(schema.feature_names)
    if labels is not None:
        names_out = names + [schema.target]
    else:
        names_out = names
    n = len(columns[names[0]])
    with path.open("w", newline="") as f:
        writer = csv.writer(f)
        writer.writerow(names_out)
        for i in range(n):
            row = [columns[name][i] for name in names]
            if labels is not None:
                row.append(int(labels[i]))
            writer.writerow(row)
