"""Parquet ingest — the columnar sibling of the CSV path.

The reference's data layer is Spark-shaped: an external table over CSV
(`00-create-external-table.ipynb:92-95`) that Databricks estates routinely
swap for Parquet/Delta without touching downstream code. This module gives
the framework the same property: a ``.parquet`` dataset flows through the
IDENTICAL column contract as ``data/ingest.py`` — categorical cells as
strings (null -> "" -> OOV), numerics as floats (null/unparseable -> NaN ->
median imputation), labels strict under ``require_target`` — so every
consumer (Preprocessor fit, streaming stats, bulk scoring) is
format-agnostic via the ``load_table_columns`` / ``iter_table_chunks``
dispatchers.

pyarrow is an optional dependency: it is present in the dev/TPU image but
deliberately NOT in the pinned serving image (`docker/requirements.txt`
stays minimal), so the import is gated and the error message says what to
install.
"""

from __future__ import annotations

from pathlib import Path
from typing import Iterator

import numpy as np

from mlops_tpu.data.ingest import _to_float, fetch_local
from mlops_tpu.schema.features import SCHEMA, FeatureSchema

PARQUET_SUFFIXES = (".parquet", ".pq")


def is_parquet(path: str | Path) -> bool:
    """Route on file extension — the only signal available for ``gs://``
    URIs without a remote read."""
    return str(path).lower().endswith(PARQUET_SUFFIXES)


def _pyarrow_parquet():
    try:
        import pyarrow.parquet as pq
    except ImportError as e:  # pragma: no cover - image always has pyarrow
        raise RuntimeError(
            "Parquet ingest requires pyarrow (`pip install pyarrow`); "
            "convert the dataset to CSV or install it"
        ) from e
    return pq


def _check_columns(
    names: list[str], path, schema: FeatureSchema, require_target: bool
) -> None:
    # Same error contract as the CSV reader (`ingest.load_csv_columns`).
    present = set(names)
    missing = [n for n in schema.feature_names if n not in present]
    if missing:
        raise ValueError(f"{path}: missing required columns {missing}")
    if require_target and schema.target not in present:
        raise ValueError(f"{path}: missing target column {schema.target!r}")


def _cat_cells(array) -> list[str]:
    """Arrow column -> list[str] with CSV semantics: null -> "" (-> OOV),
    non-string storage stringified the way ``csv.writer`` would have
    (ints stay unpadded, floats keep their repr)."""
    out = []
    for v in array.to_pylist():
        if v is None:
            out.append("")
        elif isinstance(v, str):
            out.append(v)
        else:
            out.append(str(v))
    return out


def _num_cells(array) -> list[float]:
    """Arrow column -> list[float]; null -> NaN; string storage parses
    leniently (unparseable -> NaN), matching ``ingest._to_float``.

    Numeric-typed storage converts through Arrow's vectorized cast (nulls
    become NaN in C, no per-cell boxing — this is the bulk-ingest hot
    path); only string-typed columns fall back to per-cell parsing.
    """
    import pyarrow as pa
    import pyarrow.compute as pc

    typ = array.type
    if pa.types.is_string(typ) or pa.types.is_large_string(typ):
        return [_to_float(v) if v is not None else float("nan")
                for v in array.to_pylist()]
    if pa.types.is_boolean(typ):
        array = array.cast(pa.int8())
    casted = array.cast(pa.float64(), safe=False)
    casted = pc.if_else(pc.is_null(casted), float("nan"), casted)
    return casted.to_numpy(zero_copy_only=False).tolist()


def _columns_from_table(table, schema: FeatureSchema) -> dict[str, list]:
    columns: dict[str, list] = {}
    for feat in schema.categorical:
        columns[feat.name] = _cat_cells(table.column(feat.name))
    for feat in schema.numeric:
        columns[feat.name] = _num_cells(table.column(feat.name))
    return columns


def _label_floats(table, schema: FeatureSchema) -> np.ndarray:
    return np.asarray(_num_cells(table.column(schema.target)), dtype=np.float64)


def _strict_labels(
    raw: np.ndarray, path, schema: FeatureSchema, base_row: int
) -> np.ndarray:
    """Training-label contract: fail fast on any null/unparseable value
    (mirrors ``ingest.parse_labels`` / MLOPS_ERR_BAD_LABEL)."""
    bad = ~np.isfinite(raw)
    if bad.any():
        raise ValueError(
            f"{path}: {int(bad.sum())} unparseable value(s) in target "
            f"column {schema.target!r} (first at data row "
            f"{base_row + int(np.argmax(bad))})"
        )
    return raw.astype(np.int8)


def load_parquet_columns(
    path: str | Path,
    schema: FeatureSchema = SCHEMA,
    require_target: bool = False,
) -> tuple[dict[str, list], np.ndarray | None]:
    """Read a schema-conforming Parquet file into columnar lists (+labels).

    Same signature and semantics as ``ingest.load_csv_columns`` — local
    paths and ``gs://`` URIs (staged through the same generation-keyed
    cache), strict labels only under ``require_target``, permissive
    otherwise (one bad value unlabels the file).
    """
    pq = _pyarrow_parquet()
    f = pq.ParquetFile(fetch_local(path))
    names = [field.name for field in f.schema_arrow]
    _check_columns(names, path, schema, require_target)
    wanted = [n for n in (*schema.feature_names, schema.target) if n in names]
    table = f.read(columns=wanted)
    columns = _columns_from_table(table, schema)

    labels = None
    if schema.target in names:
        raw = _label_floats(table, schema)
        if require_target:
            labels = _strict_labels(raw, path, schema, 0)
        else:
            labels = None if (~np.isfinite(raw)).any() else raw.astype(np.int8)
    return columns, labels


def iter_parquet_chunks(
    path: str | Path,
    chunk_rows: int = 65_536,
    schema: FeatureSchema = SCHEMA,
    require_target: bool = False,
) -> Iterator[tuple[dict[str, list], np.ndarray | None]]:
    """Yield ``(columns, labels)`` chunks of EXACTLY ``chunk_rows`` rows
    (except the tail), re-buffering across Arrow record batches — row-group
    boundaries would otherwise fragment chunk shapes and force the
    downstream compiled scorer to pad every chunk. Contract identical to
    ``stream.iter_csv_chunks``: labels only under ``require_target``
    (strict), memory bounded by one chunk + one record batch.
    """
    pq = _pyarrow_parquet()
    f = pq.ParquetFile(fetch_local(path))
    names = [field.name for field in f.schema_arrow]
    _check_columns(names, path, schema, require_target)
    wanted = [n for n in schema.feature_names]
    if require_target:
        wanted.append(schema.target)

    feature_names = list(schema.feature_names)
    buffers: dict[str, list] = {n: [] for n in feature_names}
    label_buffer: list[float] = []
    emitted = 0

    def emit(n: int):
        nonlocal emitted
        columns = {name: buffers[name][:n] for name in feature_names}
        for name in feature_names:
            del buffers[name][:n]
        labels = None
        if require_target:
            raw = np.asarray(label_buffer[:n], dtype=np.float64)
            del label_buffer[:n]
            labels = _strict_labels(raw, path, schema, emitted)
        emitted += n
        return columns, labels

    import pyarrow as pa

    for batch in f.iter_batches(batch_size=chunk_rows, columns=wanted):
        table = pa.Table.from_batches([batch])
        chunk_cols = _columns_from_table(table, schema)
        for name in feature_names:
            buffers[name].extend(chunk_cols[name])
        if require_target:
            label_buffer.extend(_label_floats(table, schema).tolist())
        while len(buffers[feature_names[0]]) >= chunk_rows:
            yield emit(chunk_rows)
    tail = len(buffers[feature_names[0]])
    if tail:
        yield emit(tail)


def write_parquet_columns(
    path: str | Path,
    columns: dict[str, list],
    labels: np.ndarray | None = None,
    schema: FeatureSchema = SCHEMA,
) -> None:
    """Write columnar data to Parquet in canonical schema order: categorical
    as UTF-8 strings, numeric as float64, labels as int8 — the layout
    ``load_parquet_columns`` round-trips losslessly."""
    pq = _pyarrow_parquet()
    import pyarrow as pa

    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    arrays, names = [], []
    for feat in schema.categorical:
        arrays.append(pa.array([str(v) for v in columns[feat.name]], pa.string()))
        names.append(feat.name)
    for feat in schema.numeric:
        arrays.append(pa.array(columns[feat.name], pa.float64()))
        names.append(feat.name)
    if labels is not None:
        arrays.append(pa.array(np.asarray(labels, dtype=np.int8), pa.int8()))
        names.append(schema.target)
    pq.write_table(pa.Table.from_arrays(arrays, names=names), path)
