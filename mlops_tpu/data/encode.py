"""Preprocessing: vocab/stat fit + fixed-shape array encoding.

TPU-native replacement for the reference's sklearn ColumnTransformer
(`01-train-model.ipynb:195-227`: categorical SimpleImputer(constant) +
OneHotEncoder(handle_unknown="ignore"); numeric SimpleImputer(median)):

- categoricals -> int32 ids (embedding lookup beats one-hot matmul on MXU for
  small cards; unseen values -> OOV id, same semantics as handle_unknown).
- numerics -> median-imputed then standardized float32. Standardization is
  affine, so downstream K-S drift statistics are unchanged vs raw space.

The fitted state is a plain dict of numpy arrays, serialized into the model
bundle (the reference pickles the whole sklearn Pipeline instead;
`02-register-model.ipynb` cell 7).
"""

from __future__ import annotations

import dataclasses
from pathlib import Path

import numpy as np

from mlops_tpu.schema.features import SCHEMA, FeatureSchema

# Vocab -> id lookup tables are schema constants (frozen dataclasses);
# building them inside encode() would put 9 array constructions on the
# serving hot path for every request batch. Stored sorted so the encode
# is a vectorized searchsorted instead of a per-value Python dict probe —
# encode sits on the hot path of every pipelined bulk worker
# (data/pipeline_exec.py) as well as the serving path.
_VOCAB_TABLES: dict[tuple, tuple[np.ndarray, np.ndarray]] = {}


def _vocab_table(feat) -> tuple[np.ndarray, np.ndarray]:
    """``(sorted_vocab, ids_of_sorted)`` for one categorical feature."""
    key = (feat.name, feat.vocab)
    table = _VOCAB_TABLES.get(key)
    if table is None:
        vocab = np.asarray(feat.vocab)
        order = np.argsort(vocab)
        table = (vocab[order], order.astype(np.int32))
        _VOCAB_TABLES[key] = table
    return table


@dataclasses.dataclass
class EncodedDataset:
    """Fixed-shape encoded dataset ready for device placement."""

    cat_ids: np.ndarray  # int32 [N, num_categorical]
    numeric: np.ndarray  # float32 [N, num_numeric], standardized
    labels: np.ndarray | None = None  # int8/float32 [N]

    @property
    def n(self) -> int:
        return self.cat_ids.shape[0]

    def slice(self, idx: np.ndarray) -> "EncodedDataset":
        return EncodedDataset(
            cat_ids=self.cat_ids[idx],
            numeric=self.numeric[idx],
            labels=None if self.labels is None else self.labels[idx],
        )


@dataclasses.dataclass
class Preprocessor:
    """Fitted preprocessing state. ``fit`` -> ``encode`` -> arrays."""

    numeric_median: np.ndarray  # float32 [num_numeric]
    numeric_mean: np.ndarray  # float32 [num_numeric]
    numeric_std: np.ndarray  # float32 [num_numeric]
    schema_fingerprint: str

    # ------------------------------------------------------------------ fit
    @classmethod
    def fit(
        cls, columns: dict[str, list], schema: FeatureSchema = SCHEMA
    ) -> "Preprocessor":
        medians, means, stds = [], [], []
        for feat in schema.numeric:
            raw = np.asarray(columns[feat.name], dtype=np.float64)
            finite = raw[np.isfinite(raw)]
            median = float(np.median(finite)) if finite.size else 0.0
            filled = np.where(np.isfinite(raw), raw, median)
            mean = float(filled.mean()) if filled.size else 0.0
            std = float(filled.std()) if filled.size else 1.0
            medians.append(median)
            means.append(mean)
            stds.append(std if std > 1e-12 else 1.0)
        return cls(
            numeric_median=np.asarray(medians, dtype=np.float32),
            numeric_mean=np.asarray(means, dtype=np.float32),
            numeric_std=np.asarray(stds, dtype=np.float32),
            schema_fingerprint=schema.fingerprint(),
        )

    # --------------------------------------------------------------- encode
    def encode(
        self,
        columns: dict[str, list],
        labels: np.ndarray | None = None,
        schema: FeatureSchema = SCHEMA,
    ) -> EncodedDataset:
        n = len(next(iter(columns.values())))
        cat_ids = np.empty((n, schema.num_categorical), dtype=np.int32)
        for j, feat in enumerate(schema.categorical):
            sorted_vocab, sorted_ids = _vocab_table(feat)
            # Vectorized vocab lookup: binary-search the sorted vocab and
            # verify the hit; misses (unseen value, "", non-string coerced
            # by str()) take the OOV id — same semantics as the dict probe
            # this replaces, at array speed. The column keeps its own
            # string width (casting to the vocab's would truncate long
            # unseen values into false hits).
            raw = np.asarray(columns[feat.name], dtype=np.str_)
            pos = np.minimum(
                np.searchsorted(sorted_vocab, raw), sorted_vocab.size - 1
            )
            cat_ids[:, j] = np.where(
                sorted_vocab[pos] == raw, sorted_ids[pos], feat.oov_id
            )

        numeric = np.empty((n, schema.num_numeric), dtype=np.float32)
        for j, feat in enumerate(schema.numeric):
            raw = np.asarray(columns[feat.name], dtype=np.float32)
            raw = np.where(np.isfinite(raw), raw, self.numeric_median[j])
            numeric[:, j] = (raw - self.numeric_mean[j]) / self.numeric_std[j]

        return EncodedDataset(
            cat_ids=cat_ids,
            numeric=numeric,
            labels=None if labels is None else np.asarray(labels),
        )

    # ------------------------------------------------------------ serialize
    def to_arrays(self) -> dict[str, np.ndarray]:
        return {
            "numeric_median": self.numeric_median,
            "numeric_mean": self.numeric_mean,
            "numeric_std": self.numeric_std,
            "schema_fingerprint": np.frombuffer(
                self.schema_fingerprint.encode(), dtype=np.uint8
            ),
        }

    @classmethod
    def from_arrays(cls, arrays: dict[str, np.ndarray]) -> "Preprocessor":
        return cls(
            numeric_median=np.asarray(arrays["numeric_median"], dtype=np.float32),
            numeric_mean=np.asarray(arrays["numeric_mean"], dtype=np.float32),
            numeric_std=np.asarray(arrays["numeric_std"], dtype=np.float32),
            schema_fingerprint=bytes(
                np.asarray(arrays["schema_fingerprint"], dtype=np.uint8)
            ).decode(),
        )

    def save(self, path: str | Path) -> None:
        np.savez(Path(path).with_suffix(".npz"), **self.to_arrays())

    @classmethod
    def load(cls, path: str | Path) -> "Preprocessor":
        with np.load(Path(path).with_suffix(".npz")) as data:
            return cls.from_arrays({k: data[k] for k in data.files})
