"""Out-of-core CSV streaming: chunked ingest, mergeable stats, stream scoring.

The reference delegates bigger-than-memory data to Spark (external table +
``spark.read.table``, `00-create-external-table.ipynb:92-95`); this module is
the framework-native answer: a dataset is consumed as fixed-size row chunks,
preprocessing statistics accumulate in ONE pass with bounded memory, and bulk
scoring streams chunk -> encode -> device -> append-to-output without ever
holding the dataset.

Statistics design (single pass, exact where it matters):

- mean/std: the batch fit standardizes MEDIAN-IMPUTED values. Streaming
  keeps per-feature SHIFTED sums ``(count_finite, sum(x-s), sum((x-s)^2),
  count_missing)`` with ``s`` = the first finite value seen — the shift
  kills the catastrophic cancellation a raw ``E[x^2]-E[x]^2`` suffers on
  large-magnitude features (mean ~1e8, std ~1 would otherwise collapse to
  std=1 silently). Once the median is known the imputed moments close
  exactly in shifted space — no second pass.
- median: exact only with the full sample, so a uniform RESERVOIR (default
  100k values/feature) stands in; for datasets at or under the reservoir
  size the result is exactly the batch fit's.

Chunk semantics share the batch reader's parsing helpers
(`data/ingest.py` ``rows_to_columns``/``parse_labels``, themselves
parity-tested against the native C++ kernel): blank lines skipped, short
rows read missing cells as empty (-> OOV / median). Labels are parsed only
under ``require_target=True`` and fail fast on corrupt values — the
streaming consumers (fit, scoring) are feature-only, and a permissive
per-chunk label parse could not honor the batch reader's
one-bad-value-unlabels-the-FILE contract without lookahead.
"""

from __future__ import annotations

import csv
from pathlib import Path
from typing import Iterator

import numpy as np

from mlops_tpu.data.encode import Preprocessor
from mlops_tpu.data.ingest import fetch_local, parse_labels, rows_to_columns
from mlops_tpu.schema.features import SCHEMA, FeatureSchema


def iter_csv_chunks(
    path: str | Path,
    chunk_rows: int = 65_536,
    schema: FeatureSchema = SCHEMA,
    require_target: bool = False,
) -> Iterator[tuple[dict[str, list], np.ndarray | None]]:
    """Yield ``(columns, labels)`` chunks of at most ``chunk_rows`` rows.

    Labels are parsed (strictly) only when ``require_target=True``;
    otherwise every chunk yields ``labels=None`` — see module docstring.
    Accepts local paths and ``gs://`` URIs (staged through the same cache
    as the batch reader). Memory is bounded by one chunk.
    """
    with fetch_local(path).open(newline="") as f:
        reader = csv.reader(f)
        header = next(reader)
        col_index = {name: i for i, name in enumerate(header)}
        missing = [n for n in schema.feature_names if n not in col_index]
        if missing:
            raise ValueError(f"{path}: missing required columns {missing}")
        if require_target and schema.target not in col_index:
            raise ValueError(f"{path}: missing target column {schema.target!r}")

        def emit(rows: list, base_row: int):
            columns = rows_to_columns(rows, col_index, schema)
            labels = (
                parse_labels(rows, col_index, schema, path, base_row)
                if require_target
                else None
            )
            return columns, labels

        buffer: list = []
        seen = 0
        for row in reader:
            if not row or row == [""]:
                continue
            buffer.append(row)
            if len(buffer) >= chunk_rows:
                yield emit(buffer, seen)
                seen += len(buffer)
                buffer = []
        if buffer:
            yield emit(buffer, seen)


def iter_table_chunks(
    path: str | Path,
    chunk_rows: int = 65_536,
    schema: FeatureSchema = SCHEMA,
    require_target: bool = False,
) -> Iterator[tuple[dict[str, list], np.ndarray | None]]:
    """Format-dispatching chunk iterator: Parquet files stream through
    ``parquet.iter_parquet_chunks`` (exact-size re-buffered chunks),
    everything else through ``iter_csv_chunks``. Same yielded contract."""
    from mlops_tpu.data import parquet

    if parquet.is_parquet(path):
        return parquet.iter_parquet_chunks(path, chunk_rows, schema, require_target)
    return iter_csv_chunks(path, chunk_rows, schema, require_target)


class StreamingStats:
    """Mergeable single-pass accumulator for the Preprocessor's fit.

    ``update(columns)`` per chunk, then ``finalize()`` -> Preprocessor.
    """

    def __init__(
        self,
        schema: FeatureSchema = SCHEMA,
        reservoir_size: int = 100_000,
        seed: int = 0,
    ):
        self.schema = schema
        m = schema.num_numeric
        self._count = np.zeros(m, np.int64)  # finite values
        self._missing = np.zeros(m, np.int64)
        self._shift = np.full(m, np.nan)  # first finite value per feature
        self._sum_d = np.zeros(m, np.float64)  # sum of (x - shift)
        self._sumsq_d = np.zeros(m, np.float64)  # sum of (x - shift)^2
        self._reservoirs: list[np.ndarray] = [
            np.empty(0, np.float64) for _ in range(m)
        ]
        self._reservoir_size = reservoir_size
        self._rng = np.random.default_rng(seed)

    def update(self, columns: dict[str, list]) -> None:
        for j, feat in enumerate(self.schema.numeric):
            raw = np.asarray(columns[feat.name], dtype=np.float64)
            finite = raw[np.isfinite(raw)]
            self._missing[j] += raw.size - finite.size
            if finite.size and np.isnan(self._shift[j]):
                self._shift[j] = finite[0]
            if finite.size:
                d = finite - self._shift[j]
                self._sum_d[j] += d.sum()
                self._sumsq_d[j] += np.square(d).sum()
            self._reservoirs[j] = self._fold_reservoir(
                self._reservoirs[j], finite, self._count[j]
            )
            self._count[j] += finite.size

    def _fold_reservoir(
        self, reservoir: np.ndarray, values: np.ndarray, seen: int
    ) -> np.ndarray:
        """Uniform reservoir over the stream: every value seen so far has
        equal probability of residing in the sample (Vitter's Algorithm R,
        vectorized per chunk)."""
        k = self._reservoir_size
        if reservoir.size < k:
            taken = min(k - reservoir.size, values.size)
            reservoir = np.concatenate([reservoir, values[:taken]])
            values = values[taken:]
            seen += taken
        if values.size == 0:
            return reservoir
        # For the i-th remaining value (global index seen+i), replace a
        # random slot with probability k / (seen+i+1).
        idx = seen + 1 + np.arange(values.size, dtype=np.float64)
        accept = self._rng.random(values.size) < (k / idx)
        slots = self._rng.integers(0, k, size=values.size)
        for v, s in zip(values[accept], slots[accept]):
            reservoir[s] = v
        return reservoir

    def finalize(self) -> Preprocessor:
        medians, means, stds = [], [], []
        for j in range(self.schema.num_numeric):
            reservoir = self._reservoirs[j]
            median = float(np.median(reservoir)) if reservoir.size else 0.0
            n = self._count[j] + self._missing[j]
            if n == 0:
                means.append(0.0)
                stds.append(1.0)
                medians.append(median)
                continue
            shift = self._shift[j] if np.isfinite(self._shift[j]) else 0.0
            med_d = median - shift
            mean_d = (self._sum_d[j] + self._missing[j] * med_d) / n
            ex2_d = (self._sumsq_d[j] + self._missing[j] * med_d**2) / n
            mean = shift + mean_d
            var = max(ex2_d - mean_d**2, 0.0)
            std = float(np.sqrt(var))
            medians.append(median)
            means.append(float(mean))
            stds.append(std if std > 1e-12 else 1.0)
        return Preprocessor(
            numeric_median=np.asarray(medians, np.float32),
            numeric_mean=np.asarray(means, np.float32),
            numeric_std=np.asarray(stds, np.float32),
            schema_fingerprint=self.schema.fingerprint(),
        )


def fit_streaming(
    path: str | Path,
    chunk_rows: int = 65_536,
    schema: FeatureSchema = SCHEMA,
    reservoir_size: int = 100_000,
    seed: int = 0,
) -> Preprocessor:
    """One-pass Preprocessor fit over an arbitrarily large CSV/Parquet."""
    stats = StreamingStats(schema, reservoir_size=reservoir_size, seed=seed)
    for columns, _ in iter_table_chunks(path, chunk_rows, schema):
        stats.update(columns)
    return stats.finalize()


def score_csv_stream(
    bundle,
    in_path: str | Path,
    out_path: str | Path | None = None,
    chunk_rows: int = 65_536,
    mesh=None,
    exact: bool | None = None,
) -> dict[str, float]:
    """Stream-score a CSV/Parquet of any size through the bundle's fused
    predict.

    chunk -> encode -> ONE device dispatch (classifier + outliers) ->
    append ``prediction,outlier`` rows to ``out_path``. Peak memory is one
    chunk; the dataset never materializes. With a ``mesh``, each chunk is
    data-parallel over the 'data' axis (chunk size rounds up so the batch
    divides the axis). Returns aggregate stats.
    """
    import contextlib

    from mlops_tpu.parallel.bulk import make_chunk_scorer, use_distilled_bulk

    if mesh is not None:
        axis = mesh.shape["data"]
        chunk_rows = ((chunk_rows + axis - 1) // axis) * axis
    # Same routing contract as score_dataset: ``exact=None`` auto-routes
    # through the distilled bulk student on CPU backends; the returned
    # stats carry ``path`` so the substitution is always visible.
    path_used = "distilled" if use_distilled_bulk(bundle, exact) else "exact"
    score_chunk = make_chunk_scorer(bundle, mesh=mesh, exact=exact)
    rows = 0
    outlier_count = 0.0
    prob_sum = 0.0
    writer = None
    with contextlib.ExitStack() as stack:
        if out_path is not None:
            out_path = Path(out_path)
            out_path.parent.mkdir(parents=True, exist_ok=True)
            f = stack.enter_context(out_path.open("w", newline=""))
            writer = csv.writer(f)
            writer.writerow(["prediction", "outlier"])
        for columns, _ in iter_table_chunks(in_path, chunk_rows):
            ds = bundle.preprocessor.encode(columns)
            n = ds.n
            # Pad to the fixed chunk shape so one compiled program serves
            # every chunk (the tail chunk is the only padded one).
            pad = chunk_rows - n
            cat = np.pad(ds.cat_ids, ((0, pad), (0, 0))) if pad else ds.cat_ids
            num = np.pad(ds.numeric, ((0, pad), (0, 0))) if pad else ds.numeric
            mask = np.arange(chunk_rows) < n
            probs, outliers = score_chunk(cat, num, mask)
            probs = np.asarray(probs)[:n]
            outliers = np.asarray(outliers)[:n]
            rows += n
            outlier_count += float(outliers.sum())
            prob_sum += float(probs.sum())
            if writer is not None:
                writer.writerows(
                    zip(np.round(probs, 6).tolist(), outliers.tolist())
                )
    return {
        "rows": rows,
        "path": path_used,
        "mean_prediction": prob_sum / max(rows, 1),
        "outlier_rate": outlier_count / max(rows, 1),
    }
