"""Out-of-core CSV streaming: chunked ingest, mergeable stats, stream scoring.

The reference delegates bigger-than-memory data to Spark (external table +
``spark.read.table``, `00-create-external-table.ipynb:92-95`); this module is
the framework-native answer: a dataset is consumed as fixed-size row chunks,
preprocessing statistics accumulate in ONE pass with bounded memory, and bulk
scoring streams chunk -> encode -> device -> append-to-output without ever
holding the dataset.

Statistics design (single pass, exact where it matters):

- mean/std: the batch fit standardizes MEDIAN-IMPUTED values. Streaming
  keeps per-feature SHIFTED sums ``(count_finite, sum(x-s), sum((x-s)^2),
  count_missing)`` with ``s`` = the first finite value seen — the shift
  kills the catastrophic cancellation a raw ``E[x^2]-E[x]^2`` suffers on
  large-magnitude features (mean ~1e8, std ~1 would otherwise collapse to
  std=1 silently). Once the median is known the imputed moments close
  exactly in shifted space — no second pass.
- median: exact only with the full sample, so a uniform RESERVOIR (default
  100k values/feature) stands in; for datasets at or under the reservoir
  size the result is exactly the batch fit's.

Chunk semantics share the batch reader's parsing helpers
(`data/ingest.py` ``rows_to_columns``/``parse_labels``, themselves
parity-tested against the native C++ kernel): blank lines skipped, short
rows read missing cells as empty (-> OOV / median). Labels are parsed only
under ``require_target=True`` and fail fast on corrupt values — the
streaming consumers (fit, scoring) are feature-only, and a permissive
per-chunk label parse could not honor the batch reader's
one-bad-value-unlabels-the-FILE contract without lookahead.
"""

from __future__ import annotations

import csv
from pathlib import Path
from typing import Iterator

import numpy as np

from mlops_tpu.data.encode import Preprocessor
from mlops_tpu.data.ingest import fetch_local, parse_labels, rows_to_columns
from mlops_tpu.schema.features import SCHEMA, FeatureSchema


def iter_csv_chunks(
    path: str | Path,
    chunk_rows: int = 65_536,
    schema: FeatureSchema = SCHEMA,
    require_target: bool = False,
) -> Iterator[tuple[dict[str, list], np.ndarray | None]]:
    """Yield ``(columns, labels)`` chunks of at most ``chunk_rows`` rows.

    Labels are parsed (strictly) only when ``require_target=True``;
    otherwise every chunk yields ``labels=None`` — see module docstring.
    Accepts local paths and ``gs://`` URIs (staged through the same cache
    as the batch reader). Memory is bounded by one chunk.
    """
    with fetch_local(path).open(newline="") as f:
        reader = csv.reader(f)
        header = next(reader)
        col_index = _validated_col_index(header, path, schema, require_target)

        def emit(rows: list, base_row: int):
            columns = rows_to_columns(rows, col_index, schema)
            labels = (
                parse_labels(rows, col_index, schema, path, base_row)
                if require_target
                else None
            )
            return columns, labels

        buffer: list = []
        seen = 0
        for row in reader:
            if not row or row == [""]:
                continue
            buffer.append(row)
            if len(buffer) >= chunk_rows:
                yield emit(buffer, seen)
                seen += len(buffer)
                buffer = []
        if buffer:
            yield emit(buffer, seen)


def _validated_col_index(header_fields: list[str], path, schema, require_target):
    col_index = {name: i for i, name in enumerate(header_fields)}
    missing = [n for n in schema.feature_names if n not in col_index]
    if missing:
        raise ValueError(f"{path}: missing required columns {missing}")
    if require_target and schema.target not in col_index:
        raise ValueError(f"{path}: missing target column {schema.target!r}")
    return col_index


_READ_BYTES = 4 << 20  # reader granularity; several chunks per read


def iter_raw_csv_chunks(
    path: str | Path,
    chunk_rows: int = 65_536,
    schema: FeatureSchema = SCHEMA,
) -> Iterator[tuple[str, object]]:
    """Byte-level chunk reader for the native-encode streaming path.

    Yields ``("bytes", header + rows_block)`` items of at most
    ``chunk_rows`` records each, split at newline boundaries that are
    verified record-safe: the fast split is only sound while the bytes
    contain no double quotes (an RFC-4180 quoted field may embed
    newlines) and no bare-CR record terminators. The moment a block trips
    either check, the reader degrades PERMANENTLY to the csv-module
    parser for the rest of the stream, yielding ``("columns", columns)``
    items instead — correctness over speed, decided per run, invisible to
    the consumer because the encode stage accepts both forms.

    Feature-only contract (labels are never parsed): this reader serves
    ``score_csv_stream``, whose consumers ignore the target column.
    """
    with fetch_local(path).open("rb") as f:
        header = f.readline()
        header_fields = next(csv.reader([header.decode()]))
        col_index = _validated_col_index(
            header_fields, path, schema, require_target=False
        )
        # Each read block is scanned ONCE (quote / bare-CR / newline
        # counts); blocks accumulate in a list and are joined only when a
        # chunk's worth of records is present — no quadratic re-scan of
        # the leftover when chunk_rows spans many read blocks.
        pending: list[bytes] = []
        pending_newlines = 0
        hold = b""  # trailing CR held back: may be half of a CRLF split
        # across reads, which would trip the bare-CR check
        while True:
            block = f.read(_READ_BYTES)
            if not block:
                break
            block = hold + block
            hold = b""
            if block.endswith(b"\r"):
                block, hold = block[:-1], block[-1:]
            if b'"' in block or block.count(b"\r") != block.count(b"\r\n"):
                rest = b"".join(pending) + block + hold
                yield from _python_tail_chunks(
                    col_index, rest, f, chunk_rows, schema
                )
                return
            pending.append(block)
            pending_newlines += block.count(b"\n")
            if pending_newlines >= chunk_rows:
                buf = b"".join(pending)
                newlines = np.flatnonzero(
                    np.frombuffer(buf, np.uint8) == 0x0A
                )
                start = 0
                taken = 0
                while newlines.size - taken >= chunk_rows:
                    end = int(newlines[taken + chunk_rows - 1]) + 1
                    yield ("bytes", header + buf[start:end])
                    start = end
                    taken += chunk_rows
                pending = [buf[start:]] if start < len(buf) else []
                pending_newlines = int(newlines.size) - taken
        tail = b"".join(pending) + hold
        if tail.strip(b"\r\n"):
            yield ("bytes", header + tail)


def _python_tail_chunks(
    col_index, buf: bytes, f, chunk_rows, schema
) -> Iterator[tuple[str, object]]:
    """Degraded continuation of ``iter_raw_csv_chunks``: csv-parse the
    remaining stream (already-buffered bytes + the rest of the file),
    preserving line terminators so quoted embedded newlines survive."""

    def byte_lines():
        import itertools

        carry = b""
        blocks = iter(lambda: f.read(_READ_BYTES), b"")
        for block in itertools.chain([buf], blocks):
            carry += block
            lines = carry.splitlines(keepends=True)
            carry = b""
            if lines:
                # The final piece may be a partial line (no terminator) or
                # end in a CR that could be half of a CRLF — carry it.
                if not lines[-1].endswith((b"\n", b"\r")) or lines[-1].endswith(
                    b"\r"
                ):
                    carry = lines.pop()
            yield from lines
        if carry:
            yield carry

    reader = csv.reader(line.decode() for line in byte_lines())
    buffer: list = []
    for row in reader:
        if not row or row == [""]:
            continue
        buffer.append(row)
        if len(buffer) >= chunk_rows:
            yield ("columns", rows_to_columns(buffer, col_index, schema))
            buffer = []
    if buffer:
        yield ("columns", rows_to_columns(buffer, col_index, schema))


def iter_table_chunks(
    path: str | Path,
    chunk_rows: int = 65_536,
    schema: FeatureSchema = SCHEMA,
    require_target: bool = False,
) -> Iterator[tuple[dict[str, list], np.ndarray | None]]:
    """Format-dispatching chunk iterator: Parquet files stream through
    ``parquet.iter_parquet_chunks`` (exact-size re-buffered chunks),
    everything else through ``iter_csv_chunks``. Same yielded contract."""
    from mlops_tpu.data import parquet

    if parquet.is_parquet(path):
        return parquet.iter_parquet_chunks(path, chunk_rows, schema, require_target)
    return iter_csv_chunks(path, chunk_rows, schema, require_target)


class StreamingStats:
    """Mergeable single-pass accumulator for the Preprocessor's fit.

    ``update(columns)`` per chunk, then ``finalize()`` -> Preprocessor.
    """

    def __init__(
        self,
        schema: FeatureSchema = SCHEMA,
        reservoir_size: int = 100_000,
        seed: int = 0,
    ):
        self.schema = schema
        m = schema.num_numeric
        self._count = np.zeros(m, np.int64)  # finite values
        self._missing = np.zeros(m, np.int64)
        self._shift = np.full(m, np.nan)  # first finite value per feature
        self._sum_d = np.zeros(m, np.float64)  # sum of (x - shift)
        self._sumsq_d = np.zeros(m, np.float64)  # sum of (x - shift)^2
        self._reservoirs: list[np.ndarray] = [
            np.empty(0, np.float64) for _ in range(m)
        ]
        self._reservoir_size = reservoir_size
        self._rng = np.random.default_rng(seed)

    def update(self, columns: dict[str, list]) -> None:
        self.update_arrays(
            [
                np.asarray(columns[feat.name], dtype=np.float64)
                for feat in self.schema.numeric
            ]
        )

    def update_arrays(self, raws: list[np.ndarray]) -> None:
        """Fold one chunk given per-numeric-feature float64 arrays (in
        schema order). The list-of-columns conversion is split out so the
        pipelined fit (`fit_streaming`) can run it on a worker thread
        while this fold — which must stay sequential for the reservoir
        RNG — runs on the sink."""
        for j, raw in enumerate(raws):
            finite = raw[np.isfinite(raw)]
            self._missing[j] += raw.size - finite.size
            if finite.size and np.isnan(self._shift[j]):
                self._shift[j] = finite[0]
            if finite.size:
                d = finite - self._shift[j]
                self._sum_d[j] += d.sum()
                self._sumsq_d[j] += np.square(d).sum()
            self._reservoirs[j] = self._fold_reservoir(
                self._reservoirs[j], finite, self._count[j]
            )
            self._count[j] += finite.size

    def _fold_reservoir(
        self, reservoir: np.ndarray, values: np.ndarray, seen: int
    ) -> np.ndarray:
        """Uniform reservoir over the stream: every value seen so far has
        equal probability of residing in the sample (Vitter's Algorithm R,
        vectorized per chunk)."""
        k = self._reservoir_size
        if reservoir.size < k:
            taken = min(k - reservoir.size, values.size)
            reservoir = np.concatenate([reservoir, values[:taken]])
            values = values[taken:]
            seen += taken
        if values.size == 0:
            return reservoir
        # For the i-th remaining value (global index seen+i), replace a
        # random slot with probability k / (seen+i+1).
        idx = seen + 1 + np.arange(values.size, dtype=np.float64)
        accept = self._rng.random(values.size) < (k / idx)
        slots = self._rng.integers(0, k, size=values.size)
        sel_slots = slots[accept]
        if sel_slots.size:
            # Vectorized scatter with explicit last-write-wins on duplicate
            # slots (bit-identical to the per-value loop it replaces):
            # np.unique over the REVERSED slot array returns, per unique
            # slot, the index of its last occurrence in stream order.
            sel_values = values[accept]
            unique_slots, last_in_reversed = np.unique(
                sel_slots[::-1], return_index=True
            )
            reservoir[unique_slots] = sel_values[::-1][last_in_reversed]
        return reservoir

    def finalize(self) -> Preprocessor:
        medians, means, stds = [], [], []
        for j in range(self.schema.num_numeric):
            reservoir = self._reservoirs[j]
            median = float(np.median(reservoir)) if reservoir.size else 0.0
            n = self._count[j] + self._missing[j]
            if n == 0:
                means.append(0.0)
                stds.append(1.0)
                medians.append(median)
                continue
            shift = self._shift[j] if np.isfinite(self._shift[j]) else 0.0
            med_d = median - shift
            mean_d = (self._sum_d[j] + self._missing[j] * med_d) / n
            ex2_d = (self._sumsq_d[j] + self._missing[j] * med_d**2) / n
            mean = shift + mean_d
            var = max(ex2_d - mean_d**2, 0.0)
            std = float(np.sqrt(var))
            medians.append(median)
            means.append(float(mean))
            stds.append(std if std > 1e-12 else 1.0)
        return Preprocessor(
            numeric_median=np.asarray(medians, np.float32),
            numeric_mean=np.asarray(means, np.float32),
            numeric_std=np.asarray(stds, np.float32),
            schema_fingerprint=self.schema.fingerprint(),
        )


def fit_streaming(
    path: str | Path,
    chunk_rows: int = 65_536,
    schema: FeatureSchema = SCHEMA,
    reservoir_size: int = 100_000,
    seed: int = 0,
    pipeline_depth: int = 1,
) -> Preprocessor:
    """One-pass Preprocessor fit over an arbitrarily large CSV/Parquet.

    ``pipeline_depth > 1`` overlaps chunk read+parse and the list->float64
    conversion with the sequential moment/reservoir fold on background
    threads (`data/pipeline_exec.py`); depth 1 is the serial loop. The
    fold order is preserved either way, so the fitted Preprocessor is
    bit-identical at any depth.
    """
    from mlops_tpu.data.pipeline_exec import Stage, run_pipeline

    stats = StreamingStats(schema, reservoir_size=reservoir_size, seed=seed)
    names = [feat.name for feat in schema.numeric]

    def to_float_arrays(item):
        columns, _ = item
        return [np.asarray(columns[name], dtype=np.float64) for name in names]

    run_pipeline(
        iter_table_chunks(path, chunk_rows, schema),
        [Stage("tofloat", to_float_arrays)],
        stats.update_arrays,
        depth=pipeline_depth,
        sink_name="fold",
    )
    return stats.finalize()


def score_csv_stream(
    bundle,
    in_path: str | Path,
    out_path: str | Path | None = None,
    chunk_rows: int = 65_536,
    mesh=None,
    exact: bool | None = None,
    pipeline_depth: int = 2,
    native: bool | None = None,
    compile_cache=None,
    stage_sink=None,
) -> dict[str, float]:
    """Stream-score a CSV/Parquet of any size through the bundle's fused
    predict.

    Stage graph (`data/pipeline_exec.py`): read+parse -> vectorized
    encode(+pad) -> device transfer -> ONE device dispatch (classifier +
    outliers) -> batched result fetch -> append ``prediction,outlier``
    rows to ``out_path``. At ``pipeline_depth=1`` the stages run serially
    on the caller thread (the pre-pipeline behavior, bit-identical
    output); at depth D they overlap on bounded queues — chunk N+1
    transfers while chunk N computes and chunk N-1's results fetch — with
    peak memory fixed at a few chunks. With a ``mesh``, each chunk is
    data-parallel over the 'data' axis (chunk size rounds up so the batch
    divides the axis). Returns aggregate stats including per-stage
    busy/occupancy timings and post-warmup ``rows_per_s``.

    Failure safety: output is written to a ``.tmp`` sibling and renamed
    into place only on success, so a mid-stream exception (which drains
    the pipeline and propagates) never leaves a partial file behind
    looking like a finished run.

    ``stage_sink`` (tracewire): a `TraceRecorder.stage_sink` callable —
    every stage execution additionally lands as a kind="stage" record in
    the span JSONL (`mlops-tpu score-batch score.streaming=true
    trace.enabled=true`).
    """
    import contextlib

    from mlops_tpu.data.pipeline_exec import Stage, run_pipeline
    from mlops_tpu.parallel.bulk import (
        FETCH_WAVE,
        make_chunk_scorer,
        make_chunk_transfer,
        mesh_chunk_rows,
        use_distilled_bulk,
    )

    chunk_rows = mesh_chunk_rows(chunk_rows, mesh)
    # Same routing contract as score_dataset: ``exact=None`` auto-routes
    # through the distilled bulk student on CPU backends; the returned
    # stats carry ``path`` so the substitution is always visible.
    path_used = "distilled" if use_distilled_bulk(bundle, exact) else "exact"
    score_chunk = make_chunk_scorer(
        bundle,
        mesh=mesh,
        exact=exact,
        compile_cache=compile_cache,
        chunk_rows=chunk_rows,
    )
    transfer = make_chunk_transfer(bundle, mesh)
    # cat ids narrow to int8 on the device path (max vocab cardinality is
    # 12; lossless, and host->device bytes are the transfer bottleneck on
    # remote-attached chips) — same convention as score_dataset.
    narrow = None if bundle.flavor == "sklearn" else np.int8

    # Warm the one compiled chunk program before the streamed (and timed)
    # run, so ``rows_per_s`` measures streaming, not a one-off compile.
    if bundle.flavor != "sklearn":
        import jax

        warm_cat = np.zeros((chunk_rows, SCHEMA.num_categorical), np.int8)
        warm_num = np.zeros((chunk_rows, SCHEMA.num_numeric), np.float32)
        jax.block_until_ready(
            score_chunk(warm_cat, warm_num, np.arange(chunk_rows) < 1)[0]
        )

    # Source + encode selection: when the native C++ kernel is available
    # and the input is CSV, the reader yields raw byte blocks and the
    # encode stage parses+encodes them in ONE ctypes call that RELEASES
    # the GIL — so encode genuinely overlaps the GIL-bound read/write
    # stages and the device compute (on CPU backends the Python csv parse
    # would otherwise serialize the whole pipeline on the GIL). Output is
    # parity-pinned bit-identical to the Python path (tests/test_native.py).
    # ``native=None`` auto-detects; ``False`` forces the Python csv parse
    # (the pre-executor serial baseline — bench uses it for before/after).
    from mlops_tpu.data import parquet
    from mlops_tpu.native import encode_csv_bytes, native_available

    prep = bundle.preprocessor
    use_native = (
        native is not False
        and native_available()
        and not parquet.is_parquet(in_path)
    )
    if use_native:
        source = iter_raw_csv_chunks(in_path, chunk_rows)
    else:
        source = (
            ("columns", columns)
            for columns, _ in iter_table_chunks(in_path, chunk_rows)
        )

    # Hoisted mask: every full chunk shares ONE all-true mask; only the
    # tail chunk builds a fresh one from the hoisted arange.
    base_index = np.arange(chunk_rows)
    full_mask = np.ones(chunk_rows, bool)

    def encode_chunk(item):
        kind, payload = item
        ds = (
            encode_csv_bytes(payload, prep, source=str(in_path))
            if kind == "bytes"
            else prep.encode(payload)
        )
        n = ds.n
        cat = ds.cat_ids if narrow is None else ds.cat_ids.astype(narrow)
        # Pad to the fixed chunk shape so one compiled program serves
        # every chunk (the tail chunk is the only padded one; byte-split
        # chunks may also run short when blank lines were skipped).
        pad = chunk_rows - n
        if pad:
            cat = np.pad(cat, ((0, pad), (0, 0)))
            num = np.pad(ds.numeric, ((0, pad), (0, 0)))
            mask = base_index < n
        else:
            num = ds.numeric
            mask = full_mask
        return cat, num, mask, n

    def transfer_chunk(item):
        cat, num, mask, n = item
        return (*transfer(cat, num, mask), n)

    def compute_chunk(item):
        cat, num, mask, n = item
        probs, outliers = score_chunk(cat, num, mask)
        return probs, outliers, n

    def fetch_chunks(items):
        import jax

        fetched = jax.device_get([(probs, flags) for probs, flags, _ in items])
        return [
            (np.asarray(probs)[:n], np.asarray(flags)[:n])
            for (probs, flags), (_, _, n) in zip(fetched, items)
        ]

    rows = 0
    outlier_count = 0.0
    prob_sum = 0.0
    writer = None

    def write_chunk(item):
        nonlocal rows, outlier_count, prob_sum
        probs, outliers = item
        rows += probs.size
        outlier_count += float(outliers.sum())
        prob_sum += float(probs.sum())
        if writer is not None:
            writer.writerows(
                zip(np.round(probs, 6).tolist(), outliers.tolist())
            )

    tmp_path = None
    try:
        with contextlib.ExitStack() as stack:
            if out_path is not None:
                out_path = Path(out_path)
                out_path.parent.mkdir(parents=True, exist_ok=True)
                tmp_path = out_path.with_name(out_path.name + ".tmp")
                f = stack.enter_context(tmp_path.open("w", newline=""))
                writer = csv.writer(f)
                writer.writerow(["prediction", "outlier"])
            pipe = run_pipeline(
                source,
                [
                    Stage("encode", encode_chunk),
                    Stage("transfer", transfer_chunk),
                    Stage("compute", compute_chunk),
                    # Deep fetch input queue = the async-dispatch wave:
                    # compute runs ahead and one batched device_get
                    # drains it (see parallel/bulk.py FETCH_WAVE).
                    # batch_max >= 2 keeps fetch list-in/list-out even
                    # at depth 1.
                    Stage(
                        "fetch",
                        fetch_chunks,
                        batch_max=FETCH_WAVE,
                        queue_depth=FETCH_WAVE,
                    ),
                ],
                write_chunk,
                depth=pipeline_depth,
                stage_sink=stage_sink,
            )
        if tmp_path is not None:
            tmp_path.replace(out_path)
    except BaseException:
        if tmp_path is not None:
            tmp_path.unlink(missing_ok=True)
        raise
    return {
        "rows": rows,
        "path": path_used,
        "mean_prediction": prob_sum / max(rows, 1),
        "outlier_rate": outlier_count / max(rows, 1),
        "pipeline_depth": pipe.depth,
        "elapsed_s": round(pipe.wall_s, 4),
        "rows_per_s": round(rows / max(pipe.wall_s, 1e-9), 1),
        "stages": pipe.stages,
        **(
            {"compile_cache": compile_cache.stats()}
            if compile_cache is not None
            else {}
        ),
    }
