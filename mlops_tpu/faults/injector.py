"""faultline: deterministic, config-driven fault injection.

Production serving code is full of recovery paths — compile-cache
corruption discards, deadline 504s, degraded bucket fallback, tmp+rename
persistence, circuit breakers — that ordinary traffic never exercises.
This module makes every one of them drivable ON DEMAND: code declares
NAMED INJECTION POINTS (``faults.fire("serve.engine.dispatch")``,
``raw = faults.corrupt("compilecache.read", raw)``) and a seeded
``FaultPlan`` decides, deterministically, which hits of which points do
what:

- ``raise``   — raise a named exception (device error, OSError, ...)
- ``delay``   — sleep ``delay_s`` (an engine stall / slow tunnel)
- ``kill``    — SIGKILL this process mid-operation (torn-write proofs)
- ``corrupt`` — flip seeded bits in the bytes passing a read point

Determinism is the contract: a plan is (rules, seed), every point keeps
a per-process hit counter, and each decision hashes
``(seed, rule, point, hit_index)`` — so the same seed + scenario +
request order produces the IDENTICAL injection trace (recorded, and
pinned by tests/test_faults.py). No global RNG is touched.

Arming:

- programmatic: ``faults.arm(FaultPlan.from_rules([...], seed=...))``
  (tests), ``faults.disarm()`` to restore the no-op state;
- config: ``faults.arm(load_plan("chaos.toml"))``;
- environment: ``MLOPS_TPU_FAULTS=/path/to/chaos.toml`` arms at import
  time in EVERY process that imports this module — the chaos smoke
  arms a whole forked serve plane (engine + front ends) with one env
  var, no code changes.

Zero overhead disarmed: the module-level plan is ``None`` and both
entry points return after one global load + identity check — the bench
pins the armed-off cost as ``fault_overhead_pct`` (~0). The module
imports no jax and starts no threads.

TOML plan format (``[[fault]]`` tables, see docs/operations.md):

    seed = 42                      # optional top-level plan seed
    [[fault]]
    point = "serve.engine.dispatch"   # exact name or fnmatch glob
    mode = "delay"                    # raise | delay | kill | corrupt
    delay_s = 1.5
    probability = 0.05                # seeded per-hit Bernoulli
    after = 10                        # skip the first N hits
    max_fires = 3                     # then go quiet (omit = forever)
    exc = "FaultInjected"             # raise mode: exception class
    flip_bits = 4                     # corrupt mode: bits flipped
"""

from __future__ import annotations

import dataclasses
import fnmatch
import logging
import os
import signal
import threading
import time
from hashlib import blake2b
from pathlib import Path
from typing import Any

try:
    import tomllib
except ModuleNotFoundError:  # Python < 3.11 (config.py's fallback)
    import tomli as tomllib  # type: ignore[no-redef]

logger = logging.getLogger("mlops_tpu.faults")

# tpulint Layer-3 manifest: one leaf lock guarding the hit counters and
# the trace list; decisions and actions (sleep, raise, kill) all happen
# OUTSIDE it (TPU403 discipline) — the lock covers dict/list updates only.
TPULINT_LOCK_ORDER = {"FaultPlan": ("_lock",)}

FAULT_MODES = ("raise", "delay", "kill", "corrupt")

ENV_VAR = "MLOPS_TPU_FAULTS"


class FaultInjected(RuntimeError):
    """The default exception a ``raise``-mode rule throws — named so
    tests and log greps can tell an injected failure from a real one."""


# raise-mode exception classes a plan may name. A closed set: the plan is
# config/env-controlled, so arbitrary class resolution would be an
# import-from-string gadget.
_RAISABLE: dict[str, type[BaseException]] = {
    "FaultInjected": FaultInjected,
    "RuntimeError": RuntimeError,
    "OSError": OSError,
    "IOError": OSError,
    "ValueError": ValueError,
    "TimeoutError": TimeoutError,
    "MemoryError": MemoryError,
}


@dataclasses.dataclass(frozen=True)
class FaultRule:
    """One scheduled fault: WHERE (point pattern), WHAT (mode), WHEN
    (after / max_fires / probability — all evaluated against the seeded
    per-point hit counter, never a wall clock or global RNG)."""

    point: str  # injection-point name or fnmatch glob
    mode: str  # raise | delay | kill | corrupt
    probability: float = 1.0  # seeded per-hit Bernoulli
    after: int = 0  # skip the first `after` matching hits
    max_fires: int | None = None  # stop after this many fires
    delay_s: float = 0.0  # delay mode
    exc: str = "FaultInjected"  # raise mode
    message: str = ""  # raise mode: exception text override
    flip_bits: int = 1  # corrupt mode: bit flips per payload
    seed: int = 0  # folded into every decision hash

    def __post_init__(self) -> None:
        if self.mode not in FAULT_MODES:
            raise ValueError(
                f"fault mode {self.mode!r} not in {FAULT_MODES}"
            )
        if not 0.0 <= self.probability <= 1.0:
            raise ValueError(
                f"fault probability {self.probability} outside [0, 1]"
            )
        if self.mode == "raise" and self.exc not in _RAISABLE:
            raise ValueError(
                f"fault exc {self.exc!r} not in {sorted(_RAISABLE)}"
            )
        if self.after < 0:
            raise ValueError(f"fault after={self.after} must be >= 0")
        if self.max_fires is not None and self.max_fires < 1:
            raise ValueError(
                f"fault max_fires={self.max_fires} must be >= 1"
            )
        if self.delay_s < 0:
            raise ValueError(f"fault delay_s={self.delay_s} must be >= 0")
        if self.flip_bits < 1:
            raise ValueError(
                f"fault flip_bits={self.flip_bits} must be >= 1"
            )

    def matches(self, point: str) -> bool:
        return self.point == point or fnmatch.fnmatchcase(point, self.point)


def _decision_hash(seed: int, rule_point: str, point: str, hit: int) -> int:
    """Stable 64-bit decision value for one (rule, point, hit) — the
    whole schedule derives from these, so identical plans replay
    identical traces on any host/process."""
    digest = blake2b(
        f"{seed}:{rule_point}:{point}:{hit}".encode(), digest_size=8
    ).digest()
    return int.from_bytes(digest, "little")


class FaultPlan:
    """A set of rules plus the per-point hit counters and the recorded
    injection trace. Thread-safe: counter/trace updates sit under one
    leaf lock; the ACTIONS (sleep, raise, kill, corruption arithmetic)
    run outside it."""

    def __init__(self, rules: list[FaultRule], seed: int = 0):
        self.rules = list(rules)
        self.seed = int(seed)
        self._lock = threading.Lock()
        self._hits: dict[tuple[str, str], int] = {}  # (rule.point, point)
        self._fires: dict[tuple[str, str], int] = {}
        self._trace: list[tuple[str, int, str, str]] = []

    # ------------------------------------------------------- construction
    @classmethod
    def from_rules(
        cls, rules: list[dict[str, Any] | FaultRule], seed: int = 0
    ) -> "FaultPlan":
        built = [
            r if isinstance(r, FaultRule) else FaultRule(**r) for r in rules
        ]
        return cls(built, seed=seed)

    @classmethod
    def from_toml(cls, path: str | Path) -> "FaultPlan":
        with open(path, "rb") as f:
            doc = tomllib.load(f)
        seed = int(doc.get("seed", 0))
        rules = []
        for table in doc.get("fault", []):
            fields = dict(table)
            fields.setdefault("seed", seed)
            rules.append(FaultRule(**fields))
        return cls(rules, seed=seed)

    # ------------------------------------------------------------ decide
    def _decide(self, point: str, modes: frozenset[str]) -> FaultRule | None:
        """Counter bookkeeping under the lock; returns the rule to apply
        (already counted as fired) or None.

        ``modes`` restricts which rule kinds this call site can act on
        (fire() cannot flip bits, corrupt() cannot raise/kill): rules of
        other modes are SKIPPED WITHOUT counting — a corrupt-point rule
        misconfigured as ``raise`` must not burn its max_fires budget or
        fabricate trace entries for faults that never happened.

        EVERY matching rule's hit counter advances on every hit — rules
        schedule independently, so a declined first rule (after /
        max_fires / probability) never shadows a second rule on the same
        point ("stall N times, then kill" plans compose). The first rule
        that fires wins the action; later rules still count the hit so
        their schedules stay deterministic regardless of which fired."""
        with self._lock:
            chosen: FaultRule | None = None
            for rule in self.rules:
                if rule.mode not in modes or not rule.matches(point):
                    continue
                key = (rule.point, point)
                hit = self._hits.get(key, 0)
                self._hits[key] = hit + 1
                if chosen is not None:
                    continue
                if hit < rule.after:
                    continue
                fired = self._fires.get(key, 0)
                if rule.max_fires is not None and fired >= rule.max_fires:
                    continue
                if rule.probability < 1.0:
                    draw = _decision_hash(
                        rule.seed, rule.point, point, hit
                    ) / float(1 << 64)
                    if draw >= rule.probability:
                        continue
                self._fires[key] = fired + 1
                self._trace.append((point, hit, rule.point, rule.mode))
                chosen = rule
            return chosen

    # ------------------------------------------------------------ actions
    _FIRE_MODES = frozenset({"raise", "delay", "kill"})
    _CORRUPT_MODES = frozenset({"corrupt"})

    def fire(self, point: str) -> None:
        rule = self._decide(point, self._FIRE_MODES)
        if rule is None:
            return
        if rule.mode == "delay":
            logger.warning(
                "fault injected: delay %.3fs at %s", rule.delay_s, point
            )
            time.sleep(rule.delay_s)
        elif rule.mode == "raise":
            logger.warning(
                "fault injected: raise %s at %s", rule.exc, point
            )
            raise _RAISABLE[rule.exc](
                rule.message or f"injected fault at {point}"
            )
        else:  # kill — the only remaining _FIRE_MODES member
            logger.warning("fault injected: SIGKILL at %s", point)
            os.kill(os.getpid(), signal.SIGKILL)

    def corrupt(self, point: str, data: bytes) -> bytes:
        if not data:
            return data
        rule = self._decide(point, self._CORRUPT_MODES)
        if rule is None:
            return data
        flipped = bytearray(data)
        n = len(flipped)
        for i in range(rule.flip_bits):
            h = _decision_hash(rule.seed, rule.point, f"{point}#bit", i)
            flipped[h % n] ^= 1 << ((h >> 32) % 8)
        logger.warning(
            "fault injected: %d bit flip(s) in %d bytes at %s",
            rule.flip_bits, n, point,
        )
        return bytes(flipped)

    # -------------------------------------------------------------- trace
    def trace(self) -> list[tuple[str, int, str, str]]:
        """(point, hit_index, rule_point, mode) per injected fault, in
        injection order — the determinism pin."""
        with self._lock:
            return list(self._trace)

    def fires(self) -> int:
        with self._lock:
            return len(self._trace)


# ------------------------------------------------------- module-level arm
# The ONE global the hot paths read: None = disarmed (the product state),
# a FaultPlan = armed. `fire`/`corrupt` below are the only call surface —
# one global load + identity check when disarmed.
_plan: FaultPlan | None = None


def arm(plan: FaultPlan) -> FaultPlan:
    global _plan
    _plan = plan
    logger.warning(
        "fault injection ARMED: %d rule(s), seed %d",
        len(plan.rules), plan.seed,
    )
    return plan


def disarm() -> None:
    global _plan
    _plan = None


def armed() -> bool:
    return _plan is not None


def active_plan() -> FaultPlan | None:
    return _plan


def fire(point: str) -> None:
    """Injection point for raise/delay/kill faults. No-op unless armed."""
    plan = _plan
    if plan is None:
        return
    plan.fire(point)


def corrupt(point: str, data: bytes) -> bytes:
    """Injection point for bit-corrupt-on-read faults: returns ``data``
    unchanged unless an armed corrupt rule matches."""
    plan = _plan
    if plan is None:
        return data
    return plan.corrupt(point, data)


def load_plan(path: str | Path) -> FaultPlan:
    return FaultPlan.from_toml(path)


def _arm_from_env() -> None:
    """Import-time env arming (`MLOPS_TPU_FAULTS=<toml>`): how the chaos
    smoke arms every process of a forked serve plane with one variable.
    A broken plan file fails LOUDLY — a chaos run that silently tests
    nothing is worse than one that refuses to start."""
    path = os.environ.get(ENV_VAR, "")
    if path:
        arm(FaultPlan.from_toml(path))


_arm_from_env()
