"""Deterministic fault injection (`faults.fire` / `faults.corrupt`).

The canonical import is the package itself::

    from mlops_tpu import faults
    ...
    faults.fire("serve.engine.dispatch")

See `mlops_tpu/faults/injector.py` for the plan format and semantics,
and docs/operations.md ("Failure domains & degraded modes") for the
operator view. Registered injection points live in `POINTS` so the
chaos tooling and docs can enumerate them.
"""

from mlops_tpu.faults.injector import (  # noqa: F401
    ENV_VAR,
    FAULT_MODES,
    FaultInjected,
    FaultPlan,
    FaultRule,
    active_plan,
    arm,
    armed,
    corrupt,
    disarm,
    fire,
    load_plan,
)

# Every named injection point compiled into the codebase, with the fault
# modes that make sense there. Purely documentary (fire()/corrupt() take
# any name), but the chaos smoke and the runbook enumerate THIS table —
# keep it in sync when adding points.
POINTS: dict[str, str] = {
    "serve.engine.dispatch": "before every solo device dispatch "
    "(raise = device error -> 500; delay = engine stall -> deadline 504)",
    "serve.engine.dispatch_group": "before every grouped device dispatch "
    "(same modes; covers the micro-batcher and the shm ring plane)",
    "serve.engine.compile": "inside the novel-shape AOT compile "
    "(raise = compile/cache failure -> degraded next-bucket dispatch)",
    "serve.frontend.predict": "front-end predict entry on the ring plane "
    "(kill = worker crash mid-request; the supervisor respawn path)",
    "serve.engine.exit": "each tick of the engine child's main loop "
    "(kill = deterministic in-process engine death -> supervisor respawn "
    "brownout; raise = engine main-loop failure, same recovery)",
    "serve.ring.reattach": "entry of the respawned engine's ring "
    "re-attach (delay = a slow re-attach stretching the brownout window; "
    "raise = failed re-attach -> engine exits, supervisor retries)",
    "compilecache.read": "artifact bytes on cache read "
    "(corrupt = bit flips -> checksum discard + recompile)",
    "compilecache.persist.midwrite": "between the cache artifact's tmp "
    "write and its rename (kill = torn persist; no partial artifact may "
    "survive)",
    "lifecycle.reservoir.midwrite": "between the reservoir snapshot's tmp "
    "write and its rename (kill = torn reservoir save)",
    "io.atomic_write.midwrite": "inside utils.io.atomic_write between "
    "write and rename (kill = torn checkpoint/registry write)",
    "lifecycle.retrain": "entry of the controller's retrain transition "
    "(raise = repeated retrain failure -> circuit breaker)",
    "lifecycle.shadow.evaluate": "entry of the shadow gate evaluation "
    "(raise = repeated evaluation failure -> circuit breaker)",
    "autotune.regrid.midswap": "between a regrid's warm phase and its "
    "bucket-set swap (kill = crashed apply at maximum in-flight state: "
    "the exec table keeps only valid warmed entries, serving continues "
    "on the old grid, and a restarted plane re-plans cleanly)",
}
