"""Shadow serving: warm the candidate, mirror live traffic, collect deltas.

The ``ShadowEngine`` holds a FULL candidate ``InferenceEngine`` (its own
device-resident params/monitor/temperature and its own accumulator — the
candidate's monitor folds never touch the live aggregate) plus frozen
references to the incumbent's serving state, captured at construction.

Warmup rides the existing AOT compile cache: the cache keys already
encode the model hash, so flagship and candidate executables coexist in
one store, and candidate warmup routes through exactly the registered
``serve-predict-packed`` / ``serve-predict-group-packed`` entry points
(`compilecache/warmup.py serve_predict_jobs` — the tpulint Layer-2
registry audits the same programs). The common lifecycle case — a
fine-tune with an UNCHANGED architecture — is even cheaper: the packed
programs take params/monitor/temperature as ARGUMENTS, so the incumbent's
already-compiled executables serve the candidate bit-for-bit; ``warm()``
detects the matching model fingerprint and shares the live exec table
instead of compiling anything (``warm_mode == "shared"``).

Mirroring is dispatch-only: the controller drains the engine tee's queue
on ITS thread and calls ``mirror()`` with copies of real request arrays —
the candidate scores them (timed), the incumbent's params score the SAME
rows through the SAME compiled executable (fresh throwaway accumulator,
so the live monitor aggregate is never double-counted), responses are
discarded, and only the deltas accumulate: candidate vs incumbent
latency on real request shapes and the per-row prediction shift.

The AUC/ECE evidence comes from ``evaluate(holdout)`` — the labeled
held-out split the retrain produced — scored through both sides' actual
packed serving programs in bucket-shaped chunks (real serving shapes,
not an offline-only code path).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from typing import Any

import numpy as np

from mlops_tpu.serve.engine import InferenceEngine

# tpulint Layer-3 manifest: the stats lock is a LEAF — scalar/deque
# updates only; all scoring, padding, and device fetches happen outside
# it (TPU403 discipline).
TPULINT_LOCK_ORDER = {"ShadowEngine": ("_stats_lock",)}

_LATENCY_WINDOW = 512  # mirrored-latency samples retained per side


@dataclasses.dataclass
class ShadowReport:
    """Everything the promotion gates consume (lifecycle/promote.py)."""

    auc_candidate: float
    auc_incumbent: float
    auc_delta: float  # candidate - incumbent (negative = regression)
    ece_candidate: float
    ece_incumbent: float
    p99_candidate_ms: float
    p99_incumbent_ms: float
    p50_candidate_ms: float
    p50_incumbent_ms: float
    mirrors: int
    mirror_drops: int
    mean_abs_pred_delta: float
    holdout_rows: int
    warm_mode: str
    warm_s: float


class ShadowEngine:
    def __init__(
        self,
        live: InferenceEngine,
        candidate_bundle: Any,
        warmup_workers: int = 0,
    ):
        if not getattr(live, "monitor_accumulating", False):
            raise ValueError(
                "shadow serving requires a device-accumulating (flax) "
                "live engine"
            )
        self._live = live
        # Frozen incumbent refs: a later promotion mutates the live
        # engine's attributes, but THIS candidate must keep being judged
        # against the incumbent it shadowed.
        self._inc_variables = live._variables
        self._inc_monitor = live._monitor
        self._inc_temperature = live._temperature
        self.engine = InferenceEngine(
            candidate_bundle,
            buckets=tuple(live.buckets),
            service_name=live.service_name,
            enable_grouping=live.supports_grouping,
            compile_cache=live.compile_cache,
            warmup_workers=warmup_workers,
        )
        self.warm_mode = ""
        self.warm_s = 0.0
        self._same_arch = True  # set by warm(); picks the incumbent's
        # scoring program (candidate table when shared, live table when
        # the architectures diverged — incumbent params cannot run
        # through a different architecture's compiled program)
        self._stats_lock = threading.Lock()
        self._cand_ms: deque = deque(maxlen=_LATENCY_WINDOW)
        self._inc_ms: deque = deque(maxlen=_LATENCY_WINDOW)
        self._mirrors = 0
        self._drops = 0
        self._pred_delta_sum = 0.0
        self._pred_delta_rows = 0

    # --------------------------------------------------------------- warm
    def warm(self) -> None:
        """AOT-ready the candidate. Identical architecture -> share the
        live exec table (params are per-call arguments, so the incumbent's
        executables ARE the candidate's — zero compiles, instant shadow);
        a changed architecture compiles through the persistent cache via
        the registered serve entry points, exactly like a cold engine."""
        from mlops_tpu.compilecache.keys import model_fingerprint

        t0 = time.perf_counter()
        same_model = model_fingerprint(
            self.engine.bundle.model_config
        ) == model_fingerprint(self._live.bundle.model_config)
        # The monitor state rides the compiled signature too: a candidate
        # whose K-S reference width diverged (retrain.py matches it, but
        # hand-built bundles can differ) must compile its own programs.
        same_monitor = all(
            tuple(a.shape) == tuple(b.shape)
            for a, b in zip(
                self.engine.bundle.monitor.to_arrays().values(),
                self._live.bundle.monitor.to_arrays().values(),
            )
        )
        self._same_arch = same_model and same_monitor
        if self._same_arch:
            with self._live._compile_lock:
                table = dict(self._live._exec)
            with self.engine._compile_lock:
                self.engine._exec.update(table)
            self.engine.ready = True
            self.warm_mode = "shared"
        else:
            self.engine.warmup()
            self.warm_mode = "compiled"
        self.warm_s = round(time.perf_counter() - t0, 3)

    # ------------------------------------------------------------- mirror
    def mirror(self, cat: np.ndarray, num: np.ndarray) -> None:
        """Score one mirrored request on both sides; keep only deltas.
        Runs on the CONTROLLER thread (the tee queue's consumer), never a
        request thread. All numerics happen outside the stats lock."""
        t0 = time.perf_counter()
        cand = np.asarray(
            self.engine.predict_arrays(cat, num)["predictions"], np.float64
        )
        t1 = time.perf_counter()
        inc = self._score_incumbent(cat, num)
        t2 = time.perf_counter()
        delta = float(np.abs(cand - inc).sum())
        with self._stats_lock:
            self._cand_ms.append((t1 - t0) * 1e3)
            self._inc_ms.append((t2 - t1) * 1e3)
            self._mirrors += 1
            self._pred_delta_sum += delta
            self._pred_delta_rows += len(cand)

    def note_drop(self, count: int = 1) -> None:
        with self._stats_lock:
            self._drops += count

    @property
    def mirrors(self) -> int:
        with self._stats_lock:
            return self._mirrors

    def _score_incumbent(self, cat: np.ndarray, num: np.ndarray) -> np.ndarray:
        """Incumbent predictions for the same rows with a throwaway zero
        accumulator (donation-safe: it is consumed by this one call), so
        the LIVE monitor aggregate never double-counts mirrored traffic.
        Same-architecture candidates run the incumbent's params through
        the SHARED compiled entry (params are arguments); an
        architecture-change candidate's program cannot accept the
        incumbent's param pytree, so the incumbent scores through the
        LIVE engine's own table instead — either way, same shapes, same
        padding, apples-to-apples."""
        import jax

        from mlops_tpu.monitor.state import init_accumulator
        from mlops_tpu.ops.predict import packed_layout

        eng = self.engine if self._same_arch else self._live
        n = cat.shape[0]
        bucket = eng._bucket_for(n)
        rows = bucket if bucket is not None else n
        pad = rows - n
        if pad:
            cat = np.pad(cat, ((0, pad), (0, 0)))
            num = np.pad(num, ((0, pad), (0, 0)))
        mask = np.arange(rows) < n
        key = ("bucket", rows)
        fn = eng._exec.get(key)
        if fn is None:
            fn = eng._compile_novel(key, (cat, num, mask))
        out, _ = fn(
            self._inc_variables,
            self._inc_monitor,
            jax.device_put(init_accumulator()),
            self._inc_temperature,
            cat,
            num,
            mask,
        )
        arr = np.asarray(out)
        p, _, _ = packed_layout(rows)
        return arr[p][:n].astype(np.float64)

    # ------------------------------------------------------------ evaluate
    def evaluate(self, holdout, holdout_incumbent=None) -> ShadowReport:
        """Score the labeled holdout through both sides' packed serving
        programs (bucket-shaped chunks — real request shapes) and fold in
        the mirrored latency/shift evidence.

        ``holdout_incumbent`` carries the SAME rows encoded with the
        incumbent's preprocessor (only differs under
        ``lifecycle.refit_preprocessor``): each side is graded in the
        encode configuration it actually serves — the incumbent scored on
        candidate-refit normalization stats would collapse toward 0.5 and
        bias every gate pro-candidate."""
        from mlops_tpu.lifecycle.promote import (
            expected_calibration_error,
            roc_auc_np,
        )

        if holdout_incumbent is None:
            holdout_incumbent = holdout
        labels = np.asarray(holdout.labels, np.float64)
        chunk = self.engine.max_bucket
        cand_probs, inc_probs = [], []
        for lo in range(0, holdout.n, chunk):
            cand_probs.append(
                np.asarray(
                    self.engine.predict_arrays(
                        holdout.cat_ids[lo : lo + chunk],
                        holdout.numeric[lo : lo + chunk],
                    )["predictions"],
                    np.float64,
                )
            )
            inc_probs.append(
                self._score_incumbent(
                    holdout_incumbent.cat_ids[lo : lo + chunk],
                    holdout_incumbent.numeric[lo : lo + chunk],
                )
            )
        cand = np.concatenate(cand_probs)
        inc = np.concatenate(inc_probs)
        # Latency evidence comes from MIRRORED traffic only: holdout
        # chunk wall timings are too few to gate on (an offline pass has
        # 1-5 samples; one scheduler hiccup would flakily fail the p99
        # gate). With zero mirrors both p99s report 0.0, which
        # evaluate_gates reads as "no evidence, gate passes".
        with self._stats_lock:
            mirror_cand = list(self._cand_ms)
            mirror_inc = list(self._inc_ms)
            mirrors, drops = self._mirrors, self._drops
            shift_sum = self._pred_delta_sum
            shift_rows = self._pred_delta_rows
        auc_c = roc_auc_np(cand, labels)
        auc_i = roc_auc_np(inc, labels)
        return ShadowReport(
            auc_candidate=auc_c,
            auc_incumbent=auc_i,
            auc_delta=auc_c - auc_i,
            ece_candidate=expected_calibration_error(cand, labels),
            ece_incumbent=expected_calibration_error(inc, labels),
            p99_candidate_ms=_percentile(mirror_cand, 99),
            p99_incumbent_ms=_percentile(mirror_inc, 99),
            p50_candidate_ms=_percentile(mirror_cand, 50),
            p50_incumbent_ms=_percentile(mirror_inc, 50),
            mirrors=mirrors,
            mirror_drops=drops,
            mean_abs_pred_delta=(
                shift_sum / shift_rows if shift_rows else 0.0
            ),
            holdout_rows=int(holdout.n),
            warm_mode=self.warm_mode,
            warm_s=self.warm_s,
        )


def _percentile(samples, q: float) -> float:
    if not samples:
        return 0.0
    return float(np.percentile(np.asarray(samples, np.float64), q))
