"""Off-hot-path incremental retrain: labeled window -> candidate bundle.

Two pieces:

- ``SampleReservoir`` — a bounded on-disk reservoir of encoded serving
  rows (algorithm R: every scored row has equal probability of residing
  in the fixed-size buffer regardless of traffic volume), fed from the
  serve path through the engine's lifecycle tee. It is the controller's
  record of "what recent traffic looked like" — drift forensics and the
  real request shapes the shadow replays — persisted atomically
  (tmp+rename npz) so a pod restart keeps its window.
- ``run_retrain`` — the retrain itself, run on the controller thread,
  never a request thread: read the labeled window
  (``lifecycle.labeled_path`` — serving traffic is unlabeled; realized
  outcomes arrive out of band through this file), optionally re-fit the
  preprocessor over it via the streaming one-pass fit
  (`data/stream.py fit_streaming` — single-process serving only; the
  multi-worker plane's front ends encode with the preprocessor loaded at
  fork, so the ring plane keeps the incumbent's), fine-tune from the
  INCUMBENT's params with a small step budget (`train/loop.fit`, with
  checkpoints — a preempted retrain resumes), re-fit the monitor's
  drift reference + outlier detector on the new window, re-fit
  calibration on the held-out split, and package a candidate bundle
  under ``<lifecycle.dir>/candidates/``. The held-out split is returned
  as the gate-evaluation holdout (lifecycle/promote.py).
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
import tempfile
import threading
import time
from pathlib import Path
from typing import Any

import numpy as np

from mlops_tpu import faults
from mlops_tpu.config import Config, TrainConfig
from mlops_tpu.schema import SCHEMA

# tpulint Layer-3 manifest: the reservoir's one lock is a leaf — index
# arithmetic and buffer row assignment only; persistence snapshots copy
# under the lock and write OUTSIDE it (TPU403 discipline).
TPULINT_LOCK_ORDER = {"SampleReservoir": ("_lock",)}


class LifecycleError(RuntimeError):
    """A lifecycle step that cannot proceed (no labeled window, window too
    small, flavor mismatch) — named so the controller can log-and-cool
    instead of crashing the serve process."""


class SampleReservoir:
    """Bounded uniform sample of encoded serving rows (algorithm R).

    Thread-safe: ``add_batch`` is called from the controller's drain of
    the tee queue (one thread in production), but the lock keeps direct
    feeding from tests/bench harnesses safe too. The RNG is seeded, so a
    single-threaded feed is deterministic.
    """

    def __init__(self, capacity: int, directory: str | Path, seed: int = 0):
        if capacity < 1:
            raise ValueError(f"reservoir capacity {capacity} must be >= 1")
        self.capacity = capacity
        self.directory = Path(directory)
        self._cat = np.zeros((capacity, SCHEMA.num_categorical), np.int32)
        self._num = np.zeros((capacity, SCHEMA.num_numeric), np.float32)
        self._filled = 0
        self._seen = 0
        self._rng = np.random.default_rng(seed)
        self._lock = threading.Lock()

    @property
    def path(self) -> Path:
        return self.directory / "reservoir.npz"

    # ------------------------------------------------------------- feeding
    def add_batch(self, cat: np.ndarray, num: np.ndarray) -> None:
        """Fold one request's rows into the reservoir (algorithm R row by
        row). The index draws happen OUTSIDE the lock (the RNG has its own
        serialization need, so draws sit under the lock-free fast path
        only when the buffer is still filling); the buffer writes are
        index assignments under the leaf lock."""
        n = int(cat.shape[0])
        if n == 0:
            return
        cat = np.asarray(cat, np.int32)
        num = np.asarray(num, np.float32)
        with self._lock:
            for i in range(n):
                self._seen += 1
                if self._filled < self.capacity:
                    slot = self._filled
                    self._filled += 1
                else:
                    draw = int(self._rng.integers(0, self._seen))
                    if draw >= self.capacity:
                        continue
                    slot = draw
                self._cat[slot] = cat[i]
                self._num[slot] = num[i]

    # -------------------------------------------------------------- reading
    def window(self) -> tuple[np.ndarray, np.ndarray]:
        """(cat int32[k, C], num f32[k, N]) copies of the filled rows."""
        with self._lock:
            k = self._filled
            return self._cat[:k].copy(), self._num[:k].copy()

    @property
    def rows(self) -> int:
        with self._lock:
            return self._filled

    @property
    def rows_seen(self) -> int:
        with self._lock:
            return self._seen

    # ---------------------------------------------------------- persistence
    def save(self) -> Path:
        """Atomic snapshot (tmp+rename): the copy happens under the lock,
        the file I/O outside it."""
        with self._lock:
            payload = {
                "cat": self._cat[: self._filled].copy(),
                "num": self._num[: self._filled].copy(),
                "seen": np.int64(self._seen),
            }
        self.directory.mkdir(parents=True, exist_ok=True)
        fd, tmp = tempfile.mkstemp(
            dir=str(self.directory), suffix=".reservoir.tmp"
        )
        try:
            with os.fdopen(fd, "wb") as f:
                np.savez(f, **payload)
            # Injection point (mlops_tpu/faults): kill between the tmp
            # write and the rename — a torn reservoir save must leave
            # either no snapshot or the previous intact one, never a
            # half-written npz a restart would trust.
            faults.fire("lifecycle.reservoir.midwrite")
            os.replace(tmp, self.path)
        except BaseException:
            with contextlib.suppress(OSError):
                os.unlink(tmp)
            raise
        return self.path

    def load(self) -> bool:
        """Restore a prior snapshot if one exists; True when restored."""
        if not self.path.is_file():
            return False
        with np.load(self.path) as data:
            cat, num = data["cat"], data["num"]
            seen = int(data["seen"])
        k = min(len(cat), self.capacity)
        with self._lock:
            self._cat[:k] = cat[:k]
            self._num[:k] = num[:k]
            self._filled = k
            self._seen = max(seen, k)
        return True


def _match_monitor_ref(monitor, train_ds, target: int, seed: int):
    """Resize the candidate monitor's K-S reference sample to the
    INCUMBENT's width. ``fit_monitor`` samples min(drift_ref_size, n)
    rows, so a labeled window smaller than the incumbent's training set
    would shrink ``num_ref_sorted``/``num_ref_cdf`` — changing the packed
    programs' abstract signature and defeating both the shared-exec-table
    shadow warm and the zero-compile hot swap. A window smaller than the
    target resamples WITH replacement (the tie-aware right-continuous CDF
    handles the duplicates); shapes stay bit-identical to the incumbent's
    compiled contract."""
    from mlops_tpu.monitor.state import MonitorState, _ref_cdf

    current = int(monitor.num_ref_sorted.shape[1])
    if current == target:
        return monitor
    rng = np.random.default_rng(seed)
    numeric = np.asarray(train_ds.numeric, np.float32)
    n = numeric.shape[0]
    idx = rng.choice(n, size=target, replace=n < target)
    ref = np.sort(numeric[idx], axis=0).T  # [M, target]
    arrays = monitor.to_arrays()
    arrays["num_ref_sorted"] = ref
    arrays["num_ref_cdf"] = _ref_cdf(ref)
    return MonitorState.from_arrays(arrays)


@dataclasses.dataclass
class RetrainResult:
    candidate_dir: Path
    bundle: Any  # the loaded candidate Bundle
    holdout: Any  # EncodedDataset — the held-out split, CANDIDATE encode
    holdout_incumbent: Any  # the SAME held-out rows encoded with the
    # incumbent's preprocessor (identical object when no refit): the
    # gates must score each side in the encode configuration IT serves —
    # scoring the incumbent on candidate-refit normalization stats would
    # systematically collapse its AUC and bias every gate pro-candidate
    metrics: dict[str, float]  # candidate validation metrics (fit)
    labeled_rows: int
    wall_s: float
    refit_preprocessor: bool


def run_retrain(
    incumbent,
    config: Config,
    generation: int,
    seed: int = 0,
    attempt: int = 1,
    reservoir_window: tuple[np.ndarray, np.ndarray] | None = None,
) -> RetrainResult:
    """Labeled window -> fine-tuned candidate bundle + checkpointed run.

    ``incumbent`` is the live Bundle (flax flavor required — the sklearn
    floor redeploys, it does not hot-swap). Raises ``LifecycleError`` on
    a missing/undersized labeled window so the controller can cool down
    instead of crashing the serve process.

    ``attempt`` scopes the checkpoint/candidate directories per trigger:
    a REJECTED attempt's completed checkpoints must never be resumed by
    the next one (``fit`` would restore the final step and return the
    stale params untouched, however fresh the labeled window) — while a
    crash-restarted attempt under the SAME tag still resumes mid-train.

    ``reservoir_window`` — (cat int32[k, C], num f32[k, N]) from the
    serve-path sample reservoir — refits the candidate's drift
    reference/outlier detector on RECENT SERVING TRAFFIC rather than the
    labeled file alone (falls back to the labeled train split when the
    window is thinner than the labeled one).
    """
    from mlops_tpu.bundle import load_bundle, save_bundle
    from mlops_tpu.data import load_table_columns
    from mlops_tpu.data.stream import fit_streaming
    from mlops_tpu.models import build_model
    from mlops_tpu.monitor.state import fit_monitor
    from mlops_tpu.train.loop import fit
    from mlops_tpu.train.pipeline import _fit_calibration, split_dataset

    lc = config.lifecycle.validate()
    if incumbent.flavor != "flax":
        raise LifecycleError(
            f"retrain requires a flax-flavor incumbent, got "
            f"{incumbent.flavor!r} (tree/doc bundles redeploy instead)"
        )
    if not lc.labeled_path:
        raise LifecycleError(
            "lifecycle.labeled_path is empty — no labeled window to "
            "retrain on (serving traffic is unlabeled; deliver realized "
            "outcomes to a CSV/Parquet with the target column)"
        )
    t0 = time.perf_counter()
    columns, labels = load_table_columns(lc.labeled_path)
    if labels is None:
        raise LifecycleError(
            f"{lc.labeled_path} has no target column — the retrain window "
            "must be labeled"
        )
    n_rows = len(labels)
    if n_rows < lc.min_labeled_rows:
        raise LifecycleError(
            f"labeled window has {n_rows} rows < "
            f"lifecycle.min_labeled_rows={lc.min_labeled_rows}"
        )
    if lc.refit_preprocessor:
        # One-pass streaming re-fit of the normalization stats over the
        # recent window (data/stream.py): the candidate encodes the
        # DRIFTED distribution with honest statistics. Single-process
        # serving only — the controller forces this off on the ring plane.
        preprocessor = fit_streaming(lc.labeled_path)
    else:
        preprocessor = incumbent.preprocessor
    ds = preprocessor.encode(columns, labels)
    train_ds, valid_ds = split_dataset(ds, 0.2)
    if lc.refit_preprocessor:
        # Same rows, INCUMBENT encode, for the gate comparison: the
        # split permutation depends only on (n, seed), so the two valid
        # splits select identical rows.
        _, valid_inc = split_dataset(
            incumbent.preprocessor.encode(columns, labels), 0.2
        )
    else:
        valid_inc = valid_ds

    model = build_model(incumbent.model_config)
    steps = lc.retrain_steps
    tcfg = TrainConfig(
        batch_size=min(lc.retrain_batch_size, max(1, train_ds.n)),
        steps=steps,
        eval_every=steps,
        warmup_steps=max(1, steps // 10),
        seed=seed,
        checkpoint_every=max(1, steps // 2),
        keep_best=True,
    )
    state_dir = Path(lc.dir)
    tag = f"gen-{generation}-t{attempt}"
    ckpt_dir = state_dir / "checkpoints" / tag
    # A COMPLETED prior run under this tag must never be resumed: `fit`
    # would restore the final step and return the stale params untouched
    # (attempt tags collide across process restarts — the trigger counter
    # restarts with the process — and the offline CLI reruns with the
    # same tag after a gate rejection). A PARTIAL checkpoint (crash
    # mid-retrain) is exactly what resume is for; only done-state wipes.
    latest = ckpt_dir / "latest.json"
    if latest.is_file():
        try:
            import json as _json

            done_step = int(_json.loads(latest.read_text()).get("step", 0))
        except (OSError, ValueError):
            done_step = 0
        if done_step >= lc.retrain_steps:
            import shutil

            shutil.rmtree(ckpt_dir, ignore_errors=True)
    # Fine-tune FROM the incumbent's params (fit copies them into fresh
    # buffers before the donated scan can consume them).
    result = fit(
        model,
        train_ds,
        valid_ds,
        tcfg,
        init_variables=incumbent.variables,
        metrics_path=ckpt_dir / "metrics.jsonl",
        checkpoint_dir=ckpt_dir,
    )
    # Monitor refit source: the serve-path reservoir when it carries at
    # least as much evidence as the labeled train split (the drift
    # reference should describe what TRAFFIC looks like now), else the
    # labeled window.
    monitor_ds = train_ds
    if reservoir_window is not None and (
        len(reservoir_window[0]) >= min(train_ds.n, 512)
    ):
        from mlops_tpu.data.encode import EncodedDataset

        monitor_ds = EncodedDataset(
            cat_ids=reservoir_window[0],
            numeric=reservoir_window[1],
            labels=None,
        )
    monitor = _match_monitor_ref(
        fit_monitor(monitor_ds, seed=seed), monitor_ds,
        target=int(incumbent.monitor.num_ref_sorted.shape[1]), seed=seed,
    )
    calibration = _fit_calibration(valid_ds, result.params, model)
    candidate_dir = state_dir / "candidates" / tag
    save_bundle(
        candidate_dir,
        incumbent.model_config,
        result.params,
        preprocessor,
        monitor,
        metrics=result.metrics,
        tags={
            "lifecycle": "candidate",
            "parent_generation": str(generation - 1),
            "labeled_rows": str(n_rows),
        },
        calibration=calibration,
    )
    return RetrainResult(
        candidate_dir=candidate_dir,
        bundle=load_bundle(candidate_dir),
        holdout=valid_ds,
        holdout_incumbent=valid_inc,
        metrics=result.metrics,
        labeled_rows=n_rows,
        wall_s=round(time.perf_counter() - t0, 3),
        refit_preprocessor=lc.refit_preprocessor,
    )
