"""Lifecycle controller: the closed MLOps loop (ROADMAP item 5).

train -> register -> serve -> monitor was three fast subsystems and a
gap; this package closes it: threshold policies over the device-resident
monitor aggregates (`triggers`), an off-hot-path incremental retrain fed
by a bounded on-disk sample reservoir (`retrain`), a shadow engine that
AOT-warms the candidate through the existing compile cache and mirrors
live traffic (`shadow`), and gated zero-downtime promotion with instant
rollback (`promote`) — orchestrated by `controller.LifecycleController`,
which `mlops-tpu serve` runs in-process when ``lifecycle.enabled=true``
and `mlops-tpu lifecycle` drives as a one-shot offline pass.

This package lives ENGINE-SIDE only: it (transitively) imports jax via
`serve/engine.py`, so the multi-worker plane's jax-free front-end
processes must never import it — the engine process owns the loop there.
"""

from mlops_tpu.lifecycle.controller import LifecycleController
from mlops_tpu.lifecycle.promote import (
    GateDecision,
    evaluate_gates,
    expected_calibration_error,
    promote_engine,
    rollback_engine,
    roc_auc_np,
)
from mlops_tpu.lifecycle.retrain import (
    LifecycleError,
    RetrainResult,
    SampleReservoir,
    run_retrain,
)
from mlops_tpu.lifecycle.shadow import ShadowEngine, ShadowReport
from mlops_tpu.lifecycle.triggers import TriggerDecision, TriggerPolicy

__all__ = [
    "GateDecision",
    "LifecycleController",
    "LifecycleError",
    "RetrainResult",
    "SampleReservoir",
    "ShadowEngine",
    "ShadowReport",
    "TriggerDecision",
    "TriggerPolicy",
    "evaluate_gates",
    "expected_calibration_error",
    "promote_engine",
    "roc_auc_np",
    "rollback_engine",
    "run_retrain",
]
