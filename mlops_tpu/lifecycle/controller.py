"""The lifecycle controller: the component that ACTS on what the monitor
sees — drift trigger -> off-hot-path retrain -> shadow mirror -> gated
hot promotion, with instant rollback and cooldown.

Threading model (tpulint Layer 3): the controller owns ONE worker thread
(`start`/`stop`) running ``run_once`` every ``lifecycle.tick_s``. All
heavy work — draining the tee queue into the reservoir, mirrored shadow
scoring, the retrain itself, monitor-aggregate fetches, gate evaluation,
the swap — happens on that thread (or the caller's, when tests drive
``run_once`` directly), NEVER on a request thread. The request path's
entire contribution is the engine tee: one bounded ``queue.Queue``
put_nowait per request (copies the arrays — the multi-worker ring's
slabs are reused after release, so views must not escape) which drops
and counts when full. ``_lock`` is a leaf guarding the small mutable
status/counter state; nothing blocking ever runs under it.

State machine (one transition per ``run_once``):

    idle --trigger fired--> retraining (inline, checkpointed)
         --candidate built--> shadowing (mirror live traffic)
         --evidence in--> gate evaluation --> promoted | rejected
         --either way--> cooldown --> idle

A promotion that later regresses rolls back in one ``rollback()`` call
(the engine retains the previous bundle's device state and exec table).
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from typing import Any

from mlops_tpu import faults
from mlops_tpu.config import Config
from mlops_tpu.lifecycle.retrain import (
    LifecycleError,
    SampleReservoir,
    run_retrain,
)
from mlops_tpu.lifecycle.shadow import ShadowEngine
from mlops_tpu.lifecycle.triggers import TriggerPolicy
from mlops_tpu.lifecycle.promote import (
    evaluate_gates,
    promote_engine,
    rollback_engine,
)

logger = logging.getLogger("mlops_tpu.lifecycle")

# tpulint Layer-3 manifest: one leaf lock for the status/counter state.
# The tee queue is a queue.Queue (its internal lock is library-owned);
# the reservoir and shadow carry their own declared leaves.
TPULINT_LOCK_ORDER = {"LifecycleController": ("_lock",)}

_TEE_QUEUE_SLOTS = 256  # bounded hot-path -> controller handoff


class LifecycleController:
    def __init__(
        self,
        engine: Any,
        config: Config,
        clock=time.monotonic,
        force_incumbent_preprocessor: bool = False,
    ):
        self.engine = engine
        self.config = config
        self.lifecycle = config.lifecycle.validate()
        if force_incumbent_preprocessor and self.lifecycle.refit_preprocessor:
            # Ring plane: front ends encode with the preprocessor loaded
            # at fork — a refit would skew candidate encode vs serving
            # encode. Forced off, loudly.
            logger.warning(
                "lifecycle.refit_preprocessor forced off: the multi-worker "
                "plane's front ends encode with the fork-time preprocessor"
            )
            self.lifecycle.refit_preprocessor = False
        self._clock = clock
        self.policy = TriggerPolicy(self.lifecycle)
        self.reservoir = SampleReservoir(
            self.lifecycle.reservoir_rows, self.lifecycle.dir
        )
        self.reservoir.load()  # resume a prior window if one persists
        self._queue: queue.Queue = queue.Queue(maxsize=_TEE_QUEUE_SLOTS)
        self._lock = threading.Lock()
        self._state = "idle"
        self._shadow: ShadowEngine | None = None
        self._holdout = None
        self._shadow_since = 0.0
        self._mirror_rng_state = 0x9E3779B9  # cheap deterministic LCG
        self._drift_triggers = 0
        self._promotions = {"promoted": 0, "rejected": 0, "rolled_back": 0}
        self._shadow_auc_delta: float | None = None
        # Circuit breaker: consecutive UNEXPECTED retrain/shadow/evaluate
        # failures (named LifecycleError skips don't count — those are
        # the loop declining work, already cooldown-throttled) open the
        # breaker for lifecycle.breaker_cooldown_s: triggers neither fire
        # nor accumulate while open, so a persistently broken retrain
        # path cools down instead of hot-looping attempts against live
        # serving. Exported as mlops_tpu_lifecycle_breaker_open /
        # _breaker_trips_total.
        self._consecutive_failures = 0
        self._breaker_open_until = float("-inf")
        self._breaker_trips = 0
        self._tee_drops = 0
        self._last_report: dict | None = None
        self._last_error = ""
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        engine.set_lifecycle_tee(self._offer)

    # -------------------------------------------------------------- hot tee
    def _offer(self, cat, num) -> None:
        """Engine dispatch-path hook: bounded, non-blocking, never raises
        (a lifecycle bug must not 500 live traffic). Copies the arrays —
        ring-plane callers pass shared-memory slab views that are reused
        the moment the response is released."""
        try:
            self._queue.put_nowait((cat.copy(), num.copy()))
        except queue.Full:
            with self._lock:
                self._tee_drops += 1
        except Exception:  # tpulint: disable=TPU201
            # Defensive breadth IS the contract at this boundary: any
            # unexpected failure (shutdown race, dtype surprise) must
            # cost one observation, never a request.
            logger.exception("lifecycle tee offer failed; observation lost")

    # ------------------------------------------------------------ lifecycle
    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="lifecycle", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=30)
        self.engine.set_lifecycle_tee(None)
        try:
            self.reservoir.save()
        except OSError:
            logger.exception("reservoir snapshot failed on stop")

    def _loop(self) -> None:
        while not self._stop.wait(self.lifecycle.tick_s):
            try:
                self.run_once()
            # The loop must survive anything a tick throws (transient
            # device fetch failure, a retrain crash): log, cool down,
            # RESET to idle — a tick that died mid-transition must not
            # leave the state machine stranded in 'retraining'/'shadowing'
            # (where run_once would no-op forever and the loop silently
            # dies) — and keep serving; the controller can never take the
            # engine down with it.
            except Exception as err:  # tpulint: disable=TPU201
                logger.exception("lifecycle tick failed")
                with self._lock:
                    self._last_error = f"{type(err).__name__}: {err}"
                    self._state = "idle"
                    self._shadow = None
                    self._holdout = None
                self._note_failure(self._clock())
                self.policy.start_cooldown(self._clock())

    # ------------------------------------------------------------- run_once
    def run_once(self, now: float | None = None) -> dict:
        """One controller step: drain observations, then at most one
        state-machine transition. Tests and the bench drive this
        directly; the background loop calls it every tick."""
        now = self._clock() if now is None else now
        self._drain_observations()
        state = self._state
        if state == "idle":
            self._step_idle(now)
        elif state == "shadowing":
            self._step_shadow(now)
        return self.status()

    def _drain_observations(self) -> None:
        """Tee queue -> reservoir (+ mirrored shadow scoring while a
        candidate is shadowing). Runs on the controller thread. BOUNDED
        at one queue-capacity per call: an unthrottled producer can
        refill the queue faster than mirror scoring consumes it, and an
        until-empty drain would livelock ``run_once`` (the state machine
        would never step again) — excess observations wait for the next
        tick or drop at the tee, never wedge the loop."""
        shadow = self._shadow
        mirroring = shadow is not None and self._state == "shadowing"
        for _ in range(_TEE_QUEUE_SLOTS):
            try:
                cat, num = self._queue.get_nowait()
            except queue.Empty:
                return
            self.reservoir.add_batch(cat, num)
            if mirroring and self._mirror_draw():
                try:
                    shadow.mirror(cat, num)
                # A mirror failure is shadow evidence lost, never an
                # outage: count it and keep draining.
                except Exception:  # tpulint: disable=TPU201
                    logger.exception("shadow mirror dispatch failed")
                    shadow.note_drop()

    def _mirror_draw(self) -> bool:
        """Deterministic LCG draw against mirror_fraction (no
        Random/np state shared with anything else)."""
        frac = self.lifecycle.mirror_fraction
        if frac >= 1.0:
            return True
        if frac <= 0.0:
            return False
        self._mirror_rng_state = (
            self._mirror_rng_state * 1103515245 + 12345
        ) & 0x7FFFFFFF
        return (self._mirror_rng_state / 0x80000000) < frac

    # ------------------------------------------------------ circuit breaker
    def breaker_open(self, now: float | None = None) -> bool:
        now = self._clock() if now is None else now
        with self._lock:
            return now < self._breaker_open_until

    def _note_failure(self, now: float) -> None:
        """One unexpected retrain/shadow/evaluate failure toward the
        breaker threshold; opening resets the streak (the post-cooldown
        loop gets a fresh ``breaker_failures`` budget — half-open)."""
        with self._lock:
            self._consecutive_failures += 1
            if self._consecutive_failures < self.lifecycle.breaker_failures:
                return
            self._consecutive_failures = 0
            self._breaker_trips += 1
            self._breaker_open_until = (
                now + self.lifecycle.breaker_cooldown_s
            )
            trips, cooldown = (
                self._breaker_trips, self.lifecycle.breaker_cooldown_s,
            )
        logger.error(
            "lifecycle circuit breaker OPEN (trip %d): %d consecutive "
            "failures; triggers suspended for %.0fs",
            trips, self.lifecycle.breaker_failures, cooldown,
        )

    def _note_cycle_complete(self) -> None:
        with self._lock:
            self._consecutive_failures = 0

    # ----------------------------------------------------------- idle step
    def _step_idle(self, now: float) -> None:
        snapshot = self.engine.monitor_snapshot()
        if self.breaker_open(now):
            # Open breaker: the snapshot still advances the differencing
            # baseline (windows stay continuous) through the side-effect-
            # free consume() — observe() here would accumulate hysteresis
            # and arm hidden trigger cooldowns, delaying the documented
            # half-open probe past the breaker window.
            self.policy.consume(snapshot)
            return
        decision = self.policy.observe(snapshot, now)
        if not decision.fired:
            return
        with self._lock:
            self._drift_triggers += 1
            self._state = "retraining"
            self._last_error = ""
        logger.info("lifecycle trigger fired: %s", decision.reason)
        try:
            # Injection point (mlops_tpu/faults): a raise here is the
            # repeated-retrain-failure scenario the circuit breaker
            # exists for (chaos smoke + tests/test_lifecycle.py).
            faults.fire("lifecycle.retrain")
            result = run_retrain(
                self.engine.bundle,
                self.config,
                generation=self.engine.bundle_generation + 1,
                # Attempt-scoped tag: a REJECTED candidate's completed
                # checkpoints must not be resumed by the next trigger
                # (fit would restore the final step and return the same
                # stale params no matter how fresh the labeled window);
                # a crash-restarted attempt still resumes — the counter
                # restarts with the process.
                attempt=self._drift_triggers,
                # The reservoir IS the recent serving window: the
                # candidate's drift reference/outlier detector refit on
                # what traffic actually looks like, not on the labeled
                # file alone.
                reservoir_window=self.reservoir.window(),
            )
            shadow = ShadowEngine(self.engine, result.bundle)
            shadow.warm()
        except LifecycleError as err:
            logger.warning("retrain skipped: %s", err)
            with self._lock:
                self._state = "idle"
                self._last_error = str(err)
            self.policy.start_cooldown(now)
            return
        # Breadth is deliberate at this boundary: ANY retrain/warm
        # failure (corrupt labeled file mid-append, OSError on the state
        # dir, a compile failure) must log + cool down + return to idle,
        # never strand the state machine in 'retraining' while the
        # server keeps serving.
        except Exception as err:  # tpulint: disable=TPU201
            logger.exception("retrain/shadow-warm failed; cooling down")
            with self._lock:
                self._state = "idle"
                self._last_error = f"{type(err).__name__}: {err}"
            self._note_failure(now)
            self.policy.start_cooldown(now)
            return
        logger.info(
            "candidate %s built in %.1fs (warm: %s %.2fs); shadowing",
            result.candidate_dir, result.wall_s, shadow.warm_mode,
            shadow.warm_s,
        )
        with self._lock:
            self._shadow = shadow
            # (candidate-encoded, incumbent-encoded) — identical objects
            # unless the preprocessor was refit; each side is graded in
            # the encode configuration it serves.
            self._holdout = (result.holdout, result.holdout_incumbent)
            self._shadow_since = now
            self._state = "shadowing"

    # --------------------------------------------------------- shadow step
    def _step_shadow(self, now: float) -> None:
        shadow = self._shadow
        if shadow is None:  # defensive: state says shadowing, no shadow
            with self._lock:
                self._state = "idle"
            return
        enough = shadow.mirrors >= self.lifecycle.shadow_min_mirrors
        timed_out = (now - self._shadow_since) >= self.lifecycle.shadow_max_s
        if not (enough or timed_out):
            return
        try:
            # Injection point (mlops_tpu/faults): repeated evaluation
            # failure — the shadow half of the circuit-breaker scenario.
            faults.fire("lifecycle.shadow.evaluate")
            report = shadow.evaluate(*self._holdout)
        # An evaluation that cannot complete (device error mid-holdout)
        # would otherwise retry-fail every tick forever: discard the
        # candidate, cool down, return to idle.
        except Exception as err:  # tpulint: disable=TPU201
            logger.exception("shadow evaluation failed; candidate dropped")
            with self._lock:
                self._last_error = f"{type(err).__name__}: {err}"
                self._shadow = None
                self._holdout = None
                self._state = "idle"
            self._note_failure(now)
            self.policy.start_cooldown(now)
            return
        decision = evaluate_gates(report, self.lifecycle)
        outcome = "rejected"
        if decision.passed and self.lifecycle.auto_promote:
            generation = promote_engine(self.engine, shadow)
            outcome = "promoted"
            logger.info(
                "candidate promoted: generation %d (auc %+0.4f, ece %.4f, "
                "p99 %.2f ms vs %.2f ms, %d mirrors)",
                generation, report.auc_delta, report.ece_candidate,
                report.p99_candidate_ms, report.p99_incumbent_ms,
                report.mirrors,
            )
        else:
            logger.warning(
                "candidate rejected%s: %s",
                "" if decision.passed else " by gates",
                "; ".join(decision.reasons) or "auto_promote disabled",
            )
        with self._lock:
            self._promotions[outcome] += 1
            self._shadow_auc_delta = report.auc_delta
            self._last_report = {
                **{
                    k: (round(v, 6) if isinstance(v, float) else v)
                    for k, v in vars(report).items()
                },
                "gates": decision.as_dict(),
                "outcome": outcome,
            }
            self._shadow = None
            self._holdout = None
            self._state = "idle"
        # A completed cycle — promoted OR gate-rejected — is the loop
        # WORKING; only failures feed the breaker streak.
        self._note_cycle_complete()
        self.policy.start_cooldown(now)

    # ------------------------------------------------------------- rollback
    def rollback(self) -> int:
        """One-call rollback of a promoted-then-regressing bundle."""
        generation = rollback_engine(self.engine)
        with self._lock:
            self._promotions["rolled_back"] += 1
        self.policy.start_cooldown(self._clock())
        logger.warning("bundle rolled back: generation %d", generation)
        return generation

    # -------------------------------------------------------------- status
    def status(self) -> dict:
        now = self._clock()
        with self._lock:
            return {
                "state": self._state,
                "generation": int(self.engine.bundle_generation),
                "drift_triggers": self._drift_triggers,
                "promotions": dict(self._promotions),
                "shadow_auc_delta": self._shadow_auc_delta,
                "reservoir_rows": None,  # filled below, outside the lock
                "tee_drops": self._tee_drops,
                "breaker_open": now < self._breaker_open_until,
                "breaker_trips": self._breaker_trips,
                "consecutive_failures": self._consecutive_failures,
                "last_error": self._last_error,
                "last_report": self._last_report,
            }

    def metrics_snapshot(self) -> dict:
        """The gauge payload both telemetry planes render
        (`serve/metrics.py`): single-process /metrics pulls it per
        scrape; the ring service writes it into shared memory each
        telemetry tick."""
        status = self.status()
        status["reservoir_rows"] = self.reservoir.rows
        return status
