"""Gated promotion: evaluate the shadow evidence, then hot-swap (or not).

Three gates, all spelled out in the decision so an operator can read WHY
a candidate shipped or died (`lifecycle.*` knobs in config.py):

- **AUC**: candidate ROC-AUC on the labeled holdout may trail the
  incumbent's by at most ``max_auc_drop`` (the epsilon) — a candidate
  failing this gate never swaps in.
- **Calibration**: candidate expected calibration error (ECE, equal-width
  bins) must stay under ``max_ece`` — honest probabilities are part of
  the serving contract (the bundle ships temperature-scaled).
- **Latency**: candidate p99 on the mirrored/holdout request shapes must
  stay within ``max_p99_ratio`` x the incumbent's p99 on the same shapes
  (relative, so the gate is meaningful on any backend).

Promotion itself is `InferenceEngine.swap_bundle` — an in-place exec
table + params ref-swap under the engine's existing ``_compile_lock`` ->
``_acc_lock`` discipline, bit-stable for in-flight requests, with the
outgoing state retained so ``rollback_engine`` restores it in one call.

The metric helpers are numpy-only (no jax import) so the gate math runs
identically in the serve process, the offline ``mlops-tpu lifecycle``
pass, and the tests.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from mlops_tpu.config import LifecycleConfig
from mlops_tpu.lifecycle.shadow import ShadowEngine, ShadowReport


def roc_auc_np(scores: np.ndarray, labels: np.ndarray) -> float:
    """ROC-AUC via the Mann-Whitney U statistic with average ranks for
    ties — the numpy twin of `train/metrics.py roc_auc` (same semantics,
    no device program), for gate evaluation off the compiled path."""
    scores = np.asarray(scores, np.float64)
    labels = np.asarray(labels, np.float64)
    n = scores.shape[0]
    if n == 0:
        return 0.5
    order = np.argsort(scores, kind="mergesort")
    sorted_scores = scores[order]
    first = np.searchsorted(sorted_scores, scores, side="left")
    last = np.searchsorted(sorted_scores, scores, side="right")
    ranks = (first + last + 1.0) / 2.0
    n_pos = labels.sum()
    n_neg = n - n_pos
    if n_pos == 0 or n_neg == 0:
        return 0.5
    u = float((ranks * labels).sum()) - n_pos * (n_pos + 1.0) / 2.0
    return float(u / (n_pos * n_neg))


def expected_calibration_error(
    probs: np.ndarray, labels: np.ndarray, bins: int = 10
) -> float:
    """ECE over equal-width probability bins: sum_b (n_b/N) *
    |mean confidence_b - empirical rate_b| — the standard gap between
    what the model says and what happens."""
    probs = np.asarray(probs, np.float64)
    labels = np.asarray(labels, np.float64)
    if probs.size == 0:
        return 0.0
    edges = np.linspace(0.0, 1.0, bins + 1)
    idx = np.clip(np.digitize(probs, edges[1:-1]), 0, bins - 1)
    ece = 0.0
    for b in range(bins):
        sel = idx == b
        n_b = int(sel.sum())
        if not n_b:
            continue
        ece += (n_b / probs.size) * abs(
            float(probs[sel].mean()) - float(labels[sel].mean())
        )
    return float(ece)


@dataclasses.dataclass(frozen=True)
class GateDecision:
    passed: bool
    reasons: tuple[str, ...]  # every FAILED gate, named with its numbers

    def as_dict(self) -> dict:
        return {"passed": self.passed, "reasons": list(self.reasons)}


def evaluate_gates(
    report: ShadowReport, config: LifecycleConfig
) -> GateDecision:
    """The three gates over one shadow report. Latency is skipped (passes)
    when neither side has samples — the offline CLI pass has no mirrored
    traffic and must still be able to grade AUC/ECE."""
    reasons: list[str] = []
    if report.auc_delta < -config.max_auc_drop:
        reasons.append(
            f"auc: candidate {report.auc_candidate:.4f} trails incumbent "
            f"{report.auc_incumbent:.4f} by {-report.auc_delta:.4f} > "
            f"epsilon {config.max_auc_drop:g}"
        )
    if report.ece_candidate > config.max_ece:
        reasons.append(
            f"calibration: candidate ECE {report.ece_candidate:.4f} > "
            f"bound {config.max_ece:g}"
        )
    if report.p99_incumbent_ms > 0 and (
        report.p99_candidate_ms
        > config.max_p99_ratio * report.p99_incumbent_ms
    ):
        reasons.append(
            f"latency: candidate p99 {report.p99_candidate_ms:.2f} ms > "
            f"{config.max_p99_ratio:g}x incumbent "
            f"{report.p99_incumbent_ms:.2f} ms"
        )
    return GateDecision(passed=not reasons, reasons=tuple(reasons))


def quant_tier_gates(
    fidelity: dict[str, float], config: LifecycleConfig
) -> GateDecision:
    """The promotion-gate discipline applied to the QUANTIZED student tier
    at packaging time (`train/distill.py distill_quant_student`).

    Same knobs, same semantics as `evaluate_gates`, different evidence:
    the quant tier never shadows live traffic — its AUC delta vs the
    teacher and its calibrated ECE come from the held-out validation
    split, post-quantization. The decision is STAMPED into the bundle's
    quant manifest block, and `serve/engine.py` refuses to serve (or
    auto-route to) a quant tier whose stamped decision failed — the gate
    runs once where the labels are, not on every engine boot. Latency has
    no gate here: the tier exists to be faster, and the bench round
    measures it directly."""
    reasons: list[str] = []
    delta = fidelity.get("roc_auc_delta")
    if delta is None:
        reasons.append(
            "auc: no labeled validation split — the quant tier cannot be "
            "graded and must not serve"
        )
    elif delta < -config.max_auc_drop:
        reasons.append(
            f"auc: quant student trails the teacher by {-delta:.4f} > "
            f"epsilon {config.max_auc_drop:g}"
        )
    ece = fidelity.get("ece")
    if ece is not None and ece > config.max_ece:
        reasons.append(
            f"calibration: quant ECE {ece:.4f} > bound {config.max_ece:g}"
        )
    return GateDecision(passed=not reasons, reasons=tuple(reasons))


def promote_engine(live, shadow: ShadowEngine) -> int:
    """Install the shadowed candidate into the live engine (zero-downtime
    ref-swap; the candidate engine's device state and warmed exec table
    move in wholesale). Returns the new bundle generation."""
    return live.swap_bundle(shadow.engine)


def rollback_engine(live) -> int:
    """One-call instant rollback to the retained previous bundle."""
    return live.rollback()
