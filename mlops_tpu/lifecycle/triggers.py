"""Trigger policies: when does the monitor evidence justify a retrain?

Evaluated against `InferenceEngine.monitor_snapshot()` aggregates (the
device-resident accumulator's cumulative totals — `monitor/state.py`).
The snapshot's counters are CUMULATIVE, so the policy differences
consecutive snapshots into per-window statistics: windowed mean drift
per feature ((drift_sum_t2 - drift_sum_t1) / (batches_t2 - batches_t1),
recovered from the exported means), windowed outlier rate, and windowed
row count. Firing requires

- enough evidence: the window carries >= ``min_window_rows`` scored rows,
- a breach: any feature's windowed mean drift >= ``drift_threshold``
  (drift scores are ``1 - p_val``) OR the windowed outlier rate >=
  ``outlier_threshold``,
- hysteresis: ``hysteresis_windows`` CONSECUTIVE breached windows — one
  noisy window can never retrain-storm; a clean window resets the streak,
- cooldown: after any fire (or a promotion/rejection outcome, which the
  controller reports via ``start_cooldown``), breaches neither fire nor
  accumulate hysteresis for ``cooldown_s`` — a drift spike inside the
  cooldown window does not re-trigger retrain.

Pure host arithmetic, no locks, no jax: the controller owns threading;
the clock is injected (``now``) so tests drive time deterministically.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from mlops_tpu.config import LifecycleConfig


@dataclasses.dataclass(frozen=True)
class TriggerDecision:
    """One window's verdict (returned by ``TriggerPolicy.observe``)."""

    fired: bool
    reason: str  # "" when not fired; else the named breach
    window_rows: float = 0.0
    drift_max: float = 0.0  # max windowed per-feature mean drift score
    drift_feature: str = ""  # the feature that carried drift_max
    outlier_rate: float = 0.0
    streak: int = 0  # consecutive breached windows so far
    in_cooldown: bool = False


class TriggerPolicy:
    """Threshold + hysteresis + cooldown over consecutive snapshots."""

    def __init__(self, config: LifecycleConfig):
        self.config = config.validate()
        self._prev: dict | None = None  # last snapshot's cumulative view
        self._streak = 0
        self._cooldown_until = float("-inf")

    # ------------------------------------------------------------ control
    def start_cooldown(self, now: float) -> None:
        """Arm the dead time (called on fire and on every candidate
        outcome — promoted, rejected, or rolled back — so the loop
        settles before re-evaluating)."""
        self._cooldown_until = now + self.config.cooldown_s
        self._streak = 0

    def in_cooldown(self, now: float) -> bool:
        return now < self._cooldown_until

    def consume(self, snapshot: dict) -> None:
        """Fold a snapshot into the differencing baseline WITHOUT any
        evaluation side effects — no firing, no hysteresis accumulation,
        no cooldown arming. The lifecycle circuit breaker consumes
        windows this way while open: the window sequence stays
        continuous (the first post-close observation differences against
        fresh state, not the pre-open past), but an open breaker can
        never mutate the trigger machinery's state."""
        if snapshot:
            self._prev = _cumulative_view(snapshot)

    # ------------------------------------------------------------ observe
    def observe(self, snapshot: dict, now: float) -> TriggerDecision:
        """Fold one cumulative snapshot; decide whether to fire."""
        if not snapshot:
            return TriggerDecision(fired=False, reason="")
        cum = _cumulative_view(snapshot)
        prev, self._prev = self._prev, cum
        if prev is None:
            # First observation: no window to difference yet. The
            # cumulative totals become the baseline — everything before
            # the policy attached is pre-history, not evidence.
            return TriggerDecision(fired=False, reason="")
        rows = cum["rows"] - prev["rows"]
        batches = cum["batches"] - prev["batches"]
        outliers = cum["outliers"] - prev["outliers"]
        if batches <= 0 or rows <= 0:
            return TriggerDecision(fired=False, reason="", window_rows=rows)
        drift = (cum["drift_sum"] - prev["drift_sum"]) / batches
        feature_idx = int(np.argmax(drift))
        drift_max = float(drift[feature_idx])
        outlier_rate = float(outliers / rows)
        decision = dict(
            window_rows=float(rows),
            drift_max=drift_max,
            drift_feature=cum["features"][feature_idx],
            outlier_rate=outlier_rate,
            in_cooldown=self.in_cooldown(now),
        )
        if decision["in_cooldown"]:
            # Cooldown: breaches neither fire nor accumulate hysteresis.
            return TriggerDecision(fired=False, reason="", **decision)
        if rows < self.config.min_window_rows:
            # NO EVIDENCE, not a clean bill: a thin window (traffic lull,
            # bursty arrival straddling ticks) leaves the streak
            # untouched — resetting here would let alternating thin/full
            # windows mask hours of sustained real drift forever.
            return TriggerDecision(
                fired=False, reason="", streak=self._streak, **decision
            )
        breach = ""
        if drift_max >= self.config.drift_threshold:
            breach = (
                f"drift {drift_max:.3f} >= "
                f"{self.config.drift_threshold:g} on "
                f"{decision['drift_feature']}"
            )
        elif outlier_rate >= self.config.outlier_threshold:
            breach = (
                f"outlier rate {outlier_rate:.3f} >= "
                f"{self.config.outlier_threshold:g}"
            )
        if not breach:
            self._streak = 0
            return TriggerDecision(fired=False, reason="", **decision)
        self._streak += 1
        if self._streak < self.config.hysteresis_windows:
            return TriggerDecision(
                fired=False, reason="", streak=self._streak, **decision
            )
        self.start_cooldown(now)
        return TriggerDecision(
            fired=True,
            reason=breach,
            streak=self.config.hysteresis_windows,
            **decision,
        )


def _cumulative_view(snapshot: dict) -> dict:
    """Snapshot dict -> the cumulative quantities the window differencing
    needs. Prefers the UNROUNDED ``drift_sum`` the engine exports
    (serve/engine.py monitor_snapshot): reconstructing the sum from the
    6-decimal-rounded display means would carry up to ``5e-7 * batches``
    of error — unbounded over a long-lived server, enough to fire (or
    mask) triggers spuriously after hours of uptime. The mean*batches
    fallback exists only for foreign snapshot producers (test stubs)."""
    features = list(snapshot["drift_mean"])
    batches = float(snapshot["batches"])
    if "drift_sum" in snapshot:
        drift_sum = np.asarray(snapshot["drift_sum"], np.float64)
    else:
        mean = np.asarray(
            [snapshot["drift_mean"][name] for name in features], np.float64
        )
        drift_sum = mean * max(batches, 0.0)
    return {
        "rows": float(snapshot["rows"]),
        "outliers": float(snapshot["outliers"]),
        "batches": batches,
        "drift_sum": drift_sum,
        "features": features,
    }
