"""Suppression audit: enumerate every ``# tpulint: disable`` and keep it
honest.

A disable comment is a debt note: it asserts "this rule fires here and the
pattern is deliberately safe". When the flagged code is later refactored
away, the comment silently survives — and a stale disable is worse than
none, because it pre-silences the NEXT real violation someone writes on
that line. ``mlops-tpu analyze --list-suppressions`` reports every disable
in the tree with its file:line, rule ids, and live/stale status;
``--fail-stale`` turns stale ones into gating TPU400 findings (CI runs it
so the PR 1/3/4 disables stay honest).

Staleness is decided by re-running the suppressible layers (Layer 1 AST
rules + Layer 3 concurrency rules) with suppression filtering OFF and
checking whether any finding lands where the comment applies — the exact
``findings.is_suppressed`` geometry: a trailing comment covers its own
line, a standalone comment line covers the line below. Comments are read
with ``tokenize``, so the disable examples living in docstrings (this
package documents its own syntax) are never mistaken for suppressions.

TPU400 findings are deliberately immune to disable comments: a stale
suppression must not be able to suppress its own staleness report.
"""

from __future__ import annotations

import dataclasses
import io
import tokenize
from pathlib import Path
from typing import Iterable

from mlops_tpu.analysis.astrules import analyze_source, iter_py_files
from mlops_tpu.analysis.concurrency import analyze_concurrency_source
from mlops_tpu.analysis.findings import (
    Finding,
    Severity,
    file_skipped,
    suppressed_rules,
)

STALE_RULE = "TPU400"
STALE_NAME = "stale-suppression"


@dataclasses.dataclass(frozen=True)
class Suppression:
    """One ``# tpulint: disable`` comment found in the tree."""

    path: str
    line: int
    rules: frozenset[str]  # empty = bare disable (every rule)
    standalone: bool  # comment-only line (covers the line below too)
    live: bool  # a finding exists that this comment suppresses
    skipped_file: bool = False  # inside a `# tpulint: skip-file` module

    def describe(self) -> str:
        rules = ",".join(sorted(self.rules)) if self.rules else "ALL"
        status = (
            "skip-file"
            if self.skipped_file
            else ("live" if self.live else "STALE")
        )
        return f"{self.path}:{self.line}: disable={rules} [{status}]"


def _comments(source: str) -> list[tuple[int, str, bool]]:
    """(line, text, standalone) for every comment token. tokenize sees
    only real comments — disable examples inside docstrings are STRING
    tokens and never counted."""
    out: list[tuple[int, str, bool]] = []
    lines = source.splitlines()
    try:
        tokens = tokenize.generate_tokens(io.StringIO(source).readline)
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            lineno = tok.start[0]
            text = lines[lineno - 1] if lineno <= len(lines) else tok.string
            out.append((lineno, tok.string, text.lstrip().startswith("#")))
    except (tokenize.TokenError, IndentationError, SyntaxError):
        pass  # Layer 1 reports the syntax error; nothing to audit here
    return out


def audit_file(
    source: str,
    path: str | Path,
    rel_path: str | Path | None = None,
    extra_findings: Iterable[Finding] = (),
) -> list[Suppression]:
    """Every disable comment in one file, with live/stale resolved against
    a suppression-off run of the suppressible layers. ``extra_findings``
    carries findings from layers that can't re-run per file — Layer 4's
    contract rules are cross-file (one file's manifest governs another's
    write sites), so `audit_paths` computes them project-wide once and
    passes this file's slice in."""
    path = str(path)
    skipped = file_skipped(source)
    raw = [
        (lineno, rules, standalone)
        for lineno, text, standalone in _comments(source)
        if (rules := suppressed_rules(text)) is not None
    ]
    if not raw:
        return []
    if skipped:
        return [
            Suppression(path, lineno, frozenset(rules), standalone,
                        live=False, skipped_file=True)
            for lineno, rules, standalone in raw
        ]
    findings = (
        analyze_source(source, path, rel_path=rel_path, keep_suppressed=True)
        + analyze_concurrency_source(source, path, keep_suppressed=True)
        + list(extra_findings)
    )
    by_line: dict[int, set[str]] = {}
    for f in findings:
        by_line.setdefault(f.line, set()).add(f.rule)

    def covers(lineno: int, rules: set[str], standalone: bool) -> bool:
        lines_covered = [lineno] + ([lineno + 1] if standalone else [])
        for covered in lines_covered:
            fired = by_line.get(covered, set())
            if fired and (not rules or rules & fired):
                return True
        return False

    return [
        Suppression(
            path,
            lineno,
            frozenset(rules),
            standalone,
            live=covers(lineno, rules, standalone),
        )
        for lineno, rules, standalone in raw
    ]


def audit_paths(paths: Iterable[str | Path]) -> list[Suppression]:
    from mlops_tpu.analysis.asyncdiscipline import analyze_async_paths
    from mlops_tpu.analysis.contracts import analyze_contracts_paths

    # Layer-4 and Layer-5 findings are project-wide (cross-file
    # manifests / call graph), so one suppression-off pass each up
    # front, sliced per file below — a disable covering a TPU501-504 or
    # TPU601-604 finding counts as live whether or not the current
    # invocation passed --contracts/--async.
    paths = list(paths)
    project_by_file: dict[str, list[Finding]] = {}
    for finding in analyze_contracts_paths(
        paths, keep_suppressed=True
    ) + analyze_async_paths(paths, keep_suppressed=True):
        project_by_file.setdefault(finding.path, []).append(finding)
    out: list[Suppression] = []
    for file, rel in iter_py_files(paths):
        out.extend(
            audit_file(
                file.read_text(encoding="utf-8"),
                file.as_posix(),
                rel_path=rel.as_posix(),
                extra_findings=project_by_file.get(file.as_posix(), ()),
            )
        )
    return out


def stale_findings(paths: Iterable[str | Path]) -> list[Finding]:
    """Stale suppressions as gating findings (``--fail-stale``)."""
    return [
        Finding(
            rule=STALE_RULE,
            name=STALE_NAME,
            severity=Severity.ERROR,
            path=s.path,
            line=s.line,
            message=(
                "suppression ("
                + (",".join(sorted(s.rules)) if s.rules else "ALL")
                + ") no longer suppresses any finding — the flagged code "
                "moved or was fixed; delete the comment (a stale disable "
                "pre-silences the next real violation on this line)"
            ),
        )
        for s in audit_paths(paths)
        if not s.live and not s.skipped_file
    ]


def format_suppressions(suppressions: list[Suppression]) -> str:
    ordered = sorted(suppressions, key=lambda s: (s.path, s.line))
    stale = sum(1 for s in ordered if not s.live and not s.skipped_file)
    lines = [s.describe() for s in ordered]
    lines.append(
        f"tpulint: {len(ordered)} suppression(s), {stale} stale"
    )
    return "\n".join(lines)
