"""Layer 4: cross-process contract analysis.

Layers 1-3 check one file at a time; the bugs the last three review
rounds actually found live BETWEEN processes and files — a shm field
written by a role that doesn't own it, a Prometheus series one renderer
emits and the other dropped, an alert rule referencing a renamed series,
a config knob that validates and is never read (PR 13's
``replica_affinity_slack``), a declared fault point no chaos test can
fire. This layer analyzes the package as one PROJECT: manifests are
collected from every file first, then every file is evaluated against
them. Pure ``ast`` — like Layers 1 and 3, this module must never import
JAX.

======== ============================== =======================================
ID       name                           catches
======== ============================== =======================================
TPU501   shm-ownership                  a shm ring field written from a role
                                        that is not its declared owner
                                        (``TPULINT_SHM_OWNERSHIP``), from a
                                        context with no declared role, or a
                                        ring cell-write to an undeclared field
TPU502   series-contract                a series emitted on one metrics plane
                                        but not the other (outside the
                                        declared single-plane allowlist), an
                                        unbounded (formatted, non-closed-set)
                                        label value, an alert rule referencing
                                        a series absent from the registry, or
                                        a registry series undocumented in
                                        ``docs/observability.md``
TPU503   dead-knob                      a config dataclass field never read
                                        outside the config module's class
                                        bodies (a validated no-op knob)
TPU504   fault-point-liveness           a declared fault point with no
                                        ``faults.fire``/``faults.corrupt``
                                        site, or a site naming an undeclared
                                        point
======== ============================== =======================================

Declarations are plain literals next to the contracts they describe, read
from source and never imported (the Layer-3 manifest discipline):

- ``serve/ipc.py``: ``TPULINT_SHM_OWNERSHIP`` maps each shm field to its
  writer role — a string for single-writer fields, a tuple for a declared
  handoff (every listed role may write). ``TPULINT_SHM_ROLES`` maps
  ``"Class"``, ``"Class.method"`` (most specific wins) or a module-level
  function name to one of the roles. A write participates when its target
  is a CELL write (subscripted or augmented) reached through a receiver
  containing a ``ring`` component, or through ``self`` inside a class
  with any role entry — plain attribute rebinding (view construction in
  ``__init__``) is not a data write. Writes through a local alias
  (``row = ring.mon_vals[r]; row[...] = x``) are invisible to this lexical
  pass; keep aliased writes inside their owning role.
- ``serve/metrics.py``: the series-plane manifests (`analysis/seriesreg.py`
  documents them).
- ``config.py``: ``TPULINT_CONFIG_MODULE = True`` opts the module's
  ``*Config`` dataclasses into TPU503. A field is live when its name is
  read as an attribute (or a literal ``getattr``) anywhere outside the
  config module's dataclass bodies and outside tests.
- ``faults/__init__.py``: the existing ``POINTS`` dict IS the TPU504
  manifest.

Each family only runs when its manifest exists in the analyzed project,
and every finding rides the normal suppression machinery
(``# tpulint: disable=TPU501`` + justification, audited by TPU400).
"""

from __future__ import annotations

import ast
import dataclasses
import re
from pathlib import Path
from typing import Iterable

from mlops_tpu.analysis.findings import (
    Finding,
    Severity,
    file_skipped,
    is_suppressed,
)
from mlops_tpu.analysis.seriesreg import (
    SeriesRegistry,
    build_registry,
    module_literals,
)

SHM_OWNERSHIP_NAME = "TPULINT_SHM_OWNERSHIP"
SHM_ROLES_NAME = "TPULINT_SHM_ROLES"
CONFIG_MODULE_NAME = "TPULINT_CONFIG_MODULE"
FAULT_POINTS_NAME = "POINTS"

_SERIES_TOKEN = re.compile(r"mlops_tpu_\w+")
# Rule/group IDENTIFIER lines in alert yml — a group or alert name is a
# free-form label, not a series reference, even when it matches the
# series prefix (`- name: mlops_tpu_slo_relay` names a group).
_YML_IDENTIFIER_LINE = re.compile(r"^\s*-?\s*(name|alert)\s*:")


@dataclasses.dataclass(frozen=True)
class RuleInfo:
    rule: str
    name: str
    severity: Severity
    summary: str


CONTRACT_RULES: dict[str, RuleInfo] = {
    r.rule: r
    for r in (
        RuleInfo(
            "TPU501",
            "shm-ownership",
            Severity.ERROR,
            "shm field written by a role that does not own it",
        ),
        RuleInfo(
            "TPU502",
            "series-contract",
            Severity.ERROR,
            "metric series breaks the cross-plane/alert/docs contract",
        ),
        RuleInfo(
            "TPU503",
            "dead-knob",
            Severity.ERROR,
            "config dataclass field is never read (validated no-op)",
        ),
        RuleInfo(
            "TPU504",
            "fault-point-liveness",
            Severity.ERROR,
            "fault point declared without a fire site, or fired undeclared",
        ),
    )
}


@dataclasses.dataclass
class _Module:
    path: str
    source: str
    tree: ast.Module
    lines: list[str]


def _parse_project(
    items: Iterable[tuple[str, str]],
) -> list[_Module]:
    modules: list[_Module] = []
    for path, source in items:
        if file_skipped(source):
            continue
        try:
            tree = ast.parse(source, filename=path)
        except SyntaxError:
            continue  # Layer 1 already reports TPU000 for these
        modules.append(_Module(path, source, tree, source.splitlines()))
    return modules


def _flag(
    findings: list[Finding], rule: str, path: str, line: int, message: str
) -> None:
    info = CONTRACT_RULES[rule]
    findings.append(
        Finding(
            rule=info.rule,
            name=info.name,
            severity=info.severity,
            path=path,
            line=line,
            message=message,
        )
    )


# --------------------------------------------------------------- TPU501
def _attr_chain(node: ast.AST) -> tuple[tuple[str, ...], int] | None:
    """Unwrap a write target into (dotted components, subscript depth):
    ``self.ring.shed[w] += 1`` -> (("self", "ring", "shed"), 1). ``None``
    when the target doesn't bottom out in a plain name chain."""
    depth = 0
    while isinstance(node, ast.Subscript):
        depth += 1
        node = node.value
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name) or not parts:
        return None
    parts.append(node.id)
    return tuple(reversed(parts)), depth


def _iter_write_targets(fn: ast.AST):
    """(target, is_aug) for every assignment target inside ``fn``,
    including nested defs (they execute in the same role's process)."""
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign):
            for target in node.targets:
                yield target, False
        elif isinstance(node, ast.AugAssign):
            yield node.target, True
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            yield node.target, False


def _check_shm(modules: list[_Module]) -> list[Finding]:
    ownership: dict[str, tuple[str, ...]] = {}
    roles: dict[str, str] = {}
    for mod in modules:
        literals = module_literals(
            mod.tree, {SHM_OWNERSHIP_NAME, SHM_ROLES_NAME}
        )
        value = literals.get(SHM_OWNERSHIP_NAME)
        if isinstance(value, dict):
            for field, owner in value.items():
                ownership[str(field)] = (
                    tuple(str(o) for o in owner)
                    if isinstance(owner, (tuple, list))
                    else (str(owner),)
                )
        value = literals.get(SHM_ROLES_NAME)
        if isinstance(value, dict):
            roles.update({str(k): str(v) for k, v in value.items()})
    if not ownership:
        return []

    findings: list[Finding] = []

    def check_function(
        mod: _Module, fn: ast.AST, cls: str | None
    ) -> None:
        fn_name = fn.name
        if cls is not None:
            role = roles.get(f"{cls}.{fn_name}", roles.get(cls))
            context = f"{cls}.{fn_name}"
            class_has_role = cls in roles or any(
                key.startswith(f"{cls}.") for key in roles
            )
        else:
            role = roles.get(fn_name)
            context = fn_name
            class_has_role = False
        for target, is_aug in _iter_write_targets(fn):
            chain = _attr_chain(target)
            if chain is None:
                continue
            parts, depth = chain
            receiver, field = parts[:-1], parts[-1]
            if not (depth > 0 or is_aug):
                continue  # plain rebinding: view construction, not data
            through_ring = "ring" in receiver
            through_self = receiver == ("self",) and class_has_role
            if not (through_ring or through_self):
                continue
            line = target.lineno
            if field in ownership:
                owners = ownership[field]
                if role is None:
                    _flag(
                        findings,
                        "TPU501",
                        mod.path,
                        line,
                        f"shm field {field!r} (owner: "
                        f"{'/'.join(owners)}) written from {context}, "
                        f"which has no declared role — add it to "
                        f"{SHM_ROLES_NAME} or move the write into its "
                        "owning role",
                    )
                elif role not in owners:
                    _flag(
                        findings,
                        "TPU501",
                        mod.path,
                        line,
                        f"shm field {field!r} is owned by "
                        f"{'/'.join(owners)} but written from {context} "
                        f"(role {role!r}) — a second writer races the "
                        "owner; declare a handoff tuple in "
                        f"{SHM_OWNERSHIP_NAME} only if the protocol "
                        "really passes ownership",
                    )
            elif through_ring:
                _flag(
                    findings,
                    "TPU501",
                    mod.path,
                    line,
                    f"ring cell-write to undeclared shm field {field!r} "
                    f"from {context} — every shared-memory field needs an "
                    f"owner in {SHM_OWNERSHIP_NAME}",
                )

    for mod in modules:
        for node in mod.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                check_function(mod, node, None)
            elif isinstance(node, ast.ClassDef):
                for item in node.body:
                    if isinstance(
                        item, (ast.FunctionDef, ast.AsyncFunctionDef)
                    ):
                        check_function(mod, item, node.name)
    return findings


# --------------------------------------------------------------- TPU502
def _aux_roots(
    paths: Iterable[str | Path],
) -> tuple[list[Path], Path | None]:
    """(alert rule files, observability doc) discovered near the analyzed
    paths: ``configs/alerts/*.yml`` and ``docs/observability.md`` at the
    path itself or up to two parents (the repo layout whether the gate
    analyzes ``mlops_tpu/`` from the root or the package by absolute
    path), plus any yml directly under an analyzed directory (fixtures)."""
    alert_files: list[Path] = []
    docs_file: Path | None = None
    seen: set[str] = set()

    def add_alerts(directory: Path) -> None:
        for pattern in ("*.yml", "*.yaml"):
            for file in sorted(directory.glob(pattern)):
                key = file.resolve().as_posix()
                if key not in seen:
                    seen.add(key)
                    alert_files.append(file)

    for p in paths:
        p = Path(p)
        resolved = p.resolve()
        for base in (resolved, *list(resolved.parents)[:2]):
            alerts_dir = base / "configs" / "alerts"
            if alerts_dir.is_dir():
                add_alerts(alerts_dir)
            doc = base / "docs" / "observability.md"
            if docs_file is None and doc.is_file():
                docs_file = doc
        if p.is_dir():
            for pattern in ("*.yml", "*.yaml"):
                for file in sorted(p.rglob(pattern)):
                    key = file.resolve().as_posix()
                    if key not in seen:
                        seen.add(key)
                        alert_files.append(file)
        elif p.suffix in (".yml", ".yaml") and p.is_file():
            key = resolved.as_posix()
            if key not in seen:
                seen.add(key)
                alert_files.append(p)
    return alert_files, docs_file


def _check_series(
    modules: list[_Module],
    registry: SeriesRegistry | None,
    alert_files: list[Path],
    docs_file: Path | None,
    extra_sources: dict[str, str],
) -> list[Finding]:
    if registry is None:
        return []
    findings: list[Finding] = []
    plane_names = sorted(registry.planes)
    # Parity only means something with two or more declared planes.
    if len(plane_names) >= 2:
        for name in sorted(registry.series):
            info = registry.series[name]
            missing = [p for p in plane_names if p not in info.planes]
            if not missing:
                continue
            present = sorted(info.planes)
            allowlisted = any(
                name in registry.plane_only.get(p, set()) for p in present
            )
            if allowlisted:
                continue
            path, line = info.sites[0]
            _flag(
                findings,
                "TPU502",
                path,
                line,
                f"series {name!r} is emitted on the "
                f"{'/'.join(present)} plane but not on "
                f"{'/'.join(missing)} — a scrape of the other endpoint "
                "flatlines its panels; emit it there or declare it in "
                "TPULINT_PLANE_ONLY_SERIES",
            )
    for name in sorted(registry.series):
        info = registry.series[name]
        for path, line, key in info.dynamic_labels:
            if key in registry.bounded_labels:
                continue
            _flag(
                findings,
                "TPU502",
                path,
                line,
                f"label {key!r} on {name!r} takes a formatted value "
                "outside the declared closed sets "
                "(TPULINT_BOUNDED_LABELS) — unbounded label values are "
                "unbounded series cardinality",
            )
    known = registry.names()
    for file in alert_files:
        try:
            text = extra_sources.get(
                file.as_posix(), file.read_text(encoding="utf-8")
            )
        except OSError:
            continue
        for lineno, line in enumerate(text.splitlines(), start=1):
            if _YML_IDENTIFIER_LINE.match(line):
                continue
            for token in _SERIES_TOKEN.findall(line):
                if token in known:
                    continue
                _flag(
                    findings,
                    "TPU502",
                    file.as_posix(),
                    lineno,
                    f"alert rule references series {token!r}, which no "
                    "renderer emits — this expression can never fire; "
                    "fix the name or delete the rule",
                )
    if docs_file is not None:
        try:
            docs_text = docs_file.read_text(encoding="utf-8")
        except OSError:
            docs_text = ""
        for name in sorted(registry.series):
            info = registry.series[name]
            if name in docs_text or info.base_name in docs_text:
                continue
            path, line = info.sites[0]
            _flag(
                findings,
                "TPU502",
                path,
                line,
                f"series {name!r} is emitted but undocumented in "
                f"{docs_file.as_posix()} — operators can't alert on a "
                "series they don't know exists",
            )
    return findings


# --------------------------------------------------------------- TPU503
def _is_dataclass(node: ast.ClassDef) -> bool:
    for deco in node.decorator_list:
        target = deco.func if isinstance(deco, ast.Call) else deco
        leaf = (
            target.attr
            if isinstance(target, ast.Attribute)
            else getattr(target, "id", None)
        )
        if leaf == "dataclass":
            return True
    return False


def _is_test_path(path: str) -> bool:
    parts = Path(path).parts
    if "fixtures" in parts:
        # Lint-corpus fixtures simulate production code: their reads count
        # even though the corpus lives under tests/.
        return False
    return any(part in ("tests", "test") for part in parts) or Path(
        path
    ).name.startswith("test_")


def _check_knobs(modules: list[_Module]) -> list[Finding]:
    config_modules = [
        mod
        for mod in modules
        if module_literals(mod.tree, {CONFIG_MODULE_NAME}).get(
            CONFIG_MODULE_NAME
        )
        is True
    ]
    if not config_modules:
        return []

    # field name -> [(module, class, line)], declared in config dataclasses.
    fields: dict[str, list[tuple[_Module, str, int]]] = {}
    config_class_nodes: list[tuple[_Module, ast.ClassDef]] = []
    for mod in config_modules:
        for node in mod.tree.body:
            if (
                isinstance(node, ast.ClassDef)
                and node.name.endswith("Config")
                and _is_dataclass(node)
            ):
                config_class_nodes.append((mod, node))
                for stmt in node.body:
                    if isinstance(stmt, ast.AnnAssign) and isinstance(
                        stmt.target, ast.Name
                    ):
                        name = stmt.target.id
                        if name.startswith("_"):
                            continue
                        fields.setdefault(name, []).append(
                            (mod, node.name, stmt.lineno)
                        )
    if not fields:
        return []

    # Reads: Load-context attribute names (plus literal getattr) anywhere
    # outside the config dataclass bodies and outside tests. Name-based on
    # purpose — a collision errs toward "live", never a false dead-knob.
    excluded = {
        id(sub)
        for _mod, cls in config_class_nodes
        for sub in ast.walk(cls)
    }
    reads: set[str] = set()
    for mod in modules:
        if _is_test_path(mod.path):
            continue
        for node in ast.walk(mod.tree):
            if id(node) in excluded:
                continue
            if isinstance(node, ast.Attribute) and isinstance(
                node.ctx, ast.Load
            ):
                reads.add(node.attr)
            elif (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "getattr"
                and len(node.args) >= 2
                and isinstance(node.args[1], ast.Constant)
                and isinstance(node.args[1].value, str)
            ):
                reads.add(node.args[1].value)

    findings: list[Finding] = []
    for name in sorted(fields):
        if name in reads:
            continue
        for mod, cls, line in fields[name]:
            _flag(
                findings,
                "TPU503",
                mod.path,
                line,
                f"config knob {cls}.{name} is constructed and validated "
                "but never read outside the config module — a setting "
                "that changes nothing (the PR 13 "
                "replica_affinity_slack class); wire it or delete it",
            )
    return findings


# --------------------------------------------------------------- TPU504
def _check_faults(modules: list[_Module]) -> list[Finding]:
    # name -> (module, key line) for every module-level POINTS dict.
    declared: dict[str, tuple[_Module, int]] = {}
    found_manifest = False
    for mod in modules:
        for node in mod.tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                target, value = node.targets[0], node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                target, value = node.target, node.value
            else:
                continue
            if (
                not isinstance(target, ast.Name)
                or target.id != FAULT_POINTS_NAME
                or not isinstance(value, ast.Dict)
            ):
                continue
            keys = [
                k
                for k in value.keys
                if isinstance(k, ast.Constant) and isinstance(k.value, str)
            ]
            if not keys or len(keys) != len(value.keys):
                continue  # not a string-keyed fault manifest
            found_manifest = True
            for key in keys:
                declared.setdefault(key.value, (mod, key.lineno))
    if not found_manifest:
        return []

    fired: dict[str, list[tuple[str, int]]] = {}
    for mod in modules:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if isinstance(func, ast.Attribute):
                if func.attr not in ("fire", "corrupt"):
                    continue
                receiver = func.value
                leaf_parts: list[str] = []
                while isinstance(receiver, ast.Attribute):
                    leaf_parts.append(receiver.attr)
                    receiver = receiver.value
                if isinstance(receiver, ast.Name):
                    leaf_parts.append(receiver.id)
                if "faults" not in leaf_parts:
                    continue
            elif isinstance(func, ast.Name):
                if func.id not in ("fire", "corrupt"):
                    continue
            else:
                continue
            if not node.args:
                continue
            first = node.args[0]
            if not (
                isinstance(first, ast.Constant)
                and isinstance(first.value, str)
            ):
                continue  # dynamic point name: out of lexical reach
            fired.setdefault(first.value, []).append(
                (mod.path, node.lineno)
            )

    findings: list[Finding] = []
    for name in sorted(declared):
        if name in fired:
            continue
        mod, line = declared[name]
        _flag(
            findings,
            "TPU504",
            mod.path,
            line,
            f"fault point {name!r} is declared but has no "
            "faults.fire/faults.corrupt site — chaos coverage that can "
            "never trigger; add the site or delete the point",
        )
    for name in sorted(fired):
        if name in declared:
            continue
        for path, line in fired[name]:
            _flag(
                findings,
                "TPU504",
                path,
                line,
                f"fault site names undeclared point {name!r} — the "
                f"armed-points registry ({FAULT_POINTS_NAME}) can never "
                "arm it, so this injection is dead code",
            )
    return findings


# --------------------------------------------------------------- driver
def _analyze_project(
    modules: list[_Module],
    alert_files: list[Path],
    docs_file: Path | None,
    keep_suppressed: bool,
    extra_sources: dict[str, str] | None = None,
) -> list[Finding]:
    extra_sources = extra_sources or {}
    registry = build_registry([(m.path, m.tree) for m in modules])
    findings = (
        _check_shm(modules)
        + _check_series(
            modules, registry, alert_files, docs_file, extra_sources
        )
        + _check_knobs(modules)
        + _check_faults(modules)
    )
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    if keep_suppressed:
        return findings
    lines_by_path = {m.path: m.lines for m in modules}
    for file in alert_files:
        text = extra_sources.get(file.as_posix())
        if text is None:
            try:
                text = file.read_text(encoding="utf-8")
            except OSError:
                text = ""
        lines_by_path[file.as_posix()] = text.splitlines()
    return [
        f
        for f in findings
        if not is_suppressed(f, lines_by_path.get(f.path, []))
    ]


def analyze_contracts_source(
    source: str, path: str | Path, keep_suppressed: bool = False
) -> list[Finding]:
    """Run every Layer-4 rule over one file as a single-file project —
    the fixture/test entry point. Cross-file contracts obviously see only
    this file's manifests and sites."""
    path = str(path)
    modules = _parse_project([(path, source)])
    if not modules:
        return []
    return _analyze_project(
        modules, alert_files=[], docs_file=None,
        keep_suppressed=keep_suppressed,
    )


def analyze_contracts_paths(
    paths: Iterable[str | Path], keep_suppressed: bool = False
) -> list[Finding]:
    """Layer-4 lint over every ``.py`` under ``paths`` as ONE project,
    plus the alert-rule/doc surfaces discovered next to them."""
    from mlops_tpu.analysis.astrules import iter_py_files

    paths = list(paths)
    items: list[tuple[str, str]] = []
    for file, _rel in iter_py_files(paths):
        items.append((file.as_posix(), file.read_text(encoding="utf-8")))
    modules = _parse_project(items)
    alert_files, docs_file = _aux_roots(paths)
    return _analyze_project(
        modules, alert_files, docs_file, keep_suppressed=keep_suppressed
    )
