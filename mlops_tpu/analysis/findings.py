"""Finding/severity types shared by both analyzer layers — no JAX import.

A ``Finding`` is one report line (``file:line: TPU101 [error] message``)
plus enough structure for the CLI to sort, filter, and gate on it. The
inline suppression syntax (``# tpulint: disable=TPU101,TPU202`` on the
flagged line, or a bare ``# tpulint: disable`` for every rule) is resolved
here so Layer 1 and Layer 2 share one implementation.
"""

from __future__ import annotations

import dataclasses
import enum
import re


class Severity(enum.Enum):
    WARNING = "warning"
    ERROR = "error"


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str  # "TPU101"
    name: str  # "host-sync-under-jit"
    severity: Severity
    path: str  # repo-relative file, or "<trace:entry-name>" for Layer 2
    line: int  # 1-based; 0 when the finding has no source anchor
    message: str

    def format(self) -> str:
        return (
            f"{self.path}:{self.line}: {self.rule} "
            f"[{self.severity.value}] {self.message} ({self.name})"
        )

    def gates(self, strict: bool) -> bool:
        """Does this finding fail the run? Errors always; warnings under
        ``--strict`` (the CI mode)."""
        return self.severity is Severity.ERROR or strict


def format_findings(findings: list[Finding]) -> str:
    ordered = sorted(findings, key=lambda f: (f.path, f.line, f.rule))
    return "\n".join(f.format() for f in ordered)


_DISABLE_RE = re.compile(
    r"#\s*tpulint:\s*disable(?:=(?P<rules>[A-Z0-9, ]+))?"
)
_SKIP_FILE_RE = re.compile(r"#\s*tpulint:\s*skip-file")


def suppressed_rules(source_line: str) -> set[str] | None:
    """Rules suppressed by ``source_line``'s trailing comment.

    Returns None when the line carries no tpulint comment, the empty set
    for a bare ``# tpulint: disable`` (= every rule), else the named rules.
    """
    m = _DISABLE_RE.search(source_line)
    if m is None:
        return None
    rules = m.group("rules")
    if rules is None:
        return set()
    return {r.strip() for r in rules.split(",") if r.strip()}


def is_suppressed(finding: Finding, source_lines: list[str]) -> bool:
    """Inline suppression: a ``# tpulint: disable[=RULES]`` comment on the
    flagged line, or a STANDALONE comment line directly above it (for
    lines too long to carry a trailing comment), silences the finding. A
    trailing comment on the previous code line does NOT leak downward —
    it belongs to that line's own violation."""
    candidates = [(finding.line, False), (finding.line - 1, True)]
    for lineno, must_be_standalone in candidates:
        if not 1 <= lineno <= len(source_lines):
            continue
        line = source_lines[lineno - 1]
        if must_be_standalone and not line.lstrip().startswith("#"):
            continue
        rules = suppressed_rules(line)
        if rules is not None and (not rules or finding.rule in rules):
            return True
    return False


def file_skipped(source: str) -> bool:
    """``# tpulint: skip-file`` anywhere in the first 5 lines opts a whole
    file out (generated code, vendored snippets)."""
    head = "\n".join(source.splitlines()[:5])
    return _SKIP_FILE_RE.search(head) is not None
