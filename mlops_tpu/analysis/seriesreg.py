"""Series registry: static extraction of every ``mlops_tpu_*`` series.

The serving stack renders Prometheus text from TWO independent roots —
the single-process endpoint (`serve/server.py HttpServer._metrics_endpoint`,
composing `ServingMetrics.render()` + the shape/SLO/ledger renderers) and
the shm-ring endpoint (`serve/frontend.py FrontendServer._metrics_endpoint`
-> `render_ring_metrics`). Dashboards and the shipped alert rules
(`configs/alerts/*.yml`) reference series by NAME, so a series that one
renderer emits and the other silently dropped is an outage that only shows
up as a flatlined panel. This module rebuilds the series surface from the
source itself: f-strings in every function reachable from each declared
plane root are reconstructed (formatted values become ``\\x00``
placeholders), scanned for ``# TYPE`` declarations, ``name{label="..."}``
emissions and bare-name emissions, and folded into one registry the
Layer-4 contract rules (TPU502, `analysis/contracts.py`) and the bench
gate (`scripts/bench_check.py`) both consume — the static and CI halves
can never disagree about which series exist.

Declarations are plain literals in the renderer module (`serve/metrics.py`),
read from source and never imported:

    TPULINT_SERIES_PLANES = {
        "single": ("HttpServer._metrics_endpoint",),
        "ring": ("FrontendServer._metrics_endpoint",),
    }
    TPULINT_PLANE_ONLY_SERIES = {"ring": ("mlops_tpu_ring_depth", ...)}
    TPULINT_BOUNDED_LABELS = ("route", "status", "tenant", ...)

``TPULINT_SERIES_PLANES`` maps a plane name to its root qualnames
(``Class.method`` or a bare function name). Reachability is a leaf-name
call closure: deliberately over-approximate (any ``.render()`` call links
to every ``render`` definition in the project), which errs toward seeing a
series on MORE planes, never toward inventing a missing one.
``TPULINT_PLANE_ONLY_SERIES`` is the declared allowlist for series that
legitimately exist on one plane. ``TPULINT_BOUNDED_LABELS`` names the
label KEYS whose runtime values come from closed sets — a formatted label
value under any other key is unbounded cardinality (TPU502).
"""

from __future__ import annotations

import ast
import dataclasses
import re
from pathlib import Path
from typing import Iterable

SERIES_PLANES_NAME = "TPULINT_SERIES_PLANES"
PLANE_ONLY_NAME = "TPULINT_PLANE_ONLY_SERIES"
BOUNDED_LABELS_NAME = "TPULINT_BOUNDED_LABELS"

# A formatted value inside a reconstructed f-string. NUL can't appear in
# real source text, so it is an unambiguous "dynamic here" marker.
PLACEHOLDER = "\x00"

_TYPE_RE = re.compile(r"# TYPE (mlops_tpu_\w+) (\w+)")
_NAME_RE = re.compile(r"mlops_tpu_\w+")
_LABEL_RE = re.compile(r'(\w+)="([^"]*)"')
# Histogram component suffixes: documented under the base series name.
_COMPONENT_RE = re.compile(r"_(?:bucket|sum|count)$")


def module_literals(tree: ast.Module, names: set[str]) -> dict[str, object]:
    """Module-level ``NAME = <literal>`` / ``NAME: t = <literal>``
    declarations, by name. Non-literal values are ignored rather than
    raised — a manifest the analyzer can't read is treated as absent."""
    out: dict[str, object] = {}
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            target, value_node = node.targets[0], node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            target, value_node = node.target, node.value
        else:
            continue
        if not isinstance(target, ast.Name) or target.id not in names:
            continue
        try:
            out[target.id] = ast.literal_eval(value_node)
        except (ValueError, SyntaxError):
            continue
    return out


@dataclasses.dataclass
class SeriesInfo:
    """One series name as the registry sees it across both planes."""

    name: str
    planes: set[str] = dataclasses.field(default_factory=set)
    labels: set[str] = dataclasses.field(default_factory=set)
    prom_type: str | None = None
    # First emission site per plane, insertion-ordered: (path, line).
    sites: list[tuple[str, int]] = dataclasses.field(default_factory=list)
    # Formatted label values: (path, line, label_key).
    dynamic_labels: list[tuple[str, int, str]] = dataclasses.field(
        default_factory=list
    )

    @property
    def base_name(self) -> str:
        return _COMPONENT_RE.sub("", self.name)


@dataclasses.dataclass
class SeriesRegistry:
    planes: dict[str, tuple[str, ...]]  # plane -> declared root qualnames
    plane_only: dict[str, set[str]]  # plane -> allowlisted series names
    bounded_labels: set[str]
    series: dict[str, SeriesInfo]
    manifest_site: tuple[str, int]  # where TPULINT_SERIES_PLANES lives

    def names(self) -> set[str]:
        return set(self.series)


@dataclasses.dataclass
class _FuncInfo:
    qualname: str
    path: str
    # (line, reconstructed text) for strings mentioning mlops_tpu_.
    strings: list[tuple[int, str]] = dataclasses.field(default_factory=list)
    calls: set[str] = dataclasses.field(default_factory=set)  # leaf names


def _docstring_value_ids(tree: ast.Module) -> set[int]:
    ids: set[int] = set()
    for node in ast.walk(tree):
        if isinstance(
            node,
            (ast.Module, ast.ClassDef, ast.FunctionDef, ast.AsyncFunctionDef),
        ):
            body = getattr(node, "body", [])
            if (
                body
                and isinstance(body[0], ast.Expr)
                and isinstance(body[0].value, ast.Constant)
                and isinstance(body[0].value.value, str)
            ):
                ids.add(id(body[0].value))
    return ids


def _leaf_name(func: ast.AST) -> str | None:
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


def _reconstruct(node: ast.AST) -> str | None:
    """The string a Constant/JoinedStr evaluates to, with every formatted
    value replaced by the placeholder. Adjacent plain literals were already
    merged by the parser; a plain+f-string mix is one JoinedStr."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr):
        parts: list[str] = []
        for piece in node.values:
            if isinstance(piece, ast.Constant) and isinstance(
                piece.value, str
            ):
                parts.append(piece.value)
            else:
                parts.append(PLACEHOLDER)
        return "".join(parts)
    return None


def extract_functions(
    tree: ast.Module, path: str
) -> dict[str, _FuncInfo]:
    """Every module-level function and method, with its series-bearing
    strings and called leaf names. Nested defs are attributed to their
    enclosing function — they run (if at all) as part of it."""
    doc_ids = _docstring_value_ids(tree)
    funcs: dict[str, _FuncInfo] = {}

    def visit(fn: ast.AST, qualname: str) -> None:
        info = funcs.setdefault(qualname, _FuncInfo(qualname, path))
        fragment_ids: set[int] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.JoinedStr):
                fragment_ids.update(id(v) for v in node.values)
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                leaf = _leaf_name(node.func)
                if leaf:
                    info.calls.add(leaf)
            if id(node) in doc_ids or id(node) in fragment_ids:
                continue
            text = _reconstruct(node)
            if text and "mlops_tpu_" in text:
                info.strings.append((node.lineno, text))

    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            visit(node, node.name)
        elif isinstance(node, ast.ClassDef):
            for item in node.body:
                if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    visit(item, f"{node.name}.{item.name}")
    return funcs


def _closure(
    roots: tuple[str, ...], funcs: dict[str, _FuncInfo]
) -> list[str]:
    """Qualnames reachable from ``roots`` through the leaf-name call
    graph, in BFS order (so first-seen emission sites are rootmost)."""
    leaf_index: dict[str, list[str]] = {}
    for qual in funcs:
        leaf_index.setdefault(qual.rsplit(".", 1)[-1], []).append(qual)
    seen: list[str] = []
    seen_set: set[str] = set()
    queue: list[str] = []
    for root in roots:
        if root in funcs:
            queue.append(root)
        else:
            queue.extend(leaf_index.get(root.rsplit(".", 1)[-1], []))
    while queue:
        qual = queue.pop(0)
        if qual in seen_set:
            continue
        seen_set.add(qual)
        seen.append(qual)
        for leaf in sorted(funcs[qual].calls):
            queue.extend(leaf_index.get(leaf, []))
    return seen


def _scan_text(text: str):
    """(name, prom_type, labels {key: dynamic}) per series occurrence."""
    typed: dict[str, str] = {}
    for m in _TYPE_RE.finditer(text):
        typed[m.group(1)] = m.group(2)
    for m in _NAME_RE.finditer(text):
        name, end = m.group(0), m.end()
        labels: dict[str, bool] = {}
        if end < len(text) and text[end] == "{":
            close = text.find("}", end)
            if close != -1:
                for lm in _LABEL_RE.finditer(text[end + 1 : close]):
                    labels[lm.group(1)] = PLACEHOLDER in lm.group(2)
        yield name, typed.get(name), labels


def build_registry(
    modules: Iterable[tuple[str, ast.Module]],
) -> SeriesRegistry | None:
    """The cross-plane series registry, or ``None`` when no
    ``TPULINT_SERIES_PLANES`` manifest exists in the project (the series
    contract is opt-in by declaration, like the lock-order manifest)."""
    modules = list(modules)
    planes: dict[str, tuple[str, ...]] = {}
    plane_only: dict[str, set[str]] = {}
    bounded: set[str] = set()
    manifest_site: tuple[str, int] | None = None
    funcs: dict[str, _FuncInfo] = {}
    for path, tree in modules:
        literals = module_literals(
            tree, {SERIES_PLANES_NAME, PLANE_ONLY_NAME, BOUNDED_LABELS_NAME}
        )
        value = literals.get(SERIES_PLANES_NAME)
        if isinstance(value, dict):
            for plane, roots in value.items():
                planes[str(plane)] = tuple(
                    roots if isinstance(roots, (tuple, list)) else (roots,)
                )
            manifest_site = (path, 1)
        value = literals.get(PLANE_ONLY_NAME)
        if isinstance(value, dict):
            for plane, names in value.items():
                plane_only.setdefault(str(plane), set()).update(names)
        value = literals.get(BOUNDED_LABELS_NAME)
        if isinstance(value, (tuple, list, set)):
            bounded.update(str(v) for v in value)
        # Same-leaf collisions across modules: keep both under distinct
        # synthetic keys so neither plane loses reachable emissions.
        for qual, info in extract_functions(tree, path).items():
            key = qual
            while key in funcs:
                key = f"{key}@{len(funcs)}"
            funcs[key] = info
    if not planes or manifest_site is None:
        return None

    registry = SeriesRegistry(
        planes=planes,
        plane_only=plane_only,
        bounded_labels=bounded,
        series={},
        manifest_site=manifest_site,
    )
    for plane, roots in sorted(planes.items()):
        for qual in _closure(roots, funcs):
            info = funcs[qual]
            for line, text in info.strings:
                for name, prom_type, labels in _scan_text(text):
                    entry = registry.series.setdefault(
                        name, SeriesInfo(name)
                    )
                    entry.planes.add(plane)
                    entry.labels.update(labels)
                    if prom_type and entry.prom_type is None:
                        entry.prom_type = prom_type
                    site = (info.path, line)
                    if site not in entry.sites:
                        entry.sites.append(site)
                    for key, dynamic in labels.items():
                        if dynamic:
                            record = (info.path, line, key)
                            if record not in entry.dynamic_labels:
                                entry.dynamic_labels.append(record)
    return registry


def registry_from_paths(
    paths: Iterable[str | Path],
) -> SeriesRegistry | None:
    """Registry over every ``.py`` under ``paths`` — the entry point
    `scripts/bench_check.py` uses to validate the committed alert rules
    against the renderers actually shipped."""
    from mlops_tpu.analysis.astrules import iter_py_files
    from mlops_tpu.analysis.findings import file_skipped

    modules: list[tuple[str, ast.Module]] = []
    for file, _rel in iter_py_files(paths):
        source = file.read_text(encoding="utf-8")
        if file_skipped(source):
            continue
        try:
            modules.append(
                (file.as_posix(), ast.parse(source, filename=str(file)))
            )
        except SyntaxError:
            continue
    return build_registry(modules)
