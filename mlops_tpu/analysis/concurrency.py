"""Layer 3: concurrency analysis over the hand-rolled threading layer.

PRs 2-4 grew a real threaded serving/streaming stack — the pipeline
executor's stage threads (`data/pipeline_exec.py`), the micro-batcher's
dispatch/fetch rings (`serve/batcher.py`), the engine's accumulator
ref-swap lock (`serve/engine.py`), compile-cache stats locks — and every
review round found a genuine concurrency defect in it (a synchronous XLA
compile stalling all requests under ``_acc_lock``; a dispatch slot
released before the fetch ring was claimed; a stale cumulative snapshot
racing a newer one). These invariants are now machine-checked instead of
re-discovered per review. Pure ``ast`` — like Layer 1, this module must
never import JAX.

======== ============================== =======================================
ID       name                           catches
======== ============================== =======================================
TPU401   lock-order-violation           nested lock acquisition that inverts
                                        the declared order manifest
                                        (``TPULINT_LOCK_ORDER``), closes a
                                        cycle, or involves an undeclared lock
TPU402   unguarded-shared-write         an attribute written both under and
                                        outside its dominant lock — the
                                        inferred guard is not actually held on
                                        every write path
TPU403   blocking-under-lock            a blocking call (device fetch /
                                        ``block_until_ready`` / ``np.asarray``
                                        / XLA ``.compile()`` / ``queue.put`` /
                                        ``join`` / file I/O / ``time.sleep``)
                                        while a mutex is held — the exact
                                        class of the PR 4 ``_compile_novel``
                                        bug
TPU404   semaphore-pairing              a semaphore acquired with no release
                                        anywhere in its class, or acquired in
                                        a function that never releases it
                                        without a declared cross-method
                                        pairing (``TPULINT_CROSS_METHOD_
                                        SEMAPHORES``)
======== ============================== =======================================

Declarations are read from the analyzed source itself (plain literals, so
the manifest lives next to the locks it orders):

    TPULINT_LOCK_ORDER = {"InferenceEngine": ("_compile_lock", "_acc_lock")}
    TPULINT_CROSS_METHOD_SEMAPHORES = {"MicroBatcher": ("_inflight",)}

``TPULINT_LOCK_ORDER`` maps a class name (or ``"<module>"`` for
module-level locks) to its lock attributes OUTERMOST FIRST: holding a
later lock while acquiring an earlier one is an inversion. The same
declaration is the runtime sanitizer's order source
(`analysis/lockcheck.py`), so the static and dynamic checks can never
disagree about the intended order.

Semantics are lexical and deliberately conservative: ``with self.<lock>``
blocks and bare ``.acquire()``/``.release()`` statements toggle a
held-lock set walked in statement order; nested function bodies start a
fresh (empty) held context because they execute later. Suppress a finding
the usual way (``# tpulint: disable=TPU403`` + justification).
"""

from __future__ import annotations

import ast
import dataclasses
from pathlib import Path
from typing import Iterable

from mlops_tpu.analysis import blocking
from mlops_tpu.analysis.findings import (
    Finding,
    Severity,
    file_skipped,
    is_suppressed,
)

MODULE_SCOPE = "<module>"

# Source-level declaration names (parsed as literals, never imported).
LOCK_ORDER_NAME = "TPULINT_LOCK_ORDER"
CROSS_METHOD_NAME = "TPULINT_CROSS_METHOD_SEMAPHORES"

# Constructor leaf names -> primitive kind. Matched on the last dotted
# component so ``threading.Lock``, ``asyncio.Lock`` and a bare ``Lock``
# all hit. Semaphores bound concurrency rather than guard state, so they
# participate in ordering (TPU401) and pairing (TPU404) but are never a
# "guard" for TPU402 and never make a region "under a mutex" for TPU403.
_MUTEX_FACTORIES = {"Lock", "RLock", "Condition"}
_SEMAPHORE_FACTORIES = {"Semaphore", "BoundedSemaphore"}


@dataclasses.dataclass(frozen=True)
class RuleInfo:
    rule: str
    name: str
    severity: Severity
    summary: str


CONCURRENCY_RULES: dict[str, RuleInfo] = {
    r.rule: r
    for r in (
        RuleInfo(
            "TPU401",
            "lock-order-violation",
            Severity.ERROR,
            "nested lock acquisition violates the declared order",
        ),
        RuleInfo(
            "TPU402",
            "unguarded-shared-write",
            Severity.ERROR,
            "attribute written outside its dominant lock",
        ),
        RuleInfo(
            "TPU403",
            "blocking-under-lock",
            Severity.ERROR,
            "blocking call while a mutex is held",
        ),
        RuleInfo(
            "TPU404",
            "semaphore-pairing",
            Severity.ERROR,
            "semaphore acquire without a matching release path",
        ),
    )
}

# ---------------------------------------------------------- blocking model
# The blocking-call table is SHARED with Layer 5 (asyncdiscipline.py):
# one classifier decides "does this call block?" for both the held-mutex
# walk (TPU403) and the event-loop-confinement walk (TPU601), so the two
# layers can never disagree about what a stall is. The table lives in
# blocking.py; the historical module-private names stay importable here.
_BLOCKING_METHODS = blocking.BLOCKING_METHODS
_BLOCKING_CALLS = blocking.BLOCKING_CALLS
_JOIN_SAFE_ROOTS = blocking.JOIN_SAFE_ROOTS
_COMPILE_SAFE_ROOTS = blocking.COMPILE_SAFE_ROOTS
_dotted = blocking.dotted


@dataclasses.dataclass
class _Scope:
    """One lock namespace: a class, or the module itself."""

    name: str
    mutexes: set[str] = dataclasses.field(default_factory=set)
    semaphores: set[str] = dataclasses.field(default_factory=set)
    # TPU401: (held-lock, acquired-lock) -> first acquisition site node.
    edges: dict[tuple[str, str], ast.AST] = dataclasses.field(
        default_factory=dict
    )
    # TPU402: attr -> list of (held-mutexes frozenset, node, method, in_init)
    writes: dict[str, list] = dataclasses.field(default_factory=dict)
    # TPU404 bookkeeping.
    sem_acquires: dict[str, list[ast.AST]] = dataclasses.field(
        default_factory=dict
    )
    sem_releases: dict[str, int] = dataclasses.field(default_factory=dict)
    # function name -> {sem: [acquire nodes]} / {sem: release count}
    fn_acquires: dict[str, dict[str, list[ast.AST]]] = dataclasses.field(
        default_factory=dict
    )
    fn_releases: dict[str, set[str]] = dataclasses.field(default_factory=dict)

    @property
    def locks(self) -> set[str]:
        return self.mutexes | self.semaphores


class _Collector:
    """One pass over a module: lock discovery, declarations, then a
    held-set walk of every function/method body."""

    def __init__(self, tree: ast.Module) -> None:
        self.tree = tree
        self.order: dict[str, tuple[str, ...]] = {}
        self.cross_method: dict[str, set[str]] = {}
        self.module_scope = _Scope(MODULE_SCOPE)
        self.class_scopes: dict[str, _Scope] = {}
        self.findings: list[Finding] = []
        self._path = ""

    # ----------------------------------------------------------- discovery
    def collect(self, path: str) -> list[Finding]:
        self._path = path
        self._read_declarations()
        self._discover_locks()
        for node in self.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._walk_function(self.module_scope, node)
            elif isinstance(node, ast.ClassDef):
                scope = self.class_scopes.get(node.name)
                if scope is None:
                    # Lock-less class: its methods can still nest/hold
                    # MODULE-level locks, so they get an ephemeral scope
                    # (checked like any other) rather than being skipped.
                    scope = self.class_scopes[node.name] = _Scope(node.name)
                for item in node.body:
                    if isinstance(
                        item, (ast.FunctionDef, ast.AsyncFunctionDef)
                    ):
                        self._walk_function(scope, item)
        for scope in (self.module_scope, *self.class_scopes.values()):
            self._check_order(scope)
            self._check_guards(scope)
            self._check_semaphores(scope)
        return self.findings

    def _read_declarations(self) -> None:
        for node in self.tree.body:
            # Both `X = {...}` and the annotated `X: dict = {...}` count —
            # dropping an annotated manifest would silently turn TPU401
            # into cycles-only mode while the runtime half still saw it.
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                target, value_node = node.targets[0], node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                target, value_node = node.target, node.value
            else:
                continue
            if not isinstance(target, ast.Name):
                continue
            if target.id not in (LOCK_ORDER_NAME, CROSS_METHOD_NAME):
                continue
            try:
                value = ast.literal_eval(value_node)
            except (ValueError, SyntaxError):
                continue  # non-literal manifest: ignore rather than crash
            if not isinstance(value, dict):
                continue
            for key, names in value.items():
                if target.id == LOCK_ORDER_NAME:
                    self.order[str(key)] = tuple(names)
                else:
                    self.cross_method.setdefault(str(key), set()).update(
                        names
                    )

    @staticmethod
    def _factory_kind(value: ast.AST) -> str | None:
        if not isinstance(value, ast.Call):
            return None
        leaf = (_dotted(value.func) or "").split(".")[-1]
        if leaf in _MUTEX_FACTORIES:
            return "mutex"
        if leaf in _SEMAPHORE_FACTORIES:
            return "semaphore"
        return None

    def _discover_locks(self) -> None:
        for node in self.tree.body:
            if isinstance(node, ast.Assign):
                kind = self._factory_kind(node.value)
                if kind:
                    for target in node.targets:
                        if isinstance(target, ast.Name):
                            bucket = (
                                self.module_scope.mutexes
                                if kind == "mutex"
                                else self.module_scope.semaphores
                            )
                            bucket.add(target.id)
            elif isinstance(node, ast.ClassDef):
                scope = _Scope(node.name)
                for sub in ast.walk(node):
                    if not isinstance(sub, ast.Assign):
                        continue
                    kind = self._factory_kind(sub.value)
                    if not kind:
                        continue
                    for target in sub.targets:
                        if (
                            isinstance(target, ast.Attribute)
                            and isinstance(target.value, ast.Name)
                            and target.value.id == "self"
                        ):
                            bucket = (
                                scope.mutexes
                                if kind == "mutex"
                                else scope.semaphores
                            )
                            bucket.add(target.attr)
                if scope.locks:
                    self.class_scopes[node.name] = scope

    # ------------------------------------------------------------ the walk
    def _lock_name(self, scope: _Scope, expr: ast.AST) -> str | None:
        """``self.<lock>`` (class scope) or bare ``<lock>`` (module lock)."""
        if (
            isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)
            and expr.value.id == "self"
            and expr.attr in scope.locks
        ):
            return expr.attr
        if isinstance(expr, ast.Name) and expr.id in self.module_scope.locks:
            return expr.id
        return None

    def _kind_of(self, scope: _Scope, name: str) -> str:
        if name in scope.mutexes or name in self.module_scope.mutexes:
            return "mutex"
        return "semaphore"

    def _acquire_call(self, scope: _Scope, expr: ast.AST) -> str | None:
        """The lock name when ``expr`` is ``<lock>.acquire(...)`` (possibly
        awaited)."""
        if isinstance(expr, ast.Await):
            expr = expr.value
        if (
            isinstance(expr, ast.Call)
            and isinstance(expr.func, ast.Attribute)
            and expr.func.attr == "acquire"
        ):
            return self._lock_name(scope, expr.func.value)
        return None

    def _release_call(self, scope: _Scope, expr: ast.AST) -> str | None:
        if (
            isinstance(expr, ast.Call)
            and isinstance(expr.func, ast.Attribute)
            and expr.func.attr == "release"
        ):
            return self._lock_name(scope, expr.func.value)
        return None

    def _walk_function(
        self, scope: _Scope, fn: ast.FunctionDef | ast.AsyncFunctionDef
    ) -> None:
        fn_acq: dict[str, list[ast.AST]] = {}
        fn_rel: set[str] = set()
        in_init = fn.name == "__init__"

        def note_edges(name: str, site: ast.AST, held: list[str]) -> None:
            for h in held:
                scope.edges.setdefault((h, name), site)

        def scan_expr(node: ast.AST, held: list[str]) -> None:
            """Blocking calls (TPU403) + attribute writes (TPU402) inside
            ONE simple statement / header expression. Never descends into
            nested statements (the walk visits those with the right held
            set) nor nested defs/lambdas (fresh execution context)."""
            held_mutexes = frozenset(
                h for h in held if self._kind_of(scope, h) == "mutex"
            )
            stack = [node]
            while stack:
                sub = stack.pop()
                if isinstance(
                    sub,
                    (ast.stmt, ast.Lambda),
                ) and sub is not node:
                    continue  # nested statement or deferred lambda body
                if held_mutexes and isinstance(sub, ast.Call):
                    self._check_blocking(sub, held_mutexes)
                if isinstance(sub, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                    targets = (
                        sub.targets
                        if isinstance(sub, ast.Assign)
                        else [sub.target]
                    )
                    for target in targets:
                        attr = self._written_attr(target)
                        if attr is not None and attr not in scope.locks:
                            scope.writes.setdefault(attr, []).append(
                                (held_mutexes, sub, fn.name, in_init)
                            )
                stack.extend(ast.iter_child_nodes(sub))

        def walk(stmts: list[ast.stmt], held: list[str]) -> None:
            for stmt in stmts:
                if isinstance(stmt, (ast.With, ast.AsyncWith)):
                    acquired: list[str] = []
                    for item in stmt.items:
                        name = self._lock_name(scope, item.context_expr)
                        if name is not None:
                            # `with <sem>:` is lexically balanced — TPU404
                            # only tracks bare acquire()/release() splits
                            note_edges(name, stmt, held + acquired)
                            acquired.append(name)
                        else:
                            # held + acquired: in `with self._lock, open(p):`
                            # the open() runs with the lock already held
                            scan_expr(item.context_expr, held + acquired)
                    walk(stmt.body, held + acquired)
                    continue
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    # nested def: fresh held context at call time
                    self._walk_function(scope, stmt)
                    continue
                # bare acquire()/release() as a statement (or assigned)
                value = getattr(stmt, "value", None)
                toggled = False
                if isinstance(stmt, (ast.Expr, ast.Assign)) and value is not None:
                    name = self._acquire_call(scope, value)
                    if name is not None:
                        note_edges(name, stmt, held)
                        if name in scope.semaphores:
                            scope.sem_acquires.setdefault(name, []).append(
                                stmt
                            )
                            fn_acq.setdefault(name, []).append(stmt)
                        held.append(name)
                        toggled = True
                    else:
                        name = self._release_call(scope, value)
                        if name is not None:
                            if name in held:
                                held.remove(name)
                            if name in scope.semaphores:
                                scope.sem_releases[name] = (
                                    scope.sem_releases.get(name, 0) + 1
                                )
                                fn_rel.add(name)
                            toggled = True
                if not toggled:
                    # header expressions of compound statements (if/while
                    # tests, for iterables) and whole simple statements —
                    # their bodies are walked below with the live held set
                    if isinstance(stmt, (ast.If, ast.While)):
                        scan_expr(stmt.test, held)
                    elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                        scan_expr(stmt.iter, held)
                    elif isinstance(stmt, ast.Try):
                        pass  # nothing but nested statements
                    else:
                        scan_expr(stmt, held)
                for body_attr in ("body", "orelse", "finalbody"):
                    body = getattr(stmt, body_attr, None)
                    if isinstance(body, list):
                        walk(body, held)
                for handler in getattr(stmt, "handlers", []) or []:
                    walk(handler.body, held)

        walk(fn.body, [])
        scope.fn_acquires[fn.name] = fn_acq
        scope.fn_releases[fn.name] = fn_rel

    @staticmethod
    def _written_attr(target: ast.AST) -> str | None:
        """``self.X = / self.X[...] =`` -> ``X`` (tuple targets handled by
        the caller iterating; nested tuples recursed here)."""
        if isinstance(target, ast.Subscript):
            target = target.value
        if (
            isinstance(target, ast.Attribute)
            and isinstance(target.value, ast.Name)
            and target.value.id == "self"
        ):
            return target.attr
        return None

    # ------------------------------------------------------------- TPU403
    def _check_blocking(
        self, call: ast.Call, held_mutexes: frozenset[str]
    ) -> None:
        held = ", ".join(sorted(held_mutexes))
        label = blocking.classify_blocking(call)
        if label is None:
            return
        if label == ".get() (blocking queue read)":
            self._flag(
                "TPU403",
                call,
                f"{label} while holding {held}",
            )
        elif label.startswith("."):
            self._flag(
                "TPU403",
                call,
                f"{label} while holding {held} blocks every thread "
                "queued on the lock — move the blocking work outside the "
                "critical section",
            )
        else:
            self._flag(
                "TPU403",
                call,
                f"{label} while holding {held} blocks every thread queued "
                "on the lock (device fetch / host materialization / I/O "
                "belongs outside the critical section)",
            )

    # ------------------------------------------------------------- TPU401
    def _check_order(self, scope: _Scope) -> None:
        order = self.order.get(scope.name)
        if order is not None:
            rank = {name: i for i, name in enumerate(order)}
            for (held, acquired), site in scope.edges.items():
                if held not in rank or acquired not in rank:
                    missing = acquired if acquired not in rank else held
                    self._flag(
                        "TPU401",
                        site,
                        f"nested acquisition of {acquired!r} while holding "
                        f"{held!r}, but {missing!r} is not in "
                        f"{LOCK_ORDER_NAME}[{scope.name!r}] — declare every "
                        "lock that participates in nesting",
                    )
                elif rank[acquired] < rank[held]:
                    self._flag(
                        "TPU401",
                        site,
                        f"acquiring {acquired!r} while holding {held!r} "
                        f"inverts the declared order {order} — a thread "
                        "taking them in the declared order deadlocks "
                        "against this one",
                    )
            return
        # No declared order: only flag actual cycles (pairs of edges that
        # can deadlock against each other).
        adjacency: dict[str, set[str]] = {}
        for held, acquired in scope.edges:
            adjacency.setdefault(held, set()).add(acquired)

        def reachable(src: str, dst: str) -> bool:
            seen, stack = set(), [src]
            while stack:
                node = stack.pop()
                if node == dst:
                    return True
                if node in seen:
                    continue
                seen.add(node)
                stack.extend(adjacency.get(node, ()))
            return False

        for (held, acquired), site in scope.edges.items():
            if reachable(acquired, held):
                self._flag(
                    "TPU401",
                    site,
                    f"acquiring {acquired!r} while holding {held!r} closes "
                    "a lock-order cycle (the opposite nesting exists "
                    "elsewhere in this scope) — two threads taking the two "
                    f"paths deadlock; declare {LOCK_ORDER_NAME} and fix "
                    "the inverted site",
                )

    # ------------------------------------------------------------- TPU402
    def _check_guards(self, scope: _Scope) -> None:
        if scope.name == MODULE_SCOPE:
            return  # module globals: too little structure to infer guards
        for attr, writes in scope.writes.items():
            guarded = [w for w in writes if w[0] and not w[3]]
            if not guarded:
                continue
            counts: dict[str, int] = {}
            for held, *_ in guarded:
                for lock in held:
                    counts[lock] = counts.get(lock, 0) + 1
            dominant = max(sorted(counts), key=lambda k: counts[k])
            for held, node, method, in_init in writes:
                if in_init:
                    continue  # construction precedes sharing
                if dominant not in held:
                    self._flag(
                        "TPU402",
                        node,
                        f"self.{attr} is written under {dominant!r} in "
                        f"{len(guarded)} place(s) but written here "
                        f"({method}) without it — either every write "
                        "holds the inferred guard or none should",
                    )

    # ------------------------------------------------------------- TPU404
    def _check_semaphores(self, scope: _Scope) -> None:
        dangling: set[str] = set()
        for sem, acquires in scope.sem_acquires.items():
            if not scope.sem_releases.get(sem):
                dangling.add(sem)
                self._flag(
                    "TPU404",
                    acquires[0],
                    f"{sem!r} is acquired here but never released anywhere "
                    f"in {scope.name} — every permit taken is gone for "
                    "good and the ring wedges at capacity",
                )
        declared = self.cross_method.get(scope.name, set())
        for fn_name, acq in scope.fn_acquires.items():
            released = scope.fn_releases.get(fn_name, set())
            for sem, sites in acq.items():
                if sem in dangling or sem in declared or sem in released:
                    continue
                self._flag(
                    "TPU404",
                    sites[0],
                    f"{fn_name}() acquires {sem!r} but never releases it "
                    "on any of its own paths — if the release legitimately "
                    "lives in another method (two-phase dispatch/fetch), "
                    f"declare it in {CROSS_METHOD_NAME}[{scope.name!r}]",
                )

    # -------------------------------------------------------------- util
    def _flag(self, rule: str, node: ast.AST, message: str) -> None:
        info = CONCURRENCY_RULES[rule]
        self.findings.append(
            Finding(
                rule=info.rule,
                name=info.name,
                severity=info.severity,
                path=self._path,
                line=getattr(node, "lineno", 0),
                message=message,
            )
        )


def analyze_concurrency_source(
    source: str, path: str | Path, keep_suppressed: bool = False
) -> list[Finding]:
    """Run every Layer-3 rule over one file's source text.
    ``keep_suppressed`` returns findings that inline disables would hide —
    the suppression auditor uses it to tell live disables from stale."""
    path = str(path)
    if file_skipped(source):
        return []
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError:
        return []  # Layer 1 already reports TPU000 for unparseable files
    findings = _Collector(tree).collect(path)
    findings.sort(key=lambda f: (f.line, f.rule))
    if keep_suppressed:
        return findings
    lines = source.splitlines()
    return [f for f in findings if not is_suppressed(f, lines)]


def analyze_concurrency_paths(paths: Iterable[str | Path]) -> list[Finding]:
    """Layer-3 lint over every ``.py`` under ``paths``."""
    from mlops_tpu.analysis.astrules import iter_py_files

    findings: list[Finding] = []
    for file, _rel in iter_py_files(paths):
        findings.extend(
            analyze_concurrency_source(
                file.read_text(encoding="utf-8"), file.as_posix()
            )
        )
    return findings
