"""Layer 5: async/event-loop discipline over the serve plane.

The front ends are asyncio processes whose event loop is the goodput
bottleneck under load: ONE blocking call in a coroutine stalls every
in-flight connection on that worker. Three hand-found production bugs
were exactly this class — response encode on the loop, flight-recorder
dumps fired from the loop mid-storm, blocking monitor fetches wedging
``/metrics`` — and the "event-loop confinement" discipline that fixed
them has been prose-only in server.py ever since. Layer 5 makes it
machine-checked, the same arc Layers 3 and 4 walked for locks and shm
ownership. Pure ``ast``, project-wide like Layer 4 — this module must
never import JAX.

======== ============================== =======================================
ID       name                           catches
======== ============================== =======================================
TPU601   blocking-call-on-loop          a blocking call (device fetch /
                                        ``np.asarray`` / ``block_until_ready``
                                        / ``.item`` / ``.tolist`` / sync XLA
                                        ``.compile`` / file I/O /
                                        ``time.sleep`` / ``queue.put`` /
                                        zero-arg ``.get`` / ``.join`` /
                                        subprocess waits / sync socket ops —
                                        Layer 3's blocking table plus the
                                        loop-only extras, ONE shared
                                        classifier in ``blocking.py``) inside
                                        an event-loop-confined context, or a
                                        sync acquire of a mutex Layer 3 saw
                                        held across blocking work
TPU602   fire-and-forget-task           ``create_task``/``ensure_future``
                                        whose result is neither awaited,
                                        stored, nor used again — the asyncio
                                        "Task was destroyed but it is
                                        pending" class, and its exceptions
                                        vanish
TPU603   cross-thread-loop-write        a thread-target function writing an
                                        attribute that loop-confined code
                                        also writes, without
                                        ``call_soon_threadsafe``/
                                        ``run_coroutine_threadsafe`` and
                                        without a mutex — a data race with
                                        the loop
TPU604   await-under-sync-lock          ``await`` while a synchronous
                                        ``threading`` mutex is held — the
                                        loop may run arbitrary callbacks at
                                        the suspension point while every
                                        thread queued on the lock stalls
======== ============================== =======================================

Confinement model
-----------------
A function body is EVENT-LOOP CONFINED when it can only execute on the
asyncio thread. Seeds:

- every ``async def`` body (coroutines run on the loop by construction);
- functions registered as loop callbacks — arguments of
  ``add_done_callback`` / ``call_soon`` / ``call_later`` / ``call_at`` /
  ``call_soon_threadsafe`` / ``add_reader`` / ``add_writer`` /
  ``add_signal_handler`` (the callback runs on the loop no matter which
  thread scheduled it);
- names declared in the ``TPULINT_LOOP_CONFINED`` manifest (the
  Layer-3/4 idiom: a plain literal in the analyzed source, read with
  ``ast.literal_eval``, never imported):

      TPULINT_LOOP_CONFINED = ("HttpServer", "RingClient.on_doorbell")

  Entries are ``"Class"`` (every method), ``"Class.method"``, or a
  module-level ``"function"`` name.

Confinement then propagates to synchronous helpers REACHABLE ONLY FROM
confined contexts: a sync function with at least one project call site,
all of whose callers are confined, inherits confinement. Functions
handed to an executor or a thread (``run_in_executor(..., fn)``,
``Thread(target=fn)``, ``pool.submit(fn)``) escape the loop by
definition and never inherit — which is precisely why
``await loop.run_in_executor(None, blocking_fn)`` is the sanctioned
offload recipe and produces no finding.

The runtime twin is `analysis/loopcheck.py`: a ``LoopLagSanitizer``
that wraps the running loop's callback execution, records per-callback
wall time with attribution, asserts a max lag in tests and feeds the
production ``mlops_tpu_event_loop_lag_ms`` gauge — so the static and
dynamic halves check the same discipline, exactly like
concurrency.py/lockcheck.py do for locks.

Suppress a finding the usual way (``# tpulint: disable=TPU601`` +
justification); the TPU400 ledger audits Layer-5 disables as live/stale
like every other layer's.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from pathlib import Path
from typing import Iterable, Iterator

from mlops_tpu.analysis import blocking
from mlops_tpu.analysis.concurrency import (
    _MUTEX_FACTORIES,
    _SEMAPHORE_FACTORIES,
    RuleInfo,
    analyze_concurrency_source,
)
from mlops_tpu.analysis.findings import (
    Finding,
    Severity,
    file_skipped,
    is_suppressed,
)

# Source-level declaration name (parsed as a literal, never imported).
LOOP_CONFINED_NAME = "TPULINT_LOOP_CONFINED"

ASYNC_RULES: dict[str, RuleInfo] = {
    r.rule: r
    for r in (
        RuleInfo(
            "TPU601",
            "blocking-call-on-loop",
            Severity.ERROR,
            "blocking call inside an event-loop-confined context",
        ),
        RuleInfo(
            "TPU602",
            "fire-and-forget-task",
            Severity.ERROR,
            "task created but never awaited, stored, or observed",
        ),
        RuleInfo(
            "TPU603",
            "cross-thread-loop-write",
            Severity.ERROR,
            "thread-side write to loop-confined state without "
            "call_soon_threadsafe",
        ),
        RuleInfo(
            "TPU604",
            "await-under-sync-lock",
            Severity.ERROR,
            "await while holding a synchronous threading mutex",
        ),
    )
}

# Loop-callback registrars: any function REFERENCE passed to one of
# these runs on the event loop, whichever thread scheduled it.
_CALLBACK_REGISTRARS = {
    "add_done_callback",
    "call_soon",
    "call_later",
    "call_at",
    "call_soon_threadsafe",
    "add_reader",
    "add_writer",
    "add_signal_handler",
}
# Thread-side dispatchers: a function REFERENCE passed here executes off
# the loop (executor pool / raw thread), so it must never inherit
# confinement — and it is the TPU603 "writer role" seed.
_TASK_FACTORIES = {"create_task", "ensure_future"}
# call_soon_threadsafe / run_coroutine_threadsafe hand work TO the loop;
# their callback argument is loop-side, not thread-side.
_LOOP_HANDOFF = {"call_soon_threadsafe", "run_coroutine_threadsafe"}

_HELD_RE = re.compile(r"while holding ([A-Za-z0-9_]+(?:, [A-Za-z0-9_]+)*)")


@dataclasses.dataclass
class _Module:
    path: str
    source: str
    tree: ast.Module
    lines: list[str]


def _parse_project(items: Iterable[tuple[str, str]]) -> list[_Module]:
    modules: list[_Module] = []
    for path, source in items:
        if file_skipped(source):
            continue
        try:
            tree = ast.parse(source, filename=path)
        except SyntaxError:
            continue  # Layer 1 already reports TPU000 for these
        modules.append(_Module(path, source, tree, source.splitlines()))
    return modules


def _flag(
    findings: list[Finding], rule: str, path: str, line: int, message: str
) -> None:
    info = ASYNC_RULES[rule]
    findings.append(
        Finding(
            rule=info.rule,
            name=info.name,
            severity=info.severity,
            path=path,
            line=line,
            message=message,
        )
    )


# ----------------------------------------------------------- call graph
@dataclasses.dataclass
class _Fn:
    """One function in the project call graph (methods and nested defs
    are their own nodes — a nested body executes later, in whatever
    context eventually calls or schedules it)."""

    module: _Module
    node: ast.FunctionDef | ast.AsyncFunctionDef
    name: str  # leaf name
    cls: str | None  # enclosing class, if a method
    qualname: str
    is_async: bool
    confined: bool = False
    seeded: str | None = None  # why: "async" | "manifest" | "callback"
    vetoed: bool = False  # referenced as a thread/executor target


@dataclasses.dataclass(frozen=True)
class _CallSite:
    leaf: str
    self_receiver: bool
    caller: "_Fn | None"  # None: module top level (import time, not loop)
    cls: str | None  # class context of the call site


def _direct_nodes(fn_node: ast.AST) -> Iterator[ast.AST]:
    """Every AST node lexically in ``fn_node``'s own body — nested
    function/class/lambda bodies excluded (they execute later, in their
    own context), decorators and defaults excluded (they execute at def
    time in the parent context)."""
    stack: list[ast.AST] = list(getattr(fn_node, "body", []))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
                   ast.ClassDef)
        ):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _leaf(node: ast.AST) -> str | None:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _receiver_root(node: ast.AST) -> str | None:
    while isinstance(node, ast.Attribute):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


class _Project:
    """The cross-module view: every function, every call site, every
    function reference that pins a body to the loop or to a thread."""

    def __init__(self, modules: list[_Module]) -> None:
        self.modules = modules
        self.fns: list[_Fn] = []
        self.call_sites: list[_CallSite] = []
        self.callback_leafs: set[str] = set()  # loop-callback refs
        self.thread_leafs: set[str] = set()  # thread/executor refs
        self.done_cb_leafs: set[str] = set()  # add_done_callback refs
        # names loaded OUTSIDE call position / dispatcher args: a
        # function matching one escapes (returned closure, routing
        # table, partial) and must not inherit confinement
        self.escaped_leafs: set[str] = set()
        self.manifest: set[str] = set()
        # per-class discovered lock attrs: cls -> {attr: factory dotted}
        self.locks: dict[str | None, dict[str, str]] = {}
        for module in modules:
            self._collect_module(module)
        self._collect_refs()
        self._seed_and_propagate()

    # ------------------------------------------------- collection
    def _collect_module(self, module: _Module) -> None:
        for node in module.tree.body:
            target = None
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                target = node.targets[0]
            elif isinstance(node, ast.AnnAssign):
                target = node.target
            if (
                isinstance(target, ast.Name)
                and target.id == LOOP_CONFINED_NAME
                and getattr(node, "value", None) is not None
            ):
                try:
                    value = ast.literal_eval(node.value)
                except (ValueError, SyntaxError):
                    continue
                if isinstance(value, (list, tuple, set)):
                    self.manifest.update(str(v) for v in value)

        def visit(
            node: ast.AST, cls: str | None, parent: str
        ) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(
                    child, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    qual = f"{parent}.{child.name}" if parent else child.name
                    self.fns.append(
                        _Fn(
                            module=module,
                            node=child,
                            name=child.name,
                            cls=cls,
                            qualname=qual,
                            is_async=isinstance(
                                child, ast.AsyncFunctionDef
                            ),
                        )
                    )
                    visit(child, cls, qual)
                elif isinstance(child, ast.ClassDef):
                    visit(child, child.name, child.name)
                else:
                    visit(child, cls, parent)

        visit(module.tree, None, "")
        # Lock attribute discovery (self.X = threading.Lock() / module
        # LOCK = Lock()): TPU603's mutex exemption and TPU604's held set.
        for node in ast.walk(module.tree):
            if not (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.value, ast.Call)
            ):
                continue
            factory = blocking.dotted(node.value.func) or ""
            if factory.split(".")[-1] not in (
                _MUTEX_FACTORIES | _SEMAPHORE_FACTORIES
            ):
                continue
            target = node.targets[0]
            if (
                isinstance(target, ast.Attribute)
                and isinstance(target.value, ast.Name)
                and target.value.id == "self"
            ):
                cls = self._class_of(module, node)
                self.locks.setdefault(cls, {})[target.attr] = factory
            elif isinstance(target, ast.Name):
                self.locks.setdefault(None, {})[target.id] = factory

    def _class_of(self, module: _Module, node: ast.AST) -> str | None:
        # lexical containment by line span — cheap and good enough for
        # "which class does this self.X = Lock() belong to"
        best: str | None = None
        best_span = None
        for cand in ast.walk(module.tree):
            if not isinstance(cand, ast.ClassDef):
                continue
            end = getattr(cand, "end_lineno", cand.lineno)
            if cand.lineno <= node.lineno <= end:
                span = end - cand.lineno
                if best_span is None or span < best_span:
                    best, best_span = cand.name, span
        return best

    def _collect_refs(self) -> None:
        """Walk every function body once: record call sites (for
        propagation) and function references that pin execution context
        (loop callbacks vs thread targets)."""

        def scan_owner(
            owner: _Fn | None, cls: str | None, nodes: list[ast.AST]
        ) -> None:
            consumed: set[int] = set()  # func positions + dispatcher args
            for node in nodes:
                if isinstance(node, ast.Call):
                    consumed.add(id(node.func))
            for node in nodes:
                if not isinstance(node, ast.Call):
                    if (
                        isinstance(node, (ast.Name, ast.Attribute))
                        and isinstance(node.ctx, ast.Load)
                        and id(node) not in consumed
                    ):
                        escaped = _leaf(node)
                        if escaped:
                            self.escaped_leafs.add(escaped)
                    continue
                leaf = _leaf(node.func)
                if leaf is None:
                    continue
                self.call_sites.append(
                    _CallSite(
                        leaf=leaf,
                        self_receiver=(
                            isinstance(node.func, ast.Attribute)
                            and _receiver_root(node.func.value) == "self"
                            and isinstance(node.func.value, ast.Name)
                        ),
                        caller=owner,
                        cls=cls,
                    )
                )
                refs = [
                    a for a in node.args
                    if isinstance(a, (ast.Name, ast.Attribute))
                ]
                if leaf in _CALLBACK_REGISTRARS:
                    for ref in refs:
                        ref_leaf = _leaf(ref)
                        if ref_leaf:
                            self.callback_leafs.add(ref_leaf)
                            if leaf == "add_done_callback":
                                self.done_cb_leafs.add(ref_leaf)
                if leaf in _LOOP_HANDOFF:
                    for ref in refs:
                        ref_leaf = _leaf(ref)
                        if ref_leaf:
                            self.callback_leafs.add(ref_leaf)
                elif leaf == "run_in_executor" and len(node.args) >= 2:
                    ref_leaf = _leaf(node.args[1])
                    if ref_leaf:
                        self.thread_leafs.add(ref_leaf)
                elif leaf == "Thread":
                    for kw in node.keywords:
                        if kw.arg == "target":
                            ref_leaf = _leaf(kw.value)
                            if ref_leaf:
                                self.thread_leafs.add(ref_leaf)
                elif leaf == "submit" and node.args:
                    # pool.submit(fn, ...): only when the receiver reads
                    # like an executor — the ring has a submit() too.
                    recv = (
                        blocking.dotted(node.func.value) or ""
                        if isinstance(node.func, ast.Attribute)
                        else ""
                    )
                    if "executor" in recv.lower() or "pool" in recv.lower():
                        ref_leaf = _leaf(node.args[0])
                        if ref_leaf:
                            self.thread_leafs.add(ref_leaf)

        for fn in self.fns:
            scan_owner(fn, fn.cls, list(_direct_nodes(fn.node)))
        for module in self.modules:
            # module top level: call sites here run at import time
            stack: list[ast.AST] = list(module.tree.body)
            flat: list[ast.AST] = []
            while stack:
                node = stack.pop()
                if isinstance(
                    node, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef, ast.Lambda)
                ):
                    continue
                flat.append(node)
                stack.extend(ast.iter_child_nodes(node))
            scan_owner(None, None, flat)

    # ------------------------------------------------ confinement
    def _manifest_match(self, fn: _Fn) -> bool:
        if fn.cls is not None:
            return (
                fn.cls in self.manifest
                or f"{fn.cls}.{fn.name}" in self.manifest
            )
        return fn.name in self.manifest

    def _seed_and_propagate(self) -> None:
        for fn in self.fns:
            fn.vetoed = fn.name in self.thread_leafs
            if fn.is_async:
                fn.confined, fn.seeded = True, "async"
            elif self._manifest_match(fn):
                # explicit declaration wins over the thread-ref veto
                fn.confined, fn.seeded = True, "manifest"
            elif not fn.vetoed and fn.name in self.callback_leafs:
                fn.confined, fn.seeded = True, "callback"
        # callers[leaf] -> every site that could target a fn by leaf name
        sites_by_leaf: dict[str, list[_CallSite]] = {}
        for site in self.call_sites:
            sites_by_leaf.setdefault(site.leaf, []).append(site)
        changed = True
        while changed:
            changed = False
            for fn in self.fns:
                if fn.confined or fn.vetoed:
                    continue
                if fn.name in self.escaped_leafs:
                    continue  # a bare reference escapes the call graph
                sites = [
                    s
                    for s in sites_by_leaf.get(fn.name, ())
                    if not (s.self_receiver and s.cls != fn.cls)
                ]
                if not sites:
                    continue
                if all(
                    s.caller is not None and s.caller.confined
                    for s in sites
                ):
                    fn.confined = True
                    changed = True


# ------------------------------------------------------------- TPU601
def _hot_mutexes(module: _Module) -> set[str]:
    """Lock names Layer 3 saw held across blocking work in this module
    (suppressed findings included: a justified TPU403 still means the
    mutex stalls, so acquiring it on the loop is still a stall)."""
    names: set[str] = set()
    for finding in analyze_concurrency_source(
        module.source, module.path, keep_suppressed=True
    ):
        if finding.rule != "TPU403":
            continue
        match = _HELD_RE.search(finding.message)
        if match:
            names.update(
                n.strip() for n in match.group(1).split(",") if n.strip()
            )
    return names


def _check_blocking_on_loop(
    project: _Project, findings: list[Finding]
) -> None:
    hot_by_module: dict[str, set[str]] = {}
    for fn in project.fns:
        if not fn.confined:
            continue
        module = fn.module
        hot = hot_by_module.get(module.path)
        if hot is None:
            hot = hot_by_module.setdefault(module.path, _hot_mutexes(module))
        params = {
            a.arg
            for a in (
                fn.node.args.args
                + fn.node.args.posonlyargs
                + fn.node.args.kwonlyargs
            )
        }
        is_done_cb = fn.name in project.done_cb_leafs
        awaited: set[int] = set()
        for node in _direct_nodes(fn.node):
            if isinstance(node, ast.Await):
                # the whole awaited subtree is suspension, not blocking:
                # inner calls (wait_for(self._full.wait(), t), gather,
                # shield) build coroutine objects, they don't run here
                awaited.update(id(sub) for sub in ast.walk(node.value))
                continue
            if isinstance(node, ast.With):
                for item in node.items:
                    ctx = item.context_expr
                    leaf = _leaf(ctx)
                    if leaf in hot and (
                        isinstance(ctx, ast.Name)
                        or _receiver_root(ctx) == "self"
                    ):
                        _flag(
                            findings,
                            "TPU601",
                            module.path,
                            node.lineno,
                            f"sync acquire of {leaf!r} on the event loop: "
                            "Layer 3 saw this mutex held across blocking "
                            "work, so the loop can stall behind it — "
                            "offload via loop.run_in_executor or restructure"
                            " the critical section",
                        )
                continue
            if not isinstance(node, ast.Call):
                continue
            if id(node) in awaited:
                continue  # awaited calls suspend, they don't block
            leaf = _leaf(node.func)
            if (
                leaf == "acquire"
                and isinstance(node.func, ast.Attribute)
                and _leaf(node.func.value) in hot
            ):
                _flag(
                    findings,
                    "TPU601",
                    module.path,
                    node.lineno,
                    f"blocking .acquire() of "
                    f"{_leaf(node.func.value)!r} on the event loop: "
                    "Layer 3 saw this mutex held across blocking work — "
                    "offload via loop.run_in_executor",
                )
                continue
            if (
                is_done_cb
                and leaf in {"result", "exception"}
                and isinstance(node.func, ast.Attribute)
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id in params
            ):
                # done-callback reading its (completed) future: no wait
                continue
            label = blocking.classify_blocking(node, loop_context=True)
            if label is not None:
                why = (
                    f"in async {fn.qualname!r}"
                    if fn.is_async
                    else f"in {fn.qualname!r} (reachable only from "
                    "event-loop-confined contexts)"
                )
                _flag(
                    findings,
                    "TPU601",
                    module.path,
                    node.lineno,
                    f"{label} {why} stalls every in-flight connection on "
                    "this worker — offload it: "
                    "await loop.run_in_executor(executor, fn, *args)",
                )


# ------------------------------------------------------------- TPU602
def _check_fire_and_forget(
    project: _Project, findings: list[Finding]
) -> None:
    attr_reads: dict[tuple[str, str | None], set[str]] = {}
    for fn in project.fns:
        key = (fn.module.path, fn.cls)
        reads = attr_reads.setdefault(key, set())
        for node in _direct_nodes(fn.node):
            if (
                isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self"
                and isinstance(node.ctx, ast.Load)
            ):
                reads.add(node.attr)
    for fn in project.fns:
        for node in _direct_nodes(fn.node):
            if (
                isinstance(node, ast.Expr)
                and isinstance(node.value, ast.Call)
                and _leaf(node.value.func) in _TASK_FACTORIES
            ):
                _flag(
                    findings,
                    "TPU602",
                    fn.module.path,
                    node.lineno,
                    f"{_leaf(node.value.func)}() result discarded: the "
                    "task can be garbage-collected mid-flight ('Task was "
                    "destroyed but it is pending') and its exception is "
                    "never observed — store a strong reference and "
                    "await/cancel it, or add_done_callback that logs "
                    "errors",
                )
                continue
            if not (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.value, ast.Call)
                and _leaf(node.value.func) in _TASK_FACTORIES
            ):
                continue
            factory = _leaf(node.value.func)
            target = node.targets[0]
            if isinstance(target, ast.Name):
                used = any(
                    isinstance(other, ast.Name)
                    and other.id == target.id
                    and other is not target
                    for other in _direct_nodes(fn.node)
                )
                if not used:
                    _flag(
                        findings,
                        "TPU602",
                        fn.module.path,
                        node.lineno,
                        f"{factory}() assigned to {target.id!r} but the "
                        "name is never used again — the reference dies "
                        "with this frame and the task becomes "
                        "fire-and-forget; await it, keep it in a "
                        "collection, or add an error-logging done-callback",
                    )
            elif (
                isinstance(target, ast.Attribute)
                and isinstance(target.value, ast.Name)
                and target.value.id == "self"
            ):
                key = (fn.module.path, fn.cls)
                if target.attr not in attr_reads.get(key, set()):
                    _flag(
                        findings,
                        "TPU602",
                        fn.module.path,
                        node.lineno,
                        f"{factory}() stored on self.{target.attr} but no "
                        "method of this class ever reads it — the task is "
                        "unobserved; await/cancel it somewhere or attach "
                        "an error-logging done-callback",
                    )


# ------------------------------------------------------------- TPU603
def _check_cross_thread_writes(
    project: _Project, findings: list[Finding]
) -> None:
    # loop-confined attrs per (module, class): attrs written via self in
    # confined methods — __init__ excluded (construction precedes
    # concurrency), lock attrs excluded (they ARE the synchronization).
    confined_attrs: dict[tuple[str, str], set[str]] = {}
    for fn in project.fns:
        if not fn.confined or fn.cls is None:
            continue
        if fn.name in {"__init__", "__post_init__"}:
            continue
        lock_names = set(project.locks.get(fn.cls, ()))
        attrs = confined_attrs.setdefault((fn.module.path, fn.cls), set())
        for node in _direct_nodes(fn.node):
            targets: list[ast.AST] = []
            if isinstance(node, ast.Assign):
                targets = list(node.targets)
            elif isinstance(node, ast.AugAssign):
                targets = [node.target]
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                targets = [node.target]
            for target in targets:
                if isinstance(target, ast.Subscript):
                    target = target.value
                if (
                    isinstance(target, ast.Attribute)
                    and isinstance(target.value, ast.Name)
                    and target.value.id == "self"
                    and target.attr not in lock_names
                ):
                    attrs.add(target.attr)
    for fn in project.fns:
        if fn.cls is None or fn.confined:
            continue
        if fn.name not in project.thread_leafs:
            continue
        attrs = confined_attrs.get((fn.module.path, fn.cls), set())
        if not attrs:
            continue
        lock_names = set(project.locks.get(fn.cls, ()))

        def walk(stmts: list[ast.stmt], held: bool) -> None:
            for stmt in stmts:
                if isinstance(
                    stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef)
                ):
                    continue
                if isinstance(stmt, ast.With):
                    inner_held = held or any(
                        _leaf(item.context_expr) in lock_names
                        for item in stmt.items
                    )
                    walk(stmt.body, inner_held)
                    continue
                targets: list[ast.AST] = []
                if isinstance(stmt, ast.Assign):
                    targets = list(stmt.targets)
                elif isinstance(stmt, ast.AugAssign):
                    targets = [stmt.target]
                elif (
                    isinstance(stmt, ast.AnnAssign)
                    and stmt.value is not None
                ):
                    targets = [stmt.target]
                if not held:
                    for target in targets:
                        if isinstance(target, ast.Subscript):
                            target = target.value
                        if (
                            isinstance(target, ast.Attribute)
                            and isinstance(target.value, ast.Name)
                            and target.value.id == "self"
                            and target.attr in attrs
                        ):
                            _flag(
                                findings,
                                "TPU603",
                                fn.module.path,
                                stmt.lineno,
                                f"thread-target {fn.qualname!r} writes "
                                f"self.{target.attr}, which "
                                "loop-confined code also writes — a data "
                                "race with the event loop; marshal the "
                                "update through "
                                "loop.call_soon_threadsafe (or guard "
                                "both sides with one mutex)",
                            )
                for field in ("body", "orelse", "finalbody"):
                    walk(getattr(stmt, field, []) or [], held)
                for handler in getattr(stmt, "handlers", []) or []:
                    walk(handler.body, held)

        walk(list(fn.node.body), False)


# ------------------------------------------------------------- TPU604
def _sync_mutexes(project: _Project, cls: str | None) -> set[str]:
    """Discovered mutex attrs that are SYNCHRONOUS (threading, not
    asyncio — an ``async with`` coroutine lock never blocks the loop)."""
    out: set[str] = set()
    for scope in (cls, None):
        for name, factory in project.locks.get(scope, {}).items():
            root = factory.split(".")[0]
            leaf = factory.split(".")[-1]
            if root != "asyncio" and leaf in _MUTEX_FACTORIES:
                out.add(name)
    return out


def _check_await_under_lock(
    project: _Project, findings: list[Finding]
) -> None:
    for fn in project.fns:
        if not fn.is_async:
            continue
        mutexes = _sync_mutexes(project, fn.cls)
        if not mutexes:
            continue

        def walk(stmts: list[ast.stmt], held: frozenset[str]) -> None:
            for stmt in stmts:
                if isinstance(
                    stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef)
                ):
                    continue
                if isinstance(stmt, ast.With):
                    acquired = {
                        leaf
                        for item in stmt.items
                        if (leaf := _leaf(item.context_expr)) in mutexes
                        and (
                            isinstance(item.context_expr, ast.Name)
                            or _receiver_root(item.context_expr) == "self"
                        )
                    }
                    walk(stmt.body, held | acquired)
                    continue
                inner = held
                if isinstance(stmt, ast.Expr) and isinstance(
                    stmt.value, ast.Call
                ):
                    call = stmt.value
                    if isinstance(call.func, ast.Attribute):
                        recv = _leaf(call.func.value)
                        if call.func.attr == "acquire" and recv in mutexes:
                            held = held | {recv}
                        elif (
                            call.func.attr == "release" and recv in mutexes
                        ):
                            held = held - {recv}
                if held or inner:
                    scan_awaits_shallow(stmt, held | inner)
                for field in ("body", "orelse", "finalbody"):
                    sub = getattr(stmt, field, None)
                    if sub:
                        walk(sub, held)
                for handler in getattr(stmt, "handlers", []) or []:
                    walk(handler.body, held)

        def scan_awaits_shallow(
            stmt: ast.AST, held: frozenset[str]
        ) -> None:
            # only this statement's own expressions — child statement
            # lists are walked separately with their own held set
            stack: list[ast.AST] = []
            for child in ast.iter_child_nodes(stmt):
                if not isinstance(child, ast.stmt):
                    stack.append(child)
            while stack:
                node = stack.pop()
                if isinstance(
                    node, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.Lambda, ast.ClassDef)
                ):
                    continue
                if isinstance(node, ast.Await):
                    _flag(
                        findings,
                        "TPU604",
                        fn.module.path,
                        node.lineno,
                        f"await while holding "
                        f"{', '.join(sorted(held))}: the loop runs "
                        "arbitrary callbacks at this suspension point "
                        "while every thread queued on the mutex stalls — "
                        "release before awaiting, or use an asyncio lock",
                    )
                for child in ast.iter_child_nodes(node):
                    if not isinstance(child, ast.stmt):
                        stack.append(child)

        walk(list(fn.node.body), frozenset())


# --------------------------------------------------------------- driver
def _analyze_project(
    modules: list[_Module], keep_suppressed: bool
) -> list[Finding]:
    project = _Project(modules)
    findings: list[Finding] = []
    _check_blocking_on_loop(project, findings)
    _check_fire_and_forget(project, findings)
    _check_cross_thread_writes(project, findings)
    _check_await_under_lock(project, findings)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    if keep_suppressed:
        return findings
    lines_by_path = {m.path: m.lines for m in modules}
    return [
        f
        for f in findings
        if not is_suppressed(f, lines_by_path.get(f.path, []))
    ]


def analyze_async_project(
    items: Iterable[tuple[str, str]], keep_suppressed: bool = False
) -> list[Finding]:
    """Layer-5 lint over ``(path, source)`` pairs as ONE project — the
    mutation-test entry point: callers can edit a file in memory (e.g.
    strip an executor offload) and re-analyze without touching disk."""
    return _analyze_project(_parse_project(items), keep_suppressed)


def analyze_async_source(
    source: str, path: str | Path = "<memory>",
    keep_suppressed: bool = False,
) -> list[Finding]:
    """Run every Layer-5 rule over one file as a single-file project —
    the fixture/test entry point. Confinement propagation obviously sees
    only this file's call graph and manifest."""
    return analyze_async_project([(str(path), source)], keep_suppressed)


def analyze_async_paths(
    paths: Iterable[str | Path], keep_suppressed: bool = False
) -> list[Finding]:
    """Layer-5 lint over every ``.py`` under ``paths`` as ONE project."""
    from mlops_tpu.analysis.astrules import iter_py_files

    items: list[tuple[str, str]] = []
    for file, _rel in iter_py_files(list(paths)):
        items.append((file.as_posix(), file.read_text(encoding="utf-8")))
    return analyze_async_project(items, keep_suppressed)
