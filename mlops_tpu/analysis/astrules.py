"""Layer 1: AST lint rules over the package source. Pure ``ast`` — this
module must never import JAX, so the lint runs in milliseconds on any
machine (pre-commit, docs builds, containers without an accelerator stack).

The rules encode the failure modes that kill compiled-hot-path performance
without failing any functional test:

======== ============================== =======================================
ID       name                           catches
======== ============================== =======================================
TPU101   host-sync-under-jit            ``.item()/.tolist()/np.asarray/
                                        jax.device_get/float(tracer)`` inside a
                                        traced scope — a device->host sync that
                                        serializes the pipelined dispatch queue
TPU102   host-rng-or-clock-under-jit    ``random.*`` / ``np.random.*`` /
                                        ``time.*`` under trace — baked in as a
                                        compile-time constant, not re-evaluated
TPU103   tracer-branch                  Python ``if``/``while`` on a traced
                                        value — either a ConcretizationError or
                                        a silent per-value recompile
TPU104   jit-config-arg-needs-static    ``jax.jit`` over a function taking a
                                        dict/config argument without
                                        ``static_argnames`` — unhashable args
                                        fail; hashable ones recompile per value
TPU105   train-step-missing-donate      a train-step-shaped jit without
                                        ``donate_argnums`` — params + optimizer
                                        state get double-buffered in HBM
TPU201   broad-except                   ``except Exception:`` that does not
                                        re-raise — swallows device errors
                                        (XlaRuntimeError, checkify) silently
TPU202   mutable-default-arg            list/dict/set defaults — shared state
                                        across calls
TPU203   uncached-hot-path-jit          a ``jax.jit`` site under serve/ or
                                        parallel/ not routed through the
                                        compile-cache entry-point registry
                                        (compilecache/registry.py) — the
                                        program recompiles on every process
                                        start instead of deserializing
TPU405   swallowed-exception-in-        a broad ``except`` under serve/ or
         serving-path                   lifecycle/ whose handler neither
                                        re-raises, returns a wire-shaped
                                        error, routes the error to a waiter,
                                        logs at error level, nor increments
                                        a metric — a serving failure that
                                        vanishes without a trace
======== ============================== =======================================

Traced-scope detection is heuristic but framework-aware: a function counts
as traced when it is decorated with (or passed to) ``jax.jit``/``pjit``, or
passed to a tracing combinator (``lax.scan``, ``vmap``, ``grad``,
``checkpoint``, …, or this repo's ``checked`` wrapper), including functions
defined in one scope and jitted in another (`make_train_window`'s
``run_window`` pattern). Nested functions inherit the traced scope.

Suppress any finding inline with ``# tpulint: disable=TPU101`` on (or
directly above) the flagged line; see `docs/static-analysis.md`.
"""

from __future__ import annotations

import ast
import dataclasses
from pathlib import Path
from typing import Iterable

from mlops_tpu.analysis.findings import (
    Finding,
    Severity,
    file_skipped,
    is_suppressed,
)

# JAX-free by construction (compilecache/registry.py): the builder names
# whose jit sites ARE wired through cache.load_or_compile — the TPU203
# whitelist, shared with the cache so the two can never disagree.
from mlops_tpu.compilecache.registry import CACHED_JIT_BUILDERS

# Path segments whose jit sites TPU203 polices: the serving + parallel
# trees are the per-process hot programs the AOT cache exists to warm.
_HOT_PATH_SEGMENTS = {"serve", "parallel"}

# Path segments whose broad excepts TPU405 polices: the serving + lifecycle
# trees, where a swallowed failure means a request or a control-loop
# transition silently vanishes. Every handler there must ACT: re-raise,
# return a wire-shaped error, hand the error to a waiter, log it at error
# level, or count it in a named metric (ISSUE 9 audit contract).
_SERVING_PATH_SEGMENTS = {"serve", "lifecycle"}
# Attribute-call names TPU405 accepts as "the failure was recorded": the
# logging error-level surface plus future/waiter error routing.
_EXC_ACTION_ATTRS = {"exception", "error", "critical", "set_exception"}


@dataclasses.dataclass(frozen=True)
class RuleInfo:
    rule: str
    name: str
    severity: Severity
    summary: str


RULES: dict[str, RuleInfo] = {
    r.rule: r
    for r in (
        RuleInfo(
            "TPU101",
            "host-sync-under-jit",
            Severity.ERROR,
            "host synchronization inside a traced scope",
        ),
        RuleInfo(
            "TPU102",
            "host-rng-or-clock-under-jit",
            Severity.ERROR,
            "Python RNG/clock call inside a traced scope",
        ),
        RuleInfo(
            "TPU103",
            "tracer-branch",
            Severity.ERROR,
            "data-dependent Python branch on a traced value",
        ),
        RuleInfo(
            "TPU104",
            "jit-config-arg-needs-static",
            Severity.ERROR,
            "jit over a dict/config argument without static_argnames",
        ),
        RuleInfo(
            "TPU105",
            "train-step-missing-donate",
            Severity.ERROR,
            "train-step jit without donate_argnums",
        ),
        RuleInfo(
            "TPU201",
            "broad-except",
            Severity.ERROR,
            "broad except swallowing device errors",
        ),
        RuleInfo(
            "TPU202",
            "mutable-default-arg",
            Severity.ERROR,
            "mutable default argument",
        ),
        RuleInfo(
            "TPU203",
            "uncached-hot-path-jit",
            Severity.ERROR,
            "hot-path jit not routed through the compile cache",
        ),
        RuleInfo(
            "TPU405",
            "swallowed-exception-in-serving-path",
            Severity.ERROR,
            "serving-path broad except that records nothing",
        ),
    )
}

# Callables whose FUNCTION argument(s) run under trace. Matched on the last
# dotted component so ``jax.jit``, ``jax.experimental.pjit.pjit`` and a bare
# ``jit`` all hit.
_JIT_NAMES = {"jit", "pjit"}
_TRACING_COMBINATORS = {
    "scan",
    "while_loop",
    "fori_loop",
    "cond",
    "switch",
    "associative_scan",
    "vmap",
    "pmap",
    "grad",
    "value_and_grad",
    "checkpoint",
    "remat",
    "eval_shape",
    "make_jaxpr",
    "custom_vjp",
    "custom_jvp",
    "checked",  # utils/debug.py: checkify + jit wrapper
}
# Attribute accesses on a traced value that stay STATIC at trace time (shape
# metadata) — branching on these is fine and idiomatic.
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "sharding", "aval"}
# Host-sync method calls on any value inside a traced scope.
_SYNC_METHODS = {"item", "tolist", "block_until_ready"}
# Host-sync/materialization calls by dotted name inside a traced scope.
_SYNC_CALLS = {
    "np.asarray",
    "np.array",
    "numpy.asarray",
    "numpy.array",
    "onp.asarray",
    "onp.array",
    "jax.device_get",
    "device_get",
}
_RNG_CLOCK_ROOTS = ("random.", "np.random.", "numpy.random.")
_CLOCK_CALLS = {
    "time.time",
    "time.perf_counter",
    "time.perf_counter_ns",
    "time.monotonic",
    "time.process_time",
    "time.sleep",
    "datetime.now",
    "datetime.datetime.now",
    "datetime.utcnow",
    "datetime.datetime.utcnow",
}
_CONFIG_ARG_NAMES = {"config", "cfg", "conf", "options", "opts", "settings"}
_STEP_NAME_HINTS = ("step", "train", "window")


def _dotted(node: ast.AST) -> str | None:
    """``a.b.c`` for Name/Attribute chains, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _fn_args(node: ast.FunctionDef | ast.AsyncFunctionDef | ast.Lambda):
    a = node.args
    return [*a.posonlyargs, *a.args, *a.kwonlyargs]


def _annotation_text(arg: ast.arg) -> str:
    return ast.unparse(arg.annotation) if arg.annotation is not None else ""


def _is_config_like(arg: ast.arg) -> bool:
    ann = _annotation_text(arg)
    return (
        arg.arg.lower() in _CONFIG_ARG_NAMES
        or "Config" in ann
        or "dict" in ann
        or "Dict" in ann
        or "Mapping" in ann
    )


def _looks_like_train_step(
    name: str, fn: ast.FunctionDef | ast.AsyncFunctionDef | None
) -> bool:
    lowered = name.lower()
    if any(h in lowered for h in _STEP_NAME_HINTS):
        return True
    if fn is not None:
        args = _fn_args(fn)
        return bool(args) and args[0].arg == "state"
    return False


_FnDef = ast.FunctionDef | ast.AsyncFunctionDef


def _scope_nodes(body: list[ast.stmt]) -> Iterable[ast.AST]:
    """Every node lexically in this scope: descends into statements and
    expressions but NOT into nested function/lambda bodies (those are new
    scopes). Function nodes themselves are yielded (their decorators and
    default expressions evaluate in THIS scope)."""
    stack: list[ast.AST] = list(body)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            stack.extend(node.decorator_list)
            stack.extend(d for d in node.args.defaults)
            stack.extend(d for d in node.args.kw_defaults if d is not None)
        elif isinstance(node, ast.Lambda):
            stack.extend(node.args.defaults)
            stack.extend(d for d in node.args.kw_defaults if d is not None)
        else:
            stack.extend(ast.iter_child_nodes(node))


class _TraceCollector:
    """Module pre-pass, SCOPE-AWARE: which function-def nodes end up under
    a JAX trace, and which jit sites need signature checks (TPU104/105).

    ``jax.jit(f)`` marks the ``f`` visible from the call's lexical scope
    (innermost def outward), so two unrelated functions that share a name
    in different scopes — common for closure factories that all return a
    ``predict`` — never contaminate each other."""

    def __init__(self) -> None:
        self.traced_fns: set[int] = set()  # id() of traced def nodes
        self.traced_lambdas: set[int] = set()
        # (site_node, fn_name, resolved_def_or_None, jit_kwargs,
        #  enclosing_def_names) — the name chain supports TPU203's
        # cached-builder whitelist.
        self.jit_sites: list[
            tuple[ast.AST, str, _FnDef | None, set[str], tuple[str, ...]]
        ] = []

    def collect(self, tree: ast.Module) -> None:
        self._scope(tree.body, [], ())

    def _scope(
        self,
        body: list[ast.stmt],
        env: list[dict[str, _FnDef]],
        names: tuple[str, ...],
    ) -> None:
        local: dict[str, _FnDef] = {}
        env = [*env, local]
        nested: list[_FnDef] = []
        for node in _scope_nodes(body):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                local[node.name] = node
                nested.append(node)
        for node in _scope_nodes(body):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._decorators(node, names)
            elif isinstance(node, ast.Call):
                self._call(node, env, names)
        for fn in nested:
            self._scope(fn.body, env, (*names, fn.name))
        # Lambda bodies contain no defs/jit calls worth collecting beyond
        # what _call already marked; rule checks happen in the visitor.

    @staticmethod
    def _resolve(name: str, env: list[dict[str, _FnDef]]) -> _FnDef | None:
        for scope in reversed(env):
            if name in scope:
                return scope[name]
        return None

    def _decorators(self, node: _FnDef, names: tuple[str, ...]) -> None:
        for dec in node.decorator_list:
            name = _dotted(dec)
            if name is not None:
                leaf = name.split(".")[-1]
                if leaf in _JIT_NAMES | _TRACING_COMBINATORS:
                    self.traced_fns.add(id(node))
                    if leaf in _JIT_NAMES:
                        # bare @jax.jit: no kwargs possible
                        self.jit_sites.append(
                            (node, node.name, node, set(), names)
                        )
            elif isinstance(dec, ast.Call):
                dec_name = _dotted(dec.func) or ""
                leaf = dec_name.split(".")[-1]
                kwargs = {k.arg for k in dec.keywords if k.arg}
                if leaf in _JIT_NAMES | _TRACING_COMBINATORS:
                    self.traced_fns.add(id(node))
                    if leaf in _JIT_NAMES:
                        self.jit_sites.append(
                            (node, node.name, node, kwargs, names)
                        )
                elif leaf == "partial" and dec.args:
                    # @partial(jax.jit, static_argnames=...)
                    inner = (_dotted(dec.args[0]) or "").split(".")[-1]
                    if inner in _JIT_NAMES:
                        self.traced_fns.add(id(node))
                        self.jit_sites.append(
                            (node, node.name, node, kwargs, names)
                        )
                    elif inner in _TRACING_COMBINATORS:
                        self.traced_fns.add(id(node))

    def _call(
        self,
        node: ast.Call,
        env: list[dict[str, _FnDef]],
        names: tuple[str, ...],
    ) -> None:
        name = _dotted(node.func) or ""
        leaf = name.split(".")[-1]
        if leaf in _JIT_NAMES and node.args:
            target = node.args[0]
            kwargs = {k.arg for k in node.keywords if k.arg}
            if isinstance(target, ast.Name):
                fn = self._resolve(target.id, env)
                if fn is not None:
                    self.traced_fns.add(id(fn))
                self.jit_sites.append((node, target.id, fn, kwargs, names))
            elif isinstance(target, ast.Lambda):
                self.traced_lambdas.add(id(target))
                self.jit_sites.append((node, "", None, kwargs, names))
            else:
                # jit over an arbitrary expression (`jax.jit(shard_map(...))`)
                # — nothing resolvable for TPU104/105, but TPU203 still
                # needs the site.
                self.jit_sites.append((node, "", None, kwargs, names))
        elif leaf in _TRACING_COMBINATORS:
            for arg in node.args:
                if isinstance(arg, ast.Name):
                    fn = self._resolve(arg.id, env)
                    if fn is not None:
                        self.traced_fns.add(id(fn))
                elif isinstance(arg, ast.Lambda):
                    self.traced_lambdas.add(id(arg))


class _RuleVisitor(ast.NodeVisitor):
    def __init__(
        self,
        path: str,
        collector: _TraceCollector,
        rel_path: str | None = None,
    ) -> None:
        self.path = path
        # Path RELATIVE to the analyzed root, used for scope decisions
        # (TPU203): segments of the directory the user happens to run the
        # analyzer FROM (e.g. /srv/serve/checkout/...) must not count.
        self.rel_path = rel_path if rel_path is not None else path
        self.c = collector
        self.findings: list[Finding] = []
        self._traced_depth = 0  # >0 while inside a traced scope
        self._tracer_names: list[set[str]] = []  # param names per traced fn

    # ------------------------------------------------------------- helpers
    def _flag(self, rule: str, node: ast.AST, message: str) -> None:
        info = RULES[rule]
        self.findings.append(
            Finding(
                rule=info.rule,
                name=info.name,
                severity=info.severity,
                path=self.path,
                line=getattr(node, "lineno", 0),
                message=message,
            )
        )

    @property
    def _in_trace(self) -> bool:
        return self._traced_depth > 0

    def _tracers(self) -> set[str]:
        out: set[str] = set()
        for names in self._tracer_names:
            out |= names
        return out

    # ------------------------------------------------------ scope tracking
    def _enter_fn(
        self, node: ast.FunctionDef | ast.AsyncFunctionDef | ast.Lambda
    ) -> None:
        traced = (
            id(node) in self.c.traced_fns
            or id(node) in self.c.traced_lambdas
            or self._in_trace  # nested defs run under the enclosing trace
        )
        if traced:
            self._traced_depth += 1
            self._tracer_names.append({a.arg for a in _fn_args(node)})
        else:
            self._tracer_names.append(set())
        if not isinstance(node, ast.Lambda):
            self._check_mutable_defaults(node)
        for child in ast.iter_child_nodes(node):
            self.visit(child)
        self._tracer_names.pop()
        if traced:
            self._traced_depth -= 1

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._enter_fn(node)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._enter_fn(node)

    def visit_Lambda(self, node: ast.Lambda) -> None:
        self._enter_fn(node)

    # ------------------------------------------------------------- TPU202
    def _check_mutable_defaults(
        self, node: ast.FunctionDef | ast.AsyncFunctionDef
    ) -> None:
        defaults = [*node.args.defaults, *node.args.kw_defaults]
        for default in defaults:
            if default is None:
                continue
            mutable = isinstance(default, (ast.List, ast.Dict, ast.Set))
            if isinstance(default, ast.Call):
                callee = _dotted(default.func) or ""
                mutable = callee in {"list", "dict", "set", "bytearray"}
            if mutable:
                self._flag(
                    "TPU202",
                    default,
                    f"mutable default argument in {node.name}() is shared "
                    "across calls; default to None and construct inside",
                )

    # ------------------------------------------------------------- TPU201
    def visit_ExceptHandler(self, node: ast.ExceptHandler) -> None:
        broad_names = ("Exception", "BaseException")

        def is_broad_name(t: ast.AST) -> bool:
            return isinstance(t, ast.Name) and t.id in broad_names

        broad = (
            node.type is None
            or is_broad_name(node.type)
            # Tuple form: `except (ValueError, Exception):` is just as broad
            or (
                isinstance(node.type, ast.Tuple)
                and any(is_broad_name(e) for e in node.type.elts)
            )
        )
        # A re-raise anywhere in the handler (incl. the conditional
        # narrow-by-message pattern `if ...: raise`) means nothing is
        # swallowed; nested defs are their own scope and don't count.
        reraises = any(
            isinstance(sub, ast.Raise)
            for stmt in node.body
            for sub in _scope_nodes([stmt])
        )
        if broad and not reraises:
            caught = "bare except" if node.type is None else (
                f"except {ast.unparse(node.type)}"
            )
            self._flag(
                "TPU201",
                node,
                f"{caught} without re-raise swallows device errors "
                "(XlaRuntimeError, checkify) — catch the specific "
                "exceptions or justify with a disable comment",
            )
        # TPU405: on serving paths (serve/, lifecycle/) even a JUSTIFIED
        # broad except (TPU201-disabled) must visibly ACT on the failure.
        # Orthogonal to TPU201 by design: the disable that justifies the
        # breadth does not excuse a handler that records nothing.
        if (
            broad
            and self._on_serving_path()
            and not reraises
            and not self._handler_acts(node)
        ):
            self._flag(
                "TPU405",
                node,
                "broad except on a serving path swallows the failure "
                "without a trace — re-raise, return a wire-shaped error, "
                "route it to a waiter (set_exception), log it via "
                "logger.exception/error, or increment a named metric",
            )
        self.generic_visit(node)

    @staticmethod
    def _handler_acts(node: ast.ExceptHandler) -> bool:
        """Does the handler body (nested defs excluded — their bodies run
        later, in another scope) visibly act on the failure? Accepted
        actions: ``return`` (a wire-shaped error path), an error-level
        log / waiter-routing call (`_EXC_ACTION_ATTRS`), or an augmented
        assignment (a metric/drop counter increment). ``raise`` is
        handled by the caller's re-raise check."""
        for stmt in node.body:
            for sub in _scope_nodes([stmt]):
                if isinstance(sub, ast.Return):
                    return True
                if isinstance(sub, ast.AugAssign):
                    return True
                if (
                    isinstance(sub, ast.Call)
                    and isinstance(sub.func, ast.Attribute)
                    and sub.func.attr in _EXC_ACTION_ATTRS
                ):
                    return True
        return False

    def _on_serving_path(self) -> bool:
        import re

        return bool(
            _SERVING_PATH_SEGMENTS & set(re.split(r"[\\/]+", self.rel_path))
        )

    # ------------------------------------------------------ TPU101/TPU102
    def visit_Call(self, node: ast.Call) -> None:
        if self._in_trace:
            self._check_host_sync(node)
            self._check_rng_clock(node)
        self.generic_visit(node)

    def _check_host_sync(self, node: ast.Call) -> None:
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr in _SYNC_METHODS
        ):
            self._flag(
                "TPU101",
                node,
                f".{node.func.attr}() inside a traced scope forces a "
                "device->host sync on every call",
            )
            return
        name = _dotted(node.func) or ""
        if name in _SYNC_CALLS:
            self._flag(
                "TPU101",
                node,
                f"{name}() inside a traced scope materializes the value on "
                "host — keep the computation in jnp",
            )
            return
        if (
            isinstance(node.func, ast.Name)
            and node.func.id in ("float", "int", "bool")
            and len(node.args) == 1
            and self._mentions_tracer(node.args[0])
        ):
            self._flag(
                "TPU101",
                node,
                f"{node.func.id}() on a traced value concretizes it "
                "(ConcretizationTypeError under jit, silent sync under "
                "eager) — use jnp casts instead",
            )

    def _check_rng_clock(self, node: ast.Call) -> None:
        name = _dotted(node.func) or ""
        if name.startswith(_RNG_CLOCK_ROOTS) or name in _CLOCK_CALLS:
            self._flag(
                "TPU102",
                node,
                f"{name}() under trace is evaluated ONCE at compile time "
                "and baked into the program — use jax.random with an "
                "explicit key (or pass host values in as arguments)",
            )

    # ------------------------------------------------------------- TPU103
    def _mentions_tracer(self, test: ast.AST) -> bool:
        """Does ``test`` read a probable tracer (a traced-fn parameter) in
        a way that is data-dependent (not just shape/dtype metadata)?"""
        tracers = self._tracers()
        if not tracers:
            return False
        static_values: set[int] = set()
        for sub in ast.walk(test):
            # x.shape / x.ndim / ... — static at trace time
            if (
                isinstance(sub, ast.Attribute)
                and sub.attr in _STATIC_ATTRS
            ):
                for inner in ast.walk(sub.value):
                    static_values.add(id(inner))
            # len(x) / isinstance(x, T) — static
            if isinstance(sub, ast.Call):
                callee = _dotted(sub.func) or ""
                if callee in ("len", "isinstance", "type", "hasattr"):
                    for arg in sub.args:
                        for inner in ast.walk(arg):
                            static_values.add(id(inner))
            # x is None / x is not None — identity, not data
            if isinstance(sub, ast.Compare) and all(
                isinstance(op, (ast.Is, ast.IsNot)) for op in sub.ops
            ):
                for inner in ast.walk(sub):
                    static_values.add(id(inner))
        for sub in ast.walk(test):
            if (
                isinstance(sub, ast.Name)
                and isinstance(sub.ctx, ast.Load)
                and sub.id in tracers
                and id(sub) not in static_values
            ):
                return True
        return False

    def visit_If(self, node: ast.If) -> None:
        self._check_branch(node, "if")
        self.generic_visit(node)

    def visit_While(self, node: ast.While) -> None:
        self._check_branch(node, "while")
        self.generic_visit(node)

    def _check_branch(self, node: ast.If | ast.While, kind: str) -> None:
        if self._in_trace and self._mentions_tracer(node.test):
            self._flag(
                "TPU103",
                node,
                f"Python `{kind}` on a traced value — use jnp.where / "
                "lax.cond / lax.while_loop (a Python branch either raises "
                "ConcretizationTypeError or recompiles per value)",
            )

    # ----------------------------------------------- TPU104/TPU105/TPU203
    def _on_hot_path(self) -> bool:
        import re

        # Split on either separator so Windows checkouts match too.
        return bool(
            _HOT_PATH_SEGMENTS & set(re.split(r"[\\/]+", self.rel_path))
        )

    def check_jit_sites(self) -> None:
        hot = self._on_hot_path()
        for site, fn_name, fn, kwargs, enclosing in self.c.jit_sites:
            if hot and not (set(enclosing) & CACHED_JIT_BUILDERS):
                self._flag(
                    "TPU203",
                    site,
                    "jax.jit on a serving/parallel hot path outside the "
                    "compile-cache builders "
                    f"({', '.join(sorted(CACHED_JIT_BUILDERS))}) — this "
                    "program recompiles on every process start; route it "
                    "through compilecache (cache.load_or_compile + a "
                    "registered entry point) or justify with a disable "
                    "comment",
                )
            if fn is not None and not (
                kwargs & {"static_argnames", "static_argnums"}
            ):
                for arg in _fn_args(fn):
                    if _is_config_like(arg):
                        self._flag(
                            "TPU104",
                            site,
                            f"jit of {fn_name}() takes config-like argument "
                            f"{arg.arg!r} without static_argnames — "
                            "unhashable args fail at dispatch, hashable "
                            "ones recompile per value",
                        )
                        break
            if (
                fn_name
                and _looks_like_train_step(fn_name, fn)
                and not (kwargs & {"donate_argnums", "donate_argnames"})
            ):
                self._flag(
                    "TPU105",
                    site,
                    f"jit of {fn_name}() looks like a train step but does "
                    "not donate its state — params + optimizer buffers get "
                    "double-buffered in HBM; pass donate_argnums",
                )


def analyze_source(
    source: str,
    path: str | Path,
    rel_path: str | Path | None = None,
    keep_suppressed: bool = False,
) -> list[Finding]:
    """Run every Layer-1 rule over one file's source text. ``rel_path``
    (the path relative to the analyzed root) scopes path-predicated rules
    like TPU203; it defaults to ``path`` for standalone callers.
    ``keep_suppressed`` returns findings that inline disables would hide —
    the suppression auditor uses it to tell live disables from stale."""
    path = str(path)
    if file_skipped(source):
        return []
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as err:
        return [
            Finding(
                rule="TPU000",
                name="syntax-error",
                severity=Severity.ERROR,
                path=path,
                line=err.lineno or 0,
                message=f"file does not parse: {err.msg}",
            )
        ]
    collector = _TraceCollector()
    collector.collect(tree)
    visitor = _RuleVisitor(
        path, collector, rel_path=str(rel_path) if rel_path else None
    )
    visitor.visit(tree)
    visitor.check_jit_sites()
    if keep_suppressed:
        return visitor.findings
    lines = source.splitlines()
    return [f for f in visitor.findings if not is_suppressed(f, lines)]


def iter_py_files(
    paths: Iterable[str | Path],
) -> Iterable[tuple[Path, Path]]:
    """(file, rel) for every ``.py`` under ``paths`` — the one directory
    walk shared by all analyzer layers and the suppression auditor. ``rel``
    is the path under the analyzed root, so directory names ABOVE the root
    (a checkout under /srv/serve/, say) never trip path-scoped rules; the
    root's own name still counts (analyzing `mlops_tpu/serve/` directly)."""
    for path in paths:
        path = Path(path)
        if path.is_dir():
            files = [(f, Path(path.name) / f.relative_to(path))
                     for f in sorted(path.rglob("*.py"))]
        else:
            files = [(path, path)]
        for file, rel in files:
            if "__pycache__" in file.parts:
                continue
            yield file, rel


def analyze_paths(paths: Iterable[str | Path]) -> list[Finding]:
    """Lint every ``.py`` under ``paths`` (files or directories)."""
    findings: list[Finding] = []
    for file, rel in iter_py_files(paths):
        findings.extend(
            analyze_source(
                file.read_text(encoding="utf-8"),
                file.as_posix(),
                rel_path=rel.as_posix(),
            )
        )
    return findings
