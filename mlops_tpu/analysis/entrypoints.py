"""The framework's registered jitted entry points for the trace layer.

Each entry wraps a REAL production builder (not a re-implementation) so the
jaxpr the analyzer inspects is the program production compiles:

- ``train-step-dense``   — `train/loop.py make_train_window` (the scan the
  `train` CLI runs), traced at two dataset sizes.
- ``train-step-tp``      — `parallel/steps.py make_sharded_train_step`
  (the DP×TP pjit step); needs a multi-device mesh, skipped (loudly) on
  single-device hosts.
- ``serve-predict-packed`` — `ops/predict.py make_packed_predict_base`
  (the serving hot path in its packed single-buffer cacheable form: one
  flat f32 output + the device monitor accumulator), traced at every
  warmup bucket the engine compiles. The lifecycle shadow's candidate
  warmup (`lifecycle/shadow.py`) is THIS entry too: params ride as
  arguments, so an identical-architecture candidate shares the
  incumbent's executables outright, and an architecture change warms
  through `compilecache/warmup.py serve_predict_jobs` — the same
  registered entry id, so the warmers/registry sync test keeps pinning
  ``CACHE_ENTRY_IDS`` with no lifecycle-private program anywhere.
- ``serve-predict-group-packed`` — `ops/predict.py
  make_packed_grouped_base` (the micro-batcher's packed vmapped
  dispatch), traced across slot buckets.
- ``serve-predict-quant-packed`` / ``serve-predict-quant-group-packed`` —
  `ops/quant_kernel.py make_quant_packed_base` /
  ``make_quant_grouped_base`` (the int8/bf16 quantized student tier in
  the same packed 7-arg cacheable form; Pallas-fused on TPU, traced here
  through the jnp composite route, which is the same program family the
  parity tests pin bit-identical).
- ``serve-predict-gbm-packed`` / ``serve-predict-gbm-group-packed`` —
  `ops/gbm_tensor.py make_gbm_packed_base` / ``make_gbm_grouped_base``
  (the Hummingbird-style HistGBM tensorization in the same packed 7-arg
  form; f64 tree compares by bit-parity contract, so these entries
  declare ``x64=True`` and trace inside the x64 context).
- ``bulk-score-chunk``   — `parallel/bulk.py make_bulk_fused` (the fused
  chunk program the pipelined bulk/stream scorers dispatch per chunk),
  traced at two chunk sizes with the production int8 categorical ids.

Everything is built from ``jax.ShapeDtypeStruct`` pytrees: params come from
``jax.eval_shape(model.init, ...)``, batches from the SCHEMA shapes, so the
whole registry traces abstractly — no parameter materialization, no device
execution. Adding an entry point = appending to ``registered_entry_points``
(see docs/static-analysis.md "Registering a Layer-2 entry point").

``--numeric`` additionally runs the serve entry through `utils/debug.py
checked()` (checkify float checks) on tiny CONCRETE batches — that one
executes on the current backend, so it is opt-in, not part of the gate.
"""

from __future__ import annotations

from typing import Any

from mlops_tpu.analysis.traces import EntryPoint, ShardingLink


def _schema_batch(batch: int):
    import jax
    import jax.numpy as jnp

    from mlops_tpu.schema import SCHEMA

    S = jax.ShapeDtypeStruct
    return (
        S((batch, SCHEMA.num_categorical), jnp.int32),
        S((batch, SCHEMA.num_numeric), jnp.float32),
    )


def _tiny_model_config():
    from mlops_tpu.config import ModelConfig

    # Smallest real family: the analyzer checks program STRUCTURE, which
    # width does not change, so keep tracing cheap.
    return ModelConfig(family="mlp", hidden_dims=(8,), embed_dim=4)


def _abstract_variables(model) -> Any:
    """Variable shapes via eval_shape — one shared definition
    (`models.abstract_variables`) so the compile cache derives the exact
    signatures this registry traces."""
    from mlops_tpu.models import abstract_variables

    return abstract_variables(model)


def _abstract_monitor():
    # Shared with the compile-cache warmup (`compilecache/warmup.py`): the
    # same abstract monitor produces the same cache keys.
    from mlops_tpu.monitor.state import abstract_monitor_state

    return abstract_monitor_state()


def _abstract_train_state(model, optimizer):
    import jax
    import jax.numpy as jnp

    from mlops_tpu.train.loop import TrainState

    variables = _abstract_variables(model)
    params = variables["params"]
    S = jax.ShapeDtypeStruct
    return TrainState(
        params=params,
        opt_state=jax.eval_shape(optimizer.init, params),
        step=S((), jnp.int32),
        rng=S((2,), jnp.uint32),
        ema=None,
    )


# --------------------------------------------------------------- builders
def _build_train_step_dense():
    import jax
    import jax.numpy as jnp

    from mlops_tpu.config import TrainConfig
    from mlops_tpu.models import build_model
    from mlops_tpu.train.loop import make_optimizer, make_train_window

    model = build_model(_tiny_model_config())
    config = TrainConfig(batch_size=32, steps=8, eval_every=4)
    optimizer = make_optimizer(config)
    window = make_train_window(model, optimizer, config, window=4)
    state = _abstract_train_state(model, optimizer)

    def args(rows: int):
        cat, num = _schema_batch(rows)
        lab = jax.ShapeDtypeStruct((rows,), jnp.float32)
        return (state, cat, num, lab)

    # Two dataset sizes: the scan must be the same program at any row
    # count (minibatches are gathered from indices, never data-dependent).
    return window, {256: args(256), 512: args(512)}


def _build_train_step_tp():
    import jax
    import jax.numpy as jnp

    from mlops_tpu.config import TrainConfig
    from mlops_tpu.models import build_model
    from mlops_tpu.parallel import make_mesh
    from mlops_tpu.parallel.steps import make_sharded_train_step
    from mlops_tpu.train.loop import make_optimizer

    model = build_model(_tiny_model_config())
    config = TrainConfig(batch_size=32, steps=8, eval_every=4)
    optimizer = make_optimizer(config)
    mesh = make_mesh(jax.device_count())
    params = _abstract_variables(model)["params"]
    step_fn, _ = make_sharded_train_step(
        model, optimizer, config, mesh, params
    )
    state = _abstract_train_state(model, optimizer)

    def args(rows: int):
        cat, num = _schema_batch(rows)
        lab = jax.ShapeDtypeStruct((rows,), jnp.float32)
        rng = jax.ShapeDtypeStruct((2,), jnp.uint32)
        return (state, cat, num, lab, rng)

    return step_fn, {64: args(64), 128: args(128)}


def _abstract_accumulator():
    # Shared with the compile-cache warmup: the same abstract accumulator
    # produces the same cache keys (monitor/state.py).
    from mlops_tpu.monitor.state import abstract_accumulator

    return abstract_accumulator()


def _build_serve_predict():
    import jax
    import jax.numpy as jnp

    from mlops_tpu.config import ServeConfig
    from mlops_tpu.models import build_model
    from mlops_tpu.ops.predict import make_packed_predict_base

    model = build_model(_tiny_model_config())
    variables = _abstract_variables(model)
    monitor = _abstract_monitor()
    # The CACHEABLE packed program form (params/monitor/accumulator/
    # temperature as arguments — see ops/predict.py
    # make_packed_predict_base): the jaxpr traced here is byte-for-byte
    # the program the compile cache persists.
    entry = make_packed_predict_base(model)

    def args(bucket: int):
        cat, num = _schema_batch(bucket)
        mask = jax.ShapeDtypeStruct((bucket,), jnp.bool_)
        temp = jax.ShapeDtypeStruct((), jnp.float32)
        return (variables, monitor, _abstract_accumulator(), temp, cat, num, mask)

    # Trace at every bucket the engine warms: the padded-bucket serving
    # contract ("zero steady-state recompiles") is exactly TPU304.
    buckets = ServeConfig().warmup_batch_sizes
    return entry, {b: args(b) for b in buckets}


def _build_serve_predict_group():
    import jax
    import jax.numpy as jnp

    from mlops_tpu.models import build_model
    from mlops_tpu.ops.predict import make_packed_grouped_base
    from mlops_tpu.schema import SCHEMA
    from mlops_tpu.serve.engine import GROUP_ROW_BUCKET, GROUP_SLOT_BUCKETS

    model = build_model(_tiny_model_config())
    variables = _abstract_variables(model)
    monitor = _abstract_monitor()
    entry = make_packed_grouped_base(model)

    S = jax.ShapeDtypeStruct

    def args(slots: int):
        rows = GROUP_ROW_BUCKET
        return (
            variables,
            monitor,
            _abstract_accumulator(),
            S((), jnp.float32),
            S((slots, rows, SCHEMA.num_categorical), jnp.int32),
            S((slots, rows, SCHEMA.num_numeric), jnp.float32),
            S((slots, rows), jnp.bool_),
        )

    smallest, largest = GROUP_SLOT_BUCKETS[0], GROUP_SLOT_BUCKETS[-1]
    return entry, {smallest: args(smallest), largest: args(largest)}


def _build_serve_quant():
    import jax
    import jax.numpy as jnp

    from mlops_tpu.config import ServeConfig
    from mlops_tpu.ops.quant import abstract_quant_params
    from mlops_tpu.ops.quant_kernel import make_quant_packed_base

    qparams = abstract_quant_params()
    monitor = _abstract_monitor()
    # use_kernel=False: the analyzer traces the jnp composite route — the
    # Pallas route is the same math (parity-pinned bitwise under jit) but
    # its jaxpr hides the body inside a pallas_call, which Layer-2's
    # structural checks cannot see through.
    entry = make_quant_packed_base(use_kernel=False)

    def args(bucket: int):
        cat, num = _schema_batch(bucket)
        mask = jax.ShapeDtypeStruct((bucket,), jnp.bool_)
        temp = jax.ShapeDtypeStruct((), jnp.float32)
        return (qparams, monitor, _abstract_accumulator(), temp, cat, num, mask)

    buckets = ServeConfig().warmup_batch_sizes
    return entry, {b: args(b) for b in buckets}


def _build_serve_quant_group():
    import jax
    import jax.numpy as jnp

    from mlops_tpu.ops.quant import abstract_quant_params
    from mlops_tpu.ops.quant_kernel import make_quant_grouped_base
    from mlops_tpu.schema import SCHEMA
    from mlops_tpu.serve.engine import GROUP_ROW_BUCKET, GROUP_SLOT_BUCKETS

    qparams = abstract_quant_params()
    monitor = _abstract_monitor()
    entry = make_quant_grouped_base(use_kernel=False)

    S = jax.ShapeDtypeStruct

    def args(slots: int):
        rows = GROUP_ROW_BUCKET
        return (
            qparams,
            monitor,
            _abstract_accumulator(),
            S((), jnp.float32),
            S((slots, rows, SCHEMA.num_categorical), jnp.int32),
            S((slots, rows, SCHEMA.num_numeric), jnp.float32),
            S((slots, rows), jnp.bool_),
        )

    smallest, largest = GROUP_SLOT_BUCKETS[0], GROUP_SLOT_BUCKETS[-1]
    return entry, {smallest: args(smallest), largest: args(largest)}


def _build_serve_gbm():
    import jax
    import jax.numpy as jnp

    from mlops_tpu.config import ServeConfig
    from mlops_tpu.ops.gbm_tensor import (
        GbmGeometry,
        abstract_gbm_variables,
        make_gbm_packed_base,
    )

    # Smallest real geometry: the traced STRUCTURE depends on the static
    # depth (gather-loop iterations) and tree count (the serial add
    # chain), not on node width — keep tracing cheap. The entry declares
    # ``x64=True``, so the analyzer traces it inside the x64 context
    # exactly as production lowers it.
    geometry = GbmGeometry(n_trees=4, max_nodes=7, depth=2)
    variables = abstract_gbm_variables(geometry)
    monitor = _abstract_monitor()
    entry = make_gbm_packed_base(geometry.depth)

    def args(bucket: int):
        import numpy as np

        cat, num = _schema_batch(bucket)
        mask = jax.ShapeDtypeStruct((bucket,), jnp.bool_)
        # f64 temperature — the gbm tier's one dtype deviation from the
        # packed contract (bit-parity with the host hybrid's full-float
        # logit division, compilecache/warmup.py _gbm_serve_avals).
        temp = jax.ShapeDtypeStruct((), np.float64)
        return (variables, monitor, _abstract_accumulator(), temp, cat, num, mask)

    buckets = ServeConfig().warmup_batch_sizes
    return entry, {b: args(b) for b in buckets}


def _build_serve_gbm_group():
    import jax
    import jax.numpy as jnp

    from mlops_tpu.ops.gbm_tensor import (
        GbmGeometry,
        abstract_gbm_variables,
        make_gbm_grouped_base,
    )
    from mlops_tpu.schema import SCHEMA
    from mlops_tpu.serve.engine import GROUP_ROW_BUCKET, GROUP_SLOT_BUCKETS

    geometry = GbmGeometry(n_trees=4, max_nodes=7, depth=2)
    variables = abstract_gbm_variables(geometry)
    monitor = _abstract_monitor()
    entry = make_gbm_grouped_base(geometry.depth)

    import numpy as np

    S = jax.ShapeDtypeStruct

    def args(slots: int):
        rows = GROUP_ROW_BUCKET
        return (
            variables,
            monitor,
            _abstract_accumulator(),
            S((), np.float64),  # see _build_serve_gbm
            S((slots, rows, SCHEMA.num_categorical), jnp.int32),
            S((slots, rows, SCHEMA.num_numeric), jnp.float32),
            S((slots, rows), jnp.bool_),
        )

    smallest, largest = GROUP_SLOT_BUCKETS[0], GROUP_SLOT_BUCKETS[-1]
    return entry, {smallest: args(smallest), largest: args(largest)}


def _build_bulk_score_chunk():
    import jax
    import jax.numpy as jnp

    from mlops_tpu.models import build_model
    from mlops_tpu.parallel.bulk import make_bulk_fused
    from mlops_tpu.schema import SCHEMA

    model = build_model(_tiny_model_config())
    variables = _abstract_variables(model)
    monitor = _abstract_monitor()
    entry = make_bulk_fused(model)

    S = jax.ShapeDtypeStruct

    def args(chunk: int):
        # int8 categorical ids: the bulk path narrows on the host and
        # widens in-jit (parallel/bulk.py), so the traced signature must
        # match what the pipelined chunk scorer actually dispatches.
        return (
            variables,
            monitor,
            S((), jnp.float32),
            S((chunk, SCHEMA.num_categorical), jnp.int8),
            S((chunk, SCHEMA.num_numeric), jnp.float32),
            S((chunk,), jnp.bool_),
        )

    # Two chunk sizes: the streaming executors compile ONE program per
    # sweep, so the program must be the same at any chunk shape (TPU304).
    return entry, {4096: args(4096), 16_384: args(16_384)}


def registered_entry_points() -> list[EntryPoint]:
    return [
        EntryPoint(
            name="train-step-dense",
            build=_build_train_step_dense,
            # Dense training packages replicated (host) params.
            params_out_spec=None,
        ),
        EntryPoint(
            name="train-step-tp",
            build=_build_train_step_tp,
            min_devices=2,
            # The TP product loop (train/tensor_parallel.py) merges the
            # PARAM_RULES-sharded tree back to a dense servable tree at
            # packaging — declared here as replicated-after-merge.
            params_out_spec=None,
        ),
        EntryPoint(
            name="serve-predict-packed",
            build=_build_serve_predict,
            # The engine loads bundle params replicated on the serving chip.
            params_in_spec=None,
            # Two DECLARED program families (monitor/state.py drift_scores):
            # buckets <= 64 rows run the dense small-batch K-S, larger ones
            # the sort-based K-S. Each bucket still compiles exactly once
            # at warmup; what TPU304 guards is NEW polymorphism inside a
            # family.
            bucket_families=((1, 8, 64), (256,)),
        ),
        EntryPoint(
            name="serve-predict-group-packed",
            build=_build_serve_predict_group,
            params_in_spec=None,
        ),
        EntryPoint(
            name="serve-predict-quant-packed",
            build=_build_serve_quant,
            params_in_spec=None,
            # ONE program family: the quant tier runs the dense masked K-S
            # statistic at EVERY bucket (ops/quant_kernel.py — the
            # sort-based large-batch form does not lower on Mosaic, and
            # the dense form is mathematically identical), so there is no
            # 64→256 family split like the exact tier's.
            bucket_families=((1, 8, 64, 256),),
        ),
        EntryPoint(
            name="serve-predict-quant-group-packed",
            build=_build_serve_quant_group,
            params_in_spec=None,
        ),
        EntryPoint(
            name="serve-predict-gbm-packed",
            build=_build_serve_gbm,
            params_in_spec=None,
            # f64 is this entry's CONTRACT (bit-parity with sklearn's f64
            # tree compares — ops/gbm_tensor.py): traced inside the x64
            # context, TPU301 suppressed, f64-endpoint cast round-trips
            # allowed (the calibration boundary's narrowing semantics).
            x64=True,
            # Same monitor family split as the exact tier: dense masked
            # K-S at buckets <= 64, the sort-based form at 256.
            bucket_families=((1, 8, 64), (256,)),
        ),
        EntryPoint(
            name="serve-predict-gbm-group-packed",
            build=_build_serve_gbm_group,
            params_in_spec=None,
            x64=True,
        ),
        EntryPoint(
            name="bulk-score-chunk",
            build=_build_bulk_score_chunk,
            # The pipelined bulk scorers load bundle params replicated.
            params_in_spec=None,
        ),
    ]


# Packaged-params handoffs the sharding check guards (TPU305).
LINKS = [
    ShardingLink("train-step-dense", "serve-predict-packed"),
    ShardingLink(
        "train-step-tp", "serve-predict-packed", transport="merge-to-dense"
    ),
]


class NumericAuditError(Exception):
    """A numeric-audit failure tagged with the entry point that tripped.
    `analysis/cli.py` turns this into the TPU307 finding; a raw
    ``checkify.JaxRuntimeError`` (or AssertionError) escaping instead
    would crash the analyzer with exit 2 rather than gate with exit 1."""

    def __init__(self, entry: str, detail: str):
        self.entry = entry
        super().__init__(detail)


def numeric_audit() -> list[str]:
    """Opt-in one-shot numeric audit (``analyze --numeric``): run the
    PACKED serve programs — the production hot path, accumulator fold
    included — through `utils/debug.py checked()` (checkify float checks)
    on tiny CONCRETE synthetic batches. This executes on the current
    backend (CPU under JAX_PLATFORMS=cpu), so it is not part of the
    abstract gate.

    The solo form runs with PADDING rows (the serving reality: requests
    pad up to their bucket); the grouped form runs full slots — a padding
    SLOT computes drift over zero rows, where the chi-squared path yields
    NaN by construction before the fold selects it away
    (`monitor/state.py fold_accumulator_grouped`), and checkify flags NaN
    at the op that produces it regardless of later masking, so that case
    is pinned by value in `tests/test_packed_parity.py` instead.

    Returns human-readable result lines; raises ``NumericAuditError``
    (naming the entry that tripped) if a NaN/Inf escapes the fused
    predict or the accumulator leaves the audit non-finite.
    """
    import jax
    import numpy as np
    from jax.experimental import checkify

    from mlops_tpu.data import Preprocessor, generate_synthetic
    from mlops_tpu.models import build_model, init_params
    from mlops_tpu.monitor.state import fit_monitor, init_accumulator
    from mlops_tpu.ops.predict import (
        make_packed_grouped_base,
        make_packed_predict_base,
        packed_layout,
    )
    from mlops_tpu.utils.debug import checked

    columns, labels = generate_synthetic(512, seed=0)
    prep = Preprocessor.fit(columns)
    ds = prep.encode(columns, labels)
    model = build_model(_tiny_model_config())
    variables = init_params(model, jax.random.PRNGKey(0))
    monitor = fit_monitor(ds)
    temp = np.float32(1.0)

    bucket, valid = 8, 5  # padding rows exercise the masked drift path
    solo = checked(make_packed_predict_base(model), jit=True)
    try:
        packed, acc = solo(
            variables,
            monitor,
            init_accumulator(),
            temp,
            ds.cat_ids[:bucket],
            ds.numeric[:bucket].astype(np.float32),
            np.arange(bucket) < valid,
        )
    except checkify.JaxRuntimeError as err:
        raise NumericAuditError(
            "serve-predict-packed", f"checkify float checks tripped: {err}"
        ) from err
    p, _, _ = packed_layout(bucket)
    preds = np.asarray(packed)[p][:valid]

    slots, rows = 2, 1  # full slots: every slot folds real drift
    grouped = checked(make_packed_grouped_base(model), jit=True)
    try:
        _, acc = grouped(
            variables,
            monitor,
            acc,
            temp,
            ds.cat_ids[: slots * rows].reshape(slots, rows, -1),
            ds.numeric[: slots * rows]
            .astype(np.float32)
            .reshape(slots, rows, -1),
            np.ones((slots, rows), bool),
        )
    except checkify.JaxRuntimeError as err:
        raise NumericAuditError(
            "serve-predict-group-packed",
            f"checkify float checks tripped: {err}",
        ) from err
    if not all(
        np.isfinite(np.asarray(leaf)).all()
        for leaf in jax.tree_util.tree_leaves(acc)
    ):
        raise NumericAuditError(
            "serve-predict-group-packed",
            "monitor accumulator left the numeric audit non-finite",
        )
    return [
        f"numeric audit: serve-predict-packed {valid}/{bucket} padded rows "
        f"under checkify float_checks — clean "
        f"(p50 prediction {float(np.median(preds)):.4f})",
        f"numeric audit: serve-predict-group-packed {slots}x{rows} slots + "
        "accumulator fold — clean (aggregate finite)",
    ]
