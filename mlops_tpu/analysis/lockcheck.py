"""Runtime lock sanitizer: instrumented locks + seeded schedule perturbation.

The static layer (`analysis/concurrency.py`) proves what it can see
lexically; the interleavings it cannot see — the micro-batcher's
dispatch/fetch overlap, the pipeline executor's stage threads, engine
warmup racing live traffic — are exercised here instead. Tests swap an
object's real ``threading.Lock``/``Semaphore`` attributes for instrumented
wrappers that

- record per-thread acquisition stacks and assert the DECLARED lock order
  (the same ``TPULINT_LOCK_ORDER`` manifest the static layer reads, so the
  two checks can never disagree about intent) — violations are collected,
  never raised mid-test, so the assertion happens once at the end with the
  full evidence;
- account blocked time per lock (``total_wait_ms`` — `bench.py` exports it
  as the ``lock_wait_ms`` satellite key so contention regressions show in
  the BENCH_* trajectory);
- optionally perturb the schedule: a seeded random pre-acquire delay
  shifts thread interleavings run to run, so three seeds explore three
  schedules while the deterministic stage graphs must still produce
  BIT-IDENTICAL outputs (`tests/test_batcher.py`,
  `tests/test_pipeline_exec.py`).

No JAX import — usable on any machine, including inside `bench.py` before
a backend exists.
"""

from __future__ import annotations

import contextlib
import dataclasses
import random
import sys
import threading
import time
from typing import Any, Callable, Iterator


@dataclasses.dataclass(frozen=True)
class OrderViolation:
    """One observed out-of-order (or undeclared) acquisition."""

    thread: str
    acquiring: str
    holding: tuple[str, ...]
    note: str

    def __str__(self) -> str:  # readable in pytest assertion output
        return (
            f"[{self.thread}] acquired {self.acquiring!r} while holding "
            f"{self.holding} — {self.note}"
        )


class LockSanitizer:
    """Shared state for a set of instrumented locks: per-thread held
    stacks, declared-order checking, wait accounting, and the seeded
    perturber. ``order`` lists lock names OUTERMOST FIRST (the
    ``TPULINT_LOCK_ORDER`` convention); an empty order disables order
    checking but keeps the accounting."""

    def __init__(
        self,
        order: tuple[str, ...] = (),
        perturb_seed: int | None = None,
        max_perturb_s: float = 0.002,
    ) -> None:
        self._rank = {name: i for i, name in enumerate(order)}
        # Per-thread held stacks in a shared registry (not threading.local):
        # a semaphore permit acquired on one thread and released on another
        # (the two-phase dispatch/fetch handoff) must be POPPABLE from the
        # acquirer's stack, or the stale entry manufactures order
        # violations forever and the stack grows without bound.
        self._stacks: dict[int, list[str]] = {}
        self._meta = threading.Lock()
        self._max_perturb_s = max_perturb_s
        self._rng = (
            random.Random(perturb_seed) if perturb_seed is not None else None
        )
        self.violations: list[OrderViolation] = []
        self.acquired: dict[str, int] = {}
        self.wait_s: dict[str, float] = {}

    # ------------------------------------------------------------- state
    @property
    def total_wait_s(self) -> float:
        with self._meta:
            return sum(self.wait_s.values())

    @property
    def total_wait_ms(self) -> float:
        return self.total_wait_s * 1e3

    # ----------------------------------------------------------- perturb
    def perturb(self) -> None:
        """Seeded random delay (schedule perturbation). The draw is
        serialized (Random is not thread-safe) but the sleep is not — the
        delay itself is what shifts the interleaving."""
        if self._rng is None:
            return
        with self._meta:
            delay = self._rng.random() * self._max_perturb_s
        time.sleep(delay)

    # ------------------------------------------------------------- hooks
    def note_acquire(self, name: str, waited_s: float) -> None:
        with self._meta:
            held = list(
                self._stacks.setdefault(threading.get_ident(), [])
            )
        for holding in held:
            note = None
            if self._rank:
                if name not in self._rank:
                    note = (
                        "lock is not in the declared order "
                        "(TPULINT_LOCK_ORDER) — declare every lock that "
                        "participates in nesting"
                    )
                elif holding in self._rank and (
                    self._rank[name] < self._rank[holding]
                ):
                    note = (
                        "inverts the declared order — a thread taking the "
                        "declared order deadlocks against this one"
                    )
            if note is not None:
                violation = OrderViolation(
                    thread=threading.current_thread().name,
                    acquiring=name,
                    holding=tuple(held),
                    note=note,
                )
                with self._meta:
                    self.violations.append(violation)
        with self._meta:
            self._stacks[threading.get_ident()].append(name)
            self.acquired[name] = self.acquired.get(name, 0) + 1
            self.wait_s[name] = self.wait_s.get(name, 0.0) + waited_s

    def note_release(self, name: str) -> None:
        def pop_innermost(stack: list[str]) -> bool:
            # remove the innermost occurrence (re-entrant/duplicate safe)
            for i in range(len(stack) - 1, -1, -1):
                if stack[i] == name:
                    del stack[i]
                    return True
            return False

        ident = threading.get_ident()
        with self._meta:
            own = self._stacks.setdefault(ident, [])
            if pop_innermost(own):
                return
            # Cross-thread release (semaphore handoff): pop the permit from
            # whichever thread's stack still carries it.
            for other, stack in self._stacks.items():
                if other != ident and pop_innermost(stack):
                    return

    # ------------------------------------------------------------- wraps
    def wrap(self, inner: Any, name: str) -> "InstrumentedLock":
        """Wrap any acquire/release primitive (Lock, RLock, Semaphore,
        BoundedSemaphore) — the wrapper is duck-type compatible with all
        of them for the operations this codebase uses."""
        return InstrumentedLock(self, inner, name)


class InstrumentedLock:
    """Duck-typed stand-in for a ``threading`` lock or semaphore: context
    manager + ``acquire``/``release``, reporting into a LockSanitizer."""

    def __init__(self, sanitizer: LockSanitizer, inner: Any, name: str):
        self._san = sanitizer
        self._inner = inner
        self.name = name

    def acquire(self, *args, **kwargs) -> bool:
        self._san.perturb()
        start = time.perf_counter()
        ok = self._inner.acquire(*args, **kwargs)
        waited = time.perf_counter() - start
        if ok:
            self._san.note_acquire(self.name, waited)
        return ok

    def release(self, *args, **kwargs) -> None:
        self._inner.release(*args, **kwargs)
        self._san.note_release(self.name)

    def locked(self) -> bool:  # Lock protocol passthrough
        return self._inner.locked()

    def __enter__(self) -> "InstrumentedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()


def declared_order(obj: Any) -> tuple[str, ...]:
    """The ``TPULINT_LOCK_ORDER`` entry for ``obj``'s class, read from its
    defining module — the single source of truth shared with the static
    layer."""
    module = sys.modules.get(type(obj).__module__)
    manifest = getattr(module, "TPULINT_LOCK_ORDER", {})
    return tuple(manifest.get(type(obj).__name__, ()))


def _lock_attrs(obj: Any) -> list[str]:
    """Attribute names on ``obj`` that quack like THREADING locks or
    semaphores. asyncio primitives (the batcher's dispatch/fetch rings)
    also have acquire/release, but their ``acquire`` is a coroutine — a
    synchronous wrapper would return the coroutine un-awaited, count it as
    a successful acquisition, and leave the permit count untouched, so the
    semaphore would silently stop bounding anything. They are event-loop
    confined anyway; the schedule perturber covers them instead."""
    import inspect

    names = []
    for name, value in vars(obj).items():
        if isinstance(value, InstrumentedLock):
            continue  # never double-wrap
        acquire = getattr(value, "acquire", None)
        if (
            callable(acquire)
            and callable(getattr(value, "release", None))
            and not inspect.iscoroutinefunction(acquire)
        ):
            names.append(name)
    return names


@contextlib.contextmanager
def instrument_locks(
    obj: Any,
    attrs: tuple[str, ...] | None = None,
    order: tuple[str, ...] | None = None,
    perturb_seed: int | None = None,
    max_perturb_s: float = 0.002,
) -> Iterator[LockSanitizer]:
    """Swap ``obj``'s lock attributes for instrumented wrappers for the
    duration of the block; restore the originals on exit. ``attrs``
    defaults to every lock-shaped attribute; ``order`` defaults to the
    module's ``TPULINT_LOCK_ORDER`` declaration for the class. Objects
    with no locks (the sklearn engine flavor) yield a sanitizer that
    simply reports zeros."""
    if attrs is None:
        attrs = tuple(_lock_attrs(obj))
    if order is None:
        order = declared_order(obj)
    sanitizer = LockSanitizer(
        order=order, perturb_seed=perturb_seed, max_perturb_s=max_perturb_s
    )
    saved = {}
    try:
        for name in attrs:
            inner = getattr(obj, name, None)
            if inner is None:
                continue
            saved[name] = inner
            setattr(obj, name, sanitizer.wrap(inner, name))
        yield sanitizer
    finally:
        for name, inner in saved.items():
            setattr(obj, name, inner)


def instrument_engine(
    engine: Any, perturb_seed: int | None = None, max_perturb_s: float = 0.002
):
    """Sugar for the common case: instrument an ``InferenceEngine``'s
    threading locks against its declared order."""
    return instrument_locks(
        engine, perturb_seed=perturb_seed, max_perturb_s=max_perturb_s
    )


class SchedulePerturber:
    """Seeded random delays for schedule-perturbing stress tests: wrap a
    stage function (or call ``sleep()`` at a chosen point) so thread
    interleavings shift run to run while outputs must not."""

    def __init__(self, seed: int, max_delay_s: float = 0.002) -> None:
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self.max_delay_s = max_delay_s

    def sleep(self) -> None:
        with self._lock:
            delay = self._rng.random() * self.max_delay_s
        time.sleep(delay)

    def wrap(self, fn: Callable) -> Callable:
        def perturbed(*args, **kwargs):
            self.sleep()
            return fn(*args, **kwargs)

        return perturbed
