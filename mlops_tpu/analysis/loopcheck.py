"""Runtime event-loop-lag sanitizer — asyncdiscipline's dynamic twin.

`analysis/asyncdiscipline.py` proves lexically that no loop-confined
code path makes a blocking call (TPU601–604). This module checks the
same discipline at RUNTIME, the exact split lockcheck.py provides for
concurrency.py: the static layer sees every lexical path, the sanitizer
sees the real interleaving — a callback that blocks only under load, a
C-extension stall the AST can't name, an executor pool starved into
running work inline.

``LoopLagSanitizer`` wraps the running loop's scheduling entry points
(``call_soon`` / ``call_later`` / ``call_at`` / ``call_soon_threadsafe``
— every coroutine step funnels through ``call_soon`` via ``Task.__step``,
so awaits are covered too) and times each callback on the loop thread:

    sanitizer = LoopLagSanitizer(slow_ms=50.0)
    sanitizer.attach(loop)
    ... run traffic ...
    sanitizer.assert_max_lag(100.0)   # raises listing the slow records

Tests use ``instrument_loop`` (the ``instrument_locks`` analog) or
``assert_max_lag``; production arms it via the ``serve.loop_lag_monitor``
knob and drains ``snapshot_ms()`` into the
``mlops_tpu_event_loop_lag_ms`` gauge each /metrics scrape (window max:
"no stall since the last scrape" reads 0.0, and the series always
renders — the absent-series ambiguity is exactly what the always-emit
contract forbids).

``perturb_seed`` delays each callback by a seeded pseudo-random sleep
BEFORE its timing window opens (the SchedulePerturber discipline from
lockcheck): it shifts the loop's interleaving against executor threads
to flush ordering assumptions, without polluting lag attribution.

Like lockcheck, this module is dependency-free and never imports JAX.
"""

from __future__ import annotations

import contextlib
import dataclasses
import random
import time
import traceback
from typing import Any, Callable, Iterator

_PATCHED = ("call_soon", "call_later", "call_at", "call_soon_threadsafe")


@dataclasses.dataclass(frozen=True)
class LagRecord:
    """One slower-than-threshold callback execution on the loop thread."""

    label: str  # callback attribution (coroutine / function qualname)
    duration_ms: float
    schedule_site: str | None  # where it was scheduled, if stacks are on

    def __str__(self) -> str:  # pytest-friendly, like OrderViolation
        site = f" (scheduled at {self.schedule_site})" if (
            self.schedule_site
        ) else ""
        return f"{self.label} held the event loop {self.duration_ms:.1f}ms{site}"


def _attribute(callback: Callable[..., Any]) -> str:
    """Best attribution for a loop callback: coroutine qualname for Task
    steps, function qualname otherwise."""
    owner = getattr(callback, "__self__", None)
    get_coro = getattr(owner, "get_coro", None)
    if get_coro is not None:
        try:
            coro = get_coro()
            name = getattr(coro, "__qualname__", None)
            if name:
                return f"task:{name}"
        except (AttributeError, RuntimeError, TypeError):
            pass  # not a Task after all: fall through to the qualname
    return getattr(callback, "__qualname__", None) or repr(callback)


class LoopLagSanitizer:
    """Times every callback the patched loop runs; keeps the window max
    for the gauge, the worst offenders for attribution, and an all-time
    max for ``assert_max_lag``.

    ``slow_ms``: callbacks at or above this are recorded in ``slow``
    (bounded) with attribution. ``capture_stacks`` stores the schedule
    site per callback — test-only, it prices every scheduling call.
    """

    def __init__(
        self,
        slow_ms: float = 50.0,
        capture_stacks: bool = False,
        perturb_seed: int | None = None,
        max_perturb_s: float = 0.002,
        keep: int = 16,
    ) -> None:
        self.slow_ms = float(slow_ms)
        self.capture_stacks = capture_stacks
        self.slow: list[LagRecord] = []
        self.callbacks = 0  # total callbacks timed
        self.max_lag_ms = 0.0  # all-time worst
        self._window_max_ms = 0.0  # worst since last snapshot_ms()
        self._keep = keep
        self._loop: Any = None
        self._saved: dict[str, Callable[..., Any]] = {}
        self._rng = random.Random(perturb_seed) if (
            perturb_seed is not None
        ) else None
        self._max_perturb_s = max_perturb_s

    # ------------------------------------------------------ observation
    def _note(self, duration_ms: float, label: str, site: str | None) -> None:
        self.callbacks += 1
        if duration_ms > self.max_lag_ms:
            self.max_lag_ms = duration_ms
        if duration_ms > self._window_max_ms:
            self._window_max_ms = duration_ms
        if duration_ms >= self.slow_ms:
            self.slow.append(LagRecord(label, duration_ms, site))
            if len(self.slow) > self._keep:
                # keep the worst offenders, not the most recent
                self.slow.sort(key=lambda r: -r.duration_ms)
                del self.slow[self._keep:]

    def snapshot_ms(self) -> float:
        """Worst callback wall time since the previous call, then reset —
        the /metrics gauge semantics: each scrape reads one window's max,
        and a quiet window reads 0.0."""
        value, self._window_max_ms = self._window_max_ms, 0.0
        return value

    def assert_max_lag(self, max_ms: float) -> None:
        """Raise if any callback so far held the loop ``max_ms`` or
        longer, listing the recorded offenders."""
        if self.max_lag_ms < max_ms:
            return
        offenders = "\n  ".join(
            str(r) for r in sorted(self.slow, key=lambda r: -r.duration_ms)
        ) or f"worst callback: {self.max_lag_ms:.1f}ms (below slow_ms, no attribution)"
        raise AssertionError(
            f"event-loop lag {self.max_lag_ms:.1f}ms >= {max_ms:.1f}ms "
            f"across {self.callbacks} callbacks:\n  {offenders}"
        )

    # -------------------------------------------------------- patching
    def _wrap_callback(
        self, callback: Callable[..., Any]
    ) -> Callable[..., Any]:
        if getattr(callback, "_loopcheck_wrapped", False):
            return callback  # rescheduled handle: keep one timing layer
        site = None
        if self.capture_stacks:
            # drop this frame + the patched scheduling frame
            frame = traceback.extract_stack(limit=4)[0]
            site = f"{frame.filename}:{frame.lineno} in {frame.name}"
        label = _attribute(callback)

        def timed(*args: Any) -> Any:
            if self._rng is not None:
                # seeded schedule perturbation, outside the timing window
                time.sleep(self._rng.random() * self._max_perturb_s)
            start = time.perf_counter()
            try:
                return callback(*args)
            finally:
                self._note(
                    (time.perf_counter() - start) * 1e3, label, site
                )

        timed._loopcheck_wrapped = True  # type: ignore[attr-defined]
        return timed

    def attach(self, loop: Any) -> None:
        """Patch ``loop``'s scheduling entry points (instance attributes
        — the loop class stays untouched) so every callback it runs is
        timed. Idempotent per loop; ``detach`` restores."""
        if self._loop is not None:
            raise RuntimeError("sanitizer already attached")
        self._loop = loop
        for name in ("call_soon", "call_soon_threadsafe"):
            original = getattr(loop, name)
            self._saved[name] = original

            def scheduler(
                callback: Callable[..., Any],
                *args: Any,
                _original: Callable[..., Any] = original,
                **kwargs: Any,
            ) -> Any:
                return _original(
                    self._wrap_callback(callback), *args, **kwargs
                )

            setattr(loop, name, scheduler)
        for name in ("call_later", "call_at"):
            original = getattr(loop, name)
            self._saved[name] = original

            def delayed(
                when: float,
                callback: Callable[..., Any],
                *args: Any,
                _original: Callable[..., Any] = original,
                **kwargs: Any,
            ) -> Any:
                return _original(
                    when, self._wrap_callback(callback), *args, **kwargs
                )

            setattr(loop, name, delayed)

    def detach(self) -> None:
        """Restore the loop's original scheduling methods."""
        if self._loop is None:
            return
        for name in _PATCHED:
            original = self._saved.pop(name, None)
            if original is not None:
                # the originals were bound methods; deleting the instance
                # attribute re-exposes them, keeping the loop pristine
                try:
                    delattr(self._loop, name)
                except AttributeError:
                    setattr(self._loop, name, original)
        self._loop = None


@contextlib.contextmanager
def instrument_loop(
    loop: Any,
    slow_ms: float = 50.0,
    capture_stacks: bool = True,
    perturb_seed: int | None = None,
    max_perturb_s: float = 0.002,
) -> Iterator[LoopLagSanitizer]:
    """``instrument_locks``'s loop analog: attach a ``LoopLagSanitizer``
    for the duration of a with-block and always detach, so a failing
    assertion never leaves a patched loop behind."""
    sanitizer = LoopLagSanitizer(
        slow_ms=slow_ms,
        capture_stacks=capture_stacks,
        perturb_seed=perturb_seed,
        max_perturb_s=max_perturb_s,
    )
    sanitizer.attach(loop)
    try:
        yield sanitizer
    finally:
        sanitizer.detach()
