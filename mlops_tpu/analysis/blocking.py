"""The shared blocking-call classifier (Layers 3 and 5).

ONE table answers "does this call block the calling thread?" for both
consumers: Layer 3 (`concurrency.py` TPU403 — blocking under a held
mutex) and Layer 5 (`asyncdiscipline.py` TPU601 — blocking inside an
event-loop-confined context). The two layers gate different disciplines
but must never disagree about what "blocking" means: a call the lock
layer treats as a stall is a stall on the event loop too, so the table
lives here and both import it.

Layer 5 additionally recognizes the LOOP-context extras (subprocess
waits, synchronous socket operations): a thread stalled in ``recv`` hurts
one thread, but an event loop stalled in it hurts every in-flight
connection on that worker, so the loop context classifies more calls as
blocking — strictly a superset, never a different verdict on the shared
entries.

Pure ``ast`` helpers, no JAX import (the Layer 1/3/4 discipline).
"""

from __future__ import annotations

import ast

# Method names that block (or can block) the calling thread. ``join`` is
# special-cased by callers to skip string / path-module receivers.
BLOCKING_METHODS = {
    "block_until_ready",
    "item",
    "tolist",
    "compile",
    "join",
    "result",
    "wait",
    "put",
    "read_text",
    "read_bytes",
    "write_text",
    "write_bytes",
    "unlink",
    "mkdir",
}
# Dotted-name calls that block or materialize device values on the host.
BLOCKING_CALLS = {
    "np.asarray",
    "np.array",
    "numpy.asarray",
    "numpy.array",
    "onp.asarray",
    "onp.array",
    "jax.device_get",
    "device_get",
    "jax.block_until_ready",
    "time.sleep",
    "subprocess.run",
    "os.replace",
    "open",
}
# ``.join()`` receivers that are string/path helpers, not threads/queues.
JOIN_SAFE_ROOTS = {"os", "posixpath", "ntpath", "str"}
# ``.compile()`` receivers that are regex/builtins, not XLA lowerings.
COMPILE_SAFE_ROOTS = {"re"}

# Loop-context extras (TPU601 only): calls a worker THREAD may make
# without stalling anyone else, but an EVENT LOOP must never make
# directly — subprocess waits and synchronous socket operations.
LOOP_BLOCKING_METHODS = {
    "communicate",
    "recv",
    "recv_into",
    "accept",
    "sendall",
    "getaddrinfo",
}
LOOP_BLOCKING_CALLS = {
    "subprocess.call",
    "subprocess.check_call",
    "subprocess.check_output",
    "subprocess.Popen",
    "socket.create_connection",
}


def dotted(node: ast.AST) -> str | None:
    """``a.b.c`` attribute chains as a dotted string (None otherwise)."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def classify_blocking(
    call: ast.Call, loop_context: bool = False
) -> str | None:
    """A short human label ("``.join()``", "``time.sleep()``") when
    ``call`` is a blocking operation per the shared table, else None.
    ``loop_context`` adds the event-loop-only extras (subprocess waits,
    sync socket ops) to the verdict."""
    func = call.func
    if isinstance(func, ast.Attribute):
        methods = BLOCKING_METHODS | (
            LOOP_BLOCKING_METHODS if loop_context else set()
        )
        if func.attr in methods:
            receiver = dotted(func.value) or ""
            root = receiver.split(".")[0]
            if func.attr == "join" and (
                isinstance(func.value, ast.Constant)
                or root in JOIN_SAFE_ROOTS
            ):
                return None
            if func.attr == "compile" and root in COMPILE_SAFE_ROOTS:
                return None
            return f".{func.attr}()"
        if func.attr == "get" and not call.args and not call.keywords:
            # zero-arg .get(): a blocking queue read (dict.get takes a key)
            return ".get() (blocking queue read)"
    name = dotted(func) or ""
    calls = BLOCKING_CALLS | (LOOP_BLOCKING_CALLS if loop_context else set())
    if name in calls:
        return f"{name}()"
    return None
