"""tpulint — five-layer static analysis for the TPU hot paths.

The production path (train -> register -> serve -> monitor) only hits its
latency/goodput targets while the compiled hot paths STAY compiled: one
stray host sync inside a jitted function, or a dtype-driven recompile, and
the <5 ms p50 serving target silently dies without any test failing. This
package keeps the codebase honest on every PR:

- **Layer 1** (`astrules`): named AST rules over the package source — pure
  ``ast``, no JAX import, so it runs anywhere in milliseconds. Catches
  TPU-hostile patterns at the source level (host syncs under trace, Python
  RNG/clock under trace, tracer-dependent branches, jit signatures missing
  ``static_argnames``/``donate_argnums``, broad excepts, mutable defaults).
- **Layer 2** (`traces` + `entrypoints`): the framework's REGISTERED jitted
  entry points (train step, TP step, serve predict) are abstract-evaluated
  via ``jax.make_jaxpr`` on schema-derived dummy batches — no device code
  executes — and the resulting jaxprs are checked for recompile and
  numerics hazards (float64 leaks, weak-type outputs, convert_element_type
  round-trips, per-bucket shape polymorphism, producer/consumer sharding
  mismatches).
- **Layer 3** (`concurrency`): lock-discipline rules over the hand-rolled
  threading layer (serving engine, micro-batcher, pipeline executor,
  compile cache) — lock-order graph vs the declared ``TPULINT_LOCK_ORDER``
  manifest, guard inference for shared attributes, blocking calls under a
  held mutex, semaphore acquire/release pairing. Pure ``ast``, opt-in via
  ``analyze --concurrency`` (CI runs it). The RUNTIME half (`lockcheck`)
  swaps real locks for instrumented wrappers in tests: per-thread
  acquisition stacks asserted against the same declared order, lock-wait
  accounting (bench's ``lock_wait_ms``), and seeded schedule perturbation.
- **Layer 4** (`contracts` + `seriesreg`): cross-process CONTRACT rules,
  analyzed project-wide rather than per file — shm ring fields checked
  against the declared writer-role manifest (``TPULINT_SHM_OWNERSHIP``),
  the Prometheus series surface extracted from both renderer planes and
  checked for parity, bounded labels, alert-rule references and docs
  coverage, config knobs that validate but are never read (the PR 13
  ``replica_affinity_slack`` class), and fault points without a fire
  site. Pure ``ast``, opt-in via ``analyze --contracts`` (CI runs it).
- **Layer 5** (`asyncdiscipline`): async/event-loop discipline over the
  serve plane, analyzed project-wide like Layer 4 — a call graph seeds
  event-loop confinement from ``async def`` bodies, loop-callback
  registrations, and the declared ``TPULINT_LOOP_CONFINED`` manifest,
  propagates it through sync helpers reachable only from confined
  contexts, then gates blocking calls on the loop (TPU601, sharing Layer
  3's blocking table via `blocking`), fire-and-forget tasks (TPU602),
  cross-thread writes to loop-confined state (TPU603), and ``await``
  under a sync mutex (TPU604). Pure ``ast``, opt-in via ``analyze
  --async`` (CI runs it). The RUNTIME half (`loopcheck`) wraps the
  running loop's callback execution in tests and production: per-callback
  wall time with attribution, a max-lag assert, and the
  ``mlops_tpu_event_loop_lag_ms`` gauge.

The suppression ledger stays honest via ``analyze --list-suppressions``
(every ``# tpulint: disable`` with live/stale status) and ``--fail-stale``
(stale ones gate as TPU400).

CLI: ``mlops-tpu analyze [--strict] [--concurrency] [--contracts]
[--async] [paths ...]``
(`analysis/cli.py`); CI runs it as a gate before pytest. Suppress a
finding inline with ``# tpulint: disable=TPU101`` (see
`docs/static-analysis.md`).
"""

from __future__ import annotations

from mlops_tpu.analysis.findings import Finding, Severity, format_findings
from mlops_tpu.analysis.astrules import RULES, analyze_paths, analyze_source
from mlops_tpu.analysis.concurrency import (
    CONCURRENCY_RULES,
    analyze_concurrency_paths,
    analyze_concurrency_source,
)
from mlops_tpu.analysis.contracts import (
    CONTRACT_RULES,
    analyze_contracts_paths,
    analyze_contracts_source,
)
from mlops_tpu.analysis.asyncdiscipline import (
    ASYNC_RULES,
    analyze_async_paths,
    analyze_async_project,
    analyze_async_source,
)

__all__ = [
    "ASYNC_RULES",
    "CONCURRENCY_RULES",
    "CONTRACT_RULES",
    "Finding",
    "RULES",
    "Severity",
    "analyze_async_paths",
    "analyze_async_project",
    "analyze_async_source",
    "analyze_concurrency_paths",
    "analyze_concurrency_source",
    "analyze_contracts_paths",
    "analyze_contracts_source",
    "analyze_paths",
    "analyze_source",
    "format_findings",
]
