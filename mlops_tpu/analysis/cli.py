"""``mlops-tpu analyze`` — orchestrates both layers and gates the exit code.

Exit codes: 0 clean, 1 findings that gate (errors always; warnings too
under ``--strict``), 2 internal analyzer failure. Layer 1 never imports
JAX; Layer 2 does (skip it with ``--no-trace`` on JAX-less machines).
"""

from __future__ import annotations

import argparse
from pathlib import Path

from mlops_tpu.analysis.astrules import analyze_paths
from mlops_tpu.analysis.findings import Finding, format_findings


def _default_paths() -> list[str]:
    """Lint the installed package when run without paths — works from any
    cwd, matching how CI invokes the gate."""
    return [str(Path(__file__).resolve().parents[1])]


def run_analyze(args: argparse.Namespace) -> int:
    """Exit 2 (usage/analyzer failure) is distinct from 1 (findings):
    scripts keying on the gate must not read a typo'd path or an analyzer
    crash as lint violations."""
    try:
        return _run_analyze(args)
    # The boundary that implements the documented exit-code contract:
    # any analyzer crash becomes a visible 2, never a fake 1.
    except Exception as err:  # tpulint: disable=TPU201
        print(f"tpulint: internal analyzer failure: {type(err).__name__}: {err}")
        return 2


def _run_analyze(args: argparse.Namespace) -> int:
    paths = list(getattr(args, "paths", []) or []) or _default_paths()
    strict = bool(getattr(args, "strict", False))
    missing = [p for p in paths if not Path(p).exists()]
    if missing:
        print(f"tpulint: error: no such path: {', '.join(missing)}")
        return 2

    if getattr(args, "list_suppressions", False):
        # Report mode: the suppression ledger instead of the finding gate.
        from mlops_tpu.analysis.suppressions import (
            audit_paths,
            format_suppressions,
        )

        suppressions = audit_paths(paths)
        print(format_suppressions(suppressions))
        stale = [
            s for s in suppressions if not s.live and not s.skipped_file
        ]
        return 1 if (stale and getattr(args, "fail_stale", False)) else 0

    # Per-layer wall time, reported under --strict: the gate grows a
    # layer per review epoch, and a slow layer should show up in CI
    # output (and bench.py's analysis_wall_s), not in folklore.
    from time import perf_counter

    timings: list[tuple[str, float]] = []

    def timed(label: str, fn):
        t0 = perf_counter()
        result = fn()
        timings.append((label, perf_counter() - t0))
        return result

    findings: list[Finding] = timed("layer1", lambda: analyze_paths(paths))
    if getattr(args, "concurrency", False):
        from mlops_tpu.analysis.concurrency import analyze_concurrency_paths

        findings.extend(
            timed("layer3", lambda: analyze_concurrency_paths(paths))
        )
    if getattr(args, "contracts", False):
        from mlops_tpu.analysis.contracts import analyze_contracts_paths

        findings.extend(
            timed("layer4", lambda: analyze_contracts_paths(paths))
        )
    if getattr(args, "async_rules", False):
        from mlops_tpu.analysis.asyncdiscipline import analyze_async_paths

        findings.extend(
            timed("layer5", lambda: analyze_async_paths(paths))
        )
    if getattr(args, "fail_stale", False):
        from mlops_tpu.analysis.suppressions import stale_findings

        # TPU400 findings are immune to disable comments by construction
        # (suppressions.py): a stale disable can't silence its own report.
        findings.extend(timed("audit", lambda: stale_findings(paths)))

    notes: list[str] = []
    if not getattr(args, "no_trace", False):
        # First jax touch of the command: re-assert an explicit
        # JAX_PLATFORMS before any backend initializes (commands.py does
        # this for every other subcommand; analyze defers it to here so
        # --no-trace stays importable on JAX-less machines).
        from mlops_tpu.commands import _honor_jax_platforms_env

        _honor_jax_platforms_env()
        from mlops_tpu.analysis.traces import run_trace_checks

        trace_findings, notes = timed("layer2", run_trace_checks)
        findings.extend(trace_findings)

    if getattr(args, "numeric", False):
        from mlops_tpu.analysis.entrypoints import NumericAuditError, numeric_audit

        try:
            notes.extend(numeric_audit())
        except NumericAuditError as err:
            from mlops_tpu.analysis.findings import Severity

            findings.append(
                Finding(
                    rule="TPU307",
                    name="numeric-audit-failure",
                    severity=Severity.ERROR,
                    path=f"<numeric:{err.entry}>",
                    line=0,
                    message=str(err),
                )
            )

    for note in notes:
        print(f"tpulint: {note}")
    if strict and timings:
        spent = " | ".join(f"{label} {secs:.2f}s" for label, secs in timings)
        print(f"tpulint: layer timings: {spent}")
    if findings:
        print(format_findings(findings))
    gating = [f for f in findings if f.gates(strict)]
    print(
        f"tpulint: {len(findings)} finding(s), {len(gating)} gating"
        f"{' (strict)' if strict else ''} over {len(paths)} path(s)"
    )
    return 1 if gating else 0
