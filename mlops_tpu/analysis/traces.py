"""Layer 2: jaxpr-level checks over registered jitted entry points.

Each entry point (`analysis/entrypoints.py`) is abstract-evaluated with
``jax.make_jaxpr`` on schema-derived ``ShapeDtypeStruct`` batches — the
trace runs entirely in Python (no XLA compile, no device execution, works
under ``JAX_PLATFORMS=cpu``) yet sees exactly the program the production
builder would compile, because the entry wrappers call the REAL builders
(`make_train_window`, `make_padded_predict_fn`, `make_sharded_train_step`).

Checks (rule IDs continue the tpulint catalog):

- **TPU301 float64-leak**: any f64 value anywhere in the traced program —
  on TPU that silently demotes per-op or recompiles, and it means an
  unintended ``jax_enable_x64`` dependency.
- **TPU302 weak-type-output**: an output aval with ``weak_type=True`` —
  feeding it back into the entry (train-state loops!) makes the second
  call's signature differ from the first and recompiles.
- **TPU303 convert-element-type-round-trip**: ``convert_element_type``
  directly chained onto another whose output dtype returns to the start —
  a wasted cast pair that usually marks a dtype discipline bug.
- **TPU304 bucket-shape-polymorphism**: the primitive sequence of the
  traced program differs across the declared batch buckets — each bucket
  is then a genuinely different program, not the same program at another
  shape (padding/bucketing assumptions broken).
- **TPU305 sharding-link-mismatch**: a declared producer->consumer link
  (train step emits params, serve predict consumes them) whose shardings
  disagree — the consumer reshards on every handoff.
"""

from __future__ import annotations

import contextlib
import dataclasses
from typing import Any, Callable

from mlops_tpu.analysis.findings import Finding, Severity

TRACE_RULES = {
    "TPU301": ("float64-leak", Severity.ERROR),
    "TPU302": ("weak-type-output", Severity.ERROR),
    "TPU303": ("convert-element-type-round-trip", Severity.WARNING),
    "TPU304": ("bucket-shape-polymorphism", Severity.ERROR),
    "TPU305": ("sharding-link-mismatch", Severity.ERROR),
    "TPU306": ("entry-point-trace-failure", Severity.ERROR),
}


@dataclasses.dataclass
class EntryPoint:
    """One registered jitted entry point.

    ``build()`` returns ``(fn, args_by_bucket)`` where ``args_by_bucket``
    maps a batch-bucket size to the argument pytree (ShapeDtypeStructs) the
    entry is traced with. ``min_devices`` gates mesh-dependent entries;
    they are reported as skipped, never silently dropped.
    """

    name: str
    build: Callable[[], tuple[Callable, dict[int, tuple]]]
    min_devices: int = 1
    # Declared param-sharding contract for TPU305 links: a pytree of
    # PartitionSpec-like leaves (or None = replicated), produced/consumed.
    params_out_spec: Any = None
    params_in_spec: Any = None
    # Declared program families: buckets in the SAME tuple must trace to
    # the identical primitive sequence (TPU304); buckets in different
    # tuples are KNOWN distinct programs (e.g. the serve path's dense
    # small-batch K-S below 64 rows vs the sort-based one above it,
    # monitor/state.py). None = all buckets are one family.
    bucket_families: tuple[tuple[int, ...], ...] | None = None
    # Declared x64 entry (the gbm-tensor tier): the trace runs inside
    # `jax.experimental.enable_x64()` — exactly how production lowers it
    # (ops/gbm_tensor.py) — and the dtype rules treat f64 as the entry's
    # CONTRACT rather than a leak: TPU301 is skipped, and TPU303 ignores
    # round-trips through an f64 endpoint (the f64->f32->f64 narrowing at
    # the calibration boundary is the bit-parity semantics, not waste).
    x64: bool = False


@dataclasses.dataclass(frozen=True)
class ShardingLink:
    """Producer's packaged params feed the consumer. ``transport`` names
    the declared normalization between them ("as-is", "merge-to-dense")
    purely for the report message."""

    producer: str
    consumer: str
    transport: str = "as-is"


def _flag(rule: str, entry: str, message: str, bucket: int = 0) -> Finding:
    name, severity = TRACE_RULES[rule]
    return Finding(
        rule=rule,
        name=name,
        severity=severity,
        path=f"<trace:{entry}>",
        line=bucket,
        message=message,
    )


def _walk_jaxprs(jaxpr):
    """Yield every (sub)jaxpr: the top-level one plus everything nested in
    eqn params (pjit bodies, scan bodies, cond branches, custom-vjp...)."""
    seen: set[int] = set()
    stack = [jaxpr]
    while stack:
        j = stack.pop()
        if id(j) in seen:
            continue
        seen.add(id(j))
        yield j
        for eqn in j.eqns:
            for value in eqn.params.values():
                for sub in _as_jaxprs(value):
                    stack.append(sub)


def _as_jaxprs(value) -> list:
    out = []
    values = (
        list(value) if isinstance(value, (tuple, list)) else [value]
    )
    for v in values:
        if hasattr(v, "jaxpr"):  # ClosedJaxpr
            out.append(v.jaxpr)
        elif hasattr(v, "eqns"):  # raw Jaxpr
            out.append(v)
    return out


def _iter_eqns(jaxpr):
    for j in _walk_jaxprs(jaxpr):
        yield from j.eqns


def primitive_signature(jaxpr) -> tuple[str, ...]:
    """The bucket-invariant fingerprint of the program: primitive names in
    traversal order. Shapes are deliberately excluded — shapes SHOULD
    differ across buckets; the op sequence should not."""
    return tuple(eqn.primitive.name for eqn in _iter_eqns(jaxpr))


def check_dtypes(
    entry_name: str, bucket: int, jaxpr, x64_entry: bool = False
) -> list[Finding]:
    """TPU301 (f64 anywhere) + TPU303 (convert round-trips).
    ``x64_entry`` relaxes both for a DECLARED f64 program (see
    `EntryPoint.x64`)."""
    import numpy as np

    findings: list[Finding] = []
    f64_hits = 0
    for eqn in _iter_eqns(jaxpr):
        for var in eqn.outvars:
            aval = getattr(var, "aval", None)
            dtype = getattr(aval, "dtype", None)
            if dtype is not None and dtype == np.float64:
                f64_hits += 1
    if f64_hits and not x64_entry:
        findings.append(
            _flag(
                "TPU301",
                entry_name,
                f"{f64_hits} float64 value(s) in the traced program — "
                "an unintended x64 dependency (TPUs demote or recompile); "
                "pin dtypes at the boundary",
                bucket,
            )
        )
    # Round-trip casts: convert(convert(x: A->B): B->A).
    producer_of: dict[Any, Any] = {}
    for eqn in _iter_eqns(jaxpr):
        if eqn.primitive.name != "convert_element_type":
            continue
        src = eqn.invars[0]
        prev = producer_of.get(src)
        if prev is not None:
            start = getattr(prev.invars[0], "aval", None)
            end = getattr(eqn.outvars[0], "aval", None)
            if (
                start is not None
                and end is not None
                and start.dtype == end.dtype
                and not (x64_entry and start.dtype == np.float64)
            ):
                findings.append(
                    _flag(
                        "TPU303",
                        entry_name,
                        f"convert_element_type round-trip "
                        f"{start.dtype}->{prev.outvars[0].aval.dtype}->"
                        f"{end.dtype} — a wasted cast pair (dtype "
                        "discipline bug or a missing fused op)",
                        bucket,
                    )
                )
        for out in eqn.outvars:
            producer_of[out] = eqn
    return findings


def check_weak_types(entry_name: str, bucket: int, jaxpr) -> list[Finding]:
    """TPU302: outputs whose avals are weakly typed."""
    findings = []
    for i, aval in enumerate(jaxpr.out_avals):
        if getattr(aval, "weak_type", False):
            findings.append(
                _flag(
                    "TPU302",
                    entry_name,
                    f"output {i} is weak-typed ({aval.dtype}) — feeding it "
                    "back in (train-state loop, cached buffer) changes the "
                    "call signature and recompiles; anchor it with an "
                    "explicit jnp dtype",
                    bucket,
                )
            )
    return findings


def check_bucket_stability(
    entry_name: str,
    jaxprs_by_bucket: dict[int, Any],
    families: tuple[tuple[int, ...], ...] | None = None,
) -> list[Finding]:
    """TPU304: the primitive sequence must be identical across the buckets
    of each declared family (all buckets, when no families declared)."""
    if families is None:
        families = (tuple(sorted(jaxprs_by_bucket)),)
    findings = []
    # A traced bucket missing from every declared family would silently
    # dodge the check — the registry declaration must keep up with the
    # bucket list it covers (e.g. serve warmup_batch_sizes).
    declared = {b for family in families for b in family}
    for bucket in sorted(set(jaxprs_by_bucket) - declared):
        findings.append(
            _flag(
                "TPU304",
                entry_name,
                f"bucket {bucket} is traced but belongs to no declared "
                "bucket family — add it to the entry's bucket_families "
                "so shape stability is actually checked for it",
                bucket,
            )
        )
    for family in families:
        present = [b for b in family if b in jaxprs_by_bucket]
        findings.extend(
            _family_stability(entry_name, jaxprs_by_bucket, present)
        )
    return findings


def _family_stability(
    entry_name: str, jaxprs_by_bucket: dict[int, Any], buckets: list[int]
) -> list[Finding]:
    if len(buckets) < 2:
        return []
    reference = primitive_signature(jaxprs_by_bucket[buckets[0]])
    findings = []
    for bucket in buckets[1:]:
        sig = primitive_signature(jaxprs_by_bucket[bucket])
        if sig != reference:
            diff_at = next(
                (
                    i
                    for i, (a, b) in enumerate(zip(reference, sig))
                    if a != b
                ),
                min(len(reference), len(sig)),
            )
            findings.append(
                _flag(
                    "TPU304",
                    entry_name,
                    f"program shape-polymorphic across batch buckets "
                    f"{buckets[0]} vs {bucket}: {len(reference)} vs "
                    f"{len(sig)} primitives, first divergence at op "
                    f"{diff_at} — each bucket compiles a genuinely "
                    "different program, breaking the padded-bucket "
                    "serving contract",
                    bucket,
                )
            )
    return findings


def _spec_leaves(spec_tree: Any) -> list[tuple[str, str]]:
    """Canonicalize a sharding-spec pytree to (path, spec-string) pairs so
    trees built from different libraries compare structurally."""
    import jax

    leaves = jax.tree_util.tree_flatten_with_path(spec_tree)[0]
    out = []
    for path, leaf in leaves:
        spec = getattr(leaf, "spec", leaf)  # NamedSharding -> PartitionSpec
        out.append((jax.tree_util.keystr(path), str(spec)))
    return sorted(out)


def check_sharding_links(
    entries: dict[str, EntryPoint], links: list[ShardingLink]
) -> list[Finding]:
    """TPU305 over the declared producer->consumer links."""
    findings = []
    for link in links:
        producer = entries.get(link.producer)
        consumer = entries.get(link.consumer)
        if producer is None or consumer is None:
            continue  # entry skipped (devices) — reported elsewhere
        out_spec = _spec_leaves(producer.params_out_spec)
        in_spec = _spec_leaves(consumer.params_in_spec)
        if out_spec != in_spec:
            mismatched = [
                f"{po} produces {so!r}, consumer expects {si!r}"
                for (po, so), (pi, si) in zip(out_spec, in_spec)
                if so != si
            ][:3] or [f"{len(out_spec)} vs {len(in_spec)} param leaves"]
            findings.append(
                _flag(
                    "TPU305",
                    f"{link.producer}->{link.consumer}",
                    f"params sharding mismatch over {link.transport!r} "
                    "transport: " + "; ".join(mismatched) + " — the "
                    "consumer reshards (all-gather) on every handoff",
                )
            )
    return findings


def run_trace_checks(
    entries: list[EntryPoint] | None = None,
    links: list[ShardingLink] | None = None,
) -> tuple[list[Finding], list[str]]:
    """Trace every available entry point and run every check.

    Returns ``(findings, notes)`` — notes record skipped entries (not
    enough devices) and per-entry trace stats for the CLI report.
    """
    import jax

    if entries is None or links is None:
        from mlops_tpu.analysis import entrypoints

        registered = entrypoints.registered_entry_points()
        entries = registered if entries is None else entries
        links = entrypoints.LINKS if links is None else links

    findings: list[Finding] = []
    notes: list[str] = []
    traced: dict[str, EntryPoint] = {}
    for entry in entries:
        if jax.device_count() < entry.min_devices:
            notes.append(
                f"skipped {entry.name}: needs >= {entry.min_devices} "
                f"devices, have {jax.device_count()} (run with "
                "XLA_FLAGS=--xla_force_host_platform_device_count=8)"
            )
            continue
        try:
            # A declared-x64 entry traces inside the x64 context — the
            # same context production lowers it in (ops/gbm_tensor.py);
            # aval canonicalization would otherwise silently demote its
            # f64 signature to f32 and trace a program nobody compiles.
            if entry.x64:
                from jax.experimental import enable_x64

                ctx = enable_x64()
            else:
                ctx = contextlib.nullcontext()
            with ctx:
                fn, args_by_bucket = entry.build()
                jaxprs = {
                    bucket: jax.make_jaxpr(fn)(*args)
                    for bucket, args in args_by_bucket.items()
                }
        # Any trace failure IS the finding (TPU306) — nothing is swallowed.
        except Exception as err:  # tpulint: disable=TPU201
            findings.append(
                _flag(
                    "TPU306",
                    entry.name,
                    f"entry point failed to trace abstractly: "
                    f"{type(err).__name__}: {err}",
                )
            )
            continue
        traced[entry.name] = entry
        ops = len(primitive_signature(next(iter(jaxprs.values()))))
        notes.append(
            f"traced {entry.name}: buckets {sorted(jaxprs)} "
            f"({ops} primitives, abstract — no device code executed)"
        )
        for bucket, jaxpr in jaxprs.items():
            findings.extend(
                check_dtypes(entry.name, bucket, jaxpr, x64_entry=entry.x64)
            )
            findings.extend(check_weak_types(entry.name, bucket, jaxpr))
        findings.extend(
            check_bucket_stability(entry.name, jaxprs, entry.bucket_families)
        )
    findings.extend(check_sharding_links(traced, links))
    return findings, notes
