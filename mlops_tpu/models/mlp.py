"""Embedding-MLP and linear tabular classifiers.

TPU-first design notes: categorical features enter as int32 ids and hit
embedding tables (a gather — cheap, HBM-friendly) instead of the reference's
one-hot matmul (`OneHotEncoder`, `01-train-model.ipynb:204-209`); the trunk is
dense matmuls in bfloat16 so XLA tiles them onto the MXU and fuses the
elementwise tail (GELU, LayerNorm, residual) into the matmul epilogue.
Params stay float32; only compute is bf16.
"""

from __future__ import annotations

from typing import Sequence

import jax.numpy as jnp
from flax import linen as nn


class CategoricalEmbed(nn.Module):
    """Per-feature embedding tables, concatenated.

    One table per categorical feature (cardinalities from the schema, each
    including its OOV bucket — parity with ``handle_unknown="ignore"``).
    """

    cards: Sequence[int]
    embed_dim: int
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, cat_ids: jnp.ndarray) -> jnp.ndarray:  # [N, C] -> [N, C*E]
        pieces = []
        for j, card in enumerate(self.cards):
            table = nn.Embed(
                num_embeddings=card,
                features=self.embed_dim,
                dtype=self.dtype,
                name=f"embed_{j}",
            )
            pieces.append(table(cat_ids[:, j]))
        return jnp.concatenate(pieces, axis=-1)


class LinearModel(nn.Module):
    """Logistic regression with categorical embeddings (scalar embeds).

    The quality floor / sanity baseline — replaces nothing in the reference
    directly but anchors the metric table like its per-trial weak learners.
    """

    cards: Sequence[int]
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(
        self, cat_ids: jnp.ndarray, numeric: jnp.ndarray, *, train: bool = False
    ) -> jnp.ndarray:
        cat = CategoricalEmbed(self.cards, embed_dim=1, dtype=self.dtype)(cat_ids)
        features = jnp.concatenate([cat, numeric.astype(self.dtype)], axis=-1)
        logit = nn.Dense(1, dtype=self.dtype, name="head")(features)
        return logit[:, 0].astype(jnp.float32)


class MLP(nn.Module):
    """Residual MLP over embedded categoricals + standardized numerics.

    Flagship serving model (BASELINE.json config 2). Width/depth from config;
    pre-LN residual blocks keep optimization stable at the depths HPO
    explores.
    """

    cards: Sequence[int]
    embed_dim: int = 16
    hidden_dims: tuple[int, ...] = (256, 256, 128)
    dropout: float = 0.1
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(
        self, cat_ids: jnp.ndarray, numeric: jnp.ndarray, *, train: bool = False
    ) -> jnp.ndarray:
        cat = CategoricalEmbed(self.cards, self.embed_dim, dtype=self.dtype)(cat_ids)
        x = jnp.concatenate([cat, numeric.astype(self.dtype)], axis=-1)
        x = nn.Dense(self.hidden_dims[0], dtype=self.dtype, name="stem")(x)
        for i, width in enumerate(self.hidden_dims):
            h = nn.LayerNorm(dtype=self.dtype, name=f"ln_{i}")(x)
            h = nn.Dense(width, dtype=self.dtype, name=f"dense_{i}a")(h)
            h = nn.gelu(h)
            h = nn.Dropout(self.dropout, deterministic=not train)(h)
            h = nn.Dense(self.hidden_dims[0], dtype=self.dtype, name=f"dense_{i}b")(h)
            x = x + h
        x = nn.LayerNorm(dtype=self.dtype, name="ln_out")(x)
        logit = nn.Dense(1, dtype=self.dtype, name="head")(x)
        return logit[:, 0].astype(jnp.float32)
