"""Vmapped deep ensemble — K members, one compiled program.

The reference's quality model is a RandomForest — itself an ensemble of
trees, which is why it is a strong tabular baseline
(`01-train-model.ipynb:195-227`). The TPU-native counter is a deep
ensemble of the Flax families: K independently-initialized members train
simultaneously under one ``nn.vmap`` (the member axis becomes a leading
batch dimension on every parameter — XLA turns the K small matmuls into
one batched matmul on the MXU, so the marginal cost of K=8 members at
these widths is near zero), and serving averages the K predicted
probabilities. Diversity comes from split init and dropout rngs per
member, matching how forest variance reduction comes from per-tree
randomness.

Calling convention is the zoo's standard one (``models/__init__.py``)
with one deliberate asymmetry:

- ``train=True``  -> logits ``[K, N]`` — each member its own head, so the
  mean BCE over the array is the average of independent member losses and
  gradients never couple members (coupled training would collapse the
  variance the ensemble exists to reduce);
- ``train=False`` -> logits ``[N]`` — the logit of the mean member
  probability, keeping the trainer's eval, the fused predict path and the
  serving engine family-agnostic.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import linen as nn


class DeepEnsemble(nn.Module):
    """K-member ensemble of any zoo module, lifted with ``nn.vmap``."""

    member: nn.Module
    size: int

    @nn.compact
    def __call__(
        self, cat_ids: jnp.ndarray, numeric: jnp.ndarray, *, train: bool = False
    ) -> jnp.ndarray:
        def member_call(mdl: nn.Module, cat: jnp.ndarray, num: jnp.ndarray):
            return mdl(cat, num, train=train)

        vmapped = nn.vmap(
            member_call,
            in_axes=(None, None),  # every member sees the same minibatch
            out_axes=0,
            axis_size=self.size,
            # member axis leads every param; sown auxiliaries (e.g. MoE
            # load-balance losses) stack the same way
            variable_axes={"params": 0, "aux_losses": 0},
            split_rngs={"params": True, "dropout": True},  # the diversity
        )
        logits = vmapped(self.member, cat_ids, numeric)  # [K, N]
        if train:
            return logits
        probs = jnp.mean(jax.nn.sigmoid(logits.astype(jnp.float32)), axis=0)
        probs = jnp.clip(probs, 1e-7, 1.0 - 1e-7)
        return jnp.log(probs) - jnp.log1p(-probs)
