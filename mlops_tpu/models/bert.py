"""BERT-style tabular-as-text encoder (BASELINE.json config 5, the stretch).

The reference never goes near language models; this family exists because
the rebuild's baseline contract lists "BERT-base tabular-as-text fine-tune
(full TPU training loop, data-parallel on v5e-8)" as its stretch config.
Design is TPU-first rather than a port of any HF pipeline:

- **Tokenization is part of the jitted forward pass.** A record renders as
  the token sequence ``[CLS] name_1 value_1 ... name_23 value_23 [SEP]``
  (48 tokens, static shape). Categorical values map to per-feature vocab
  tokens by integer offset; numeric values (already standardized by the
  data pipeline) land in per-feature quantile-bin tokens via
  ``searchsorted`` over fixed standard-normal bin edges. No strings, no
  host-side tokenizer, no dynamic shapes — the "text" rendering is pure
  int32 arithmetic fused into the same XLA program as the encoder.
- **Same calling convention as every other family**
  (``apply(vars, cat_ids, numeric, train) -> logits[N]``), so the trainer,
  vmapped HPO, sharded train step, bundle format, and serving engine all
  work on BERT unchanged.
- Encoder blocks are the shared pre-LN ``TransformerBlock`` (GELU FFN at
  4x hidden, attention through ``ops.attention.attend`` which dispatches to
  the Pallas flash kernel at long sequence). Blocks are named ``block_i``
  and projections follow the zoo's naming, so the Megatron-style
  ``PARAM_RULES`` tensor-parallel layouts apply to BERT with zero new
  rules; DP x TP runs through ``parallel.make_sharded_train_step`` as-is.
- For sequence lengths beyond one record (multi-record documents), the
  sequence-parallel path is ``parallel.ring_attention`` — same online
  softmax, sharded over the 'seq' mesh axis.

``BERT_BASE`` is the true-scale preset (hidden 768, 12 layers, 12 heads,
FFN 3072, ~86M params + vocab). Tests and HPO use scaled-down instances.
"""

from __future__ import annotations

import dataclasses
from statistics import NormalDist
from typing import Sequence

import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from mlops_tpu.models.ft_transformer import TransformerBlock

PAD_ID, CLS_ID, SEP_ID, MASK_ID = 0, 1, 2, 3
_SPECIAL = 4


@dataclasses.dataclass(frozen=True)
class TokenLayout:
    """Static vocabulary layout derived from the feature schema.

    Token id space: ``[PAD][CLS][SEP][MASK]`` | one name token per feature |
    per-categorical-feature value blocks (card each, OOV included) |
    per-numeric-feature bin blocks (num_bins each).
    """

    cards: tuple[int, ...]
    num_numeric: int
    num_bins: int

    @property
    def num_features(self) -> int:
        return len(self.cards) + self.num_numeric

    @property
    def name_offset(self) -> int:
        return _SPECIAL

    @property
    def cat_offsets(self) -> tuple[int, ...]:
        base = _SPECIAL + self.num_features
        offsets = []
        for card in self.cards:
            offsets.append(base)
            base += card
        return tuple(offsets)

    @property
    def bin_offsets(self) -> tuple[int, ...]:
        base = _SPECIAL + self.num_features + sum(self.cards)
        return tuple(
            base + j * self.num_bins for j in range(self.num_numeric)
        )

    @property
    def vocab_size(self) -> int:
        return (
            _SPECIAL
            + self.num_features
            + sum(self.cards)
            + self.num_numeric * self.num_bins
        )

    @property
    def seq_len(self) -> int:
        # [CLS] + (name, value) per feature + [SEP]
        return 2 + 2 * self.num_features

    def bin_edges(self) -> np.ndarray:
        """Interior standard-normal quantile edges (num_bins - 1 of them).

        Numeric features arrive standardized (mean 0 / std 1 under the
        train distribution), so fixed N(0,1) quantiles give near-uniform
        bin occupancy without any data-dependent state in the model.
        """
        nd = NormalDist()
        qs = [i / self.num_bins for i in range(1, self.num_bins)]
        return np.asarray([nd.inv_cdf(q) for q in qs], np.float32)


def tokenize(
    cat_ids: jnp.ndarray, numeric: jnp.ndarray, layout: TokenLayout
) -> jnp.ndarray:
    """Render records as token ids: (int32[N,C], f32[N,M]) -> int32[N,S].

    Pure jnp integer math — traces into the encoder's XLA program.
    """
    n = cat_ids.shape[0]
    f = layout.num_features

    names = jnp.arange(
        layout.name_offset, layout.name_offset + f, dtype=jnp.int32
    )
    cat_tok = jnp.asarray(layout.cat_offsets, jnp.int32)[None, :] + cat_ids
    bins = jnp.searchsorted(
        jnp.asarray(layout.bin_edges()), numeric, side="right"
    ).astype(jnp.int32)
    num_tok = jnp.asarray(layout.bin_offsets, jnp.int32)[None, :] + bins

    values = jnp.concatenate([cat_tok, num_tok], axis=1)  # [N, F]
    pairs = jnp.stack(
        [jnp.broadcast_to(names[None, :], (n, f)), values], axis=2
    ).reshape(n, 2 * f)
    cls = jnp.full((n, 1), CLS_ID, jnp.int32)
    sep = jnp.full((n, 1), SEP_ID, jnp.int32)
    return jnp.concatenate([cls, pairs, sep], axis=1)


def apply_embed_front(
    mod: nn.Module,
    tokens: jnp.ndarray,
    vocab_size: int,
    seq_len: int,
    hidden: int,
    dtype: jnp.dtype,
) -> jnp.ndarray:
    """The shared embedding front: tok_embed + pos_embed → ln_embed.

    Called from inside a ``@nn.compact`` ``__call__`` (``mod`` is the owning
    module); submodule/param names are fixed here ONCE so every consumer —
    ``BertEncoder``, ``BertMaskedLM``, ``BertDocEncoder``, and the
    pipeline-parallel split (`train/pipeline_parallel.py`) — produces
    byte-compatible param trees.
    """
    x = nn.Embed(vocab_size, hidden, dtype=dtype, name="tok_embed")(tokens)
    pos = mod.param(
        "pos_embed", nn.initializers.normal(0.02), (seq_len, hidden)
    )
    x = x + pos.astype(dtype)[None]
    return nn.LayerNorm(dtype=dtype, name="ln_embed")(x)


def apply_cls_head(
    mod: nn.Module, x: jnp.ndarray, hidden: int, dtype: jnp.dtype
) -> jnp.ndarray:
    """The shared read-out: ln_final on [CLS] → tanh pooler → head logit."""
    cls = nn.LayerNorm(dtype=dtype, name="ln_final")(x[:, 0])
    pooled = nn.tanh(nn.Dense(hidden, dtype=dtype, name="pooler")(cls))
    logit = nn.Dense(1, dtype=dtype, name="head")(pooled)
    return logit[:, 0].astype(jnp.float32)


class BertEncoder(nn.Module):
    """Pre-LN BERT-style encoder over the tabular token rendering.

    ``apply(vars, cat_ids, numeric, train) -> logits[f32 N]`` — the zoo
    convention (`mlops_tpu.models`), classifier head reading [CLS].
    """

    cards: Sequence[int]
    num_numeric: int
    hidden: int = 768
    depth: int = 12
    heads: int = 12
    dropout: float = 0.1
    num_bins: int = 32
    dtype: jnp.dtype = jnp.bfloat16

    @property
    def layout(self) -> TokenLayout:
        return TokenLayout(tuple(self.cards), self.num_numeric, self.num_bins)

    @nn.compact
    def __call__(
        self, cat_ids: jnp.ndarray, numeric: jnp.ndarray, *, train: bool = False
    ) -> jnp.ndarray:
        layout = self.layout
        tokens = tokenize(cat_ids, numeric, layout)  # [N, S]
        x = apply_embed_front(
            self, tokens, layout.vocab_size, layout.seq_len, self.hidden, self.dtype
        )
        x = nn.Dropout(self.dropout, deterministic=not train)(x)

        for i in range(self.depth):
            x = TransformerBlock(
                heads=self.heads,
                token_dim=self.hidden,
                dropout=self.dropout,
                dtype=self.dtype,
                name=f"block_{i}",
            )(x, train=train)

        return apply_cls_head(self, x, self.hidden, self.dtype)


class BertMaskedLM(nn.Module):
    """Masked-feature pretraining head over the same encoder trunk.

    Tabular analogue of BERT's MLM objective: mask a fraction of VALUE
    tokens (never names/CLS/SEP) and predict the original token id from
    context — self-supervised pretraining on unlabeled rows, no target
    column needed. The trunk modules carry the same names as
    ``BertEncoder`` (tok_embed, pos_embed, ln_embed, block_i, ln_final),
    so pretrained params transfer into the classifier via
    ``transfer_encoder_params`` and fine-tuning proceeds with the standard
    trainer.
    """

    cards: Sequence[int]
    num_numeric: int
    hidden: int = 768
    depth: int = 12
    heads: int = 12
    dropout: float = 0.1
    num_bins: int = 32
    dtype: jnp.dtype = jnp.bfloat16

    @property
    def layout(self) -> TokenLayout:
        return TokenLayout(tuple(self.cards), self.num_numeric, self.num_bins)

    def value_positions(self) -> np.ndarray:
        """Sequence indices holding value tokens (maskable positions):
        every second slot after CLS — [2, 4, ..., 2F]."""
        f = self.layout.num_features
        return np.arange(2, 2 * f + 1, 2)

    @nn.compact
    def __call__(
        self,
        cat_ids: jnp.ndarray,
        numeric: jnp.ndarray,
        mask: jnp.ndarray,
        *,
        train: bool = True,
    ) -> tuple[jnp.ndarray, jnp.ndarray]:
        """mask: bool [N, S], True = replace with [MASK] and predict.

        Returns (logits [N, S, vocab], original token ids [N, S]).
        """
        layout = self.layout
        targets = tokenize(cat_ids, numeric, layout)
        tokens = jnp.where(mask, MASK_ID, targets)
        x = apply_embed_front(
            self, tokens, layout.vocab_size, layout.seq_len, self.hidden, self.dtype
        )
        x = nn.Dropout(self.dropout, deterministic=not train)(x)
        for i in range(self.depth):
            x = TransformerBlock(
                heads=self.heads,
                token_dim=self.hidden,
                dropout=self.dropout,
                dtype=self.dtype,
                name=f"block_{i}",
            )(x, train=train)
        x = nn.LayerNorm(dtype=self.dtype, name="ln_final")(x)
        logits = nn.Dense(layout.vocab_size, dtype=self.dtype, name="mlm_head")(x)
        return logits.astype(jnp.float32), targets


def tokenize_documents(
    cat_ids: jnp.ndarray, numeric: jnp.ndarray, layout: TokenLayout
) -> jnp.ndarray:
    """Render record HISTORIES as one token sequence:
    (int32[N,R,C], f32[N,R,M]) -> int32[N, 2 + 2*F*R].

    Layout: ``[CLS] rec_1 pairs ... rec_R pairs [SEP]`` — each record
    contributes its (name, value) pairs from ``tokenize`` (per-record
    CLS/SEP stripped). Long-context consumer: `train/long_context.py`.
    """
    n, r, c = cat_ids.shape
    flat = tokenize(
        cat_ids.reshape(n * r, c), numeric.reshape(n * r, -1), layout
    )  # [N*R, 2 + 2F]
    pairs = flat[:, 1:-1].reshape(n, r * 2 * layout.num_features)
    cls = jnp.full((n, 1), CLS_ID, jnp.int32)
    sep = jnp.full((n, 1), SEP_ID, jnp.int32)
    return jnp.concatenate([cls, pairs, sep], axis=1)


class BertDocEncoder(nn.Module):
    """Long-context BERT over record histories (documents).

    The tabular-as-text rendering makes ONE record a 48-token sentence;
    this model reads ``doc_records`` consecutive records as one document
    (seq = 2 + 46R: R=11 -> 508 tokens) and predicts the default of the
    LAST record from the whole history. Calling convention is 3-D:
    ``apply(vars, cat[N,R,C], numeric[N,R,M], train) -> logits[N]``.

    This is the model the sequence-parallel training path runs
    (`train/long_context.py`): ``attend_fn`` injects the ppermute ring
    (`parallel.make_ring_attention`) so the sequence axis shards over the
    mesh's 'seq' axis; ``attend_fn=None`` is the dense single-chip
    reference the tests compare against. Trunk module names match
    ``BertEncoder`` (tok_embed, pos_embed, ln_embed, block_i, ln_final,
    pooler, head) so TP ``PARAM_RULES`` and pretrained-trunk grafting
    apply unchanged.
    """

    cards: Sequence[int]
    num_numeric: int
    doc_records: int
    hidden: int = 256
    depth: int = 4
    heads: int = 8
    dropout: float = 0.0  # attention-weight dropout needs materialized
    # scores, which the ring path never forms — keep 0 for SP training
    num_bins: int = 32
    dtype: jnp.dtype = jnp.bfloat16
    attend_fn: "object" = None  # Callable | None; static module attribute

    @property
    def layout(self) -> TokenLayout:
        return TokenLayout(tuple(self.cards), self.num_numeric, self.num_bins)

    @property
    def doc_seq_len(self) -> int:
        return 2 + 2 * self.layout.num_features * self.doc_records

    @nn.compact
    def __call__(
        self, cat_ids: jnp.ndarray, numeric: jnp.ndarray, *, train: bool = False
    ) -> jnp.ndarray:
        layout = self.layout
        tokens = tokenize_documents(cat_ids, numeric, layout)  # [N, S]
        x = apply_embed_front(
            self, tokens, layout.vocab_size, self.doc_seq_len, self.hidden, self.dtype
        )
        x = nn.Dropout(self.dropout, deterministic=not train)(x)
        for i in range(self.depth):
            x = TransformerBlock(
                heads=self.heads,
                token_dim=self.hidden,
                dropout=self.dropout,
                dtype=self.dtype,
                attend_fn=self.attend_fn,
                name=f"block_{i}",
            )(x, train=train)
        return apply_cls_head(self, x, self.hidden, self.dtype)


def transfer_encoder_params(pretrained: dict, target: dict) -> dict:
    """Graft pretrained trunk params into a freshly-initialized classifier
    param tree (same-named subtrees copy; heads keep their fresh init)."""
    merged = dict(target)
    for key, value in pretrained.items():
        if key in merged and key != "mlm_head":
            merged[key] = value
    return merged


def bert_base_config():
    """ModelConfig preset at true BERT-base scale (v5e-8 data-parallel)."""
    from mlops_tpu.config import ModelConfig

    return ModelConfig(family="bert", token_dim=768, depth=12, heads=12)
