"""Model zoo — Flax replacements for the reference's sklearn pipeline.

The reference's only model family is
``SimpleImputer + OneHotEncoder + RandomForestClassifier``
(`01-train-model.ipynb:195-227`). Tree ensembles don't map onto the MXU, so
the TPU-native zoo is:

- ``linear``          embedding-sum logistic regression (fast floor)
- ``mlp``             embeddings + residual MLP (flagship for serving)
- ``ft_transformer``  feature-tokenized transformer (BASELINE.json config 3)
- ``bert``            tabular-as-text BERT encoder with jit-fused
  tokenization (BASELINE.json config 5, the stretch)

All families share one calling convention:
``model.apply(vars, cat_ids[int32 N,C], numeric[f32 N,M], train=...) ->
logits[f32 N]`` so the trainer, bundle, and server are family-agnostic.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from flax import linen as nn

from mlops_tpu.config import ModelConfig
from mlops_tpu.models.bert import BertEncoder
from mlops_tpu.models.ensemble import DeepEnsemble
from mlops_tpu.models.ft_transformer import FTTransformer
from mlops_tpu.models.mlp import MLP, LinearModel
from mlops_tpu.models.moe import MoETransformer
from mlops_tpu.schema.features import SCHEMA

FAMILIES = ("linear", "mlp", "ft_transformer", "moe", "bert")


def build_model(config: ModelConfig) -> nn.Module:
    """Instantiate a model family from config (embedding sizes from SCHEMA).

    ``ensemble_size > 1`` wraps the family in a vmapped deep ensemble
    (models/ensemble.py) — same calling convention, K× the params with a
    leading member axis.
    """
    if config.ensemble_size > 1:
        single = dataclasses.replace(config, ensemble_size=1)
        return DeepEnsemble(member=build_model(single), size=config.ensemble_size)
    dtype = {"bf16": jnp.bfloat16, "f32": jnp.float32}[config.precision]
    if config.family == "linear":
        return LinearModel(cards=SCHEMA.cards, dtype=dtype)
    if config.family == "mlp":
        return MLP(
            cards=SCHEMA.cards,
            embed_dim=config.embed_dim,
            hidden_dims=tuple(config.hidden_dims),
            dropout=config.dropout,
            dtype=dtype,
        )
    if config.family == "ft_transformer":
        return FTTransformer(
            cards=SCHEMA.cards,
            num_numeric=SCHEMA.num_numeric,
            token_dim=config.token_dim,
            depth=config.depth,
            heads=config.heads,
            dropout=config.dropout,
            dtype=dtype,
        )
    if config.family == "moe":
        return MoETransformer(
            cards=SCHEMA.cards,
            num_numeric=SCHEMA.num_numeric,
            token_dim=config.token_dim,
            depth=config.depth,
            heads=config.heads,
            num_experts=config.num_experts,
            dropout=config.dropout,
            dtype=dtype,
        )
    if config.family == "bert":
        return BertEncoder(
            cards=SCHEMA.cards,
            num_numeric=SCHEMA.num_numeric,
            hidden=config.token_dim,
            depth=config.depth,
            heads=config.heads,
            dropout=config.dropout,
            dtype=dtype,
        )
    from mlops_tpu.models.gbm import SKLEARN_FAMILIES

    if config.family in SKLEARN_FAMILIES:
        raise ValueError(
            f"family {config.family!r} is the CPU sklearn baseline (BASELINE "
            "config 1) — it has no Flax module; train it via `run_training` / "
            "the `train` CLI, which packages it as a sklearn-flavor bundle"
        )
    raise ValueError(f"unknown model family {config.family!r}; one of {FAMILIES}")


def init_params(model: nn.Module, rng: jax.Array, batch: int = 2):
    """Initialize variables with dummy fixed-shape inputs."""
    cat = jnp.zeros((batch, SCHEMA.num_categorical), jnp.int32)
    num = jnp.zeros((batch, SCHEMA.num_numeric), jnp.float32)
    return model.init({"params": rng}, cat, num, train=False)


def abstract_variables(model: nn.Module, batch: int = 2):
    """Variable SHAPES via ``jax.eval_shape`` — init never runs, no
    parameters materialize. The one definition shared by tpulint's Layer-2
    entry-point registry (`analysis/entrypoints.py`) and the compile-cache
    warmup (`compilecache/warmup.py`): both must derive identical abstract
    signatures or the analyzer and the cache disagree about the programs.
    """

    def init():
        return init_params(model, jax.random.PRNGKey(0), batch=batch)

    return jax.eval_shape(init)


__all__ = [
    "FAMILIES",
    "BertEncoder",
    "DeepEnsemble",
    "FTTransformer",
    "LinearModel",
    "MLP",
    "MoETransformer",
    "build_model",
    "init_params",
]
