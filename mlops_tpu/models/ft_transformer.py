"""FT-Transformer: feature-tokenized transformer for tabular data.

BASELINE.json config 3 ("FT-Transformer tabular model on credit-default").
Each of the 23 features becomes one token: categoricals via embedding lookup,
numerics via a learned per-feature direction scaled by the standardized
value. A CLS token aggregates; pre-LN transformer blocks; the head reads CLS.

TPU notes: sequence length is 24 (23 features + CLS) — attention here is a
small batched matmul, ideal MXU shape when heads*head_dim is a multiple of
128; everything is bf16 compute / f32 params; no dynamic shapes anywhere.
The attention inner loop is also the framework's first Pallas candidate
(``mlops_tpu.ops.attention``) though at seq=24 XLA's fused attention is
already near-roofline.
"""

from __future__ import annotations

from typing import Callable, Sequence

import jax.numpy as jnp
from flax import linen as nn

from mlops_tpu.models.layers import MultiHeadSelfAttention


class FeatureTokenizer(nn.Module):
    """Map (cat_ids, numeric) -> token sequence [N, F+1, D] with CLS first."""

    cards: Sequence[int]
    num_numeric: int
    token_dim: int
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, cat_ids: jnp.ndarray, numeric: jnp.ndarray) -> jnp.ndarray:
        n = cat_ids.shape[0]
        # Categorical tokens: one embedding table per feature, stacked.
        cat_tokens = []
        for j, card in enumerate(self.cards):
            table = nn.Embed(card, self.token_dim, dtype=self.dtype, name=f"cat_{j}")
            cat_tokens.append(table(cat_ids[:, j]))
        cat_tok = jnp.stack(cat_tokens, axis=1)  # [N, C, D]

        # Numeric tokens: value * learned direction + per-feature bias.
        weight = self.param(
            "num_weight",
            nn.initializers.normal(0.02),
            (self.num_numeric, self.token_dim),
        )
        bias = self.param(
            "num_bias",
            nn.initializers.zeros_init(),
            (self.num_numeric, self.token_dim),
        )
        num_tok = (
            numeric[:, :, None].astype(self.dtype) * weight.astype(self.dtype)
            + bias.astype(self.dtype)
        )  # [N, M, D]

        cls = self.param(
            "cls", nn.initializers.normal(0.02), (1, 1, self.token_dim)
        )
        cls_tok = jnp.broadcast_to(cls.astype(self.dtype), (n, 1, self.token_dim))
        return jnp.concatenate([cls_tok, cat_tok, num_tok], axis=1)


class TransformerBlock(nn.Module):
    """Pre-LN block: MHA + GELU MLP, residual, dropout.

    ``attend_fn`` (optional) overrides the attention kernel — the
    sequence-parallel BERT path injects the shard_map'd ring
    (`parallel.make_ring_attention`) through here.
    """

    heads: int
    token_dim: int
    dropout: float
    dtype: jnp.dtype = jnp.bfloat16
    attend_fn: Callable | None = None

    @nn.compact
    def __call__(self, x: jnp.ndarray, *, train: bool) -> jnp.ndarray:
        h = nn.LayerNorm(dtype=self.dtype)(x)
        h = MultiHeadSelfAttention(
            heads=self.heads,
            dtype=self.dtype,
            dropout=self.dropout,
            attend_fn=self.attend_fn,
        )(h, deterministic=not train)
        x = x + nn.Dropout(self.dropout, deterministic=not train)(h)

        h = nn.LayerNorm(dtype=self.dtype)(x)
        # MLP on [N*S, D]: same params/numerics, but the backward's dW is
        # a single 2D GEMM instead of a two-contracting-dims dot_general
        # XLA:CPU can't run fast (see MultiHeadSelfAttention's note).
        n, s, d = h.shape
        h = h.reshape(n * s, d)
        h = nn.Dense(4 * self.token_dim, dtype=self.dtype)(h)
        h = nn.gelu(h)
        h = nn.Dropout(self.dropout, deterministic=not train)(h)
        h = nn.Dense(self.token_dim, dtype=self.dtype)(h)
        return x + h.reshape(n, s, d)


def apply_ft_head(mod: nn.Module, x: jnp.ndarray, dtype: jnp.dtype) -> jnp.ndarray:
    """The FT read-out (ln_final on CLS → head logit), factored so the
    pipeline-parallel split (`train/pipeline_parallel.py`) produces a
    byte-compatible param tree."""
    cls = nn.LayerNorm(dtype=dtype, name="ln_final")(x[:, 0])
    logit = nn.Dense(1, dtype=dtype, name="head")(cls)
    return logit[:, 0].astype(jnp.float32)


class FTTransformer(nn.Module):
    cards: Sequence[int]
    num_numeric: int
    token_dim: int = 64
    depth: int = 3
    heads: int = 8
    dropout: float = 0.1
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(
        self, cat_ids: jnp.ndarray, numeric: jnp.ndarray, *, train: bool = False
    ) -> jnp.ndarray:
        # Name pinned explicitly: it is a cross-file contract — the
        # pipeline-parallel split slices the dense tree by this key
        # (`train/pipeline_parallel.py` _FAMILY_SPLITS).
        tokens = FeatureTokenizer(
            self.cards,
            self.num_numeric,
            self.token_dim,
            dtype=self.dtype,
            name="FeatureTokenizer_0",
        )(cat_ids, numeric)
        for i in range(self.depth):
            tokens = TransformerBlock(
                heads=self.heads,
                token_dim=self.token_dim,
                dropout=self.dropout,
                dtype=self.dtype,
                name=f"block_{i}",
            )(tokens, train=train)
        return apply_ft_head(self, tokens, self.dtype)
