"""Shared Flax layers: self-attention over the framework's kernel dispatcher.

``MultiHeadSelfAttention`` replaces ``nn.MultiHeadDotProductAttention`` so
every transformer in the zoo (FT-Transformer, BERT) runs inference through
``mlops_tpu.ops.attention.attend`` — dense XLA fusion at short sequence,
the Pallas flash kernel at BERT-length sequence. Attention-weight dropout
requires the materialized score matrix, so training with dropout uses the
dense path; eval/serving always goes through the dispatcher.
"""

from __future__ import annotations

import math
from typing import Callable

import jax
import jax.numpy as jnp
from flax import linen as nn

from mlops_tpu.ops.attention import attend, reference_attention


class MultiHeadSelfAttention(nn.Module):
    heads: int
    dtype: jnp.dtype = jnp.bfloat16
    dropout: float = 0.0
    use_flash: bool | None = None  # None = dispatch on sequence length
    attend_fn: Callable | None = None  # override the kernel dispatcher —
    # the sequence-parallel path injects `parallel.make_ring_attention`'s
    # shard_map'd ring here so the SAME module runs dense on one chip and
    # ring-sharded over a ('data','seq') mesh. Incompatible with padding
    # masks and attention-weight dropout (both need the materialized score
    # matrix); those combinations raise rather than silently fall back.

    @nn.compact
    def __call__(
        self,
        x: jnp.ndarray,
        *,
        deterministic: bool = True,
        mask: jnp.ndarray | None = None,
    ) -> jnp.ndarray:
        n, s, dim = x.shape
        if dim % self.heads:
            raise ValueError(f"dim {dim} not divisible by heads {self.heads}")
        head_dim = dim // self.heads

        # Projections run on [N*S, dim], not [N, S, dim]: the backward's
        # dW is then one clean 2D GEMM. On a 3D input it is a
        # two-contracting-dims dot_general that XLA:CPU cannot map to its
        # fast GEMM (measured 2x slower fwd+bwd on the bench host); on
        # TPU the reshape is layout-free. Params and numerics unchanged.
        qkv = nn.DenseGeneral(
            (3, self.heads, head_dim), dtype=self.dtype, name="qkv"
        )(x.reshape(n * s, dim)).reshape(n, s, 3, self.heads, head_dim)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]

        needs_weight_dropout = self.dropout > 0.0 and not deterministic
        if self.attend_fn is not None:
            if mask is not None or needs_weight_dropout:
                raise ValueError(
                    "attend_fn (ring attention) cannot combine with padding "
                    "masks or attention-weight dropout — both require the "
                    "materialized score matrix; train with dropout=0.0 on "
                    "the sequence-parallel path"
                )
            out = self.attend_fn(q, k, v)
        elif mask is not None or needs_weight_dropout:
            # Dense path: padding masks and attention-weight dropout need the
            # materialized [B,H,S,S] scores (training-time only for dropout).
            scale = 1.0 / math.sqrt(head_dim)
            scores = (
                jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
            )
            if mask is not None:  # mask: [N, S] True = attend
                scores = jnp.where(mask[:, None, None, :], scores, -1e30)
            probs = jax.nn.softmax(scores, axis=-1)
            if needs_weight_dropout:
                probs = nn.Dropout(self.dropout, deterministic=False)(probs)
            out = jnp.einsum("bhqk,bkhd->bqhd", probs.astype(v.dtype), v)
        else:
            out = attend(q, k, v, use_flash=self.use_flash)

        return nn.DenseGeneral(
            dim, axis=(-2, -1), dtype=self.dtype, name="out"
        )(out.reshape(n * s, self.heads, head_dim)).reshape(n, s, dim)


__all__ = ["MultiHeadSelfAttention", "attend", "reference_attention"]
