"""Mixture-of-experts transformer — expert parallelism for the zoo.

The reference has no parallelism of any kind (SURVEY.md SS2.7); DP/TP/SP
already exist here (`parallel/`), and this family adds the remaining
axis: **expert parallelism**. The FFN of every transformer block becomes
a top-2 gated mixture of experts whose stacked weights ``[E, D, F]``
shard their leading expert axis over the mesh's 'model' axis
(PARAM_RULES in `parallel/sharding.py`), so each device holds E/ep
experts and XLA inserts the cross-expert collectives.

TPU-first design choice — **dense dispatch**: every expert runs on every
token via two einsums (``nsd,edf->nsef`` then ``nsef,efd->nsed``) and
the gate weights zero out non-selected experts at combine time. At this
scale (seq 24, few experts) the E× FLOPs are far cheaper than the
gather/scatter of a sparse dispatch — the einsums stay static-shape
batched matmuls on the MXU, which is exactly what a Switch/GShard
capacity-buffer formulation degenerates to when tokens-per-expert is
tiny. Routing runs in float32 (softmax over expert logits is
precision-sensitive); compute stays bf16.

Load balancing: the standard Switch auxiliary loss
``E * sum(importance . load)`` is sown into the ``aux_losses``
collection, scaled by ``aux_weight``; the trainers pick up every sown
auxiliary through ``training_loss`` (`train/loop.py`) without knowing
MoE exists.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
from flax import linen as nn

from mlops_tpu.models.ft_transformer import FeatureTokenizer
from mlops_tpu.models.layers import MultiHeadSelfAttention


class MoEFeedForward(nn.Module):
    """Top-2 gated expert FFN with dense (all-matmul) dispatch."""

    num_experts: int
    token_dim: int
    hidden_mult: int = 4
    top_k: int = 2
    aux_weight: float = 0.01
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jnp.ndarray, *, train: bool) -> jnp.ndarray:  # [N,S,D]
        e, d = self.num_experts, self.token_dim
        f = self.hidden_mult * d
        k = min(self.top_k, e)

        # Router in f32: softmax over expert logits is precision-sensitive.
        gate_logits = nn.Dense(e, dtype=jnp.float32, name="router")(
            x.astype(jnp.float32)
        )  # [N,S,E]
        gates = jax.nn.softmax(gate_logits, axis=-1)
        _, top_idx = jax.lax.top_k(gates, k)
        mask = jax.nn.one_hot(top_idx, e, dtype=gates.dtype).sum(-2)  # [N,S,E]
        weights = gates * mask
        weights = weights / (weights.sum(-1, keepdims=True) + 1e-9)

        if train:
            # Switch load-balance loss: E * importance . load — minimized
            # by uniform routing; scaled here so trainers stay MoE-blind.
            importance = gates.mean(axis=(0, 1))  # [E] mean router prob
            load = (mask / k).mean(axis=(0, 1))  # [E] fraction routed
            aux = e * jnp.sum(importance * load)
            self.sow("aux_losses", "moe_load_balance", self.aux_weight * aux)

        w_in = self.param(
            "experts_in", nn.initializers.normal(0.02), (e, d, f)
        )
        b_in = self.param("experts_in_bias", nn.initializers.zeros_init(), (e, f))
        w_out = self.param(
            "experts_out", nn.initializers.normal(0.02), (e, f, d)
        )
        b_out = self.param("experts_out_bias", nn.initializers.zeros_init(), (e, d))

        # Expert einsums run on [N*S, ...] tokens: the backward's dW then
        # has ONE contracting dim (tokens) per expert instead of the
        # two-contracting-dims dot_general XLA:CPU can't map to a fast
        # GEMM (same fix as the shared attention/MLP layers). Params and
        # numerics unchanged — pure reshape.
        n, s, _ = x.shape
        xb = x.astype(self.dtype).reshape(n * s, d)
        h = (
            jnp.einsum("td,edf->tef", xb, w_in.astype(self.dtype))
            + b_in.astype(self.dtype)[None]
        )
        h = nn.gelu(h)
        y = (
            jnp.einsum("tef,efd->ted", h, w_out.astype(self.dtype))
            + b_out.astype(self.dtype)[None]
        )
        out = jnp.einsum(
            "te,ted->td", weights.astype(self.dtype).reshape(n * s, e), y
        )
        return out.reshape(n, s, d)


class MoEBlock(nn.Module):
    """Pre-LN block: MHA + MoE FFN, residual, dropout."""

    heads: int
    token_dim: int
    num_experts: int
    dropout: float
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jnp.ndarray, *, train: bool) -> jnp.ndarray:
        h = nn.LayerNorm(dtype=self.dtype)(x)
        h = MultiHeadSelfAttention(
            heads=self.heads, dtype=self.dtype, dropout=self.dropout
        )(h, deterministic=not train)
        x = x + nn.Dropout(self.dropout, deterministic=not train)(h)

        h = nn.LayerNorm(dtype=self.dtype)(x)
        h = MoEFeedForward(
            num_experts=self.num_experts,
            token_dim=self.token_dim,
            dtype=self.dtype,
        )(h, train=train)
        h = nn.Dropout(self.dropout, deterministic=not train)(h)
        return x + h


class MoETransformer(nn.Module):
    """FT-Transformer body with mixture-of-experts FFNs (family "moe")."""

    cards: Sequence[int]
    num_numeric: int
    token_dim: int = 64
    depth: int = 3
    heads: int = 8
    num_experts: int = 8
    dropout: float = 0.1
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(
        self, cat_ids: jnp.ndarray, numeric: jnp.ndarray, *, train: bool = False
    ) -> jnp.ndarray:
        tokens = FeatureTokenizer(
            self.cards, self.num_numeric, self.token_dim, dtype=self.dtype
        )(cat_ids, numeric)
        for i in range(self.depth):
            tokens = MoEBlock(
                heads=self.heads,
                token_dim=self.token_dim,
                num_experts=self.num_experts,
                dropout=self.dropout,
                dtype=self.dtype,
                name=f"block_{i}",
            )(tokens, train=train)
        cls = nn.LayerNorm(dtype=self.dtype, name="ln_final")(tokens[:, 0])
        logit = nn.Dense(1, dtype=self.dtype, name="head")(cls)
        return logit[:, 0].astype(jnp.float32)
