"""CPU tree-ensemble baseline — BASELINE.json config 1, the comparison floor.

The reference's production model is an sklearn ``RandomForestClassifier``
behind a ``ColumnTransformer`` (`01-train-model.ipynb:195-227`). Tree
ensembles don't map onto the MXU, so they are NOT the TPU path — they are the
shipped CPU fallback and the quality floor every Flax family is measured
against (SURVEY.md §7 "hard parts": RF is a strong tabular baseline).

Two families, both servable through the exact same bundle + engine interface
as the Flax models (flavor="sklearn" in the bundle manifest):

- ``gbm`` — ``HistGradientBoostingClassifier`` with native categorical
  support (the stronger, faster floor; BASELINE config 1 names gradient
  boosting).
- ``rf``  — ``RandomForestClassifier``, the reference's stock family, for
  exact parity comparisons (n_estimators/max_depth match the reference's
  hyperopt search space, `01-train-model.ipynb:342-353`).

Input convention matches the Flax zoo: ``(cat_ids[int32 N,C],
numeric[f32 N,M])`` from the shared ``Preprocessor`` — integer category ids
are consumed natively by HistGBM (``categorical_features``) and ordinally by
RF (the reference one-hots instead; ordinal trees split the same partitions
at equal depth).
"""

from __future__ import annotations

import io
from pathlib import Path
from typing import Any

import numpy as np

from mlops_tpu.config import ModelConfig, TrainConfig
from mlops_tpu.data.encode import EncodedDataset
from mlops_tpu.schema.features import SCHEMA

SKLEARN_FAMILIES = ("gbm", "rf")


class SklearnBaseline:
    """Fitted tree-ensemble wrapper with the zoo's predict convention."""

    def __init__(self, estimator: Any, family: str):
        self.estimator = estimator
        self.family = family

    # ------------------------------------------------------------------ fit
    @classmethod
    def train(
        cls,
        model_config: ModelConfig,
        train_config: TrainConfig,
        train_ds: EncodedDataset,
    ) -> "SklearnBaseline":
        X = _design_matrix(train_ds)
        y = np.asarray(train_ds.labels)
        family = model_config.family
        if family == "gbm":
            from sklearn.ensemble import HistGradientBoostingClassifier

            est = HistGradientBoostingClassifier(
                max_iter=model_config.n_estimators,
                max_depth=model_config.max_tree_depth or None,
                categorical_features=list(range(SCHEMA.num_categorical)),
                random_state=train_config.seed,
            )
        elif family == "rf":
            from sklearn.ensemble import RandomForestClassifier

            est = RandomForestClassifier(
                n_estimators=model_config.n_estimators,
                max_depth=model_config.max_tree_depth or None,
                n_jobs=-1,
                random_state=train_config.seed,
            )
        else:
            raise ValueError(
                f"unknown sklearn family {family!r}; one of {SKLEARN_FAMILIES}"
            )
        est.fit(X, y)
        return cls(est, family)

    # -------------------------------------------------------------- predict
    def predict_proba(
        self, cat_ids: np.ndarray, numeric: np.ndarray
    ) -> np.ndarray:
        """P(default) per row — same contract as sigmoid(logits) in the zoo."""
        X = _design_matrix_arrays(cat_ids, numeric)
        return self.estimator.predict_proba(X)[:, 1].astype(np.float32)

    def evaluate(self, ds: EncodedDataset) -> dict[str, float]:
        """Reference-named validation metrics (`01-train-model.ipynb:296-304`)."""
        import jax.numpy as jnp

        from mlops_tpu.train.metrics import binary_metrics

        probs = self.predict_proba(ds.cat_ids, ds.numeric)
        # binary_metrics takes raw logits; invert the sigmoid on clipped probs.
        p = np.clip(probs, 1e-7, 1.0 - 1e-7)
        logits = jnp.asarray(np.log(p / (1.0 - p)))
        metrics = binary_metrics(logits, jnp.asarray(ds.labels))
        return {f"validation_{k}_score": float(v) for k, v in metrics.items()}

    # ------------------------------------------------------------ serialize
    def to_bytes(self) -> bytes:
        import joblib

        buf = io.BytesIO()
        joblib.dump({"family": self.family, "estimator": self.estimator}, buf)
        return buf.getvalue()

    @classmethod
    def from_bytes(cls, data: bytes) -> "SklearnBaseline":
        import joblib

        payload = joblib.load(io.BytesIO(data))
        return cls(payload["estimator"], payload["family"])

    def save(self, path: str | Path) -> None:
        Path(path).write_bytes(self.to_bytes())

    @classmethod
    def load(cls, path: str | Path) -> "SklearnBaseline":
        return cls.from_bytes(Path(path).read_bytes())


def _design_matrix_arrays(cat_ids: np.ndarray, numeric: np.ndarray) -> np.ndarray:
    """[cat_ids | numeric] as float64 — one matrix layout, fit AND predict."""
    return np.concatenate(
        [np.asarray(cat_ids, np.float64), np.asarray(numeric, np.float64)],
        axis=1,
    )


def _design_matrix(ds: EncodedDataset) -> np.ndarray:
    return _design_matrix_arrays(ds.cat_ids, ds.numeric)
