"""Grid search: the bucket set that maximizes goodput on observed traffic.

jax-free. Given the fitted cost model (autotune/costmodel.py) and the
observed requested-rows demand, pick the bucket set that minimizes total
predicted device time — equivalently maximizes predicted
``useful_rows_per_s`` (ML-fleet goodput accounting: useful rows over
device seconds, padding is pure waste).

The search is EXACT, not heuristic: for a fixed affine cost model the
optimal bucket set's members always coincide with observed demand sizes
(lowering any bucket to the largest demand size it serves never raises
any dispatch's cost), so the space collapses to "choose <= max_entries
boundaries among the distinct observed sizes" — a classic O(k·n²)
dynamic program over sorted sizes, exact in milliseconds at telemetry
cardinalities (the occupancy table bounds n).

Constraints honored here, not re-litigated:
- the plan never shrinks shape coverage: the live ``max_bucket`` stays
  in every candidate set (the admission ceiling engines/front ends
  clamped against at start — `InferenceEngine.swap_bundle` enforces the
  same floor);
- ``max_entries`` is the compile budget (each solo bucket is one AOT
  compile at warm time);
- group geometries / ``pipeline_depth`` / batch windows ride the plan
  as ADVISORY fields only (`ServeConfig.validate` stays the arbiter for
  anything an operator applies by restart; the hot path applies bucket
  sets only).
"""

from __future__ import annotations

import dataclasses

from mlops_tpu.autotune.costmodel import CostModel

PLAN_FORMAT = 1  # plan.json schema version (replica adoption contract)


@dataclasses.dataclass(frozen=True)
class GridPlan:
    """One searched grid: the warmup plan plus its predicted economics
    (the predicted-vs-measured audit's "predicted" half)."""

    buckets: tuple[int, ...]
    baseline_buckets: tuple[int, ...]
    predicted_rows_per_s: float  # useful rows per device-second, new grid
    baseline_rows_per_s: float  # ... on the baseline (live) grid
    predicted_gain_pct: float
    predicted_waste_pct: float
    baseline_waste_pct: float
    demand_dispatches: float  # total dispatch weight the search saw
    cost_model: dict  # CostModel.as_dict()

    def as_dict(self) -> dict:
        doc = dataclasses.asdict(self)
        doc["buckets"] = list(self.buckets)
        doc["baseline_buckets"] = list(self.baseline_buckets)
        doc["format"] = PLAN_FORMAT
        return doc

    @staticmethod
    def from_dict(doc: dict) -> "GridPlan":
        return GridPlan(
            buckets=tuple(int(b) for b in doc["buckets"]),
            baseline_buckets=tuple(
                int(b) for b in doc.get("baseline_buckets", ())
            ),
            predicted_rows_per_s=float(doc["predicted_rows_per_s"]),
            baseline_rows_per_s=float(doc["baseline_rows_per_s"]),
            predicted_gain_pct=float(doc["predicted_gain_pct"]),
            predicted_waste_pct=float(doc["predicted_waste_pct"]),
            baseline_waste_pct=float(doc["baseline_waste_pct"]),
            demand_dispatches=float(doc.get("demand_dispatches", 0.0)),
            cost_model=dict(doc.get("cost_model", {})),
        )


def score_grid(
    buckets: tuple[int, ...],
    demand: list[tuple[int, float]],
    model: CostModel,
) -> tuple[float, float]:
    """Predicted ``(useful_rows_per_s, padding_waste_pct)`` of serving
    the demand through ``buckets``. Demand above the largest bucket pads
    to it (the engine's degraded/novel path would compile exactly that
    shape; the search keeps the ceiling covering observed max, so this
    only triggers on stale inputs)."""
    top = buckets[-1]
    useful = device_s = padded_total = 0.0
    for rows, weight in demand:
        padded = next((b for b in buckets if b >= rows), top)
        useful += rows * weight
        device_s += model.dispatch_s(padded) * weight
        padded_total += padded * weight
    if device_s <= 0 or padded_total <= 0:
        return 0.0, 0.0
    waste = 100.0 * (padded_total - useful) / padded_total
    return useful / device_s, waste


def _optimal_buckets(
    sizes: list[int],
    weights: list[float],
    max_entries: int,
    model: CostModel,
) -> tuple[int, ...]:
    """The DP: choose <= max_entries boundaries among sorted ``sizes``
    (the last is mandatory — it is the coverage ceiling) minimizing
    total affine cost. ``f[k][j]`` = min cost of covering sizes[0..j]
    with k chosen buckets, the k-th at sizes[j]."""
    n = len(sizes)
    k_max = min(max_entries, n)
    # prefix[j] = total weight of sizes[0..j-1]
    prefix = [0.0]
    for w in weights:
        prefix.append(prefix[-1] + w)

    def seg_cost(i: int, j: int) -> float:
        # sizes[i..j] all dispatch through a bucket at sizes[j]
        return (prefix[j + 1] - prefix[i]) * model.dispatch_s(sizes[j])

    INF = float("inf")
    # Exactly-k formulation: f[k][j] defined for j >= k-1; more buckets
    # never hurt under an affine model, but a strictly-best smaller k
    # can win when extra boundaries buy nothing — the final min over k
    # keeps the plan (and its compile bill) minimal.
    f = [[INF] * n for _ in range(k_max + 1)]
    back = [[-1] * n for _ in range(k_max + 1)]
    for j in range(n):
        f[1][j] = seg_cost(0, j)
    for k in range(2, k_max + 1):
        for j in range(k - 1, n):
            best, arg = INF, -1
            for i in range(k - 2, j):
                cand = f[k - 1][i] + seg_cost(i + 1, j)
                if cand < best:
                    best, arg = cand, i
            f[k][j] = best
            back[k][j] = arg
    # Best k ending at the mandatory ceiling sizes[n-1].
    best_k = min(range(1, k_max + 1), key=lambda k: f[k][n - 1])
    chosen = []
    j, k = n - 1, best_k
    while k > 1:
        chosen.append(sizes[j])
        j, k = back[k][j], k - 1
    chosen.append(sizes[j])
    return tuple(sorted(set(chosen)))


def search_plan(
    demand: list[tuple[int, float]],
    model: CostModel,
    current_buckets: tuple[int, ...],
    max_entries: int,
) -> GridPlan:
    """Search the grid for the given demand and return the winner as a
    plan (rejection thresholds are the CALLER's policy — controller/CLI
    apply ``min_gain_pct``; this stays a pure function of telemetry)."""
    current = tuple(sorted(current_buckets))
    ceiling = current[-1]
    sizes = sorted({min(r, ceiling) for r, _ in demand} | {ceiling})
    weights_by_size = {s: 0.0 for s in sizes}
    for rows, weight in demand:
        weights_by_size[min(rows, ceiling)] += weight
    weights = [weights_by_size[s] for s in sizes]
    best = _optimal_buckets(sizes, weights, max_entries, model)
    predicted, pred_waste = score_grid(best, demand, model)
    baseline, base_waste = score_grid(current, demand, model)
    gain = (
        100.0 * (predicted - baseline) / baseline if baseline > 0 else 0.0
    )
    return GridPlan(
        buckets=best,
        baseline_buckets=current,
        predicted_rows_per_s=predicted,
        baseline_rows_per_s=baseline,
        predicted_gain_pct=gain,
        predicted_waste_pct=pred_waste,
        baseline_waste_pct=base_waste,
        demand_dispatches=sum(w for _, w in demand),
        cost_model=model.as_dict(),
    )
