"""gridtuner — the traffic-shape autotuner (ROADMAP item 2).

The closed loop that re-grids the serving plane from its own telemetry:

- `costmodel.py` (jax-free) fits a measured per-entry dispatch cost
  model from the device-time cost ledger (slo/ledger.py);
- `search.py` (jax-free) searches candidate bucket grids against the
  observed requested-rows histogram (trace/shapes.py) and emits the
  winner as a warmup **plan**;
- `apply.py` pre-compiles the plan OFF the request path through the AOT
  cache warmers (compilecache/warmup.py) and hot-applies it through the
  lifecycle controller's bit-stable ``swap_bundle`` machinery — a regrid
  is a promotion whose candidate differs in exec table, not params.

Runs as the in-process `AutotuneController` (``autotune.enabled``) or
one-shot offline via ``mlops-tpu autotune`` (ledger + spans in, plan
out, `lifecycle`-style exit codes).
"""

from mlops_tpu.autotune.costmodel import (  # noqa: F401
    CostModel,
    demand_from_shapes,
    demand_from_spans,
    fit_cost_model,
    ledger_rows_from_snapshot,
)
from mlops_tpu.autotune.search import GridPlan, search_plan  # noqa: F401
from mlops_tpu.autotune.apply import (  # noqa: F401
    AutotuneController,
    apply_plan,
    warm_plan,
)
