"""Measured per-entry dispatch cost model — the gridtuner's physics.

jax-free by construction: the model is fit from the device-time cost
ledger (slo/ledger.py — per-entry ``device_s / dispatches / rows /
padded_rows``), optionally refined by span history, and consumed by the
grid search (autotune/search.py). Everything here is plain arithmetic
over telemetry the plane already exports.

The model is AFFINE in padded rows: ``dispatch_s(p) = a + b*p``. That
shape is the whole economics of bucketing — ``a`` is the fixed
per-dispatch overhead (kernel launch, host round trip, accumulator
chain) that punishes grids with too many tiny buckets, ``b`` is the
per-padded-row device cost that punishes grids that pad too much. Both
are FIT from ledger observations at the warmed bucket sizes (weighted
least squares, dispatch-count weights); with fewer than two distinct
observed sizes the fit degenerates and we fall back to a
measured-affine split of the one observed mean cost
(``MEASURED_OVERHEAD_FRACTION`` of it as overhead) — still anchored to
a measurement, never a guess about absolute speed.
"""

from __future__ import annotations

import dataclasses
import re

# Measured-affine fallback: with a single observed bucket size the
# overhead/slope split is unidentifiable, so treat this fraction of the
# observed mean dispatch cost as fixed overhead and amortize the rest
# per padded row. The absolute scale stays measured; only the split is
# assumed (and recorded in the plan via CostModel.mode for the audit).
MEASURED_OVERHEAD_FRACTION = 0.25

_BUCKET_ENTRY = re.compile(r"^bucket_(\d+)$")


@dataclasses.dataclass(frozen=True)
class CostModel:
    """``dispatch_s(padded_rows) = a_s + b_s * padded_rows``."""

    a_s: float  # fixed per-dispatch overhead, seconds
    b_s: float  # marginal cost per padded row, seconds
    points: int  # distinct bucket sizes the fit saw
    mode: str  # "affine-fit" | "measured-affine"

    def dispatch_s(self, padded_rows: float) -> float:
        return self.a_s + self.b_s * float(padded_rows)

    def as_dict(self) -> dict:
        return {
            "a_s": self.a_s,
            "b_s": self.b_s,
            "points": self.points,
            "mode": self.mode,
        }


def ledger_rows_from_snapshot(snapshot: dict) -> list[dict]:
    """Normalize a LIVE ledger snapshot (`CostLedger.snapshot()`:
    ``<entry>@<tag>`` -> [device_s, dispatches, rows, padded_rows]) into
    the same row dicts `slo.ledger.ledger_report` produces offline, so
    the fit consumes one shape from either plane. Model tags are folded
    away: the autotuner grids the PLANE, and the ledger keys only split
    tags so promotions don't cross-pollute history — here the union IS
    the observed traffic."""
    merged: dict[str, list[float]] = {}
    for key, vals in snapshot.items():
        entry = key.rsplit("@", 1)[0] if "@" in key else key
        acc = merged.setdefault(entry, [0.0, 0.0, 0.0, 0.0])
        for i in range(4):
            acc[i] += float(vals[i])
    return [
        {
            "entry": entry,
            "device_s": acc[0],
            "dispatches": acc[1],
            "rows": acc[2],
            "padded_rows": acc[3],
        }
        for entry, acc in sorted(merged.items())
    ]


def bucket_cost_points(
    ledger_rows: list[dict],
) -> list[tuple[int, float, float]]:
    """Per observed SOLO bucket size: ``(size, mean_dispatch_s,
    dispatch_weight)``. Group entries are excluded on purpose — the
    grouped path's geometry is the fixed module-constant grid
    (serve/wire.py), not part of the search space, and its fused
    multi-request dispatches would bias the solo overhead estimate."""
    points: list[tuple[int, float, float]] = []
    for row in ledger_rows:
        m = _BUCKET_ENTRY.match(str(row.get("entry", "")))
        if not m:
            continue
        dispatches = float(row.get("dispatches", 0.0))
        if dispatches <= 0:
            continue
        points.append(
            (
                int(m.group(1)),
                float(row.get("device_s", 0.0)) / dispatches,
                dispatches,
            )
        )
    points.sort()
    return points


def fit_cost_model(ledger_rows: list[dict]) -> CostModel | None:
    """Weighted least-squares affine fit over the observed bucket cost
    points; measured-affine fallback below two distinct sizes; None with
    no solo observations at all (the caller holds — no model, no plan)."""
    points = bucket_cost_points(ledger_rows)
    if not points:
        return None
    if len(points) == 1:
        size, cost, _w = points[0]
        a = cost * MEASURED_OVERHEAD_FRACTION
        return CostModel(
            a_s=a, b_s=(cost - a) / max(size, 1), points=1,
            mode="measured-affine",
        )
    sw = sum(w for _, _, w in points)
    sx = sum(s * w for s, _, w in points)
    sy = sum(c * w for _, c, w in points)
    sxx = sum(s * s * w for s, _, w in points)
    sxy = sum(s * c * w for s, c, w in points)
    det = sw * sxx - sx * sx
    if det <= 0:
        return None
    b = (sw * sxy - sx * sy) / det
    a = (sy - b * sx) / sw
    if b <= 0 or a < 0:
        # A noisy fit with non-physical coefficients (bigger buckets
        # measured cheaper, negative overhead) would make the search
        # prefer maximal padding — degrade to the measured-affine split
        # of the dispatch-weighted mean instead of optimizing noise.
        mean_cost = sy / sw
        mean_size = sx / sw
        a = mean_cost * MEASURED_OVERHEAD_FRACTION
        return CostModel(
            a_s=a, b_s=(mean_cost - a) / max(mean_size, 1.0),
            points=len(points), mode="measured-affine",
        )
    return CostModel(a_s=a, b_s=b, points=len(points), mode="affine-fit")


# Occupancy histogram edges — MUST mirror trace/shapes.OCCUPANCY_BUCKETS
# (imported lazily in demand_from_shapes to keep this module standalone
# for the offline CLI; the import asserts the mirror).


def demand_from_shapes(shape_entries: dict) -> list[tuple[int, float]]:
    """Reconstruct the requested-rows distribution from ShapeStats
    entries (``{entry: [dispatches, requested, padded, hist...]}``):
    weighted points ``(requested_rows, dispatches)``.

    Per solo entry ``bucket_B``, occupancy bin (lo, hi] holding ``n``
    dispatches contributes a point at ``B * (lo+hi)/2`` requested rows
    — then every entry's points are rescaled so their weighted sum
    matches the entry's EXACT requested-rows counter (the histogram
    bounds the granularity; the counters pin the mass). Group entries
    are excluded (fixed geometry, see bucket_cost_points)."""
    from mlops_tpu.trace.shapes import OCCUPANCY_BUCKETS

    edges = (0.0,) + tuple(OCCUPANCY_BUCKETS)
    demand: list[tuple[int, float]] = []
    for entry, vals in shape_entries.items():
        m = _BUCKET_ENTRY.match(str(entry))
        if not m:
            continue
        size = int(m.group(1))
        dispatches = float(vals[0])
        requested = float(vals[1])
        hist = [float(x) for x in vals[3:3 + len(OCCUPANCY_BUCKETS)]]
        if dispatches <= 0 or sum(hist) <= 0:
            continue
        points = []
        for i, count in enumerate(hist):
            if count <= 0:
                continue
            rep = size * (edges[i] + edges[i + 1]) / 2.0
            points.append([max(1, int(round(rep))), count])
        approx = sum(r * w for r, w in points)
        if approx > 0 and requested > 0:
            scale = requested / approx
            points = [
                [max(1, min(size, int(round(r * scale)))), w]
                for r, w in points
            ]
        demand.extend((r, w) for r, w in points)
    # Merge duplicate sizes across entries (keeps the search DP small).
    merged: dict[int, float] = {}
    for r, w in demand:
        merged[r] = merged.get(r, 0.0) + w
    return sorted(merged.items())


def demand_from_spans(spans: list[dict]) -> list[tuple[int, float]]:
    """Offline demand from span history (trace/report.load_spans):
    every solo-entry span's exact requested ``rows`` is one unit-weight
    point — finer-grained than the occupancy-histogram reconstruction,
    used by `mlops-tpu autotune` when span files are available."""
    merged: dict[int, float] = {}
    for span in spans:
        if not _BUCKET_ENTRY.match(str(span.get("entry", ""))):
            continue
        rows = int(span.get("rows", 0))
        if rows <= 0:
            continue
        merged[rows] = merged.get(rows, 0.0) + 1.0
    return sorted(merged.items())
