"""Hot regrid: warm the plan off-path, swap it in with ~0 ms downtime.

A regrid is a promotion whose candidate differs in EXEC TABLE, not
params (lifecycle/promote.py's machinery, reused bit-for-bit):

1. **Warm** (`warm_plan`): the plan's missing solo-bucket entries are
   AOT-compiled (or cache-deserialized) through compilecache/warmup.py
   jobs and installed into the LIVE engine's shared exec table under
   ``_compile_lock`` per write — exactly `InferenceEngine.warmup`'s
   discipline. A regrid never compiles under ``_acc_lock``, and a crash
   mid-warm leaves only harmless extra warmed entries behind.
2. **Twin** (`build_grid_twin`): an architecture twin of the live
   engine is built with the plan's bucket set and adopts the live exec
   table BY REFERENCE (`adopt_executables`) — no compile, no transfer
   of executables.
3. **Swap** (`apply_plan`): `swap_bundle(twin)` re-points the dispatch
   refs (including ``buckets``/``max_bucket``) under the existing
   ``_compile_lock`` -> ``_acc_lock`` order. Because the table is
   SHARED, a request racing the swap still hits every old entry — no
   hot-path compile is ever introduced; `rollback()` restores the old
   grid in one call.

`AutotuneController` runs the loop periodically off the request path
(the LifecycleController thread discipline): gather ShapeStats demand +
ledger costs, fit, search, gate on ``min_gain_pct``, apply (or dry-run
"planned"), and audit predicted-vs-measured gain from windowed ledger
deltas. On the ring plane the LEAD replica computes and persists the
plan (``plan_dir/plan.json``, atomic); sibling controllers run in
``adopt`` mode and apply the lead's plan locally, warming through the
SHARED compile cache (deserialize, not compile).
"""

from __future__ import annotations

import json
import logging
import threading
import time
from pathlib import Path
from typing import Any

from mlops_tpu import faults
from mlops_tpu.autotune.costmodel import (
    demand_from_shapes,
    fit_cost_model,
    ledger_rows_from_snapshot,
)
from mlops_tpu.autotune.search import GridPlan, search_plan
from mlops_tpu.serve.metrics import AUTOTUNE_OUTCOMES
from mlops_tpu.utils.io import atomic_write

logger = logging.getLogger(__name__)

PLAN_FILE = "plan.json"

# Declared lock universe (tpulint TPU401): the controller's leaf lock
# guards its own counters/gauges only — every engine call (warm, swap,
# rollback) happens OUTSIDE it, so the engine's _compile_lock/_acc_lock
# order composes with this lock held by nobody.
TPULINT_LOCK_ORDER = {"AutotuneController": ("_lock",)}


class RegridAborted(RuntimeError):
    """The live bundle was promoted between warm and swap: the twin's
    state snapshot is stale, and swapping it would silently ROLL BACK
    the promotion. The plan is simply recomputed next tick."""


def warm_plan(engine, buckets, workers: int = 0) -> int:
    """Pre-compile the plan's missing solo-bucket entries into the LIVE
    engine's exec table, off the request path. Returns how many entries
    were actually warmed (0 = the table already covered the plan)."""
    if not engine.monitor_accumulating:
        raise ValueError(
            "autotune requires the device-accumulating (flax) serving "
            "flavor — the sklearn flavor has no AOT exec table to regrid"
        )
    from mlops_tpu.compilecache.warmup import (
        run_jobs,
        serve_predict_jobs,
        serve_quant_jobs,
    )

    wanted = sorted(int(b) for b in buckets)
    with engine._compile_lock:
        missing = tuple(
            b for b in wanted if ("bucket", b) not in engine._exec
        )
    if not missing:
        return 0
    bundle = engine.bundle
    device_tag = (
        f"@dev{engine.device_index}" if engine.device_index is not None
        else ""
    )
    if engine.serve_tier == "quant":
        jobs = serve_quant_jobs(
            engine._variables,
            engine._monitor,
            missing,
            temperature=bundle.quant_temperature,
            placement=engine._placement,
            device_tag=device_tag,
        )
    else:
        jobs = serve_predict_jobs(
            bundle.model,
            bundle.model_config,
            engine._variables,
            engine._monitor,
            missing,
            temperature=bundle.temperature,
            mesh=engine._mesh,
            placement=engine._placement,
            device_tag=device_tag,
        )
    for job, fn in run_jobs(
        jobs, cache=engine.compile_cache,
        workers=workers or engine.warmup_workers,
    ):
        # Per-write lock hold, never across run_jobs — warmup()'s
        # discipline: live novel-shape compiles keep flowing.
        with engine._compile_lock:
            engine._exec[("bucket", job.meta["bucket"])] = fn
    return len(missing)


def build_grid_twin(engine, buckets):
    """An architecture twin of the live engine carrying the plan's
    bucket set, sharing the live exec table (and compile lock) BY
    REFERENCE — `swap_bundle`-ready with zero additional compiles."""
    from mlops_tpu.serve.engine import InferenceEngine

    twin = InferenceEngine(
        engine.bundle,
        buckets=tuple(int(b) for b in buckets),
        service_name=engine.service_name,
        enable_grouping=engine.supports_grouping,
        compile_cache=engine.compile_cache,
        warmup_workers=engine.warmup_workers,
        model_shards=engine.model_shards,
        device_index=engine.device_index,
        serve_tier=engine.serve_tier,
        # A regrid twin must carry the whole tier ladder (ISSUE 19): a
        # hot swap that dropped the gated tiers would silently break
        # per-request SLO routing mid-flight.
        tier_routing=engine.tier_routing,
    )
    twin.adopt_executables(engine)
    return twin


def apply_plan(engine, buckets, workers: int = 0) -> int:
    """Warm + twin + swap: the full hot regrid. Returns the engine's new
    ``grid_generation``. Raises `RegridAborted` if a lifecycle promotion
    landed between warm and swap (the twin would reinstall pre-promotion
    params); the caller retries from fresh telemetry next tick."""
    generation = engine.bundle_generation
    warm_plan(engine, buckets, workers=workers)
    # Injection point (mlops_tpu/faults): kill -9 here = a crash after
    # the warm compiles landed but BEFORE the swap — the most state a
    # regrid ever has in flight. Nothing durable or shared is mid-
    # mutation at this point (the exec table only gained valid warmed
    # entries; the grid refs are untouched), which is what the chaos
    # smoke's mid-regrid scenario proves: a restart serves on the old
    # grid and a re-run regrid completes cleanly.
    faults.fire("autotune.regrid.midswap")
    twin = build_grid_twin(engine, buckets)
    if engine.bundle_generation != generation:
        raise RegridAborted(
            f"bundle generation moved {generation} -> "
            f"{engine.bundle_generation} during warm; regrid plan is stale"
        )
    engine.swap_bundle(twin)
    return engine.grid_generation


class AutotuneController:
    """The periodic gridtuner loop — one per engine process, started
    after warmup, stopped at drain (the LifecycleController thread
    pattern: daemon worker, `_stop` event, `run_once` as the testable
    unit, a leaf `_lock` over counters only)."""

    def __init__(
        self,
        engine,
        config,
        adopt: bool = False,
        replica: int = 0,
    ) -> None:
        self.engine = engine
        self.config = config
        self.adopt = bool(adopt)
        self.replica = int(replica)
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._plans = {outcome: 0 for outcome in AUTOTUNE_OUTCOMES}
        self._last_plan: GridPlan | None = None
        self._predicted_gain: float | None = None
        self._measured_gain: float | None = None
        self._cooldown_until = 0.0
        # Windowed goodput audit state: last ledger totals over solo
        # entries (useful rows, device seconds) and the rate measured
        # in the window before the last apply.
        self._window_totals: tuple[float, float] | None = None
        self._window_rate: float | None = None
        self._pre_apply_rate: float | None = None
        self._adopted_plan_gen = 0

    # ------------------------------------------------------------ thread
    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop,
            name=f"autotune-controller-r{self.replica}",
            daemon=True,
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        thread = self._thread
        if thread is not None:
            thread.join(timeout=30)
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(self.config.interval_s):
            try:
                self.run_once()
            except Exception:  # tpulint: disable=TPU201
                # The LifecycleController contract: a failed tick (bad
                # telemetry, a compile error mid-warm, a promotion race)
                # is counted and logged — the controller can never take
                # the serving engine down with it.
                logger.exception("autotune tick failed")
                self._count("failed")

    # ------------------------------------------------------------- ticks
    def _count(self, outcome: str) -> None:
        with self._lock:
            self._plans[outcome] += 1

    def run_once(self, now: float | None = None) -> str:
        """One evaluation. Returns a short status string (tests + the
        offline trace of what the loop decided and why)."""
        if self.adopt:
            return self._run_adopt()
        now = time.monotonic() if now is None else now
        self._measure_window()
        if now < self._cooldown_until:
            return "cooling"
        stats = self.engine.shape_stats
        ledger = self.engine.cost_ledger
        if stats is None or ledger is None:
            return "disarmed"
        shape_entries = stats.snapshot()
        demand = demand_from_shapes(shape_entries)
        dispatches = sum(w for _, w in demand)
        if dispatches < self.config.min_dispatches:
            return f"held: {int(dispatches)} dispatches < min"
        model = fit_cost_model(
            ledger_rows_from_snapshot(ledger.snapshot())
        )
        if model is None:
            return "held: no solo ledger observations"
        plan = search_plan(
            demand, model, tuple(self.engine.buckets),
            self.config.max_entries,
        )
        with self._lock:
            self._last_plan = plan
            self._predicted_gain = plan.predicted_gain_pct
        if (
            plan.buckets == tuple(self.engine.buckets)
            or plan.predicted_gain_pct < self.config.min_gain_pct
        ):
            self._count("rejected")
            return (
                f"rejected: gain {plan.predicted_gain_pct:.1f}% "
                f"(min {self.config.min_gain_pct:g}%)"
            )
        if not self.config.apply:
            self._count("planned")
            self._persist(plan, applied=False)
            return f"planned (dry-run): {list(plan.buckets)}"
        try:
            grid_generation = apply_plan(self.engine, plan.buckets)
        except RegridAborted as exc:
            logger.warning("regrid aborted: %s", exc)
            self._count("failed")
            return "failed: promotion raced the warm phase"
        with self._lock:
            self._pre_apply_rate = self._window_rate
            self._measured_gain = None
        self._cooldown_until = now + self.config.cooldown_s
        self._count("applied")
        self._persist(plan, applied=True, grid_generation=grid_generation)
        return f"applied: grid_generation={grid_generation}"

    def rollback(self) -> str:
        """Restore the pre-regrid grid in one call (the runbook's manual
        bail-out; the table still holds every retired entry, so the old
        grid dispatches warm immediately)."""
        self.engine.rollback()
        self._count("rolled_back")
        with self._lock:
            self._pre_apply_rate = None
            self._measured_gain = None
        return f"rolled_back: grid_generation={self.engine.grid_generation}"

    def _measure_window(self) -> None:
        """Windowed measured goodput from ledger deltas: useful rows per
        device-second over THIS tick's window — directly comparable to
        the plan's predicted ``useful_rows_per_s`` and load-shape
        independent (both numerator and denominator come from the same
        dispatched window)."""
        ledger = self.engine.cost_ledger
        if ledger is None:
            return
        rows = device_s = 0.0
        for row in ledger_rows_from_snapshot(ledger.snapshot()):
            if not str(row["entry"]).startswith("bucket_"):
                continue
            rows += row["rows"]
            device_s += row["device_s"]
        prev = self._window_totals
        self._window_totals = (rows, device_s)
        if prev is None:
            return
        d_rows, d_dev = rows - prev[0], device_s - prev[1]
        if d_dev <= 0 or d_rows <= 0:
            return
        rate = d_rows / d_dev
        with self._lock:
            self._window_rate = rate
            if self._pre_apply_rate and self._pre_apply_rate > 0:
                self._measured_gain = (
                    100.0 * (rate - self._pre_apply_rate)
                    / self._pre_apply_rate
                )

    # ---------------------------------------------------- plan file (ring)
    def _plan_path(self) -> Path:
        return Path(self.config.plan_dir) / PLAN_FILE

    def _persist(
        self, plan: GridPlan, applied: bool, grid_generation: int = 0
    ) -> None:
        if not self.config.plan_dir:
            return
        doc = plan.as_dict()
        doc["applied"] = bool(applied)
        doc["grid_generation"] = int(grid_generation)
        doc["replica"] = self.replica
        try:
            path = self._plan_path()
            path.parent.mkdir(parents=True, exist_ok=True)
            atomic_write(path, (json.dumps(doc) + "\n").encode())
        except OSError:
            # The plan file is adoption/audit metadata, never
            # load-bearing for the plane that already applied the grid.
            logger.exception("failed to persist autotune plan")

    def _run_adopt(self) -> str:
        """Sibling-replica mode (ring plane): apply the lead's persisted
        plan locally. The shared compile cache turns the warm phase into
        deserialization — the lead paid the compiles once."""
        path = self._plan_path()
        try:
            doc = json.loads(path.read_text())
        except (OSError, ValueError):
            return "adopt: no plan"
        plan_gen = int(doc.get("grid_generation", 0))
        if not doc.get("applied") or plan_gen <= self._adopted_plan_gen:
            return "adopt: current"
        buckets = tuple(int(b) for b in doc.get("buckets", ()))
        if not buckets:
            return "adopt: malformed plan"
        if buckets == tuple(self.engine.buckets):
            self._adopted_plan_gen = plan_gen
            return "adopt: already on plan grid"
        try:
            grid_generation = apply_plan(self.engine, buckets)
        except RegridAborted:
            self._count("failed")
            return "failed: promotion raced the adopt warm"
        self._adopted_plan_gen = plan_gen
        self._count("applied")
        return f"adopted: grid_generation={grid_generation}"

    # ------------------------------------------------------------ reads
    def metrics_snapshot(self) -> dict[str, Any]:
        """The shared-formatter input (`ServingMetrics.autotune_lines`)
        — also what the ring telemetry loop mirrors into shm."""
        with self._lock:
            return {
                "grid_generation": int(self.engine.grid_generation),
                "plans": dict(self._plans),
                "predicted_gain_pct": self._predicted_gain,
                "measured_gain_pct": self._measured_gain,
            }

    def status(self) -> dict[str, Any]:
        with self._lock:
            return {
                "adopt": self.adopt,
                "replica": self.replica,
                "grid": list(self.engine.buckets),
                "grid_generation": int(self.engine.grid_generation),
                "plans": dict(self._plans),
                "predicted_gain_pct": self._predicted_gain,
                "measured_gain_pct": self._measured_gain,
                "last_plan": (
                    self._last_plan.as_dict() if self._last_plan else None
                ),
            }
