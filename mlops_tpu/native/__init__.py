"""Native (C++) data-loading kernel with ctypes bindings.

The reference has zero native code (SURVEY.md §2: "Native components:
NONE") — its bulk data handling lives in managed Spark. This framework's
equivalent obligation is a native host-side path of its own: the CSV
parse + encode hot loop (``encoder.cpp``) that feeds the TPU during bulk
scoring (BASELINE config 4), where the Python csv module would otherwise be
the bottleneck long before the chip is.

Build model: compiled on first use with plain ``g++ -O3 -shared -fPIC``
into ``_build/`` next to the source, keyed by a source hash so edits
rebuild automatically. No pybind11 (not in the image) — a pure C ABI called
through ctypes. Everything degrades gracefully: if the toolchain is absent
or compilation fails, callers fall back to the pure-Python encoder
(``Preprocessor.encode``) with identical semantics — a parity test pins
native == Python output exactly.
"""

from __future__ import annotations

import ctypes
import hashlib
import logging
import os
import subprocess
from pathlib import Path

import numpy as np

from mlops_tpu.data.encode import EncodedDataset, Preprocessor
from mlops_tpu.schema.features import SCHEMA, FeatureSchema

logger = logging.getLogger(__name__)

_SRC = Path(__file__).with_name("encoder.cpp")
_BUILD_DIR = Path(__file__).with_name("_build")

_ERRORS = {
    -1: "required schema column missing from CSV header",
    -2: "row count exceeded the preallocated buffer",
    -3: "target column required but absent",
    -4: "unparseable value in the target column",
}

_lib_cache: ctypes.CDLL | None | bool = None  # False = tried and failed


def _compile() -> Path | None:
    source = _SRC.read_bytes()
    tag = hashlib.sha256(source).hexdigest()[:12]
    so_path = _BUILD_DIR / f"encoder_{tag}.so"
    if so_path.exists():
        return so_path
    _BUILD_DIR.mkdir(exist_ok=True)
    # Compile to a private temp name, then rename: an interrupted or
    # concurrent build must never leave a partial .so at the final path
    # (os.replace is atomic within the directory).
    tmp_path = _BUILD_DIR / f".encoder_{tag}.{os.getpid()}.tmp.so"
    cmd = [
        "g++", "-O3", "-std=c++17", "-shared", "-fPIC",
        str(_SRC), "-o", str(tmp_path),
    ]
    try:
        subprocess.run(
            cmd, check=True, capture_output=True, text=True, timeout=120
        )
        os.replace(tmp_path, so_path)
    except (OSError, subprocess.SubprocessError) as err:
        detail = getattr(err, "stderr", "") or str(err)
        logger.warning("native encoder build failed (%s); using Python path",
                       detail.strip()[:500])
        tmp_path.unlink(missing_ok=True)
        return None
    # Clean superseded builds (old source hashes).
    for stale in _BUILD_DIR.glob("encoder_*.so"):
        if stale != so_path:
            stale.unlink(missing_ok=True)
    return so_path


def _lib() -> ctypes.CDLL | None:
    global _lib_cache
    if _lib_cache is None:
        if os.environ.get("MLOPS_TPU_NO_NATIVE"):
            _lib_cache = False
        else:
            so_path = _compile()
            if so_path is None:
                _lib_cache = False
            else:
                try:
                    lib = ctypes.CDLL(str(so_path))
                except OSError as err:
                    # Unloadable artifact (e.g. leftover from a crashed
                    # build): drop it and fall back to the Python path —
                    # the module contract is graceful degradation, never
                    # a hard failure.
                    logger.warning(
                        "native encoder %s failed to load (%s); using "
                        "Python path", so_path.name, err,
                    )
                    so_path.unlink(missing_ok=True)
                    _lib_cache = False
                    return None
                lib.mlops_encode_csv.restype = ctypes.c_long
                lib.mlops_encode_csv.argtypes = [
                    ctypes.c_char_p, ctypes.c_long,      # csv, csv_len
                    ctypes.c_char_p,                     # feature_names
                    ctypes.c_int, ctypes.c_int,          # n_cat, n_num
                    ctypes.c_char_p,                     # vocabs
                    ctypes.POINTER(ctypes.c_float),      # medians
                    ctypes.POINTER(ctypes.c_float),      # means
                    ctypes.POINTER(ctypes.c_float),      # stds
                    ctypes.POINTER(ctypes.c_int32),      # cat_out
                    ctypes.POINTER(ctypes.c_float),      # num_out
                    ctypes.POINTER(ctypes.c_float),      # lab_out
                    ctypes.c_long,                       # max_rows
                    ctypes.c_int,                        # require_label
                    ctypes.POINTER(ctypes.c_int),        # has_label_out
                ]
                _lib_cache = lib
    return _lib_cache or None


def native_available() -> bool:
    return _lib() is not None


def encode_csv_native(
    path: str | Path,
    prep: Preprocessor,
    schema: FeatureSchema = SCHEMA,
    require_target: bool = False,
) -> EncodedDataset:
    """Parse + encode a schema CSV file in one native pass.

    Semantics identical to ``load_csv_columns`` + ``Preprocessor.encode``;
    raises ``RuntimeError`` if the native library is unavailable (callers
    use ``encode_csv`` for automatic fallback).
    """
    return encode_csv_bytes(
        Path(path).read_bytes(), prep, schema, require_target, source=str(path)
    )


def encode_csv_bytes(
    data: bytes,
    prep: Preprocessor,
    schema: FeatureSchema = SCHEMA,
    require_target: bool = False,
    source: str = "<bytes>",
) -> EncodedDataset:
    """Parse + encode an in-memory CSV byte buffer (header + rows) with
    the native kernel.

    This is the streaming hot path: the pipelined executor
    (`data/stream.py score_csv_stream`) feeds header-prefixed chunk
    buffers through here on a worker thread, and the ctypes foreign call
    RELEASES the GIL for the whole parse+encode — so chunk N+1 encodes in
    C++ while chunk N computes on the device and the GIL-bound
    reader/writer stages keep running.
    """
    lib = _lib()
    if lib is None:
        raise RuntimeError("native encoder unavailable")

    # Upper bound on data rows; the kernel returns the true count. max()
    # covers every record-terminator convention (LF, CRLF, bare CR).
    max_rows = max(1, data.count(b"\n"), data.count(b"\r")) + 1

    names = "\x1e".join(
        [f.name for f in schema.categorical]
        + [f.name for f in schema.numeric]
        + [schema.target]
    ).encode()
    vocabs = "\x1e".join(
        "\x1f".join(f.vocab) for f in schema.categorical
    ).encode()

    cat = np.empty((max_rows, schema.num_categorical), np.int32)
    num = np.empty((max_rows, schema.num_numeric), np.float32)
    lab = np.empty(max_rows, np.float32)
    has_label = ctypes.c_int(0)

    def fptr(a: np.ndarray):
        return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))

    result_rows = lib.mlops_encode_csv(
        data, len(data), names,
        schema.num_categorical, schema.num_numeric, vocabs,
        fptr(np.ascontiguousarray(prep.numeric_median)),
        fptr(np.ascontiguousarray(prep.numeric_mean)),
        fptr(np.ascontiguousarray(prep.numeric_std)),
        cat.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        fptr(num), fptr(lab),
        max_rows, int(require_target), ctypes.byref(has_label),
    )
    if result_rows < 0:
        raise ValueError(
            f"{source}: native encode failed: "
            f"{_ERRORS.get(result_rows, result_rows)}"
        )
    labels = (
        lab[:result_rows].astype(np.int8) if has_label.value else None
    )
    return EncodedDataset(
        cat_ids=cat[:result_rows].copy(),
        numeric=num[:result_rows].copy(),
        labels=labels,
    )


def encode_csv(
    path: str | Path,
    prep: Preprocessor,
    schema: FeatureSchema = SCHEMA,
    require_target: bool = False,
) -> EncodedDataset:
    """Encode a CSV with the native kernel when available, else pure Python.

    ``gs://`` sources are materialized locally first (`data/ingest.py`
    ``fetch_local``) so the byte-oriented native kernel serves remote
    datasets too.
    """
    from mlops_tpu.data.ingest import fetch_local, load_csv_columns

    path = fetch_local(path)
    if native_available():
        return encode_csv_native(path, prep, schema, require_target)
    columns, labels = load_csv_columns(path, schema, require_target)
    return prep.encode(columns, labels, schema)


__all__ = [
    "encode_csv",
    "encode_csv_bytes",
    "encode_csv_native",
    "native_available",
]
