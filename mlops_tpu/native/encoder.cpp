// Native CSV parse + feature encode — the framework's data-loading kernel.
//
// The reference repo has no native code at all (SURVEY.md §0: Python/YAML
// only) and delegates bulk data handling to managed Spark. This framework's
// bulk path (1M-row batch scoring, BASELINE config 4) instead parses and
// encodes on the serving host itself, where Python's csv module + per-value
// dict lookups are the bottleneck long before the TPU is. This translation
// unit does the whole host-side hot loop in one pass over the byte buffer:
//
//   CSV bytes -> (int32 categorical ids, standardized float32 numerics,
//                 optional float32 labels)
//
// with the exact semantics of mlops_tpu.data.encode.Preprocessor.encode:
// unseen categorical values -> the OOV id (handle_unknown="ignore" parity),
// missing/non-finite numerics -> train-time median, then (x - mean) / std.
//
// C ABI only (called via ctypes from mlops_tpu.native); no Python.h, no
// external deps; builds with plain `g++ -O3 -shared -fPIC`.

#include <cctype>
#include <cmath>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

// Split one CSV record starting at `p` (end `end`) into `fields`.
// Handles RFC-4180 double-quoted fields with embedded commas/quotes.
// Returns the pointer just past the record's newline (or `end`).
const char* split_record(const char* p, const char* end,
                         std::vector<std::string>& fields) {
  fields.clear();
  std::string cur;
  bool quoted = false;
  bool at_field_start = true;
  while (p < end) {
    char c = *p;
    if (quoted) {
      if (c == '"') {
        if (p + 1 < end && p[1] == '"') { cur.push_back('"'); p += 2; continue; }
        quoted = false; ++p; continue;
      }
      cur.push_back(c); ++p; continue;
    }
    if (c == '"' && at_field_start) {
      // Only a quote at field start opens quoted mode; a stray quote
      // mid-field stays literal (csv.reader parity).
      quoted = true; at_field_start = false; ++p; continue;
    }
    if (c == ',') {
      fields.push_back(cur); cur.clear();
      at_field_start = true; ++p; continue;
    }
    if (c == '\n' || c == '\r') {
      while (p < end && (*p == '\n' || *p == '\r')) ++p;
      fields.push_back(cur);
      return p;
    }
    cur.push_back(c); at_field_start = false; ++p;
  }
  fields.push_back(cur);
  return p;
}

std::vector<std::string> split_on(const std::string& s, char sep) {
  std::vector<std::string> out;
  size_t start = 0;
  while (true) {
    size_t pos = s.find(sep, start);
    if (pos == std::string::npos) { out.push_back(s.substr(start)); break; }
    out.push_back(s.substr(start, pos - start));
    start = pos + 1;
  }
  return out;
}

// Python-float() parity: the WHOLE trimmed cell must parse (reject
// trailing garbage like "1.5abc") and hex literals are rejected (strtof
// accepts "0x1A"; Python float() does not). Underscore separators follow
// Python's numeric-literal rule — float("1_000") == 1000.0, but an
// underscore is only valid BETWEEN two digits ("_1", "1_", "1__0",
// "1_.5" all raise) — so validate placement, strip, then parse.
float parse_numeric(const std::string& s) {
  if (s.empty()) return NAN;
  if (s.find('x') != std::string::npos || s.find('X') != std::string::npos)
    return NAN;
  std::string cleaned;
  if (s.find('_') != std::string::npos) {
    for (size_t i = 0; i < s.size(); ++i) {
      if (s[i] == '_') {
        bool digit_before = i > 0 && std::isdigit((unsigned char)s[i - 1]);
        bool digit_after =
            i + 1 < s.size() && std::isdigit((unsigned char)s[i + 1]);
        if (!digit_before || !digit_after) return NAN;
      } else {
        cleaned.push_back(s[i]);
      }
    }
  } else {
    cleaned = s;
  }
  char* endp = nullptr;
  float v = std::strtof(cleaned.c_str(), &endp);
  if (endp == cleaned.c_str()) return NAN;  // unparseable -> missing
  while (*endp == ' ' || *endp == '\t') ++endp;  // float() strips whitespace
  if (*endp != '\0') return NAN;  // trailing garbage -> missing
  return v;
}

}  // namespace

extern "C" {

// Error codes (negative returns).
enum {
  MLOPS_ERR_MISSING_COLUMN = -1,
  MLOPS_ERR_TOO_MANY_ROWS = -2,
  MLOPS_ERR_MISSING_TARGET = -3,
  MLOPS_ERR_BAD_LABEL = -4,
};

// Parse `csv[0..csv_len)` (header + records) and encode into the caller's
// preallocated buffers.
//
//   feature_names: '\x1e'-separated — n_cat categorical names, then n_num
//                  numeric names, then the target column name.
//   vocabs:        per categorical feature the vocab values '\x1f'-joined,
//                  features '\x1e'-joined. Unseen value -> id len(vocab).
//   medians/means/stds: float32[n_num] train-time stats.
//   cat_out:       int32[max_rows * n_cat]
//   num_out:       float32[max_rows * n_num]
//   lab_out:       float32[max_rows]; filled iff the target column exists
//                  (then *has_label_out = 1).
//   require_label: nonzero -> error if the target column is absent.
//
// Returns the number of data rows encoded, or a negative error code.
long mlops_encode_csv(const char* csv, long csv_len,
                      const char* feature_names, int n_cat, int n_num,
                      const char* vocabs,
                      const float* medians, const float* means,
                      const float* stds,
                      int32_t* cat_out, float* num_out, float* lab_out,
                      long max_rows, int require_label, int* has_label_out) {
  const char* p = csv;
  const char* end = csv + csv_len;

  std::vector<std::string> names = split_on(feature_names, '\x1e');
  std::vector<std::string> vocab_blocks = split_on(vocabs, '\x1e');

  // Per-categorical-feature value -> id maps; OOV id = vocab size.
  std::vector<std::unordered_map<std::string, int32_t>> luts(n_cat);
  std::vector<int32_t> oov(n_cat);
  for (int j = 0; j < n_cat; ++j) {
    std::vector<std::string> values = split_on(vocab_blocks[j], '\x1f');
    for (size_t i = 0; i < values.size(); ++i)
      luts[j].emplace(values[i], static_cast<int32_t>(i));
    oov[j] = static_cast<int32_t>(values.size());
  }

  // Header -> column positions for every schema feature (+ target).
  std::vector<std::string> header;
  p = split_record(p, end, header);
  std::unordered_map<std::string, int> col_index;
  for (size_t i = 0; i < header.size(); ++i)
    col_index[header[i]] = static_cast<int>(i);  // duplicate names: last wins
                                                 // (Python dict parity)

  std::vector<int> cat_col(n_cat), num_col(n_num);
  for (int j = 0; j < n_cat + n_num; ++j) {
    auto it = col_index.find(names[j]);
    if (it == col_index.end()) return MLOPS_ERR_MISSING_COLUMN;
    (j < n_cat ? cat_col[j] : num_col[j - n_cat]) = it->second;
  }
  int label_col = -1;
  auto target_it = col_index.find(names[n_cat + n_num]);
  if (target_it != col_index.end()) label_col = target_it->second;
  if (require_label && label_col < 0) return MLOPS_ERR_MISSING_TARGET;
  *has_label_out = label_col >= 0 ? 1 : 0;

  std::vector<std::string> fields;
  long row = 0;
  while (p < end) {
    // Skip blank trailing lines.
    if (*p == '\n' || *p == '\r') { ++p; continue; }
    p = split_record(p, end, fields);
    if (fields.size() == 1 && fields[0].empty()) continue;
    if (row >= max_rows) return MLOPS_ERR_TOO_MANY_ROWS;

    for (int j = 0; j < n_cat; ++j) {
      int col = cat_col[j];
      int32_t id = oov[j];
      if (col < static_cast<int>(fields.size())) {
        auto it = luts[j].find(fields[col]);
        if (it != luts[j].end()) id = it->second;
      }
      cat_out[row * n_cat + j] = id;
    }
    for (int j = 0; j < n_num; ++j) {
      int col = num_col[j];
      float v = col < static_cast<int>(fields.size())
                    ? parse_numeric(fields[col])
                    : NAN;
      if (!std::isfinite(v)) v = medians[j];
      num_out[row * n_num + j] = (v - means[j]) / stds[j];
    }
    if (label_col >= 0) {
      float v = label_col < static_cast<int>(fields.size())
                    ? parse_numeric(fields[label_col])
                    : NAN;
      if (!std::isfinite(v)) {
        // Corrupt TRAINING labels fail fast — silently training on
        // garbage is the one place lenient coercion is wrong (ingest.py
        // mirrors this). On scoring paths a partially-blank target
        // column just means the file is unlabeled.
        if (require_label) return MLOPS_ERR_BAD_LABEL;
        label_col = -1;
        *has_label_out = 0;
      } else {
        lab_out[row] = v;
      }
    }
    ++row;
  }
  return row;
}

}  // extern "C"
