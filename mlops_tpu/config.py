"""Typed configuration tree.

The reference scatters configuration across four ad-hoc mechanisms (notebook
widgets, bundle variables, env vars, CI secrets/vars — SURVEY.md SS5.6). Here
a single dataclass tree covers model/train/serve/monitor, loadable from
TOML, overridable from environment (``MLOPS_TPU_<SECTION>_<FIELD>``) and CLI
flags (``--section.field=value``). Every knob constructed here must be READ
somewhere outside this module — tpulint's TPU503 dead-knob rule
(`analysis/contracts.py`) gates CI on it, keyed off the declaration below
(the PR 13 ``replica_affinity_slack`` lesson: a validated setting that
changes nothing is worse than no setting).
"""

from __future__ import annotations

import dataclasses
import os
import warnings

try:
    import tomllib
except ModuleNotFoundError:  # Python < 3.11: tomllib landed in 3.11
    import tomli as tomllib  # type: ignore[no-redef]
from pathlib import Path
from typing import Any

# Opts this module's *Config dataclasses into the TPU503 knob-liveness
# contract (read from source by the analyzer, never imported).
TPULINT_CONFIG_MODULE = True


@dataclasses.dataclass
class DataConfig:
    train_path: str = ""  # empty -> synthetic
    rows: int = 50_000  # synthetic row count
    seed: int = 0
    valid_fraction: float = 0.2  # parity: train_test_split 80/20,
    # random_state=2024 (`01-train-model.ipynb` cell 7)


@dataclasses.dataclass
class ModelConfig:
    family: str = "mlp"  # mlp | ft_transformer | moe | linear | bert | gbm | rf
    hidden_dims: tuple[int, ...] = (256, 256, 128)
    embed_dim: int = 16
    dropout: float = 0.1
    precision: str = "bf16"  # compute dtype on MXU: bf16 | f32 (params stay f32)
    ensemble_size: int = 1  # >1 wraps the Flax family in a vmapped deep
    # ensemble (models/ensemble.py) — the MXU-native answer to the
    # reference's RandomForest variance reduction; 1 = single model
    # FT-Transformer / MoE specifics
    depth: int = 3
    heads: int = 8
    token_dim: int = 64
    num_experts: int = 8  # moe family: experts per block; the stacked
    # expert axis shards over the mesh 'model' axis (expert parallelism)
    # CPU tree-baseline specifics (families gbm/rf — BASELINE config 1;
    # bounds mirror the reference's hyperopt space, `01-train-model.ipynb:342-353`)
    n_estimators: int = 300
    max_tree_depth: int = 8
    # Long-context (family bert): read `doc_records` consecutive records as
    # ONE document (seq = 2 + 46R tokens) and predict the last record's
    # default from the history; `seq_parallel` routes attention through the
    # ppermute ring (`parallel.make_ring_attention`) over the mesh's 'seq'
    # axis — the training path is `train/long_context.py`.
    doc_records: int = 1
    seq_parallel: bool = False
    # Pipeline parallelism (families bert / ft_transformer): split the
    # `depth` encoder blocks
    # into `pipeline_stages` GPipe stages over the mesh's 'stage' axis
    # (`train/pipeline_parallel.py`); microbatches stream through the
    # ppermute ring (`parallel/pipeline.py`). 0 = off. Requires
    # depth % pipeline_stages == 0 and dropout == 0.
    pipeline_stages: int = 0
    # Tensor parallelism (Flax families): lay params out over a
    # ('data','model') mesh with 'model' axis = tensor_parallel, per the
    # Megatron column/row/head PARAM_RULES (`parallel/sharding.py`);
    # the train step is `parallel/steps.py make_sharded_train_step`, the
    # product loop `train/tensor_parallel.py`. 0 = off. The device count
    # must be a multiple of it.
    tensor_parallel: int = 0

    @property
    def uses_layout_trainer(self) -> bool:
        """True when this config needs a multi-device layout trainer
        (`train/pipeline.py run_layout_training`) instead of the dense
        ``run_training`` path — the ONE predicate both the CLI dispatch
        and run_training's guard share."""
        return bool(
            self.pipeline_stages
            or self.seq_parallel
            or self.doc_records > 1
            or self.tensor_parallel
        )


@dataclasses.dataclass
class TrainConfig:
    batch_size: int = 1024
    steps: int = 2000
    learning_rate: float = 3e-3
    weight_decay: float = 1e-4
    warmup_steps: int = 100
    seed: int = 0
    eval_every: int = 200
    checkpoint_every: int = 500
    pos_weight: float = 1.0  # class-imbalance weight on the positive class
    init_params: str = ""  # path to pretrained masked-LM params (`pretrain`
    # CLI output) to graft into the bert trunk before fine-tuning
    tensorboard_dir: str = ""  # also stream metrics.jsonl records as TF
    # scalar events here (utils/tboard.py); empty = jsonl only
    keep_best: bool = True  # package the eval window with the highest
    # validation ROC-AUC instead of the final step — the reference's
    # select-best-by-validation-metric semantics (cell 10), and the guard
    # against the measured overfitting cliff (2400 steps: AUC 0.8056 ->
    # 0.7537 on the synthetic task). False = always package final params.
    distill_bulk: bool = True  # ensembles (>1 member) also package a
    # distilled single-MLP "bulk student" (train/distill.py): CPU-backend
    # bulk sweeps route through it so they beat the sklearn GBM floor
    # instead of paying K× ensemble FLOPs; serving stays exact. The
    # student's fidelity record lands in the bundle manifest.
    distill_quant: bool = False  # also package the int8/bf16 QUANTIZED
    # student tier (train/distill.py distill_quant_student, served by
    # ops/quant_kernel.py): the raw-speed serving/bulk tier behind the
    # lifecycle AUC/ECE promotion gates. Opt-in — it costs a second
    # distillation fit at packaging time, and only deployments that set
    # serve.serve_tier (or bulk --tier quant) away from "exact" use it.
    pipeline_microbatches: int = 8  # GPipe microbatches per step on the
    # pipeline-parallel path (model.pipeline_stages > 0): bubble fraction
    # is (S-1)/(M+S-1), so raise M to amortize; batch_size must divide by
    # it (times the 'data' axis when composing DP x PP)
    pipeline_remat: bool = False  # jax.checkpoint around each stage:
    # recompute the stage's INTERNAL block activations (attention/MLP
    # intermediates x layers-per-stage, the dominant backward-memory term
    # at depth) from the stage-boundary input instead of storing them;
    # the boundary inputs themselves stay stored (the scan needs them)
    ema_decay: float = 0.0  # >0 serves bias-corrected Polyak-averaged
    # params (EMA folded into the compiled step; eval/packaging use the
    # debiased average, raw params keep training). 0 disables. Supported
    # by EVERY trainer: dense fit, the DP/TP sharded step, the vmapped
    # HPO sweep, the long-context/document loop, and pipeline parallel.


@dataclasses.dataclass
class HPOConfig:
    """Hyperparameter search (replaces hyperopt TPE ``fmin(max_evals=10)``,
    `01-train-model.ipynb:342-353`). Trials with identical architectures are
    vmapped; distinct architectures loop; everything shards across the mesh."""

    trials: int = 10
    seed: int = 2024
    objective: str = "roc_auc"  # selection metric, parity with
    # `mlflow.search_runs(order_by validation_roc_auc_score DESC)` (cell 10)
    steps: int = 1000
    strategy: str = "random"  # random | sha. "sha" = successive halving
    # (the ADAPTIVE analogue of the reference's TPE, `01-train-model.ipynb:349`):
    # train all `trials` candidates one rung in ONE vmapped program, keep
    # the top 1/eta by `objective`, continue the survivors — total step
    # budget stays <= trials*steps (equal-budget vs random search), but
    # most of it lands on the candidates that earn it.
    eta: int = 3  # sha survivor fraction per rung (keep top 1/eta)
    sha_rungs: int = 3  # sha rung count (last rung trains the finalists)
    # Continuous search space (both strategies sample from these — the
    # reference's TPE space is RandomForest-shaped; these are the neural
    # optimizer's knobs). log10 bounds for the log-uniform draws:
    lr_log10: tuple[float, float] = (-3.7, -2.0)
    wd_log10: tuple[float, float] = (-6.0, -3.0)
    pos_weight_range: tuple[float, float] = (1.0, 4.0)  # uniform
    architectures: tuple[str, ...] = ()  # structural sweep axis (the
    # reference's n_estimators/max_depth/criterion analogue,
    # `01-train-model.ipynb:342-353`): each spec is comma-separated
    # ModelConfig overrides, e.g. "family=mlp,hidden_dims=64x64,embed_dim=8"
    # (tuples use 'x'). Each spec is one vmapped group of `trials` trials;
    # groups loop in Python (shapes differ -> separate compiles), selection
    # crosses groups by the same objective ordering. Empty = single group
    # with the configured model.


@dataclasses.dataclass
class MonitorConfig:
    # (drift_p_val, the TabularDrift(p_val=.05) parity knob, was removed:
    # the fused monitor exports CONTINUOUS 1-p drift scores and the only
    # consumed threshold is lifecycle.drift_threshold on windowed means
    # — a p-value cutoff here was a validated no-op, TPU503.)
    outlier_quantile: float = 0.95  # parity: IForest(threshold=0.95)
    drift_ref_size: int = 2048  # per-feature reference sample for K-S


class ServeConfigError(ValueError):
    """An inconsistent serving geometry, named at startup.

    Raised by ``ServeConfig.validate()`` for ring/worker shapes that the
    server used to clamp silently into locals — a deployment that asked
    for ``max_inflight=8`` on a 4-thread pool now fails its rollout with
    the constraint spelled out instead of quietly serving with different
    numbers than its config says."""


@dataclasses.dataclass
class ServeConfig:
    host: str = "0.0.0.0"
    port: int = 5000  # parity: `app/Dockerfile:22-24`
    workers: int = 0  # HTTP front-end PROCESSES. 0/1 = the single-process
    # asyncio server (serve/server.py). >= 2 = the multi-worker plane
    # (serve/frontend.py): N processes each bind the same port via
    # SO_REUSEPORT (kernel load-balances accepts), parse/validate/encode
    # requests, and feed ONE engine process over the zero-copy
    # shared-memory ring (serve/ipc.py). Linux-only (SO_REUSEPORT + fork)
    ring_slots_small: int = 64  # per-front-end request slots whose slab
    # holds up to GROUP_ROW_BUCKET rows (the coalescable class — batch-1
    # traffic rides these). Slots bound admission: a front end with no
    # free slot sheds 503 + Retry-After instead of queueing unboundedly
    ring_slots_large: int = 4  # per-front-end slots sized at max_batch
    # rows (the solo class; small requests may overflow into them, large
    # requests never take a small slot)
    shed_retry_after_s: int = 1  # Retry-After header on shed 503s
    service_name: str = "credit-default-api"
    model_directory: str = "model"  # parity: MODEL_DIRECTORY (`app/main.py:27`)
    max_batch: int = 256  # request-size cap; must equal the largest warmed
    # bucket so steady-state serving never compiles a novel shape
    warmup_batch_sizes: tuple[int, ...] = (1, 8, 64, 256)
    batch_window_ms: float = 1.0  # micro-batching window: concurrent small
    # requests arriving within it coalesce into one vmapped dispatch
    # (serve/batcher.py); 0 disables coalescing. In continuous mode this
    # is the CAP on the measured admit deadline, not a fixed wave
    max_group: int = 64  # most requests one vmapped dispatch may carry;
    # clamped to the largest warmed slot bucket. Large groups are what
    # amortize the flat per-dispatch transport round trip into req/s
    batch_mode: str = "continuous"  # micro-batcher admission policy
    # (serve/batcher.py): "continuous" admits pending requests into the
    # next free in-flight dispatch slot at dispatch boundaries — while a
    # dispatch is in flight new arrivals accumulate for free, so the
    # admit wait only exists when the pipe is empty, where it is sized
    # from the MEASURED dispatch time (batch_admit_fraction x EWMA,
    # capped by batch_window_ms). "windowed" is the legacy fixed-wave
    # policy: hold every group open for the full window first. Responses
    # are bit-identical either way (group geometry never changes the
    # per-request math — tests/test_batcher.py pins it)
    batch_admit_fraction: float = 0.5  # continuous mode: fraction of the
    # EWMA dispatch-stage seconds an empty-pipe group waits for
    # co-travelers before dispatching. Higher coalesces more at idle,
    # lower trims batch-1 p50; irrelevant under load (in-flight
    # dispatches make the admit wait 0)
    serve_tier: str = "exact"  # which packed program family serves
    # (serve/engine.py): "exact" = the bundle's full model; "quant" =
    # the int8/bf16 distilled student tier (ops/quant_kernel.py —
    # Pallas-fused on TPU, ~2x bulk rows/s), REQUIRED to exist and to
    # have passed its packaging-time fidelity gates (refuses otherwise);
    # "auto" = quant when admissible, exact (logged) when not. Train
    # with train.distill_quant=true to package the tier
    max_inflight: int = 4  # overlapped grouped dispatches the micro-batcher
    # may have in flight at once. Sync constraint: must not exceed
    # max_workers, or dispatches just queue inside the executor and the
    # overlap is fiction (serve/batcher.py)
    max_workers: int = 8  # predict thread pool size; >= max_inflight so
    # every overlapped dispatch gets a thread, with headroom for the
    # batcher's solo fast-path and bulk scoring
    monitor_fetch_every_s: float = 2.0  # telemetry cadence for the
    # device-resident monitor aggregate (serve/engine.py
    # monitor_snapshot): the request path never fetches it; a background
    # task reads it at most this often when traffic is flowing. 0
    # disables the timer (the K-request trigger and /metrics scrapes
    # still fetch). Staleness bound: gauges lag live traffic by at most
    # max(monitor_fetch_every_s, monitor_fetch_every_requests requests)
    # — /metrics scrapes always read fresh (docs/operations.md)
    monitor_fetch_every_requests: int = 512  # also fetch after this many
    # predict requests since the last fetch; 0 disables the K-trigger
    request_timeout_s: float = 30.0  # per-request deadline on the predict
    # path: a stalled device (observed live: a remote-attached chip's
    # tunnel hanging dispatches for 40+ min) answers the documented 504
    # fast instead of wedging every in-flight connection until the
    # client gives up. Clients can tighten it per request with the
    # x-request-deadline-ms header (serve/httpcore.py — the budget also
    # rides into the engine so expired work is shed, never dispatched).
    # 0 disables.
    drain_deadline_s: float = 30.0  # graceful-drain window: how long a
    # draining server (single-process) or front-end worker (multi-worker)
    # waits for busy exchanges and in-flight ring slots to finish before
    # force-closing connections. Tune DOWN for chaos scenarios that
    # should converge fast, UP for slow CI boxes; keep it under the pod's
    # terminationGracePeriodSeconds (the hard stop)
    zygote_join_deadline_s: float = 35.0  # supervisor shutdown: ONE
    # shared wall-clock budget for joining all front-end children after
    # the SIGTERM forward (they drain concurrently; stragglers past it
    # are SIGKILLed). Must cover drain_deadline_s plus respawn slack.
    # (Name kept from the PR 6 zygote model for config stability; the
    # supervisor absorbed the zygote's role in ISSUE 11.)
    engine_zygote_join_s: float = 50.0  # engine-child drain: how long
    # the supervisor waits for the engine process (SIGTERMed AFTER the
    # front ends joined — their in-flight slots need a live engine)
    # before escalating to SIGKILL. Must exceed zygote_join_deadline_s
    # + 5 so a cleanly-draining plane is never cut short end to end
    engine_respawn_eta_s: float = 5.0  # brownout contract (ISSUE 11): the
    # Retry-After a front end advertises on a 503 shed while the ENGINE
    # process is down and the parking partition is full — the estimated
    # detect -> fork -> cached-warmup -> replay wall time, minus however
    # long the engine has already been down. Tune to the measured warm
    # re-attach on the deployment box (bench: engine_respawn_gap_ms);
    # too low hammers retries into the still-full parking lot, too high
    # parks well-behaved clients longer than the outage
    engine_replicas: int = 1  # engine replica set (ISSUE 13,
    # mlops_tpu/replicaset/): E engine PROCESSES behind one shm ring on
    # the multi-worker plane — the front ends' ReplicaRouter fans
    # descriptors out least-loaded with small-class affinity, every
    # replica AOT-warms from the SAME compile cache (E deserializes, not
    # E compiles), and a kill -9 of one replica is a brownout of 1/E
    # capacity (its busy slots replay on the respawned incarnation while
    # the router routes around the hole). 1 (default) = the single
    # supervised engine child. Requires serve.workers >= 2 (the ring
    # plane); size E to the device budget, not the worker count
    # (docs/operations.md "Engine replica set")
    replica_affinity_slack: int = 4  # how many slots of extra live depth
    # the small-class sticky replica may carry before the router re-picks
    # least-loaded: low values spread faster (less coalescing company),
    # high values batch better (lumpier load) — see the runbook
    model_shards: int = 1  # partition-rule model sharding (ISSUE 13,
    # parallel/sharding.py match-style regex rules): >1 lays each
    # engine's params out over a ('model',) mesh of that many devices —
    # large families (moe experts, bert/ft_transformer projections)
    # SHARD instead of replicating, and the compile-cache key carries
    # the mesh shape so sharded and unsharded artifacts can never mix.
    # Requires at least that many visible jax devices in the engine
    # process
    tier_routing: bool = False  # per-request SLO tier routing (ISSUE 19,
    # serve/tierroute.py): the engine commits every OTHER gated tier
    # alongside the default one (exact-default keeps its gated quant
    # student; quant-default keeps its exact teacher) and each request
    # picks its tier by SLO class — x-slo-class: cheap|default|accurate,
    # defaulting by x-request-deadline-ms budget. Off (default) =
    # single-tier serving, bit-identical to pre-routing behavior
    slo_cheap_deadline_ms: float = 50.0  # requests with no explicit
    # x-slo-class whose x-request-deadline-ms budget is at or under this
    # route to the CHEAP class (tight budgets can't afford the accurate
    # tier's latency). <= 0 disables deadline-based classing: only the
    # explicit header routes
    brownout_demote_depth: float = 0.75  # brownout-over-shed (ISSUE 19):
    # when admission pressure (in-flight depth fraction) crosses this,
    # DEFAULT-class requests demote to the next-cheaper gated tier
    # instead of shedding 503 — degraded answers beat refused ones.
    # Explicit cheap/accurate classes are never reclassified
    brownout_restore_depth: float = 0.5  # pressure must fall back under
    # this before demotion stops (hysteresis: a gap below
    # brownout_demote_depth prevents flapping at the threshold)
    tenants_path: str = ""  # multi-tenant fleet declaration
    # (mlops_tpu/tenancy/): a tenants.toml naming N tenants (name,
    # bundle_dir, quota weight, default tenant) served from ONE engine
    # process on either plane — `mlops-tpu serve --tenants <file>` is the
    # flag sugar. Empty (default) = the single-tenant "default" fleet
    # serving serve.model_directory, bit-identical to pre-tenancy serving
    profile_dir: str = ""  # jax.profiler trace dir for the /debug/profile
    # endpoints (SURVEY.md SS5.1). Empty = DISABLED (default): the routes
    # are unauthenticated, so tracing is opt-in per deployment — enable
    # with serve.profile_dir=/tmp/profile when debugging a pod
    log_sample_rate: float = 1.0  # fraction of the two-event structured
    # request logs (InferenceData/ModelOutput) actually emitted. At 10x
    # overload the per-request json.dumps becomes measurable hot-path
    # CPU; sampling keeps a statistical picture while non-200 responses
    # (sheds, 504s, 500s) are ALWAYS logged regardless of the rate —
    # errors must never be sampled out of the evidence stream. 1.0
    # (default) = log everything, the pre-sampling behavior
    loop_lag_monitor: bool = False  # arm the LoopLagSanitizer
    # (analysis/loopcheck.py) on each serving event loop: every callback
    # is timed and the worst window lands in the
    # mlops_tpu_event_loop_lag_ms gauge. Off by default — the wrapper
    # adds one closure per scheduled callback to the hot path
    loop_lag_slow_ms: float = 100.0  # callbacks at or above this are
    # recorded with attribution (coroutine qualname) for the sanitizer's
    # slow-callback report; only meaningful with loop_lag_monitor=true

    def validate(self) -> "ServeConfig":
        """Reject inconsistent worker/ring geometries at startup.

        One named error per broken invariant (``ServeConfigError``)
        instead of the ad-hoc warn-and-clamp that used to live in server
        locals: a config that says one thing while the server runs
        another is exactly the silent degradation this gate exists to
        stop. Returns self so call sites can chain."""
        problems: list[str] = []
        if self.max_workers < 1:
            problems.append(f"serve.max_workers={self.max_workers} must be >= 1")
        if self.max_batch < 1:
            problems.append(f"serve.max_batch={self.max_batch} must be >= 1")
        inflight_cap = max(1, self.max_workers - 2)
        if not 1 <= self.max_inflight <= inflight_cap:
            problems.append(
                f"serve.max_inflight={self.max_inflight} outside "
                f"[1, max(1, serve.max_workers - 2) = {inflight_cap}]: the "
                "dispatch bound, the fetch ring, and one thread of headroom "
                "(solo fast path / monitor fetch) must fit the predict pool "
                "— raise serve.max_workers or lower serve.max_inflight"
            )
        if self.workers < 0:
            problems.append(f"serve.workers={self.workers} must be >= 0")
        if self.batch_window_ms < 0:
            problems.append(
                f"serve.batch_window_ms={self.batch_window_ms} must be "
                ">= 0 (0 disables coalescing; negative has no meaning)"
            )
        if self.max_group < 2:
            problems.append(
                f"serve.max_group={self.max_group} must be >= 2 (a group "
                "of one is the solo path; the batcher clamps the top end "
                "to the largest warmed slot bucket)"
            )
        if self.batch_mode not in ("continuous", "windowed"):
            problems.append(
                f"serve.batch_mode={self.batch_mode!r} must be "
                "'continuous' or 'windowed'"
            )
        if not 0.0 < self.batch_admit_fraction <= 1.0:
            problems.append(
                f"serve.batch_admit_fraction={self.batch_admit_fraction} "
                "must be in (0, 1] — it scales the measured dispatch time "
                "into the empty-pipe admit deadline; more than one whole "
                "dispatch of waiting buys nothing a deeper group wouldn't"
            )
        if self.serve_tier not in ("exact", "quant", "auto"):
            problems.append(
                f"serve.serve_tier={self.serve_tier!r} must be 'exact', "
                "'quant' or 'auto'"
            )
        if not 0.0 < self.brownout_demote_depth <= 1.0:
            problems.append(
                f"serve.brownout_demote_depth={self.brownout_demote_depth} "
                "must be in (0, 1] — it is a fraction of admission depth"
            )
        if not 0.0 <= self.brownout_restore_depth < self.brownout_demote_depth:
            problems.append(
                f"serve.brownout_restore_depth={self.brownout_restore_depth}"
                " must be in [0, serve.brownout_demote_depth ="
                f" {self.brownout_demote_depth}) — restoring at or above "
                "the demote threshold flaps the brownout on every sample"
            )
        if self.drain_deadline_s <= 0:
            problems.append(
                f"serve.drain_deadline_s={self.drain_deadline_s} must be "
                "> 0 (a zero drain window severs in-flight responses on "
                "every rollout)"
            )
        if self.zygote_join_deadline_s < self.drain_deadline_s:
            problems.append(
                f"serve.zygote_join_deadline_s={self.zygote_join_deadline_s}"
                f" must cover serve.drain_deadline_s={self.drain_deadline_s}"
                " (the zygote joins children that are themselves draining "
                "for the full drain window)"
            )
        if self.engine_zygote_join_s < self.zygote_join_deadline_s + 5:
            problems.append(
                f"serve.engine_zygote_join_s={self.engine_zygote_join_s} "
                "must exceed serve.zygote_join_deadline_s + 5 "
                f"(= {self.zygote_join_deadline_s + 5:g}: the zygote's "
                "child-join budget plus its SIGKILL grace — a shorter "
                "engine wait SIGKILLs a zygote that is still joining "
                "cleanly)"
            )
        if self.workers > 1:
            if self.ring_slots_small < 1 or self.ring_slots_large < 1:
                problems.append(
                    f"serve.ring_slots_small={self.ring_slots_small} / "
                    f"serve.ring_slots_large={self.ring_slots_large} must "
                    "each be >= 1 with serve.workers > 1 (every front end "
                    "needs at least one slot per bucket class, or whole "
                    "request classes would shed 100%)"
                )
            if self.shed_retry_after_s < 1:
                problems.append(
                    f"serve.shed_retry_after_s={self.shed_retry_after_s} "
                    "must be >= 1 (the shed 503 contract promises a "
                    "positive Retry-After)"
                )
            if self.engine_respawn_eta_s <= 0:
                problems.append(
                    f"serve.engine_respawn_eta_s={self.engine_respawn_eta_s}"
                    " must be > 0 (the brownout 503 contract promises a "
                    "positive respawn-ETA Retry-After)"
                )
        if self.engine_replicas < 1:
            problems.append(
                f"serve.engine_replicas={self.engine_replicas} must be "
                ">= 1"
            )
        if self.engine_replicas > 1 and self.workers < 2:
            problems.append(
                f"serve.engine_replicas={self.engine_replicas} needs the "
                "multi-worker ring plane (serve.workers >= 2): the "
                "single-process server has no descriptor ring to fan out"
            )
        if self.replica_affinity_slack < 0:
            problems.append(
                f"serve.replica_affinity_slack={self.replica_affinity_slack}"
                " must be >= 0"
            )
        if self.model_shards < 1:
            problems.append(
                f"serve.model_shards={self.model_shards} must be >= 1"
            )
        if not 0.0 < self.log_sample_rate <= 1.0:
            problems.append(
                f"serve.log_sample_rate={self.log_sample_rate} must be in "
                "(0, 1] (0 would silence even the always-logged errors' "
                "InferenceData events; sample DOWN, never off)"
            )
        if self.loop_lag_slow_ms <= 0:
            problems.append(
                f"serve.loop_lag_slow_ms={self.loop_lag_slow_ms} must be "
                "> 0 (0 would record every callback as slow, unbounded "
                "attribution overhead)"
            )
        if problems:
            raise ServeConfigError("; ".join(problems))
        return self


@dataclasses.dataclass
class RegistryConfig:
    root: str = "registry"
    model_name: str = "credit-default-uci-custom"  # parity:
    # `databricks/resources/train_register_model.yml` var model_name
    experiment_name: str = "credit-default-uci-train"  # parity: parent
    # MLflow run name (`01-train-model.ipynb` cell 8)
    run_root: str = "runs"  # per-run artifacts: metrics.jsonl, checkpoints
    run_name: str = ""  # stable run-directory name: a retried/preempted
    # job that passes the same name (e.g. the K8s ${JOB_NAME}) lands in
    # the same <run_root>/<run_name> and RESUMES from its checkpoints —
    # provided run_root is on storage that survives the pod. Empty = a
    # fresh timestamped directory per invocation.
    promote_version: str = ""  # `promote` CLI: version to move
    promote_stage: str = "staging"  # `promote` CLI: target stage
    gc_keep: int = 0  # `gc` CLI: also prune old unstaged versions beyond
    # the newest N (0 = remove crash orphans only)


@dataclasses.dataclass
class ScoreConfig:
    """Bulk scoring (BASELINE config 4: 1M rows over the data mesh)."""

    chunk_rows: int = 131_072  # rows per compiled chunk (rounded to mesh axis)
    drift_sample: int = 65_536  # bounded sample for dataset-level drift
    pipeline_depth: int = 2  # bounded-queue depth of the streaming
    # executor (data/pipeline_exec.py): read+parse, encode, device
    # transfer, compute, and result fetch/output each run on their own
    # stage, overlapped across chunks, with peak memory fixed at a few
    # chunks. 1 = strict serial (bit-identical outputs, the debugging
    # baseline); 2 = classic double buffering (the measured sweet spot —
    # deeper queues oversubscribe small CPU hosts without buying overlap)
    output_path: str = ""  # optional .npz with predictions/outliers
    streaming: bool = False  # out-of-core: stream CSV chunks through the
    # fused predict with one-chunk peak memory (data/stream.py); output
    # becomes an incrementally-written CSV instead of an .npz
    exact: bool = False  # True forces the serving-identical ensemble for
    # bulk scoring; False (default) auto-routes through the distilled
    # bulk student on CPU backends (parallel/bulk.py use_distilled_bulk —
    # the output JSON's "path" field records which ran)


class LifecycleConfigError(ValueError):
    """An inconsistent lifecycle geometry, named at startup (the
    ``ServeConfigError`` discipline applied to the controller knobs)."""


@dataclasses.dataclass
class LifecycleConfig:
    """The closed-loop controller (`mlops_tpu/lifecycle/`): drift-triggered
    retrain -> shadow serve -> gated hot promotion. Disabled by default —
    `serve` grows the loop only when ``lifecycle.enabled=true`` (or the
    one-shot offline pass runs via ``mlops-tpu lifecycle``)."""

    enabled: bool = False
    dir: str = "lifecycle"  # controller state root: the on-disk sample
    # reservoir, candidate bundles (candidates/gen-N), retrain checkpoints
    labeled_path: str = ""  # labeled window source (CSV/Parquet WITH the
    # target column) for retrain + the candidate-vs-incumbent gates.
    # Serving traffic is unlabeled; ground truth (the realized default)
    # arrives out of band — this file is that delivery point. Empty =
    # retrain triggers are observed but can never produce a candidate
    # ---------------------------------------------------------- triggers
    drift_threshold: float = 0.9  # fire when the WINDOWED per-feature mean
    # drift score (1 - p_val, monitor aggregates between controller ticks)
    # exceeds this on any feature
    outlier_threshold: float = 0.5  # ... or the windowed outlier rate does
    min_window_rows: int = 256  # a trigger window must carry at least this
    # many scored rows (a near-empty window's statistics are noise)
    hysteresis_windows: int = 2  # consecutive over-threshold windows
    # required before firing — one noisy window can never retrain-storm
    cooldown_s: float = 300.0  # dead time after any trigger/outcome during
    # which new spikes neither fire nor accumulate hysteresis
    tick_s: float = 1.0  # controller evaluation cadence (its own thread,
    # off the request path)
    # ----------------------------------------------------------- retrain
    reservoir_rows: int = 8192  # bounded on-disk sample reservoir fed from
    # the serve path (algorithm-R over every scored row)
    retrain_steps: int = 300  # incremental fine-tune budget from the
    # incumbent's params over the labeled window
    retrain_batch_size: int = 256
    min_labeled_rows: int = 512  # labeled window smaller than this skips
    # retrain (the gate evaluation would be statistically meaningless)
    refit_preprocessor: bool = False  # True re-fits normalization stats on
    # the labeled window via `fit_streaming` (single-process serving
    # only): the multi-worker plane's front ends encode with the
    # preprocessor loaded at fork, so the ring plane forces False — the
    # encode contract is part of the promotion contract there. False
    # (default) also makes the hot swap's one-generation guarantee cover
    # the encode stage unconditionally (the preprocessor is then
    # identical across generations); with a refit, a request already
    # past encode when a swap lands scores old-stats rows against the
    # new params for that instant (serve/engine.py swap_bundle)
    # ------------------------------------------------------------ shadow
    mirror_fraction: float = 0.1  # fraction of live traffic mirrored to
    # the shadow candidate (dispatch-only; responses discarded)
    shadow_min_mirrors: int = 32  # mirrored dispatches to accumulate
    # before the gates are evaluated
    shadow_max_s: float = 600.0  # evaluate anyway after this long in
    # shadow (a traffic lull must not wedge the loop mid-candidate)
    # ------------------------------------------------------------- gates
    max_auc_drop: float = 0.01  # candidate AUC may trail the incumbent's
    # by at most this (epsilon) on the labeled holdout
    max_ece: float = 0.1  # candidate expected-calibration-error bound
    max_p99_ratio: float = 2.0  # candidate p99 latency bound, relative to
    # the incumbent's on the same mirrored/holdout shapes
    auto_promote: bool = True  # False stops after the gate report (the
    # human-in-the-loop mode; promote later via the registry CLI)
    # ---------------------------------------------------- circuit breaker
    breaker_failures: int = 3  # consecutive retrain/shadow/evaluate
    # FAILURES (not gate rejections — those are the loop working) that
    # open the circuit breaker: while open, triggers neither fire nor
    # accumulate hysteresis, so a persistently broken retrain path
    # (corrupt labeled file, full disk, compile regression) cools down
    # instead of hot-looping retrain attempts against live serving
    breaker_cooldown_s: float = 1800.0  # how long the breaker stays open
    # before the loop re-arms (half-open: the next trigger is the probe)

    def validate(self) -> "LifecycleConfig":
        problems: list[str] = []
        if not 0.0 < self.drift_threshold <= 1.0:
            problems.append(
                f"lifecycle.drift_threshold={self.drift_threshold} must be "
                "in (0, 1] (drift scores are 1 - p_val)"
            )
        if not 0.0 < self.outlier_threshold <= 1.0:
            problems.append(
                f"lifecycle.outlier_threshold={self.outlier_threshold} "
                "must be in (0, 1] (a rate)"
            )
        if self.hysteresis_windows < 1:
            problems.append(
                f"lifecycle.hysteresis_windows={self.hysteresis_windows} "
                "must be >= 1 (0 would fire on no evidence at all)"
            )
        if not 0.0 <= self.mirror_fraction <= 1.0:
            problems.append(
                f"lifecycle.mirror_fraction={self.mirror_fraction} must be "
                "in [0, 1]"
            )
        if self.reservoir_rows < 1:
            problems.append(
                f"lifecycle.reservoir_rows={self.reservoir_rows} must be >= 1"
            )
        if self.retrain_steps < 1:
            problems.append(
                f"lifecycle.retrain_steps={self.retrain_steps} must be >= 1"
            )
        if self.max_p99_ratio <= 0:
            problems.append(
                f"lifecycle.max_p99_ratio={self.max_p99_ratio} must be > 0"
            )
        if self.tick_s <= 0:
            problems.append(
                f"lifecycle.tick_s={self.tick_s} must be > 0 (a zero tick "
                "turns the controller thread into a busy loop of "
                "fetch-and-reset device round trips contending the "
                "accumulator lock with live traffic)"
            )
        if self.cooldown_s < 0:
            problems.append(
                f"lifecycle.cooldown_s={self.cooldown_s} must be >= 0"
            )
        if self.min_window_rows < 1:
            problems.append(
                f"lifecycle.min_window_rows={self.min_window_rows} must "
                "be >= 1"
            )
        if self.min_labeled_rows < 2:
            problems.append(
                f"lifecycle.min_labeled_rows={self.min_labeled_rows} must "
                "be >= 2 (the holdout split needs both classes a chance "
                "to exist)"
            )
        if self.shadow_min_mirrors < 0:
            problems.append(
                f"lifecycle.shadow_min_mirrors={self.shadow_min_mirrors} "
                "must be >= 0"
            )
        if self.shadow_max_s <= 0:
            problems.append(
                f"lifecycle.shadow_max_s={self.shadow_max_s} must be > 0 "
                "(the shadow phase needs a bounded evaluation deadline)"
            )
        if self.breaker_failures < 1:
            problems.append(
                f"lifecycle.breaker_failures={self.breaker_failures} must "
                "be >= 1 (0 would open the breaker on no evidence)"
            )
        if self.breaker_cooldown_s < 0:
            problems.append(
                f"lifecycle.breaker_cooldown_s={self.breaker_cooldown_s} "
                "must be >= 0"
            )
        if problems:
            raise LifecycleConfigError("; ".join(problems))
        return self


class TraceConfigError(ValueError):
    """An inconsistent tracing geometry, named at startup (the
    ``ServeConfigError`` discipline applied to the tracewire knobs)."""


@dataclasses.dataclass
class TraceConfig:
    """tracewire (`mlops_tpu/trace/`): end-to-end request tracing +
    shape/goodput telemetry on both serving planes. Disabled by default —
    disarmed, the hot path pays one ``is None`` check per request (bench
    pins ``trace_overhead_pct`` ~0 disarmed, <= 2 armed)."""

    enabled: bool = False
    dir: str = "traces"  # span JSONL root: the single-process server
    # writes spans.jsonl, each multi-worker front end spans-w{N}.jsonl;
    # `mlops-tpu trace-report trace.dir=<dir>` aggregates them
    ring_capacity: int = 4096  # bounded span buffer per process; a full
    # buffer DROPS (counted in mlops_tpu_trace_dropped_total) instead of
    # ever back-pressuring the request path
    flush_interval_s: float = 0.5  # background writer cadence; the drain
    # path flushes everything regardless, so this only bounds how long a
    # span sits in memory while the server runs
    tenant: str = ""  # `trace-report` filter (`--tenant` flag sugar):
    # only aggregate spans carrying this tenant label — multi-tenant
    # planes (mlops_tpu/tenancy/) stamp every span with its tenant;
    # pre-tenancy spans count as "default". Empty = all tenants
    replica: int = -1  # `trace-report` filter (`--replica` flag sugar):
    # only aggregate spans served by this engine replica (the ring
    # plane stitches the router's choice into every span; pre-replica
    # spans count as replica 0). -1 = all replicas
    ledger: bool = False  # `trace-report --ledger` flag sugar: report
    # the device-time cost ledger (slo.ledger_dir) ranked by
    # cost_ms_per_row instead of aggregating span files

    def validate(self) -> "TraceConfig":
        problems: list[str] = []
        if self.ring_capacity < 1:
            problems.append(
                f"trace.ring_capacity={self.ring_capacity} must be >= 1"
            )
        if self.flush_interval_s <= 0:
            problems.append(
                f"trace.flush_interval_s={self.flush_interval_s} must be "
                "> 0 (a zero interval busy-loops the writer thread)"
            )
        if self.enabled and not self.dir:
            problems.append(
                "trace.enabled=true requires trace.dir (the span JSONL "
                "root)"
            )
        if problems:
            raise TraceConfigError("; ".join(problems))
        return self


class SLOConfigError(ValueError):
    """An inconsistent sloscope geometry, named at startup (the
    ``ServeConfigError`` discipline applied to the SLO knobs)."""


@dataclasses.dataclass
class SLOConfig:
    """sloscope (`mlops_tpu/slo/`): SLO/error-budget accounting with
    multi-window multi-burn-rate alerts, the anomaly-triggered flight
    recorder, and the per-entry device-time cost ledger. Disabled by
    default — disarmed, every hot path pays one ``is None`` check
    (bench key ``slo_overhead_pct``)."""

    enabled: bool = False
    # ------------------------------------------------------------- targets
    availability_target: float = 0.999  # fraction of /predict requests
    # answered without a server-side failure (5xx: 500s, shed 503s, and
    # deadline 504s all spend budget — a shed request is not goodput)
    latency_target: float = 0.99  # fraction of requests answered inside
    # the latency threshold below
    latency_threshold_ms: float = 50.0  # measured against the existing
    # latency histogram: the EFFECTIVE threshold is the smallest bucket
    # edge >= this value (ServingMetrics.LATENCY_BUCKETS)
    tick_s: float = 1.0  # evaluation cadence (the single-process plane's
    # timer task; the ring plane's lead-replica telemetry loop). The
    # alert contract is "flips within two ticks of the counters
    # crossing" — tune down for chaos drills, up for huge fleets
    # --------------------------------------------------------- burn alerts
    # The SRE-workbook multiwindow multi-burn-rate pairs: each alert
    # requires BOTH its windows over the threshold (long filters blips,
    # short ends the alert fast once the burn stops). Defaults are the
    # classic 30-day-budget numbers; chaos drills shrink the windows.
    fast_burn_threshold: float = 14.4  # page: budget gone in ~2 days
    slow_burn_threshold: float = 6.0  # ticket: budget gone in ~5 days
    fast_short_s: float = 300.0  # 5m
    fast_long_s: float = 3600.0  # 1h
    slow_short_s: float = 21600.0  # 6h
    slow_long_s: float = 259200.0  # 3d
    # ---------------------------------------------------- flight recorder
    flightrec_enabled: bool = True  # armed with slo.enabled: each serving
    # process keeps a bounded in-memory ring of recent request summaries
    # (+ spans when tracewire is armed) and dumps it atomically on
    # anomaly — burn alert, engine respawn, 5xx/504 spike, breaker open,
    # SIGTERM-with-evidence. A clean run writes NOTHING.
    flightrec_dir: str = "runs"  # dump directory (flightrec-*.json)
    flightrec_capacity: int = 2048  # events per process ring
    flightrec_cooldown_s: float = 30.0  # min seconds between triggered
    # dumps per process (a sustained burn produces a bounded stream)
    flightrec_keep: int = 8  # retention: newest N dumps kept in the dir
    flightrec_spike_errors: int = 8  # 5xx/504 spike trigger: this many
    # server-side failures inside the window below trips a dump even
    # when no burn alert is armed to notice
    flightrec_spike_window_s: float = 5.0
    # --------------------------------------------------------- cost ledger
    ledger_dir: str = ""  # per-entry device-time cost ledger root
    # (mlops_tpu/slo/ledger.py): empty = OFF. Set it and every packed
    # dispatch accounts (entry, rows, padded rows, device-path seconds)
    # into <dir>/ledger.json — persisted atomically, ACCUMULATED across
    # runs, keyed by entry + model fingerprint (a regrid/promotion never
    # cross-pollutes), exported as mlops_tpu_entry_* series and ranked
    # by `mlops-tpu trace-report --ledger`. Arms independently of
    # slo.enabled: the ledger is autotuner input, not alerting.
    ledger_flush_s: float = 30.0  # background flush cadence

    def validate(self) -> "SLOConfig":
        problems: list[str] = []
        for name, target in (
            ("availability_target", self.availability_target),
            ("latency_target", self.latency_target),
        ):
            if not 0.0 < target < 1.0:
                problems.append(
                    f"slo.{name}={target} must be in (0, 1) — a target of "
                    "1.0 leaves zero error budget and every burn rate "
                    "undefined"
                )
        if self.latency_threshold_ms <= 0:
            problems.append(
                f"slo.latency_threshold_ms={self.latency_threshold_ms} "
                "must be > 0"
            )
        else:
            # The SLO measures against the serving latency histogram;
            # a threshold past its largest FINITE edge would map to the
            # +Inf bucket and count EVERY request as good — a silently
            # dead latency alert, exactly what the always-emit contract
            # exists to prevent. (Lazy import: serve/metrics is jax-free
            # and never imports config back.)
            from mlops_tpu.serve.metrics import ServingMetrics

            max_edge = ServingMetrics.LATENCY_BUCKETS[-2]
            if self.latency_threshold_ms > max_edge:
                problems.append(
                    f"slo.latency_threshold_ms={self.latency_threshold_ms}"
                    f" exceeds the largest finite latency bucket "
                    f"({max_edge:g} ms) — every request would count as "
                    "good and the latency alerts could never fire"
                )
        if self.tick_s <= 0:
            problems.append(
                f"slo.tick_s={self.tick_s} must be > 0 (a zero tick "
                "busy-loops the evaluator)"
            )
        if self.fast_burn_threshold <= 0 or self.slow_burn_threshold <= 0:
            problems.append(
                "slo.fast_burn_threshold/slow_burn_threshold must be > 0"
            )
        if not (
            0 < self.fast_short_s < self.fast_long_s
            and 0 < self.slow_short_s < self.slow_long_s
        ):
            problems.append(
                "slo burn windows must satisfy 0 < fast_short_s < "
                "fast_long_s and 0 < slow_short_s < slow_long_s "
                f"(got {self.fast_short_s}/{self.fast_long_s} and "
                f"{self.slow_short_s}/{self.slow_long_s}): each alert "
                "pairs a short window with its long one"
            )
        else:
            # Burn gauges carry a window LABEL dimension ("5m"/"1h"):
            # two windows collapsing to one label (90 vs 90.5 s both →
            # "90s") would silently overwrite each other's burns and
            # drop a series — reject the collision by name instead.
            from mlops_tpu.slo.engine import window_label

            windows = (self.fast_short_s, self.fast_long_s,
                       self.slow_short_s, self.slow_long_s)
            labels = [window_label(w) for w in windows]
            if len(set(labels)) != len(labels):
                problems.append(
                    f"slo burn windows {windows} collapse to duplicate "
                    f"window labels {labels}: every window needs a "
                    "distinct whole-second label (the burn gauges' "
                    "window dimension)"
                )
        if self.flightrec_capacity < 1:
            problems.append(
                f"slo.flightrec_capacity={self.flightrec_capacity} must "
                "be >= 1"
            )
        if self.flightrec_cooldown_s < 0:
            problems.append(
                f"slo.flightrec_cooldown_s={self.flightrec_cooldown_s} "
                "must be >= 0"
            )
        if self.flightrec_keep < 1:
            problems.append(
                f"slo.flightrec_keep={self.flightrec_keep} must be >= 1"
            )
        if self.flightrec_spike_errors < 1:
            problems.append(
                f"slo.flightrec_spike_errors={self.flightrec_spike_errors}"
                " must be >= 1"
            )
        if self.flightrec_spike_window_s <= 0:
            problems.append(
                f"slo.flightrec_spike_window_s="
                f"{self.flightrec_spike_window_s} must be > 0"
            )
        if self.ledger_flush_s <= 0:
            problems.append(
                f"slo.ledger_flush_s={self.ledger_flush_s} must be > 0"
            )
        if problems:
            raise SLOConfigError("; ".join(problems))
        return self


class AutotuneConfigError(ValueError):
    """An inconsistent autotuner geometry, named at startup (the
    ``ServeConfigError`` discipline applied to the gridtuner knobs)."""


@dataclasses.dataclass
class AutotuneConfig:
    """gridtuner (`mlops_tpu/autotune/`): the traffic-shape autotuner —
    fit a measured per-entry cost model from the device-time ledger,
    search bucket grids against the observed shape histogram, and
    hot-apply the winner through the swap machinery. Disabled by
    default; the one-shot offline pass runs via ``mlops-tpu autotune``."""

    enabled: bool = False
    interval_s: float = 60.0  # periodic evaluation cadence (its own
    # thread, off the request path — the LifecycleController discipline)
    min_dispatches: int = 512  # observed dispatches required before a
    # plan is even considered: a near-empty shape histogram is noise,
    # and regridding on noise churns the compile cache for nothing
    max_entries: int = 16  # compile budget: the most solo-bucket entries
    # a plan may carry (each is one AOT compile at warm time; group
    # geometries stay the full fixed grid and don't count against this)
    min_gain_pct: float = 5.0  # predicted useful_rows_per_s gain below
    # which a plan is rejected (outcome="rejected"): swapping grids for
    # sub-noise gains invalidates warm telemetry for nothing
    apply: bool = True  # False = dry-run: plans are computed, exported,
    # and persisted, but never hot-applied (the human-in-the-loop mode —
    # read the plan, then `mlops-tpu serve autotune.apply=true`)
    plan_dir: str = "autotune"  # plan root: the controller (and the
    # offline CLI) writes plan.json here atomically; on the ring plane
    # sibling replicas ADOPT the lead's applied plan from this file,
    # warming through the shared compile cache instead of re-searching
    cooldown_s: float = 300.0  # dead time after any apply/rollback
    # before the next evaluation: measured-gain audit needs a full
    # observation window on the new grid before anyone moves again

    def validate(self) -> "AutotuneConfig":
        problems: list[str] = []
        if self.interval_s <= 0:
            problems.append(
                f"autotune.interval_s={self.interval_s} must be > 0 (a "
                "zero interval busy-loops the controller thread)"
            )
        if self.min_dispatches < 1:
            problems.append(
                f"autotune.min_dispatches={self.min_dispatches} must be "
                ">= 1 (0 would regrid on an empty histogram)"
            )
        if self.max_entries < 2:
            problems.append(
                f"autotune.max_entries={self.max_entries} must be >= 2 "
                "(every grid needs at least a batch-1 bucket and a tail "
                "bucket)"
            )
        if self.min_gain_pct < 0:
            problems.append(
                f"autotune.min_gain_pct={self.min_gain_pct} must be >= 0"
            )
        if self.cooldown_s < 0:
            problems.append(
                f"autotune.cooldown_s={self.cooldown_s} must be >= 0"
            )
        if self.enabled and not self.plan_dir:
            problems.append(
                "autotune.enabled=true requires autotune.plan_dir (the "
                "plan root sibling replicas adopt from)"
            )
        if problems:
            raise AutotuneConfigError("; ".join(problems))
        return self


@dataclasses.dataclass
class CacheConfig:
    """Persistent AOT executable cache (`mlops_tpu/compilecache/`)."""

    dir: str = ""  # cache directory; empty (default) = caching OFF. Set
    # (or export MLOPS_TPU_CACHE_DIR) and every hot program — the serve
    # engine's bucketed/grouped predicts, the dense train window, the TP
    # pjit step, the bulk chunk scorer — deserializes its compiled
    # executable from here instead of re-XLA-compiling per process; the
    # `warmup` CLI pre-populates it (e.g. at container build time)
    warmup_workers: int = 0  # parallel compile threads for warmup misses
    # (XLA compilation releases the GIL); 0 = auto: min(8, cpu count)


@dataclasses.dataclass
class Config:
    data: DataConfig = dataclasses.field(default_factory=DataConfig)
    model: ModelConfig = dataclasses.field(default_factory=ModelConfig)
    train: TrainConfig = dataclasses.field(default_factory=TrainConfig)
    hpo: HPOConfig = dataclasses.field(default_factory=HPOConfig)
    monitor: MonitorConfig = dataclasses.field(default_factory=MonitorConfig)
    serve: ServeConfig = dataclasses.field(default_factory=ServeConfig)
    registry: RegistryConfig = dataclasses.field(default_factory=RegistryConfig)
    score: ScoreConfig = dataclasses.field(default_factory=ScoreConfig)
    lifecycle: LifecycleConfig = dataclasses.field(
        default_factory=LifecycleConfig
    )
    trace: TraceConfig = dataclasses.field(default_factory=TraceConfig)
    slo: SLOConfig = dataclasses.field(default_factory=SLOConfig)
    autotune: AutotuneConfig = dataclasses.field(
        default_factory=AutotuneConfig
    )
    cache: CacheConfig = dataclasses.field(default_factory=CacheConfig)
    # (mesh: MeshConfig was removed — its data_axis/model_axis index knobs
    # were never read; the mesh axis layout is the hardcoded
    # parallel/mesh.py AXES, and sizing flows through make_mesh(n,
    # model_parallel=...) arguments. TPU503 dead-knob cleanup.)


def _tuple_element_type(owner: type, field: str) -> type:
    """Element type of a ``tuple[X, ...]`` dataclass field, read from the
    annotation — the one place the type is stated, instead of guessing
    from the (possibly empty) current value."""
    import typing

    args = typing.get_args(typing.get_type_hints(owner).get(field, tuple))
    return args[0] if args else str


def _coerce(current: Any, raw: str, inner: type = str) -> Any:
    if isinstance(current, bool):
        return raw.lower() in ("1", "true", "yes", "on")
    if isinstance(current, int):
        return int(raw)
    if isinstance(current, float):
        return float(raw)
    if isinstance(current, tuple):
        body = raw.strip("()[] ")
        if inner is str:
            # String tuples (hpo.architectures) hold comma-containing
            # specs ("hidden_dims=16,embed_dim=8"), so their CLI/env
            # items separate on ';':
            # hpo.architectures='hidden_dims=16;family=bert'.
            return tuple(x.strip() for x in body.split(";") if x.strip())
        return tuple(inner(x) for x in body.split(",") if x.strip())
    return raw


def _apply(config: Config, section: str, field: str, value: Any) -> None:
    sub = getattr(config, section, None)
    if sub is None or not hasattr(sub, field):
        raise KeyError(f"unknown config key {section}.{field}")
    current = getattr(sub, field)
    if isinstance(value, str) and not isinstance(current, str):
        inner = (
            _tuple_element_type(type(sub), field)
            if isinstance(current, tuple)
            else str
        )
        value = _coerce(current, value, inner)
    if isinstance(current, tuple) and isinstance(value, list):
        value = tuple(value)
    setattr(sub, field, value)


def load_config(
    toml_path: str | Path | None = None,
    overrides: list[str] | None = None,
    env: dict[str, str] | None = None,
) -> Config:
    """Build a Config: defaults <- TOML <- env <- CLI overrides."""
    config = Config()
    if toml_path:
        with open(toml_path, "rb") as f:
            doc = tomllib.load(f)
        for section, fields in doc.items():
            for field, value in fields.items():
                _apply(config, section, field, value)
    env = dict(os.environ if env is None else env)
    for key, raw in env.items():
        if not key.startswith("MLOPS_TPU_"):
            continue
        parts = key[len("MLOPS_TPU_") :].lower().split("_", 1)
        if len(parts) != 2:
            warnings.warn(f"ignoring malformed env override {key}", stacklevel=2)
            continue
        section, field = parts
        try:
            _apply(config, section, field, raw)
        except KeyError:
            warnings.warn(f"ignoring unknown env override {key}", stacklevel=2)
    for item in overrides or []:
        key, _, raw = item.partition("=")
        section, _, field = key.strip("-").partition(".")
        _apply(config, section, field, raw)
    return config
