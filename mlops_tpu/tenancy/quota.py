"""Per-tenant admission quota: weighted max-min fair, by construction.

The fairness contract (ROADMAP item 5 / ML-fleet goodput, arXiv
2502.06982): a hot tenant at 10x its share must shed 503s against ITS OWN
quota while every cold tenant's capacity stays reachable. The policy is
weighted max-min with RESERVED FLOORS over a fixed capacity ``C`` — one
governor PER SLOT CLASS of a ring front end's partition (the classes are
separate physical pools, so fairness must hold in each; a partition-wide
governor would let a hot tenant monopolize the small large-slab pool
while under its combined floor). The single-process plane enforces its
share of the same contract STRUCTURALLY instead: each tenant's
micro-batcher gets its own divided slice of the shared executor's
dispatch/fetch bounds (serve/server.py), so a flood queues in the hot
tenant's own batcher and never consumes another tenant's dispatch
capacity — no governor, and therefore no quota-shed 503s, on that plane.

- tenant ``i``'s fractional floor is ``C * w_i / sum(w)``; the HARD
  guarantee is its integer part: admission up to ``int(floor_i)``
  always succeeds while capacity physically exists;
- every admission (floor or borrow alike) must leave capacity for
  every OTHER tenant's unmet INTEGER floor — one rule, no fast path.
  Slots are integral, so reserving the exact fractions would deadlock
  small pools (two tenants over one large slab would each reserve 0.5
  and neither could ever take it), while letting a tenant overshoot
  its fractional floor unchecked would let ``C/ceil(floor)`` flooders
  fill the pool and physically starve a cold tenant whose 1.6-slot
  "reservation" was never actually held back. Integer reservations
  give both properties: the fractional remainders are borrowable
  slack, the integer floors are inviolable, and reservations re-arm
  as holds release. Deterministic reserved shares were chosen over
  work-conserving borrowing on purpose: admitted holds cannot be
  evicted, so lending a silent tenant's floor to a flood would make
  that tenant's burst latency depend on the flood's dispatch time —
  the exact starvation coupling this governor exists to forbid.

The 1-tenant fleet bypasses the governor entirely (``reserved_others``
is vacuously zero and the callers skip construction), which is what
makes the single-tenant degeneration exactly the pre-tenancy admission
check.

Concurrency (tpulint Layer 3): NO LOCKS — every governor instance is
single-owner state: each ring front-end worker owns one governor per
slot class, touched only from that worker's event loop (the same
confinement as `RingClient`'s free lists). Keep it that way rather than
adding locks here.
"""

from __future__ import annotations

# Declared lock-free (tpulint Layer 3 + lockcheck): every instance is
# single-owner, event-loop-confined state. An empty order makes the
# sanitizer's "no locks" observation an asserted contract, not an
# accident.
TPULINT_LOCK_ORDER: dict[str, tuple[str, ...]] = {"QuotaGovernor": ()}


class QuotaGovernor:
    """Admission counters for one capacity pool (one slot class)."""

    __slots__ = ("capacity", "floors", "_reserved", "used")

    def __init__(self, capacity: int, weights: tuple[float, ...]) -> None:
        if capacity < 1:
            raise ValueError(f"quota capacity {capacity} must be >= 1")
        if not weights or any(w <= 0 for w in weights):
            raise ValueError(f"quota weights {weights} must all be > 0")
        total = float(sum(weights))
        self.capacity = int(capacity)
        # Fractional floors are the exported shares; the RESERVED floors
        # are their integer parts (slots are integral — see the module
        # docstring for why neither rounding up nor reserving the exact
        # fractions works on small pools).
        self.floors = tuple(capacity * w / total for w in weights)
        self._reserved = tuple(int(f) for f in self.floors)
        self.used = [0] * len(weights)

    @property
    def total_used(self) -> int:
        return sum(self.used)

    def try_acquire(self, tenant: int) -> str:
        """Admit one request for ``tenant``. Returns one of three
        verdicts, because the caller's shed CONTRACT differs:

        - ``"ok"``: admitted (the caller must `release` later);
        - ``"quota"``: capacity physically exists but this tenant's
          weighted max-min share does not cover it — the caller sheds
          503 against the tenant's OWN quota and owns the per-tenant
          rejection counter (one owner per event: the exported
          mlops_tpu_tenant_quota_shed_total, never a duplicate here);
        - ``"full"``: the pool is physically exhausted — NOT a quota
          event (no per-tenant quota shed is counted): the caller falls
          through to its physical-shed contract (the class/brownout 503
          with its own counters and Retry-After semantics).

        O(T)."""
        used = self.used
        total = sum(used)
        if total >= self.capacity:
            return "full"
        # ONE admission rule, floor and borrow alike: idle capacity
        # minus every OTHER tenant's unmet INTEGER reservation must
        # cover this request. A floor fast-path that skipped this check
        # would let flooders overshoot fractional floors by one slot
        # each and fill the pool — a cold tenant's reservation only
        # exists if every admission actually holds capacity back for
        # it. For integral floors this is exactly "admission under the
        # floor always succeeds"; an under-integer-floor tenant always
        # passes by construction (its own unmet reservation is excluded
        # and every admission preserved the others').
        reserved_others = 0
        for j, floor in enumerate(self._reserved):
            if j != tenant and used[j] < floor:
                reserved_others += floor - used[j]
        if total + 1 + reserved_others <= self.capacity:
            used[tenant] += 1
            return "ok"
        return "quota"

    def release(self, tenant: int) -> None:
        """Return one admitted request's capacity. Defensive floor at
        zero: a release bug must clamp, never let a negative count
        manufacture infinite quota."""
        if self.used[tenant] > 0:
            self.used[tenant] -= 1
