"""Tenant bundle registry: N bundles -> N engines, executables deduped.

The multiplexer's load half (ROADMAP item 5): every tenant gets its OWN
`InferenceEngine` — its own params/monitor-accumulator/temperature refs,
its own exact host-side totals, its own lifecycle tee — but
architecture-identical tenants SHARE one set of compiled executables.
The mechanism is the one `lifecycle/shadow.py` already exploits: since
PR 3 the packed serving programs take params/monitor/temperature as
ARGUMENTS (never closures), so a compiled entry is keyed purely by the
abstract signature the compile cache hashes (model config + state
avals + shape — `compilecache/keys.py`); two tenants whose bundles agree
on that key can run the SAME executable with different params passed per
dispatch. Warmup therefore compiles (or deserializes) each distinct
architecture ONCE and every architecture-twin adopts the donor's exec
table by reference (`InferenceEngine.adopt_executables`) — N tenants at
K distinct architectures pay K warmups, and ``shared_exec_count`` is the
provable sharing the bench/tests pin.

Concurrency (tpulint Layer 3): the registry itself holds NO locks — the
tenant list is immutable after construction and ``warmup`` runs once,
before traffic, on the starting thread. All serving-time synchronization
lives in the engines (whose ``_compile_lock`` is SHARED across an
adoption group, so concurrent novel-shape compiles from twin tenants
serialize on one lock and install into one table).
"""

from __future__ import annotations

import dataclasses
import json
import logging
from typing import Any

from mlops_tpu.config import Config
from mlops_tpu.tenancy.config import TenancyConfig

logger = logging.getLogger("mlops_tpu.tenancy")

# Declared lock-free (tpulint Layer 3 + lockcheck): the tenant list is
# immutable after construction and warmup runs once, pre-traffic, on the
# starting thread. Serving-time synchronization lives in the ENGINES
# (whose _compile_lock is shared across an adoption group).
TPULINT_LOCK_ORDER: dict[str, tuple[str, ...]] = {"TenantRegistry": ()}


def _arch_key(engine: Any) -> str:
    """The executable-sharing identity: model config + the abstract
    signature of the bound serving state (param/monitor avals). This is
    exactly the bundle-dependent material `compilecache/keys.py` hashes
    into the persistent cache key — equal here implies equal cache keys
    for every warmed entry, so adopting the donor's table hands the twin
    the artifacts its own warmup would have produced."""
    import jax

    shapes = jax.tree_util.tree_map(
        lambda x: [list(getattr(x, "shape", ())), str(getattr(x, "dtype", ""))],
        (engine._variables, engine._monitor),
    )
    return json.dumps(
        {
            "model_config": dataclasses.asdict(engine.bundle.model_config),
            "state": jax.tree_util.tree_leaves(shapes),
            "treedef": str(jax.tree_util.tree_structure(shapes)),
        },
        sort_keys=True,
    )


class TenantRegistry:
    """Load every tenant's bundle, build one engine per tenant, and warm
    the fleet with architecture-level executable dedupe. Tenant INDEX is
    the position in ``tenancy.tenants`` — the same index the shm slot
    tag, the quota governor, and the per-tenant telemetry blocks use."""

    def __init__(
        self,
        tenancy: TenancyConfig,
        buckets: tuple[int, ...],
        service_name: str = "credit-default-api",
        enable_grouping: bool = True,
        compile_cache: Any = None,
        warmup_workers: int = 0,
        model_shards: int = 1,
        device_index: int | None = None,
        serve_tier: str = "exact",
        tier_routing: bool = False,
    ) -> None:
        from mlops_tpu.bundle import load_bundle
        from mlops_tpu.serve.engine import InferenceEngine

        self.tenancy = tenancy.validate()
        self.names: tuple[str, ...] = self.tenancy.names
        self.default_index = self.tenancy.default_index
        self.bundles = [
            load_bundle(spec.bundle_dir) for spec in self.tenancy.tenants
        ]
        # ``model_shards`` is fleet-global (ISSUE 13): every tenant's
        # params lay out over the same ('model',) serve mesh, so
        # architecture twins still share executables — the mesh shape is
        # part of the cache key, identical across the fleet, and N
        # tenants × E replicas at K architectures still pay K warmups
        # per replica process (each against the same persistent cache:
        # one replica compiles, the rest deserialize).
        self.engines = [
            InferenceEngine(
                bundle,
                buckets=buckets,
                service_name=service_name,
                enable_grouping=enable_grouping,
                compile_cache=compile_cache,
                warmup_workers=warmup_workers,
                model_shards=model_shards,
                device_index=device_index,
                # Fleet-global like model_shards: per-tenant tier mixing
                # would break architecture-twin executable sharing (the
                # tiers are different program families).
                serve_tier=serve_tier,
                # Fleet-global for the same reason (ISSUE 19): the tier
                # ladder is extra program families, and every tenant of
                # one architecture must warm the same families to keep
                # the executable-dedupe contract.
                tier_routing=tier_routing,
            )
            for bundle in self.bundles
        ]
        # Tenants served through another tenant's compiled entries (the
        # sharing proof the bench's tenants_shared_exec_count reports).
        self.shared_exec_count = 0

    def __len__(self) -> int:
        return len(self.engines)

    @property
    def default_engine(self) -> Any:
        return self.engines[self.default_index]

    @property
    def ready(self) -> bool:
        return all(engine.ready for engine in self.engines)

    def index(self, name: str) -> int:
        return self.names.index(name)

    def warmup(self) -> dict[str, Any]:
        """Warm each DISTINCT architecture once; twins adopt the donor's
        exec table by reference. Returns a per-tenant warmup report."""
        donors: dict[str, tuple[str, Any]] = {}
        report: dict[str, Any] = {}
        for name, engine in zip(self.names, self.engines):
            if not engine.monitor_accumulating:
                # sklearn flavor: the "executable" is a host estimator —
                # nothing to share; each tenant warms its own.
                engine.warmup()
                report[name] = {"mode": "warmed", **engine.warmup_stats}
                continue
            key = _arch_key(engine)
            donor = donors.get(key)
            if donor is None:
                engine.warmup()
                donors[key] = (name, engine)
                report[name] = {"mode": "warmed", **engine.warmup_stats}
            else:
                donor_name, donor_engine = donor
                engine.adopt_executables(donor_engine)
                self.shared_exec_count += 1
                report[name] = dict(engine.warmup_stats)
                logger.info(
                    "tenant %s shares compiled entries with %s "
                    "(identical architecture)", name, donor_name,
                )
        report["shared_exec_count"] = self.shared_exec_count
        return report


def tenant_scoped_config(config: Config, tenant: str) -> Config:
    """A per-tenant view of the global config for the per-tenant
    lifecycle controllers: the SAME knobs, with the controller state root
    namespaced per tenant (``lifecycle.dir/<tenant>``) so reservoirs,
    candidate bundles, and retrain checkpoints can never cross tenants.
    Shallow-replaces only the lifecycle section — every other section is
    shared by reference (read-only at serving time)."""
    from pathlib import Path

    return dataclasses.replace(
        config,
        lifecycle=dataclasses.replace(
            config.lifecycle, dir=str(Path(config.lifecycle.dir) / tenant)
        ),
    )
