"""Tenant routing: the ``x-tenant`` header -> a tenant index, bounded.

Jax-free (the HTTP front ends and the engine-free protocol layer both
import it). The router is immutable after construction — no locks, safe
to share across threads and to inherit across forks.
"""

from __future__ import annotations

from mlops_tpu.tenancy.config import DEFAULT_TENANT, TenancyConfig

# The catch-all Prometheus label for a request naming an unknown tenant
# (the request itself answers 404): arbitrary header text must never
# become an unbounded (and injectable) label value — the same closed-set
# discipline as ServingMetrics.KNOWN_ROUTES.
UNKNOWN_TENANT_LABEL = "<unknown>"

# Declared lock-free (tpulint Layer 3 + lockcheck): immutable after
# construction, shared across threads and inherited across forks.
TPULINT_LOCK_ORDER: dict[str, tuple[str, ...]] = {"TenantRouter": ()}


class TenantRouter:
    """Name <-> index resolution for one plane's tenant fleet."""

    __slots__ = ("names", "default_index", "_index")

    def __init__(
        self, names: tuple[str, ...], default_index: int = 0
    ) -> None:
        if not names:
            names = (DEFAULT_TENANT,)
        self.names = tuple(names)
        self.default_index = int(default_index)
        self._index = {name: i for i, name in enumerate(self.names)}

    @classmethod
    def from_config(cls, tenancy: TenancyConfig) -> "TenantRouter":
        return cls(tenancy.names, tenancy.default_index)

    def resolve(self, raw: str) -> int | None:
        """Tenant index for a request's ``x-tenant`` header value; an
        empty/absent header rides the config-declared default tenant;
        an unknown name returns None (the caller answers 404 — routing a
        stranger to the default tenant would silently bill one tenant's
        quota and monitors for another's traffic)."""
        if not raw:
            return self.default_index
        return self._index.get(raw)

    def label(self, raw: str) -> str:
        """The BOUNDED Prometheus/span label for a header value: the
        tenant's declared name (the default tenant's for untagged
        traffic) or the closed unknown marker."""
        if not raw:
            return self.names[self.default_index]
        if raw in self._index:
            return raw
        return UNKNOWN_TENANT_LABEL

    def bill_label(self, raw: str) -> str:
        """The tenant name whose row a request's METRICS land on —
        always a declared name. Strangers (404s) bill the default
        tenant's row: the ring plane's shm counters have one fixed row
        per declared tenant and nowhere else to put them, so the
        single-process plane folds identically to keep every series
        bit-compatible across planes (spans keep the distinct
        `<unknown>` marker — they are records, not fixed-axis
        counters)."""
        if raw in self._index:
            return raw
        return self.names[self.default_index]
