"""Tenancy configuration: the tenants.toml contract, jax-free.

A multi-tenant serve plane (`mlops-tpu serve --tenants tenants.toml`)
declares its fleet in one TOML file — tenant names, bundle directories,
quota weights, and the default tenant untagged traffic lands on:

    default_tenant = "emea"

    [[tenant]]
    name = "emea"
    bundle_dir = "registry/credit-default/3"
    weight = 2.0

    [[tenant]]
    name = "apac"
    bundle_dir = "registry/credit-default-apac/1"
    # weight defaults to 1.0

Everything here must import without jax (the front-end processes and the
CLI's config layer read it), mirroring `serve/wire.py`'s discipline.
"""

from __future__ import annotations

import dataclasses
from pathlib import Path

try:
    import tomllib
except ModuleNotFoundError:  # Python < 3.11: tomllib landed in 3.11
    import tomli as tomllib  # type: ignore[no-redef]

DEFAULT_TENANT = "default"

# Tenant names become Prometheus label values and span fields: the same
# bounded-charset discipline as request ids (httpcore._REQUEST_ID_RE)
# keeps label-injection text out of the exposition and the JSONL stream.
_NAME_CHARS = frozenset(
    "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789_-"
)


class TenancyConfigError(ValueError):
    """An inconsistent tenant fleet, named at startup (the
    ``ServeConfigError`` discipline applied to the tenancy knobs):
    duplicate names, zero/negative weights, and missing bundle
    directories all fail the rollout with the constraint spelled out."""


@dataclasses.dataclass(frozen=True)
class TenantSpec:
    """One tenant: a name (rides requests as ``x-tenant`` and every
    Prometheus series as the ``tenant`` label), the bundle it serves,
    and its admission weight in the weighted max-min quota."""

    name: str
    bundle_dir: str
    weight: float = 1.0


@dataclasses.dataclass
class TenancyConfig:
    """The fleet: an ordered tuple of tenants (tenant INDEX — the shm
    slot tag, the metrics block row — is the position here, so the order
    is part of the serving contract for one plane's lifetime) plus the
    default tenant untagged requests resolve to."""

    tenants: tuple[TenantSpec, ...] = ()
    default_tenant: str = ""  # empty = the first tenant

    @property
    def names(self) -> tuple[str, ...]:
        return tuple(spec.name for spec in self.tenants)

    @property
    def weights(self) -> tuple[float, ...]:
        return tuple(float(spec.weight) for spec in self.tenants)

    @property
    def default_index(self) -> int:
        if not self.default_tenant:
            return 0
        return self.names.index(self.default_tenant)

    def validate(self, check_bundles: bool = True) -> "TenancyConfig":
        """Reject a broken fleet at startup with every problem named.
        ``check_bundles=False`` skips the on-disk existence check (unit
        tests and config-only tooling validate shapes without bundles)."""
        problems: list[str] = []
        if not self.tenants:
            problems.append("tenancy: at least one [[tenant]] is required")
        seen: set[str] = set()
        for spec in self.tenants:
            if not spec.name:
                problems.append("tenancy: tenant name must be non-empty")
                continue
            if len(spec.name) > 64 or not set(spec.name) <= _NAME_CHARS:
                problems.append(
                    f"tenancy: tenant name {spec.name!r} must be 1-64 chars "
                    "of [A-Za-z0-9_-] (it becomes a Prometheus label value "
                    "and a span field)"
                )
            if spec.name in seen:
                problems.append(
                    f"tenancy: duplicate tenant name {spec.name!r}"
                )
            seen.add(spec.name)
            if not spec.weight > 0:
                problems.append(
                    f"tenancy: tenant {spec.name!r} weight={spec.weight} "
                    "must be > 0 (a zero-weight tenant could never admit a "
                    "request; remove it instead)"
                )
            if not spec.bundle_dir:
                problems.append(
                    f"tenancy: tenant {spec.name!r} has no bundle_dir"
                )
            elif check_bundles and not Path(spec.bundle_dir).is_dir():
                problems.append(
                    f"tenancy: tenant {spec.name!r} bundle_dir="
                    f"{spec.bundle_dir!r} is not a directory"
                )
        if self.default_tenant and self.default_tenant not in seen:
            problems.append(
                f"tenancy: default_tenant={self.default_tenant!r} names no "
                "declared tenant"
            )
        if problems:
            raise TenancyConfigError("; ".join(problems))
        return self


def single_tenant_config(bundle_dir: str) -> TenancyConfig:
    """The degenerate fleet every pre-tenancy deployment is: ONE tenant
    named ``default`` serving the configured bundle — the shape that makes
    single-tenant serving ride the exact multi-tenant code path while
    staying bit-identical to the pre-tenancy plane."""
    return TenancyConfig(
        tenants=(TenantSpec(name=DEFAULT_TENANT, bundle_dir=bundle_dir),),
        default_tenant=DEFAULT_TENANT,
    )


def load_tenants_toml(path: str | Path) -> TenancyConfig:
    """Parse a tenants.toml (shape errors become TenancyConfigError with
    the offending key named; validation is the caller's separate step so
    tooling can load-then-inspect a broken file)."""
    path = Path(path)
    try:
        with open(path, "rb") as f:
            doc = tomllib.load(f)
    except OSError as err:
        raise TenancyConfigError(f"tenancy: cannot read {path}: {err}")
    except tomllib.TOMLDecodeError as err:
        raise TenancyConfigError(f"tenancy: {path} is not valid TOML: {err}")
    # Unknown keys are named at BOTH levels: a misspelled top-level
    # `default_tenant` (e.g. `default-tenant`) would otherwise parse
    # cleanly, fall back to the first tenant, and silently route all
    # untagged production traffic to the wrong model — the exact
    # misrouting the 404-on-unknown-tenant contract exists to prevent.
    unknown_top = set(doc) - {"tenant", "default_tenant"}
    if unknown_top:
        raise TenancyConfigError(
            f"tenancy: {path} has unknown top-level keys "
            f"{sorted(unknown_top)} (expected 'default_tenant' and "
            "[[tenant]] tables)"
        )
    raw_tenants = doc.get("tenant", [])
    if not isinstance(raw_tenants, list):
        raise TenancyConfigError(
            f"tenancy: {path} 'tenant' must be an array of tables "
            "([[tenant]] blocks)"
        )
    specs: list[TenantSpec] = []
    for i, entry in enumerate(raw_tenants):
        if not isinstance(entry, dict):
            raise TenancyConfigError(
                f"tenancy: {path} [[tenant]] #{i} is not a table"
            )
        unknown = set(entry) - {"name", "bundle_dir", "weight"}
        if unknown:
            raise TenancyConfigError(
                f"tenancy: {path} [[tenant]] #{i} has unknown keys "
                f"{sorted(unknown)}"
            )
        try:
            specs.append(
                TenantSpec(
                    name=str(entry.get("name", "")),
                    bundle_dir=str(entry.get("bundle_dir", "")),
                    weight=float(entry.get("weight", 1.0)),
                )
            )
        except (TypeError, ValueError) as err:
            raise TenancyConfigError(
                f"tenancy: {path} [[tenant]] #{i}: {err}"
            )
    return TenancyConfig(
        tenants=tuple(specs),
        default_tenant=str(doc.get("default_tenant", "")),
    )
