"""Multi-tenant model multiplexing: one serve plane, many portfolios.

The subsystem that turns the single-model serving plane into a fleet
(ROADMAP item 5): a bundle registry serving N named tenants from one
engine process — architecture-identical tenants share compiled entries
(params-as-args), each tenant owns its params/monitor/lifecycle — with
tenant-tagged routing, weighted max-min admission quotas, and a
``tenant`` label on every per-tenant Prometheus series and trace span.

Import discipline mirrors ``serve/``: `config`, `quota`, and `router`
are jax-free (front-end processes import them); `registry` pulls the
engine (jax) and is imported lazily here so ``from mlops_tpu.tenancy
import TenantRouter`` stays backend-free.
"""

from mlops_tpu.tenancy.config import (  # noqa: F401
    DEFAULT_TENANT,
    TenancyConfig,
    TenancyConfigError,
    TenantSpec,
    load_tenants_toml,
    single_tenant_config,
)
from mlops_tpu.tenancy.quota import QuotaGovernor  # noqa: F401
from mlops_tpu.tenancy.router import (  # noqa: F401
    UNKNOWN_TENANT_LABEL,
    TenantRouter,
)

_LAZY = {"TenantRegistry", "tenant_scoped_config"}


def __getattr__(name: str):
    if name in _LAZY:
        from mlops_tpu.tenancy import registry

        return getattr(registry, name)
    raise AttributeError(name)
