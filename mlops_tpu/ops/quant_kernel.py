"""Pallas-fused packed predict for the quantized student tier.

The exact tier's packed program (`ops/predict.py make_packed_predict_base`)
is already ONE XLA computation, but XLA still materializes the student
activations, the one-hot tables, and the [B,R] K-S comparison planes in
HBM between fusions. Here the whole per-request body — student forward
(int8 dequant in VMEM), Mahalanobis outlier flags, categorical batch
counts, and the dense masked K-S statistics — is a single hand-written
`pltpu` kernel in the `ops/attention.py` style: operands stream through
VMEM once, int8/bf16 weights stay quantized in HBM, and nothing round-
trips between fusion islands.

Split of labor (shared by kernel AND composite, so they agree bitwise):

- IN the kernel: student logits -> calibrated probabilities, outlier
  flags, per-feature categorical one-hot COUNTS, and the numeric K-S
  STATISTICS (dense masked form — `ops/drift.py ks_small_masked_statistic`
  — for EVERY bucket; the sort-based large-batch form does not lower on
  Mosaic, and the dense form is mathematically identical).
- OUTSIDE (plain jnp, fuses around the pallas_call): the chi-squared and
  Kolmogorov p-values over the tiny [C, max_card] / [M] aggregates,
  drift assembly (``1 - p``), and the accumulator fold — scalar series
  math (whose ``arange`` constants a kernel body cannot capture), not
  worth kernel bytes.

Capability gate: the kernel is the TPU path. Off-TPU (this CPU container)
the default route is the jnp COMPOSITE — the same `_fused_core` called
directly, which is also the bit-parity reference; ``use_kernel=True``
forces the kernel (interpret mode off-TPU) so the parity tests exercise
the pallas_call pipeline everywhere. The packed calling convention,
layout (`packed_layout`), and accumulator fold are identical to the
exact tier, so `serve/engine.py` runs this tier through the SAME exec
tables, buckets, and swap/rollback machinery.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from mlops_tpu.monitor.state import (
    MonitorAccumulator,
    MonitorState,
    fold_accumulator,
    fold_accumulator_grouped,
)
from mlops_tpu.ops.drift import (
    _kolmogorov_sf,
    chi2_two_sample,
    ks_small_masked_statistic,
)
from mlops_tpu.ops.quant import dequantize_dense, one_hot_2d

# Same compat alias as ops/attention.py (jax >= 0.5 renamed the class).
_CompilerParams = getattr(
    pltpu, "CompilerParams", getattr(pltpu, "TPUCompilerParams", None)
)
if _CompilerParams is None:
    raise ImportError(
        "jax.experimental.pallas.tpu exposes neither CompilerParams nor "
        "TPUCompilerParams — update the compat alias in ops/quant_kernel.py "
        "for this jax version"
    )

# Largest row bucket the kernel serves. 256 is the top serve bucket; the
# dense K-S working set at B=256 (a [256, 2048] f32 comparison plane per
# numeric feature, features walked sequentially) stays a few MB — well
# inside VMEM.
QUANT_KERNEL_MAX_ROWS = 256


def quant_kernel_available() -> bool:
    """Capability gate: Mosaic lowering exists on the TPU backend only.
    Everything else (this CPU container included) runs the jnp composite
    by default and the kernel only under interpret-mode force."""
    return jax.default_backend() == "tpu"


def _route_kernel(use_kernel: bool | None, rows: int) -> tuple[bool, bool]:
    """-> (run_pallas_call, interpret). ``None`` auto-routes: kernel on
    TPU for supported buckets, composite otherwise. ``True`` forces the
    pallas_call anywhere (interpret off-TPU — the parity tests);
    ``False`` forces the composite."""
    if use_kernel is None:
        use_kernel = quant_kernel_available() and rows <= QUANT_KERNEL_MAX_ROWS
    return use_kernel, jax.default_backend() != "tpu"


def _fused_core(
    embed,  # bf16 [C, K, E]
    w1_q,  # int8 [Din, H]
    w1_s_row,  # f32 (1, H)
    b1_row,  # f32 (1, H)
    w2_q_col,  # int8 (H, 1)
    w2_s,  # f32 (1, 1)
    b2,  # f32 (1, 1)
    ref_sorted,  # f32 [M, R]
    ref_cdf,  # f32 [M, R]
    mean_row,  # f32 (1, M)
    precision,  # f32 [M, M]
    threshold,  # f32 (1, 1)
    temperature,  # f32 (1, 1)
    cat_ids,  # int32 [B, C]
    numeric,  # f32 [B, M]
    maskf_row,  # f32 (1, B)
):
    """The ONE fused-body definition — executed verbatim by the Pallas
    kernel (on refs' loaded values) and by the jnp composite (on arrays),
    which is what makes kernel-vs-composite parity structural rather than
    aspirational. Everything stays 2-D (Mosaic's preferred rank).

    Returns ``(preds (1,B), flags (1,B), cat_counts [C,K], ks_stat (1,M))``.
    """
    c, k = embed.shape[0], embed.shape[1]
    m = numeric.shape[1]
    numeric = numeric.astype(jnp.float32)
    # Transposes, not reshapes, for the (1,B)<->(B,1) flips: at B=1 a
    # same-shape jnp.reshape is elided from the jaxpr, which would make
    # bucket 1 a different primitive sequence than the rest of its
    # declared TPU304 family (analysis/entrypoints.py).
    maskf_col = maskf_row.T  # (B, 1)
    mask_bool = maskf_row[0] > 0  # [B]

    # Student forward: one-hot embed matmuls (the one-hot doubles as the
    # categorical drift count table), int8 dequant, dense/relu/dense.
    feats = []
    counts = []
    for j in range(c):
        oh = one_hot_2d(cat_ids[:, j], k)  # [B, K]
        feats.append(oh @ embed[j].astype(jnp.float32))  # [B, E]
        counts.append((oh * maskf_col).sum(axis=0, keepdims=True))  # (1, K)
    x = jnp.concatenate(feats + [numeric], axis=1)  # [B, Din]
    cat_counts = jnp.concatenate(counts, axis=0)  # [C, K]

    w1 = dequantize_dense(w1_q, w1_s_row[0])  # f32 [Din, H]
    h = jnp.maximum(x @ w1 + b1_row, 0.0)  # [B, H]
    w2_col = w2_q_col.astype(jnp.float32) * w2_s  # (H, 1)
    logits_col = h @ w2_col + b2  # (B, 1)
    preds = jax.nn.sigmoid(logits_col / temperature).T  # (1, B)

    # Mahalanobis outlier flags (explicit 2-D form of ops/outlier's
    # einsum; mask-zeroed like `monitor.state.outlier_flags`).
    diff = numeric - mean_row  # [B, M]
    d2_col = ((diff @ precision) * diff).sum(axis=1, keepdims=True)  # (B, 1)
    flags = (
        (d2_col > threshold).astype(jnp.float32).T * maskf_row
    )  # (1, B)

    # Numeric drift: dense masked K-S statistics per feature, features
    # walked sequentially so only one [B, R] comparison plane is live at
    # a time (the survival function runs outside the kernel).
    ks_stats = []
    for j in range(m):
        stat = ks_small_masked_statistic(
            ref_sorted[j], ref_cdf[j], numeric[:, j], mask_bool
        )
        ks_stats.append(stat.reshape(1, 1))
    ks_stat = jnp.concatenate(ks_stats, axis=1)  # (1, M)

    return preds, flags, cat_counts, ks_stat


def _fused_kernel(
    embed_ref, w1q_ref, w1s_ref, b1_ref, w2q_ref, w2s_ref, b2_ref,
    refsort_ref, refcdf_ref, mean_ref, prec_ref, thr_ref, temp_ref,
    cat_ref, num_ref, maskf_ref,
    preds_ref, flags_ref, counts_ref, ksp_ref,
):
    """Whole-problem kernel (grid=()): serve buckets fit VMEM outright, so
    there is no tiling loop — the win is fusion (one pass, no HBM
    round-trips between the student, the outlier score, and the drift
    planes), not streaming."""
    preds, flags, cat_counts, ks_stat = _fused_core(
        embed_ref[...], w1q_ref[...], w1s_ref[...], b1_ref[...],
        w2q_ref[...], w2s_ref[0, 0], b2_ref[0, 0],
        refsort_ref[...], refcdf_ref[...], mean_ref[...], prec_ref[...],
        thr_ref[0, 0], temp_ref[0, 0],
        cat_ref[...], num_ref[...], maskf_ref[...],
    )
    preds_ref[...] = preds
    flags_ref[...] = flags
    counts_ref[...] = cat_counts
    ksp_ref[...] = ks_stat


def quant_fused(
    qparams: dict[str, Any],
    monitor: MonitorState,
    temperature: jnp.ndarray,
    cat_ids: jnp.ndarray,
    numeric: jnp.ndarray,
    mask: jnp.ndarray,
    use_kernel: bool | None = None,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Fused quant predict for one padded request:
    ``(preds [B], flags [B], drift [D])`` — the same triple the exact
    tier's packed body produces, with the heavy body routed through the
    Pallas kernel or its jnp composite (`_route_kernel`)."""
    b = cat_ids.shape[0]
    maskf_row = mask.astype(jnp.float32)[None, :]
    temp_11 = jnp.asarray(temperature, jnp.float32).reshape(1, 1)
    core_args = (
        qparams["embed"], qparams["w1_q"],
        qparams["w1_s"][None, :], qparams["b1"][None, :],
        qparams["w2_q"][:, None],
        qparams["w2_s"].reshape(1, 1), qparams["b2"].reshape(1, 1),
        monitor.num_ref_sorted, monitor.num_ref_cdf,
        monitor.out_mean[None, :], monitor.out_precision,
        monitor.out_threshold.reshape(1, 1), temp_11,
        cat_ids, numeric, maskf_row,
    )
    run_kernel, interpret = _route_kernel(use_kernel, b)
    if run_kernel:
        c, k = qparams["embed"].shape[0], qparams["embed"].shape[1]
        m = numeric.shape[1]
        # Scalars ride SMEM; every tensor operand is a whole-array VMEM
        # block (grid=() — no index maps).
        smem = {5, 6, 11, 12}  # w2_s, b2, threshold, temperature
        in_specs = [
            pl.BlockSpec(
                memory_space=pltpu.SMEM if i in smem else pltpu.VMEM
            )
            for i in range(len(core_args))
        ]
        preds, flags, cat_counts, ks_stat = pl.pallas_call(
            _fused_kernel,
            in_specs=in_specs,
            out_specs=[
                pl.BlockSpec(memory_space=pltpu.VMEM) for _ in range(4)
            ],
            out_shape=[
                jax.ShapeDtypeStruct((1, b), jnp.float32),
                jax.ShapeDtypeStruct((1, b), jnp.float32),
                jax.ShapeDtypeStruct((c, k), jnp.float32),
                jax.ShapeDtypeStruct((1, m), jnp.float32),
            ],
            interpret=interpret,
        )(*core_args)
    else:
        preds, flags, cat_counts, ks_stat = _fused_core(*core_args)

    # P-value assembly + drift: tiny scalar math on [C,K]/[M] aggregates,
    # shared by both routes (same `1 - p` order as
    # `monitor.state.drift_scores`: cat then num). The Kolmogorov sf here
    # is exactly `ks_two_sample_small_masked`'s tail, applied outside the
    # kernel because its series constants can't live in one.
    _, cat_p = jax.vmap(chi2_two_sample)(monitor.cat_ref_counts, cat_counts)
    r = monitor.num_ref_sorted.shape[1]
    n_valid = jnp.maximum(mask.astype(jnp.float32).sum(), 1.0)
    en = jnp.sqrt(r * n_valid / (r + n_valid))
    ks_p = jax.vmap(
        lambda s: _kolmogorov_sf((en + 0.12 + 0.11 / en) * s)
    )(ks_stat[0])
    drift = 1.0 - jnp.concatenate([cat_p, ks_p])
    return preds[0], flags[0], drift


def make_quant_packed_base(use_kernel: bool | None = None) -> Callable:
    """Quant twin of `ops/predict.py make_packed_predict_base`: identical
    7-argument cacheable signature and ``f32[2B + D]`` packed layout
    (`packed_layout` slices it), with ``variables`` = the quant param
    dict. The engine serves it through the same exec tables, donation
    gate, and fetch paths as the exact tier."""

    def predict(
        qparams: dict[str, Any],
        monitor: MonitorState,
        acc: MonitorAccumulator,
        temperature: jnp.ndarray,
        cat_ids: jnp.ndarray,
        numeric: jnp.ndarray,
        mask: jnp.ndarray,
    ):
        preds, flags, drift = quant_fused(
            qparams, monitor, temperature, cat_ids, numeric, mask, use_kernel
        )
        packed = jnp.concatenate([preds, flags, drift])
        return packed, fold_accumulator(acc, flags, drift, mask)

    return predict


def make_quant_grouped_base(use_kernel: bool | None = None) -> Callable:
    """Quant twin of `make_packed_grouped_base`: ``f32[S, 2R+D]`` packed
    group output, per-request drift over each slot's OWN rows (the vmap
    batches the pallas_call over slots), accumulator folded outside the
    vmap."""

    def single(qparams, monitor, temperature, cat_ids, numeric, mask):
        return quant_fused(
            qparams, monitor, temperature, cat_ids, numeric, mask, use_kernel
        )

    def grouped(
        qparams: dict[str, Any],
        monitor: MonitorState,
        acc: MonitorAccumulator,
        temperature: jnp.ndarray,
        cat_ids: jnp.ndarray,
        numeric: jnp.ndarray,
        mask: jnp.ndarray,
    ):
        preds, flags, drift = jax.vmap(
            single, in_axes=(None, None, None, 0, 0, 0)
        )(qparams, monitor, temperature, cat_ids, numeric, mask)
        packed = jnp.concatenate([preds, flags, drift], axis=1)
        return packed, fold_accumulator_grouped(acc, flags, drift, mask)

    return grouped
