"""Attention kernels: Pallas flash attention + XLA reference path.

The reference has no attention anywhere (sklearn trees only); attention
enters this framework through the FT-Transformer (BASELINE.json config 3)
and the BERT stretch config (config 5). Two execution paths:

- ``reference_attention`` — plain jnp einsum softmax; what XLA already fuses
  well at short sequence (FT-Transformer runs at seq=24 where this is
  near-roofline).
- ``flash_attention`` — a Pallas TPU kernel with online softmax: Q/K/V are
  streamed through VMEM in (block_q, block_k) tiles, scores never materialize
  in HBM, so activation memory is O(S·D) instead of O(S²). This is the path
  for BERT-length sequences (128–512+) and the building block the ring
  variant (``mlops_tpu.parallel.ring_attention``) reuses per-shard.

Backward: ``flash_attention`` carries a custom VJP whose forward runs the
Pallas kernel and whose backward rematerializes dense attention with XLA ops
(O(S²) only inside the backward, standard remat trade). Training at BERT
scale fits comfortably; the serving hot path is forward-only.

Layout convention matches Flax: ``[batch, seq, heads, head_dim]``.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def reference_attention(
    q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray, scale: float | None = None
) -> jnp.ndarray:
    """Dense softmax attention, [B,S,H,D] -> [B,S,H,D]; fp32 softmax."""
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v)


# --------------------------------------------------------------------------
# Pallas kernel
# --------------------------------------------------------------------------


def _flash_kernel(
    q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref, *, scale, kv_len, block_k
):
    """One (batch*head, q_block) tile; grid axis 2 walks k blocks.

    Online softmax: running max ``m``, normalizer ``l`` and unnormalized
    accumulator ``acc`` live in VMEM scratch across the k-block loop; the
    output tile is written once on the final k block.
    """
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    q = q_ref[0]  # [block_q, d]
    k = k_ref[0]  # [block_k, d]
    v = v_ref[0]

    s = jax.lax.dot_general(
        q,
        k,
        (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale  # [block_q, block_k]

    # Mask key positions beyond the true sequence length (the wrapper pads
    # seq up to a block multiple; padded keys must not receive probability).
    col = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    s = jnp.where(col < kv_len, s, NEG_INF)

    m_prev = m_ref[:, :1]  # [block_q, 1]
    l_prev = l_ref[:, :1]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
    p = jnp.exp(s - m_new)
    alpha = jnp.exp(m_prev - m_new)
    l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
    acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
        p.astype(v.dtype),
        v,
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
    l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(ki == nk - 1)
    def _finalize():
        o_ref[0] = (acc_ref[:] / l_ref[:, :1]).astype(o_ref.dtype)


def _flash_forward(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    scale: float,
    block_q: int,
    block_k: int,
    interpret: bool,
) -> jnp.ndarray:
    b, s_q, h, d = q.shape
    s_kv = k.shape[1]

    # [B,S,H,D] -> [B*H, S, D]: fold batch and heads into one parallel axis.
    def fold(x):
        return x.transpose(0, 2, 1, 3).reshape(b * h, x.shape[1], d)

    qf, kf, vf = fold(q), fold(k), fold(v)

    block_q = min(block_q, max(8, s_q))
    block_k = min(block_k, max(8, s_kv))
    pad_q = (-s_q) % block_q
    pad_k = (-s_kv) % block_k
    if pad_q:
        qf = jnp.pad(qf, ((0, 0), (0, pad_q), (0, 0)))
    if pad_k:
        kf = jnp.pad(kf, ((0, 0), (0, pad_k), (0, 0)))
        vf = jnp.pad(vf, ((0, 0), (0, pad_k), (0, 0)))
    nq = qf.shape[1] // block_q
    nk = kf.shape[1] // block_k

    grid = (b * h, nq, nk)
    kernel = functools.partial(
        _flash_kernel, scale=scale, kv_len=s_kv, block_k=block_k
    )
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, qi, ki: (bh, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, qi, ki: (bh, ki, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct(qf.shape, q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),  # running max m
            pltpu.VMEM((block_q, 128), jnp.float32),  # running normalizer l
            pltpu.VMEM((block_q, d), jnp.float32),  # unnormalized accumulator
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=(
                pltpu.PARALLEL,
                pltpu.PARALLEL,
                pltpu.ARBITRARY,  # k-block loop carries scratch state
            ),
        ),
        interpret=interpret,
    )(qf, kf, vf)

    out = out[:, :s_q].reshape(b, h, s_q, d).transpose(0, 2, 1, 3)
    return out


def _use_interpret() -> bool:
    """Pallas TPU kernels run in interpret mode on CPU (tests, fake mesh)."""
    return jax.default_backend() != "tpu"


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash_attention(q, k, v, scale, block_q, block_k):
    return _flash_forward(q, k, v, scale, block_q, block_k, _use_interpret())


def _flash_fwd(q, k, v, scale, block_q, block_k):
    out = _flash_forward(q, k, v, scale, block_q, block_k, _use_interpret())
    return out, (q, k, v)


def _flash_bwd(scale, block_q, block_k, residuals, g):
    q, k, v = residuals
    _, vjp = jax.vjp(lambda q, k, v: reference_attention(q, k, v, scale), q, k, v)
    return vjp(g)


_flash_attention.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    scale: float | None = None,
    block_q: int = 1024,
    block_k: int = 1024,
) -> jnp.ndarray:
    """Fused flash attention, [B,S,H,D] -> [B,S,H,D] (self- or cross-).

    Default blocks are 1024x1024 (clamped to the sequence): measured on
    v5e, 128x128 tiles leave the kernel grid-overhead-bound (2.2 ms at
    B2xH8xS2048xD64 — 3x SLOWER than XLA's fused dense) while 1024-blocks
    run the same shape in 0.16 ms and S=8192 in 3.9 ms vs 449 ms dense —
    the f32 score tile (1024x1024x4 B = 4 MB) still fits VMEM comfortably.
    """
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    return _flash_attention(q, k, v, scale, block_q, block_k)


# Below this sequence length the O(S²) score matrix fits trivially in VMEM
# and XLA's fused attention beats kernel-launch bookkeeping; above it the
# streaming kernel wins on HBM traffic.
FLASH_MIN_SEQ = 128


def attend(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    scale: float | None = None,
    use_flash: bool | None = None,
) -> jnp.ndarray:
    """Dispatch: flash kernel for long sequences, XLA einsum for short."""
    if use_flash is None:
        use_flash = q.shape[1] >= FLASH_MIN_SEQ
    if use_flash:
        return flash_attention(q, k, v, scale)
    return reference_attention(q, k, v, scale)
