"""Attention kernels: Pallas flash attention + XLA reference path.

The reference has no attention anywhere (sklearn trees only); attention
enters this framework through the FT-Transformer (BASELINE.json config 3)
and the BERT stretch config (config 5). Two execution paths:

- ``reference_attention`` — plain jnp einsum softmax; what XLA already fuses
  well at short sequence (FT-Transformer runs at seq=24 where this is
  near-roofline).
- ``flash_attention`` — a Pallas TPU kernel with online softmax: Q/K/V are
  streamed through VMEM in (block_q, block_k) tiles, scores never materialize
  in HBM, so activation memory is O(S·D) instead of O(S²). This is the path
  for BERT-length sequences (128–512+) and the building block the ring
  variant (``mlops_tpu.parallel.ring_attention``) reuses per-shard.

Backward: ``flash_attention`` carries a custom VJP whose backward is TWO
Pallas kernels (VERDICT r4 #5, the FlashAttention-2 recipe): the forward
additionally emits the per-row logsumexp ``L = m + log l``; the backward
recomputes the probability tiles ``p = exp(s - L)`` from it — one kernel
walks k-blocks accumulating dq, one walks q-blocks accumulating dk/dv —
so the backward, like the forward, never materializes the O(S²) score
matrix in HBM. (Round 4 rematerialized DENSE attention in XLA here,
which walled training at the 2k–8k lengths the forward was tuned for.)

Layout convention matches Flax: ``[batch, seq, heads, head_dim]``.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# jax >= 0.5 renamed TPUCompilerParams -> CompilerParams; support both so
# the kernels run on the container's pinned jax as well as current ones.
# Fail HERE, by name, if a future rename breaks both — not as an opaque
# "'NoneType' object is not callable" at the first kernel build.
_CompilerParams = getattr(
    pltpu, "CompilerParams", getattr(pltpu, "TPUCompilerParams", None)
)
if _CompilerParams is None:
    raise ImportError(
        "jax.experimental.pallas.tpu exposes neither CompilerParams nor "
        "TPUCompilerParams — update the compat alias in ops/attention.py "
        "for this jax version"
    )

NEG_INF = -1e30


def reference_attention(
    q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray, scale: float | None = None
) -> jnp.ndarray:
    """Dense softmax attention, [B,S,H,D] -> [B,S,H,D]; fp32 softmax."""
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v)


# --------------------------------------------------------------------------
# Pallas kernel
# --------------------------------------------------------------------------


def _flash_kernel(
    q_ref, k_ref, v_ref, o_ref, lse_ref, m_ref, l_ref, acc_ref,
    *, scale, kv_len, block_k,
):
    """One (batch*head, q_block) tile; grid axis 2 walks k blocks.

    Online softmax: running max ``m``, normalizer ``l`` and unnormalized
    accumulator ``acc`` live in VMEM scratch across the k-block loop; the
    output tile is written once on the final k block.
    """
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    q = q_ref[0]  # [block_q, d]
    k = k_ref[0]  # [block_k, d]
    v = v_ref[0]

    s = jax.lax.dot_general(
        q,
        k,
        (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale  # [block_q, block_k]

    # Mask key positions beyond the true sequence length (the wrapper pads
    # seq up to a block multiple; padded keys must not receive probability).
    col = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    s = jnp.where(col < kv_len, s, NEG_INF)

    m_prev = m_ref[:, :1]  # [block_q, 1]
    l_prev = l_ref[:, :1]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
    p = jnp.exp(s - m_new)
    alpha = jnp.exp(m_prev - m_new)
    l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
    acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
        p.astype(v.dtype),
        v,
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
    l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(ki == nk - 1)
    def _finalize():
        o_ref[0] = (acc_ref[:] / l_ref[:, :1]).astype(o_ref.dtype)
        # Per-row logsumexp for the Pallas backward: p = exp(s - L)
        # reconstructs the probability tile without storing it. l == 0
        # cannot happen for real rows (kv_len >= 1 unmasked key), but
        # guard the log anyway — padded-q rows still sum real keys.
        lse_ref[0] = (
            m_ref[:, 0] + jnp.log(jnp.maximum(l_ref[:, 0], 1e-30))
        )


def _fold_heads(x: jnp.ndarray) -> jnp.ndarray:
    """[B,S,H,D] -> [B*H, S, D]: batch and heads fold into one parallel
    grid axis."""
    b, s, h, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b * h, s, d)


def _pad_seq(x: jnp.ndarray, block: int) -> jnp.ndarray:
    pad = (-x.shape[1]) % block
    return jnp.pad(x, ((0, 0), (0, pad), (0, 0))) if pad else x


def _flash_forward(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    scale: float,
    block_q: int,
    block_k: int,
    interpret: bool,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Returns ``(out [B,S,H,D], lse [B*H, padded_Sq])`` — the logsumexp
    stays in the folded/padded layout the backward kernels consume."""
    b, s_q, h, d = q.shape
    s_kv = k.shape[1]

    block_q = min(block_q, max(8, s_q))
    block_k = min(block_k, max(8, s_kv))
    qf = _pad_seq(_fold_heads(q), block_q)
    kf = _pad_seq(_fold_heads(k), block_k)
    vf = _pad_seq(_fold_heads(v), block_k)
    nq = qf.shape[1] // block_q
    nk = kf.shape[1] // block_k

    grid = (b * h, nq, nk)
    kernel = functools.partial(
        _flash_kernel, scale=scale, kv_len=s_kv, block_k=block_k
    )
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, qi, ki: (bh, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, qi, ki: (bh, ki, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, block_q), lambda bh, qi, ki: (bh, qi)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(qf.shape, q.dtype),
            jax.ShapeDtypeStruct((b * h, qf.shape[1]), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),  # running max m
            pltpu.VMEM((block_q, 128), jnp.float32),  # running normalizer l
            pltpu.VMEM((block_q, d), jnp.float32),  # unnormalized accumulator
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=(
                pltpu.PARALLEL,
                pltpu.PARALLEL,
                pltpu.ARBITRARY,  # k-block loop carries scratch state
            ),
        ),
        interpret=interpret,
    )(qf, kf, vf)

    out = out[:, :s_q].reshape(b, h, s_q, d).transpose(0, 2, 1, 3)
    return out, lse


def _flash_bwd_dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, dq_acc,
    *, scale, kv_len, block_k,
):
    """dq tile: grid (B*H, q blocks, k blocks); the k loop accumulates
    ``dq_i = scale * sum_j p_ij (dp_ij - delta_i) k_j`` in VMEM scratch,
    with ``p`` recomputed from the stored logsumexp."""
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    q = q_ref[0]  # [bq, d]
    k = k_ref[0]  # [bk, d]
    v = v_ref[0]
    do = do_ref[0]  # [bq, d]
    lse = lse_ref[0]  # [bq]
    delta = delta_ref[0]  # [bq]

    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale  # [bq, bk]
    col = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    s = jnp.where(col < kv_len, s, NEG_INF)
    p = jnp.exp(s - lse[:, None])  # [bq, bk]
    dp = jax.lax.dot_general(
        do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )  # [bq, bk]
    ds = p * (dp - delta[:, None]) * scale
    dq_acc[:] += jax.lax.dot_general(
        ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(ki == nk - 1)
    def _finalize():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(
    k_ref, v_ref, q_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
    dk_acc, dv_acc, *, scale, kv_len, block_k,
):
    """dk/dv tiles: grid (B*H, k blocks, q blocks); the q loop accumulates
    ``dv_j = sum_i p_ij do_i`` and
    ``dk_j = scale * sum_i p_ij (dp_ij - delta_i) q_i``. Probabilities
    recompute transposed (``[bk, bq]``) from the same logsumexp."""
    qi = pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    k = k_ref[0]  # [bk, d]
    v = v_ref[0]
    q = q_ref[0]  # [bq, d]
    do = do_ref[0]
    lse = lse_ref[0]  # [bq]
    delta = delta_ref[0]

    # s_t[j, i] = k_j . q_i * scale (the transposed score tile). The
    # kv_len mask lands on ROWS here; masked rows only touch dk/dv tiles
    # that are sliced off after the call, but masking keeps them zero so
    # the f32 accumulator never sees garbage.
    s_t = jax.lax.dot_general(
        k, q, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale  # [bk, bq]
    row = (
        pl.program_id(1) * k.shape[0]
        + jax.lax.broadcasted_iota(jnp.int32, s_t.shape, 0)
    )
    s_t = jnp.where(row < kv_len, s_t, NEG_INF)
    p_t = jnp.exp(s_t - lse[None, :])  # [bk, bq]
    dv_acc[:] += jax.lax.dot_general(
        p_t.astype(do.dtype), do, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    dp_t = jax.lax.dot_general(
        v, do, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )  # [bk, bq]
    ds_t = p_t * (dp_t - delta[None, :]) * scale
    dk_acc[:] += jax.lax.dot_general(
        ds_t.astype(q.dtype), q, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(qi == nq - 1)
    def _finalize():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _flash_backward(
    q, k, v, out, lse, g, scale, block_q, block_k, interpret
):
    """Assemble dq/dk/dv from the two Pallas kernels. ``lse`` arrives in
    the folded/padded ``[B*H, padded_Sq]`` layout the forward produced."""
    b, s_q, h, d = q.shape
    s_kv = k.shape[1]
    block_q = min(block_q, max(8, s_q))
    block_k = min(block_k, max(8, s_kv))

    qf = _pad_seq(_fold_heads(q), block_q)
    kf = _pad_seq(_fold_heads(k), block_k)
    vf = _pad_seq(_fold_heads(v), block_k)
    dof = _pad_seq(_fold_heads(g), block_q)
    # delta_i = do_i . out_i (rowsum, [B*H, Sq]) — the softmax-jacobian
    # correction term; tiny, so XLA computes it outside the kernels.
    delta = jnp.sum(
        _fold_heads(g).astype(jnp.float32) * _fold_heads(out).astype(jnp.float32),
        axis=-1,
    )
    pad_q = (-s_q) % block_q
    if pad_q:
        delta = jnp.pad(delta, ((0, 0), (0, pad_q)))

    bh = b * h
    nq = qf.shape[1] // block_q
    nk = kf.shape[1] // block_k
    common = dict(scale=scale, kv_len=s_kv, block_k=block_k)
    qspec = pl.BlockSpec((1, block_q, d), lambda bhi, qi, ki: (bhi, qi, 0))
    kspec = pl.BlockSpec((1, block_k, d), lambda bhi, qi, ki: (bhi, ki, 0))
    rowspec = pl.BlockSpec((1, block_q), lambda bhi, qi, ki: (bhi, qi))

    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, **common),
        grid=(bh, nq, nk),
        in_specs=[qspec, kspec, kspec, qspec, rowspec, rowspec],
        out_specs=qspec,
        out_shape=jax.ShapeDtypeStruct(qf.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=(pltpu.PARALLEL, pltpu.PARALLEL, pltpu.ARBITRARY),
        ),
        interpret=interpret,
    )(qf, kf, vf, dof, lse, delta)

    # dk/dv walk the grid transposed: axis 1 = k blocks, axis 2 = q loop.
    kspec_t = pl.BlockSpec((1, block_k, d), lambda bhi, ki, qi: (bhi, ki, 0))
    qspec_t = pl.BlockSpec((1, block_q, d), lambda bhi, ki, qi: (bhi, qi, 0))
    rowspec_t = pl.BlockSpec((1, block_q), lambda bhi, ki, qi: (bhi, qi))
    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, **common),
        grid=(bh, nk, nq),
        in_specs=[kspec_t, kspec_t, qspec_t, qspec_t, rowspec_t, rowspec_t],
        out_specs=[kspec_t, kspec_t],
        out_shape=[
            jax.ShapeDtypeStruct(kf.shape, k.dtype),
            jax.ShapeDtypeStruct(vf.shape, v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=(pltpu.PARALLEL, pltpu.PARALLEL, pltpu.ARBITRARY),
        ),
        interpret=interpret,
    )(kf, vf, qf, dof, lse, delta)

    def unfold(x, s):
        return x[:, :s].reshape(b, h, s, d).transpose(0, 2, 1, 3)

    return unfold(dq, s_q), unfold(dk, s_kv), unfold(dv, s_kv)


def _use_interpret() -> bool:
    """Pallas TPU kernels run in interpret mode on CPU (tests, fake mesh)."""
    return jax.default_backend() != "tpu"


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash_attention(q, k, v, scale, block_q, block_k):
    out, _ = _flash_forward(q, k, v, scale, block_q, block_k, _use_interpret())
    return out


def _flash_fwd(q, k, v, scale, block_q, block_k):
    out, lse = _flash_forward(
        q, k, v, scale, block_q, block_k, _use_interpret()
    )
    return out, (q, k, v, out, lse)


def _flash_bwd(scale, block_q, block_k, residuals, g):
    q, k, v, out, lse = residuals
    return _flash_backward(
        q, k, v, out, lse, g, scale, block_q, block_k, _use_interpret()
    )


_flash_attention.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    scale: float | None = None,
    block_q: int = 1024,
    block_k: int = 1024,
) -> jnp.ndarray:
    """Fused flash attention, [B,S,H,D] -> [B,S,H,D] (self- or cross-).

    Default blocks are 1024x1024 (clamped to the sequence): measured on
    v5e, 128x128 tiles leave the kernel grid-overhead-bound (2.2 ms at
    B2xH8xS2048xD64 — 3x SLOWER than XLA's fused dense) while 1024-blocks
    run the same shape in 0.16 ms and S=8192 in 3.9 ms vs 449 ms dense —
    the f32 score tile (1024x1024x4 B = 4 MB) still fits VMEM comfortably.
    """
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    return _flash_attention(q, k, v, scale, block_q, block_k)


# Below this sequence length the O(S²) score matrix fits trivially in VMEM
# and XLA's fused attention beats kernel-launch bookkeeping; above it the
# streaming kernel wins on HBM traffic.
FLASH_MIN_SEQ = 128


def attend(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    scale: float | None = None,
    use_flash: bool | None = None,
) -> jnp.ndarray:
    """Dispatch: flash kernel for long sequences ON TPU, XLA einsum
    otherwise. The backend gate matters for product paths: off-TPU the
    Pallas kernels run in INTERPRET mode (orders of magnitude slower than
    XLA's fused dense attention), so a CPU-fallback doc-model run must
    not auto-route into them — and with the round-5 Pallas backward that
    would now cover training too. ``use_flash=True`` still forces the
    kernel anywhere (the equivalence tests exercise it on CPU)."""
    if use_flash is None:
        use_flash = (
            q.shape[1] >= FLASH_MIN_SEQ and jax.default_backend() == "tpu"
        )
    if use_flash:
        return flash_attention(q, k, v, scale)
    return reference_attention(q, k, v, scale)
