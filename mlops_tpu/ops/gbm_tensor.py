"""Hummingbird-style tensorization of the HistGBM baseline (ISSUE 19).

The gbm family (`models/gbm.py SklearnBaseline`) was the one `models/`
family outside the packed serving contract: trees scored on host CPU
through `make_hybrid_predict_fn` while every Flax family (and the quant
student) rode the one packed 7-arg cacheable program. Hummingbird
(PAPERS.md, arxiv 2010.04804) shows tree ensembles compile to pure
tensor programs; this module does that for the fitted
``HistGradientBoostingClassifier``:

- ``extract_gbm`` flattens the fitted ensemble into padded per-tree node
  arrays (value / threshold / child pointers / leaf + categorical flags)
  plus a per-node 256-entry categorical go-left LUT built from the
  estimator's raw category bitsets — pure data, shaped ``[T, Nmax]``.
- ``make_gbm_packed_base`` / ``make_gbm_grouped_base`` are the packed
  program builders in the SAME cacheable 7-arg form as
  `ops/predict.py make_packed_predict_base`: the tree tensors are the
  ``variables`` ARGUMENT (never a closure), the monitors fuse alongside,
  one flat f32 output buffer + the device monitor accumulator.

Traversal is a depth-many static gather loop: each step gathers every
tree's current node fields at once (``[B, T]`` advanced indexing),
resolves the split (numeric ``x <= threshold``; categorical via the LUT
with sklearn's unknown-category -> missing_go_to_left rule; NaN ->
missing side), and advances the node index — leaves self-loop, so a
ragged ensemble needs no per-tree control flow.

BIT PARITY: sklearn compares raw f64 feature values against f64
thresholds and accumulates f64 leaf values tree-by-tree onto the
baseline, then ``expit``s. The program reproduces exactly that — f64
compares, the SAME serial tree-accumulation order (XLA preserves the
explicit add chain), ``1/(1+exp(-s))`` on the f64 score — so
``predictions.astype(f32)`` is bit-identical to
``SklearnBaseline.predict_proba`` (pinned in tests/test_gbm_tensor.py),
including unknown / out-of-range / non-integer category values. The f64
compute requires tracing, lowering, AND ``device_put`` of the tree
tensors inside a ``jax.experimental.enable_x64()`` context (thread-local
in jax 0.4.x — concurrent f32 dispatches on other threads are
unaffected); the compiled executable itself runs fine outside it. The
monitors stay f32 by the explicit dtype pins in `ops/drift.py` /
`ops/outlier.py`, so the packed buffer is one f32 vector exactly like
the other tiers.
"""

from __future__ import annotations

import contextlib
import dataclasses
from typing import Any, Callable

import numpy as np

# The tensorized layout's format tag: part of the compile-cache config
# hash (compilecache/warmup.py serve_gbm_jobs) so a layout change here can
# never collide with a persisted executable of the old layout.
GBM_FORMAT = "gbm-gather-v1"

# Raw category ids the LUT covers — HistGBM itself bins categories into
# [0, 255] (its bitsets are 8x uint32 words), so any raw value outside
# the LUT range is by construction unknown -> missing_go_to_left.
_CAT_LUT_SIZE = 256


@dataclasses.dataclass(frozen=True)
class GbmGeometry:
    """Static shape facts of one tensorized ensemble — everything the
    traced program's structure depends on beyond the aval shapes. Rides
    the compile-cache config hash."""

    n_trees: int
    max_nodes: int
    depth: int  # static traversal iterations = deepest decision path


def x64_context():
    """The thread-local double-precision context every gbm-tensor trace,
    lowering, and ``device_put`` of tree tensors must run inside (jax
    0.4.x: entering it inside an f32 trace is a type error; committed f64
    arrays fed to a non-x64 jit silently downcast)."""
    from jax.experimental import enable_x64

    return enable_x64()


def device_put_x64(tree: Any) -> Any:
    """``jax.device_put`` under the x64 context — f64 leaves stay f64."""
    import jax

    with x64_context():
        return jax.device_put(tree)


def _unwrap(estimator: Any) -> Any:
    """Accept either the raw sklearn estimator or the zoo's
    `models/gbm.py SklearnBaseline` wrapper (what bundles expose)."""
    return getattr(estimator, "estimator", estimator)


def supports_gbm_tensorization(estimator: Any) -> bool:
    """True when ``estimator`` is (or wraps) a fitted binary
    HistGradientBoostingClassifier this module can lower (the rf family
    keeps the host hybrid path: unbinned deep forests explode Nmax)."""
    estimator = _unwrap(estimator)
    predictors = getattr(estimator, "_predictors", None)
    if not predictors:
        return False
    classes = getattr(estimator, "classes_", None)
    if classes is None or len(classes) != 2:
        return False
    return hasattr(estimator, "_bin_mapper")


def _bit(bitset_row: np.ndarray, value: int) -> bool:
    return bool((int(bitset_row[value // 32]) >> (value % 32)) & 1)


def extract_gbm(estimator: Any) -> tuple[dict[str, np.ndarray], GbmGeometry]:
    """Fitted HistGBM -> (tree-tensor ``variables`` pytree, geometry).

    The returned dict is the packed program's ``variables`` argument:

    - ``value``      f64  [T, N]  leaf values (0 on decision/pad nodes)
    - ``threshold``  f64  [T, N]  numeric split thresholds
    - ``feature``    i32  [T, N]  split feature column in [cat | numeric]
    - ``left/right`` i32  [T, N]  child node indices
    - ``is_leaf``    bool [T, N]  (padding nodes are leaves: they
                                   self-loop harmlessly, value 0, and are
                                   unreachable from node 0 anyway)
    - ``is_cat``     bool [T, N]  categorical split?
    - ``mgtl``       bool [T, N]  missing_go_to_left
    - ``cat_go_left`` bool [T, N, 256] per-node LUT: go left for raw
      category v? sklearn semantics baked in: v in the split's raw
      bitset if v is a KNOWN category of that feature, else the missing
      side (unknown categories follow missing_go_to_left)
    - ``baseline``   f64  []     the ensemble's baseline prediction
    """
    estimator = _unwrap(estimator)
    predictors = [trees[0] for trees in estimator._predictors]
    baseline = float(np.asarray(estimator._baseline_prediction).ravel()[0])
    known_bitsets, f_idx_map = (
        estimator._bin_mapper.make_known_categories_bitsets()
    )

    n_trees = len(predictors)
    max_nodes = max(p.nodes.shape[0] for p in predictors)
    value = np.zeros((n_trees, max_nodes), np.float64)
    threshold = np.zeros((n_trees, max_nodes), np.float64)
    feature = np.zeros((n_trees, max_nodes), np.int32)
    left = np.zeros((n_trees, max_nodes), np.int32)
    right = np.zeros((n_trees, max_nodes), np.int32)
    is_leaf = np.ones((n_trees, max_nodes), bool)  # padding = leaf
    is_cat = np.zeros((n_trees, max_nodes), bool)
    mgtl = np.zeros((n_trees, max_nodes), bool)
    cat_go_left = np.zeros((n_trees, max_nodes, _CAT_LUT_SIZE), bool)

    depth = 1
    for t, pred in enumerate(predictors):
        nodes = pred.nodes
        n = nodes.shape[0]
        value[t, :n] = nodes["value"]
        threshold[t, :n] = nodes["num_threshold"]
        feature[t, :n] = nodes["feature_idx"]
        left[t, :n] = nodes["left"]
        right[t, :n] = nodes["right"]
        is_leaf[t, :n] = nodes["is_leaf"].astype(bool)
        mgtl[t, :n] = nodes["missing_go_to_left"].astype(bool)
        cat_mask = nodes["is_categorical"].astype(bool)
        is_cat[t, :n] = cat_mask
        for i in np.nonzero(cat_mask)[0]:
            raw_bits = pred.raw_left_cat_bitsets[int(nodes["bitset_idx"][i])]
            known_row = known_bitsets[int(f_idx_map[nodes["feature_idx"][i]])]
            miss = bool(nodes["missing_go_to_left"][i])
            for v in range(_CAT_LUT_SIZE):
                cat_go_left[t, i, v] = (
                    _bit(raw_bits, v) if _bit(known_row, v) else miss
                )
        # Decision depth of this tree: longest root->leaf path.
        node_depth = np.zeros(n, np.int32)
        for i in range(n):  # parents precede children in the node array
            if not is_leaf[t, i]:
                for child in (int(left[t, i]), int(right[t, i])):
                    node_depth[child] = max(
                        node_depth[child], node_depth[i] + 1
                    )
        depth = max(depth, int(node_depth.max()))

    variables = {
        "value": value,
        "threshold": threshold,
        "feature": feature,
        "left": left,
        "right": right,
        "is_leaf": is_leaf,
        "is_cat": is_cat,
        "mgtl": mgtl,
        "cat_go_left": cat_go_left,
        "baseline": np.float64(baseline),
    }
    return variables, GbmGeometry(
        n_trees=n_trees, max_nodes=max_nodes, depth=depth
    )


def gbm_raw_scores(variables: dict, depth: int, cat_ids, numeric):
    """The tensorized ensemble's raw f64 decision scores for one batch —
    the gather/compare traversal described in the module docstring. Must
    be traced under ``x64_context()``."""
    import jax.numpy as jnp

    # Exactly models/gbm.py _design_matrix_arrays: [cat_ids | numeric] as
    # f64 (int32 ids and f32 numerics widen exactly, so the compares see
    # bit-for-bit sklearn's inputs).
    from jax import lax

    xall = jnp.concatenate(
        [cat_ids.astype(jnp.float64), numeric.astype(jnp.float64)], axis=1
    )
    n_trees = variables["value"].shape[0]
    # [B, T] tree-axis gather index, broadcast EXPLICITLY (lax, not jnp:
    # jnp.broadcast_to short-circuits at B=1, eliding the broadcast eqn
    # and making the traced program bucket-polymorphic — TPU304).
    rows = lax.broadcast_in_dim(
        jnp.arange(n_trees, dtype=jnp.int32),
        (xall.shape[0], n_trees),
        (1,),
    )
    idx = jnp.zeros((xall.shape[0], n_trees), jnp.int32)
    for _ in range(depth):
        leaf = variables["is_leaf"][rows, idx]
        feat = variables["feature"][rows, idx]
        thr = variables["threshold"][rows, idx]
        miss = variables["mgtl"][rows, idx]
        cat = variables["is_cat"][rows, idx]
        xv = jnp.take_along_axis(xall, feat, axis=1)
        # Categorical resolution: integral raw values inside the LUT
        # range read the per-node LUT (which already encodes the
        # unknown-category -> missing rule); anything else is unknown.
        vi = jnp.clip(xv, 0, _CAT_LUT_SIZE - 1).astype(jnp.int32)
        in_range = (
            (xv >= 0) & (xv < _CAT_LUT_SIZE) & (xv == jnp.floor(xv))
        )
        cat_go = variables["cat_go_left"][rows, idx, vi]
        go_left = jnp.where(
            jnp.isnan(xv),
            miss,
            jnp.where(
                cat,
                jnp.where(in_range, cat_go, miss),
                xv <= thr,
            ),
        )
        nxt = jnp.where(
            go_left, variables["left"][rows, idx], variables["right"][rows, idx]
        )
        idx = jnp.where(leaf, idx, nxt)
    leaf_values = variables["value"][rows, idx]  # [B, T] f64
    # Serial accumulation in tree order — sklearn adds one iteration's
    # predictions at a time onto the baseline, and XLA preserves this
    # explicit add chain, so the f64 sum is bit-identical (a tree-axis
    # reduction could reassociate).
    score = variables["baseline"] + leaf_values[:, 0]
    for t in range(1, n_trees):
        score = score + leaf_values[:, t]
    return score


def _gbm_predictions(variables, depth, temperature, cat_ids, numeric):
    """Raw traversal -> the hybrid path's EXACT f32 probabilities.

    The host hybrid (`ops/predict.py make_hybrid_predict_fn`) computes
    ``apply_temperature(predict_proba(X), T)`` — expit of the raw f64
    score, one narrowing cast to f32, and then (only when T != 1.0) the
    clipped-logit rescale ``sigmoid(logit(clip(p)) / T)`` of
    `train/calibrate.py`, narrowed again on assignment into the f32
    output. This reproduces both branches bit-for-bit; ``temperature``
    is a traced argument, so the T==1 shortcut becomes a select. The
    engine passes T as a f64 scalar (the gbm tier's one dtype deviation
    from the packed contract): the host hybrid divides by the FULL
    python float, and an f32 rounding of T shifts tempered
    probabilities by one ulp."""
    import jax.numpy as jnp

    from mlops_tpu.train.calibrate import PROB_EPS

    raw = gbm_raw_scores(variables, depth, cat_ids, numeric)
    # expit on the f64 raw score (sklearn's exact arithmetic), then one
    # narrowing cast — bit-identical to predict_proba's f32 view.
    p32 = (1.0 / (1.0 + jnp.exp(-raw))).astype(jnp.float32)
    t64 = temperature.astype(jnp.float64)
    p64 = jnp.clip(p32.astype(jnp.float64), PROB_EPS, 1.0 - PROB_EPS)
    logits = jnp.log(p64) - jnp.log1p(-p64)
    tempered = (1.0 / (1.0 + jnp.exp(-logits / t64))).astype(jnp.float32)
    return jnp.where(temperature == jnp.float32(1.0), p32, tempered)


def make_gbm_packed_base(depth: int) -> Callable:
    """The gbm-tensor tier's packed program in the one cacheable 7-arg
    serving form (`ops/predict.py make_packed_predict_base` contract):
    tree tensors as ``variables``, one flat ``f32[2B + D]`` output, the
    monitor accumulator folded on device. ``depth`` is static program
    structure (GbmGeometry — part of the cache config hash)."""
    import jax.numpy as jnp

    from mlops_tpu.monitor.state import (
        drift_scores,
        fold_accumulator,
        outlier_flags,
    )

    def predict(
        variables: dict,
        monitor,
        acc,
        temperature,
        cat_ids,
        numeric,
        mask,
    ):
        preds = _gbm_predictions(variables, depth, temperature, cat_ids, numeric)
        flags = outlier_flags(monitor, numeric, mask)
        drift = drift_scores(monitor, cat_ids, numeric, mask)
        packed = jnp.concatenate([preds, flags, drift])
        return packed, fold_accumulator(acc, flags, drift, mask)

    return predict


def make_gbm_grouped_base(depth: int) -> Callable:
    """Packed grouped (vmapped) form — `make_packed_grouped_base` shape
    contract: ``f32[S, 2R + D]`` slots, accumulator folded across the
    group outside the vmap."""
    import jax
    import jax.numpy as jnp

    from mlops_tpu.monitor.state import (
        drift_scores,
        fold_accumulator_grouped,
        outlier_flags,
    )

    def single(variables, monitor, temperature, cat_ids, numeric, mask):
        return (
            _gbm_predictions(variables, depth, temperature, cat_ids, numeric),
            outlier_flags(monitor, numeric, mask),
            drift_scores(monitor, cat_ids, numeric, mask),
        )

    def grouped(variables, monitor, acc, temperature, cat_ids, numeric, mask):
        preds, flags, drift = jax.vmap(
            single, in_axes=(None, None, None, 0, 0, 0)
        )(variables, monitor, temperature, cat_ids, numeric, mask)
        packed = jnp.concatenate([preds, flags, drift], axis=1)
        return packed, fold_accumulator_grouped(acc, flags, drift, mask)

    return grouped


def abstract_gbm_variables(geometry: GbmGeometry) -> dict:
    """ShapeDtypeStruct twin of `extract_gbm`'s variables tree at one
    geometry — what the Layer-2 analyzer traces against (the compile-cache
    warmers use real fitted trees: the geometry is a fact of the fitted
    ensemble, so there is no config-only abstract warmup)."""
    import jax

    S = jax.ShapeDtypeStruct
    t, n = geometry.n_trees, geometry.max_nodes
    return {
        "value": S((t, n), np.float64),
        "threshold": S((t, n), np.float64),
        "feature": S((t, n), np.int32),
        "left": S((t, n), np.int32),
        "right": S((t, n), np.int32),
        "is_leaf": S((t, n), np.bool_),
        "is_cat": S((t, n), np.bool_),
        "mgtl": S((t, n), np.bool_),
        "cat_go_left": S((t, n, _CAT_LUT_SIZE), np.bool_),
        "baseline": S((), np.float64),
    }


def gbm_reference_proba(
    variables: dict, geometry: GbmGeometry, cat_ids, numeric
) -> np.ndarray:
    """The jnp-composite reference: run the traversal eagerly under the
    x64 context and return f32 probabilities — the bit-parity bridge the
    tests pin against BOTH `SklearnBaseline.predict_proba` and the
    compiled packed program."""
    import jax.numpy as jnp

    with x64_context():
        raw = gbm_raw_scores(
            variables,
            geometry.depth,
            jnp.asarray(np.asarray(cat_ids, np.int32)),
            jnp.asarray(np.asarray(numeric, np.float32)),
        )
        return np.asarray((1.0 / (1.0 + jnp.exp(-raw))).astype(jnp.float32))


def gbm_fingerprint(geometry: GbmGeometry) -> str:
    """Compile-cache config hash for the gbm entries: the layout format
    tag + the static geometry the traced program bakes in, plus an
    explicit x64 marker (the programs are lowered inside the x64 context,
    while `keys.environment_fingerprint` reads the ambient flag — the
    marker keeps f64 artifacts keyed apart regardless of when the key was
    computed relative to the context)."""
    from mlops_tpu.compilecache.keys import model_fingerprint

    return model_fingerprint(
        ("gbm-tensor", GBM_FORMAT, "x64", dataclasses.asdict(geometry))
    )


@contextlib.contextmanager
def _noop():
    yield


def trace_context(tier: str):
    """The tracing/lowering context a tier's programs require: the x64
    context for the gbm-tensor tier, a no-op for everything else — the
    engine and warmup wrap compiles in this so tier routing stays one
    code path."""
    return x64_context() if tier == "gbm" else _noop()
