"""Pure-JAX numerics: drift tests, outlier scores, fused predict.

These are the jit-able building blocks the monitor and serving layers use.
The reference delegates this math to CPU libraries (alibi-detect's
``TabularDrift`` chi2/K-S tests and ``IForest``,
`02-register-model.ipynb:225-233`) executed serially after the classifier
(`02-register-model.ipynb:330-353`); here every statistic is expressed in
XLA-friendly form so classifier + drift + outlier run as ONE fused device
computation per request.
"""

from mlops_tpu.ops.drift import chi2_two_sample, ks_two_sample
from mlops_tpu.ops.outlier import mahalanobis_sq

# NOTE: the fused predict builder lives in ``mlops_tpu.ops.predict`` and is
# imported from there directly (not re-exported here) because it composes the
# monitor layer on top of these primitives.

__all__ = [
    "chi2_two_sample",
    "ks_two_sample",
    "mahalanobis_sq",
]
