"""Int8/bf16 quantized student — the raw-speed serving/bulk tier.

Hummingbird (arxiv 2010.04804) showed classical-model inference compiles
to pure tensor programs worth kernel-level treatment; Gemma-on-TPU serving
(arxiv 2605.25645) is the reference frame for a quantized low-precision
serving tier behind quality gates. This module is that tier's NUMERIC
core: a hand-written two-layer MLP student (no flax module — the whole
forward is a handful of explicit matmuls, which is what makes the Pallas
fusion in `ops/quant_kernel.py` tractable) stored in a quantized format:

- dense kernels:  int8 weights + per-output-channel f32 scales
  (symmetric, scale = max|w| / 127 per column)
- embedding tables: bf16 (stacked ``[C, max_card, E]``; unused tail rows
  of narrow-cardinality features stay zero and are never selected)
- biases: f32

Compute dequantizes IN-JIT and runs f32 (XLA folds the dequant into the
matmul epilogue; on CPU backends bf16 arithmetic is emulated and slow —
the f32-after-dequant rule is what buys the bulk throughput there).

Categorical lookup is a one-hot matmul, not a gather: `broadcasted_iota`
comparisons lower on Mosaic (TPU Pallas) where dynamic gathers do not,
and every consumer — the jnp composite, the Pallas kernel body, and the
bulk chunk program — calls the SAME `student_logits`, so serve/bulk/
kernel paths are bit-identical by construction.

Fitting lives in `train/distill.py distill_quant_student` (the fidelity
gate) and `train/calibrate.py` (the post-hoc temperature refit); this
module is jax-math + format only.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from mlops_tpu.schema import SCHEMA

# Default student geometry: embed width + hidden width. Small on purpose —
# the tier's reason to exist is FLOPs/row (~6x under the (64,64) distilled
# flax student at the credit-default widths); fidelity is enforced by the
# distillation gate, not by capacity.
QUANT_EMBED_DIM = 4
QUANT_HIDDEN = 32

# Manifest format tag: bundles carry it so a loader can refuse a quant
# blob written by a different packing scheme.
QUANT_FORMAT = "int8-dense/bf16-embed/v1"


def quantize_dense(w: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Per-output-channel symmetric int8 quantization of a dense kernel
    ``[in, out]`` -> ``(int8 [in, out], f32 scales [out])``. All-zero
    columns get scale 1 (nothing to represent; dequant stays exact)."""
    w = np.asarray(w, np.float32)
    absmax = np.abs(w).max(axis=0)
    scale = np.where(absmax > 0, absmax / 127.0, 1.0).astype(np.float32)
    q = np.clip(np.rint(w / scale[None, :]), -127, 127).astype(np.int8)
    return q, scale


def dequantize_dense(w_q: jnp.ndarray, scale: jnp.ndarray) -> jnp.ndarray:
    """int8 kernel + per-column scales -> f32 kernel (the in-jit inverse
    of `quantize_dense`)."""
    return w_q.astype(jnp.float32) * scale[None, :]


def quantize_student(master: dict[str, Any]) -> dict[str, jnp.ndarray]:
    """f32 master tree (from the distillation fit) -> the quantized
    serving tree. Head vector ``w2`` quantizes as a 1-column kernel."""
    w1_q, w1_s = quantize_dense(np.asarray(master["w1"]))
    w2_q, w2_s = quantize_dense(np.asarray(master["w2"])[:, None])
    return {
        "embed": jnp.asarray(master["embed"], jnp.bfloat16),
        "w1_q": jnp.asarray(w1_q),
        "w1_s": jnp.asarray(w1_s),
        "b1": jnp.asarray(master["b1"], jnp.float32),
        "w2_q": jnp.asarray(w2_q[:, 0]),
        "w2_s": jnp.asarray(w2_s[0]),
        "b2": jnp.asarray(master["b2"], jnp.float32),
    }


def quant_params_geometry(qparams: dict[str, Any]) -> tuple[int, int]:
    """(embed_dim, hidden) read back from a quant tree — the compile-cache
    key's geometry axis (`compilecache/warmup.py serve_quant_jobs`)."""
    return int(qparams["embed"].shape[2]), int(qparams["w1_q"].shape[1])


def init_quant_master(
    seed: int = 0,
    embed_dim: int = QUANT_EMBED_DIM,
    hidden: int = QUANT_HIDDEN,
) -> dict[str, jnp.ndarray]:
    """f32 master init for the distillation fit (train/distill.py)."""
    c, k = SCHEMA.num_categorical, max(SCHEMA.cards)
    d_in = c * embed_dim + SCHEMA.num_numeric
    ke = jax.random.split(jax.random.PRNGKey(seed), 3)
    return {
        "embed": 0.02 * jax.random.normal(ke[0], (c, k, embed_dim), jnp.float32),
        "w1": jax.random.normal(ke[1], (d_in, hidden), jnp.float32)
        / np.sqrt(d_in),
        "b1": jnp.zeros((hidden,), jnp.float32),
        "w2": jax.random.normal(ke[2], (hidden,), jnp.float32)
        / np.sqrt(hidden),
        "b2": jnp.zeros((), jnp.float32),
    }


def abstract_quant_params(
    embed_dim: int = QUANT_EMBED_DIM, hidden: int = QUANT_HIDDEN
) -> dict[str, jax.ShapeDtypeStruct]:
    """Shape-only quant tree for abstract tracing and AOT cache keys (the
    `abstract_monitor_state` discipline): shapes depend only on the schema
    and the (embed_dim, hidden) geometry."""
    c, k = SCHEMA.num_categorical, max(SCHEMA.cards)
    d_in = c * embed_dim + SCHEMA.num_numeric
    S = jax.ShapeDtypeStruct
    return {
        "embed": S((c, k, embed_dim), jnp.bfloat16),
        "w1_q": S((d_in, hidden), jnp.int8),
        "w1_s": S((hidden,), jnp.float32),
        "b1": S((hidden,), jnp.float32),
        "w2_q": S((hidden,), jnp.int8),
        "w2_s": S((), jnp.float32),
        "b2": S((), jnp.float32),
    }


def one_hot_2d(ids_col: jnp.ndarray, k: int) -> jnp.ndarray:
    """One-hot of an id column ``[N]`` -> f32 ``[N, k]`` via a 2-D
    broadcasted iota — the Mosaic-safe form (1-D iota does not lower on
    TPU Pallas; `jax.nn.one_hot` builds one). The ONE one-hot rule every
    quant-tier consumer shares, so kernel and composite agree bitwise."""
    iota = jax.lax.broadcasted_iota(jnp.int32, (ids_col.shape[0], k), 1)
    return (ids_col[:, None] == iota).astype(jnp.float32)


def student_logits(
    embed: jnp.ndarray,  # [C, K, E] any float dtype (cast to f32)
    w1: jnp.ndarray,  # f32 [C*E + M, H]
    b1: jnp.ndarray,  # f32 [H]
    w2: jnp.ndarray,  # f32 [H]
    b2: jnp.ndarray,  # f32 []
    cat_ids: jnp.ndarray,  # int32 [N, C]
    numeric: jnp.ndarray,  # f32 [N, M]
) -> jnp.ndarray:
    """The hand-written student forward, f32 end to end: per-feature
    one-hot embed matmuls (unrolled over the ~9 categorical features —
    each is a 2-D ``[N,K] @ [K,E]`` dot, the shape Mosaic wants) -> concat
    with numerics -> dense/relu/dense. Returns logits ``[N]``."""
    c, k = embed.shape[0], embed.shape[1]
    feats = [
        one_hot_2d(cat_ids[:, j], k) @ embed[j].astype(jnp.float32)
        for j in range(c)
    ]
    x = jnp.concatenate(feats + [numeric.astype(jnp.float32)], axis=1)
    h = jnp.maximum(x @ w1 + b1[None, :], 0.0)
    return h @ w2 + b2


def master_student_logits(
    master: dict[str, Any], cat_ids: jnp.ndarray, numeric: jnp.ndarray
) -> jnp.ndarray:
    """Forward through the un-quantized f32 master (the distillation fit's
    objective surface)."""
    return student_logits(
        master["embed"], master["w1"], master["b1"], master["w2"],
        master["b2"], cat_ids, numeric,
    )


def quant_student_logits(
    qparams: dict[str, Any], cat_ids: jnp.ndarray, numeric: jnp.ndarray
) -> jnp.ndarray:
    """Forward through the QUANTIZED tree: dequantize in-jit, then the
    shared f32 forward — serving, bulk, and the Pallas kernel body all
    route through here (bit parity by construction)."""
    w1 = dequantize_dense(qparams["w1_q"], qparams["w1_s"])
    w2 = qparams["w2_q"].astype(jnp.float32) * qparams["w2_s"]
    return student_logits(
        qparams["embed"], w1, qparams["b1"], w2, qparams["b2"],
        cat_ids, numeric,
    )


# --------------------------------------------------------- serialization
def quant_params_to_arrays(qparams: dict[str, Any]) -> dict[str, np.ndarray]:
    """npz-safe host arrays: numpy has no bf16, so the embed table ships
    as the f32 image of its bf16 values — bf16 -> f32 is exact and the
    f32 -> bf16 cast on load returns the original bits (round-trip
    lossless)."""
    out = {}
    for key, leaf in qparams.items():
        arr = np.asarray(
            leaf.astype(jnp.float32) if leaf.dtype == jnp.bfloat16 else leaf
        )
        out[key] = arr
    return out


def quant_params_from_arrays(
    arrays: dict[str, np.ndarray],
) -> dict[str, jnp.ndarray]:
    """Inverse of `quant_params_to_arrays` (embed goes back to bf16)."""
    out = {}
    for key, arr in arrays.items():
        if key == "embed":
            out[key] = jnp.asarray(arr, jnp.bfloat16)
        else:
            out[key] = jnp.asarray(arr)
    return out
