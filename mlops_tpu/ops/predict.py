"""The fused predict function — the serving hot path.

The reference's hot path runs three detectors **serially** on CPU inside
``CustomModel.predict`` (`02-register-model.ipynb:330-353`: classifier
``predict_proba``, then ``drift.predict``, then ``outliers.predict``). Here
all three are one XLA computation: the classifier's matmuls dominate, the
Mahalanobis score shares the same batch in registers/VMEM, and the drift
reductions fuse alongside — a single dispatch, a single host->device->host
round trip per request batch.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np

from mlops_tpu.monitor.state import (
    MonitorAccumulator,
    MonitorState,
    drift_scores,
    fold_accumulator,
    fold_accumulator_grouped,
    outlier_flags,
)
from mlops_tpu.train.calibrate import apply_temperature


def make_predict_fn(
    bundle,
) -> Callable[[jnp.ndarray, jnp.ndarray], dict[str, jnp.ndarray]]:
    """Build the jitted fused predict for a loaded (flax-flavor) bundle:
    (cat_ids, numeric) -> response arrays.

    Returns a function producing the reference's response fields
    (`app/model.py:64-70`): ``predictions`` (P(default) per row),
    ``outliers`` (0/1 per row), ``feature_drift_batch`` (per-feature
    ``1 - p_val`` scores for the batch). Takes the whole bundle so the
    fitted calibration temperature (train/calibrate.py) cannot be
    forgotten — the lower-level ``make_*_predict_fn`` builders are for
    the engine, which resolves it once.
    """
    model, variables, monitor = bundle.model, bundle.variables, bundle.monitor
    temperature = bundle.temperature

    @jax.jit
    def predict(cat_ids: jnp.ndarray, numeric: jnp.ndarray):
        logits = model.apply(variables, cat_ids, numeric, train=False)
        return {
            "predictions": jax.nn.sigmoid(logits / temperature),
            "outliers": outlier_flags(monitor, numeric),
            "feature_drift_batch": drift_scores(monitor, cat_ids, numeric),
        }

    return predict


def make_padded_predict_base(model) -> Callable:
    """The serving hot-path program in its CACHEABLE form: everything the
    executable depends on beyond the model architecture — params, monitor
    state, calibration temperature — is an ARGUMENT, never a closure. A
    closed-over array would be baked into the serialized executable as a
    constant, and a persistent compile cache (`compilecache/`) keyed on
    shapes alone would then silently serve a stale model; with args, the
    abstract signature carries the shapes and the values flow per call.
    """

    def predict(
        variables: Any,
        monitor: MonitorState,
        temperature: jnp.ndarray,
        cat_ids: jnp.ndarray,
        numeric: jnp.ndarray,
        mask: jnp.ndarray,
    ):
        logits = model.apply(variables, cat_ids, numeric, train=False)
        return {
            "predictions": jax.nn.sigmoid(logits / temperature),
            "outliers": outlier_flags(monitor, numeric, mask),
            "feature_drift_batch": drift_scores(monitor, cat_ids, numeric, mask),
        }

    return predict


def make_grouped_predict_base(model) -> Callable:
    """Cacheable form of the micro-batcher's vmapped program (same
    argument discipline as ``make_padded_predict_base``): params/monitor/
    temperature broadcast across the request axis, per-request drift stays
    computed over each request's OWN rows."""

    def single(variables, monitor, temperature, cat_ids, numeric, mask):
        logits = model.apply(variables, cat_ids, numeric, train=False)
        return {
            "predictions": jax.nn.sigmoid(logits / temperature),
            "outliers": outlier_flags(monitor, numeric, mask),
            "feature_drift_batch": drift_scores(monitor, cat_ids, numeric, mask),
        }

    def grouped(variables, monitor, temperature, cat_ids, numeric, mask):
        return jax.vmap(single, in_axes=(None, None, None, 0, 0, 0))(
            variables, monitor, temperature, cat_ids, numeric, mask
        )

    return grouped


def make_packed_predict_base(model) -> Callable:
    """The serving hot path's ZERO-WASTE form: one contiguous f32 output
    buffer plus the device-resident monitor aggregate.

    The dict form (`make_padded_predict_base`) returns a 3-leaf pytree, so
    every request pays THREE device->host transfers (on a remote-attached
    chip each is a full ~70-90 ms tunnel round trip — `serve/engine.py`).
    Here the program emits a single ``f32[2*B + D]`` vector laid out as

        [0 : B]        predictions  (P(default) per padded row)
        [B : 2B]       outlier flags (0/1, mask-zeroed)
        [2B : 2B + D]  per-batch drift scores in schema order

    sliced host-side by `packed_layout`, so the whole response is ONE D2H
    buffer — and the running monitor aggregate (`MonitorAccumulator`) is
    folded in the same fused program and STAYS on the device (the second
    output; the engine threads it through as a donated argument where the
    backend's donation gate allows). Same cacheable argument discipline as
    the dict form: everything beyond the architecture is an ARGUMENT.

    Numerics are bit-identical to the dict form: the three sub-programs
    are unchanged, the concatenation is layout only (pinned by the packed
    parity test)."""

    def predict(
        variables: Any,
        monitor: MonitorState,
        acc: MonitorAccumulator,
        temperature: jnp.ndarray,
        cat_ids: jnp.ndarray,
        numeric: jnp.ndarray,
        mask: jnp.ndarray,
    ):
        logits = model.apply(variables, cat_ids, numeric, train=False)
        flags = outlier_flags(monitor, numeric, mask)
        drift = drift_scores(monitor, cat_ids, numeric, mask)
        packed = jnp.concatenate(
            [jax.nn.sigmoid(logits / temperature), flags, drift]
        )
        return packed, fold_accumulator(acc, flags, drift, mask)

    return predict


def make_packed_grouped_base(model) -> Callable:
    """Packed form of the micro-batcher's vmapped program: ``f32[S, 2R+D]``
    (each slot's predictions ‖ outliers ‖ drift), monitor aggregate folded
    across the group's non-empty slots outside the vmap. Per-request drift
    stays computed over each request's OWN rows, exactly as the dict form."""

    def single(variables, monitor, temperature, cat_ids, numeric, mask):
        logits = model.apply(variables, cat_ids, numeric, train=False)
        return (
            jax.nn.sigmoid(logits / temperature),
            outlier_flags(monitor, numeric, mask),
            drift_scores(monitor, cat_ids, numeric, mask),
        )

    def grouped(
        variables: Any,
        monitor: MonitorState,
        acc: MonitorAccumulator,
        temperature: jnp.ndarray,
        cat_ids: jnp.ndarray,
        numeric: jnp.ndarray,
        mask: jnp.ndarray,
    ):
        preds, flags, drift = jax.vmap(
            single, in_axes=(None, None, None, 0, 0, 0)
        )(variables, monitor, temperature, cat_ids, numeric, mask)
        packed = jnp.concatenate([preds, flags, drift], axis=1)
        return packed, fold_accumulator_grouped(acc, flags, drift, mask)

    return grouped


def packed_layout(rows: int) -> tuple[slice, slice, slice]:
    """(predictions, outliers, drift) slices of a packed row vector of
    ``rows`` padded rows — the ONE definition of the buffer layout shared
    by the engine's host-side unpack and the tests."""
    from mlops_tpu.schema import SCHEMA

    d = SCHEMA.num_categorical + SCHEMA.num_numeric
    return (
        slice(0, rows),
        slice(rows, 2 * rows),
        slice(2 * rows, 2 * rows + d),
    )


def _acc_donation():
    """Donation argnums for the packed programs' accumulator argument
    (position 2), gated by the backend capability check in
    `parallel/compat.py` (jaxlib 0.4.x CPU executes donated cached
    executables incorrectly — PR 1/PR 3)."""
    from mlops_tpu.parallel.compat import donation_argnums

    return donation_argnums(2)


def _bind_serving_args(base: Callable, variables, monitor, temperature):
    """Close a base program over one bundle's state, jitted, preserving the
    old ``(cat_ids, numeric, mask)`` call surface. ``__wrapped__`` exposes
    the unjitted bound function (checkify audits re-wrap it).

    The bound state is ``device_put`` ONCE here: params/monitor are now
    per-call ARGUMENTS (the cacheable form), and host numpy arrays would
    re-pay the full host->device param transfer on EVERY request —
    committed device arrays transfer once and are passed by reference.
    (No-op when the caller already placed them, e.g. the engine.)"""
    jitted = jax.jit(base)
    variables = jax.device_put(variables)
    monitor = jax.device_put(monitor)
    t = jax.device_put(np.float32(temperature))

    def predict(cat_ids, numeric, mask):
        return jitted(variables, monitor, t, cat_ids, numeric, mask)

    def raw(cat_ids, numeric, mask):
        return base(variables, monitor, t, cat_ids, numeric, mask)

    predict.__wrapped__ = raw
    return predict


def make_padded_predict_fn(
    model, variables: Any, monitor: MonitorState, temperature: float = 1.0
) -> Callable[[jnp.ndarray, jnp.ndarray, jnp.ndarray], dict[str, jnp.ndarray]]:
    """Fused predict for serving: takes a row-validity mask so batches padded
    to fixed bucket sizes produce statistics identical to the unpadded batch
    (one compiled program per bucket size, zero recompiles in steady state).
    Built on ``make_padded_predict_base`` so the engine's AOT compile-cache
    path and this bound convenience form share ONE program definition.
    """
    return _bind_serving_args(
        make_padded_predict_base(model), variables, monitor, temperature
    )


def make_grouped_predict_fn(
    model, variables: Any, monitor: MonitorState, temperature: float = 1.0
) -> Callable[[jnp.ndarray, jnp.ndarray, jnp.ndarray], dict[str, jnp.ndarray]]:
    """Vmapped fused predict for the micro-batching queue: R concurrent
    requests ride ONE device dispatch as ``[R, B, ...]`` stacks, and the
    per-request vmap keeps every request's drift statistics computed over
    its OWN rows — identical responses to R separate calls, ~1 dispatch
    instead of R. (The reference serves strictly one request per model
    call, `app/main.py:72`.)
    """
    return _bind_serving_args(
        make_grouped_predict_base(model), variables, monitor, temperature
    )


def make_hybrid_predict_fn(
    estimator, monitor: MonitorState, temperature: float = 1.0
) -> Callable[[jnp.ndarray, jnp.ndarray, jnp.ndarray], dict[str, Any]]:
    """Fused predict for the sklearn-flavor bundle (BASELINE config 1 floor).

    The tree ensemble scores on host CPU (trees don't map to the MXU) while
    the drift + outlier monitors stay one jitted device computation — same
    response contract and padding/mask semantics as the Flax path, so the
    engine serves both flavors identically.
    """

    @jax.jit
    def monitors(cat_ids: jnp.ndarray, numeric: jnp.ndarray, mask: jnp.ndarray):
        return {
            "outliers": outlier_flags(monitor, numeric, mask),
            "feature_drift_batch": drift_scores(monitor, cat_ids, numeric, mask),
        }

    def predict(cat_ids, numeric, mask):
        import numpy as np

        out = dict(monitors(cat_ids, numeric, mask))
        # Score only valid rows on the host (padding would waste tree
        # inference); scatter back so the output length matches the bucket.
        valid = np.asarray(mask)
        probs = np.zeros(valid.shape[0], np.float32)
        p = estimator.predict_proba(
            np.asarray(cat_ids)[valid], np.asarray(numeric)[valid]
        )
        probs[valid] = apply_temperature(p, temperature)
        out["predictions"] = probs
        return out

    return predict
