"""Outlier scoring as a jittable JAX function.

The reference uses ``alibi_detect.od.IForest(threshold=0.95)`` fit on the
numeric features only (`02-register-model.ipynb:232-233`), whose ``predict``
yields per-row 0/1 flags consumed at `02-register-model.ipynb:330-353`.
Isolation forests are a poor fit for XLA (data-dependent tree walks), so the
TPU-native detector is **Mahalanobis distance** on the same numeric features
with the decision threshold calibrated to the same quantile contract: flag a
row when its squared distance exceeds the train-split quantile (0.95 by
default). Same response semantics (``outliers: list[float]`` of 0/1 —
`app/model.py:69`), hardware-friendly math: one (x-mu) @ P matmul.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np


def mahalanobis_sq(
    x: jnp.ndarray,  # f32 [N, M]
    mean: jnp.ndarray,  # f32 [M]
    precision: jnp.ndarray,  # f32 [M, M] inverse covariance
) -> jnp.ndarray:
    """Squared Mahalanobis distance per row — one matmul + reduction."""
    centered = x - mean
    return jnp.einsum("ni,ij,nj->n", centered, precision, centered)


def fit_mahalanobis(
    x: np.ndarray, quantile: float = 0.95, ridge: float = 1e-6
) -> tuple[np.ndarray, np.ndarray, float]:
    """Host-side fit: mean, precision (ridge-regularized), threshold.

    ``quantile`` mirrors the reference's ``IForest(threshold=0.95)``: the
    flag threshold is the empirical quantile of training distances.
    """
    x = np.asarray(x, dtype=np.float64)
    mean = x.mean(axis=0)
    cov = np.cov(x, rowvar=False)
    cov += ridge * np.eye(cov.shape[0])
    precision = np.linalg.inv(cov)
    centered = x - mean
    distances = np.einsum("ni,ij,nj->n", centered, precision, centered)
    threshold = float(np.quantile(distances, quantile))
    return (
        mean.astype(np.float32),
        precision.astype(np.float32),
        threshold,
    )
