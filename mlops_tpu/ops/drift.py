"""Two-sample drift statistics as jittable JAX functions.

Parity targets (alibi-detect ``TabularDrift(p_val=.05)``,
`02-register-model.ipynb:225-230`; scored at serve time in
`02-register-model.ipynb:330-353` as ``1 - p_val`` per feature):

- categorical features -> two-sample chi-squared contingency test
- numeric features     -> two-sample Kolmogorov-Smirnov test (asymptotic
  p-value with the Stephens small-sample correction; matches
  ``scipy.stats.ks_2samp(method="asymp")`` to ~1e-6)

Everything is fixed-shape: categorical counts are padded to a common
``max_card`` with masked cells, so one vmap covers all 9 features and the
whole drift pass is a handful of fused reductions — no per-feature Python.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def chi2_two_sample(
    ref_counts: jnp.ndarray,  # f32 [K] category counts from training
    batch_counts: jnp.ndarray,  # f32 [K] category counts from the batch
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Chi-squared contingency test on a 2 x K table with empty-cell masking.

    Returns ``(statistic, p_value)``. Categories absent from BOTH samples are
    dropped from the table (and from the degrees of freedom), mirroring how a
    dense implementation would build the contingency table only over observed
    categories.
    """
    ref_counts = ref_counts.astype(jnp.float32)
    batch_counts = batch_counts.astype(jnp.float32)
    col_total = ref_counts + batch_counts
    valid = col_total > 0
    n_ref = ref_counts.sum()
    n_batch = batch_counts.sum()
    grand = n_ref + n_batch

    expected_ref = n_ref * col_total / jnp.maximum(grand, 1.0)
    expected_batch = n_batch * col_total / jnp.maximum(grand, 1.0)
    safe_ref = jnp.where(valid, expected_ref, 1.0)
    safe_batch = jnp.where(valid, expected_batch, 1.0)
    stat = jnp.sum(
        jnp.where(valid, (ref_counts - expected_ref) ** 2 / safe_ref, 0.0)
    ) + jnp.sum(
        jnp.where(valid, (batch_counts - expected_batch) ** 2 / safe_batch, 0.0)
    )
    df = jnp.maximum(valid.sum() - 1, 1).astype(jnp.float32)
    # chi2 survival function: Q(df/2, stat/2) via the regularized upper
    # incomplete gamma function.
    p_value = jax.scipy.special.gammaincc(df / 2.0, stat / 2.0)
    return stat, p_value


def _kolmogorov_sf(t: jnp.ndarray, terms: int = 32) -> jnp.ndarray:
    """Kolmogorov distribution survival function Q(t).

    Two jit-safe branches: the alternating series
    ``2*sum (-1)^{k-1} e^{-2k^2 t^2}`` converges fast for large ``t`` but
    diverges as ``t -> 0``, so small ``t`` uses the Jacobi-theta dual form
    ``1 - sqrt(2*pi)/t * sum e^{-(2k-1)^2 pi^2 / (8 t^2)}``.
    """
    t_safe = jnp.maximum(t, 1e-8)
    k = jnp.arange(1, terms + 1, dtype=jnp.float32)
    signs = jnp.where(k % 2 == 1, 1.0, -1.0)
    large = 2.0 * jnp.sum(signs * jnp.exp(-2.0 * (k**2) * (t_safe**2)))
    odd = 2.0 * k - 1.0
    # f32-pinned constant: under jax_enable_x64 (the gbm-tensor tier traces
    # its whole program in an x64 context — ops/gbm_tensor.py) the bare
    # Python-float expression would promote to f64 and drag the drift
    # branch with it; the monitors are f32 by contract on every tier.
    small = 1.0 - jnp.sqrt(jnp.float32(2.0 * jnp.pi)) / t_safe * jnp.sum(
        jnp.exp(-(odd**2) * (jnp.pi**2) / (8.0 * t_safe**2))
    )
    return jnp.clip(jnp.where(t_safe < 1.0, small, large), 0.0, 1.0)


def ks_two_sample(
    ref_sorted: jnp.ndarray,  # f32 [R] training reference sample, ASCENDING
    batch: jnp.ndarray,  # f32 [B] serve-time batch (unsorted)
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Two-sample K-S test. Returns ``(statistic, p_value)``.

    The supremum of |ECDF_ref - ECDF_batch| is attained at sample points; we
    evaluate both ECDFs at the batch's sorted points (from both sides) and at
    the reference points via ``searchsorted`` — fixed-shape, O((R+B) log)
    work that XLA fuses into a few sorts and gathers.
    """
    r = ref_sorted.shape[0]
    b = batch.shape[0]
    batch_sorted = jnp.sort(batch.astype(jnp.float32))
    ref_sorted = ref_sorted.astype(jnp.float32)

    # Evaluate both ECDFs (right-continuous) at every sample point of the
    # pooled sample. This is tie-safe: the supremum of |F_ref - F_batch| over
    # x is attained just after some sample point, and the left-limit at any
    # point equals the value just after the previous distinct point — also a
    # sample point.
    pooled = jnp.concatenate([ref_sorted, batch_sorted])
    # Integer-count / integer-size divisions are f32-pinned: under
    # jax_enable_x64 searchsorted yields int64 and the true division would
    # otherwise produce f64 statistics (the gbm-tensor tier traces this
    # program inside an x64 context; bit-identical in f32 mode).
    ref_cdf = (
        jnp.searchsorted(ref_sorted, pooled, side="right") / r
    ).astype(jnp.float32)
    batch_cdf = (
        jnp.searchsorted(batch_sorted, pooled, side="right") / b
    ).astype(jnp.float32)
    statistic = jnp.abs(ref_cdf - batch_cdf).max()
    en = jnp.sqrt(r * b / jnp.asarray(r + b, jnp.float32))
    # Stephens correction (as used by scipy's asymptotic two-sample mode).
    p_value = _kolmogorov_sf((en + 0.12 + 0.11 / en) * statistic)
    return statistic, p_value


def ks_small_masked_statistic(
    ref_sorted: jnp.ndarray,  # f32 [R] ascending
    ref_cdf: jnp.ndarray,  # f32 [R] ECDF_ref at its own points (right-cont.)
    batch: jnp.ndarray,  # f32 [B] possibly padded, B small
    mask: jnp.ndarray,  # bool [B] True for real rows
) -> jnp.ndarray:
    """The dense masked K-S STATISTIC alone — split from the p-value so
    the Pallas fused kernel (`ops/quant_kernel.py`) can run the heavy
    [B,R]/[R,B] comparison planes in-kernel while the Kolmogorov survival
    function stays outside (its series builds ``arange`` constants, which
    a Pallas kernel body cannot capture)."""
    r = ref_sorted.shape[0]
    ref_sorted = ref_sorted.astype(jnp.float32)
    bvals = jnp.where(mask, batch.astype(jnp.float32), jnp.inf)
    n_valid = jnp.maximum(mask.sum().astype(jnp.float32), 1.0)

    # ECDFs at batch points ([B,R] and [B,B] comparisons). The count
    # division is f32-pinned (x64-context tracing — see ks_two_sample).
    f_ref_b = (
        (ref_sorted[None, :] <= bvals[:, None]).sum(axis=1) / r
    ).astype(jnp.float32)
    cnt_b = (bvals[None, :] <= bvals[:, None]).sum(axis=1).astype(jnp.float32)
    f_b_b = jnp.minimum(cnt_b, n_valid) / n_valid
    d_b = jnp.where(
        jnp.isfinite(bvals), jnp.abs(f_ref_b - f_b_b), 0.0
    ).max()

    # ECDFs at reference points ([R,B] comparisons; ECDF_ref precomputed).
    cnt_r = (bvals[None, :] <= ref_sorted[:, None]).sum(axis=1)
    f_b_r = jnp.minimum(cnt_r.astype(jnp.float32), n_valid) / n_valid
    d_r = jnp.abs(ref_cdf - f_b_r).max()

    return jnp.where(mask.any(), jnp.maximum(d_b, d_r), 0.0)


def ks_two_sample_small_masked(
    ref_sorted: jnp.ndarray,  # f32 [R] ascending
    ref_cdf: jnp.ndarray,  # f32 [R] ECDF_ref at its own points (right-cont.)
    batch: jnp.ndarray,  # f32 [B] possibly padded, B small
    mask: jnp.ndarray,  # bool [B] True for real rows
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """K-S for SMALL batches as dense comparisons — the grouped-serving
    hot path.

    ``ks_two_sample_masked`` sorts the batch and runs ``searchsorted``
    over the pooled R+B points; vmapped per request-slot that lowers to
    per-slot sorts/gathers, which are slow on TPU (~4-5 ms per slot
    measured on v5e — it dominated grouped dispatch). For B << R the
    supremum over pooled points splits into batch points and reference
    points, and every ECDF evaluation becomes a ``<=`` outer comparison
    ([B,R] and [R,B] elementwise reductions, MXU/VPU-friendly), with
    ECDF_ref at reference points a fit-time constant (``ref_cdf``).
    Identical statistics to the pooled form, including ties and padding
    (+inf rows contribute 0 everywhere).
    """
    r = ref_sorted.shape[0]
    statistic = ks_small_masked_statistic(ref_sorted, ref_cdf, batch, mask)
    n_valid = jnp.maximum(mask.sum().astype(jnp.float32), 1.0)
    en = jnp.sqrt(r * n_valid / (r + n_valid))
    p_value = _kolmogorov_sf((en + 0.12 + 0.11 / en) * statistic)
    return statistic, p_value


def ks_two_sample_masked(
    ref_sorted: jnp.ndarray,  # f32 [R] ascending
    batch: jnp.ndarray,  # f32 [B] possibly padded
    mask: jnp.ndarray,  # bool [B] True for real rows
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """K-S test ignoring padded rows — serving pads batches to fixed bucket
    sizes for compile-cache stability, and padding must not perturb the
    statistics.

    Padded entries are replaced with +inf so they sort to the tail; the batch
    ECDF denominator is the number of REAL rows, so at every finite pooled
    point both ECDFs agree with the unpadded computation, and at +inf points
    both are exactly 1.
    """
    r = ref_sorted.shape[0]
    ref_sorted = ref_sorted.astype(jnp.float32)
    bvals = jnp.where(mask, batch.astype(jnp.float32), jnp.inf)
    batch_sorted = jnp.sort(bvals)
    n_valid = jnp.maximum(mask.sum().astype(jnp.float32), 1.0)

    pooled = jnp.concatenate([ref_sorted, batch_sorted])
    # f32-pinned count division (x64-context tracing — see ks_two_sample).
    ref_cdf = (
        jnp.searchsorted(ref_sorted, pooled, side="right") / r
    ).astype(jnp.float32)
    batch_counts = jnp.searchsorted(batch_sorted, pooled, side="right")
    batch_cdf = jnp.minimum(batch_counts.astype(jnp.float32), n_valid) / n_valid
    finite = jnp.isfinite(pooled)
    statistic = jnp.where(finite, jnp.abs(ref_cdf - batch_cdf), 0.0).max()
    # All-padded batch: no data, no signal.
    statistic = jnp.where(mask.any(), statistic, 0.0)

    en = jnp.sqrt(r * n_valid / (r + n_valid))
    p_value = _kolmogorov_sf((en + 0.12 + 0.11 / en) * statistic)
    return statistic, p_value
