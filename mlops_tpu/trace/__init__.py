"""tracewire: end-to-end request tracing + shape/goodput telemetry.

The reference repo's whole monitoring story is structured per-request
logs queryable after the fact (`app/main.py:59-84` -> Log Analytics /
Kusto). This package is that story rebuilt for a serving path that now
crosses three processes (front end -> shm ring -> engine -> device):

- `Span` (span.py): one request's monotonic stage stamps — admission ->
  encode -> ring wait -> engine queue -> dispatch -> device fetch ->
  respond — stitched across the process boundary from the engine-half
  stamps the shm slot carries (serve/ipc.py ``resp_trace``).
- `TraceRecorder` (recorder.py): a bounded, drop-counting ring buffer
  flushed to JSONL by a background writer — the queryable-log story,
  locally; `jq` is the Kusto console (docs/observability.md).
- `ShapeStats` (shapes.py): per-compiled-entry shape histograms
  (requested rows vs padded rows, group geometry occupancy) exported as
  real Prometheus ``_bucket`` series plus the ``padding_waste_pct`` /
  ``useful_rows_per_s`` goodput keys — the exact input ROADMAP item 4's
  traffic-shape autotuner needs.
- `report.py`: the ``mlops-tpu trace-report`` CLI's aggregation —
  p50/p99 per stage per compiled entry from the span JSONL.

Everything here is jax-free (front-end processes import it) and gated
behind the ``trace`` config section: disarmed, the serving hot path pays
one ``is None`` check per request (the faultline discipline — bench pins
``trace_overhead_pct``).
"""

from mlops_tpu.trace.recorder import TraceRecorder
from mlops_tpu.trace.report import format_report, load_spans, stage_report
from mlops_tpu.trace.shapes import ShapeStats
from mlops_tpu.trace.span import Span

__all__ = [
    "Span",
    "TraceRecorder",
    "ShapeStats",
    "load_spans",
    "stage_report",
    "format_report",
]
