"""Per-compiled-entry shape histograms: requested vs padded rows.

Every dispatch through a compiled serving program records
``(entry, requested_rows, padded_rows)``: ``bucket_8`` entries pad a
request up to the bucket's rows; ``group_16x1`` entries scatter a
coalesced job into a slots x rows grid. The ratio requested/padded is
the entry's OCCUPANCY — 1.0 means zero padding waste, and the histogram
over it is exactly the live traffic-shape evidence ROADMAP item 4's
bucket/geometry autotuner needs (the learned-TPU-cost-model line,
PAPERS.md arXiv 2008.01040; goodput accounting, arXiv 2502.06982).

Exported two ways:

- `render_lines()` -> real Prometheus histogram series
  (``mlops_tpu_shape_occupancy_bucket{entry=...,le=...}`` + ``_sum`` /
  ``_count``), per-entry requested/padded row counters, and the derived
  ``mlops_tpu_padding_waste_pct`` / ``mlops_tpu_useful_rows_per_s``
  goodput gauges;
- a fixed-size shm table (`write_table` / `render_table_lines`) so the
  multi-worker plane's ENGINE process (the only one that dispatches) can
  mirror the stats into the ring and any SO_REUSEPORT front end renders
  them on a scrape.

Jax-free; one leaf lock.
"""

from __future__ import annotations

import threading
import time

import numpy as np

# tpulint Layer-3 manifest: one leaf lock guarding the counter dict; the
# observe() critical section is a handful of float adds (never I/O, never
# a device call).
TPULINT_LOCK_ORDER = {"ShapeStats": ("_lock",)}

# Occupancy histogram edges (occupancy = requested/padded is in (0, 1],
# so 1.0 is the +Inf-equivalent top bucket; the explicit +Inf series is
# still emitted — Prometheus histogram_quantile requires it).
OCCUPANCY_BUCKETS = (0.125, 0.25, 0.5, 0.75, 0.9, 1.0)

# shm mirror geometry: entry keys are short ascii ("bucket_16384",
# "group_64x8"); 32 rows cover the warmed grid (6 buckets + 12 group
# geometries) with headroom for novel shapes. Entries past the table
# (pathological novel-shape churn) are dropped from the MIRROR only —
# the engine-side stats keep everything, and trace-report reads those.
TABLE_ROWS = 32
TABLE_KEY_BYTES = 24
TABLE_VALS = 3 + len(OCCUPANCY_BUCKETS)  # dispatches, requested, padded, hist


class ShapeStats:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        # entry -> [dispatches, requested_rows, padded_rows, hist...]
        self._entries: dict[str, list[float]] = {}
        # entry -> shm table row, assigned ONCE on first mirror and never
        # reassigned: a novel entry must not shift existing rows, or a
        # scrape racing the rewrite could pair one entry's key with
        # another entry's counters (a non-monotone _total is a Prometheus
        # counter reset). Entries past the table stay engine-side only.
        self._table_rows: dict[str, int] = {}
        # Entries the shm MIRROR cannot hold (table saturated): updated
        # by write_table, exported as
        # ``mlops_tpu_shape_table_evicted_total`` on both planes.
        # Nonzero means the ring scrape's histograms — and anything fed
        # from them, like the gridtuner's demand reconstruction — are
        # SILENTLY MISSING entries that the engine-side stats still
        # hold; 0 on the single-process plane by construction (it
        # renders the in-memory dict, no mirror, nothing evicted).
        # Monotone: entries only accumulate and row assignment is
        # first-seen-forever, so once the table saturates the overflow
        # set can only grow.
        self._evicted = 0
        # Armed-at monotonic time: the useful_rows_per_s rate base, also
        # mirrored into shm so the ring renderer shares the same base.
        self.t0 = time.monotonic()

    # ------------------------------------------------------------ hot path
    def observe(self, entry: str, requested: int, padded: int) -> None:
        padded = max(int(padded), 1)
        occupancy = min(int(requested) / padded, 1.0)
        bucket = int(np.searchsorted(OCCUPANCY_BUCKETS, occupancy))
        bucket = min(bucket, len(OCCUPANCY_BUCKETS) - 1)
        with self._lock:
            row = self._entries.get(entry)
            if row is None:
                row = self._entries[entry] = [0.0] * TABLE_VALS
            row[0] += 1
            row[1] += requested
            row[2] += padded
            row[3 + bucket] += 1

    def seed(
        self, entries: dict[str, list[float]], t0: float | None = None
    ) -> None:
        """Install a prior engine incarnation's mirrored totals
        (ISSUE 11): a respawned engine starts its in-memory histograms at
        zero, and re-mirroring absolute zeros over the shm table would
        regress the exported ``_total``/``_bucket`` counters into a
        Prometheus counter reset. Seeding folds the dead incarnation's
        last-published entries back in (first-seen row order preserved)
        and restores the armed-at rate base so ``useful_rows_per_s``
        keeps its denominator across the respawn."""
        with self._lock:
            for entry, vals in entries.items():
                row = self._entries.get(entry)
                if row is None:
                    self._entries[entry] = [float(v) for v in vals]
                else:
                    for i, v in enumerate(vals):
                        row[i] += float(v)
                if entry not in self._table_rows and (
                    len(self._table_rows) < TABLE_ROWS
                ):
                    self._table_rows[entry] = len(self._table_rows)
            if t0 is not None and t0 > 0:
                self.t0 = t0

    # ----------------------------------------------------------- snapshots
    def snapshot(self) -> dict[str, list[float]]:
        with self._lock:
            return {k: list(v) for k, v in self._entries.items()}

    def padding_waste_pct(self) -> float:
        """Overall goodput loss to padding: 100 * (1 - requested/padded)
        over every dispatch since the stats armed."""
        snap = self.snapshot()
        requested = sum(v[1] for v in snap.values())
        padded = sum(v[2] for v in snap.values())
        if padded <= 0:
            return 0.0
        return round(100.0 * (1.0 - requested / padded), 3)

    def useful_rows_per_s(self) -> float:
        """Goodput rate: REQUESTED rows (the ones a client asked for —
        padding excluded) per second since the stats armed."""
        snap = self.snapshot()
        requested = sum(v[1] for v in snap.values())
        elapsed = max(time.monotonic() - self.t0, 1e-9)
        return round(requested / elapsed, 1)

    @property
    def evicted_total(self) -> int:
        """Entries the shm mirror has dropped (first-seen 32-row cap):
        the silent-staleness observable. Always 0 until `write_table`
        runs (the single-process plane has no mirror to overflow)."""
        with self._lock:
            return self._evicted

    def render_lines(self) -> list[str]:
        return _lines(
            self.snapshot(), self.useful_rows_per_s(), self.evicted_total
        )

    # ----------------------------------------------------------- shm mirror
    def write_table(self, keys: np.ndarray, vals: np.ndarray) -> None:
        """Engine-process single writer: mirror the stats into the ring's
        fixed table (serve/ipc.py ``shape_keys``/``shape_vals``). Row
        assignment is STABLE (first-seen, never reshuffled) so a scrape
        racing this write can never pair entry A's key with entry B's
        counters; within one row, per-cell stores are individually atomic
        and a mid-update mix of one entry's own counters is
        gauge-tolerable (the monitor block's tearing contract). New rows
        write vals BEFORE key — the reader requires both a nonempty key
        and dispatches > 0, so a half-born row is skipped, not misread."""
        with self._lock:
            snap = {k: list(v) for k, v in self._entries.items()}
            for entry in snap:
                if entry not in self._table_rows and (
                    len(self._table_rows) < TABLE_ROWS
                ):
                    self._table_rows[entry] = len(self._table_rows)
            rows = dict(self._table_rows)
            # Overflow accounting: every entry that exists engine-side
            # but holds no mirror row is invisible to ring scrapes (and
            # to the autotuner's demand input) — count them instead of
            # letting a saturated table quietly bias the grid search.
            self._evicted = len(snap) - len(rows)
        for entry, i in rows.items():
            vals[i] = snap[entry]
            raw = entry.encode()[:TABLE_KEY_BYTES]
            key_row = np.zeros(TABLE_KEY_BYTES, np.uint8)
            key_row[: len(raw)] = np.frombuffer(raw, np.uint8)
            keys[i] = key_row


def read_table(keys: np.ndarray, vals: np.ndarray) -> dict[str, list[float]]:
    entries: dict[str, list[float]] = {}
    for i in range(keys.shape[0]):
        if vals[i, 0] <= 0:
            continue
        raw = bytes(keys[i]).rstrip(b"\x00")
        if not raw:
            continue
        entries[raw.decode(errors="replace")] = [float(v) for v in vals[i]]
    return entries


def merge_entries(
    tables: list[dict[str, list[float]]]
) -> dict[str, list[float]]:
    """Fold several replicas' entry tables into one (ISSUE 13): entries
    are keyed by compiled shape, which every replica warms identically,
    so the fold is a per-key elementwise sum — histogram counts,
    requested/padded totals, and dispatch counts all add."""
    merged: dict[str, list[float]] = {}
    for table in tables:
        for entry, vals in table.items():
            row = merged.get(entry)
            if row is None:
                merged[entry] = [float(v) for v in vals]
            else:
                for i, v in enumerate(vals):
                    row[i] += float(v)
    return merged


def render_table_lines(
    keys: np.ndarray, vals: np.ndarray, elapsed_s: float,
    evicted: int = 0,
) -> list[str]:
    """The ring renderer's half: same series as `ShapeStats.render_lines`
    but from the shm mirror (any front end serves the scrape)."""
    return render_entries_lines(read_table(keys, vals), elapsed_s, evicted)


def render_entries_lines(
    entries: dict[str, list[float]], elapsed_s: float, evicted: int = 0
) -> list[str]:
    """Format an already-merged entry table (the multi-replica render):
    identical series to `render_table_lines`, rate base = the merged
    fleet's oldest armed clock, ``evicted`` = the fleet's summed mirror
    overflow (serve/ipc.py ``shape_evicted``)."""
    requested = sum(v[1] for v in entries.values())
    rate = round(requested / max(elapsed_s, 1e-9), 1)
    return _lines(entries, rate, evicted)


def _lines(
    entries: dict[str, list[float]],
    useful_rows_per_s: float,
    evicted: int = 0,
) -> list[str]:
    """ONE formatting rule for both telemetry planes (the
    `ServingMetrics.robustness_lines` discipline): identical series names
    whether the scrape lands on the single-process server or a ring
    front end."""
    if not entries:
        return []
    lines = ["# TYPE mlops_tpu_shape_occupancy histogram"]
    for entry in sorted(entries):
        row = entries[entry]
        dispatches = int(row[0])
        cumulative = 0
        for j, edge in enumerate(OCCUPANCY_BUCKETS):
            cumulative += int(row[3 + j])
            lines.append(
                f'mlops_tpu_shape_occupancy_bucket{{entry="{entry}",'
                f'le="{edge}"}} {cumulative}'
            )
        lines.append(
            f'mlops_tpu_shape_occupancy_bucket{{entry="{entry}",'
            f'le="+Inf"}} {dispatches}'
        )
        # _sum of observed occupancies is not recoverable from the
        # counters exactly; the mean requested/padded IS the
        # dispatch-weighted occupancy mass, which is what rate queries
        # divide by _count anyway.
        mean = row[1] / max(row[2], 1e-9)
        lines.append(
            f'mlops_tpu_shape_occupancy_sum{{entry="{entry}"}} '
            f"{round(mean * dispatches, 4)}"
        )
        lines.append(
            f'mlops_tpu_shape_occupancy_count{{entry="{entry}"}} {dispatches}'
        )
    lines.append("# TYPE mlops_tpu_requested_rows_total counter")
    for entry in sorted(entries):
        lines.append(
            f'mlops_tpu_requested_rows_total{{entry="{entry}"}} '
            f"{int(entries[entry][1])}"
        )
    lines.append("# TYPE mlops_tpu_padded_rows_total counter")
    for entry in sorted(entries):
        lines.append(
            f'mlops_tpu_padded_rows_total{{entry="{entry}"}} '
            f"{int(entries[entry][2])}"
        )
    requested = sum(v[1] for v in entries.values())
    padded = sum(v[2] for v in entries.values())
    waste = 100.0 * (1.0 - requested / padded) if padded > 0 else 0.0
    lines.append("# TYPE mlops_tpu_padding_waste_pct gauge")
    lines.append(f"mlops_tpu_padding_waste_pct {round(waste, 3)}")
    lines.append("# TYPE mlops_tpu_useful_rows_per_s gauge")
    lines.append(f"mlops_tpu_useful_rows_per_s {useful_rows_per_s}")
    # Mirror-overflow marker (always emitted with the block — the zero
    # baseline keeps chaos-smoke monotonicity checkable): nonzero means
    # these histograms are MISSING entries the engine still tracks, so
    # any consumer — a dashboard, the gridtuner's demand input — is
    # seeing a biased shape distribution.
    lines.append("# TYPE mlops_tpu_shape_table_evicted_total counter")
    lines.append(f"mlops_tpu_shape_table_evicted_total {int(evicted)}")
    return lines
