"""Bounded, drop-counting span sink flushed to JSONL by a writer thread.

The hot path calls ``record(dict)``: one lock-guarded deque append, never
a syscall, never blocking — a full buffer DROPS the record and counts it
(``dropped`` / the ``on_drop`` hook feeds ``mlops_tpu_trace_dropped_total``)
instead of ever back-pressuring the serving path. A background writer
drains the buffer every ``flush_interval_s`` and on ``close()``.

Write discipline (the utils/io.py atomic/append family): every record is
ONE ``os.write`` of one newline-terminated line on an ``O_APPEND`` fd —
appends of a single write are not interleaved by the kernel, so a reader
(or a SIGTERM arriving between lines) never sees a torn record, and N
worker processes appending to their own per-worker files never
coordinate at all.
"""

from __future__ import annotations

import collections
import json
import logging
import os
import threading
from pathlib import Path
from typing import Any, Callable

logger = logging.getLogger("mlops_tpu.trace")

# tpulint Layer-3 manifest (analysis/concurrency.py TPU401 + the runtime
# sanitizer): one leaf lock guarding only the deque and the drop counter.
# The writer thread drains under the lock (a popleft loop of index moves)
# and performs the json.dumps + os.write OUTSIDE it — file I/O under a
# hot-path lock is exactly the TPU403 class this layout avoids.
TPULINT_LOCK_ORDER = {"TraceRecorder": ("_lock",)}


class TraceRecorder:
    """One process's span sink -> one JSONL file."""

    def __init__(
        self,
        path: str | Path,
        capacity: int = 4096,
        flush_interval_s: float = 0.5,
        on_drop: Callable[[int], None] | None = None,
    ) -> None:
        self.path = Path(path)
        self.path.parent.mkdir(parents=True, exist_ok=True)
        self._fd = os.open(
            self.path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644
        )
        self.capacity = max(1, int(capacity))
        self._lock = threading.Lock()
        self._buf: collections.deque = collections.deque()
        self.dropped = 0
        self._on_drop = on_drop
        self._closed = False
        self._wake = threading.Event()
        self._writer = threading.Thread(
            target=self._run, name="trace-writer", daemon=True
        )
        self._flush_interval_s = max(0.01, float(flush_interval_s))
        self._writer.start()

    # ------------------------------------------------------------ hot path
    def record(self, record: dict[str, Any]) -> None:
        """Non-blocking enqueue; a full buffer drops + counts."""
        with self._lock:
            if self._closed or len(self._buf) >= self.capacity:
                self.dropped += 1
                dropped = True
            else:
                self._buf.append(record)
                dropped = False
        if dropped and self._on_drop is not None:
            # Outside the lock: the hook may touch shm/metrics state with
            # its own discipline.
            self._on_drop(1)

    def stage_sink(self, source: str) -> Callable[[str, float, float, int], None]:
        """A `utils/timing.StageClock` sink: pipeline/bulk stage timings
        land in the same JSONL stream as request spans (kind="stage"),
        so trace-report and the jq runbook see one file format."""
        import time

        def sink(stage: str, start: float, elapsed_s: float, items: int) -> None:
            self.record(
                {
                    "kind": "stage",
                    "ts": time.time(),
                    "source": source,
                    "stage": stage,
                    "dur_ms": round(elapsed_s * 1e3, 4),
                    "items": items,
                }
            )

        return sink

    # ------------------------------------------------------------- writer
    def _drain(self) -> list[dict[str, Any]]:
        with self._lock:
            batch = list(self._buf)
            self._buf.clear()
        return batch

    def _write(self, batch: list[dict[str, Any]]) -> None:
        for record in batch:
            try:
                line = json.dumps(record, default=float) + "\n"
                # ONE write per line on an O_APPEND fd: the no-torn-lines
                # guarantee (SIGTERM drain, concurrent worker files).
                os.write(self._fd, line.encode())
            except (OSError, ValueError, TypeError):
                # A full disk / unserializable record costs that record,
                # never the writer thread or the serving path.
                logger.exception("trace writer failed to append a span")

    def _run(self) -> None:
        while not self._wake.wait(self._flush_interval_s):
            self._write(self._drain())
        self._write(self._drain())  # final drain on close

    # -------------------------------------------------------------- drain
    def close(self) -> None:
        """Flush everything buffered and stop the writer. Safe to call
        twice; records arriving after close are counted as dropped."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
        self._wake.set()
        self._writer.join(timeout=10)
        if self._writer.is_alive():
            # Writer stuck inside a blocked os.write (hung filesystem):
            # leave the fd to it. Closing here could recycle the fd
            # number under its pending writes — span lines appended into
            # whatever file next claims that number. One leaked fd on a
            # pathological path beats corrupting an unrelated file.
            logger.error(
                "trace writer did not drain within 10s (stalled "
                "filesystem?); leaving %s open", self.path,
            )
            return
        # The writer's final drain ran before it exited; catch any
        # in-flight stragglers that slipped in between, then release the fd.
        self._write(self._drain())
        try:
            os.close(self._fd)
        except OSError:
            pass
