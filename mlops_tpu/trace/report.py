"""`mlops-tpu trace-report`: p50/p99 per stage per compiled entry.

Reads the span JSONL a traced server left behind (``trace.dir`` — one
``spans*.jsonl`` per serving process; the multi-worker plane writes
``spans-w{N}.jsonl`` per front end) and aggregates stage latencies: the
local answer to the reference repo's "query the Log Analytics table"
workflow, for the question its per-request logs could never answer —
*where* did a request spend its time.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Any

from mlops_tpu.trace.span import STAGES
from mlops_tpu.utils.timing import percentile


def load_spans(path: str | Path) -> list[dict[str, Any]]:
    """Every parseable span record under ``path`` — a trace dir (all its
    ``spans*.jsonl``, so a multi-worker plane's per-worker files
    aggregate as ONE trace set with no manual concatenation), a glob
    pattern (``traces/spans-w*.jsonl`` — cross-directory sweeps), or a
    single JSONL file. Non-span records (kind="stage") and torn/garbage
    lines are skipped — the report must work on a file mid-append."""
    import glob as _glob

    raw = str(path)
    path = Path(path)
    if path.is_dir():
        files = sorted(path.glob("spans*.jsonl"))
    elif not path.exists() and any(c in raw for c in "*?["):
        # Glob form — only when the LITERAL path does not exist, so a
        # real directory/file whose name happens to contain bracket
        # characters keeps loading directly instead of being parsed as
        # a character class that matches nothing.
        files = [Path(f) for f in sorted(_glob.glob(raw))]
    else:
        files = [path]
    spans: list[dict[str, Any]] = []
    for file in files:
        try:
            lines = file.read_text().splitlines()
        except OSError:
            continue
        for line in lines:
            try:
                record = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(record, dict) and record.get("kind") == "span":
                spans.append(record)
    return spans


def stage_report(spans: list[dict[str, Any]]) -> dict[str, Any]:
    """Aggregate: (plane, entry) group -> per-stage {p50_ms, p99_ms,
    count} plus wall p50/p99 and request/row counts. Spans without a
    compiled entry (error paths, sheds) group under entry "-"."""
    groups: dict[tuple[str, str], list[dict[str, Any]]] = {}
    for span in spans:
        key = (str(span.get("plane", "?")), str(span.get("entry", "-")))
        groups.setdefault(key, []).append(span)
    out: dict[str, Any] = {"spans": len(spans), "groups": []}
    for (plane, entry), members in sorted(groups.items()):
        stages: dict[str, list[float]] = {}
        walls: list[float] = []
        rows = 0
        for span in members:
            walls.append(float(span.get("wall_ms", 0.0)))
            rows += int(span.get("rows", 0))
            for stage, ms in (span.get("stages") or {}).items():
                stages.setdefault(stage, []).append(float(ms))
        group: dict[str, Any] = {
            "plane": plane,
            "entry": entry,
            "requests": len(members),
            "rows": rows,
            "wall_p50_ms": round(percentile(sorted(walls), 50), 4),
            "wall_p99_ms": round(percentile(sorted(walls), 99), 4),
            "stages": {},
        }
        for stage, values in stages.items():
            values.sort()
            group["stages"][stage] = {
                "p50_ms": round(percentile(values, 50), 4),
                "p99_ms": round(percentile(values, 99), 4),
                "count": len(values),
            }
        out["groups"].append(group)
    return out


def format_report(report: dict[str, Any]) -> str:
    """Human-readable table (the CLI also prints the JSON for scripts)."""
    lines = [f"spans: {report['spans']}"]
    for group in report["groups"]:
        lines.append(
            f"\n[{group['plane']}] entry={group['entry']} "
            f"requests={group['requests']} rows={group['rows']} "
            f"wall p50={group['wall_p50_ms']}ms p99={group['wall_p99_ms']}ms"
        )
        # Canonical hot-path order first, stragglers after.
        ordered = [s for s in STAGES if s in group["stages"]] + [
            s for s in sorted(group["stages"]) if s not in STAGES
        ]
        for stage in ordered:
            stat = group["stages"][stage]
            lines.append(
                f"  {stage:>13}: p50 {stat['p50_ms']:9.3f} ms   "
                f"p99 {stat['p99_ms']:9.3f} ms   n={stat['count']}"
            )
    return "\n".join(lines)
