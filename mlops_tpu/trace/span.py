"""One request's span: a trace id plus monotonic stage stamps.

A span is a sequence of (stage-name, monotonic-time) stamps where each
stamp marks the END of the named stage — stage durations are the deltas
between consecutive stamps, so by construction the stages are monotone,
non-overlapping, and sum exactly to the span's wall clock. Stamps may
come from another PROCESS on the same host (the engine half of a ring
request, read back out of the shm slot): ``CLOCK_MONOTONIC`` is shared
across processes on one host, and ``stamp_at`` clamps against the
previous stamp so a microscopic cross-process skew can never manufacture
a negative stage.

Jax-free and lock-free: one request's stamps are only ever written by
the thread currently advancing that request (the stages are sequential),
so a plain list append is the whole synchronization story.
"""

from __future__ import annotations

import time
from typing import Any

# Canonical stage vocabulary, in hot-path order. Not every plane emits
# every stage: the ring plane stitches all seven; the single-process solo
# path has no ring/queue stages; the grouped path folds encode into
# dispatch (the engine encodes inside `dispatch_group`). trace-report
# aggregates whatever stages a span carries.
STAGES = (
    "admission",  # head+body read + pydantic validation
    "encode",  # preprocessor encode (front-end side on the ring plane)
    "queue",  # micro-batcher window + claim wait (single-process grouped)
    "ring_wait",  # shm descriptor queued until the engine collector popped it
    "engine_queue",  # collector claim -> pool thread picked the job up
    "dispatch",  # pad/scatter + device enqueue + async D2H copy start
    "device_fetch",  # blocking host-copy wait + packed-buffer slicing
    "respond",  # completion wait + format_response + socket write
)


class Span:
    """Stamp accumulator for one traced request."""

    __slots__ = ("trace_id", "plane", "worker", "route", "rows", "entry",
                 "t0", "stamps", "abandoned", "tenant", "replica", "tier")

    def __init__(
        self,
        trace_id: str,
        plane: str = "single",
        worker: int = 0,
        route: str = "/predict",
        t0: float | None = None,
        tenant: str = "default",
    ) -> None:
        self.trace_id = trace_id
        self.plane = plane
        self.worker = worker
        self.route = route
        # Engine replica that served the request (ISSUE 13): stitched in
        # from the shm slot tag on the ring plane; 0 everywhere else
        # (the single-process plane has exactly one engine).
        self.replica = 0
        # Bounded tenant label (mlops_tpu/tenancy/router.py): rides every
        # span record so trace-report can slice per tenant; "default" for
        # untagged traffic keeps pre-tenancy reports parsing unchanged.
        self.tenant = tenant
        self.rows = 0
        # Compiled-entry key ("bucket_8", "group_16x1") when the engine
        # told us which program served the request; None otherwise.
        self.entry: str | None = None
        # Routed serving tier (ISSUE 19, serve/tierroute.py — a member of
        # the closed TIERS set) when SLO routing resolved one; None keeps
        # single-tier spans byte-identical to pre-routing records.
        self.tier: str | None = None
        self.t0 = time.monotonic() if t0 is None else t0
        self.stamps: list[tuple[str, float]] = []
        # Set when the request path gave up on this span while a
        # background thread may still be stamping it (a deadline-timed-out
        # engine call keeps running in its executor thread): an abandoned
        # span is NEVER finished/recorded — finish() iterating stamps
        # while another thread appends would corrupt the record, and the
        # single-writer rule above only holds while exactly one thread is
        # advancing the request.
        self.abandoned = False

    def stamp(self, stage: str) -> None:
        """End the named stage NOW (this process's monotonic clock)."""
        self.stamp_at(stage, time.monotonic())

    def stamp_at(self, stage: str, t: float) -> None:
        """End the named stage at an absolute monotonic time — the
        cross-process form (engine-half stamps read from the shm slot).
        Clamped non-decreasing: a stamp can never precede its
        predecessor, so stage durations are >= 0 by construction."""
        last = self.stamps[-1][1] if self.stamps else self.t0
        self.stamps.append((stage, max(float(t), last)))

    def finish(self, status: int) -> dict[str, Any]:
        """Close the span into the JSONL record shape. ``stages`` maps
        stage name -> milliseconds; ``stamps`` keeps the raw offsets (ms
        from span start) for monotonicity audits and ad-hoc queries;
        ``wall_ms`` is last-stamp - start, which equals sum(stages) by
        construction."""
        stages: dict[str, float] = {}
        offsets: list[list[Any]] = []
        prev = self.t0
        for stage, t in self.stamps:
            stages[stage] = stages.get(stage, 0.0) + round((t - prev) * 1e3, 4)
            offsets.append([stage, round((t - self.t0) * 1e3, 4)])
            prev = t
        record: dict[str, Any] = {
            "kind": "span",
            "ts": time.time(),
            "trace_id": self.trace_id,
            "plane": self.plane,
            "worker": self.worker,
            "route": self.route,
            "tenant": self.tenant,
            "replica": int(self.replica),
            "status": int(status),
            "rows": int(self.rows),
            "wall_ms": round((prev - self.t0) * 1e3, 4),
            "stages": stages,
            "stamps": offsets,
        }
        if self.entry is not None:
            record["entry"] = self.entry
        if self.tier is not None:
            record["tier"] = self.tier
        return record
