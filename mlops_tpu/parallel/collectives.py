"""Explicit-SPMD building blocks via shard_map.

Most of the framework relies on jit+shardings and lets XLA place
collectives; these wrappers exist for code that wants manual control (custom
reductions, ring algorithms, comms/compute overlap experiments) and as the
tested seam where psum/all_gather/ppermute semantics are pinned down on the
fake 8-device mesh.
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from mlops_tpu.parallel.compat import shard_map


def pmean_over_data(fn: Callable, mesh: Mesh) -> Callable:
    """Wrap ``fn(batch_shard) -> scalar`` into a data-parallel mean over the
    'data' axis (the gradient-reduction primitive, made explicit)."""

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=P("data"),
        out_specs=P(),
        check_vma=False,
    )
    def wrapped(shard):
        return jax.lax.pmean(fn(shard), axis_name="data")

    return wrapped


def all_gather_rows(mesh: Mesh) -> Callable:
    """Gather row-sharded arrays onto every device (diagnostics, eval)."""

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=P("data"),
        out_specs=P(),
        check_vma=False,
    )
    def gather(shard):
        return jax.lax.all_gather(shard, axis_name="data", tiled=True)

    return gather


def ring_shift(mesh: Mesh, axis: str = "data") -> Callable:
    """Rotate shards one step around the mesh axis ring via ppermute — the
    primitive under ring-attention / ring all-reduce patterns."""
    n = mesh.devices.shape[list(mesh.axis_names).index(axis)]
    perm = [(i, (i + 1) % n) for i in range(n)]

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=P(axis),
        out_specs=P(axis),
        check_vma=False,
    )
    def shift(shard):
        return jax.lax.ppermute(shard, axis_name=axis, perm=perm)

    return shift
