"""Pipeline parallelism: GPipe-style microbatch streaming over a mesh axis.

Completes the framework's parallelism set (DP `bulk`/`steps`, TP
`sharding`, SP `ring_attention`, EP `models/moe.py` — the reference has
none of these, SURVEY.md SS2.7). Stage-stacked layer weights ``[S, ...]``
shard their leading axis over a 'stage' mesh axis so each device holds
one stage; microbatches stream through the ring: at every tick each
device applies its stage to the activation it received, hands the result
to the next stage with a single-hop ``ppermute`` (ICI-neighbor traffic
only), and stage ``S-1`` banks finished microbatches. ``M`` microbatches
drain in ``M + S - 1`` ticks — the classic GPipe bubble of
``(S-1)/(M+S-1)`` idle fraction, amortized by raising ``M``.

The tick loop is a ``lax.scan`` with static length, so the whole
pipeline is reverse-mode differentiable (``ppermute`` transposes to the
inverse permutation) and usable for training, not just inference.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from mlops_tpu.parallel.compat import (
    LEGACY_SHARD_MAP,
    pcast_varying,
    shard_map,
)


def pipeline_stage_shard(
    stage_weights: Any,
    x: jnp.ndarray,
    *,
    stage_fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
    axis_name: str,
    axis_size: int,
    extra_varying: tuple[str, ...] = (),
) -> jnp.ndarray:
    """Per-device body, to be called INSIDE shard_map.

    Args:
      stage_weights: local stage slice — leading axis length 1 (this
        device's stage), e.g. ``[1, D, D]`` kernels.
      x: the full microbatch stack ``[M, B, D]`` (replicated; only stage 0
        reads it).
      stage_fn: ``(weights_for_one_stage, activation [B, D]) -> [B, D]``.
      axis_name: the 'stage' mesh axis.
      axis_size: number of stages S (static).

    Returns the completed ``[M, B, D]`` outputs (identical on every device
    after the closing psum).
    """
    s = jax.lax.axis_index(axis_name)
    num_micro = x.shape[0]
    local = jax.tree_util.tree_map(lambda w: w[0], stage_weights)
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]
    varying_axes = (axis_name, *extra_varying)

    def tick(carry, t):
        recv, out = carry
        # Stage 0 ingests microbatch t; later stages consume what the
        # previous stage handed them last tick.
        ingest = jax.lax.dynamic_index_in_dim(
            x, jnp.clip(t, 0, num_micro - 1), 0, keepdims=False
        )
        h = jnp.where(s == 0, ingest, recv)
        y = stage_fn(local, h)
        # Stage S-1 banks microbatch m = t - (S-1) once it exists.
        m = t - (axis_size - 1)
        banked = jax.lax.dynamic_update_index_in_dim(
            out, y, jnp.clip(m, 0, num_micro - 1), 0
        )
        is_last = s == axis_size - 1
        valid = jnp.logical_and(is_last, jnp.logical_and(m >= 0, m < num_micro))
        out = jnp.where(valid, banked, out)
        # One-hop hand-off to the next stage (ICI-neighbor ppermute).
        recv = jax.lax.ppermute(y, axis_name, perm)
        return (recv, out), None

    # The carry varies per device from the first tick (each stage computes
    # its own activations), so the zero initials must be typed as varying
    # over the stage axis — and over the batch axis too when the
    # microbatches arrive DP-sharded (extra_varying) — for shard_map's
    # scan typing.
    recv0 = pcast_varying(jnp.zeros(x.shape[1:], x.dtype), varying_axes)
    # zeros_like(x) already inherits x's varying axes (the batch axis when
    # DP-sharded), so out0 only needs the stage axis added.
    out0 = pcast_varying(jnp.zeros_like(x), (axis_name,))
    (recv, out), _ = jax.lax.scan(
        tick, (recv0, out0), jnp.arange(num_micro + axis_size - 1)
    )
    # Only stage S-1 holds the results; psum broadcasts them to the ring
    # (every other contribution is zero).
    return jax.lax.psum(out, axis_name)


def make_pipeline(
    mesh: Mesh,
    stage_fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
    axis_name: str = "stage",
    batch_axis: str | None = None,
) -> Callable[[Any, jnp.ndarray], jnp.ndarray]:
    """Build ``run(stage_weights, x) -> y`` executing ``stage_fn`` as an
    S-deep pipeline over ``mesh[axis_name]``.

    ``stage_weights`` is any pytree whose leaves carry a leading stage
    axis of size S (sharded across devices); ``x`` is ``[M, B, D]``
    microbatches. ``batch_axis`` composes DP x PP: the microbatch B dim
    shards over that mesh axis and the stage ring runs independently per
    batch shard (all communication stays on the 'stage' axis).
    Equivalent to folding ``stage_fn`` sequentially over the stage axis —
    validated exactly in ``tests/test_pipeline_parallel.py``.
    """
    axis_size = mesh.shape[axis_name]
    body = partial(
        pipeline_stage_shard,
        stage_fn=stage_fn,
        axis_name=axis_name,
        axis_size=axis_size,
        extra_varying=(batch_axis,) if batch_axis else (),
    )
    x_spec = P(None, batch_axis) if batch_axis else P()
    # Not compile-cached: this is a GPipe TRAINING-layout building block
    # (one compile per training run, amortized over thousands of steps),
    # not a per-process serving entry point; the cached train entries are
    # train-step-dense and train-step-tp (compilecache/registry.py).
    return jax.jit(  # tpulint: disable=TPU203
        shard_map(
            body,
            mesh=mesh,
            in_specs=(P(axis_name), x_spec),
            out_specs=x_spec,
            # 0.4.x's replication checker cannot type the stage-varying
            # scan carry, so only THERE is it disabled (correctness is
            # pinned by the fold-equivalence tests); modern jax accepts
            # the pcast_varying annotations and keeps its checker on.
            check_vma=False if LEGACY_SHARD_MAP else None,
        )
    )
