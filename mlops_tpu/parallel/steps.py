"""Distributed train step + batch scorer via jit-with-shardings (pjit).

The idiomatic TPU recipe (scaling-book style): annotate input/output
shardings on a jit'd function over a Mesh and let XLA insert the collectives
— gradient psums over 'data', activation all-gathers/reduce-scatters over
'model' — riding ICI. No hand-written communication.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh

from mlops_tpu.config import TrainConfig
from mlops_tpu.parallel.compat import donation_argnums
from mlops_tpu.parallel.sharding import batch_sharding, param_shardings, replicated
from mlops_tpu.train.loop import TrainState, training_loss, update_ema


def make_sharded_train_step(
    model,
    optimizer: optax.GradientTransformation,
    config: TrainConfig,
    mesh: Mesh,
    params_template: Any,
) -> tuple[Callable, Any]:
    """Build a pjit train step: data-parallel batch, tensor-parallel params.

    Returns ``(step_fn, state_shardings)``. ``step_fn(state, cat, num, lab,
    rng) -> (state, loss)`` with the batch sharded over 'data' and params
    laid out per ``PARAM_RULES`` over 'model'. Gradients reduce over ICI via
    XLA-inserted psums.
    """
    p_shard = param_shardings(mesh, params_template)
    # Optimizer state mirrors the param layout (adamw: mu/nu per param);
    # so does the EMA accumulator — one shadow copy per param shard, no
    # extra collectives (the update is elementwise on co-located tiles).
    state_shardings = TrainState(
        params=p_shard,
        opt_state=_opt_shardings(optimizer, params_template, p_shard, mesh),
        step=replicated(mesh),
        rng=replicated(mesh),
        ema=p_shard if config.ema_decay else None,
    )
    data_in = batch_sharding(mesh)
    label_in = batch_sharding(mesh, ndim=1)

    def step(state: TrainState, cat, num, lab, dropout_rng):
        def loss_of(params):
            return training_loss(
                model, params, cat, num, lab, dropout_rng, config.pos_weight
            )

        loss, grads = jax.value_and_grad(loss_of)(state.params)
        updates, opt_state = optimizer.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        ema = state.ema
        if config.ema_decay:  # static at trace time
            ema = update_ema(ema, params, config.ema_decay)
        return (
            state.replace(
                params=params, opt_state=opt_state, step=state.step + 1, ema=ema
            ),
            loss,
        )

    step_fn = jax.jit(
        step,
        in_shardings=(state_shardings, data_in, data_in, label_in, replicated(mesh)),
        out_shardings=(state_shardings, replicated(mesh)),
        # Full donation on TPU/GPU and on jax >= 0.5; empty only on the
        # 0.4.x CPU backend, where a cached donated executable misbehaves
        # after deserialization (parallel/compat.py).
        donate_argnums=donation_argnums(0),
    )
    return step_fn, state_shardings


def _opt_shardings(optimizer, params_template, p_shard, mesh):
    """Optimizer-state shardings: leaves shaped like a param adopt its spec
    (adam mu/nu), everything else (counts, scalars) replicates."""
    opt_state = optimizer.init(params_template)
    param_leaves = jax.tree_util.tree_leaves(params_template)
    shard_leaves = jax.tree_util.tree_leaves(p_shard)
    by_shape: dict[tuple, Any] = {}
    for leaf, shard in zip(param_leaves, shard_leaves):
        by_shape.setdefault(leaf.shape, shard)

    def assign(leaf):
        if hasattr(leaf, "shape") and leaf.shape in by_shape and leaf.ndim > 0:
            return by_shape[leaf.shape]
        return replicated(mesh)

    return jax.tree_util.tree_map(assign, opt_state)


def make_sharded_batch_scorer(model, mesh: Mesh) -> Callable:
    """Data-parallel bulk scorer (BASELINE config 4: 1M-row batch scoring).

    ``score(variables, cat, num) -> probabilities`` with the batch sharded
    across 'data'; params replicated. Call with row counts divisible by the
    data-axis size (pad the tail chunk).
    """
    data_in = batch_sharding(mesh)

    def score(variables, cat, num):
        logits = model.apply(variables, cat, num, train=False)
        return jax.nn.sigmoid(logits)

    # Not compile-cached: the production bulk path is make_bulk_jit
    # (parallel/bulk.py, entry ``bulk-score-chunk``); this probabilities-only
    # scorer is the library/test surface and compiles once per process use.
    return jax.jit(  # tpulint: disable=TPU203
        score,
        in_shardings=(replicated(mesh), data_in, data_in),
        out_shardings=batch_sharding(mesh, ndim=1),
    )
