"""shard_map across JAX versions.

jax >= 0.5 exposes ``jax.shard_map`` with a ``check_vma`` kwarg; 0.4.x has
``jax.experimental.shard_map.shard_map`` with the same flag named
``check_rep``. Every shard_map in the framework routes through this one
seam so the kernels run on the container's pinned jax and current releases
alike.
"""

from __future__ import annotations

from typing import Callable

try:  # jax >= 0.5
    from jax import shard_map as _shard_map

    _REP_KWARG = "check_vma"
except ImportError:  # jax 0.4.x
    from jax.experimental.shard_map import shard_map as _shard_map

    _REP_KWARG = "check_rep"

# True on jax 0.4.x. Callers that only need the replication checker off on
# the legacy path (because modern jax accepts their pvary/pcast
# annotations) gate on this instead of passing check_vma=False outright.
LEGACY_SHARD_MAP = _REP_KWARG == "check_rep"


def shard_map(
    f: Callable, *, mesh, in_specs, out_specs, check_vma: bool | None = None
) -> Callable:
    kwargs = {"mesh": mesh, "in_specs": in_specs, "out_specs": out_specs}
    if check_vma is not None:
        kwargs[_REP_KWARG] = check_vma
    return _shard_map(f, **kwargs)


def donation_argnums(*argnums: int) -> tuple[int, ...]:
    """Donation argnums for a train-step jit, gated on a jaxlib 0.4.x CPU
    bug: a DONATED executable deserialized from the persistent compilation
    cache misbehaves when run — the sharded TP step segfaults outright and
    the dense scan window silently returns corrupted numbers (both
    reproduced fresh-vs-warm on this container; gone in jax >= 0.5). On
    the 0.4.x CPU backend donation buys nothing anyway, so drop it there;
    TPU/GPU and newer jax get the full donation list."""
    import jax

    # One version boundary for the whole module: the structural
    # LEGACY_SHARD_MAP probe, not a second __version__ parse.
    if not LEGACY_SHARD_MAP or jax.default_backend() != "cpu":
        return argnums
    return ()


def pcast_varying(x, axis_names: tuple[str, ...]):
    """Type ``x`` as varying over ``axis_names`` inside shard_map.

    jax >= 0.7 requires the annotation (``lax.pcast``/``pvary``) for scan
    carries under the varying-manual-axes type system; 0.4.x has no such
    system (``check_rep=False`` covers it) and the value passes through."""
    import jax

    pcast = getattr(jax.lax, "pcast", None)
    if pcast is not None:
        return pcast(x, axis_names, to="varying")
    pvary = getattr(jax.lax, "pvary", None)
    if pvary is not None:
        return pvary(x, axis_names)
    return x
