"""Multi-host initialization — the DCN side of the comms story.

The reference has no distributed communication backend at all (SURVEY.md
SS5.8: HTTPS to managed control planes). The TPU-native answer has two
layers, and this module is the second:

1. **Within a slice (ICI)**: nothing to initialize — XLA lowers the
   collectives in pjit/shard_map programs onto the ICI ring directly.
2. **Across hosts (DCN)**: ``jax.distributed.initialize`` wires the
   per-host JAX runtimes into one logical device set, after which the very
   same ``Mesh``/``NamedSharding`` code spans all hosts' chips (data
   arrives per-host; meshes built from ``jax.devices()`` are global).

On Cloud TPU (GKE TPU podslices, TPU VMs) the coordinator address, process
id, and process count are discoverable from the runtime environment, so
``initialize()`` here is argument-free in the common case and an explicit
escape hatch otherwise. Idempotent and single-host-safe: calling it on a
laptop, in tests, or on a 1-host v5e slice is a no-op.
"""

from __future__ import annotations

import logging
import os

import jax

logger = logging.getLogger(__name__)

_initialized = False


def multihost_env() -> dict | None:
    """Detect a multi-host launch from the environment, if any.

    Recognized conventions, in order:
    - explicit ``MLOPS_TPU_COORDINATOR`` / ``MLOPS_TPU_PROCESS_ID`` /
      ``MLOPS_TPU_NUM_PROCESSES`` (our own contract, set by the K8s JobSet
      or mpirun wrapper),
    - Cloud TPU pod env (``TPU_WORKER_HOSTNAMES``/``TPU_WORKER_ID``), which
      ``jax.distributed.initialize()`` also auto-detects natively.
    """
    if "MLOPS_TPU_COORDINATOR" in os.environ:
        return {
            "coordinator_address": os.environ["MLOPS_TPU_COORDINATOR"],
            "process_id": int(os.environ.get("MLOPS_TPU_PROCESS_ID", "0")),
            "num_processes": int(os.environ.get("MLOPS_TPU_NUM_PROCESSES", "1")),
        }
    hostnames = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    if len([h for h in hostnames.split(",") if h.strip()]) >= 2:
        return {}  # >=2 workers: native auto-detection path
    # A single-entry TPU_WORKER_HOSTNAMES (e.g. "localhost" on 1-host
    # slices and dev containers) is NOT a pod launch.
    return None


def initialize(force: bool = False) -> bool:
    """Initialize the DCN layer when the environment calls for it.

    Returns True when ``jax.distributed.initialize`` ran (multi-host),
    False when single-host (no-op). Safe to call more than once.
    """
    global _initialized
    if _initialized:
        return True
    env = multihost_env()
    if env is None and not force:
        logger.debug("single-host launch: skipping jax.distributed")
        return False
    if env and env.get("num_processes", 2) <= 1 and not force:
        # A coordinator with <2 processes is an inconsistent launch env
        # (e.g. MLOPS_TPU_NUM_PROCESSES forgotten). Running each host as an
        # independent job would silently train N divergent models — fail
        # fast instead.
        raise ValueError(
            "MLOPS_TPU_COORDINATOR is set but MLOPS_TPU_NUM_PROCESSES is "
            f"{env.get('num_processes')}; a multi-host launch needs >= 2 "
            "(unset the coordinator for single-host runs)"
        )
    jax.distributed.initialize(**(env or {}))
    _initialized = True
    logger.info(
        "jax.distributed initialized: process %d/%d, %d global devices",
        jax.process_index(),
        jax.process_count(),
        jax.device_count(),
    )
    return True


def is_coordinator() -> bool:
    """True on the process that should write artifacts / registry entries
    (in single-host runs: always)."""
    return jax.process_index() == 0
