"""Ring attention: exact sequence-parallel attention over a mesh axis.

The reference has no sequence workloads at all (SURVEY.md SS5.7 — 23 fixed
tabular features), so long-context capability is a build obligation of the
TPU rebuild, not a port. This module provides it the TPU-native way:

Each device in the ring holds a ``[B, S/n, H, D]`` shard of Q, K and V.
K/V shards rotate around the mesh axis with ``ppermute`` while every device
folds the visiting chunk into an online-softmax accumulator for its local
Q block. After ``n`` hops each Q position has attended over the FULL
sequence, yet neither the complete K/V nor any ``S x S`` score matrix ever
materializes on a single chip:

- HBM per chip: O(B * S/n * H * D) activations + one transient
  ``[B, H, S/n, S/n]`` score tile per hop.
- Comms: ``n-1`` neighbor hops of the K/V shard riding the ICI ring
  (``ppermute`` with the +1 cyclic permutation); XLA overlaps the send of
  chunk ``i+1`` with the matmuls of chunk ``i``.

The accumulation is the same online softmax the Pallas flash kernel uses
(``mlops_tpu.ops.attention``), lifted one level up: flash streams K/V
*blocks through VMEM*, the ring streams K/V *shards across chips*. The loop
is a ``lax.scan`` with static length so the whole thing is reverse-mode
differentiable (``ppermute`` transposes to the inverse permutation), making
it usable for long-sequence *training*, not just inference.

Attention here is bidirectional (non-causal) — the consumers are the
FT-Transformer feature tokens and BERT-style encoders (BASELINE.json
configs 3 and 5), both bidirectional.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from mlops_tpu.parallel.compat import shard_map

NEG_INF = -1e30


def ring_attention_shard(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    axis_name: str,
    axis_size: int,
    scale: float | None = None,
) -> jnp.ndarray:
    """Per-device body, to be called INSIDE shard_map.

    Args:
      q, k, v: local sequence shards ``[B, S_local, H, D]``.
      axis_name: mesh axis the sequence is sharded over.
      axis_size: number of devices in the ring (static).
      scale: score scale, default ``1/sqrt(D)``.

    Returns the local output shard ``[B, S_local, H, D]``.
    """
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    b, s_q, h, _ = q.shape
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    def fold(carry, k_cur, v_cur):
        """Fold one K/V chunk into the online-softmax accumulator."""
        m, l, acc = carry
        s = (
            jnp.einsum("bqhd,bkhd->bhqk", q, k_cur).astype(jnp.float32)
            * scale
        )
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
        pv = jnp.einsum(
            "bhqk,bkhd->bqhd", p.astype(v_cur.dtype), v_cur
        ).astype(jnp.float32)
        return m_new, l_new, acc * jnp.moveaxis(alpha, 1, 2) + pv

    # Local chunk first (no communication), then exactly axis_size - 1
    # permute-then-fold hops — no wasted final rotation.
    m0 = jnp.full((b, h, s_q, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, s_q, 1), jnp.float32)
    acc0 = jnp.zeros((b, s_q, h, d), jnp.float32)
    carry0 = fold((m0, l0, acc0), k, v)

    def hop(carry, _):
        m, l, acc, k_cur, v_cur = carry
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        m, l, acc = fold((m, l, acc), k_nxt, v_nxt)
        return (m, l, acc, k_nxt, v_nxt), None

    (m, l, acc, _, _), _ = jax.lax.scan(
        hop, (*carry0, k, v), None, length=axis_size - 1
    )
    return (acc / jnp.moveaxis(l, 1, 2)).astype(q.dtype)


def make_ring_attention(
    mesh: Mesh,
    seq_axis: str = "seq",
    batch_axis: str | None = None,
    scale: float | None = None,
    head_axis: str | None = None,
) -> Callable:
    """Host-level ring attention over global ``[B, S, H, D]`` arrays.

    Returns ``fn(q, k, v) -> out`` with S sharded over ``seq_axis`` and,
    when ``batch_axis`` is given, B sharded over it too (combined DP x SP —
    each data-parallel ring runs independently). S must divide evenly by
    the seq axis size — pad upstream; for BERT-style fixed-length inputs
    even division is the normal case.

    ``head_axis`` additionally shards the HEAD dimension (Megatron-style
    tensor parallelism composed with the ring — a 3-way DP×SP×TP layout on
    a ``{'data','seq','model'}`` mesh): heads are independent in
    attention, so each (seq, head) shard runs its own online-softmax fold
    and the K/V ring hops stay strictly within the 'seq' axis — no
    cross-head communication is added. Without it, head-sharded
    activations entering the ring would be all-gathered at the shard_map
    boundary, serializing TP through SP.
    """
    n = dict(zip(mesh.axis_names, mesh.devices.shape))[seq_axis]
    spec = P(batch_axis, seq_axis, head_axis, None)

    body = partial(
        ring_attention_shard, axis_name=seq_axis, axis_size=n, scale=scale
    )

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    def ring(q, k, v):
        return body(q, k, v)

    return ring
