"""Device mesh construction for single-host slices and multi-host pods."""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh

AXES = ("data", "model")


def mesh_shape_for(n_devices: int, model_parallel: int = 1) -> tuple[int, int]:
    """Factor a device count into (data, model) axis sizes."""
    if n_devices % model_parallel:
        raise ValueError(
            f"{n_devices} devices not divisible by model_parallel="
            f"{model_parallel}"
        )
    return n_devices // model_parallel, model_parallel


def make_nd_mesh(
    axis_sizes: dict[str, int], devices: list | None = None
) -> Mesh:
    """Build a mesh with arbitrary named axes, e.g.
    ``{'data': 2, 'seq': 4}`` for combined DP x sequence-parallel or
    ``{'data': 2, 'seq': 2, 'model': 2}`` for 3-way hybrid layouts.

    Axis order is the order of ``axis_sizes``; put the fastest-varying
    (most-communicating) axis LAST so its neighbors are ICI-adjacent in the
    default device enumeration.
    """
    devices = devices if devices is not None else jax.devices()
    n = 1
    for size in axis_sizes.values():
        n *= size
    if n > len(devices):
        raise ValueError(
            f"mesh {axis_sizes} needs {n} devices, have {len(devices)}"
        )
    grid = np.asarray(devices[:n]).reshape(tuple(axis_sizes.values()))
    return Mesh(grid, tuple(axis_sizes.keys()))


def make_mesh(
    n_devices: int | None = None,
    model_parallel: int = 1,
    devices: list | None = None,
) -> Mesh:
    """Build a ``('data', 'model')`` mesh.

    On a v5e slice the devices enumerate in ICI-adjacent order, so adjacent
    mesh coordinates ride ICI links; on the CPU-simulated test mesh
    (``xla_force_host_platform_device_count``) topology is moot.
    """
    devices = devices if devices is not None else jax.devices()
    n = n_devices or len(devices)
    dp, mp = mesh_shape_for(n, model_parallel)
    grid = np.asarray(devices[:n]).reshape(dp, mp)
    return Mesh(grid, AXES)
