"""Parallelism: device mesh, shardings, collectives, distributed steps.

The reference has NO distributed compute of any kind (SURVEY.md SS2.7: a
1-worker Spark cluster, sequential hyperopt, `n_jobs=-1` threads). This
module is the TPU-native capability the rebuild owes instead:

- ``mesh``        build a ``jax.sharding.Mesh`` with ``('data', 'model')``
  axes over a v5e slice (or the CPU-simulated 8-device test mesh)
- ``sharding``    NamedSharding helpers + regex param-partition rules
  (Megatron-style column/row splits for the dense trunks)
- ``steps``       pjit train step + batch scorer: annotate shardings, let
  XLA insert the collectives over ICI (psum for grads, all-gathers for TP)
- ``collectives`` explicit shard_map building blocks (psum/all_gather/
  ppermute) for paths that want manual SPMD
- ``ring_attention`` exact sequence-parallel attention: K/V shards rotate
  the ICI ring via ppermute with online-softmax accumulation (long-context
  path for the BERT config; differentiable, so usable in training)
- ``pipeline``    GPipe-style pipeline parallelism: stage-stacked weights
  sharded over a 'stage' axis, microbatches streamed via one-hop ppermute
  (differentiable scan; completes the DP/TP/SP/EP/PP set)

- ``distributed``  multi-host (DCN) bring-up: env-detecting, idempotent
  ``jax.distributed.initialize`` wrapper + coordinator predicate; the same
  mesh code then spans hosts (SURVEY.md SS5.8)
- ``ring_attention`` (below) and ``distributed`` together are the
  long-context / multi-host capability the reference never had
"""

from mlops_tpu.parallel.distributed import (
    initialize as distributed_initialize,
    is_coordinator,
)
from mlops_tpu.parallel.mesh import make_mesh, make_nd_mesh, mesh_shape_for
from mlops_tpu.parallel.pipeline import make_pipeline
from mlops_tpu.parallel.ring_attention import (
    make_ring_attention,
    ring_attention_shard,
)
from mlops_tpu.parallel.sharding import (
    PARAM_RULES,
    batch_sharding,
    param_shardings,
    replicated,
)
from mlops_tpu.parallel.steps import (
    make_sharded_batch_scorer,
    make_sharded_train_step,
)

__all__ = [
    "PARAM_RULES",
    "batch_sharding",
    "distributed_initialize",
    "is_coordinator",
    "make_mesh",
    "make_nd_mesh",
    "make_pipeline",
    "make_ring_attention",
    "make_sharded_batch_scorer",
    "make_sharded_train_step",
    "mesh_shape_for",
    "ring_attention_shard",
    "param_shardings",
    "replicated",
]
